"""NDArray save/load.

Parity target: the dmlc binary blob in [U:src/ndarray/ndarray.cc]
(``MXNDArraySave/Load``, ``.params`` files).  Two containers:

* ``.params`` (and any explicit ``format='params'``): the reference's
  binary stream layout — uint64 list magic 0x112, NDArray records with the
  V2 per-array magic (stype / shape / context / dtype / raw data), then
  the name table.  Written little-endian like the reference on x86.
  Round-trip tested; byte-level compat is based on the upstream 1.x layout
  (the reference mount was empty this round — re-verify against real
  ``.params`` files when one exists).
* anything else: NumPy ``.npz`` with a name-mangling convention — same
  API, portable, readable by plain numpy.  Keys ``idx:<n>`` encode the
  reference's "list without names" mode.

``load`` sniffs the container by magic, so either format loads through the
same call (reference scripts pass ``.params`` paths everywhere).
"""
from __future__ import annotations

import struct

import numpy as _np

from .ndarray import NDArray, array

__all__ = ["save", "load"]

_LIST_MAGIC = 0x112            # kMXAPINDArrayListMagic
_NDARRAY_V1_MAGIC = 0xF993FAC8
_NDARRAY_V2_MAGIC = 0xF993FAC9
_NDARRAY_V3_MAGIC = 0xF993FACA

# mshadow type flags ([U:3rdparty/mshadow/mshadow/base.h])
_TYPE_FLAG_TO_DTYPE = {
    0: _np.dtype("float32"), 1: _np.dtype("float64"), 2: _np.dtype("float16"),
    3: _np.dtype("uint8"), 4: _np.dtype("int32"), 5: _np.dtype("int8"),
    6: _np.dtype("int64"),
}
_DTYPE_TO_TYPE_FLAG = {v: k for k, v in _TYPE_FLAG_TO_DTYPE.items()}


def _write_params(f, payload):
    """payload: list of (name_or_None, np.ndarray).  Layout per upstream
    NDArray::Save: list magic, reserved, data vector, key vector."""
    f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
    f.write(struct.pack("<Q", len(payload)))
    for _, arr in payload:
        arr = _np.ascontiguousarray(arr)
        if arr.dtype not in _DTYPE_TO_TYPE_FLAG:
            # bf16 etc. — no mshadow flag in the reference format
            arr = arr.astype(_np.float32)
        f.write(struct.pack("<I", _NDARRAY_V2_MAGIC))
        f.write(struct.pack("<i", 0))                      # stype: kDefaultStorage
        f.write(struct.pack("<I", arr.ndim))               # TShape: uint32 ndim
        for d in arr.shape:
            f.write(struct.pack("<q", d))                  # int64 dims
        f.write(struct.pack("<ii", 1, 0))                  # Context: cpu(0)
        f.write(struct.pack("<i", _DTYPE_TO_TYPE_FLAG[arr.dtype]))
        f.write(arr.tobytes())
    names = [n for n, _ in payload if n is not None]
    f.write(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        f.write(struct.pack("<Q", len(b)))
        f.write(b)


def _read_ndarray_record(f):
    magic = struct.unpack("<I", f.read(4))[0]
    if magic == _NDARRAY_V1_MAGIC:
        stype = 0
    elif magic in (_NDARRAY_V2_MAGIC, _NDARRAY_V3_MAGIC):
        stype = struct.unpack("<i", f.read(4))[0]
    else:
        raise ValueError(f"unsupported NDArray record magic 0x{magic:x}")
    if stype not in (0, -1):  # kDefaultStorage / kUndefinedStorage
        raise NotImplementedError(
            f"sparse storage type {stype} in .params is not supported (dense only)")
    ndim = struct.unpack("<I", f.read(4))[0]
    shape = tuple(struct.unpack("<q", f.read(8))[0] for _ in range(ndim))
    _devtype, _devid = struct.unpack("<ii", f.read(8))
    type_flag = struct.unpack("<i", f.read(4))[0]
    dtype = _TYPE_FLAG_TO_DTYPE.get(type_flag)
    if dtype is None:
        raise ValueError(f"unknown type flag {type_flag} in .params")
    count = 1
    for d in shape:
        count *= d
    data = _np.frombuffer(f.read(count * dtype.itemsize), dtype=dtype).reshape(shape)
    return data


def _read_params(f):
    magic, _reserved = struct.unpack("<QQ", f.read(16))
    if magic != _LIST_MAGIC:
        raise ValueError(f"bad .params magic 0x{magic:x}")
    n = struct.unpack("<Q", f.read(8))[0]
    arrays = [_read_ndarray_record(f) for _ in range(n)]
    raw = f.read(8)
    nkeys = struct.unpack("<Q", raw)[0] if len(raw) == 8 else 0
    names = []
    for _ in range(nkeys):
        ln = struct.unpack("<Q", f.read(8))[0]
        names.append(f.read(ln).decode("utf-8"))
    if names:
        if len(names) != len(arrays):
            raise ValueError(
                f".params name table has {len(names)} keys for {len(arrays)} arrays")
        return {k: array(v) for k, v in zip(names, arrays)}
    return [array(v) for v in arrays]


def save(fname, data, format=None):
    """Save a list or str-keyed dict of NDArrays (parity: ``mx.nd.save``).
    ``.params`` paths (or ``format='params'``) use the reference binary
    layout; everything else uses npz."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        items = [(None, _np.asarray(v.asnumpy())) for v in data]
        payload = {f"idx:{i}": a for i, (_, a) in enumerate(items)}
    elif isinstance(data, dict):
        items = [(k, _np.asarray(v.asnumpy())) for k, v in data.items()]
        payload = dict(items)
    else:
        raise TypeError(f"cannot save {type(data)}")
    if format == "params" or (format is None and str(fname).endswith(".params")):
        with open(fname, "wb") as f:
            _write_params(f, items)
        return
    with open(fname, "wb") as f:
        _np.savez(f, **payload)


def load(fname):
    """Load NDArrays saved by :func:`save` or by the reference's
    ``mx.nd.save`` (parity: ``mx.nd.load``).  Container is sniffed by
    magic, so reference ``.params`` files load transparently."""
    with open(fname, "rb") as f:
        head = f.read(8)
    if len(head) == 8 and struct.unpack("<Q", head)[0] == _LIST_MAGIC:
        with open(fname, "rb") as f:
            return _read_params(f)
    with _np.load(fname, allow_pickle=False) as z:
        keys = list(z.keys())
        if keys and all(k.startswith("idx:") for k in keys):
            keys.sort(key=lambda k: int(k.split(":", 1)[1]))
            return [array(z[k]) for k in keys]
        return {k: array(z[k]) for k in keys}
