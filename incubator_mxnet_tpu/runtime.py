"""Runtime feature detection (parity: [U:python/mxnet/runtime.py] +
[U:src/libinfo.cc]).

The reference reports compile-time feature bits (CUDA, CUDNN, MKLDNN, ...);
here features are probed live from the JAX runtime: backend platform, TPU
topology, pallas availability, distributed initialization state.
"""
from __future__ import annotations

__all__ = ["Feature", "Features", "feature_list"]


class Feature:
    def __init__(self, name, enabled):
        self.name = name
        self.enabled = enabled

    def __repr__(self):
        return f"[{'✔' if self.enabled else '✖'} {self.name}]"


def _probe():
    import jax

    feats = {}
    devs = jax.devices()
    platforms = {d.platform for d in devs}
    feats["TPU"] = any(p not in ("cpu",) for p in platforms)
    feats["CPU"] = True
    feats["CUDA"] = False  # by design: XLA:TPU replaces the CUDA stack
    feats["CUDNN"] = False
    feats["MKLDNN"] = False
    feats["XLA"] = True
    feats["BF16"] = True
    feats["INT64_TENSOR_SIZE"] = True
    try:
        from jax.experimental import pallas  # noqa: F401

        feats["PALLAS"] = True
    except Exception:
        feats["PALLAS"] = False
    feats["DIST_KVSTORE"] = True  # jax.distributed-based; see kvstore/
    feats["OPENMP"] = False
    feats["F16C"] = False
    feats["SIGNAL_HANDLER"] = True
    feats["PROFILER"] = True
    return feats


class Features(dict):
    """Parity: ``mx.runtime.Features`` — mapping name -> Feature."""

    def __init__(self):
        super().__init__({k: Feature(k, v) for k, v in _probe().items()})

    def is_enabled(self, name):
        return self[name].enabled

    def __repr__(self):
        return "[" + ", ".join(repr(v) for v in self.values()) + "]"


def feature_list():
    return list(Features().values())
