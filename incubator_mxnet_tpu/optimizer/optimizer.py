"""Optimizer registry and implementations.

Parity target: [U:python/mxnet/optimizer/optimizer.py] (registry, lr/wd
mults, num_update bookkeeping, multi_precision master weights) backed by the
fused jitted kernels in ops/optimizer_ops.py (the reference's fused C++/CUDA
update ops, [U:src/operator/optimizer_op.cc]).

States are NDArrays; updates swap buffers in place (engine-var style), so
``trainer.step`` behaves exactly like the reference.  The fully-jitted
training path (gluon.contrib / parallel.data_parallel) instead calls the
pure kernels directly inside one compiled step.
"""
from __future__ import annotations

import math
import os as _os

import jax.numpy as jnp
import numpy as _np

from ..ndarray.ndarray import NDArray, zeros
from ..ops import optimizer_ops as K

__all__ = [
    "Optimizer", "SGD", "NAG", "Adam", "AdamW", "AdaGrad", "AdaDelta",
    "RMSProp", "Ftrl", "Signum", "LAMB", "Updater", "get_updater", "create", "register",
]

_REGISTRY = {}

_INF = float("inf")


def register(klass):
    _REGISTRY[klass.__name__.lower()] = klass
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REGISTRY[name.lower()](**kwargs)


def _f32(x):
    return jnp.float32(x)


def _default_aggregate_num():
    """Resolve the fused-update group cap from ``MXNET_OPTIMIZER_AGGREGATION``
    (the env escape hatch of docs/optimizer_fusion.md): 0/off/false disables
    the fused Trainer step entirely, an integer caps params per fused
    dispatch, unset/on means fuse aggressively (reference ``aggregate_num``
    is 4 because CUDA kernels take fixed-arity pointer lists; a jitted
    pytree call has no such limit)."""
    v = _os.environ.get("MXNET_OPTIMIZER_AGGREGATION", "").strip().lower()
    if v in ("0", "off", "false", "no", "none"):
        return 0
    if v in ("", "on", "true", "yes", "auto"):
        return 256
    try:
        return max(0, int(v))
    except ValueError:
        return 256


class Optimizer:
    """Base optimizer (parity: ``mx.optimizer.Optimizer``)."""

    def __init__(
        self,
        rescale_grad=1.0,
        param_idx2name=None,
        wd=0.0,
        clip_gradient=None,
        learning_rate=0.01,
        lr_scheduler=None,
        begin_num_update=0,
        multi_precision=False,
        param_dict=None,
        aggregate_num=None,
        **kwargs,
    ):
        # max parameters per fused whole-group update (Trainer fast path;
        # parity-adjacent to the reference's aggregate_num).  <= 1 keeps the
        # per-tensor loop.
        self.aggregate_num = (_default_aggregate_num() if aggregate_num is None
                              else max(0, int(aggregate_num)))
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient if clip_gradient is not None else _INF
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.lr_mult = {}
        self.wd_mult = {}

    # -- lr/wd plumbing (parity with reference semantics) ---------------
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been defined.")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler is not None else self.lr
        param = self.param_dict.get(index)
        if param is not None:
            lr *= getattr(param, "lr_mult", 1.0)
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        param = self.param_dict.get(index)
        if param is not None:
            wd *= getattr(param, "wd_mult", 1.0)
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    # -- state ----------------------------------------------------------
    def create_state(self, index, weight):
        return None

    def _use_mp(self, weight):
        return self.multi_precision and weight.dtype in (jnp.float16, jnp.bfloat16)

    def create_state_multi_precision(self, index, weight):
        if self._use_mp(weight):
            w32 = NDArray(weight._data.astype(jnp.float32), ctx=weight.ctx)
            return (self.create_state(index, NDArray(w32._data)), w32)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self._use_mp(weight):
            self._update_mp(index, weight, grad, state)
        else:
            self.update(index, weight, grad, state)

    def _update_mp(self, index, weight, grad, state):
        inner_state, w32 = state
        self.update(index, w32, grad, inner_state)
        weight._data = w32._data.astype(weight.dtype)
        weight._version += 1

    # serialization (sent to dist kvstore servers in the reference) -----
    def __getstate__(self):
        return self.__dict__.copy()

    def __setstate__(self, state):
        self.__dict__.update(state)


def _swap(arr, new_data):
    arr._data = new_data
    arr._version += 1


@register
class SGD(Optimizer):
    """SGD with momentum + optional multi-precision
    (parity: sgd_update/sgd_mom_update/mp_sgd_mom_update)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        # lazy_update default True as in the reference: it only changes
        # behavior for row_sparse parameters (per-row lazy state updates)
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def _lazy_for(self, index):
        p = self.param_dict.get(index)
        return self.lazy_update and getattr(p, "stype", "default") == "row_sparse"

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            kernel = K.sgd_lazy_update if self._lazy_for(index) else K.sgd_update
            _swap(
                weight,
                kernel(
                    weight._data, grad._data, _f32(lr), _f32(wd), _f32(self.rescale_grad), _f32(self.clip_gradient)
                ),
            )
        else:
            kernel = K.sgd_mom_lazy_update if self._lazy_for(index) else K.sgd_mom_update
            new_w, new_mom = kernel(
                weight._data,
                grad._data,
                state._data,
                _f32(lr),
                _f32(wd),
                _f32(self.rescale_grad),
                _f32(self.clip_gradient),
                _f32(self.momentum),
            )
            _swap(weight, new_w)
            _swap(state, new_mom)

    def update_multi_precision(self, index, weight, grad, state):
        if self._use_mp(weight) and self.momentum != 0.0:
            mom, w32 = state
            self._update_count(index)
            lr, wd = self._get_lr(index), self._get_wd(index)
            mp_kernel = (K.mp_sgd_mom_lazy_update if self._lazy_for(index)
                         else K.mp_sgd_mom_update)
            new_w, new_mom, new_w32 = mp_kernel(
                weight._data,
                grad._data,
                mom._data,
                w32._data,
                _f32(lr),
                _f32(wd),
                _f32(self.rescale_grad),
                _f32(self.clip_gradient),
                _f32(self.momentum),
            )
            _swap(weight, new_w)
            _swap(mom, new_mom)
            _swap(w32, new_w32)
        else:
            super().update_multi_precision(index, weight, grad, state)


@register
class NAG(Optimizer):
    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if state is None:
            _swap(
                weight,
                K.sgd_update(
                    weight._data, grad._data, _f32(lr), _f32(wd), _f32(self.rescale_grad), _f32(self.clip_gradient)
                ),
            )
            return
        new_w, new_mom = K.nag_mom_update(
            weight._data,
            grad._data,
            state._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.momentum),
        )
        _swap(weight, new_w)
        _swap(state, new_mom)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def _lazy_for(self, index):
        p = self.param_dict.get(index)
        return self.lazy_update and getattr(p, "stype", "default") == "row_sparse"

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        kernel = K.adam_lazy_update if self._lazy_for(index) else K.adam_update
        new_w, new_mean, new_var = kernel(
            weight._data,
            grad._data,
            mean._data,
            var._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
        )
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(var, new_var)

    def _update_mp(self, index, weight, grad, state):
        (mean, var), w32 = state[0], state[1]
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        new_w, new_mean, new_var, new_w32 = K.mp_adam_update(
            weight._data,
            grad._data,
            mean._data,
            var._data,
            w32._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
        )
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(var, new_var)
        _swap(w32, new_w32)


@register
class AdamW(Adam):
    """Decoupled weight decay (not in the 1.x core op set; provided for the
    BERT workload — GluonNLP ships it as a contrib optimizer)."""

    def __init__(self, eta=1.0, **kwargs):
        super().__init__(**kwargs)
        self.eta = eta

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        new_w, new_mean, new_var = K.adamw_update(
            weight._data,
            grad._data,
            mean._data,
            var._data,
            _f32(lr),
            _f32(wd),
            _f32(self.eta),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
        )
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(var, new_var)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        new_w, new_hist = K.adagrad_update(
            weight._data,
            grad._data,
            state._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.float_stable_eps),
        )
        _swap(weight, new_w)
        _swap(state, new_hist)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_d = state
        new_w, new_g, new_d = K.adadelta_update(
            weight._data,
            grad._data,
            acc_g._data,
            acc_d._data,
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.rho),
            _f32(self.epsilon),
        )
        _swap(weight, new_w)
        _swap(acc_g, new_g)
        _swap(acc_d, new_d)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.9, momentum=0.9, epsilon=1e-8, centered=False, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.rho, self.momentum, self.epsilon, self.centered = rho, momentum, epsilon, centered

    def create_state(self, index, weight):
        if self.centered:
            return tuple(zeros(weight.shape, dtype="float32", ctx=weight.ctx) for _ in range(3))
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        if self.centered:
            n, g_avg, delta = state
            new_w, new_n, new_g, new_d = K.rmspropalex_update(
                weight._data,
                grad._data,
                n._data,
                g_avg._data,
                delta._data,
                _f32(lr),
                _f32(wd),
                _f32(self.rescale_grad),
                _f32(self.clip_gradient),
                _f32(self.rho),
                _f32(self.momentum),
                _f32(self.epsilon),
            )
            _swap(weight, new_w)
            _swap(n, new_n)
            _swap(g_avg, new_g)
            _swap(delta, new_d)
        else:
            new_w, new_n = K.rmsprop_update(
                weight._data,
                grad._data,
                state._data,
                _f32(lr),
                _f32(wd),
                _f32(self.rescale_grad),
                _f32(self.clip_gradient),
                _f32(self.rho),
                _f32(self.epsilon),
            )
            _swap(weight, new_w)
            _swap(state, new_n)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        new_w, new_z, new_n = K.ftrl_update(
            weight._data,
            grad._data,
            z._data,
            n._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.lamda1),
            _f32(self.beta),
        )
        _swap(weight, new_w)
        _swap(z, new_z)
        _swap(n, new_n)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        new_w, new_mom = K.signum_update(
            weight._data,
            grad._data,
            state._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.momentum),
            _f32(self.wd_lh),
        )
        _swap(weight, new_w)
        _swap(state, new_mom)


@register
class LAMB(Optimizer):
    """Layer-wise adaptive moments for large-batch BERT (parity:
    lamb_update_phase1/2 in [U:src/operator/optimizer_op.cc])."""

    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-6,
        lower_bound=None,
        upper_bound=None,
        bias_correction=True,
        **kwargs,
    ):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound = lower_bound if lower_bound is not None else 0.0
        self.upper_bound = upper_bound if upper_bound is not None else _INF
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        r, new_mean, new_var = K.lamb_update_phase1(
            weight._data,
            grad._data,
            mean._data,
            var._data,
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
            jnp.bool_(self.bias_correction),
        )
        new_w = K.lamb_update_phase2(weight._data, r, _f32(lr), _f32(self.lower_bound), _f32(self.upper_bound))
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(var, new_var)


@register
class Nadam(Optimizer):
    """Nesterov Adam (parity: [U:python/mxnet/optimizer/optimizer.py] Nadam).
    The momentum-schedule product is kept as a 0-d state array (the python
    reference mutates ``self.m_schedule``; a state array keeps the fused
    SPMD step pure and trace-safe)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            NDArray(jnp.ones((), dtype=jnp.float32)),  # m_schedule product
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        mean, var, sched = state
        new_w, new_mean, new_var, new_sched = K.nadam_update(
            weight._data,
            grad._data,
            mean._data,
            var._data,
            sched._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
            _f32(self.schedule_decay),
        )
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(var, new_var)
        _swap(sched, new_sched)


@register
class FTML(Optimizer):
    """Follow the Moving Leader (Zheng & Kwok 2017; parity: ftml_update in
    [U:src/operator/optimizer_op.cc])."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return tuple(zeros(weight.shape, dtype="float32", ctx=weight.ctx)
                     for _ in range(3))  # d, v, z

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        d, v, z = state
        new_w, new_d, new_v, new_z = K.ftml_update(
            weight._data,
            grad._data,
            d._data,
            v._data,
            z._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
            _f32(self.epsilon),
            _f32(t),
        )
        _swap(weight, new_w)
        _swap(d, new_d)
        _swap(v, new_v)
        _swap(z, new_z)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics (parity:
    [U:python/mxnet/optimizer/optimizer.py] SGLD): posterior sampling via
    gradient noise ~ N(0, lr)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from ..random import get_key
        import jax as _jax

        noise = _jax.random.normal(get_key(), weight.shape, dtype=jnp.float32)
        new_w = K.sgld_update(
            weight._data,
            grad._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            noise,
        )
        _swap(weight, new_w)


@register
class DCASGD(Optimizer):
    """Delay-Compensated Async SGD (Zheng et al. 2017; parity:
    [U:python/mxnet/optimizer/optimizer.py] DCASGD): keeps the previous
    weight to compensate gradient staleness with λ·g²·(w − w_prev)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum, self.lamda = momentum, lamda

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),  # momentum
            NDArray(weight._data.astype(jnp.float32)),              # prev weight
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, prev = state
        new_w, new_mom, new_prev = K.dcasgd_update(
            weight._data,
            grad._data,
            mom._data,
            prev._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.momentum),
            _f32(self.lamda),
        )
        _swap(weight, new_w)
        _swap(mom, new_mom)
        _swap(prev, new_prev)


@register
class Adamax(Optimizer):
    """AdaMax — infinity-norm Adam (parity: [U:python/mxnet/optimizer/
    optimizer.py] Adamax)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
            zeros(weight.shape, dtype="float32", ctx=weight.ctx),
        )

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        # bias-corrected lr (the reference folds 1/(1-beta1^t) into lr)
        lr = lr / (1.0 - self.beta1 ** t)
        mean, inf_norm = state
        new_w, new_mean, new_inf = K.adamax_update(
            weight._data,
            grad._data,
            mean._data,
            inf_norm._data,
            _f32(lr),
            _f32(wd),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.beta1),
            _f32(self.beta2),
        )
        _swap(weight, new_w)
        _swap(mean, new_mean)
        _swap(inf_norm, new_inf)


@register
class LBSGD(Optimizer):
    """Large-Batch SGD with LARS layer-wise rate scaling + warmup (parity:
    [U:python/mxnet/optimizer/optimizer.py] LBSGD).  warmup_strategy in
    {'linear', 'power2', 'sqrt', 'lars'}; 'lars' applies the layerwise
    trust-ratio throughout.  ``batch_scale`` is the large-batch multiplier:
    the effective rate ramps from ``lr`` to ``lr * batch_scale`` over the
    warmup window and stays there (the reference's lr_linear target).
    ``begin_epoch``/``num_epochs`` are accepted for signature parity (the
    reference threads them into its internal scheduler bookkeeping only).
    """

    def __init__(self, momentum=0.0, multi_precision=False,
                 warmup_strategy="linear", warmup_epochs=5, batch_scale=1,
                 updates_per_epoch=32, begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(multi_precision=multi_precision, **kwargs)
        self.momentum = momentum
        self.warmup_strategy = warmup_strategy
        self.warmup_epochs = warmup_epochs
        self.batch_scale = max(1.0, float(batch_scale))
        self.updates_per_epoch = max(1, updates_per_epoch)
        self.lars_eta = 0.001
        self.lars_eps = 1e-9

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def _warmup_scale(self, t):
        """Ramp 1 → batch_scale over the warmup window, shaped by the
        warmup strategy.  Exactly 1.0 when batch_scale <= 1 (the
        reference's _get_lbmult multiplier never drops the rate below
        the base lr)."""
        if self.batch_scale <= 1.0:
            return 1.0
        total = self.warmup_epochs * self.updates_per_epoch
        frac = jnp.minimum(_f32(t) / float(total), 1.0)
        if self.warmup_strategy == "power2":
            frac = frac * frac
        elif self.warmup_strategy == "sqrt":
            frac = jnp.sqrt(frac)
        return 1.0 + (self.batch_scale - 1.0) * frac

    def _lars_ratio(self, weight, grad, wd):
        return _lars_trust(weight, grad, wd, self.lars_eta, self.lars_eps,
                           self.rescale_grad)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        if self.warmup_strategy == "lars":
            # the reference uses the LARS trust ratio *instead of* the
            # warmup multiplier, not on top of it
            lr = _f32(lr) * self._lars_ratio(weight, grad, wd)
        else:
            lr = _f32(lr) * self._warmup_scale(t)
        if state is None:
            new_w = K.sgd_update(
                weight._data, grad._data, lr, _f32(wd),
                _f32(self.rescale_grad), _f32(self.clip_gradient))
            _swap(weight, new_w)
        else:
            new_w, new_mom = K.sgd_mom_update(
                weight._data, grad._data, state._data, lr, _f32(wd),
                _f32(self.rescale_grad), _f32(self.clip_gradient),
                _f32(self.momentum))
            _swap(weight, new_w)
            _swap(state, new_mom)


def _lars_trust(weight, grad, wd, eta, eps, rescale_grad):
    """eta*||w|| / (||g|| + wd*||w|| + eps), 1.0 when either norm is 0
    (shared by LARS and LBSGD's lars warmup strategy)."""
    w32 = weight._data.astype(jnp.float32)
    g32 = grad._data.astype(jnp.float32) * _f32(rescale_grad)
    w_norm = jnp.linalg.norm(w32)
    g_norm = jnp.linalg.norm(g32)
    ratio = eta * w_norm / (g_norm + wd * w_norm + eps)
    return jnp.where((w_norm > 0) & (g_norm > 0), ratio, 1.0)


@register
class LARS(Optimizer):
    """Layerwise-adaptive-rate SGD (parity: ``mx.optimizer.LARS``, 1.6+):
    momentum SGD where each layer's lr is scaled by the trust ratio
    eta*||w|| / (||g|| + wd*||w|| + eps); layers whose norm is 0 fall
    back to the plain lr (the reference convention)."""

    def __init__(self, momentum=0.0, eta=0.001, epsilon=1e-8, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        # trust-scaled lr into the SAME fused kernels every optimizer uses
        # (uniform clip_gradient semantics, one compiled update)
        lr = _f32(lr) * _lars_trust(weight, grad, wd, self.eta, self.epsilon,
                                    self.rescale_grad)
        if state is None:
            new_w = K.sgd_update(
                weight._data, grad._data, lr, _f32(wd),
                _f32(self.rescale_grad), _f32(self.clip_gradient))
            _swap(weight, new_w)
        else:
            new_w, new_mom = K.sgd_mom_update(
                weight._data, grad._data, state._data, lr, _f32(wd),
                _f32(self.rescale_grad), _f32(self.clip_gradient),
                _f32(self.momentum))
            _swap(weight, new_w)
            _swap(state, new_mom)


@register
class GroupAdaGrad(Optimizer):
    """Per-row (grouped) AdaGrad for embedding-style parameters (parity:
    [U:python/mxnet/optimizer/contrib.py] GroupAdaGrad): one accumulated
    statistic per row instead of per element — 1/dim the optimizer state
    of AdaGrad for [vocab, dim] tables."""

    def __init__(self, learning_rate=0.01, eps=1e-5, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros((weight.shape[0],) + (1,) * (len(weight.shape) - 1),
                     dtype="float32", ctx=weight.ctx)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        assert self._get_wd(index) == 0.0, "GroupAdaGrad has no wd (parity)"
        new_w, new_hist = K.group_adagrad_update(
            weight._data,
            grad._data,
            state._data,
            _f32(lr),
            _f32(self.rescale_grad),
            _f32(self.clip_gradient),
            _f32(self.float_stable_eps),
        )
        _swap(weight, new_w)
        _swap(state, new_hist)


class Updater:
    """KVStore-side updater closure (parity: ``mx.optimizer.get_updater`` /
    the serialized optimizer shipped to dist servers)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def get_states(self, dump_optimizer=False):
        import pickle

        return pickle.dumps((self.states, self.optimizer) if dump_optimizer else self.states)

    def set_states(self, states):
        import pickle

        obj = pickle.loads(states)
        if isinstance(obj, tuple):
            self.states, self.optimizer = obj
        else:
            self.states = obj


def get_updater(optimizer):
    return Updater(optimizer)


math  # keep import
_np  # keep import
