"""Fused whole-group optimizer step — the Trainer fast path.

Parity motivation: the reference ships grouped kernels (``multi_sgd_update``
et al., [U:src/operator/optimizer_op.cc]) because a model with hundreds of
small parameters otherwise pays one kernel launch per tensor per step.  Here
the same idea rides ``ops/optimizer_ops.group_apply``: parameters are
grouped by (optimizer class, weight dtype, multi-precision, lazy/row-sparse,
context) and each group is updated by ONE jitted pytree call —

* weights / grads / states travel as list pytrees (jit's aval cache keys on
  the group's shapes, so steady-state steps are a single cached dispatch);
* per-param lr / wd / t arrive as stacked device arrays, so lr-schedule
  progress and Adam's bias-correction counters never retrace;
* scalar hypers (momentum, betas, rescale_grad, clip_gradient, eps, eta)
  are dynamic 0-d args — hyper changes never retrace either;
* weight and state buffers are DONATED to XLA (in-place reuse, no fresh
  HBM allocations per step) unless ``MXNET_OPTIMIZER_DONATE=0``.

Escape hatches (docs/optimizer_fusion.md): ``MXNET_OPTIMIZER_AGGREGATION=0``
(or ``Optimizer(aggregate_num=0)``) restores the per-tensor loop, and
``NaiveEngine`` bypasses fusion entirely (jit is globally disabled there).
Unsupported optimizers and lazy row-sparse parameters fall back per-tensor,
preserving their kernels' lazy semantics.
"""
from __future__ import annotations

import os as _os
import re as _re
from time import perf_counter as _perf

from .. import engine as _engine
from .. import profiler as _profiler
from ..ops import optimizer_ops as K
from .optimizer import LAMB, NAG, RMSProp, SGD, Adam, AdamW, _swap

__all__ = ["fused_update", "plan_groups", "supports", "donation_enabled",
           "quantization_sensitive"]


# Name-derived parameter grouping, part 2 (part 1 is the fused-step group
# key below): the QUANTIZATION-SENSITIVE group the gradient-compression
# policy (comm/compression.py) opts out of int8/bf16 wire formats.  Same
# name conventions the reference's no-weight-decay grouping keys on
# (``set_wd_mult``'s ``_gamma``/``_beta``/``_bias`` suffixes) plus
# normalization state and embeddings: tensors with few, large-magnitude
# gradient entries that a shared block scale would crush.
_QUANT_SENSITIVE_RE = _re.compile(
    r"(_gamma|_beta|_bias|_moving_mean|_moving_var|norm|embed)", _re.I)


def quantization_sensitive(name):
    """Whether a parameter (by name) belongs to a quantization-sensitive
    group — the canonical per-parameter-group opt-out consulted by
    ``comm.CompressionPolicy`` (override per run with
    ``MXNET_GRAD_COMPRESS_SKIP=<regex>``)."""
    return bool(_QUANT_SENSITIVE_RE.search(str(name)))


def donation_enabled():
    """Buffer donation escape hatch (``MXNET_OPTIMIZER_DONATE=0``): donated
    weight/state buffers are reused in place by XLA, which invalidates any
    user-held alias of the OLD buffer (e.g. ``w = p.data().copy()`` shares
    the jax buffer).  See docs/optimizer_fusion.md."""
    return _os.environ.get("MXNET_OPTIMIZER_DONATE", "1") != "0"


def _select(opt, index, weight, state):
    """Map one (optimizer, param, state) to its group-step adapter and the
    flat tuple of state NDArrays, or None when this param must take the
    per-tensor path.  Exact-type checks: a subclass overriding ``update``
    must not silently inherit a fused kernel it no longer matches."""
    t = type(opt)
    mp = opt._use_mp(weight)
    if t is SGD:
        if opt._lazy_for(index):
            return None  # lazy row-sparse: per-tensor lazy kernels
        if mp:
            if opt.momentum != 0.0:
                mom, w32 = state
                return K.mp_sgd_mom_step, (mom, w32)
            _inner, w32 = state
            return K.mp_sgd_step, (w32,)
        if state is None:
            return K.sgd_step, ()
        return K.sgd_mom_step, (state,)
    if t is NAG:
        if mp:
            inner, w32 = state
            if opt.momentum == 0.0:
                # base _update_mp runs plain SGD on the fp32 master copy
                return K.mp_sgd_step, (w32,)
            return K.mp_nag_mom_step, (inner, w32)
        if state is None:
            return K.sgd_step, ()
        return K.nag_mom_step, (state,)
    if t in (Adam, AdamW):
        if opt._lazy_for(index):
            return None
        if mp:
            # AdamW inherits Adam._update_mp (mp_adam_update) unfused; the
            # fused path matches that exactly
            (mean, var), w32 = state
            return K.mp_adam_step, (mean, var, w32)
        mean, var = state
        return (K.adamw_step if t is AdamW else K.adam_step), (mean, var)
    if t is RMSProp:
        if mp:
            return None  # base-class mp wrapper: per-tensor path
        if opt.centered:
            n, g_avg, delta = state
            return K.rmspropalex_step, (n, g_avg, delta)
        return K.rmsprop_step, (state,)
    if t is LAMB:
        if mp:
            return None
        mean, var = state
        return K.lamb_step, (mean, var)
    return None


def supports(opt):
    """Whether this optimizer instance has fused group kernels at all."""
    return type(opt) in (SGD, NAG, Adam, AdamW, RMSProp, LAMB)


def _scalars(opt):
    S = {"rescale": opt.rescale_grad, "clip": opt.clip_gradient}
    t = type(opt)
    if t in (SGD, NAG):
        S["momentum"] = opt.momentum
    elif t is RMSProp:
        S["rho"], S["epsilon"] = opt.rho, opt.epsilon
        if opt.centered:
            S["momentum"] = opt.momentum
    elif t is LAMB:
        S["beta1"], S["beta2"] = opt.beta1, opt.beta2
        S["epsilon"] = opt.epsilon
        S["lower_bound"], S["upper_bound"] = opt.lower_bound, opt.upper_bound
        S["bias_correction"] = 1.0 if opt.bias_correction else 0.0
    else:
        S["beta1"], S["beta2"] = opt.beta1, opt.beta2
        S["epsilon"] = opt.epsilon
        if t is AdamW:
            S["eta"] = opt.eta
    return S


def _concrete(nd):
    """Resolve a pending bulk-deferred buffer in place (grads produced
    inside an engine.bulk scope must flush before donation/jit)."""
    raw = nd._data
    if isinstance(raw, _engine.DeferredArray):
        raw = raw._resolve()
        nd._data = raw
    return raw


def plan_groups(optimizer, items, states):
    """THE fused-group planning rule, shared by :func:`fused_update` and
    the step fold (``gluon/step_fold.py``): map ``(index, weight, grad)``
    triples onto their fused step adapters, grouped by
    ``(adapter, dtype, context)``.  Returns ``(groups, rest)`` where
    ``groups`` is an insertion-ordered dict ``key -> [(i, w, g, flat)]``
    (``flat`` = the adapter's flat tuple of state NDArrays, aliasing
    ``states[i]``) and ``rest`` collects the items with no fused kernel
    (unsupported optimizer, lazy row-sparse, mp fallbacks) that must take
    the per-tensor path."""
    groups, rest = {}, []
    for item in items:
        i, w, g = item
        sel = _select(optimizer, i, w, states[i])
        if sel is None:
            rest.append(item)
            continue
        step, flat = sel
        key = (step, str(w.dtype), str(w.context))
        groups.setdefault(key, []).append((i, w, g, flat))
    return groups, rest


def fused_update(optimizer, items, states):
    """Update every supported ``(index, weight, grad)`` in ``items`` via
    grouped single-dispatch jitted calls; returns the leftover items the
    caller must update per-tensor.  ``states`` maps index -> the state the
    per-tensor path would use — the SAME NDArray objects are swapped in
    place, so fused and per-tensor steps are interchangeable mid-training.
    """
    agg = int(getattr(optimizer, "aggregate_num", 0) or 0)
    if agg <= 1 or not items or _engine._engine_type == "NaiveEngine":
        return items
    groups, rest = plan_groups(optimizer, items, states)
    if groups:
        donate = donation_enabled()
        scalars = _scalars(optimizer)
        for (step, _, _), members in groups.items():
            for start in range(0, len(members), agg):
                chunk = members[start:start + agg]
                # bump ALL counts first, then read lr/wd — matches the
                # per-tensor loop for synchronized params (every param sees
                # the same num_update) and the reference's aggregate path
                for i, _, _, _ in chunk:
                    optimizer._update_count(i)
                lrs = [optimizer._get_lr(i) for i, _, _, _ in chunk]
                wds = [optimizer._get_wd(i) for i, _, _, _ in chunk]
                ts = [optimizer._index_update_count[i] for i, _, _, _ in chunk]
                # resolve pending bulk-deferred buffers BEFORE the span
                # opens: a flush recorded inside it would double-bill the
                # host bucket (bulk.flush and fused.group_apply are both
                # telemetry roots)
                ws = [_concrete(w) for _, w, _, _ in chunk]
                gs = [_concrete(g) for _, _, g, _ in chunk]
                t0 = _perf() if _profiler._active else None
                guard_err = None
                try:
                    new_w, new_s = K.group_apply(
                        step, ws, gs,
                        [[s._data for s in flat] for _, _, _, flat in chunk],
                        lrs, wds, ts, scalars, donate=donate)
                except _profiler.CompileGuardError as e:
                    # the compile guard fired AFTER the dispatch: the old
                    # buffers are already donated, so wire the new ones in
                    # below and re-raise once the group is consistent
                    res = getattr(e, "group_result", None)
                    if res is None:
                        raise
                    new_w, new_s = res
                    guard_err = e
                except Exception as e:
                    # donated-buffer dispatch is an OOM choke point: the
                    # step's fresh outputs are the allocation that fails
                    # when HBM is exhausted — name the owners before the
                    # error surfaces (no-op for unrelated errors)
                    _profiler.maybe_oom_postmortem(e, "optimizer.group_apply")
                    raise
                if t0 is not None:
                    _profiler.record_span("fused.group_apply", "optimizer",
                                          t0, args={"params": len(chunk)})
                for m, (_, w, _, flat) in enumerate(chunk):
                    _swap(w, new_w[m])
                    for s_nd, s_new in zip(flat, new_s[m]):
                        _swap(s_nd, s_new)
                _profiler.incr("fused_step_call")
                _profiler.incr("fused_step_params", len(chunk))
                if guard_err is not None:
                    raise guard_err  # every buffer re-wired: safe to surface
    if rest:
        _profiler.incr("fused_step_fallback_params", len(rest))
    return rest
