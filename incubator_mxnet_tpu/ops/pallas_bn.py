"""Pallas fused BatchNorm epilogue — the experiment VERDICT r4 item 4 names
for ResNet-50 ([U:src/operator/nn/batch_norm.cc] is the reference op; the
reference's cuDNN path fuses BN+ReLU into the convolution epilogue the
same way).

Two kernels over the conv output viewed as ``[N, C, H*W]`` (a free reshape
of contiguous NCHW):

* :func:`bn_stats` — one tiled pass accumulating per-channel ``sum`` and
  ``sum(x²)`` in fp32 (grid iterates N inside each channel block, output
  block revisited — the standard Pallas accumulation pattern), i.e. ONE
  HBM read of the activations for both statistics.
* :func:`bn_apply` — one pass computing
  ``relu((x − mean)·inv·γ + β [+ residual])`` — normalize, scale/shift,
  the optional bottleneck residual add, and ReLU fused into a single
  read(+read)→write.

Together: 2 reads + 1 write of the feature map for the full train-mode
BN+ReLU(+add) epilogue — the HBM floor for batch statistics (the mean
must exist before normalization can start).  ``tools/bench_fused_bn.py``
measures this against the stock XLA path on one ResNet stage shape; the
kernels run under ``interpret=True`` on CPU for correctness tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _stats_kernel(x_ref, out_ref):
    n = pl.program_id(1)
    x = x_ref[...].astype(jnp.float32)  # [1, CB, HW]
    s = jnp.sum(x, axis=(0, 2))         # [CB]
    sq = jnp.sum(jnp.square(x), axis=(0, 2))
    part = jnp.stack([s, sq], axis=1)   # [CB, 2]

    @pl.when(n == 0)
    def _init():
        out_ref[...] = part

    @pl.when(n > 0)
    def _acc():
        out_ref[...] += part


def bn_stats(x, c_block=8, interpret=False):
    """Per-channel (sum, sum_sq) of ``x`` [N, C, HW] in one read pass.
    Returns fp32 [C, 2]."""
    N, C, HW = x.shape
    c_block = min(c_block, C)
    while C % c_block:
        c_block -= 1
    out = pl.pallas_call(
        _stats_kernel,
        grid=(C // c_block, N),
        in_specs=[pl.BlockSpec((1, c_block, HW), lambda c, n: (n, c, 0))],
        out_specs=pl.BlockSpec((c_block, 2), lambda c, n: (c, 0)),
        out_shape=jax.ShapeDtypeStruct((C, 2), jnp.float32),
        interpret=interpret,
    )(x)
    return out


def _apply_kernel(x_ref, scale_ref, shift_ref, out_ref, *, relu):
    x = x_ref[...].astype(jnp.float32)                    # [1, CB, HW]
    y = x * scale_ref[...][None, :, :] + shift_ref[...][None, :, :]
    if relu:
        y = jnp.maximum(y, 0.0)
    out_ref[...] = y.astype(out_ref.dtype)


def _apply_res_kernel(x_ref, scale_ref, shift_ref, res_ref, out_ref, *, relu):
    x = x_ref[...].astype(jnp.float32)
    y = x * scale_ref[...][None, :, :] + shift_ref[...][None, :, :]
    y = y + res_ref[...].astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    out_ref[...] = y.astype(out_ref.dtype)


def bn_apply(x, scale, shift, residual=None, relu=True, c_block=8,
             interpret=False):
    """One-pass ``relu(x·scale + shift [+ residual])`` with per-channel
    fp32 ``scale``/``shift`` [C] (fold mean/var/γ/β/eps on the host side:
    scale = γ·rsqrt(var+eps), shift = β − mean·scale — scalars per channel,
    negligible).  Output keeps ``x.dtype``."""
    N, C, HW = x.shape
    c_block = min(c_block, C)
    while C % c_block:
        c_block -= 1
    scale2 = scale.reshape(C, 1).astype(jnp.float32)
    shift2 = shift.reshape(C, 1).astype(jnp.float32)
    spec_x = pl.BlockSpec((1, c_block, HW), lambda c, n: (n, c, 0))
    spec_s = pl.BlockSpec((c_block, 1), lambda c, n: (c, 0))
    if residual is None:
        return pl.pallas_call(
            functools.partial(_apply_kernel, relu=relu),
            grid=(C // c_block, N),
            in_specs=[spec_x, spec_s, spec_s],
            out_specs=spec_x,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret,
        )(x, scale2, shift2)
    return pl.pallas_call(
        functools.partial(_apply_res_kernel, relu=relu),
        grid=(C // c_block, N),
        in_specs=[spec_x, spec_s, spec_s, spec_x],
        out_specs=spec_x,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=interpret,
    )(x, scale2, shift2, residual)


@functools.lru_cache(maxsize=None)
def _make_trainable_bn(eps, interpret):
    """Trainable wrapper: forward runs the two Pallas passes; backward is
    jax.vjp of the reference formula (recompute — XLA fuses it into the
    backward graph, and correctness is inherited rather than hand-derived).
    Returns (out, mean, var) like ``ops.nn.batch_norm``."""
    def _ref(x, gamma, beta):
        x32 = x.astype(jnp.float32)
        axes = (0, 2, 3)
        mean = jnp.mean(x32, axis=axes)
        var = jnp.maximum(jnp.mean(jnp.square(x32), axis=axes)
                          - jnp.square(mean), 0.0)
        inv = jax.lax.rsqrt(var + eps) * gamma.astype(jnp.float32)
        out = ((x32 - mean[None, :, None, None]) * inv[None, :, None, None]
               + beta.astype(jnp.float32)[None, :, None, None])
        return out.astype(x.dtype), mean, var

    @jax.custom_vjp
    def f(x, gamma, beta):
        return _ref(x, gamma, beta)

    def fwd(x, gamma, beta):
        out, mean, var = fused_bn_relu(x, gamma, beta, eps=eps, relu=False,
                                       interpret=interpret)
        return (out, mean, var), (x, gamma, beta)

    def bwd(res, cts):
        _, vjp_fn = jax.vjp(_ref, *res)
        return vjp_fn(cts)

    f.defvjp(fwd, bwd)
    return f


def trainable_batch_norm(x_nchw, gamma, beta, eps=1e-5, interpret=False):
    """Train-mode NCHW BatchNorm with the Pallas forward and a reference
    backward — the opt-in path ``ops.nn.batch_norm`` dispatches to under
    ``MXNET_TPU_PALLAS_BN=1``."""
    return _make_trainable_bn(float(eps), bool(interpret))(x_nchw, gamma, beta)


def fused_bn_relu(x_nchw, gamma, beta, eps=1e-5, residual=None, relu=True,
                  interpret=False):
    """Train-mode BN+ReLU(+residual) over NCHW conv output via the two
    Pallas passes.  Returns ``(out, batch_mean, batch_var)`` matching the
    functional contract of ``ops.nn.batch_norm``."""
    N, C, H, W = x_nchw.shape
    x = x_nchw.reshape(N, C, H * W)
    stats = bn_stats(x, interpret=interpret)
    cnt = float(N * H * W)
    mean = stats[:, 0] / cnt
    var = jnp.maximum(stats[:, 1] / cnt - jnp.square(mean), 0.0)
    scale = gamma.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    shift = beta.astype(jnp.float32) - mean * scale
    res = residual.reshape(N, C, H * W) if residual is not None else None
    out = bn_apply(x, scale, shift, residual=res, relu=relu,
                   interpret=interpret)
    return out.reshape(N, C, H, W), mean, var
