"""Control-flow operators — ``foreach`` / ``while_loop`` / ``cond``.

Parity: [U:src/operator/control_flow.cc] (the reference registers them as
first-class ops carrying nnvm subgraphs; the Python front end is
``mx.nd.contrib.foreach/while_loop/cond``).  Here the subgraph is simply a
Python callable over NDArrays, traced by ``lax.scan`` / ``lax.cond`` —
SURVEY.md §2.1 calls this mapping near-mechanical, and it is.

Each op executes as ONE pure-jax function dispatched through
``ndarray.invoke``, so the autograd tape records a single node whose vjp
is jax's own gradient through the loop — gradients flow to the explicit
``data``/``init_states``/``loop_vars`` inputs in eager ``autograd.record``
mode.  Arrays only *closed over* by the callable (e.g. weights referenced
inside ``body``) become trace constants in eager mode and get no eager
gradient — under ``hybridize``/``SPMDTrainer`` (the performance path) the
whole step is traced functionally and closure gradients flow exactly.
This matches the spirit of the reference (its subgraph cut hoists closure
vars into explicit inputs at symbol-construction time, which an eager
Python callable cannot express).

TPU-friendliness: ``while_loop`` requires ``max_iterations`` and lowers to
a fixed-trip ``lax.scan`` with an active-mask — constant shapes and FLOPs
regardless of the dynamic trip count (results beyond the executed steps
are zeros, the reference documents them as undefined).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    if x is None:
        return [], False
    if isinstance(x, (list, tuple)):
        return list(x), True
    return [x], False


def _arrays(nds):
    from ..ndarray.ndarray import NDArray

    return [a._data if isinstance(a, NDArray) else jnp.asarray(a) for a in nds]


def _paused():
    from .. import autograd

    return autograd._scope(False, None)


def foreach(body, data, init_states):
    """``body(data_slice, states) -> (out, new_states)`` scanned over axis 0
    of ``data`` (parity: ``mx.nd.contrib.foreach``).  Returns
    ``(outputs, final_states)`` with outputs stacked on axis 0."""
    from ..ndarray.ndarray import NDArray, invoke

    data_list, multi_data = _as_list(data)
    state_list, multi_state = _as_list(init_states)
    nd_, ns_ = len(data_list), len(state_list)

    def pure(*arrays):
        xs = tuple(arrays[:nd_])
        init = tuple(arrays[nd_:])

        def scan_body(carry, x):
            with _paused():
                d = [NDArray(a) for a in x]
                s = [NDArray(c) for c in carry]
                out, new_s = body(d if multi_data else d[0],
                                  s if multi_state else (s[0] if s else []))
            outs, _ = _as_list(out)
            new, _ = _as_list(new_s)
            return tuple(o._data for o in new), tuple(o._data for o in outs)

        carry, ys = lax.scan(scan_body, init, xs)
        return tuple(ys) + tuple(carry)

    results = invoke(pure, data_list + state_list, {}, name="_foreach")
    results = results if isinstance(results, list) else [results]
    n_out = len(results) - ns_
    outs = results[:n_out]
    states = results[n_out:]
    return (outs if (len(outs) != 1) else outs[0],
            states if multi_state else (states[0] if states else []))


def while_loop(cond_fn, func, loop_vars, max_iterations=None):
    """``while cond_fn(*loop_vars): out, loop_vars = func(*loop_vars)``
    (parity: ``mx.nd.contrib.while_loop``).  ``max_iterations`` is required
    (as in the reference); lowered to a fixed-trip scan with an active mask
    so shapes/FLOPs are static.  Returns ``(outputs, final_loop_vars)``;
    output rows past the executed step count are zeros.

    Early-exit fast path: when the loop emits NO per-step outputs, the call
    is eager (concrete arrays, not inside an outer jit trace) and no
    autograd tape is recording, the loop lowers to ``lax.while_loop``
    instead — it stops at the actual trip count rather than running
    ``max_iterations`` masked iterations.  (The masked scan remains the
    traced/training form: it is differentiable and stack-shaped; lax.while_loop
    is neither.)"""
    import jax as _jax

    from ..ndarray.ndarray import NDArray, invoke

    if max_iterations is None:
        raise ValueError("while_loop requires max_iterations (static shapes on TPU)")
    var_list, multi_var = _as_list(loop_vars)
    nv = len(var_list)

    from .. import autograd as _autograd

    concrete = all(
        not isinstance(getattr(v, "_data", v), _jax.core.Tracer)
        for v in var_list)
    if concrete and not _autograd.is_recording():
        # probe the output structure abstractly (tracers, no FLOPs)
        n_outs_cell = []

        def _probe(*arrays):
            with _paused():
                out, new_vars = func(*[NDArray(a) for a in arrays])
            outs, _ = _as_list(out)
            n_outs_cell.append(len(outs))
            new, _ = _as_list(new_vars)
            return tuple(n._data for n in new)

        try:
            _jax.eval_shape(_probe, *[jnp.asarray(getattr(v, "_data", v))
                                      for v in var_list])
        except Exception:
            n_outs_cell = [None]
        if n_outs_cell and n_outs_cell[0] == 0:
            def pure_early(*arrays):
                def cond_f(carry):
                    i, vars_ = carry
                    with _paused():
                        c = cond_fn(*[NDArray(v) for v in vars_])
                    return jnp.logical_and(i < int(max_iterations),
                                           c._data.astype(bool).reshape(()))

                def body_f(carry):
                    i, vars_ = carry
                    with _paused():
                        _, new_vars = func(*[NDArray(v) for v in vars_])
                    new, _ = _as_list(new_vars)
                    return (i + 1, tuple(n._data for n in new))

                _, final = lax.while_loop(cond_f, body_f,
                                          (jnp.int32(0), tuple(arrays)))
                return tuple(final)

            results = invoke(pure_early, var_list, {}, name="_while_loop")
            results = results if isinstance(results, list) else [results]
            return [], (results if multi_var else results[0])

    def pure(*arrays):
        def scan_body(carry, _):
            active, vars_ = carry
            with _paused():
                c = cond_fn(*[NDArray(v) for v in vars_])
                out, new_vars = func(*[NDArray(v) for v in vars_])
            pred = jnp.logical_and(active, c._data.astype(bool).reshape(()))
            outs, _ = _as_list(out)
            new, _ = _as_list(new_vars)
            vars_next = tuple(jnp.where(pred, n._data, v)
                              for n, v in zip(new, vars_))
            outs_masked = tuple(jnp.where(pred, o._data, jnp.zeros_like(o._data))
                                for o in outs)
            return (pred, vars_next), outs_masked

        (_, final_vars), ys = lax.scan(
            scan_body, (jnp.bool_(True), tuple(arrays)), None,
            length=int(max_iterations))
        return tuple(ys) + tuple(final_vars)

    results = invoke(pure, var_list, {}, name="_while_loop")
    results = results if isinstance(results, list) else [results]
    n_out = len(results) - nv
    outs = results[:n_out]
    states = results[n_out:]
    return (outs if len(outs) != 1 else outs[0],
            states if multi_var else states[0])


def cond(pred, then_func, else_func):
    """``then_func() if pred else else_func()`` with both branches traced
    (parity: ``mx.nd.contrib.cond``).  Branch outputs must match in
    shape/dtype; branch callables take no arguments and close over their
    operands."""
    from ..ndarray.ndarray import NDArray, invoke

    def pure(p):
        def run(fn):
            def branch(_):
                with _paused():
                    out = fn()
                outs, _ = _as_list(out)
                return tuple(o._data for o in outs)

            return branch

        return lax.cond(p.astype(bool).reshape(()), run(then_func),
                        run(else_func), operand=None)

    results = invoke(pure, [pred], {}, name="_cond")
    return results if not isinstance(results, list) or len(results) != 1 else results[0]
