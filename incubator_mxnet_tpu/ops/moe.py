"""Mixture-of-Experts dispatch/combine kernel (the 'ep' mesh axis payload).

One registered op, :func:`moe_ffn`, computes a full top-k-routed expert
FFN layer: router logits → top-k gates → capacity-limited einsum
dispatch → per-expert two-layer FFN → weighted combine.  The dispatch is
the Mesh-TF/Switch formulation — dense one-hot [tokens, experts,
capacity] tensors instead of gather/scatter — because it is pure MXU
work, shards over 'ep' on the stacked expert dim with zero custom
collectives (XLA derives the all-to-alls from the shardings), and its
drop rule is exact and deterministic: slots are granted in (choice rank,
token position) order by a cumsum, so token t's first choice always
beats token t+1's first choice, which beats every second choice.

Static knobs (``num_experts``/``top_k``/``capacity_factor``) arrive as
kwargs → part of the dispatch-cache/compile signature; capacity derives
from the static token count, so a fixed batch shape never recompiles.

Returns ``(y, aux_loss, z_loss, tokens_dropped, load_min, load_max)`` —
losses raw (callers weight them), metrics ``stop_gradient``-ed float32
so the tuple is vjp-safe end to end.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .registry import register

__all__ = ["moe_ffn", "moe_capacity"]


def moe_capacity(n_tokens, num_experts, top_k, capacity_factor):
    """Per-expert slot budget: ``ceil(T·k/E · capacity_factor)``, clipped
    to [1, T].  Static — shapes and knobs only."""
    cap = math.ceil(n_tokens * top_k / num_experts * capacity_factor)
    return max(1, min(int(cap), int(n_tokens)))


@register("moe_ffn")
def moe_ffn(x, router_w, w1, b1, w2, b2, num_experts=1, top_k=1,
            capacity_factor=1.25, activation="relu"):
    """Top-k routed expert FFN over the last axis of ``x``.

    Shapes: ``x`` [..., d]; ``router_w`` [d, E]; ``w1`` [E, d, h];
    ``b1`` [E, h]; ``w2`` [E, h, d]; ``b2`` [E, d].  Router math runs in
    float32 regardless of ``x.dtype`` (gate ordering must not flip with
    an AMP cast); expert GEMMs run in ``x.dtype``.
    """
    E, k = int(num_experts), int(top_k)
    d = x.shape[-1]
    xt = x.reshape(-1, d)
    T = xt.shape[0]
    C = moe_capacity(T, E, k, capacity_factor)

    logits = xt.astype(jnp.float32) @ router_w.astype(jnp.float32)  # [T, E]
    z_loss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, idx = jax.lax.top_k(probs, k)                        # [T, k]
    em = jax.nn.one_hot(idx, E, dtype=jnp.float32)                  # [T, k, E]

    # slot grant order: choice-rank major, token order minor — the cumsum
    # over the [k·T, E] layout IS the priority rule (deterministic drops)
    em_flat = em.transpose(1, 0, 2).reshape(k * T, E)
    pos_flat = jnp.cumsum(em_flat, axis=0) - em_flat
    pos = pos_flat.reshape(k, T, E).transpose(1, 0, 2)              # [T, k, E]
    pos_tk = jnp.sum(pos * em, axis=-1)                             # [T, k]
    kept = (pos_tk < C).astype(jnp.float32)                         # [T, k]

    gates = gate_vals.astype(jnp.float32) * kept
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    disp = em * kept[..., None]                                     # [T, k, E]
    oh_pos = jax.nn.one_hot(pos_tk.astype(jnp.int32), C,
                            dtype=jnp.float32) * kept[..., None]    # [T, k, C]
    dispatch = jnp.einsum("tke,tkc->tec", disp, oh_pos)             # [T, E, C]
    combine = jnp.einsum("tke,tkc,tk->tec", disp, oh_pos, gates)

    cdt = x.dtype
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cdt), xt)
    h = jnp.einsum("ecd,edh->ech", expert_in, w1) + b1[:, None, :]
    if activation:
        from .nn import _ACTS

        h = _ACTS[activation](h)
    out_e = jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]
    y = jnp.einsum("tec,ecd->td", combine.astype(cdt), out_e)
    y = y.reshape(x.shape)

    # Switch-style load-balance loss: fraction routed × mean router prob,
    # summed over experts, scaled by E (uniform routing → 1.0)
    f = em.sum(axis=(0, 1)) / float(T * k)
    p_mean = probs.mean(axis=0)
    aux_loss = float(E) * jnp.sum(f * p_mean)

    sg = jax.lax.stop_gradient
    load = dispatch.sum(axis=(0, 2))                                # [E]
    tokens_dropped = sg(float(k * T) - kept.sum())
    return (y, aux_loss, z_loss, tokens_dropped,
            sg(load.min()), sg(load.max()))
