"""INT8 quantization operators.

Parity: [U:src/operator/quantization/] — ``quantize_v2`` / ``dequantize`` /
``requantize`` and the int8 compute ops (``quantized_fully_connected``,
``quantized_conv``).  The reference backs these with oneDNN/cuDNN int8
kernels; on TPU the MXU multiplies int8 natively with int32 accumulation
(``preferred_element_type=int32``), so the compute ops are one
``dot_general``/``conv_general_dilated`` with scale bookkeeping.

Scheme: symmetric signed int8 (scale = 127 / max|range|, zero-point 0) —
the reference's default for weights and its ``quantized_dtype='int8'``
activation mode.  Ranges travel with the tensors as (min, max) pairs
exactly like the reference's 3-output convention.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = [
    "quantize_v2", "dequantize", "requantize",
    "quantized_fully_connected", "quantized_conv",
]


def _scale_from_range(min_r, max_r):
    amax = jnp.maximum(jnp.abs(min_r), jnp.abs(max_r))
    return jnp.where(amax > 0, amax / 127.0, 1.0)


@register("quantize_v2")
def quantize_v2(data, min_calib_range=None, max_calib_range=None, out_type="int8"):
    """float → (int8, min_range, max_range).  With calib ranges given they
    are used (and saturating-cast applied); otherwise the tensor's own
    min/max (the reference's in-op minmax mode)."""
    if out_type != "int8":
        raise NotImplementedError("TPU path quantizes to int8 (symmetric)")
    x = data.astype(jnp.float32)
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.minimum(x.min(), 0.0)
        max_r = jnp.maximum(x.max(), 0.0)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    scale = _scale_from_range(min_r, max_r)
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, min_r.reshape(1), max_r.reshape(1)


@register("dequantize")
def dequantize(data, min_range, max_range, out_type="float32"):
    scale = _scale_from_range(min_range.reshape(()), max_range.reshape(()))
    return data.astype(jnp.float32) * scale


@register("requantize")
def requantize(data, min_range, max_range, min_calib_range=None, max_calib_range=None):
    """int32 accumulator → int8 with recomputed ranges (parity:
    requantize after quantized matmul).  The int32 range is the product of
    the two int8 scales."""
    in_scale = _scale_from_range(min_range.reshape(()), max_range.reshape(()))
    real = data.astype(jnp.float32) * in_scale
    if min_calib_range is None or max_calib_range is None:
        min_r = jnp.minimum(real.min(), 0.0)
        max_r = jnp.maximum(real.max(), 0.0)
    else:
        min_r = jnp.float32(min_calib_range)
        max_r = jnp.float32(max_calib_range)
    out_scale = _scale_from_range(min_r, max_r)
    q = jnp.clip(jnp.round(real / out_scale), -127, 127).astype(jnp.int8)
    return q, min_r.reshape(1), max_r.reshape(1)


@register("quantized_fully_connected")
def quantized_fully_connected(data, weight, bias,
                              min_data, max_data, min_weight, max_weight,
                              num_hidden=0, no_bias=False, flatten=True):
    """int8 × int8 FC with int32 accumulation on the MXU; float output
    (already dequantized — the fused requantize-to-float the reference's
    ``_sg_mkldnn_fully_connected`` performs).  data/weight: int8; bias:
    float (added post-scale, matching calibrated-graph semantics)."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    acc = lax.dot_general(
        data, weight, (((data.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32)
    s = (_scale_from_range(min_data.reshape(()), max_data.reshape(()))
         * _scale_from_range(min_weight.reshape(()), max_weight.reshape(())))
    out = acc.astype(jnp.float32) * s
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)
    return out


@register("quantized_conv")
def quantized_conv(data, weight, bias,
                   min_data, max_data, min_weight, max_weight,
                   kernel=(1, 1), stride=None, dilate=None, pad=None,
                   num_filter=0, num_group=1, no_bias=False, layout=None):
    """int8 NCHW convolution, int32 accumulation, float output."""
    from .nn import _CONV_DIMS, _tuplize

    n = len(kernel)
    stride = _tuplize(stride, n)
    dilate = _tuplize(dilate, n)
    pad = _tuplize(pad if pad is not None else 0, n)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMS[n])
    acc = lax.conv_general_dilated(
        data, weight, window_strides=stride,
        padding=[(p, p) for p in pad], rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=num_group,
        preferred_element_type=jnp.int32)
    s = (_scale_from_range(min_data.reshape(()), max_data.reshape(()))
         * _scale_from_range(min_weight.reshape(()), max_weight.reshape(())))
    out = acc.astype(jnp.float32) * s
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32).reshape((1, -1) + (1,) * n)
    return out


@register("quantize")
def quantize(data, min_range, max_range, out_type="uint8"):
    """Legacy explicit-range quantize (parity:
    [U:src/operator/quantization/quantize.cc] — quantize_v2 is the
    calibrated successor).  uint8: affine over [min, max]; int8:
    symmetric over max(|min|, |max|).  Returns (q, min, max)."""
    x = data.astype(jnp.float32)
    min_r = jnp.asarray(min_range, jnp.float32).reshape(())
    max_r = jnp.asarray(max_range, jnp.float32).reshape(())
    if out_type == "uint8":
        scale = jnp.where(max_r > min_r, 255.0 / (max_r - min_r), 1.0)
        q = jnp.clip(jnp.round((jnp.clip(x, min_r, max_r) - min_r) * scale),
                     0, 255).astype(jnp.uint8)
    elif out_type == "int8":
        scale = 127.0 / jnp.maximum(jnp.maximum(jnp.abs(min_r),
                                                jnp.abs(max_r)), 1e-30)
        q = jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
    else:
        raise NotImplementedError(f"quantize out_type {out_type!r}")
    return q, min_r.reshape(1), max_r.reshape(1)
