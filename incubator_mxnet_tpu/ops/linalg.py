"""Advanced linear-algebra operators — the full ``la_op`` family.

Parity target: [U:src/operator/tensor/la_op.cc] / la_op.cu (``linalg_gemm``,
``linalg_trmm``, ``linalg_trsm``, ``linalg_potrf``, ``linalg_potri``,
``linalg_gelqf``, ``linalg_syevd``, ``linalg_sumlogdiag``, the diag/trian
pack/unpack ops, det variants).  The reference dispatches to cuSOLVER/LAPACK;
here every op lowers through XLA's native decomposition/triangular-solve HLOs
(MXU-backed batched matmuls, vectorized solves), and every op is
differentiable through ``jax.vjp`` with no hand-written backward kernels
(the reference maintains ~40 backward La* structs).

Conventions follow the reference: all ops operate on the last two axes and
batch over leading axes; ``lower`` selects the triangle; gemm/trmm/trsm take
an ``alpha`` scale.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register

__all__ = []


def _tri_mask(n, lower, offset=0, dtype=jnp.float32):
    r = jnp.arange(n)
    if lower:
        return (r[:, None] >= (r[None, :] - offset)).astype(dtype)
    return (r[:, None] <= (r[None, :] - offset)).astype(dtype)


@register("linalg_gemm")
def linalg_gemm(a, b, c, transpose_a=False, transpose_b=False, alpha=1.0, beta=1.0):
    """C_out = alpha * op(A) @ op(B) + beta * C."""
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b) + beta * c


@register("linalg_potri")
def linalg_potri(a, lower=True):
    """Inverse of the SPD matrix whose Cholesky factor is ``a``:
    given L (lower) returns (L Lᵀ)⁻¹ = L⁻ᵀ L⁻¹."""
    n = a.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=a.dtype), a.shape)
    inv = lax.linalg.triangular_solve(a, eye, left_side=True, lower=lower)
    if lower:
        return jnp.matmul(jnp.swapaxes(inv, -1, -2), inv)
    return jnp.matmul(inv, jnp.swapaxes(inv, -1, -2))


@register("linalg_trmm")
def linalg_trmm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Triangular matmul: out = alpha * op(A) @ B (or B @ op(A) if
    ``rightside``); only the selected triangle of A participates."""
    mask = _tri_mask(a.shape[-1], lower, dtype=a.dtype)
    a = a * mask
    if transpose:
        a = jnp.swapaxes(a, -1, -2)
    out = jnp.matmul(b, a) if rightside else jnp.matmul(a, b)
    return alpha * out


@register("linalg_trsm")
def linalg_trsm(a, b, transpose=False, rightside=False, lower=True, alpha=1.0):
    """Solve op(A) X = alpha B (or X op(A) = alpha B if ``rightside``) with A
    triangular."""
    return lax.linalg.triangular_solve(
        a, alpha * b, left_side=not rightside, lower=lower,
        transpose_a=transpose)


@register("linalg_sumlogdiag")
def linalg_sumlogdiag(a):
    """Sum of log of the diagonal entries (per batch matrix)."""
    d = jnp.diagonal(a, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(d), axis=-1)


@register("linalg_extractdiag")
def linalg_extractdiag(a, offset=0):
    return jnp.diagonal(a, offset=offset, axis1=-2, axis2=-1)


@register("linalg_makediag")
def linalg_makediag(v, offset=0):
    n = v.shape[-1] + abs(offset)
    eye = jnp.eye(n, k=offset, dtype=v.dtype)
    idx = jnp.arange(v.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    out = jnp.zeros(v.shape[:-1] + (n, n), dtype=v.dtype)
    return out.at[..., rows, cols].set(v)


def _trian_indices(n, offset, lower):
    if lower:
        rows, cols = jnp.tril_indices(n, k=offset)
    else:
        rows, cols = jnp.triu_indices(n, k=offset)
    return rows, cols


@register("linalg_extracttrian")
def linalg_extracttrian(a, offset=0, lower=True):
    """Pack the selected triangle into a vector (row-major order of the
    triangle entries, matching the reference's copy order)."""
    rows, cols = _trian_indices(a.shape[-1], offset, lower)
    return a[..., rows, cols]


@register("linalg_maketrian")
def linalg_maketrian(v, offset=0, lower=True):
    """Unpack a packed-triangle vector into an otherwise-zero square matrix
    (inverse of ``linalg_extracttrian``)."""
    # infer n from the packed length: len = n(n+1)/2 shifted by the offset band
    L = v.shape[-1]
    k = abs(offset)
    # solve m(m+1)/2 = L where m = n - k  (entries of the shifted triangle)
    m = int((-1 + (1 + 8 * L) ** 0.5) // 2)
    n = m + k
    rows, cols = _trian_indices(n, offset, lower)
    out = jnp.zeros(v.shape[:-1] + (n, n), dtype=v.dtype)
    return out.at[..., rows, cols].set(v)


@register("linalg_gelqf")
def linalg_gelqf(a):
    """LQ factorization A = L Q with Q orthonormal rows (m ≤ n).  Returns
    (Q, L) like the reference (two outputs)."""
    q2, r2 = jnp.linalg.qr(jnp.swapaxes(a, -1, -2))
    q = jnp.swapaxes(q2, -1, -2)
    l = jnp.swapaxes(r2, -1, -2)
    # sign-normalize so diag(L) >= 0 (LAPACK convention the reference tests)
    s = jnp.sign(jnp.diagonal(l, axis1=-2, axis2=-1))
    s = jnp.where(s == 0, 1.0, s).astype(a.dtype)
    return q * s[..., :, None], l * s[..., None, :]


@register("linalg_syevd")
def linalg_syevd(a):
    """Symmetric eigendecomposition: returns (U, L) with A = Uᵀ diag(L) U —
    eigenvectors in ROWS (the reference's convention, transposed from
    LAPACK's columns)."""
    w, v = jnp.linalg.eigh(a)
    return jnp.swapaxes(v, -1, -2), w


@register("linalg_inverse")
def linalg_inverse(a):
    return jnp.linalg.inv(a)


@register("linalg_det")
def linalg_det(a):
    return jnp.linalg.det(a)


@register("linalg_slogdet")
def linalg_slogdet(a):
    sign, logabs = jnp.linalg.slogdet(a)
    return sign, logabs


@register("khatri_rao")
def khatri_rao(*matrices):
    """Column-wise Kronecker product (parity: [U:src/operator/contrib/
    krprod.cc]).  All inputs share the trailing (column) dimension."""
    if not matrices:
        raise ValueError("khatri_rao needs at least one matrix")
    out = matrices[0]
    for m in matrices[1:]:
        out = (out[:, None, :] * m[None, :, :]).reshape(-1, out.shape[-1])
    return out


@register("moments")
def moments(data, axes=None, keepdims=False):
    """Mean and variance over ``axes`` (parity: [U:src/operator/nn/moments.cc]).
    One-pass E[x²]−E[x]² form so both statistics fuse into a single read."""
    ax = tuple(axes) if axes is not None else None
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data), axis=ax, keepdims=True) - jnp.square(mean)
    if not keepdims:
        mean = jnp.squeeze(mean, axis=ax) if ax else mean.reshape(())
        var = jnp.squeeze(var, axis=ax) if ax else var.reshape(())
    return mean, var
