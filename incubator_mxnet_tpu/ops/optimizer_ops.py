"""Fused optimizer update kernels (parity: [U:src/operator/optimizer_op.cc] —
``sgd_update``, ``sgd_mom_update``, ``adam_update``, ``ftrl_update``,
``lamb_*``, multi-precision variants).

Each update is one jitted pure function; hyperparameters are passed as
0-d arrays so lr schedules don't trigger retraces.  ``clip`` uses +inf as
the no-clip sentinel to keep one compiled graph.  Multi-precision (bf16
weights + fp32 master copy) mirrors the reference's mp_* variants.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _prep(grad, rescale, clip, wd, weight):
    g = grad.astype(jnp.float32) * rescale
    g = jnp.clip(g, -clip, clip)
    return g + wd * weight.astype(jnp.float32)


@jax.jit
def sgd_update(weight, grad, lr, wd, rescale, clip):
    g = _prep(grad, rescale, clip, wd, weight)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@jax.jit
def sgd_mom_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom - lr * g
    return (weight.astype(jnp.float32) + new_mom).astype(weight.dtype), new_mom


def _row_active(grad):
    """Mask of rows touched by the gradient — the TPU-native stand-in for
    the reference's row_sparse index list ([U:src/operator/optimizer_op.cc]
    sparse variants): lazy SEMANTICS (untouched rows skip state decay /
    wd), dense compute (static shapes, no gather of dynamic row sets)."""
    active = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))
    return active.reshape((-1,) + (1,) * (grad.ndim - 1))


@jax.jit
def sgd_lazy_update(weight, grad, lr, wd, rescale, clip):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_w = (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)
    return jnp.where(a, new_w, weight)


@jax.jit
def mp_sgd_mom_lazy_update(weight, grad, mom, weight32, lr, wd, rescale, clip, momentum):
    a = _row_active(grad)
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip) + wd * weight32
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return (jnp.where(a, new_w32.astype(weight.dtype), weight),
            jnp.where(a, new_mom, mom), jnp.where(a, new_w32, weight32))


@jax.jit
def sgd_mom_lazy_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom - lr * g
    new_w = (weight.astype(jnp.float32) + new_mom).astype(weight.dtype)
    return jnp.where(a, new_w, weight), jnp.where(a, new_mom, mom)


@jax.jit
def adam_lazy_update(weight, grad, mean, var, lr, wd, rescale, clip, beta1, beta2, eps, t):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    upd = lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    new_w = (weight.astype(jnp.float32) - upd).astype(weight.dtype)
    return (jnp.where(a, new_w, weight), jnp.where(a, new_mean, mean),
            jnp.where(a, new_var, var))


@jax.jit
def nag_mom_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom + g
    update = momentum * new_mom + g
    return (weight.astype(jnp.float32) - lr * update).astype(weight.dtype), new_mom


@jax.jit
def adam_update(weight, grad, mean, var, lr, wd, rescale, clip, beta1, beta2, eps, t):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    upd = lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_mean, new_var


@jax.jit
def adamw_update(weight, grad, mean, var, lr, wd, eta, rescale, clip, beta1, beta2, eps, t):
    w32 = weight.astype(jnp.float32)
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    upd = (new_mean / coef1) / (jnp.sqrt(new_var / coef2) + eps) + wd * w32
    return (w32 - eta * lr * upd).astype(weight.dtype), new_mean, new_var


@jax.jit
def rmsprop_update(weight, grad, n, lr, wd, rescale, clip, rho, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    upd = lr * g / jnp.sqrt(new_n + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_n


@jax.jit
def rmspropalex_update(weight, grad, n, g_avg, delta, lr, wd, rescale, clip, rho, momentum, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    new_g = rho * g_avg + (1 - rho) * g
    new_delta = momentum * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + eps)
    return (weight.astype(jnp.float32) + new_delta).astype(weight.dtype), new_n, new_g, new_delta


@jax.jit
def adagrad_update(weight, grad, history, lr, wd, rescale, clip, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_hist = history + jnp.square(g)
    upd = lr * g / (jnp.sqrt(new_hist) + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_hist


@jax.jit
def adadelta_update(weight, grad, acc_g, acc_delta, wd, rescale, clip, rho, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + eps) / jnp.sqrt(new_acc_g + eps) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return (weight.astype(jnp.float32) - delta).astype(weight.dtype), new_acc_g, new_acc_delta


@jax.jit
def ftrl_update(weight, grad, z, n, lr, wd, rescale, clip, lamda1, beta):
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip)
    w32 = weight.astype(jnp.float32)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * w32
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        0.0,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return new_w.astype(weight.dtype), new_z, new_n


@jax.jit
def signum_update(weight, grad, mom, lr, wd, rescale, clip, momentum, wd_lh):
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip)
    w32 = weight.astype(jnp.float32)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * w32)
    new_w = (1 - lr * wd_lh) * w32 + lr * jnp.sign(new_mom)
    return new_w.astype(weight.dtype), new_mom


@jax.jit
def lamb_update_phase1(weight, grad, mean, var, wd, rescale, clip, beta1, beta2, eps, t, bias_correction):
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip)
    w32 = weight.astype(jnp.float32)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    mean_hat = jnp.where(bias_correction, new_mean / (1 - beta1 ** t), new_mean)
    var_hat = jnp.where(bias_correction, new_var / (1 - beta2 ** t), new_var)
    r = mean_hat / (jnp.sqrt(var_hat) + eps) + wd * w32
    return r, new_mean, new_var


@jax.jit
def lamb_update_phase2(weight, r, lr, lower_bound, upper_bound):
    w32 = weight.astype(jnp.float32)
    w_norm = jnp.linalg.norm(w32)
    r_norm = jnp.linalg.norm(r)
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    ratio = jnp.clip(ratio, lower_bound, upper_bound)
    return (w32 - lr * ratio * r).astype(weight.dtype)


# -- multi-precision (fp32 master weights for bf16/fp16 params) -------------


@jax.jit
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, wd, rescale, clip, momentum):
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip) + wd * weight32
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@jax.jit
def mp_adam_update(weight, grad, mean, var, weight32, lr, wd, rescale, clip, beta1, beta2, eps, t):
    g = jnp.clip(grad.astype(jnp.float32) * rescale, -clip, clip) + wd * weight32
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    new_w32 = weight32 - lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32
