"""Fused optimizer update kernels (parity: [U:src/operator/optimizer_op.cc] —
``sgd_update``, ``sgd_mom_update``, ``adam_update``, ``ftrl_update``,
``lamb_*``, multi-precision variants).

Each update is one jitted pure function; hyperparameters are passed as
0-d arrays so lr schedules don't trigger retraces.  ``clip`` uses +inf as
the no-clip sentinel to keep one compiled graph.  Multi-precision (bf16
weights + fp32 master copy) mirrors the reference's mp_* variants.
"""
from __future__ import annotations

import warnings
from time import perf_counter as _perf

import jax
import jax.numpy as jnp


def _gclip(g, clip):
    """Gradient clipping with BOTH no-clip sentinels honored: the
    reference's public ops clip iff ``clip_gradient >= 0`` (default -1 =
    don't clip, [U:src/operator/optimizer_op-inl.h]; 0 clamps to zero),
    while the internal optimizer framework passes +inf (inf takes the
    clip branch and is a no-op).  One jnp.where keeps a single compiled
    graph either way."""
    clip = jnp.asarray(clip, jnp.float32)
    return jnp.where(clip >= 0, jnp.clip(g, -clip, clip), g)


def _prep(grad, rescale, clip, wd, weight):
    g = grad.astype(jnp.float32) * rescale
    g = _gclip(g, clip)
    return g + wd * weight.astype(jnp.float32)


@jax.jit
def sgd_update(weight, grad, lr, wd, rescale, clip):
    g = _prep(grad, rescale, clip, wd, weight)
    return (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)


@jax.jit
def sgd_mom_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom - lr * g
    return (weight.astype(jnp.float32) + new_mom).astype(weight.dtype), new_mom


def _row_active(grad):
    """Mask of rows touched by the gradient — the TPU-native stand-in for
    the reference's row_sparse index list ([U:src/operator/optimizer_op.cc]
    sparse variants): lazy SEMANTICS (untouched rows skip state decay /
    wd), dense compute (static shapes, no gather of dynamic row sets)."""
    active = jnp.any(grad != 0, axis=tuple(range(1, grad.ndim)))
    return active.reshape((-1,) + (1,) * (grad.ndim - 1))


@jax.jit
def sgd_lazy_update(weight, grad, lr, wd, rescale, clip):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_w = (weight.astype(jnp.float32) - lr * g).astype(weight.dtype)
    return jnp.where(a, new_w, weight)


@jax.jit
def mp_sgd_mom_lazy_update(weight, grad, mom, weight32, lr, wd, rescale, clip, momentum):
    a = _row_active(grad)
    g = _gclip(grad.astype(jnp.float32) * rescale, clip) + wd * weight32
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return (jnp.where(a, new_w32.astype(weight.dtype), weight),
            jnp.where(a, new_mom, mom), jnp.where(a, new_w32, weight32))


@jax.jit
def sgd_mom_lazy_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom - lr * g
    new_w = (weight.astype(jnp.float32) + new_mom).astype(weight.dtype)
    return jnp.where(a, new_w, weight), jnp.where(a, new_mom, mom)


@jax.jit
def adam_lazy_update(weight, grad, mean, var, lr, wd, rescale, clip, beta1, beta2, eps, t):
    a = _row_active(grad)
    g = _prep(grad, rescale, clip, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    upd = lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    new_w = (weight.astype(jnp.float32) - upd).astype(weight.dtype)
    return (jnp.where(a, new_w, weight), jnp.where(a, new_mean, mean),
            jnp.where(a, new_var, var))


@jax.jit
def nag_mom_update(weight, grad, mom, lr, wd, rescale, clip, momentum):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mom = momentum * mom + g
    update = momentum * new_mom + g
    return (weight.astype(jnp.float32) - lr * update).astype(weight.dtype), new_mom


@jax.jit
def adam_update(weight, grad, mean, var, lr, wd, rescale, clip, beta1, beta2, eps, t):
    g = _prep(grad, rescale, clip, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    lr_t = lr * jnp.sqrt(coef2) / coef1
    upd = lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_mean, new_var


@jax.jit
def adamw_update(weight, grad, mean, var, lr, wd, eta, rescale, clip, beta1, beta2, eps, t):
    w32 = weight.astype(jnp.float32)
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    coef1 = 1 - beta1 ** t
    coef2 = 1 - beta2 ** t
    upd = (new_mean / coef1) / (jnp.sqrt(new_var / coef2) + eps) + wd * w32
    return (w32 - eta * lr * upd).astype(weight.dtype), new_mean, new_var


@jax.jit
def rmsprop_update(weight, grad, n, lr, wd, rescale, clip, rho, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    upd = lr * g / jnp.sqrt(new_n + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_n


@jax.jit
def rmspropalex_update(weight, grad, n, g_avg, delta, lr, wd, rescale, clip, rho, momentum, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_n = rho * n + (1 - rho) * jnp.square(g)
    new_g = rho * g_avg + (1 - rho) * g
    new_delta = momentum * delta - lr * g / jnp.sqrt(new_n - jnp.square(new_g) + eps)
    return (weight.astype(jnp.float32) + new_delta).astype(weight.dtype), new_n, new_g, new_delta


@jax.jit
def adagrad_update(weight, grad, history, lr, wd, rescale, clip, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_hist = history + jnp.square(g)
    upd = lr * g / (jnp.sqrt(new_hist) + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_hist


@jax.jit
def adadelta_update(weight, grad, acc_g, acc_delta, wd, rescale, clip, rho, eps):
    g = _prep(grad, rescale, clip, wd, weight)
    new_acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
    delta = jnp.sqrt(acc_delta + eps) / jnp.sqrt(new_acc_g + eps) * g
    new_acc_delta = rho * acc_delta + (1 - rho) * jnp.square(delta)
    return (weight.astype(jnp.float32) - delta).astype(weight.dtype), new_acc_g, new_acc_delta


@jax.jit
def ftrl_update(weight, grad, z, n, lr, wd, rescale, clip, lamda1, beta):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    w32 = weight.astype(jnp.float32)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    new_z = z + g - sigma * w32
    new_w = jnp.where(
        jnp.abs(new_z) <= lamda1,
        0.0,
        -(new_z - jnp.sign(new_z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd),
    )
    return new_w.astype(weight.dtype), new_z, new_n


@jax.jit
def signum_update(weight, grad, mom, lr, wd, rescale, clip, momentum, wd_lh):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    w32 = weight.astype(jnp.float32)
    new_mom = momentum * mom - (1 - momentum) * (g + wd * w32)
    new_w = (1 - lr * wd_lh) * w32 + lr * jnp.sign(new_mom)
    return new_w.astype(weight.dtype), new_mom


@jax.jit
def lamb_update_phase1(weight, grad, mean, var, wd, rescale, clip, beta1, beta2, eps, t, bias_correction):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    w32 = weight.astype(jnp.float32)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    mean_hat = jnp.where(bias_correction, new_mean / (1 - beta1 ** t), new_mean)
    var_hat = jnp.where(bias_correction, new_var / (1 - beta2 ** t), new_var)
    r = mean_hat / (jnp.sqrt(var_hat) + eps) + wd * w32
    return r, new_mean, new_var


@jax.jit
def lamb_update_phase2(weight, r, lr, lower_bound, upper_bound):
    w32 = weight.astype(jnp.float32)
    w_norm = jnp.linalg.norm(w32)
    r_norm = jnp.linalg.norm(r)
    ratio = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    ratio = jnp.clip(ratio, lower_bound, upper_bound)
    return (w32 - lr * ratio * r).astype(weight.dtype)


# -- multi-precision (fp32 master weights for bf16/fp16 params) -------------


@jax.jit
def mp_sgd_mom_update(weight, grad, mom, weight32, lr, wd, rescale, clip, momentum):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip) + wd * weight32
    new_mom = momentum * mom - lr * g
    new_w32 = weight32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@jax.jit
def mp_adam_update(weight, grad, mean, var, weight32, lr, wd, rescale, clip, beta1, beta2, eps, t):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip) + wd * weight32
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - beta2 ** t) / (1 - beta1 ** t)
    new_w32 = weight32 - lr_t * new_mean / (jnp.sqrt(new_var) + eps)
    return new_w32.astype(weight.dtype), new_mean, new_var, new_w32


@jax.jit
def mp_sgd_update(weight, grad, weight32, lr, wd, rescale, clip):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip) + wd * weight32
    new_w32 = weight32 - lr * g
    return new_w32.astype(weight.dtype), new_w32


@jax.jit
def mp_nag_mom_update(weight, grad, mom, weight32, lr, wd, rescale, clip, momentum):
    g = _gclip(grad.astype(jnp.float32) * rescale, clip) + wd * weight32
    new_mom = momentum * mom + g
    new_w32 = weight32 - lr * (momentum * new_mom + g)
    return new_w32.astype(weight.dtype), new_mom, new_w32


@jax.jit
def nadam_update(weight, grad, mean, var, m_schedule, lr, wd, rescale, clip,
                 beta1, beta2, eps, t, schedule_decay):
    """Nesterov Adam ([U:python/mxnet/optimizer/optimizer.py] Nadam, Dozat
    2016).  ``m_schedule`` is the running momentum-schedule product the
    python reference keeps as optimizer state — carried here as a 0-d state
    array so the kernel stays a pure function of (state, t)."""
    g = _prep(grad, rescale, clip, wd, weight)
    m_t = beta1 * (1.0 - 0.5 * 0.96 ** (t * schedule_decay))
    m_t1 = beta1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * schedule_decay))
    new_schedule = m_schedule * m_t
    schedule_next = new_schedule * m_t1
    new_mean = beta1 * mean + (1 - beta1) * g
    new_var = beta2 * var + (1 - beta2) * jnp.square(g)
    g_hat = g / (1.0 - new_schedule)
    m_hat = new_mean / (1.0 - schedule_next)
    v_hat = new_var / (1.0 - beta2 ** t)
    upd = lr * ((1.0 - m_t) * g_hat + m_t1 * m_hat) / (jnp.sqrt(v_hat) + eps)
    return ((weight.astype(jnp.float32) - upd).astype(weight.dtype),
            new_mean, new_var, new_schedule)


@jax.jit
def ftml_update(weight, grad, d, v, z, lr, wd, rescale, clip, beta1, beta2, eps, t):
    """FTML (Zheng & Kwok 2017; parity: [U:src/operator/optimizer_op.cc]
    ftml_update)."""
    g = _prep(grad, rescale, clip, wd, weight)
    new_v = beta2 * v + (1 - beta2) * jnp.square(g)
    d_t = (1 - beta1 ** t) / lr * (jnp.sqrt(new_v / (1 - beta2 ** t)) + eps)
    sigma = d_t - beta1 * d
    new_z = beta1 * z + (1 - beta1) * g - sigma * weight.astype(jnp.float32)
    new_w = -new_z / d_t
    return new_w.astype(weight.dtype), d_t, new_v, new_z


@jax.jit
def sgld_update(weight, grad, lr, wd, rescale, clip, noise):
    """Stochastic Gradient Langevin Dynamics: SGD + N(0, sqrt(lr)) noise
    (parity: the python SGLD optimizer in [U:python/mxnet/optimizer/])."""
    g = _prep(grad, rescale, clip, wd, weight)
    w32 = weight.astype(jnp.float32)
    return (w32 - 0.5 * lr * g + jnp.sqrt(lr) * noise).astype(weight.dtype)


@jax.jit
def dcasgd_update(weight, grad, mom, prev_weight, lr, wd, rescale, clip, momentum, lamda):
    """Delay-Compensated ASGD (Zheng et al. 2017): compensates stale
    gradients with a λ·g²·(w − w_prev) term (g excludes wd, matching the
    reference recurrence)."""
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    w32 = weight.astype(jnp.float32)
    comp = g + wd * w32 + lamda * jnp.square(g) * (w32 - prev_weight)
    new_mom = momentum * mom - lr * comp
    new_w32 = w32 + new_mom
    return new_w32.astype(weight.dtype), new_mom, new_w32


@jax.jit
def adamax_update(weight, grad, mean, inf_norm, lr, wd, rescale, clip, beta1, beta2):
    """AdaMax (Kingma & Ba): the infinity-norm Adam variant."""
    g = _prep(grad, rescale, clip, wd, weight)
    new_mean = beta1 * mean + (1 - beta1) * g
    new_inf = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    upd = lr * new_mean / (new_inf + 1e-8)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_mean, new_inf


@jax.jit
def group_adagrad_update(weight, grad, history, lr, rescale, clip, eps):
    """GroupAdaGrad ([U:src/operator/contrib/optimizer_op.cc]): AdaGrad
    with ONE accumulated statistic per row (group) instead of per element
    — the embedding-table optimizer."""
    g = _gclip(grad.astype(jnp.float32) * rescale, clip)
    row_sq = jnp.mean(jnp.square(g), axis=tuple(range(1, g.ndim)),
                      keepdims=True)
    new_hist = history + row_sq
    upd = lr * g / (jnp.sqrt(new_hist) + eps)
    return (weight.astype(jnp.float32) - upd).astype(weight.dtype), new_hist


# -- multi-tensor (grouped) update machinery --------------------------------
# Parity: [U:src/operator/optimizer_op.cc] multi_sgd_update /
# multi_sgd_mom_update / multi_mp_sgd_* — ONE fused kernel updating a whole
# parameter group.  The group is passed as list pytrees (weights, grads,
# per-param state tuples) with per-param lr/wd/t as stacked device arrays
# and scalar hypers as dynamic 0-d args, so neither lr-schedule changes nor
# hyper changes retrace; jit's aval cache keys on the group's shapes.  With
# ``donate=True`` XLA reuses the weight and state buffers in place (the
# Trainer fused-step path; see docs/optimizer_fusion.md for the aliasing
# caveat).  One dispatch (and one lr/wd transfer) for hundreds of tensors.

_GROUP_JIT = {}


def _group_fn(step, donate):
    fn = _GROUP_JIT.get((step, donate))
    if fn is None:
        if donate:
            # backends without real donation warn per compile; semantics are
            # unchanged (XLA falls back to copying), so keep the fused path
            # quiet.  Installed lazily on the FIRST donating group build —
            # never for the non-donating multi_* ops or with
            # MXNET_OPTIMIZER_DONATE=0 — so user jits keep the diagnostic
            # until they opt into this machinery.
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
        def body(weights, grads, states, lrs, wds, ts, scalars):
            new_w, new_s = [], []
            for i in range(len(weights)):
                nw, ns = step(weights[i], grads[i], states[i],
                              lrs[i], wds[i], ts[i], scalars)
                new_w.append(nw)
                new_s.append(list(ns))
            return new_w, new_s
        fn = jax.jit(body, donate_argnums=(0, 2) if donate else ())
        _GROUP_JIT[(step, donate)] = fn
    return fn


def group_apply(step, weights, grads, states, lrs, wds, ts, scalars,
                donate=False):
    """Apply a per-tensor ``step(w, g, state_tuple, lr, wd, t, scalars)``
    adapter to a whole parameter group in ONE jitted dispatch.

    ``states`` is a list of per-param state tuples (flat arrays), ``lrs`` /
    ``wds`` / ``ts`` are per-param sequences stacked into device arrays, and
    ``scalars`` is a dict of group-wide hypers traced as 0-d arrays.  When
    ``donate`` is set the weight and state buffers are donated to XLA
    (in-place reuse); callers must guarantee no live aliases."""
    weights, grads = list(weights), list(grads)
    states = [list(s) for s in states]
    lrs = jnp.asarray(lrs, jnp.float32)
    wds = jnp.asarray(wds, jnp.float32)
    ts = jnp.asarray(ts, jnp.float32)
    scalars = {k: jnp.asarray(v, jnp.float32) for k, v in scalars.items()}
    fn = _group_fn(step, donate)
    from .registry import _counters

    prof = _counters()
    n0 = prof.jit_cache_size(fn)  # exact O(1) did-this-compile probe
    tc = _perf()
    out = fn(weights, grads, states, lrs, wds, ts, scalars)
    if n0 >= 0 and prof.jit_cache_size(fn) > n0:
        # program = adapter name only: a group-SIZE drift (rechunking)
        # should attribute as an added/removed w<i> argument, not hide
        # behind a "different program".  (shape/dtype are aval metadata —
        # safe to read off donated-and-deleted input buffers)
        name = getattr(step, "__name__", str(step))
        sig = {"__program__": f"group:{name}",
               "donate": {"k": "static", "value": repr(donate)}}
        for i, w in enumerate(weights):
            sig[f"w{i}"] = {"k": "array", "shape": tuple(w.shape),
                            "dtype": str(w.dtype)}
        try:
            prof.record_compile("optimizer.group_apply", sig,
                                (_perf() - tc) * 1e3)
        except prof.CompileGuardError as e:
            # the inputs were DONATED: if this guard-raise escaped bare,
            # the caller could never swap the new buffers in and every
            # weight/state in the group would be left deleted.  Ship the
            # result on the exception so fused_update can wire it first
            # and then re-raise.
            e.group_result = out
            raise
    return out


# Per-tensor step adapters over the fused kernels above — the shared
# vocabulary of group_apply: the public multi_* ops and the Trainer fused
# step (optimizer/fused.py) compose the SAME adapters, so their numerics
# cannot drift from the per-tensor kernels they inline.

def sgd_step(w, g, st, lr, wd, t, S):
    return sgd_update(w, g, lr, wd, S["rescale"], S["clip"]), ()


def sgd_mom_step(w, g, st, lr, wd, t, S):
    nw, nm = sgd_mom_update(w, g, st[0], lr, wd, S["rescale"], S["clip"],
                            S["momentum"])
    return nw, (nm,)


def mp_sgd_step(w, g, st, lr, wd, t, S):
    nw, nw32 = mp_sgd_update(w, g, st[0], lr, wd, S["rescale"], S["clip"])
    return nw, (nw32,)


def mp_sgd_mom_step(w, g, st, lr, wd, t, S):
    nw, nm, nw32 = mp_sgd_mom_update(w, g, st[0], st[1], lr, wd, S["rescale"],
                                     S["clip"], S["momentum"])
    return nw, (nm, nw32)


def nag_mom_step(w, g, st, lr, wd, t, S):
    nw, nm = nag_mom_update(w, g, st[0], lr, wd, S["rescale"], S["clip"],
                            S["momentum"])
    return nw, (nm,)


def mp_nag_mom_step(w, g, st, lr, wd, t, S):
    nw, nm, nw32 = mp_nag_mom_update(w, g, st[0], st[1], lr, wd, S["rescale"],
                                     S["clip"], S["momentum"])
    return nw, (nm, nw32)


def adam_step(w, g, st, lr, wd, t, S):
    nw, nm, nv = adam_update(w, g, st[0], st[1], lr, wd, S["rescale"],
                             S["clip"], S["beta1"], S["beta2"], S["epsilon"], t)
    return nw, (nm, nv)


def mp_adam_step(w, g, st, lr, wd, t, S):
    nw, nm, nv, nw32 = mp_adam_update(w, g, st[0], st[1], st[2], lr, wd,
                                      S["rescale"], S["clip"], S["beta1"],
                                      S["beta2"], S["epsilon"], t)
    return nw, (nm, nv, nw32)


def adamw_step(w, g, st, lr, wd, t, S):
    nw, nm, nv = adamw_update(w, g, st[0], st[1], lr, wd, S["eta"],
                              S["rescale"], S["clip"], S["beta1"], S["beta2"],
                              S["epsilon"], t)
    return nw, (nm, nv)


def rmsprop_step(w, g, st, lr, wd, t, S):
    nw, nn = rmsprop_update(w, g, st[0], lr, wd, S["rescale"], S["clip"],
                            S["rho"], S["epsilon"])
    return nw, (nn,)


def rmspropalex_step(w, g, st, lr, wd, t, S):
    nw, nn, ng, nd = rmspropalex_update(w, g, st[0], st[1], st[2], lr, wd,
                                        S["rescale"], S["clip"], S["rho"],
                                        S["momentum"], S["epsilon"])
    return nw, (nn, ng, nd)


def lamb_step(w, g, st, lr, wd, t, S):
    """LAMB inside a fused group: phase1 (adaptive moment direction) then
    phase2 (PER-TENSOR trust ratio — ``jnp.linalg.norm`` of this weight
    and its update direction, computed inside the group body, so every
    parameter of the group keeps its own layerwise rate exactly as the
    per-tensor path does)."""
    r, nm, nv = lamb_update_phase1(w, g, st[0], st[1], wd, S["rescale"],
                                   S["clip"], S["beta1"], S["beta2"],
                                   S["epsilon"], t,
                                   S["bias_correction"] != 0)
    nw = lamb_update_phase2(w, r, lr, S["lower_bound"], S["upper_bound"])
    return nw, (nm, nv)


# The public grouped ops, now genuinely single-dispatch.  clip_gradient
# keeps the REFERENCE sentinel everywhere: ``< 0`` = no clipping, ``0``
# clamps gradients to zero (the old ``> 0``-to-inf mapping silently
# disabled clipping for clip_gradient=0.0, diverging from _gclip).


def multi_sgd_update(weights, grads, lrs, wds, rescale_grad=1.0,
                     clip_gradient=-1.0):
    weights = list(weights)
    new_w, _ = group_apply(
        sgd_step, weights, grads, [()] * len(weights), lrs, wds,
        [0.0] * len(weights),
        {"rescale": rescale_grad, "clip": clip_gradient})
    return new_w


def multi_sgd_mom_update(weights, grads, moms, lrs, wds, momentum=0.0,
                         rescale_grad=1.0, clip_gradient=-1.0):
    weights = list(weights)
    new_w, new_s = group_apply(
        sgd_mom_step, weights, grads, [(m,) for m in moms], lrs, wds,
        [0.0] * len(weights),
        {"rescale": rescale_grad, "clip": clip_gradient, "momentum": momentum})
    return new_w, [s[0] for s in new_s]


def multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                        rescale_grad=1.0, clip_gradient=-1.0):
    weights = list(weights)
    new_w, new_s = group_apply(
        mp_sgd_step, weights, grads, [(w32,) for w32 in weights32], lrs, wds,
        [0.0] * len(weights),
        {"rescale": rescale_grad, "clip": clip_gradient})
    return new_w, [s[0] for s in new_s]


def multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs, wds,
                            momentum=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    weights = list(weights)
    new_w, new_s = group_apply(
        mp_sgd_mom_step, weights, grads,
        [(m, w32) for m, w32 in zip(moms, weights32)], lrs, wds,
        [0.0] * len(weights),
        {"rescale": rescale_grad, "clip": clip_gradient, "momentum": momentum})
    return new_w, [s[0] for s in new_s], [s[1] for s in new_s]


# -- preloaded (device-resident lr/wd) group variants ------------------------
# Parity: [U:src/operator/contrib/preloaded_multi_sgd-inl.h] — identical to
# multi_sgd_* except learning rates and weight decays arrive as device
# ARRAYS (one element per tensor), not host scalars, so a training loop can
# update lr on-device without a host sync.  group_apply already stacks lr/wd
# into device arrays, so these are the same single-dispatch calls.


def preloaded_multi_sgd_update(weights, grads, lrs, wds,
                               rescale_grad=1.0, clip_gradient=-1.0):
    return multi_sgd_update(weights, grads, lrs, wds, rescale_grad,
                            clip_gradient)


def preloaded_multi_sgd_mom_update(weights, grads, moms, lrs, wds, momentum=0.0,
                                   rescale_grad=1.0, clip_gradient=-1.0):
    return multi_sgd_mom_update(weights, grads, moms, lrs, wds, momentum,
                                rescale_grad, clip_gradient)


def preloaded_multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                                  rescale_grad=1.0, clip_gradient=-1.0):
    return multi_mp_sgd_update(weights, grads, weights32, lrs, wds,
                               rescale_grad, clip_gradient)


def preloaded_multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs, wds,
                                      momentum=0.0, rescale_grad=1.0,
                                      clip_gradient=-1.0):
    return multi_mp_sgd_mom_update(weights, grads, moms, weights32, lrs, wds,
                                   momentum, rescale_grad, clip_gradient)


def multi_sum_sq(*arrays):
    """Per-tensor sum of squares, one fused pass (parity:
    [U:src/operator/contrib/multi_sum_sq.cc]; feeds multi_lars)."""
    return jnp.stack([jnp.sum(jnp.square(a.astype(jnp.float32))) for a in arrays])


def multi_lars(lrs, weights_sum_sq, grads_sum_sq, wds, eta=0.001, eps=1e-8,
               rescale_grad=1.0):
    """LARS layerwise rates from the stacked norms (parity:
    [U:src/operator/contrib/multi_lars.cc])."""
    w_norm = jnp.sqrt(weights_sum_sq)
    g_norm = jnp.sqrt(grads_sum_sq) * rescale_grad
    ratio = eta * w_norm / (g_norm + wds * w_norm + eps)
    return jnp.where(w_norm > 0, lrs * jnp.where(g_norm > 0, ratio, 1.0), lrs)


def all_finite(*arrays):
    """True iff every element of every array is finite (parity:
    [U:src/operator/contrib/all_finite.cc]; the AMP overflow check)."""
    ok = jnp.bool_(True)
    for a in arrays:
        ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(a.astype(jnp.float32))))
    return ok


multi_all_finite = all_finite


def _register_public_ops():
    """Expose the fused update kernels through the op registry —
    ``mx.nd.sgd_update`` etc. are public API in the reference
    ([U:src/operator/optimizer_op.cc] registration block)."""
    from .registry import register as _reg

    for fn in (
        sgd_update, sgd_mom_update, sgd_lazy_update, sgd_mom_lazy_update,
        mp_sgd_update, mp_sgd_mom_update, mp_sgd_mom_lazy_update,
        nag_mom_update, mp_nag_mom_update,
        adam_update, adam_lazy_update, mp_adam_update, adamw_update,
        nadam_update, ftml_update, sgld_update, dcasgd_update, adamax_update,
        rmsprop_update, rmspropalex_update, adagrad_update,
        group_adagrad_update, adadelta_update,
        ftrl_update, signum_update, lamb_update_phase1, lamb_update_phase2,
        multi_sgd_update, multi_sgd_mom_update, multi_mp_sgd_update,
        multi_mp_sgd_mom_update, preloaded_multi_sgd_update,
        preloaded_multi_sgd_mom_update, preloaded_multi_mp_sgd_update,
        preloaded_multi_mp_sgd_mom_update,
        multi_sum_sq, multi_lars, all_finite,
    ):
        name = fn.__name__ if hasattr(fn, "__name__") else fn.__wrapped__.__name__
        _reg(name, differentiable=False, wrap_ndarray=False)(fn)
    from .registry import alias as _alias

    _alias("multi_all_finite", "all_finite")


_register_public_ops()
