"""Dense tensor operators — TPU-native equivalent of [U:src/operator/tensor/]
(``elemwise_binary_op*``, ``broadcast_reduce_op*``, ``matrix_op*``,
``indexing_op``, ``init_op``, ``ordering_op``).

Every op is a pure jax function; XLA fuses elementwise chains (subsuming the
reference's NVRTC pointwise fusion, [U:src/operator/fusion/]) and tiles
matmuls onto the MXU.  MXNet-specific calling conventions (reshape magic
values, ``exclude`` reduction, topk ``ret_typ``...) are honored so reference
scripts/tests port unchanged.
"""
from __future__ import annotations

import functools

import numpy as _np

import jax
import jax.numpy as jnp
from jax import lax

from ..base import _as_np_dtype
from .registry import register, alias

# ---------------------------------------------------------------------------
# broadcasting binary (MXNet names both `elemwise_*` and `broadcast_*`; jax
# broadcasts everywhere so they collapse)
# ---------------------------------------------------------------------------


@register("broadcast_add")
def broadcast_add(lhs, rhs):
    return jnp.add(lhs, rhs)


@register("broadcast_sub")
def broadcast_sub(lhs, rhs):
    return jnp.subtract(lhs, rhs)


@register("broadcast_mul")
def broadcast_mul(lhs, rhs):
    return jnp.multiply(lhs, rhs)


@register("broadcast_div")
def broadcast_div(lhs, rhs):
    return jnp.divide(lhs, rhs)


@register("broadcast_mod")
def broadcast_mod(lhs, rhs):
    return jnp.mod(lhs, rhs)


@register("broadcast_power")
def broadcast_power(lhs, rhs):
    return jnp.power(lhs, rhs)


@register("broadcast_maximum")
def broadcast_maximum(lhs, rhs):
    return jnp.maximum(lhs, rhs)


@register("broadcast_minimum")
def broadcast_minimum(lhs, rhs):
    return jnp.minimum(lhs, rhs)


@register("broadcast_hypot")
def broadcast_hypot(lhs, rhs):
    return jnp.hypot(lhs, rhs)


def _cmp_dtype(x):
    return x.dtype if hasattr(x, "dtype") else jnp.float32


@register("broadcast_equal", differentiable=False)
def broadcast_equal(lhs, rhs):
    return (jnp.equal(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_not_equal", differentiable=False)
def broadcast_not_equal(lhs, rhs):
    return (jnp.not_equal(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_greater", differentiable=False)
def broadcast_greater(lhs, rhs):
    return (jnp.greater(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_greater_equal", differentiable=False)
def broadcast_greater_equal(lhs, rhs):
    return (jnp.greater_equal(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_lesser", differentiable=False)
def broadcast_lesser(lhs, rhs):
    return (jnp.less(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_lesser_equal", differentiable=False)
def broadcast_lesser_equal(lhs, rhs):
    return (jnp.less_equal(lhs, rhs)).astype(_cmp_dtype(lhs))


@register("broadcast_logical_and", differentiable=False)
def broadcast_logical_and(lhs, rhs):
    return jnp.logical_and(lhs, rhs).astype(_cmp_dtype(lhs))


@register("broadcast_logical_or", differentiable=False)
def broadcast_logical_or(lhs, rhs):
    return jnp.logical_or(lhs, rhs).astype(_cmp_dtype(lhs))


@register("broadcast_logical_xor", differentiable=False)
def broadcast_logical_xor(lhs, rhs):
    return jnp.logical_xor(lhs, rhs).astype(_cmp_dtype(lhs))


for _new, _old in [
    ("elemwise_add", "broadcast_add"),
    ("elemwise_sub", "broadcast_sub"),
    ("elemwise_mul", "broadcast_mul"),
    ("elemwise_div", "broadcast_div"),
    ("add", "broadcast_add"),
    ("subtract", "broadcast_sub"),
    ("multiply", "broadcast_mul"),
    ("divide", "broadcast_div"),
    ("power", "broadcast_power"),
    ("maximum", "broadcast_maximum"),
    ("minimum", "broadcast_minimum"),
    ("equal", "broadcast_equal"),
    ("not_equal", "broadcast_not_equal"),
    ("greater", "broadcast_greater"),
    ("greater_equal", "broadcast_greater_equal"),
    ("lesser", "broadcast_lesser"),
    ("lesser_equal", "broadcast_lesser_equal"),
    ("logical_and", "broadcast_logical_and"),
    ("logical_or", "broadcast_logical_or"),
    ("logical_xor", "broadcast_logical_xor"),
]:
    alias(_new, _old)


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    # MXNet rounds half AWAY from zero ([U:src/operator/tensor/
    # elemwise_unary_op_basic.cc] round); jnp.round is banker's rounding
    "round": lambda x: jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5),
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,
    "exp": jnp.exp,
    "expm1": jnp.expm1,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "sqrt": jnp.sqrt,
    "square": jnp.square,
    "cbrt": jnp.cbrt,
    "negative": jnp.negative,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gammaln": jax.scipy.special.gammaln,
    "logical_not": lambda x: jnp.logical_not(x).astype(x.dtype),
    "isnan": lambda x: jnp.isnan(x).astype(jnp.bool_),
    "isinf": lambda x: jnp.isinf(x).astype(jnp.bool_),
    "isfinite": lambda x: jnp.isfinite(x).astype(jnp.bool_),
}

for _name, _fn in _UNARY.items():
    register(_name)(_fn)


@register("gamma")
def gamma_fn(x):
    """Γ(x) (MXNet ``gamma`` is the gamma *function*, distinct from
    ``gammaln``)."""
    try:
        return jax.scipy.special.gamma(x)
    except AttributeError:
        return jnp.exp(jax.scipy.special.gammaln(x))


@register("digamma")
def digamma_fn(x):
    """ψ(x) = d/dx ln Γ(x) ([U:src/operator/mshadow_op.h] gamma digamma
    family)."""
    return jax.scipy.special.digamma(x)


@register("polygamma")
def polygamma_fn(x, n=0):
    """n-th derivative of digamma ([U:src/operator/mshadow_op.h]); n=0 is
    digamma itself."""
    return jax.scipy.special.polygamma(int(n), x)


@register("reciprocal")
def reciprocal(x):
    return 1.0 / x


@register("rsqrt")
def rsqrt(x):
    return lax.rsqrt(x)


@register("rcbrt")
def rcbrt(x):
    return 1.0 / jnp.cbrt(x)


@register("relu")
def relu(x):
    return jnp.maximum(x, 0)


@register("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register("softsign")
def softsign(x):
    return x / (1 + jnp.abs(x))


@register("clip")
def clip(x, a_min=None, a_max=None):
    return jnp.clip(x, a_min, a_max)


@register("cast")
def cast(x, dtype):
    return x.astype(_as_np_dtype(dtype))


alias("Cast", "cast")


# ---------------------------------------------------------------------------
# reductions (MXNet semantics: axis int|tuple|None, keepdims, exclude)
# ---------------------------------------------------------------------------


def _norm_axis(x, axis, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % x.ndim for a in axis)
    if exclude:
        axis = tuple(i for i in range(x.ndim) if i not in axis)
    return axis


def _make_reduce(name, jfn):
    def red(x, axis=None, keepdims=False, exclude=False):
        ax = _norm_axis(x, axis, exclude)
        return jfn(x, axis=ax, keepdims=keepdims)

    red.__name__ = name
    register(name)(red)
    return red


_make_reduce("sum", jnp.sum)
_make_reduce("mean", jnp.mean)
_make_reduce("prod", jnp.prod)
_make_reduce("max", jnp.max)
_make_reduce("min", jnp.min)
_make_reduce("nansum", jnp.nansum)
_make_reduce("nanprod", jnp.nanprod)
alias("sum_axis", "sum")
alias("max_axis", "max")
alias("min_axis", "min")


@register("argmax", differentiable=False)
def argmax(x, axis=None, keepdims=False):
    out = jnp.argmax(x, axis=axis, keepdims=keepdims).astype(jnp.float32)
    return out


@register("argmin", differentiable=False)
def argmin(x, axis=None, keepdims=False):
    return jnp.argmin(x, axis=axis, keepdims=keepdims).astype(jnp.float32)


@register("norm")
def norm(x, ord=2, axis=None, keepdims=False):
    ax = _norm_axis(x, axis)
    if ord == 1:
        return jnp.sum(jnp.abs(x), axis=ax, keepdims=keepdims)
    if ord == 2:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=keepdims))
    raise ValueError(f"unsupported ord {ord}")


@register("L2Normalization")
def l2_normalization(x, eps=1e-10, mode="instance"):
    if mode == "instance":
        ax = tuple(range(1, x.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, x.ndim))
    else:
        raise ValueError(mode)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=ax, keepdims=True) + eps)
    return x / n


# ---------------------------------------------------------------------------
# matrix / shape ops
# ---------------------------------------------------------------------------


@register("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """MXNet dot: contract last axis of lhs with first axis of rhs
    (parity: [U:src/operator/tensor/dot-inl.h]).  Lowered to an MXU matmul
    by XLA via tensordot/dot_general."""
    if transpose_a:
        lhs = jnp.transpose(lhs)
    if transpose_b:
        rhs = jnp.transpose(rhs)
    if lhs.ndim == 1 and rhs.ndim == 1:
        return jnp.dot(lhs, rhs)
    return jnp.tensordot(lhs, rhs, axes=([-1], [0]))


@register("matmul")
def matmul(lhs, rhs):
    return jnp.matmul(lhs, rhs)


@register("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False):
    if transpose_a:
        lhs = jnp.swapaxes(lhs, -1, -2)
    if transpose_b:
        rhs = jnp.swapaxes(rhs, -1, -2)
    return jnp.matmul(lhs, rhs)


def _infer_mx_reshape(src, target, reverse=False):
    """MXNet reshape magic values 0/-1/-2/-3/-4
    (parity: [U:src/operator/tensor/matrix_op.cc] Reshape)."""
    src = list(src)
    target = list(target)
    if reverse:
        src = src[::-1]
        target = target[::-1]
        # -4's two factors read left-to-right; reversing swaps them back below
    out = []
    i = 0
    j = 0
    while j < len(target):
        t = target[j]
        if t > 0:
            out.append(t)
            i += 1
        elif t == 0:
            if i >= len(src):
                raise ValueError("reshape 0 refers past input rank")
            out.append(src[i])
            i += 1
        elif t == -1:
            out.append(-1)
            i += 1
        elif t == -2:
            out.extend(src[i:])
            i = len(src)
        elif t == -3:
            if i + 1 >= len(src):
                raise ValueError("reshape -3 needs two input dims")
            out.append(src[i] * src[i + 1])
            i += 2
        elif t == -4:
            d = src[i]
            a, b = target[j + 1], target[j + 2]
            if a == -1 and b == -1:
                raise ValueError("reshape -4 with two -1s")
            if a == -1:
                a = d // b
            if b == -1:
                b = d // a
            if a * b != d:
                raise ValueError(f"reshape -4 split {d} != {a}*{b}")
            out.extend([a, b])
            i += 1
            j += 2
        else:
            raise ValueError(f"invalid reshape code {t}")
        j += 1
    total = 1
    for d in src:
        total *= d
    known = 1
    neg = 0
    for d in out:
        if d == -1:
            neg += 1
        else:
            known *= d
    if neg > 1:
        raise ValueError("more than one -1 in reshape")
    if neg == 1:
        out = [total // known if d == -1 else d for d in out]
    if reverse:
        out = out[::-1]
    return tuple(out)


@register("reshape")
def reshape(x, shape, reverse=False):
    return jnp.reshape(x, _infer_mx_reshape(x.shape, shape, reverse))


alias("Reshape", "reshape")


@register("reshape_like")
def reshape_like(lhs, rhs):
    return jnp.reshape(lhs, rhs.shape)


@register("flatten")
def flatten(x):
    """Flatten to 2D keeping batch dim (parity: MXNet Flatten)."""
    if x.ndim == 0:
        return jnp.reshape(x, (1, 1))
    lead = x.shape[0]
    return jnp.reshape(x, (lead, -1))


alias("Flatten", "flatten")


@register("transpose")
def transpose(x, axes=None):
    if axes is not None and len(axes) == 0:
        axes = None
    return jnp.transpose(x, axes=axes)


@register("swapaxes")
def swapaxes(x, dim1=0, dim2=0):
    return jnp.swapaxes(x, dim1, dim2)


alias("SwapAxis", "swapaxes")


@register("expand_dims")
def expand_dims(x, axis):
    return jnp.expand_dims(x, axis)


@register("squeeze")
def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


@register("broadcast_to")
def broadcast_to(x, shape):
    # MXNet allows 0 meaning "keep this dim"
    shape = tuple(s if s != 0 else x.shape[i] for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


@register("broadcast_like")
def broadcast_like(lhs, rhs):
    return jnp.broadcast_to(lhs, rhs.shape)


@register("broadcast_axis")
def broadcast_axis(x, axis=(), size=()):
    if isinstance(axis, int):
        axis = (axis,)
    if isinstance(size, int):
        size = (size,)
    shape = list(x.shape)
    for a, s in zip(axis, size):
        shape[a] = s
    return jnp.broadcast_to(x, tuple(shape))


@register("tile")
def tile(x, reps):
    return jnp.tile(x, reps)


@register("repeat")
def repeat(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


@register("flip")
def flip(x, axis):
    return jnp.flip(x, axis=axis)


alias("reverse", "flip")


@register("pad")
def pad(x, mode="constant", pad_width=(), constant_value=0.0):
    """Parity: [U:src/operator/pad.cc] — pad_width is the flat MXNet tuple
    (before/after per axis)."""
    pw = [(pad_width[2 * i], pad_width[2 * i + 1]) for i in range(len(pad_width) // 2)]
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(x, pw, mode="constant", constant_values=constant_value)
    return jnp.pad(x, pw, mode=jmode)


@register("concat")
def concat(*args, dim=1):
    return jnp.concatenate(args, axis=dim)


alias("Concat", "concat")


@register("stack")
def stack(*args, axis=0):
    return jnp.stack(args, axis=axis)


@register("add_n")
def add_n(*args):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


alias("ElementWiseSum", "add_n")


@register("split")
def split(x, num_outputs, axis=1, squeeze_axis=False):
    parts = jnp.split(x, num_outputs, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)


alias("SliceChannel", "split")


@register("slice")
def slice_op(x, begin, end, step=None):
    slices = []
    step = step or [None] * len(begin)
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return x[tuple(slices)]


@register("slice_axis")
def slice_axis(x, axis, begin, end):
    sl = [slice(None)] * x.ndim
    if end is None:
        end = x.shape[axis]
    sl[axis] = slice(begin, end)
    return x[tuple(sl)]


@register("slice_like")
def slice_like(x, like, axes=()):
    axes = axes or tuple(range(min(x.ndim, like.ndim)))
    sl = [slice(None)] * x.ndim
    for a in axes:
        sl[a] = slice(0, like.shape[a])
    return x[tuple(sl)]


@register("take")
def take(x, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    if mode == "wrap":
        idx = jnp.mod(idx, x.shape[axis])
    else:
        idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    return jnp.take(x, idx, axis=axis)


@register("batch_take")
def batch_take(x, indices):
    idx = indices.astype(jnp.int32).reshape(-1)
    return x[jnp.arange(x.shape[0]), idx]


@register("pick")
def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    idx = index.astype(jnp.int32)
    idx = jnp.clip(idx, 0, x.shape[axis] - 1)
    out = jnp.take_along_axis(x, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("gather_nd")
def gather_nd(data, indices):
    """Parity: MXNet gather_nd — indices shape (M, ...) where leading dim
    indexes the first M axes of data."""
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd")
def scatter_nd(data, indices, shape):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("where")
def where(condition, x, y):
    return jnp.where(condition.astype(jnp.bool_) if condition.dtype != jnp.bool_ else condition, x, y)


@register("one_hot", differentiable=False)
def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    idx = indices.astype(jnp.int32)
    oh = jax.nn.one_hot(idx, depth)
    out = oh * on_value + (1 - oh) * off_value
    return out.astype(_as_np_dtype(dtype))


@register("diag")
def diag(x, k=0):
    if x.ndim == 1:
        return jnp.diag(x, k)
    return jnp.diagonal(x, offset=k, axis1=-2, axis2=-1)


@register("shape_array", differentiable=False)
def shape_array(x):
    return jnp.asarray(x.shape, dtype=jnp.int64 if False else jnp.int32)


@register("size_array", differentiable=False)
def size_array(x):
    return jnp.asarray([x.size], dtype=jnp.int32)


@register("zeros_like")
def zeros_like(x):
    return jnp.zeros_like(x)


@register("ones_like")
def ones_like(x):
    return jnp.ones_like(x)


@register("full_like")
def full_like(x, fill_value=0.0):
    return jnp.full_like(x, fill_value)


# ---------------------------------------------------------------------------
# ordering ops
# ---------------------------------------------------------------------------


@register("sort")
def sort(x, axis=-1, is_ascend=True):
    out = jnp.sort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out


@register("argsort", differentiable=False)
def argsort(x, axis=-1, is_ascend=True, dtype="float32"):
    out = jnp.argsort(x, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis)
    return out.astype(_as_np_dtype(dtype))


@register("topk", differentiable=False)
def topk(x, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Parity: [U:src/operator/tensor/ordering_op.cc] topk."""
    ax = axis % x.ndim
    xt = jnp.moveaxis(x, ax, -1)
    vals, idx = lax.top_k(jnp.negative(xt) if is_ascend else xt, k)
    if is_ascend:
        vals = jnp.negative(vals)
    vals = jnp.moveaxis(vals, -1, ax)
    idx = jnp.moveaxis(idx, -1, ax)
    if ret_typ == "indices":
        return idx.astype(_as_np_dtype(dtype))
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(_as_np_dtype(dtype))
    if ret_typ == "mask":
        mask = jnp.zeros_like(jnp.moveaxis(x, ax, -1))
        mask = jax.vmap(lambda m, i: m.at[i].set(1.0), in_axes=(0, 0))(
            mask.reshape(-1, mask.shape[-1]), idx.reshape(-1, idx.shape[-1] if idx.ndim else 1)
        ).reshape(mask.shape)
        return jnp.moveaxis(mask, -1, ax)
    raise ValueError(ret_typ)


# ---------------------------------------------------------------------------
# misc
# ---------------------------------------------------------------------------


@register("identity")
def identity(x):
    return x


@register("BlockGrad")
def block_grad(x):
    return lax.stop_gradient(x)


alias("stop_gradient", "BlockGrad")
alias("make_loss", "identity")


@register("smooth_l1")
def smooth_l1(x, scalar=1.0):
    s2 = scalar * scalar
    return jnp.where(jnp.abs(x) < 1.0 / s2, 0.5 * s2 * jnp.square(x), jnp.abs(x) - 0.5 / s2)


@register("hard_sigmoid")
def hard_sigmoid(x, alpha=0.2, beta=0.5):
    return jnp.clip(alpha * x + beta, 0.0, 1.0)


@register("linalg_gemm2")
def linalg_gemm2(a, b, transpose_a=False, transpose_b=False, alpha=1.0):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return alpha * jnp.matmul(a, b)


@register("linalg_potrf")
def linalg_potrf(a):
    return jnp.linalg.cholesky(a)


@register("linalg_syrk")
def linalg_syrk(a, transpose=False, alpha=1.0):
    if transpose:
        return alpha * jnp.matmul(jnp.swapaxes(a, -1, -2), a)
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


_np  # keep import


# ---------------------------------------------------------------------------
# Scalar-operand arithmetic ops (parity: [U:src/operator/tensor/
# elemwise_binary_scalar_op_basic.cc]).  NDArray dunders compute these
# directly; they are registered so the symbolic front end (mx.sym) can emit
# them as graph nodes.
# ---------------------------------------------------------------------------


@register("_plus_scalar")
def _plus_scalar(data, scalar=0.0):
    return data + data.dtype.type(scalar)


@register("_minus_scalar")
def _minus_scalar(data, scalar=0.0):
    return data - data.dtype.type(scalar)


@register("_rminus_scalar")
def _rminus_scalar(data, scalar=0.0):
    return data.dtype.type(scalar) - data


@register("_mul_scalar")
def _mul_scalar(data, scalar=1.0):
    return data * data.dtype.type(scalar)


@register("_div_scalar")
def _div_scalar(data, scalar=1.0):
    return data / data.dtype.type(scalar)


@register("_rdiv_scalar")
def _rdiv_scalar(data, scalar=1.0):
    return data.dtype.type(scalar) / data


@register("_power_scalar")
def _power_scalar(data, scalar=1.0):
    return data ** data.dtype.type(scalar)


@register("_rpower_scalar")
def _rpower_scalar(data, scalar=1.0):
    return data.dtype.type(scalar) ** data


@register("split_v2")
def split_v2(data, indices_or_sections=1, axis=0, squeeze_axis=False):
    """Parity: [U:src/operator/tensor/matrix_op.cc] _split_v2 — int = N
    equal sections, tuple = split points along ``axis``."""
    spec = (int(indices_or_sections) if isinstance(indices_or_sections, int)
            else [int(i) for i in indices_or_sections])
    if not isinstance(spec, int):
        # the reference rejects out-of-range/unsorted indices at shape
        # inference; jnp.split would silently clamp to empty parts
        if any(i < 0 or i > data.shape[axis] for i in spec) \
                or sorted(spec) != spec:
            raise ValueError(
                f"split_v2 indices {spec} invalid for axis {axis} of "
                f"size {data.shape[axis]} (must be sorted, in range)")
    parts = jnp.split(data, spec, axis=axis)
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=axis) for p in parts]
    return tuple(parts)  # tuple = multi-output contract (a list would stack)


@register("_mod_scalar")
def _mod_scalar(data, scalar=1.0):
    return jnp.mod(data, data.dtype.type(scalar))


@register("_rmod_scalar")
def _rmod_scalar(data, scalar=1.0):
    return jnp.mod(data.dtype.type(scalar), data)


@register("_maximum_scalar")
def _maximum_scalar(data, scalar=0.0):
    return jnp.maximum(data, data.dtype.type(scalar))


@register("_minimum_scalar")
def _minimum_scalar(data, scalar=0.0):
    return jnp.minimum(data, data.dtype.type(scalar))


@register("_hypot_scalar")
def _hypot_scalar(data, scalar=0.0):
    return jnp.hypot(data, data.dtype.type(scalar))


# comparisons return 1.0/0.0 in the INPUT dtype ([U:src/operator/tensor/
# elemwise_binary_scalar_op_logic.cc] — the reference's float-mask
# convention, not bool arrays)
def _make_cmp_scalar(name, fn):
    @register(name, differentiable=False)
    def cmp_scalar(data, scalar=0.0, _fn=fn):
        return _fn(data, data.dtype.type(scalar)).astype(data.dtype)

    cmp_scalar.__name__ = name.lstrip("_")
    return cmp_scalar


for _name, _fn in [
    ("_equal_scalar", jnp.equal),
    ("_not_equal_scalar", jnp.not_equal),
    ("_greater_scalar", jnp.greater),
    ("_greater_equal_scalar", jnp.greater_equal),
    ("_lesser_scalar", jnp.less),
    ("_lesser_equal_scalar", jnp.less_equal),
    ("_logical_and_scalar", jnp.logical_and),
    ("_logical_or_scalar", jnp.logical_or),
    ("_logical_xor_scalar", jnp.logical_xor),
]:
    _make_cmp_scalar(_name, _fn)


@register("_sym_zeros")
def _sym_zeros(shape=(), dtype="float32"):
    return jnp.zeros(tuple(shape), dtype=_as_np_dtype(dtype))


@register("_sym_ones")
def _sym_ones(shape=(), dtype="float32"):
    return jnp.ones(tuple(shape), dtype=_as_np_dtype(dtype))


alias("broadcast_axes", "broadcast_axis")
alias("crop", "slice")  # [U:src/operator/tensor/matrix_op.cc] add_alias("crop")


# ---------------------------------------------------------------------------
# legacy ndarray functions (parity: [U:src/ndarray/ndarray_function.cc] —
# the pre-Gluon RL/embedding-era API; choose_element_0index is the old
# name for pick along axis 1)
# ---------------------------------------------------------------------------


@register("choose_element_0index")
def choose_element_0index(lhs, rhs):
    """out[i] = lhs[i, rhs[i]] — the old name for pick along axis 1."""
    return pick(lhs, rhs, axis=1)


@register("fill_element_0index")
def fill_element_0index(lhs, mhs, rhs):
    """out = lhs with out[i, rhs[i]] = mhs[i] (functional, not in-place —
    the buffer-swap NDArray layer applies the mutation)."""
    idx = jnp.clip(rhs.astype(jnp.int32), 0, lhs.shape[1] - 1)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


@register("one_hot_encode")
def one_hot_encode(indices, out):
    """Legacy 2-arg form: the second operand supplies the [N, C] shape."""
    idx = indices.astype(jnp.int32)
    return jax.nn.one_hot(idx, out.shape[1], dtype=out.dtype)


# ---------------------------------------------------------------------------
# AMP graph-pass ops (parity: [U:src/operator/tensor/amp_cast.cc]) — the
# reference inserts these around float ops during the AMP symbol pass;
# they exist here so reference-era symbol graphs execute unchanged
# ---------------------------------------------------------------------------


@register("amp_cast")
def amp_cast(x, dtype="float32"):
    """Cast floating inputs; pass integer/bool tensors through unchanged
    (the reference op's contract)."""
    if not jnp.issubdtype(x.dtype, jnp.floating):
        return x
    return x.astype(_as_np_dtype(dtype))


@register("amp_multicast")
def amp_multicast(*data, num_outputs=0, cast_narrow=False):
    """Cast every floating operand to a common width: the widest among the
    inputs (or the narrowest with ``cast_narrow``)."""
    floats = [d.dtype for d in data if jnp.issubdtype(d.dtype, jnp.floating)]
    if not floats:
        return tuple(data)
    if cast_narrow:
        # deterministic tie-break (f16 vs bf16): sort by (bits, name)
        target = min(floats, key=lambda dt: (jnp.finfo(dt).bits, dt.name))
    else:
        # promote_types is order-invariant and lifts f16+bf16 to f32
        target = functools.reduce(jnp.promote_types, floats)
    return tuple(d.astype(target)
                 if jnp.issubdtype(d.dtype, jnp.floating) else d
                 for d in data)


@register("_contrib_boolean_mask")
def boolean_mask(data, index, axis=0):
    """Select the slices of ``data`` along ``axis`` where ``index`` is
    nonzero (parity: [U:src/operator/contrib/boolean_mask.cc]).  The
    output length depends on the MASK's values, so the mask must be
    concrete: with a concrete mask the op lowers to ``take`` over the
    precomputed indices (static shape, differentiable — the autograd tape
    keeps no-grad inputs concrete, so ``data`` may be traced); a traced
    mask raises with guidance."""
    import jax.core as _core

    if isinstance(index, _core.Tracer):
        raise NotImplementedError(
            "boolean_mask needs a CONCRETE mask (its output length is the "
            "mask's popcount); inside jit use jnp.where-style masked "
            "compute or mask-and-pad instead")
    mask = _np.asarray(index)
    if mask.ndim != 1 or mask.shape[0] != data.shape[axis]:
        raise ValueError(
            f"boolean_mask: mask shape {mask.shape} must be 1-D of length "
            f"data.shape[{axis}]={data.shape[axis]}")
    idx = _np.nonzero(mask != 0)[0]
    return jnp.take(data, idx, axis=axis)
