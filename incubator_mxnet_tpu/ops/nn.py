"""Neural-network operators — TPU-native equivalent of [U:src/operator/nn/]
(convolution, fully_connected, pooling, batch_norm, layer_norm, activation,
softmax, dropout, embedding, upsampling) and the cuDNN/oneDNN dispatch layers
([U:src/operator/nn/cudnn/], [U:src/operator/nn/mkldnn/]).

On TPU the vendor-library role is played by XLA itself: ``lax.conv_general_
dilated`` / ``dot_general`` lower onto the MXU with autotuned tiling, and
elementwise epilogues fuse into the matmul — there is no algo-selection cache
to manage.  MXNet calling conventions (NCHW layout, OIHW weights, param
names) are preserved.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from ..base import _as_np_dtype
from .registry import register, alias


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------


@register("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False, flatten=True):
    """Parity: [U:src/operator/nn/fully_connected.cc].  weight is
    (num_hidden, in_units) like the reference; lowered to one MXU matmul."""
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.matmul(data, weight.T)
    if bias is not None and not no_bias:
        out = out + bias
    return out


alias("fully_connected", "FullyConnected")


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

_CONV_DIMS = {1: ("NCW", "OIW", "NCW"), 2: ("NCHW", "OIHW", "NCHW"), 3: ("NCDHW", "OIDHW", "NCDHW")}


def _tuplize(v, n):
    if v is None:
        return (1,) * n
    if isinstance(v, int):
        return (v,) * n
    v = tuple(v)
    return v if len(v) == n else v + (v[-1],) * (n - len(v))


@register("Convolution")
def convolution(
    data,
    weight,
    bias=None,
    kernel=(1, 1),
    stride=None,
    dilate=None,
    pad=None,
    num_filter=0,
    num_group=1,
    no_bias=False,
    layout=None,
):
    """Parity: [U:src/operator/nn/convolution.cc].  NCHW/OIHW convention kept;
    XLA:TPU relayouts internally for the MXU so no NHWC rewrite is needed at
    the API level."""
    n = len(kernel)
    stride = _tuplize(stride, n)
    dilate = _tuplize(dilate, n)
    pad = _tuplize(pad if pad is not None else 0, n)
    dn = lax.conv_dimension_numbers(data.shape, weight.shape, _CONV_DIMS[n])
    # No preferred_element_type: XLA:TPU already accumulates bf16 convs in
    # fp32 on the MXU, and requesting an f32 output breaks jax's conv
    # transpose rule under AMP (f32 cotangent paired with bf16 operands).
    out = lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


@register("Deconvolution")
def deconvolution(
    data,
    weight,
    bias=None,
    kernel=(1, 1),
    stride=None,
    dilate=None,
    pad=None,
    adj=None,
    num_filter=0,
    num_group=1,
    no_bias=True,
    target_shape=None,
):
    """Parity: [U:src/operator/nn/deconvolution.cc] — transposed conv as the
    exact gradient of Convolution.  MXNet stores the weight as
    (C_in, C_out/g, *K): that IS the forward conv's OIHW kernel for the
    C_out→C_in conv this op is the transpose of.  Lowered as
    conv_general_dilated with lhs_dilation=stride (input dilation), so
    output size = (in-1)*stride - 2*pad + kernel + adj, matching the
    reference."""
    n = len(kernel)
    stride = _tuplize(stride, n)
    dilate = _tuplize(dilate, n)
    pad = _tuplize(pad if pad is not None else 0, n)
    adj = _tuplize(adj if adj is not None else 0, n)
    keff = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    if target_shape:
        # derive pad so output spatial dims == target_shape (reference
        # semantics: out = (in-1)*s + keff - 2*pad + adj)
        pad = tuple(
            (( (i - 1) * s + ke + a - t) // 2)
            for i, s, ke, a, t in zip(data.shape[2:], stride, keff, adj, target_shape)
        )
    c_in = weight.shape[0]
    c_out_g = weight.shape[1]
    c_out = c_out_g * num_group
    # (C_in, C_out/g, *K) -> grouped swap -> (C_out, C_in/g, *K), spatial flip
    w = weight.reshape((num_group, c_in // num_group, c_out_g) + tuple(weight.shape[2:]))
    w = jnp.swapaxes(w, 1, 2).reshape((c_out, c_in // num_group) + tuple(weight.shape[2:]))
    w = jnp.flip(w, axis=tuple(range(2, 2 + n)))
    dn = lax.conv_dimension_numbers(data.shape, w.shape, _CONV_DIMS[n])
    out = lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * n,
        padding=[(ke - 1 - p, ke - 1 - p + a) for ke, p, a in zip(keff, pad, adj)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=num_group,
    )
    if bias is not None and not no_bias:
        out = out + bias.reshape((1, -1) + (1,) * n)
    return out


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------


@register("Pooling")
def pooling(
    data,
    kernel=(2, 2),
    pool_type="max",
    global_pool=False,
    stride=None,
    pad=None,
    pooling_convention="valid",
    count_include_pad=True,
    layout=None,
):
    """Parity: [U:src/operator/nn/pooling.cc] via ``lax.reduce_window``."""
    n = data.ndim - 2
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * n
        pad = (0,) * n
    else:
        kernel = _tuplize(kernel, n)
        stride = _tuplize(stride if stride is not None else kernel, n)
        pad = _tuplize(pad if pad is not None else 0, n)
    window = (1, 1) + tuple(kernel)
    strides = (1, 1) + tuple(stride)
    padding = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if pooling_convention == "full" and not global_pool:
        # ceil-mode: extend upper padding so the last window fits
        ext = []
        for i, (k, s, p) in enumerate(zip(kernel, stride, pad)):
            size = data.shape[2 + i]
            out_full = -(-(size + 2 * p - k) // s) + 1  # ceil
            needed = (out_full - 1) * s + k - size - p
            ext.append((p, max(p, needed)))
        padding = ((0, 0), (0, 0)) + tuple(ext)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return lax.reduce_window(data, init, lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        summed = lax.reduce_window(data, 0.0, lax.add, window, strides, padding)
        if pool_type == "sum":
            return summed
        if count_include_pad:
            denom = 1
            for k in kernel:
                denom *= k
            return summed / denom
        ones = jnp.ones_like(data)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
        return summed / counts
    if pool_type == "lp":
        p2 = lax.reduce_window(jnp.square(data), 0.0, lax.add, window, strides, padding)
        return jnp.sqrt(p2)
    raise ValueError(pool_type)


# ---------------------------------------------------------------------------
# Normalization
# ---------------------------------------------------------------------------


@register("BatchNorm")
def batch_norm(
    data,
    gamma,
    beta,
    moving_mean,
    moving_var,
    eps=1e-5,
    momentum=0.9,
    fix_gamma=True,
    use_global_stats=False,
    output_mean_var=False,
    axis=1,
):
    """Parity: [U:src/operator/nn/batch_norm.cc].

    Functional contract: returns ``(out, batch_mean, batch_var)`` — the layer
    (gluon.nn.BatchNorm) owns the running-stat mutation, because aux-state
    mutation inside the op would break purity.  When ``use_global_stats`` the
    moving stats are used and returned unchanged.
    """
    ax = axis % data.ndim
    pallas_mode = os.environ.get("MXNET_TPU_PALLAS_BN", "0")
    if (pallas_mode in ("1", "interpret") and not use_global_stats
            and ax == 1 and data.ndim == 4):
        # opt-in A/B path (VERDICT r4 item 4b): Pallas 2-pass forward,
        # reference-vjp backward; "interpret" runs the kernels in
        # interpreter mode for CPU tests
        from .pallas_bn import trainable_batch_norm

        g = jnp.ones_like(gamma) if fix_gamma else gamma
        return trainable_batch_norm(data, g, beta, eps=float(eps),
                                    interpret=pallas_mode == "interpret")
    reduce_axes = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    x32 = data.astype(jnp.float32)
    if use_global_stats:
        mean, var = moving_mean.astype(jnp.float32), moving_var.astype(jnp.float32)
    else:
        # statistics always in fp32 — on bf16 inputs the converts fuse into
        # the reduction, so this costs nothing while AMP can leave the
        # activations in bf16 end-to-end (no hook cast copies).
        # E[x²]−E[x]² form on purpose: both sums reduce the SAME input, so
        # XLA fuses them into ONE pass over the activations — jnp.var's
        # (x−mean)² needs mean first and forces a second full read
        # (profiled at 38% of the ResNet-50 step, docs/PERF_NOTES.md).
        # fp32 accumulation keeps the cancellation benign at BN scales.
        mean = jnp.mean(x32, axis=reduce_axes)
        var = jnp.maximum(jnp.mean(jnp.square(x32), axis=reduce_axes)
                          - jnp.square(mean), 0.0)
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = lax.rsqrt(var + eps)
    out = ((x32 - mean.reshape(bshape)) * (g.astype(jnp.float32) * inv).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape))
    return out.astype(data.dtype), mean, var


def _layer_norm_ref(data, gamma, beta, axis, eps):
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axis, keepdims=True)
    # one-pass stats: see batch_norm's E[x²]−E[x]² note
    var = jnp.maximum(jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
                      - jnp.square(mean), 0.0)
    out = (x32 - mean) * lax.rsqrt(var + eps)
    ax = axis % data.ndim
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = (out * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape))
    return out.astype(data.dtype)


@functools.lru_cache(maxsize=None)
def _make_ln_custom_bwd(eps, g_dtype, b_dtype):
    """Hand-written LayerNorm VJP (axis=-1): saves x̂ in the INPUT dtype
    and expresses backward in the closed form
    ``dx = inv·(dŷ − mean(dŷ) − x̂·mean(dŷ·x̂))`` — an A/B lever for the
    profiled lane-dimension convert_reduce cost in the BERT/transformer
    backward (docs/PERF_NOTES.md); enabled by MXNET_TPU_LN_CUSTOM_BWD=1."""

    @jax.custom_vjp
    def f(x, gamma, beta):
        return _layer_norm_ref(x, gamma, beta, -1, eps)

    def fwd(x, gamma, beta):
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.maximum(jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
                          - jnp.square(mean), 0.0)
        inv = lax.rsqrt(var + eps)
        xhat = (x32 - mean) * inv
        g32 = gamma.astype(jnp.float32)
        out = (xhat * g32 + beta.astype(jnp.float32)).astype(x.dtype)
        # x̂ saved in the compute dtype (bf16 under AMP): halves the
        # residual's HBM footprint vs saving x+mean+var in fp32
        return out, (xhat.astype(x.dtype), inv, g32)

    def bwd(res, dy):
        xhat_c, inv, g32 = res
        xdtype = xhat_c.dtype  # == the input dtype by construction
        xhat = xhat_c.astype(jnp.float32)
        dyg = dy.astype(jnp.float32) * g32
        m1 = jnp.mean(dyg, axis=-1, keepdims=True)
        m2 = jnp.mean(dyg * xhat, axis=-1, keepdims=True)
        dx = (inv * (dyg - m1 - xhat * m2)).astype(xdtype)
        batch_axes = tuple(range(dy.ndim - 1))
        dy32 = dy.astype(jnp.float32)
        # grads must come back in the PRIMAL dtypes or the knob changes
        # grad-buffer dtypes (an A/B artifact, not a kernel effect)
        dgamma = jnp.sum(dy32 * xhat, axis=batch_axes).astype(g_dtype)
        dbeta = jnp.sum(dy32, axis=batch_axes).astype(b_dtype)
        return dx, dgamma, dbeta

    f.defvjp(fwd, bwd)
    return f


@register("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5):
    """Parity: [U:src/operator/nn/layer_norm.cc].  fp32 statistics with the
    output in the input dtype: under bf16 AMP the activations never leave
    bf16 at the op boundary (the internal converts fuse into the reduction
    and the normalize loop — no materialized cast copies).

    ``MXNET_TPU_LN_CUSTOM_BWD=1`` switches axis=-1 calls to a hand-written
    VJP (see ``_make_ln_custom_bwd``) — an on-chip A/B knob; default off."""
    ax = axis % data.ndim
    if (os.environ.get("MXNET_TPU_LN_CUSTOM_BWD") == "1"
            and ax == data.ndim - 1):
        return _make_ln_custom_bwd(float(eps), jnp.dtype(gamma.dtype).name,
                                   jnp.dtype(beta.dtype).name)(data, gamma, beta)
    return _layer_norm_ref(data, gamma, beta, axis, eps)


@register("GroupNorm")
def group_norm(data, gamma, beta, num_groups=1, eps=1e-5):
    n, c = data.shape[0], data.shape[1]
    rest = data.shape[2:]
    x = data.astype(jnp.float32).reshape((n, num_groups, c // num_groups) + rest)
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.maximum(jnp.mean(jnp.square(x), axis=axes, keepdims=True)
                      - jnp.square(mean), 0.0)
    x = (x - mean) * lax.rsqrt(var + eps)
    x = x.reshape(data.shape)
    bshape = (1, c) + (1,) * len(rest)
    out = (x * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape))
    return out.astype(data.dtype)


@register("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    x32 = data.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.maximum(jnp.mean(jnp.square(x32), axis=axes, keepdims=True)
                      - jnp.square(mean), 0.0)
    x = (x32 - mean) * lax.rsqrt(var + eps)
    bshape = (1, data.shape[1]) + (1,) * (data.ndim - 2)
    out = (x * gamma.astype(jnp.float32).reshape(bshape)
           + beta.astype(jnp.float32).reshape(bshape))
    return out.astype(data.dtype)


@register("RMSNorm")
def rms_norm(data, gamma, axis=-1, eps=1e-6):
    """TPU-era extension (not in reference): RMSNorm for LLM blocks."""
    x32 = data.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=axis, keepdims=True)
    out = x32 * lax.rsqrt(ms + eps) * gamma.astype(jnp.float32)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Activations / softmax
# ---------------------------------------------------------------------------

_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": lambda x: x / (1 + jnp.abs(x)),
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "erf": jax.scipy.special.erf,
}


@register("Activation")
def activation(data, act_type="relu"):
    """Parity: [U:src/operator/nn/activation.cc]."""
    return _ACTS[act_type](data)


@register("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25, lower_bound=0.125, upper_bound=0.334):
    """Parity: [U:src/operator/leaky_relu.cc] (leaky/prelu/elu/selu/gelu/rrelu)."""
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "prelu":
        g = gamma
        if g.ndim < data.ndim and g.ndim == 1:
            g = g.reshape((1, -1) + (1,) * (data.ndim - 2))
        return jnp.where(data > 0, data, g * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * (jnp.exp(data) - 1))
    if act_type == "selu":
        lam, a = 1.0507009873554805, 1.6732632423543772
        return lam * jnp.where(data > 0, data, a * (jnp.exp(data) - 1))
    if act_type == "gelu":
        return jax.nn.gelu(data, approximate=False)
    if act_type == "rrelu":
        mid = (lower_bound + upper_bound) / 2
        return jnp.where(data > 0, data, mid * data)
    raise ValueError(act_type)


@register("softmax")
def softmax(data, axis=-1, temperature=None, length=None):
    """Parity: [U:src/operator/nn/softmax.cc] (with optional temperature and
    length masking).  Internally fp32 (exp/sum), output in the input dtype —
    bf16 activations stay bf16 under AMP with no hook cast copies."""
    x = data.astype(jnp.float32)
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        ax = axis % x.ndim
        idx = jnp.arange(x.shape[ax])
        idx = idx.reshape((-1,) + (1,) * (x.ndim - 1 - ax))
        mask = idx < jnp.expand_dims(length, tuple(range(len(length.shape), x.ndim - 1)) if False else -1).reshape(
            length.shape + (1,) * (x.ndim - length.ndim)
        )
        x = jnp.where(mask, x, -jnp.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(mask, out, 0.0).astype(data.dtype)
    return jax.nn.softmax(x, axis=axis).astype(data.dtype)


@register("log_softmax")
def log_softmax(data, axis=-1, temperature=None):
    x = data.astype(jnp.float32)
    if temperature not in (None, 1.0):
        x = x / temperature
    return jax.nn.log_softmax(x, axis=axis).astype(data.dtype)


@register("softmin")
def softmin(data, axis=-1):
    return jax.nn.softmax(-data.astype(jnp.float32), axis=axis).astype(data.dtype)


def streaming_softmax_ce(logits, labels):
    """Per-position CE with a streaming log-sum-exp over the class axis:
    ``nll = lse(logits) - logits[label]``.  The max/exp/sum fuse into the
    class reduction, so no fp32 log-prob tensor of the logits' shape is
    ever materialized — at BERT-scale vocab that tensor is ~1 GB and
    costs ms of pure HBM traffic per step (docs/PERF_NOTES.md).  Works on
    bf16 logits; accumulation is fp32.  labels: integer, logits.shape[:-1].
    """
    m = lax.stop_gradient(jnp.max(logits, axis=-1, keepdims=True))
    lse = (m[..., 0].astype(jnp.float32)
           + jnp.log(jnp.sum(jnp.exp((logits - m).astype(jnp.float32)), axis=-1)))
    gold = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0].astype(jnp.float32)
    return lse - gold


@register("softmax_cross_entropy")
def softmax_cross_entropy(data, label):
    """Parity: [U:src/operator/loss_binary_op.cc] — summed CE with integer labels."""
    return jnp.sum(streaming_softmax_ce(data, label.reshape(data.shape[:-1])))


def _zero_cotangent(x):
    """Zero cotangent matching custom_vjp's contract: float0 for integer
    primals, zeros_like otherwise."""
    if jnp.issubdtype(x.dtype, jnp.inexact):
        return jnp.zeros_like(x)
    import numpy as _onp
    return _onp.zeros(x.shape, jax.dtypes.float0)


@functools.lru_cache(maxsize=None)
def _make_softmax_output(grad_scale, ignore_label, use_ignore, multi_output, normalization):
    """Static op attrs live in this closure so the custom_vjp sees only
    array args (strings through custom_vjp break abstract eval)."""
    ax_of = lambda out: 1 if multi_output else -1

    @jax.custom_vjp
    def f(data, label):
        return jax.nn.softmax(data, axis=ax_of(data))

    def fwd(data, label):
        out = jax.nn.softmax(data, axis=ax_of(data))
        return out, (out, label)

    def bwd(res, g):
        out, label = res
        ax = ax_of(out)
        nclass = out.shape[ax]
        lab = label.astype(jnp.int32)
        oh = jax.nn.one_hot(lab, nclass, axis=ax)
        grad = (out - oh) * grad_scale
        if use_ignore:
            keep = (lab != int(ignore_label)).astype(out.dtype)
            grad = grad * jnp.expand_dims(keep, ax)
        if normalization == "batch":
            grad = grad / out.shape[0]
        elif normalization == "valid":
            # reference: divide by the VALID count — without use_ignore
            # every label is valid, so this is the total label count (NOT
            # a silent no-op; [U:src/operator/softmax_output-inl.h])
            if use_ignore:
                keep = (lab != int(ignore_label)).astype(out.dtype)
                grad = grad / jnp.maximum(jnp.sum(keep), 1.0)
            else:
                grad = grad / float(lab.size)
        return (grad, _zero_cotangent(label))

    f.defvjp(fwd, bwd)
    return f


@register("SoftmaxOutput")
def softmax_output(
    data,
    label,
    grad_scale=1.0,
    ignore_label=-1.0,
    use_ignore=False,
    multi_output=False,
    normalization="null",
    **kw,
):
    """Legacy Module-API loss head (parity: [U:src/operator/softmax_output.cc]):
    forward = softmax, backward = scaled (p - onehot)."""
    f = _make_softmax_output(float(grad_scale), float(ignore_label),
                             bool(use_ignore), bool(multi_output), str(normalization))
    return f(data, label)


alias("Softmax", "SoftmaxOutput")


@register("LinearRegressionOutput")
def linear_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return ((d - l) * grad_scale / d.shape[0] * 0 + (d - l) * grad_scale, None)

    f.defvjp(fwd, bwd)
    return f(data, label.reshape(data.shape))


@register("MakeLoss")
def make_loss(data, grad_scale=1.0, normalization="null", valid_thresh=0.0):
    return data * 1.0


# ---------------------------------------------------------------------------
# Dropout / Embedding / UpSampling
# ---------------------------------------------------------------------------


@register("Dropout")
def dropout(data, p=0.5, mode="training", axes=(), key=None, training=None):
    """Parity: [U:src/operator/nn/dropout.cc].  The PRNG key is threaded from
    mx.random (trace-safe under jit); ``mode='always'`` applies at inference.
    When ``training`` is not given it follows ``autograd.is_training()``,
    matching the reference's is_train dispatch."""
    if training is None:
        from .. import autograd

        training = autograd.is_training()
    if not training and mode != "always":
        return data
    if p <= 0:
        return data
    if key is None:
        from ..random import get_key

        key = get_key()
    shape = list(data.shape)
    if axes:
        for ax in axes:
            shape[ax] = 1
    keep = 1.0 - p
    import os as _os

    if _os.environ.get("MXNET_TPU_FAST_DROPOUT", "1") == "1":
        # 8-bit mask draw: 4× fewer threefry blocks than bernoulli's
        # uint32-per-element (dropout RNG was 12% of the BERT step —
        # docs/PERF_NOTES.md).  keep is quantized to n/256 (≤1/512 absolute
        # error); the rescale uses the quantized keep, so E[out] == data
        # exactly.  MXNET_TPU_FAST_DROPOUT=0 restores exact-probability
        # bernoulli — NOTE the flag is read at TRACE time: flipping it
        # after a hybridize/jit cache is built requires
        # base.invalidate_jit_caches() (as amp.init does) to take effect.
        thresh = int(round(keep * 256))
        if 0 < thresh < 256:
            bits = jax.random.bits(key, tuple(shape), dtype=jnp.uint8)
            mask = (bits < thresh).astype(data.dtype)
            return data * mask * (256.0 / thresh)
    mask = jax.random.bernoulli(key, keep, tuple(shape)).astype(data.dtype)
    return data * mask / keep


@register("gather_positions")
def gather_positions(data, positions):
    """[B, S, D] × [B, P] int → [B, P, D]: per-batch sequence-position
    gather.  The MLM masked-position path (parity: GluonNLP BERTModel's
    ``masked_positions`` — only ~15% of positions reach the vocab
    projection, which is the workload the reference benchmarks)."""
    idx = positions.astype(jnp.int32)
    return jnp.take_along_axis(data, idx[..., None], axis=1)


@register("Embedding")
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32", sparse_grad=False):
    """Parity: [U:src/operator/tensor/indexing_op.cc] Embedding — a gather
    from the weight table; XLA lowers to dynamic-gather on TPU."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("UpSampling")
def upsampling(data, scale=2, sample_type="nearest", num_args=1):
    """Parity: [U:src/operator/nn/upsampling.cc] (nearest / bilinear)."""
    n, c, h, w = data.shape
    method = "nearest" if sample_type == "nearest" else "linear"
    return jax.image.resize(data, (n, c, h * scale, w * scale), method=method)


@register("SequenceMask")
def sequence_mask(data, sequence_length=None, use_sequence_length=False, value=0.0, axis=0):
    """Parity: [U:src/operator/sequence_mask.cc] — mask positions beyond each
    sequence's length along the time axis."""
    if not use_sequence_length or sequence_length is None:
        return data
    t = data.shape[axis]
    idx = jnp.arange(t)
    idx = idx.reshape((-1,) + (1,) * (data.ndim - 1 - axis)) if axis == 0 else idx
    if axis == 0:
        mask = idx < sequence_length.reshape((1, -1) + (1,) * (data.ndim - 2))
    else:
        mask = idx.reshape((1, -1) + (1,) * (data.ndim - 2)) < sequence_length.reshape(
            (-1, 1) + (1,) * (data.ndim - 2)
        )
    return jnp.where(mask, data, value)


@register("SequenceLast")
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        idx = [slice(None)] * data.ndim
        idx[axis] = -1
        return data[tuple(idx)]
    last = (sequence_length.astype(jnp.int32) - 1)
    if axis == 0:
        return data[last, jnp.arange(data.shape[1])]
    return data[jnp.arange(data.shape[0]), last]


@register("SequenceReverse")
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    t = data.shape[axis]
    idx = jnp.arange(t).reshape(-1, 1)
    lens = sequence_length.astype(jnp.int32).reshape(1, -1)
    rev = jnp.where(idx < lens, lens - 1 - idx, idx)
    return jnp.take_along_axis(data, rev.reshape(t, -1, *([1] * (data.ndim - 2))).astype(jnp.int32), axis=0)
