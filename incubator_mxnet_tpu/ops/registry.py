"""Operator registry — TPU-native replacement for the NNVM op registry.

Parity target: ``NNVM_REGISTER_OP`` + ``FCompute`` dispatch
([U:src/operator/], [U:include/mxnet/op_attr_types.h]).  Differences by
design:

* An op is a **pure function** ``fn(*jax_arrays, **static_kwargs)`` returning
  a jax.Array or tuple thereof.  No FInferShape/FInferType tables are needed —
  ``jax.eval_shape`` performs shape/dtype inference on the same function that
  computes (used by Symbol.infer_shape and deferred Parameter init).
* No FGradient registration — gradients come from ``jax.vjp`` of the same
  pure function (the autograd tape calls it), so every op is differentiable
  for free unless marked ``differentiable=False``.
* CPU/GPU/TPU kernel variants collapse into one definition; XLA specializes
  per backend.

Dispatch cache
--------------
This module also owns level 1 of the eager dispatch accelerator (see
docs/eager_dispatch.md): every ``ndarray.invoke`` of a *registered* op is
routed through a jit-compiled entry cached by

    ``(fn, static argument/kwarg values, input avals+shardings)``

so the steady-state eager hot path replays a compiled XLA executable
instead of re-tracing the op in Python and dispatching one primitive at a
time.  The autograd path caches a jitted vjp alongside (``lookup_recorded``)
so tapes built under ``autograd.record()`` replay compiled code too.

Knobs: ``MXNET_DISPATCH_CACHE=0`` disables the cache,
``MXNET_DISPATCH_CACHE_SIZE`` bounds the LRU (default 512 entries),
``MXNET_DISPATCH_CACHE_WARMUP`` is the number of un-jitted sightings of a
key before compiling it (default 1: one-shot shapes never pay a compile).
``engine.set_engine_type('NaiveEngine')`` bypasses the cache entirely.
"""
from __future__ import annotations

import functools
import inspect
import os
import threading
from collections import OrderedDict
from time import perf_counter as _perf

import jax as _jax
import numpy as _np

# hot-path type constants: attribute chains like ``jax.core.Tracer`` cost a
# dict walk per call at ~100k calls/sec dispatch rates, and
# ``isinstance(x, jax.Array)`` is an ABC __instancecheck__ (~10x the cost of
# an exact type test against the one concrete array class)
_JArray = _jax.Array
_JTracer = _jax.core.Tracer
try:
    # the concrete eager array class, WITHOUT running a computation —
    # type(jnp.zeros(())) would initialize the XLA backend at import time
    # and break jax.distributed.initialize() on multi-host workers
    from jax._src.array import ArrayImpl as _ArrayImpl
except ImportError:  # jax internals moved: exact-type fast path off,
    _ArrayImpl = ()  # the isinstance(_JArray) slow path still catches all

_SDSharding = _jax.sharding.SingleDeviceSharding
_SCALAR_TYPES = frozenset((bool, int, float, complex, str, type(None)))


def _sharding_token(s):
    """Hashable stand-in for a sharding in cache keys.  SingleDeviceSharding
    (the only kind eager CPU/GPU arrays carry) hashes by recomputation every
    time (~1us); its Device hashes like an int and compares equal exactly
    when the shardings do."""
    if type(s) is _SDSharding:
        return s._device
    return s

__all__ = ["Op", "register", "get_op", "list_ops", "alias",
           "dispatch_eager", "MISS", "lookup_eager", "lookup_recorded",
           "dispatch_cache_stats", "clear_dispatch_cache",
           "dispatch_cache_enabled", "set_dispatch_cache"]

_REGISTRY: dict[str, "Op"] = {}


class Op:
    """A registered operator.

    ``alias()`` registers the *same* ``Op`` object under additional names
    (recorded in ``aliases``), so ``elemwise_add``/``broadcast_add``/
    ``__add__`` share one ``fn`` identity and therefore one dispatch-cache
    entry — the cache key starts with ``fn``, never the name.
    """

    __slots__ = ("name", "fn", "differentiable", "wrap_ndarray", "doc", "aliases")

    def __init__(self, name, fn, differentiable=True, wrap_ndarray=True):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.wrap_ndarray = wrap_ndarray
        self.doc = fn.__doc__
        self.aliases = []

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return f"<Op {self.name}>"


def register(name=None, differentiable=True, wrap_ndarray=True,
             cacheable=True):
    """Decorator registering a pure function as a framework operator.

    ``cacheable=False`` keeps the op off both levels of the eager dispatch
    accelerator (level-1 jit cache and engine.bulk micro-graphs) — required
    for ops whose body runs arbitrary user python with side effects
    (``Custom``: freezing it into a compiled executable would replay stale
    state and skip the side effects)."""

    def deco(fn):
        opname = name or fn.__name__
        if opname in _REGISTRY:
            raise ValueError(f"op {opname!r} already registered")
        op = Op(opname, fn, differentiable, wrap_ndarray)
        _REGISTRY[opname] = op
        if cacheable:
            _CACHEABLE_FNS[fn] = op
        return fn

    return deco


def alias(new_name, existing):
    """Register an alias for an existing op (MXNet has many, e.g.
    ``elemwise_add`` vs ``broadcast_add`` vs ``__add__``).  The alias shares
    the canonical ``Op`` object — NOT a copy — so the dispatch cache compiles
    the underlying ``fn`` once no matter which name invoked it."""
    op = get_op(existing)
    if new_name in _REGISTRY:
        raise ValueError(f"op {new_name!r} already registered")
    _REGISTRY[new_name] = op
    op.aliases.append(new_name)


def get_op(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(_REGISTRY)


# ---------------------------------------------------------------------------
# Level-1 eager dispatch cache
# ---------------------------------------------------------------------------

# fn -> Op for every registered pure function; only these are eligible for
# the cache (closures handed to invoke() have no stable identity to key on).
_CACHEABLE_FNS: dict = {}

_enabled = os.environ.get("MXNET_DISPATCH_CACHE", "1") != "0"
_max_entries = int(os.environ.get("MXNET_DISPATCH_CACHE_SIZE", "512"))
_warmup = int(os.environ.get("MXNET_DISPATCH_CACHE_WARMUP", "1"))

_lock = threading.RLock()
_entries: OrderedDict = OrderedDict()   # key -> _Entry (compiled)
_pending: OrderedDict = OrderedDict()   # key -> sighting count (pre-warmup)
_unjittable: set = set()                # (fn, static key parts) that failed to trace

_DYN = object()  # sentinel in arg specs: "comes from the dynamic args"


class _Ineligible(Exception):
    """Raised during classification when a call can't be cached."""


def _scalar_token(tv, v):
    """THE scalar cache-key rule, shared by every non-fast-path key builder
    in this module and engine.py: type-tagged (1, 1.0, True, and
    np.float64(1.0) — a float subclass — are ==/hash-equal but bake
    different dtypes/promotion behavior into a compiled entry) and
    -0.0-split (-0.0 == 0.0 and they hash alike, but baking the wrong zero
    flips signs, e.g. x / -0.0; str() separates them).  The two genuinely
    hot inlined copies (the exact-type branches in _classify_args and
    engine._BulkQueue.enqueue) must mirror any change made here."""
    if isinstance(v, _np.generic):
        item = v.item()
        if isinstance(item, (float, complex)) and item == 0:
            return ("npg", v.dtype.str, item, str(item))
        return ("npg", v.dtype.str, item)
    if isinstance(v, (float, complex)) and v == 0:
        return (tv, v, str(v))
    return (tv, v)


def _static_token(v):
    """Hashable cache token for a static value.  Whitelist-based: anything
    not provably safe to bake into a jitted closure and compare by value
    (arbitrary objects may define exotic __eq__/__hash__, e.g. NDArray)
    raises TypeError → the call stays on the raw path."""
    if v is None:
        return v
    if isinstance(v, (bool, int, float, complex, str, bytes, type,
                      _np.generic)):
        return _scalar_token(type(v), v)
    if isinstance(v, (list, tuple)):
        return ("seq", type(v).__name__, tuple(_static_token(x) for x in v))
    if isinstance(v, dict):
        return ("map", tuple(sorted((k, _static_token(x)) for k, x in v.items())))
    if isinstance(v, _np.dtype):
        return ("dt", v.str)
    raise TypeError(f"not cache-keyable: {type(v)}")


def _aval_token(a):
    # NB: dtype object, not str(dtype) — str() costs ~6us per call on the
    # hottest path in the codebase; np.dtype hashes/compares cheaply
    return (a.shape, a.dtype, a.aval.weak_type, _sharding_token(a.sharding))


def _seq_has_array(v):
    return any(isinstance(x, (_JArray, _np.ndarray))
               or (isinstance(x, (list, tuple)) and _seq_has_array(x))
               for x in v)


_SCALARS = (bool, int, float, complex, str)


def _classify_args(raw_args):
    """Split positional args into (spec, key_parts, dyn_args).

    spec is a tuple with ``_DYN`` markers where a dynamic value is
    substituted at call time and literal values for statics (baked into the
    jitted closure; their tokens are part of the key).
    """
    spec, key, dyn = [], [], []
    for a in raw_args:
        ta = type(a)
        if ta is _ArrayImpl:  # exact test dodges the jax.Array ABC check
            key.append(("a", a.shape, a.dtype, a.aval.weak_type,
                        _sharding_token(a.sharding)))
            spec.append(_DYN)
            dyn.append(a)
            continue
        if ta in _SCALAR_TYPES:
            # scalars are STATIC (baked trace constants, keyed by type+value):
            # a dynamic scalar arg defeats jit's C++ fast dispatch path and
            # costs ~2x per call; eager chains overwhelmingly reuse the same
            # literal, and one-shot values never compile thanks to warmup
            if (ta is float or ta is complex) and a == 0:
                # -0.0 == 0.0 and they hash alike, but baking the wrong
                # zero flips signs (x / -0.0); str() splits them
                key.append(("s", ta, a, str(a)))
            else:
                key.append(("s", ta, a))
            spec.append(a)
            continue
        if isinstance(a, _JTracer):
            raise _Ineligible  # inside hybridize/SPMD traces: raw fallthrough
        if isinstance(a, _JArray):
            key.append(("a", a.shape, a.dtype, a.aval.weak_type,
                        _sharding_token(a.sharding)))
            spec.append(_DYN)
            dyn.append(a)
        elif isinstance(a, _SCALARS):
            # scalar subclasses (np.float64 subclasses float!): shared rule
            key.append(("s", _scalar_token(ta, a)))
            spec.append(a)
        elif isinstance(a, _np.ndarray):
            key.append(("n", a.shape, a.dtype.str))
            spec.append(_DYN)
            dyn.append(a)
        elif isinstance(a, _np.generic):
            key.append(("s", _scalar_token(ta, a)))
            spec.append(a)
        elif isinstance(a, (list, tuple)) and _seq_has_array(a):
            # pytree argument (e.g. add_n's array list): dynamic as a whole
            sub_spec, sub_key, _ = _classify_args(list(a))
            if any(s is not _DYN for s in sub_spec):
                raise _Ineligible  # mixed static/dynamic nesting: keep it raw
            key.append(("t", type(a).__name__, tuple(sub_key)))
            spec.append(_DYN)
            dyn.append(a)
        else:
            try:
                key.append(("s", _static_token(a)))
            except TypeError:
                raise _Ineligible from None
            spec.append(a)
    return tuple(spec), tuple(key), dyn


def _classify_kwargs(kwargs, jax=None):
    """Split kwargs into static (baked, keyed by value) and dynamic
    (jax.Array-valued, keyed by aval) parts."""
    static, key, dyn_names, dyn_vals = {}, [], [], []
    for k in sorted(kwargs):
        v = kwargs[k]
        if isinstance(v, _JTracer):
            raise _Ineligible
        if isinstance(v, _JArray):
            key.append(("ka", k) + _aval_token(v))
            dyn_names.append(k)
            dyn_vals.append(v)
        else:
            try:
                key.append(("ks", k, _static_token(v)))
            except TypeError:
                raise _Ineligible from None
            static[k] = v
    return static, tuple(key), tuple(dyn_names), dyn_vals


# flat memo of _reads_ambient_prng used by dispatch_eager: one dict get on
# the hot path instead of the lru_cache C wrapper + a kwargs.get per call
_PRNG_FNS: dict = {}


@functools.lru_cache(maxsize=None)
def _reads_ambient_prng(fn):
    """Ops with a ``key=None`` parameter split the process PRNG key at call
    time (Dropout, samplers) — caching them without an explicit key would
    freeze the randomness into the executable."""
    try:
        return "key" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return True  # can't introspect: stay off the fast path


def _cache_key(fn, raw_args, kwargs):
    """Returns (key, spec, dyn_args, static_kwargs, dyn_kw_names, dyn_kw_vals)
    or raises _Ineligible."""
    if fn not in _CACHEABLE_FNS:
        raise _Ineligible
    if _reads_ambient_prng(fn) and kwargs.get("key") is None:
        raise _Ineligible
    spec, akey, dyn = _classify_args(raw_args)
    if not kwargs:
        return (fn, akey, ()), spec, dyn, {}, (), []
    static_kw, kkey, dyn_kw_names, dyn_kw_vals = _classify_kwargs(kwargs)
    return (fn, akey, kkey), spec, dyn, static_kw, dyn_kw_names, dyn_kw_vals


class _Entry:
    __slots__ = ("fwd", "bwd", "call", "spec")

    # NB: fwd stays a pjit wrapper, NOT an AOT ``.lower().compile()``d
    # object — Compiled.__call__ is a pure-Python path (~1.5x slower per
    # call than pjit's C++ fast dispatch on repeat avals)

    def __init__(self, call, spec, jax):
        self.call = call            # un-jitted (dyn_args, dyn_kw) -> out
        self.spec = spec            # per-positional-arg _DYN/static markers
        self.fwd = jax.jit(call)
        self.bwd = {}               # needs mask -> jitted (dyn, kw, cots) -> grads


def _make_caller(fn, spec, static_kwargs, dyn_kw_names):
    def call(dyn_args, dyn_kw_vals):
        it = iter(dyn_args)
        args = [next(it) if s is _DYN else s for s in spec]
        if static_kwargs or dyn_kw_names:
            kw = dict(static_kwargs)
            kw.update(zip(dyn_kw_names, dyn_kw_vals))
            return fn(*args, **kw)
        return fn(*args)
    return call


_prof = None


def _counters():
    global _prof, _incr
    if _prof is None:
        from .. import profiler as _p

        _prof = _p
        _incr = _p.incr
    return _prof


def _incr(name):  # rebound to profiler.incr on first use (import-cycle dodge)
    _counters().incr(name)


def _compile_tok(tok):
    """Cache-key token -> compile-registry signature token (the key
    already carries exactly what the compiled entry specializes on)."""
    kind = tok[0]
    if kind == "a" or kind == "ka":
        off = 1 if kind == "a" else 2
        t = {"k": "array", "shape": tuple(tok[off]),
             "dtype": str(tok[off + 1])}
        spec = getattr(tok[off + 3], "spec", None)
        if spec is not None:
            t["sharding"] = str(spec)
        return t
    if kind == "n":
        return {"k": "array", "shape": tuple(tok[1]), "dtype": str(tok[2])}
    off = 2 if kind == "ks" else 1
    return {"k": "static", "value": repr(tok[off] if len(tok) == off + 1
                                         else tok[off:])[:120]}


def _compile_sig(fn, akey, kkey):
    """Compile-registry signature for a level-1 cache entry: per-position
    array/static tokens namespaced by the op (__program__), so a new op's
    first compile is never misattributed as another op's recompile.  A
    pytree argument ("t" token, e.g. add_n's array list) expands into one
    entry per leaf — ``arg0[2]`` — so a drift inside the list attributes
    at the leaf with its real kind (shape/dtype), not as an opaque
    static-value change."""
    sig = {"__program__": getattr(fn, "__name__", str(fn))}
    for i, tok in enumerate(akey):
        if tok[0] == "t":
            for j, sub in enumerate(tok[2]):
                sig[f"arg{i}[{j}]"] = _compile_tok(sub)
        else:
            sig[f"arg{i}"] = _compile_tok(tok)
    for tok in kkey:
        sig[str(tok[1])] = _compile_tok(tok)
    return sig


def _get_entry(fn, raw_args, kwargs):
    """Core lookup: returns (entry, dyn_args, dyn_kw_vals, key, fresh)
    when a compiled entry exists (counting a hit; ``fresh`` means this
    call just created it, so its first execution pays trace+compile), or
    None (counting a miss/bypass) when the call should take the raw path
    this time."""
    try:
        key, spec, dyn, static_kw, dkn, dkv = _cache_key(fn, raw_args, kwargs)
    except _Ineligible:
        _incr("dispatch_cache_bypass")
        return None
    # hit path is lock-free: C OrderedDict ops are GIL-atomic, and a lost
    # move_to_end race only perturbs LRU order, never correctness
    entry = _entries.get(key)
    if entry is not None:
        try:
            _entries.move_to_end(key)
        except KeyError:
            pass  # concurrently evicted; the fetched entry is still valid
        _incr("dispatch_cache_hit")
        return entry, dyn, dkv, key, False
    entry = _miss(fn, key, spec, static_kw, dkn)
    if entry is None:
        return None
    return entry, dyn, dkv, key, True


def _blacklist(fn, key):
    """Drop a failed entry and remember not to recompile it (under _lock).
    Keyed per exact (fn, statics, avals) key, so a shape-independent trace
    failure is re-attempted once per new input shape; bounded so
    variable-shape workloads can't grow the set without limit (a clear just
    costs the occasional repeat failed compile)."""
    _entries.pop(key, None)
    _unjittable.add((fn, key[1], key[2]))
    if len(_unjittable) > 4 * _max_entries:
        _unjittable.clear()


MISS = object()  # dispatch_eager sentinel: caller must run the raw fn


def dispatch_eager(fn, raw_args, kwargs):
    """Level-1 fast path for non-recorded eager dispatch.

    Returns the op's raw output when served from a compiled cache entry,
    else the ``MISS`` sentinel (caller runs the raw fn).  Never raises for
    cache reasons: a key that fails to trace is blacklisted and the genuine
    error is re-raised from the raw eager call so user-visible errors keep
    eager semantics.
    """
    if not _enabled:
        return MISS
    # inlined _cache_key + hit lookup: this runs once per eager op call
    try:
        prng = _PRNG_FNS.get(fn)
        if prng is None:
            if fn not in _CACHEABLE_FNS:
                raise _Ineligible
            prng = _PRNG_FNS[fn] = _reads_ambient_prng(fn)
        if prng and kwargs.get("key") is None:
            raise _Ineligible
        spec, akey, dyn = _classify_args(raw_args)
        if kwargs:
            static_kw, kkey, dkn, dkv = _classify_kwargs(kwargs)
        else:
            static_kw, kkey, dkn, dkv = {}, (), (), []
    except _Ineligible:
        _incr("dispatch_cache_bypass")
        return MISS
    key = (fn, akey, kkey)
    # hit path is lock-free: C OrderedDict ops are GIL-atomic, and a lost
    # move_to_end race only perturbs LRU order, never correctness
    entry = _entries.get(key)
    fresh = False
    if entry is None:
        entry = _miss(fn, key, spec, static_kw, dkn)
        if entry is None:
            return MISS
        fresh = True  # first fwd call traces+compiles: the jit-trace span
    else:
        try:
            _entries.move_to_end(key)
        except KeyError:
            pass  # concurrently evicted; the fetched entry is still valid
        _incr("dispatch_cache_hit")
    prof = _prof
    t0 = _perf() if (prof is not None and prof._active) else None
    tc = _perf() if fresh else None
    try:
        out = entry.fwd(tuple(dyn), tuple(dkv))
    except Exception:
        # Re-run raw: if *that* succeeds the failure was a jit artifact
        # (concretization on a dynamic value, etc.) — blacklist the key
        # family.  If raw raises too, the error was genuine and propagates
        # with eager semantics.
        out = fn(*raw_args, **kwargs)
        with _lock:
            _blacklist(fn, key)
        _counters().incr("dispatch_cache_fallback")
        if t0 is not None:
            prof.record_span("dispatch.fallback", "dispatch", t0)
        return out
    if t0 is not None:
        prof.record_span("dispatch.jit_compile" if fresh
                         else "dispatch.cache_hit", "dispatch", t0)
    if fresh:
        # compile registry AFTER the fallback try-block: a guard in raise
        # mode must surface, not blacklist the entry as a jit failure
        _counters().record_compile("ops.dispatch", _compile_sig(fn, akey, kkey),
                                   (_perf() - tc) * 1e3)
    return out


def _miss(fn, key, spec, static_kw, dkn):
    """Slow half of dispatch_eager: warmup accounting and entry compilation
    under the registry lock.  Returns the new entry or None (raw path)."""
    with _lock:
        entry = _entries.get(key)
        if entry is not None:
            _incr("dispatch_cache_hit")
            return entry
        if (fn, key[1], key[2]) in _unjittable:
            _incr("dispatch_cache_bypass")
            return None
        _incr("dispatch_cache_miss")
        seen = _pending.get(key, 0) + 1
        if seen <= _warmup:
            # not hot yet: remember the sighting, stay on the raw path
            _pending[key] = seen
            _pending.move_to_end(key)
            while len(_pending) > 4 * _max_entries:
                _pending.popitem(last=False)
            return None
        _pending.pop(key, None)
        entry = _Entry(_make_caller(fn, spec, static_kw, dkn), spec, _jax)
        _entries[key] = entry
        while len(_entries) > _max_entries:
            _entries.popitem(last=False)
    return entry


def lookup_eager(fn, raw_args, kwargs):
    """Compatibility shim over :func:`dispatch_eager` returning the old
    ``(hit, out)`` pair (tests and external callers)."""
    out = dispatch_eager(fn, raw_args, kwargs)
    if out is MISS:
        return False, None
    return True, out


def _make_bwd(call, diff_pos, jax):
    def bwd(dyn_args, dyn_kw_vals, cots):
        def pure(*diff):
            full = list(dyn_args)
            for p, d in zip(diff_pos, diff):
                full[p] = d
            out = call(tuple(full), dyn_kw_vals)
            return out if isinstance(out, tuple) else (out,)

        _, vjp = jax.vjp(pure, *[dyn_args[p] for p in diff_pos])
        return vjp(cots)
    return bwd


def lookup_recorded(fn, raw_args, kwargs, needs):
    """Level-1 fast path for dispatch under ``autograd.record()``.

    Returns ``(outs_tuple, vjp_fn, pure, diff_in)`` where ``vjp_fn`` replays
    a cached jitted vjp (rematerializing the forward inside the compiled
    backward, so no residuals persist beyond the input arrays), or ``None``
    when the caller should take the raw ``jax.vjp`` path.  ``pure`` and
    ``diff_in`` satisfy the tape's grad-of-grad replay contract
    (autograd._grad_create_graph re-derives the vjp from them eagerly).
    """
    if not _enabled:
        return None
    jax = _jax
    found = _get_entry(fn, raw_args, kwargs)
    if found is None:
        return None
    entry, dyn, dkv, key, fresh = found
    dyn = tuple(dyn)
    dkv = tuple(dkv)
    # positions of the grad-needing inputs within the dynamic-arg tuple:
    # every needing input is an unwrapped NDArray, hence dynamic
    diff_pos, dyn_i = [], 0
    for a_needs, s in zip(needs, entry.spec):
        if s is _DYN:
            if a_needs:
                diff_pos.append(dyn_i)
            dyn_i += 1
        elif a_needs:  # needing input landed in a static slot: not cacheable
            return None
    diff_pos = tuple(diff_pos)

    prof = _prof
    t0 = _perf() if (prof is not None and prof._active) else None
    tc = _perf() if fresh else None
    try:
        out = entry.fwd(dyn, dkv)
    except Exception:
        # blacklist and hand control back to record_op's raw jax.vjp path:
        # a genuine user error re-raises from there with eager semantics
        # (no need to probe-run fn here — that would execute the op twice)
        with _lock:
            _blacklist(fn, key)
        _counters().incr("dispatch_cache_fallback")
        return None
    if t0 is not None:
        prof.record_span("dispatch.jit_compile" if fresh
                         else "dispatch.cache_hit", "dispatch", t0)
    if fresh:
        _counters().record_compile("ops.dispatch",
                                   _compile_sig(fn, key[1], key[2]),
                                   (_perf() - tc) * 1e3)
    outs = out if isinstance(out, tuple) else (out,)

    bwd = entry.bwd.get(diff_pos)
    if bwd is None:
        bwd = jax.jit(_make_bwd(entry.call, diff_pos, jax))
        entry.bwd[diff_pos] = bwd

    def vjp_fn(cots, _bwd=bwd, _call=entry.call, _pos=diff_pos,
               _dyn=dyn, _dkv=dkv):
        cots = tuple(cots)
        p = _prof
        tb = _perf() if (p is not None and p._active) else None
        try:
            grads = _bwd(_dyn, _dkv, cots)
            if tb is not None:
                p.record_span("dispatch.backward", "dispatch", tb)
            return grads
        except Exception:
            # mirror the forward fallback: eager vjp keeps correctness if
            # the jitted backward trips on something the forward didn't
            # (built lazily — this path is exceptional)
            return _make_bwd(_call, _pos, _jax)(_dyn, _dkv, cots)

    # grad-of-grad replay contract: a pure fn over just the diff inputs
    # plus their record-time snapshots
    def pure(*diff, _call=entry.call, _dyn=dyn, _dkv=dkv, _pos=diff_pos):
        full = list(_dyn)
        for p, d in zip(_pos, diff):
            full[p] = d
        out = _call(tuple(full), _dkv)
        return out if isinstance(out, tuple) else (out,)

    diff_in = [dyn[p] for p in diff_pos]
    return outs, vjp_fn, pure, diff_in


def dispatch_cache_stats():
    """Snapshot of cache occupancy (counters live in mx.profiler)."""
    with _lock:
        return {
            "entries": len(_entries),
            "pending": len(_pending),
            "blacklisted": len(_unjittable),
            "enabled": _enabled,
            "max_entries": _max_entries,
            "warmup": _warmup,
        }


def clear_dispatch_cache():
    """Drop all compiled entries, warmup counts, and blacklists (used by
    amp.init-style global-semantics flips and tests)."""
    with _lock:
        _entries.clear()
        _pending.clear()
        _unjittable.clear()
    _reads_ambient_prng.cache_clear()
    _PRNG_FNS.clear()


def dispatch_cache_enabled():
    return _enabled


def set_dispatch_cache(enabled=None, max_entries=None, warmup=None):
    """Runtime control of the level-1 cache; returns previous settings."""
    global _enabled, _max_entries, _warmup
    prev = (_enabled, _max_entries, _warmup)
    with _lock:
        if enabled is not None:
            _enabled = bool(enabled)
        if max_entries is not None:
            _max_entries = int(max_entries)
            while len(_entries) > _max_entries:
                _entries.popitem(last=False)
        if warmup is not None:
            _warmup = int(warmup)
    return prev
