"""Operator registry — TPU-native replacement for the NNVM op registry.

Parity target: ``NNVM_REGISTER_OP`` + ``FCompute`` dispatch
([U:src/operator/], [U:include/mxnet/op_attr_types.h]).  Differences by
design:

* An op is a **pure function** ``fn(*jax_arrays, **static_kwargs)`` returning
  a jax.Array or tuple thereof.  No FInferShape/FInferType tables are needed —
  ``jax.eval_shape`` performs shape/dtype inference on the same function that
  computes (used by Symbol.infer_shape and deferred Parameter init).
* No FGradient registration — gradients come from ``jax.vjp`` of the same
  pure function (the autograd tape calls it), so every op is differentiable
  for free unless marked ``differentiable=False``.
* CPU/GPU/TPU kernel variants collapse into one definition; XLA specializes
  per backend.
"""
from __future__ import annotations

import functools

__all__ = ["Op", "register", "get_op", "list_ops", "alias"]

_REGISTRY: dict[str, "Op"] = {}


class Op:
    """A registered operator."""

    __slots__ = ("name", "fn", "differentiable", "wrap_ndarray", "doc")

    def __init__(self, name, fn, differentiable=True, wrap_ndarray=True):
        self.name = name
        self.fn = fn
        self.differentiable = differentiable
        self.wrap_ndarray = wrap_ndarray
        self.doc = fn.__doc__

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return f"<Op {self.name}>"


def register(name=None, differentiable=True, wrap_ndarray=True):
    """Decorator registering a pure function as a framework operator."""

    def deco(fn):
        opname = name or fn.__name__
        if opname in _REGISTRY:
            raise ValueError(f"op {opname!r} already registered")
        _REGISTRY[opname] = Op(opname, fn, differentiable, wrap_ndarray)
        return fn

    return deco


def alias(new_name, existing):
    """Register an alias for an existing op (MXNet has many, e.g.
    ``elemwise_add`` vs ``broadcast_add`` vs ``__add__``)."""
    op = get_op(existing)
    if new_name in _REGISTRY:
        raise ValueError(f"op {new_name!r} already registered")
    _REGISTRY[new_name] = Op(new_name, op.fn, op.differentiable, op.wrap_ndarray)


def get_op(name):
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(_REGISTRY)


@functools.lru_cache(maxsize=None)
def _jitted(name):
    """Return a jit-compiled version of a registered op (used by hot paths
    like fused optimizer updates; everyday eager dispatch stays un-jitted and
    relies on XLA's per-primitive caching)."""
    import jax

    op = get_op(name)
    return jax.jit(op.fn, static_argnames=())
