"""Spatial / vision operators and the remaining legacy loss heads.

Parity targets: [U:src/operator/roi_pooling.cc], [U:src/operator/contrib/
roi_align.cc], [U:src/operator/bilinear_sampler.cc], [U:src/operator/
spatial_transformer.cc], [U:src/operator/grid_generator.cc],
[U:src/operator/correlation.cc], [U:src/operator/nn/im2col.h],
[U:src/operator/nn/lrn.cc], [U:src/operator/contrib/bilinear_resize.cc],
[U:src/operator/contrib/adaptive_avg_pooling.cc], [U:src/operator/
svm_output.cc], [U:src/operator/regression_output.cc], [U:src/operator/
contrib/ctc_loss.cc], and assorted tensor utilities (depth_to_space,
unravel_index, index_array …).

TPU-first design notes:

* Everything is static-shape.  Per-ROI dynamic bin extents become masked
  reductions (ROIPooling) or fixed sampling grids (ROIAlign with an
  explicit ``sample_ratio``); adaptive pooling becomes two averaging
  matmuls that run on the MXU instead of per-bin scalar loops.
* CTC runs the log-space forward recursion as one ``lax.scan`` over time —
  the gradient comes from differentiating the scan, no hand-written
  backward (the reference carries a warp-ctc port for this).
* ``col2im`` is literally the VJP of ``im2col`` — scatter-add inverse for
  free instead of a mirrored kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import alias, register

__all__ = []


# ---------------------------------------------------------------------------
# layout shuffles
# ---------------------------------------------------------------------------


@register("depth_to_space")
def depth_to_space(data, block_size):
    """DCR-mode depth→space ([U:src/operator/tensor/matrix_op.cc])."""
    b, c, h, w = data.shape
    bs = int(block_size)
    x = data.reshape(b, bs, bs, c // (bs * bs), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(b, c // (bs * bs), h * bs, w * bs)


@register("space_to_depth")
def space_to_depth(data, block_size):
    b, c, h, w = data.shape
    bs = int(block_size)
    x = data.reshape(b, c, h // bs, bs, w // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(b, c * bs * bs, h // bs, w // bs)


@register("unravel_index")
def unravel_index(data, shape):
    out = jnp.unravel_index(data.astype(jnp.int32), tuple(shape))
    return jnp.stack(out, axis=0)


@register("ravel_multi_index")
def ravel_multi_index(data, shape):
    shape = tuple(shape)
    strides = []
    acc = 1
    for s in reversed(shape):
        strides.append(acc)
        acc *= s
    strides = jnp.asarray(list(reversed(strides)), dtype=data.dtype)
    return jnp.sum(data * strides.reshape(-1, *([1] * (data.ndim - 1))), axis=0)


@register("index_array", differentiable=False)
def index_array(data, axes=None):
    """Per-element coordinate tensor ([U:src/operator/contrib/index_array.cc]):
    output shape = data.shape + (len(axes),)."""
    axes = tuple(axes) if axes is not None else tuple(range(data.ndim))
    grids = jnp.meshgrid(*[jnp.arange(s) for s in data.shape], indexing="ij")
    return jnp.stack([grids[a] for a in axes], axis=-1).astype(jnp.int64)


@register("index_copy")
def index_copy(old, index, new):
    """Row-copy into a tensor at ``index`` ([U:src/operator/contrib/
    index_copy.cc])."""
    return old.at[index.astype(jnp.int32)].set(new)


@register("arange_like", differentiable=False)
def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    """arange shaped by ``data`` ([U:src/operator/tensor/init_op.cc]
    _contrib_arange_like): output size is fixed by data (full shape for
    axis=None, ``data.shape[axis]`` otherwise); ``repeat`` packs
    ``size // repeat`` distinct values, each repeated, into that size."""
    repeat = max(1, int(repeat))

    def _ramp(size):
        n_distinct = -(-size // repeat)  # ceil
        vals = start + step * jnp.arange(n_distinct, dtype=jnp.float32)
        return jnp.repeat(vals, repeat)[:size]

    if axis is None:
        n = 1
        for s in data.shape:
            n *= s
        return _ramp(n).reshape(data.shape)
    return _ramp(data.shape[axis])


# ---------------------------------------------------------------------------
# masked softmax
# ---------------------------------------------------------------------------


@register("masked_softmax")
def masked_softmax(data, mask, axis=-1, temperature=1.0):
    """Softmax over positions where ``mask`` is True ([U:src/operator/nn/
    softmax.cc] masked variant); fully-masked rows return 0."""
    neg = jnp.finfo(jnp.float32).min
    x = jnp.where(mask, data.astype(jnp.float32) / temperature, neg)
    m = jnp.max(x, axis=axis, keepdims=True)
    e = jnp.exp(x - m) * mask.astype(jnp.float32)
    denom = jnp.sum(e, axis=axis, keepdims=True)
    return (e / jnp.maximum(denom, 1e-37)).astype(data.dtype)


@register("masked_log_softmax")
def masked_log_softmax(data, mask, axis=-1, temperature=1.0):
    neg = jnp.finfo(jnp.float32).min
    x = jnp.where(mask, data.astype(jnp.float32) / temperature, neg)
    m = jnp.max(x, axis=axis, keepdims=True)
    e = jnp.exp(x - m) * mask.astype(jnp.float32)
    lse = jnp.log(jnp.maximum(jnp.sum(e, axis=axis, keepdims=True), 1e-37)) + m
    return jnp.where(mask, (x - lse), neg).astype(data.dtype)


# ---------------------------------------------------------------------------
# LRN
# ---------------------------------------------------------------------------


@register("LRN")
def lrn(data, nsize=5, alpha=1e-4, beta=0.75, knorm=2.0):
    """Cross-channel local response normalization (AlexNet-era;
    [U:src/operator/nn/lrn.cc])."""
    sq = jnp.square(data.astype(jnp.float32))
    half = int(nsize) // 2
    # sum over a channel window via padded cumulative trick (static shapes)
    padded = jnp.pad(sq, [(0, 0), (half, half), (0, 0), (0, 0)])
    windows = [padded[:, i:i + data.shape[1]] for i in range(2 * half + 1)]
    ssum = sum(windows)
    norm = (knorm + alpha / nsize * ssum) ** beta
    return (data.astype(jnp.float32) / norm).astype(data.dtype)


# ---------------------------------------------------------------------------
# bilinear sampling core + its consumers
# ---------------------------------------------------------------------------


def _bilinear_gather(data, x, y):
    """Sample data [B,C,H,W] at fractional pixel coords x,y [B,...] with
    zero padding outside; returns [B,C,...]."""
    B, C, H, W = data.shape
    out_shape = x.shape[1:]
    x = x.reshape(B, -1)
    y = y.reshape(B, -1)
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    def gather(yi, xi):
        inb = (xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        flat = data.reshape(B, C, H * W)
        idx = yc * W + xc  # [B, N]
        vals = jnp.take_along_axis(flat, idx[:, None, :], axis=2)  # [B,C,N]
        return vals * inb[:, None, :].astype(data.dtype)

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx[:, None, :].astype(data.dtype)
    wy = wy[:, None, :].astype(data.dtype)
    out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
           + v10 * (1 - wx) * wy + v11 * wx * wy)
    return out.reshape(B, C, *out_shape)


@register("BilinearSampler")
def bilinear_sampler(data, grid, cudnn_off=None):
    """Sample ``data`` at ``grid`` coords in [-1,1] ([U:src/operator/
    bilinear_sampler.cc]); grid layout [B, 2(x,y), Ho, Wo]."""
    B, C, H, W = data.shape
    gx = (grid[:, 0].astype(jnp.float32) + 1.0) * (W - 1) / 2.0
    gy = (grid[:, 1].astype(jnp.float32) + 1.0) * (H - 1) / 2.0
    return _bilinear_gather(data, gx, gy)


@register("GridGenerator")
def grid_generator(data, transform_type="affine", target_shape=(0, 0)):
    """Affine-parameter or flow input → sampling grid ([U:src/operator/
    grid_generator.cc])."""
    H, W = int(target_shape[0]), int(target_shape[1])
    if transform_type == "affine":
        B = data.shape[0]
        theta = data.reshape(B, 2, 3).astype(jnp.float32)
        ys = jnp.linspace(-1.0, 1.0, H)
        xs = jnp.linspace(-1.0, 1.0, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # [3, HW]
        out = jnp.einsum("bij,jk->bik", theta, coords)  # [B, 2, HW]
        return out.reshape(B, 2, H, W)
    # 'warp': data is a flow field [B, 2, H, W] in pixels
    B, _, Hf, Wf = data.shape
    ys = jnp.arange(Hf, dtype=jnp.float32)
    xs = jnp.arange(Wf, dtype=jnp.float32)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    x = data[:, 0].astype(jnp.float32) + gx
    y = data[:, 1].astype(jnp.float32) + gy
    gxn = 2.0 * x / max(Wf - 1, 1) - 1.0
    gyn = 2.0 * y / max(Hf - 1, 1) - 1.0
    return jnp.stack([gxn, gyn], axis=1)


@register("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0), transform_type="affine",
                        sampler_type="bilinear", cudnn_off=None):
    """Affine spatial transformer = GridGenerator ∘ BilinearSampler
    ([U:src/operator/spatial_transformer.cc])."""
    grid = grid_generator(loc, "affine", target_shape)
    return bilinear_sampler(data, grid)


@register("_contrib_BilinearResize2D")
def bilinear_resize2d(data, height=0, width=0, scale_height=None, scale_width=None,
                      mode="size", align_corners=True):
    """Bilinear resize with align-corners semantics ([U:src/operator/
    contrib/bilinear_resize.cc])."""
    B, C, H, W = data.shape
    if scale_height is not None:
        height = int(round(H * scale_height))
    if scale_width is not None:
        width = int(round(W * scale_width))
    Ho, Wo = int(height), int(width)

    def coords(n_out, n_in):
        if align_corners and n_out > 1:
            return jnp.linspace(0.0, n_in - 1.0, n_out)
        return (jnp.arange(n_out) + 0.5) * n_in / n_out - 0.5

    ys = coords(Ho, H)
    xs = coords(Wo, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    gx = jnp.broadcast_to(gx, (B, Ho, Wo))
    gy = jnp.broadcast_to(gy, (B, Ho, Wo))
    return _bilinear_gather(data, gx, gy)


@register("_contrib_AdaptiveAvgPooling2D")
def adaptive_avg_pooling2d(data, output_size=(1, 1)):
    """Adaptive average pooling as two averaging matmuls (MXU-friendly;
    per-bin boundaries follow the reference's floor/ceil rule)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    Ho, Wo = int(output_size[0]), int(output_size[1])
    B, C, H, W = data.shape

    def avg_matrix(n_out, n_in):
        import numpy as np

        m = np.zeros((n_out, n_in), dtype=np.float32)
        for i in range(n_out):
            s = (i * n_in) // n_out
            e = -(-((i + 1) * n_in) // n_out)  # ceil
            m[i, s:e] = 1.0 / (e - s)
        return jnp.asarray(m)

    A = avg_matrix(Ho, H)
    Bm = avg_matrix(Wo, W)
    x = data.astype(jnp.float32)
    out = jnp.einsum("oh,bchw,pw->bcop", A, x, Bm)
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# ROI ops
# ---------------------------------------------------------------------------


@register("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0):
    """Max-pool each ROI into a fixed grid ([U:src/operator/roi_pooling.cc]).
    Dynamic per-ROI bin extents become masked max-reductions (static
    shapes; empty bins yield 0 as in the reference)."""
    PH, PW = int(pooled_size[0]), int(pooled_size[1])
    B, C, H, W = data.shape
    R = rois.shape[0]
    batch_idx = rois[:, 0].astype(jnp.int32)
    # half-away-from-zero, as the reference rounds (not banker's)
    _round = lambda v: jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)
    x1 = _round(rois[:, 1] * spatial_scale)
    y1 = _round(rois[:, 2] * spatial_scale)
    x2 = _round(rois[:, 3] * spatial_scale)
    y2 = _round(rois[:, 4] * spatial_scale)
    roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)

    def bin_mask(start, extent, P, size):
        # mask[r, p, s] = start_p <= s < end_p  over the spatial axis
        idx = jnp.arange(P, dtype=jnp.float32)
        bin_sz = extent[:, None] / P  # [R,1]
        s0 = jnp.floor(start[:, None] + idx[None, :] * bin_sz)
        s1 = jnp.ceil(start[:, None] + (idx[None, :] + 1) * bin_sz)
        s0 = jnp.clip(s0, 0, size)
        s1 = jnp.clip(s1, 0, size)
        coords = jnp.arange(size, dtype=jnp.float32)
        return (coords[None, None, :] >= s0[:, :, None]) & (coords[None, None, :] < s1[:, :, None])

    row_m = bin_mask(y1, roi_h, PH, H)  # [R, PH, H]
    col_m = bin_mask(x1, roi_w, PW, W)  # [R, PW, W]
    feat = data[batch_idx]  # [R, C, H, W]
    neg = jnp.finfo(jnp.float32).min
    f32 = feat.astype(jnp.float32)
    # reduce H under the row mask: [R,1,PH,H,1] × [R,C,1,H,W] → [R,C,PH,W]
    tmp = jnp.max(jnp.where(row_m[:, None, :, :, None], f32[:, :, None, :, :], neg), axis=3)
    # reduce W under the col mask: [R,1,1,PW,W] × [R,C,PH,1,W] → [R,C,PH,PW]
    out = jnp.max(jnp.where(col_m[:, None, None, :, :], tmp[:, :, :, None, :], neg), axis=4)
    out = jnp.where(out == neg, 0.0, out)  # empty bins → 0
    return out.astype(data.dtype)


@register("_contrib_ROIAlign")
def roi_align(data, rois, pooled_size=(1, 1), spatial_scale=1.0, sample_ratio=-1,
              position_sensitive=False, aligned=False):
    """Average of bilinear samples per bin ([U:src/operator/contrib/
    roi_align.cc]).  ``sample_ratio<=0`` (adaptive in the reference) uses a
    fixed 2×2 grid — static shapes are the TPU contract; GluonCV's
    detectors use sample_ratio=2 as well."""
    if position_sensitive:
        raise NotImplementedError("position_sensitive ROIAlign is not supported")
    PH, PW = int(pooled_size[0]), int(pooled_size[1])
    S = int(sample_ratio) if int(sample_ratio) > 0 else 2
    B, C, H, W = data.shape
    R = rois.shape[0]
    batch_idx = rois[:, 0].astype(jnp.int32)
    offset = 0.5 if aligned else 0.0
    x1 = rois[:, 1] * spatial_scale - offset
    y1 = rois[:, 2] * spatial_scale - offset
    x2 = rois[:, 3] * spatial_scale - offset
    y2 = rois[:, 4] * spatial_scale - offset
    roi_w = x2 - x1
    roi_h = y2 - y1
    if not aligned:
        roi_w = jnp.maximum(roi_w, 1.0)
        roi_h = jnp.maximum(roi_h, 1.0)
    bin_h = roi_h / PH
    bin_w = roi_w / PW
    iy = (jnp.arange(S, dtype=jnp.float32) + 0.5) / S  # offsets inside a bin
    py = jnp.arange(PH, dtype=jnp.float32)
    px = jnp.arange(PW, dtype=jnp.float32)
    # y coords: [R, PH, S]
    ys = (y1[:, None, None] + (py[None, :, None] + iy[None, None, :]) * bin_h[:, None, None])
    xs = (x1[:, None, None] + (px[None, :, None] + iy[None, None, :]) * bin_w[:, None, None])
    # full sample grid per roi: [R, PH, S, PW, S]
    gy = jnp.broadcast_to(ys[:, :, :, None, None], (R, PH, S, PW, S))
    gx = jnp.broadcast_to(xs[:, None, None, :, :], (R, PH, S, PW, S))
    feat = data[batch_idx]
    vals = _bilinear_gather(feat, gx, gy)  # [R, C, PH, S, PW, S]
    return jnp.mean(vals, axis=(3, 5)).astype(data.dtype)


alias("ROIAlign", "_contrib_ROIAlign")


# ---------------------------------------------------------------------------
# Deformable ops (DCN / R-FCN lineage)
# ---------------------------------------------------------------------------


@register("_contrib_DeformableConvolution")
def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1, num_deformable_group=1,
                           no_bias=False, workspace=None, layout=None):
    """Deformable convolution v1 ([U:src/operator/contrib/
    deformable_convolution.cc], Dai et al. 2017): each kernel tap samples
    the input at a learned fractional offset.  TPU design: one vectorized
    bilinear gather builds the deformed im2col patches [B, C, K, Ho, Wo],
    then the conv contraction is a single einsum (MXU matmul) over (c, k) —
    no per-position scalar loops, static shapes throughout.

    ``offset`` is [B, 2*DG*kh*kw, Ho, Wo]; per deformable group the channel
    pairs are (Δy, Δx) per kernel tap, the reference's layout.  Out-of-image
    samples read 0, as the reference's im2col does.
    """
    kh, kw = int(kernel[0]), int(kernel[1])
    sh, sw = int(stride[0]), int(stride[1])
    dh, dw = int(dilate[0]), int(dilate[1])
    ph, pw = int(pad[0]), int(pad[1])
    B, C, H, W = data.shape
    DG = int(num_deformable_group)
    G = int(num_group)
    O = int(num_filter) or weight.shape[0]
    K = kh * kw
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    if offset.shape[1] != 2 * DG * K:
        raise ValueError(
            f"offset channels {offset.shape[1]} != 2*num_deformable_group*kh*kw"
            f" = {2 * DG * K}")
    if C % DG or C % G:
        raise ValueError(
            f"num_group ({G}) and num_deformable_group ({DG}) must both "
            f"divide the input channel count ({C})")
    if O % G:
        raise ValueError(f"num_group ({G}) must divide num_filter ({O})")

    off = offset.reshape(B, DG, K, 2, Ho, Wo).astype(jnp.float32)
    ky, kx = jnp.meshgrid(jnp.arange(kh, dtype=jnp.float32) * dh,
                          jnp.arange(kw, dtype=jnp.float32) * dw, indexing="ij")
    base_y = (ky.ravel()[:, None, None]
              + (jnp.arange(Ho, dtype=jnp.float32) * sh - ph)[None, :, None])
    base_x = (kx.ravel()[:, None, None]
              + (jnp.arange(Wo, dtype=jnp.float32) * sw - pw)[None, None, :])
    y = base_y[None, None] + off[:, :, :, 0]  # [B, DG, K, Ho, Wo]
    x = base_x[None, None] + off[:, :, :, 1]

    Cg = C // DG
    datag = data.reshape(B * DG, Cg, H, W)
    vals = _bilinear_gather(datag, x.reshape(B * DG, K, Ho, Wo),
                            y.reshape(B * DG, K, Ho, Wo))  # [B*DG, Cg, K, Ho, Wo]
    patches = vals.reshape(B, C, K, Ho, Wo)

    wg = weight.reshape(G, O // G, C // G, K).astype(jnp.float32)
    pg = patches.reshape(B, G, C // G, K, Ho, Wo).astype(jnp.float32)
    out = jnp.einsum("bgckhw,gock->bgohw", pg, wg).reshape(B, O, Ho, Wo)
    if bias is not None and not no_bias:
        out = out + bias.astype(jnp.float32)[None, :, None, None]
    return out.astype(data.dtype)


@register("_contrib_DeformablePSROIPooling")
def deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=0, group_size=1, pooled_size=1,
                             part_size=0, sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """Deformable position-sensitive ROI pooling ([U:src/operator/contrib/
    deformable_psroi_pooling.cc], the R-FCN/DCN head).  Each pooled bin
    (ph, pw) of output channel ``ctop`` averages ``sample_per_part``²
    bilinear samples from score-map channel ``(ctop*G + gh)*G + gw``, the
    bin region shifted by the learned normalized offsets in ``trans``
    (scaled by ``trans_std`` and the ROI extent).  TPU design: the bin→
    channel map is static, so everything becomes one flattened 4-corner
    gather over [R, OD, P, P, S, S] — no dynamic shapes.

    Returns the pooled output [R, output_dim, P, P] (the reference's second
    ``top_count`` output is backward bookkeeping its CUDA kernel needs;
    autodiff subsumes it here).
    """
    B, C, H, W = data.shape
    R = rois.shape[0]
    P = int(pooled_size)
    G = int(group_size)
    S = int(sample_per_part)
    part = int(part_size) or P
    OD = int(output_dim) or C // (G * G)
    if C != OD * G * G:
        raise ValueError(f"data channels {C} != output_dim*group_size² = {OD * G * G}")

    rois = rois.astype(jnp.float32)
    batch_idx = rois[:, 0].astype(jnp.int32)
    _round = lambda v: jnp.sign(v) * jnp.floor(jnp.abs(v) + 0.5)
    x1 = _round(rois[:, 1]) * spatial_scale - 0.5
    y1 = _round(rois[:, 2]) * spatial_scale - 0.5
    x2 = (_round(rois[:, 3]) + 1.0) * spatial_scale - 0.5
    y2 = (_round(rois[:, 4]) + 1.0) * spatial_scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h, bin_w = rh / P, rw / P
    sub_h, sub_w = bin_h / S, bin_w / S

    phs = jnp.arange(P, dtype=jnp.float32)
    part_idx = jnp.floor(jnp.arange(P) * part / P).astype(jnp.int32)
    if no_trans or trans is None:
        ncls = 1
        tx = jnp.zeros((R, 1, P, P), jnp.float32)
        ty = jnp.zeros((R, 1, P, P), jnp.float32)
    else:
        ncls = trans.shape[1] // 2
        t = trans.reshape(R, ncls, 2, part, part).astype(jnp.float32)
        t = t[:, :, :, part_idx][:, :, :, :, part_idx]  # [R, ncls, 2, P, P]
        tx = t[:, :, 0] * float(trans_std)
        ty = t[:, :, 1] * float(trans_std)
    ch_per_cls = OD // ncls

    # bin start coords, per class: [R, ncls, P(ph), P(pw)]
    hstart = (phs[None, None, :, None] * bin_h[:, None, None, None]
              + y1[:, None, None, None] + ty * rh[:, None, None, None])
    wstart = (phs[None, None, None, :] * bin_w[:, None, None, None]
              + x1[:, None, None, None] + tx * rw[:, None, None, None])
    # sample grid: [R, ncls, P, P, S, S]
    ss = jnp.arange(S, dtype=jnp.float32)
    hh = hstart[..., None, None] + ss[:, None] * sub_h[:, None, None, None, None, None]
    ww = wstart[..., None, None] + ss[None, :] * sub_w[:, None, None, None, None, None]
    valid = (ww >= -0.5) & (ww <= W - 0.5) & (hh >= -0.5) & (hh <= H - 0.5)
    hc = jnp.clip(hh, 0.0, H - 1.0)
    wc = jnp.clip(ww, 0.0, W - 1.0)

    # static bin -> score-map channel map: [OD, P, P]
    gh = jnp.clip(jnp.floor(jnp.arange(P) * G / P), 0, G - 1).astype(jnp.int32)
    ch = ((jnp.arange(OD)[:, None, None] * G + gh[None, :, None]) * G
          + gh[None, None, :])
    cls_of = jnp.arange(OD) // ch_per_cls

    # expand coords to per-output-channel via its class: [R, OD, P, P, S, S]
    hh_c = hc[:, cls_of]
    ww_c = wc[:, cls_of]
    val_c = valid[:, cls_of]

    y0 = jnp.floor(hh_c)
    x0 = jnp.floor(ww_c)
    wy = hh_c - y0
    wx = ww_c - x0
    flat = data.astype(jnp.float32).reshape(B, C * H * W)[batch_idx]  # [R, CHW]
    chb = ch[None, :, :, :, None, None]

    def corner(yi, xi):
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        idx = (chb * H + yc) * W + xc  # [R, OD, P, P, S, S]
        return jnp.take_along_axis(flat, idx.reshape(R, -1), axis=1).reshape(idx.shape)

    v = (corner(y0, x0) * (1 - wx) * (1 - wy)
         + corner(y0, x0 + 1) * wx * (1 - wy)
         + corner(y0 + 1, x0) * (1 - wx) * wy
         + corner(y0 + 1, x0 + 1) * wx * wy)
    v = v * val_c.astype(jnp.float32)
    count = jnp.sum(val_c, axis=(-1, -2)).astype(jnp.float32)
    out = jnp.sum(v, axis=(-1, -2)) / jnp.maximum(count, 1.0)
    return out.astype(data.dtype)


alias("DeformableConvolution", "_contrib_DeformableConvolution")
alias("DeformablePSROIPooling", "_contrib_DeformablePSROIPooling")


# ---------------------------------------------------------------------------
# Correlation (FlowNet)
# ---------------------------------------------------------------------------


@register("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Patch correlation between two feature maps ([U:src/operator/
    correlation.cc]).  The displacement loop is a static python loop over
    shifted slices — XLA sees D² independent fused multiply-reduces."""
    K = int(kernel_size)
    md = int(max_displacement)
    s1, s2, pad = int(stride1), int(stride2), int(pad_size)
    B, C, H, W = data1.shape
    d = md // s2
    D = 2 * d + 1
    p1 = jnp.pad(data1.astype(jnp.float32), [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    p2 = jnp.pad(data2.astype(jnp.float32), [(0, 0), (0, 0), (pad, pad), (pad, pad)])
    Hp, Wp = H + 2 * pad, W + 2 * pad
    bor = md + (K - 1) // 2
    out_h = -(-(Hp - 2 * bor) // s1)
    out_w = -(-(Wp - 2 * bor) // s1)
    norm = C * K * K

    def window(x, dy, dx):
        ys = bor + dy
        xs = bor + dx
        v = lax.dynamic_slice(
            x, (0, 0, ys, xs),
            (B, C, (out_h - 1) * s1 + K, (out_w - 1) * s1 + K))
        if K == 1:
            return v[:, :, ::s1, ::s1]
        patches = lax.conv_general_dilated_patches(
            v, (K, K), (s1, s1), "VALID")
        return patches  # [B, C*K*K, out_h, out_w]

    base = window(p1, 0, 0)
    outs = []
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            shifted = window(p2, dy * s2, dx * s2)
            if is_multiply:
                outs.append(jnp.sum(base * shifted, axis=1) / norm)
            else:
                outs.append(jnp.sum(jnp.abs(base - shifted), axis=1) / norm)
    out = jnp.stack(outs, axis=1)  # [B, D*D, out_h, out_w]
    return out.astype(data1.dtype)


# ---------------------------------------------------------------------------
# im2col / col2im
# ---------------------------------------------------------------------------


def _im2col_raw(data, kernel, stride, dilate, pad):
    kh, kw = kernel
    patches = lax.conv_general_dilated_patches(
        data, (kh, kw), tuple(stride),
        [(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=tuple(dilate))
    B = data.shape[0]
    return patches.reshape(B, patches.shape[1], -1)


@register("im2col")
def im2col(data, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Unfold conv patches to columns ([U:src/operator/nn/im2col.h]):
    output [B, C·kh·kw, out_h·out_w]."""
    return _im2col_raw(data, tuple(kernel), tuple(stride), tuple(dilate), tuple(pad))


@register("col2im")
def col2im(data, output_size, kernel, stride=(1, 1), dilate=(1, 1), pad=(0, 0)):
    """Fold columns back (scatter-add inverse of im2col) — implemented as
    the VJP of :func:`im2col`, which IS the fold operation."""
    H, W = int(output_size[0]), int(output_size[1])
    kh, kw = kernel
    B = data.shape[0]
    C = data.shape[1] // (kh * kw)
    zero = jnp.zeros((B, C, H, W), dtype=data.dtype)
    _, vjp = jax.vjp(
        lambda x: _im2col_raw(x, tuple(kernel), tuple(stride), tuple(dilate), tuple(pad)),
        zero)
    return vjp(data)[0]


# ---------------------------------------------------------------------------
# legacy loss heads
# ---------------------------------------------------------------------------


@register("SVMOutput")
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """Hinge-loss head ([U:src/operator/svm_output.cc]): forward=identity,
    backward = (L1 or squared) hinge gradient on the true-class margin."""
    margin = float(margin)
    reg = float(regularization_coefficient)
    lin = bool(use_linear)

    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        lab = l.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, d.shape[-1], dtype=d.dtype)
        score_y = jnp.take_along_axis(d, lab[:, None], axis=-1)
        viol = margin - (2 * onehot - 1) * d  # margin violation per class
        if lin:
            grad = jnp.where(viol > 0, -(2 * onehot - 1), 0.0) * reg
        else:
            grad = jnp.where(viol > 0, -2.0 * viol * (2 * onehot - 1), 0.0) * reg
        del score_y
        return (grad.astype(d.dtype), None)

    f.defvjp(fwd, bwd)
    return f(data, label)


@register("MAERegressionOutput")
def mae_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return d

    def fwd(d, l):
        return d, (d, l)

    def bwd(res, g):
        d, l = res
        return (jnp.sign(d - l) * grad_scale, None)

    f.defvjp(fwd, bwd)
    return f(data, label.reshape(data.shape))


@register("LogisticRegressionOutput")
def logistic_regression_output(data, label, grad_scale=1.0):
    @jax.custom_vjp
    def f(d, l):
        return jax.nn.sigmoid(d)

    def fwd(d, l):
        return jax.nn.sigmoid(d), (d, l)

    def bwd(res, g):
        d, l = res
        return ((jax.nn.sigmoid(d) - l) * grad_scale, None)

    f.defvjp(fwd, bwd)
    return f(data, label.reshape(data.shape))


# ---------------------------------------------------------------------------
# CTC loss
# ---------------------------------------------------------------------------


@register("CTCLoss")
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first"):
    """Connectionist Temporal Classification ([U:src/operator/contrib/
    ctc_loss.cc]; the reference wraps warp-ctc).  data: [T, B, C] raw
    activations (softmax applied internally, as the reference does);
    label: [B, L] class ids — with ``blank_label='first'`` ids are
    1..C-1 and 0 pads, with 'last' ids are 0..C-2, C-1 is blank and -1
    pads.  Log-space forward algorithm as one ``lax.scan`` over T; the
    backward pass is jax.grad of the scan (no hand-written kernel)."""
    T, B, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)

    first = str(blank_label) == "first"
    blank = 0 if first else C - 1
    lab = label.astype(jnp.int32)
    if label_lengths is not None and use_label_lengths:
        lab_len = label_lengths.astype(jnp.int32)
    else:
        pad = 0 if first else -1
        lab_len = jnp.sum((lab != pad).astype(jnp.int32), axis=1)
    if data_lengths is not None and use_data_lengths:
        dat_len = data_lengths.astype(jnp.int32)
    else:
        dat_len = jnp.full((B,), T, dtype=jnp.int32)

    # extended sequence: [B, 2L+1] = blank, l1, blank, l2, ... blank
    S = 2 * L + 1
    pos = jnp.arange(S)
    lab_at = jnp.take_along_axis(
        lab, jnp.minimum(pos[None, :] // 2, L - 1) * jnp.ones((B, 1), jnp.int32), axis=1)
    ext = jnp.where(pos[None, :] % 2 == 0, blank, lab_at)  # [B, S]
    # valid extended length per sample: 2*lab_len+1
    ext_valid = pos[None, :] < (2 * lab_len[:, None] + 1)

    NEG = -1e30
    # can we skip from s-2 to s? only if ext[s] != blank and ext[s] != ext[s-2]
    ext_m2 = jnp.concatenate([jnp.full((B, 2), -1, jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != blank) & (ext != ext_m2)

    alpha0 = jnp.full((B, S), NEG)
    # t=0: alpha[0] = logp(blank), alpha[1] = logp(l1)
    a00 = jnp.take_along_axis(logp[0], ext[:, :1], axis=1)[:, 0]
    a01 = jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 0].set(a00)
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, a01, NEG))
    alpha0 = jnp.where(ext_valid, alpha0, NEG)

    def step(alpha, t):
        prev1 = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], axis=1)
        prev2 = jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], axis=1)
        prev2 = jnp.where(can_skip, prev2, NEG)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
        emit = jnp.take_along_axis(logp[t], ext, axis=1)
        new = merged + emit
        new = jnp.where(ext_valid, new, NEG)
        # freeze once past this sample's data length
        new = jnp.where((t < dat_len)[:, None], new, alpha)
        return new, None

    alpha, _ = lax.scan(step, alpha0, jnp.arange(1, T))
    # loss = -log(alpha[2*lab_len] + alpha[2*lab_len - 1])
    last_b = jnp.take_along_axis(alpha, (2 * lab_len)[:, None], axis=1)[:, 0]
    idx_nb = jnp.maximum(2 * lab_len - 1, 0)[:, None]
    last_nb = jnp.take_along_axis(alpha, idx_nb, axis=1)[:, 0]
    last_nb = jnp.where(lab_len > 0, last_nb, NEG)
    loss = -jnp.logaddexp(last_b, last_nb)
    return loss


alias("ctc_loss", "CTCLoss")
alias("_contrib_CTCLoss", "CTCLoss")
alias("_contrib_ctc_loss", "CTCLoss")


@register("Crop")
def crop_op(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
            num_args=None):
    """Parity: [U:src/operator/crop.cc] — NCHW spatial crop (the FCN-era
    op).  One input: crop to ``h_w`` at ``offset`` (or centered).  Two
    inputs: crop the first to the second's H×W."""
    data = inputs[0]
    if len(inputs) == 2:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = int(h_w[0]), int(h_w[1])
        if th == 0 or tw == 0:
            raise ValueError("Crop: give h_w or a second (crop_like) input")
    h, w = data.shape[2], data.shape[3]
    if th > h or tw > w:
        raise ValueError(f"Crop: target {th}x{tw} exceeds input {h}x{w}")
    if center_crop:
        oy, ox = (h - th) // 2, (w - tw) // 2
    else:
        oy, ox = int(offset[0]), int(offset[1])
    if oy < 0 or ox < 0:
        raise ValueError(f"Crop: negative offset ({oy},{ox})")
    if oy + th > h or ox + tw > w:
        raise ValueError(f"Crop: offset {oy},{ox} + {th}x{tw} exceeds {h}x{w}")
    return data[:, :, oy:oy + th, ox:ox + tw]
# NOTE: lowercase `crop` is the reference's legacy alias for `slice`
# ([U:src/operator/tensor/matrix_op.cc] add_alias("crop")), registered in
# tensor.py — NOT an alias of this op.
