"""Detection operators — anchors, target assignment, decoding, NMS.

Parity targets: ``MultiBoxPrior``/``MultiBoxTarget``/``MultiBoxDetection``
([U:src/operator/contrib/multibox_prior.cc], [U:.../multibox_target.cc],
[U:.../multibox_detection.cc]) and ``box_nms``/``box_iou``
([U:src/operator/contrib/bounding_box.cc]) — the op set the SSD example
family ([U:example/ssd/]) is built on, BASELINE.md config 5.

TPU-first design notes (vs the reference's CPU/GPU kernels):

* Everything is **fixed-shape and mask-based** — no dynamic box counts
  anywhere.  "Suppressed"/"invalid" results are encoded as ``-1`` rows in
  a constant-shape output, exactly the reference's output convention, so
  the whole pipeline jits.
* Matching and NMS are dense matrix computations (IoU matrices on the
  VPU/MXU) + ``lax.fori_loop`` sequential scans, instead of the
  reference's per-box scalar loops; ``vmap`` supplies the batch dim.
* NMS is O(K²) in the post-top-k candidate count: callers bound K via
  ``topk``/``nms_topk`` (the reference sorts all N; on TPU a static top-k
  prefilter keeps the IoU matrix MXU-sized).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register, alias

__all__ = ["box_iou", "multibox_prior", "multibox_target", "multibox_detection",
           "box_nms"]


def _corner_iou(lhs, rhs, eps=1e-12):
    """IoU of corner-format boxes: lhs [N, 4] x rhs [M, 4] → [N, M]."""
    lx1, ly1, lx2, ly2 = [lhs[..., i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., i] for i in range(4)]
    ix1 = jnp.maximum(lx1[..., :, None], rx1[..., None, :])
    iy1 = jnp.maximum(ly1[..., :, None], ry1[..., None, :])
    ix2 = jnp.minimum(lx2[..., :, None], rx2[..., None, :])
    iy2 = jnp.minimum(ly2[..., :, None], ry2[..., None, :])
    iw = jnp.clip(ix2 - ix1, 0.0)
    ih = jnp.clip(iy2 - iy1, 0.0)
    inter = iw * ih
    larea = jnp.clip(lx2 - lx1, 0.0) * jnp.clip(ly2 - ly1, 0.0)
    rarea = jnp.clip(rx2 - rx1, 0.0) * jnp.clip(ry2 - ry1, 0.0)
    union = larea[..., :, None] + rarea[..., None, :] - inter
    return inter / jnp.maximum(union, eps)


def _center_to_corner(b):
    cx, cy, w, h = [b[..., i] for i in range(4)]
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)


@register("box_iou", differentiable=False)
def box_iou(lhs, rhs, format="corner"):
    """Pairwise IoU.  lhs [..., N, 4], rhs [..., M, 4] → [..., N, M]."""
    if format == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    return _corner_iou(lhs, rhs)


@register("contrib_MultiBoxPrior", differentiable=False)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Anchor generation for a [B, C, H, W] feature map → [1, H·W·A, 4]
    corner boxes normalized to [0, 1], A = len(sizes) + len(ratios) - 1
    (all sizes at ratios[0], plus sizes[0] at each remaining ratio —
    the reference's combination rule)."""
    if isinstance(sizes, (int, float)):
        sizes = (sizes,)
    if isinstance(ratios, (int, float)):
        ratios = (ratios,)
    h, w = data.shape[-2], data.shape[-1]
    step_y = steps[0] if steps and steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps and steps[1] > 0 else 1.0 / w
    cy = (jnp.arange(h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(w, dtype=jnp.float32) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")  # [H, W]

    wh = []
    for s in sizes:
        r = math.sqrt(ratios[0])
        wh.append((s * r, s / r))
    for ratio in ratios[1:]:
        r = math.sqrt(ratio)
        wh.append((sizes[0] * r, sizes[0] / r))
    ws = jnp.asarray([p[0] for p in wh], jnp.float32)  # [A]
    hs = jnp.asarray([p[1] for p in wh], jnp.float32)

    cx = cx[..., None]  # [H, W, 1]
    cy = cy[..., None]
    boxes = jnp.stack([
        cx - ws / 2, cy - hs / 2, cx + ws / 2, cy + hs / 2], axis=-1)  # [H,W,A,4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.reshape(1, h * w * len(wh), 4)


def _encode_boxes(anchors, gt, variances):
    """SSD box encoding: corner anchors + corner gt → regression targets."""
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = jnp.maximum(anchors[:, 2] - anchors[:, 0], 1e-12)
    ah = jnp.maximum(anchors[:, 3] - anchors[:, 1], 1e-12)
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-12)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-12)
    return jnp.stack([
        (gcx - acx) / aw / variances[0],
        (gcy - acy) / ah / variances[1],
        jnp.log(gw / aw) / variances[2],
        jnp.log(gh / ah) / variances[3],
    ], axis=-1)  # [N, 4]


def _decode_boxes(anchors, pred, variances, clip):
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    cx = pred[:, 0] * variances[0] * aw + acx
    cy = pred[:, 1] * variances[1] * ah + acy
    w = jnp.exp(pred[:, 2] * variances[2]) * aw
    h = jnp.exp(pred[:, 3] * variances[3]) * ah
    out = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _match_anchors(anchors, gt_boxes, gt_valid, overlap_threshold):
    """Reference matching rule, dense form: every gt claims its best anchor
    (bipartite stage), then remaining anchors match their best gt if IoU
    exceeds the threshold.  Returns match ∈ {-1, gt index} per anchor."""
    iou = _corner_iou(anchors, gt_boxes)            # [N, M]
    iou = jnp.where(gt_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)               # [N]
    best_iou = jnp.max(iou, axis=1)
    match = jnp.where(best_iou >= overlap_threshold, best_gt, -1)
    # bipartite stage: gt j's best anchor is forced to match j (overrides
    # the threshold rule, exactly once per valid gt)
    best_anchor = jnp.argmax(iou, axis=0)           # [M]
    gt_has_overlap = jnp.max(iou, axis=0) > 0
    force = gt_valid & gt_has_overlap
    m = gt_boxes.shape[0]
    # scatter each valid gt's index onto its best anchor (later gts win on
    # collision, matching the reference's sequential bipartite pass)
    forced = jnp.full_like(match, -1)
    forced = forced.at[best_anchor].set(
        jnp.where(force, jnp.arange(m), forced[best_anchor]))
    return jnp.where(forced >= 0, forced, match)


@register("contrib_MultiBoxTarget", differentiable=False)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Anchor → ground-truth assignment for SSD training.

    anchor: [1, N, 4] corner boxes; label: [B, M, 5] rows of
    (class_id, xmin, ymin, xmax, ymax), padded with -1; cls_pred:
    [B, num_classes+1, N] (used for hard-negative mining when
    ``negative_mining_ratio > 0``).

    Returns (box_target [B, N·4], box_mask [B, N·4], cls_target [B, N])
    where cls_target is gt class + 1 for matched anchors, 0 for
    background, ``ignore_label`` for mined-out negatives.
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]

    def per_sample(lab, cpred):
        gt_cls = lab[:, 0]
        gt_valid = gt_cls >= 0
        gt_boxes = lab[:, 1:5]
        match = _match_anchors(anchors, gt_boxes, gt_valid, overlap_threshold)
        matched = match >= 0
        safe = jnp.clip(match, 0)
        targets = _encode_boxes(anchors, gt_boxes[safe], variances)
        box_target = jnp.where(matched[:, None], targets, 0.0).reshape(-1)
        box_mask = jnp.where(matched[:, None],
                             jnp.ones((n, 4), jnp.float32), 0.0).reshape(-1)
        cls_target = jnp.where(matched, gt_cls[safe].astype(jnp.int32) + 1, 0)
        if negative_mining_ratio > 0:
            # hard-negative mining: keep the top (ratio × #pos) background
            # anchors by background-loss proxy (1 - P(bg)); others → ignore
            bg_prob = cpred[0]
            neg_score = jnp.where(matched, -jnp.inf, 1.0 - bg_prob)
            neg_score = jnp.where(neg_score >= (1.0 - negative_mining_thresh),
                                  neg_score, -jnp.inf)
            num_pos = jnp.sum(matched)
            budget = jnp.maximum(
                (negative_mining_ratio * num_pos).astype(jnp.int32),
                jnp.int32(minimum_negative_samples))
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros(n, jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
            keep_neg = (rank < budget) & jnp.isfinite(neg_score)
            cls_target = jnp.where(matched | keep_neg, cls_target,
                                   jnp.int32(ignore_label))
        return box_target, box_mask, cls_target.astype(jnp.float32)

    return tuple(jax.vmap(per_sample)(label, cls_pred))


def _nms_keep(boxes, scores, cls_id, valid, thresh, force_suppress):
    """Sequential NMS over pre-sorted candidates (descending score).
    Returns keep mask [K]."""
    k = boxes.shape[0]
    iou = _corner_iou(boxes, boxes)
    same = jnp.ones((k, k), bool) if force_suppress else (
        cls_id[:, None] == cls_id[None, :])
    earlier = jnp.arange(k)[:, None] < jnp.arange(k)[None, :]  # j earlier than i
    sup = (iou > thresh) & same & earlier.T  # sup[i, j]: j can suppress i (j<i)

    def body(i, keep):
        suppressed = jnp.any(keep & sup[i])
        return keep.at[i].set(keep[i] & ~suppressed)

    return lax.fori_loop(0, k, body, valid)


@register("contrib_MultiBoxDetection", differentiable=False)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5, force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=400):
    """Decode + per-class NMS → [B, N, 6] rows (class_id, score, xmin,
    ymin, xmax, ymax); suppressed/invalid rows are all -1.

    cls_prob: [B, num_classes+1, N] softmax class probabilities (class
    ``background_id`` is background), loc_pred: [B, N·4], anchor:
    [1, N, 4].  ``nms_topk`` bounds the O(K²) NMS candidate count (static
    shape; the reference's -1 "all" maps to K = min(N, 400) by default).
    """
    anchors = anchor.reshape(-1, 4)
    n = anchors.shape[0]
    k = n if nms_topk is None or nms_topk <= 0 else min(int(nms_topk), n)

    def per_sample(cprob, lpred):
        fg = jnp.concatenate([cprob[:background_id], cprob[background_id + 1:]],
                             axis=0)                       # [C, N]
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.int32)  # [N]
        score = jnp.max(fg, axis=0)
        boxes = _decode_boxes(anchors, lpred.reshape(-1, 4), variances, clip)
        valid = score > threshold
        # static top-k prefilter by score
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))[:k]
        keep = _nms_keep(boxes[order], score[order], cls_id[order],
                         valid[order], nms_threshold, force_suppress)
        out = jnp.full((n, 6), -1.0, jnp.float32)
        rows = jnp.concatenate([
            cls_id[order][:, None].astype(jnp.float32),
            score[order][:, None], boxes[order]], axis=1)
        return out.at[jnp.arange(k)].set(jnp.where(keep[:, None], rows, -1.0))

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register("box_nms", differentiable=False)
def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, background_id=-1,
            force_suppress=False, in_format="corner", out_format="corner"):
    """Generic NMS over [..., N, K] records (parity: ``nd.contrib.box_nms``).
    Suppressed records are overwritten with -1; shape is unchanged."""
    shape = data.shape
    flat = data.reshape((-1,) + shape[-2:])
    n = shape[-2]
    k = n if topk is None or topk <= 0 else min(int(topk), n)

    def per_batch(recs):
        score = recs[:, score_index]
        boxes = recs[:, coord_start:coord_start + 4]
        if in_format == "center":
            boxes = _center_to_corner(boxes)
        if id_index >= 0:
            cls_id = recs[:, id_index].astype(jnp.int32)
            valid = (score > valid_thresh) & (cls_id != background_id)
        else:
            cls_id = jnp.zeros(n, jnp.int32)
            valid = score > valid_thresh
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))[:k]
        keep = _nms_keep(boxes[order], score[order], cls_id[order],
                         valid[order], overlap_thresh,
                         force_suppress or id_index < 0)
        out = jnp.full_like(recs, -1.0)
        return out.at[jnp.arange(k)].set(
            jnp.where(keep[:, None], recs[order], -1.0))

    return jax.vmap(per_batch)(flat).reshape(shape)


alias("contrib_box_nms", "box_nms")
alias("contrib_box_iou", "box_iou")
