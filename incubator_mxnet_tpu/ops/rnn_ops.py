"""Fused multi-layer RNN — the TPU equivalent of the reference's cuDNN
fused path ([U:src/operator/rnn.cc], [U:src/operator/nn/cudnn/
cudnn_rnn-inl.h]).

One ``lax.scan`` per layer/direction: weights stay resident, the time loop
is compiled (no per-step dispatch), and XLA pipelines the gate matmuls onto
the MXU.  Gate orders match rnn_cell.py (LSTM [i,f,g,o], GRU [r,z,n]).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .registry import register


def _cell_step(mode, hidden_size):
    if mode == "rnn_tanh":
        def step(carry, gates_x, w_h, b_h):
            (h,) = carry
            g = gates_x + h @ w_h.T + b_h
            nh = jnp.tanh(g)
            return (nh,), nh
        n_gates = 1
    elif mode == "rnn_relu":
        def step(carry, gates_x, w_h, b_h):
            (h,) = carry
            g = gates_x + h @ w_h.T + b_h
            nh = jnp.maximum(g, 0)
            return (nh,), nh
        n_gates = 1
    elif mode == "lstm":
        def step(carry, gates_x, w_h, b_h):
            h, c = carry
            g = gates_x + h @ w_h.T + b_h
            i, f, gg, o = jnp.split(g, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            gg = jnp.tanh(gg)
            o = jax.nn.sigmoid(o)
            nc = f * c + i * gg
            nh = o * jnp.tanh(nc)
            return (nh, nc), nh
        n_gates = 4
    elif mode == "gru":
        def step(carry, gates_x, w_h, b_h):
            (h,) = carry
            hh = h @ w_h.T + b_h
            xr, xz, xn = jnp.split(gates_x, 3, axis=-1)
            hr, hz, hn = jnp.split(hh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            nh = (1 - z) * n + z * h
            return (nh,), nh
        n_gates = 3
    else:
        raise ValueError(mode)
    return step, n_gates


def rnn_param_size(mode, input_size, state_size, num_layers=1, bidirectional=False):
    """Length of the packed flat parameter vector the ``RNN`` mega-op
    consumes — the reference's GetRnnParamSize ([U:src/operator/rnn-inl.h])."""
    _, n_gates = _cell_step(mode, state_size)
    dirs = 2 if bidirectional else 1
    H = int(state_size)
    total = 0
    for layer in range(int(num_layers)):
        in_dim = int(input_size) if layer == 0 else H * dirs
        total += dirs * (n_gates * H * (in_dim + H)  # i2h + h2h weights
                         + 2 * n_gates * H)          # i2h + h2h biases
    return total


def _unpack_rnn_params(parameters, mode, input_size, state_size, num_layers,
                       bidirectional):
    """Split the flat vector into per-layer/direction (w_i2h, w_h2h, b_i2h,
    b_h2h), cuDNN layout: ALL weights first (layer-major, direction-minor,
    i2h before h2h), then ALL biases in the same order
    ([U:src/operator/rnn-inl.h] GetRnnParamSize / rnn_cell.py FusedRNNCell
    unpack_weights)."""
    _, n_gates = _cell_step(mode, state_size)
    dirs = 2 if bidirectional else 1
    H = int(state_size)
    offset = 0

    def take(*shape):
        nonlocal offset
        n = 1
        for s in shape:
            n *= s
        out = parameters[offset:offset + n].reshape(shape)
        offset += n
        return out

    groups = []
    for layer in range(int(num_layers)):
        in_dim = int(input_size) if layer == 0 else H * dirs
        for _ in range(dirs):
            groups.append([take(n_gates * H, in_dim), take(n_gates * H, H)])
    for g in groups:
        g.append(take(n_gates * H))  # b_i2h
        g.append(take(n_gates * H))  # b_h2h
    if offset != parameters.shape[0]:
        raise ValueError(
            f"RNN parameters length {parameters.shape[0]} != expected {offset} "
            f"for mode={mode} input_size={input_size} state_size={state_size} "
            f"num_layers={num_layers} bidirectional={bidirectional}")
    return [w for g in groups for w in g]


@register("RNN")
def rnn_mega(data, parameters, state=None, state_cell=None, *, mode="lstm",
             state_size=0, num_layers=1, bidirectional=False, p=0.0,
             state_outputs=False, training=False, key=None,
             projection_size=None, lstm_state_clip_min=None,
             lstm_state_clip_max=None, lstm_state_clip_nan=False,
             use_sequence_length=False, sequence_length=None):
    """The reference's fused RNN mega-op under its real name/signature
    ([U:src/operator/rnn.cc]): ``data`` (T, N, C), ``parameters`` the packed
    flat vector (cuDNN layout — see ``_unpack_rnn_params``), ``state``
    (L*dirs, N, H), ``state_cell`` likewise for LSTM.  ``p`` is inter-layer
    dropout.  Returns ``out`` alone, or with ``state_outputs=True``:
    ``(out, h_n)`` / ``(out, h_n, c_n)`` for LSTM.  A thin unpacking shim
    over the one-``lax.scan``-per-layer ``RNNFused`` kernel."""
    if projection_size is not None:
        raise NotImplementedError(
            "RNN projection_size (LSTMP) is not supported; use an explicit "
            "Dense projection after the layer")
    if lstm_state_clip_min is not None or lstm_state_clip_max is not None \
            or lstm_state_clip_nan:
        raise NotImplementedError("RNN lstm_state_clip_* is not supported")
    if use_sequence_length:
        # flag OFF with a sequence_length tensor supplied is a no-op in the
        # reference (the input is ignored) — only the flag itself rejects
        raise NotImplementedError(
            "RNN use_sequence_length is not supported; mask outputs with "
            "SequenceMask instead")
    H = int(state_size)
    flat = _unpack_rnn_params(parameters, mode, data.shape[2], H,
                              num_layers, bidirectional)
    dirs = 2 if bidirectional else 1
    if mode == "lstm" and (state is None) != (state_cell is None):
        raise ValueError(
            "LSTM mode takes BOTH state and state_cell, or neither "
            "(omitting both synthesizes zero initial states)")
    if state is None:  # ONNX-style default: zero initial states
        state = jnp.zeros((int(num_layers) * dirs, data.shape[1], H),
                          dtype=data.dtype)
        if mode == "lstm":
            state_cell = jnp.zeros_like(state)
    c0 = state_cell if mode == "lstm" else state  # dummy for non-LSTM
    res = rnn_fused(data, state, c0, *flat, mode=mode,
                    num_layers=int(num_layers), hidden_size=H,
                    bidirectional=bool(bidirectional), dropout=float(p),
                    training=training, key=key)
    if state_outputs:
        return res  # (out, h_n) or (out, h_n, c_n)
    return res[0]


@register("RNNFused")
def rnn_fused(
    data,
    h0,
    c0,
    *weights,
    mode="lstm",
    num_layers=1,
    hidden_size=0,
    bidirectional=False,
    dropout=0.0,
    training=False,
    key=None,
):
    """data: (T, N, C); h0/c0: (num_layers*dirs, N, H); weights: per layer,
    per direction: i2h_w, h2h_w, i2h_b, h2h_b.  Returns (out, h_n[, c_n])."""
    step, n_gates = _cell_step(mode, hidden_size)
    dirs = 2 if bidirectional else 1
    x = data
    h_finals = []
    c_finals = []
    widx = 0
    for layer in range(num_layers):
        outs_dir = []
        for d in range(dirs):
            w_i, w_h, b_i, b_h = weights[widx : widx + 4]
            widx += 4
            sidx = layer * dirs + d
            h_init = h0[sidx]
            carry = (h_init, c0[sidx]) if mode == "lstm" else (h_init,)
            seq = x if d == 0 else jnp.flip(x, axis=0)
            # precompute input projection for the whole sequence: one big MXU matmul
            gates_x = jnp.einsum("tnc,gc->tng", seq, w_i) + b_i

            def scan_fn(c, gx, _w_h=w_h, _b_h=b_h):
                return step(c, gx, _w_h, _b_h)

            final_carry, out = lax.scan(scan_fn, carry, gates_x)
            if d == 1:
                out = jnp.flip(out, axis=0)
            outs_dir.append(out)
            h_finals.append(final_carry[0])
            if mode == "lstm":
                c_finals.append(final_carry[1])
        x = outs_dir[0] if dirs == 1 else jnp.concatenate(outs_dir, axis=-1)
        if dropout > 0 and training and layer < num_layers - 1 and key is not None:
            key, sub = jax.random.split(key)
            mask = jax.random.bernoulli(sub, 1 - dropout, x.shape).astype(x.dtype)
            x = x * mask / (1 - dropout)
    h_n = jnp.stack(h_finals)
    if mode == "lstm":
        return x, h_n, jnp.stack(c_finals)
    return x, h_n
