"""Operator library (TPU-native equivalent of [U:src/operator/]).

The reference registers ~1000 C++/CUDA kernels behind the NNVM registry; here
every operator is a *pure function on jax.Arrays* registered in
:mod:`.registry`.  XLA plays the role of mshadow/cuDNN/oneDNN: lowering,
fusion, tiling onto the MXU.  Custom Pallas kernels slot in as just another
registered function.
"""
from . import registry
from .registry import register, get_op, list_ops, Op
from . import tensor  # noqa: F401  (registers tensor ops)
from . import nn  # noqa: F401  (registers NN ops)
from . import rnn_ops  # noqa: F401  (registers fused RNN)
from . import attention  # noqa: F401  (registers fused/flash attention)
from . import moe  # noqa: F401  (registers the MoE dispatch/combine kernel)
from . import detection  # noqa: F401  (registers MultiBox*/box_nms/box_iou)
from . import quantization  # noqa: F401  (registers quantize_v2/dequantize/int8 ops)
from . import linalg  # noqa: F401  (registers the la_op family)
from . import random_ops  # noqa: F401  (registers _random_*/_sample_* samplers)
from . import optimizer_ops  # noqa: F401  (registers fused update kernels as public ops)
from . import spatial  # noqa: F401  (registers ROI/grid/bilinear/spatial CV ops)

__all__ = ["register", "get_op", "list_ops", "Op", "registry", "tensor", "nn"]
