"""Fused scaled-dot-product attention — the TPU answer to cuDNN fused
attention (the reference has no fused attention at all; its transformer
support lived out-of-repo in GluonNLP.  SURVEY.md §5 marks this as the one
area where this framework intentionally EXCEEDS the reference).

Three tiers, chosen by :func:`flash_attention`:

1. **Pallas flash kernel** (TPU, and CPU tests via ``interpret=True``):
   blockwise online-softmax forward — queries tiled over the grid, K/V
   streamed through VMEM in ``block_k`` chunks, so the S×S score matrix is
   never materialized in HBM.  Accumulation in fp32 on the MXU
   (``preferred_element_type``), inputs may be bf16.
2. **XLA reference path** (non-TPU backends / ``MXNET_TPU_FLASH=off``):
   same math as one fused jnp expression; XLA fuses adequately for short
   sequences.
3. **Ring attention** (``parallel/ring.py``) for sequence-parallel long
   context — built on the same online-softmax update.

Gradients: ``jax.custom_vjp`` — backward recomputes attention probabilities
from the saved (q, k, v), so no S×S residual is stored *between* fwd and
bwd.  The backward itself currently materializes the S×S score matrix
(fine through BERT/WMT-scale sequence lengths; a blockwise Pallas backward
is the planned long-context upgrade — until then use ring attention /
sequence parallelism for very long sequences, which never forms S×S).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "attention_reference"]


def _use_pallas(x=None):
    mode = os.environ.get("MXNET_TPU_FLASH", "auto")
    if mode == "off":
        return False, False
    if mode == "interpret":
        return True, True
    # Resolve the platform this call will actually execute on: a concrete
    # input's device wins (eager op on a CPU-placed array while the default
    # backend is tpu, e.g. model init under ``jax.default_device(cpu)``);
    # then an active jax_default_device override; then the default backend.
    platform = None
    if x is not None and not isinstance(x, jax.core.Tracer):
        try:
            platform = next(iter(x.devices())).platform
        except Exception:
            platform = None
    if platform is None:
        dd = getattr(jax.config, "jax_default_device", None)
        platform = getattr(dd, "platform", None) or jax.default_backend()
    on_tpu = platform == "tpu"
    if mode == "on":
        return True, not on_tpu
    return on_tpu, False  # auto


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def online_softmax_update(o, m, l, s, v, matmul):
    """One blockwise online-softmax accumulation step (shared by the Pallas
    kernel below and parallel/ring.py).  ``m``/``l`` carry a trailing
    keepdim; ``s`` may contain -inf for masked entries; fully-masked rows
    keep zero mass (caller fixes l==0 before the final divide)."""
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + p.sum(axis=-1, keepdims=True)
    o_new = o * corr + matmul(p, v)
    return o_new, m_new, l_new


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k, causal, scale):
    """One (batch·head, q-block) grid cell: stream K/V blocks, online
    softmax in fp32.  Shapes: q_ref [1, Bq, D], k/v_ref [1, Sk, D].

    Operands stay in their input dtype (bf16 rides the MXU at full rate)
    with fp32 accumulation via preferred_element_type; matmul precision is
    pinned per-dtype because the package-global 'highest' default would
    request an fp32 contraction on bf16 operands, which Mosaic rejects."""
    i = _pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    seq_k = k_ref.shape[1]
    nk = seq_k // block_k
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)  # bf16 AND fp16 operands

    q = q_ref[0]  # [Bq, D], native dtype
    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, _pl.ds(j * block_k, block_k), :]
        v = v_ref[0, _pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec,
        ) * scale  # [Bq, Bk], fp32 accumulate then scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        acc_new, m_new, l_new = online_softmax_update(
            acc, m, l, s, v,
            lambda p, v: jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec,
            ),
        )
        return m_new, l_new, acc_new

    if causal:
        # Skip K/V blocks entirely in the masked future: q-block i only
        # attends to k positions < (i+1)*block_q (halves FLOPs/bandwidth
        # for decoder self-attention vs. streaming all nk blocks).
        nk_bound = jnp.minimum(nk, ((i + 1) * block_q + block_k - 1) // block_k)
    else:
        nk_bound = nk
    m, l, acc = lax.fori_loop(0, nk_bound, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


try:  # pallas import is deferred-safe: CPU-only jax builds still have it
    from jax.experimental import pallas as _pl
    from jax.experimental.pallas import tpu as _pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _pl = None
    _pltpu = None
    _HAVE_PALLAS = False


def _flash_fwd_pallas(q, k, v, causal, scale, interpret, block_q=128, block_k=128):
    """q/k/v: [BH, S, D] (batch·heads flattened)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"sequence lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")
    kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal, scale=scale)
    grid = (bh, sq // block_q)
    return _pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid,
        in_specs=[
            _pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            _pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            _pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=_pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Reference path (XLA-fused) + custom VJP
# ---------------------------------------------------------------------------


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain jnp attention: q/k/v [B, H, S, D] (or [BH, S, D])."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("...qd,...kd->...qk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v)


def _pallas_blocks(sq, sk, block_q=128, block_k=128):
    """Largest MXU-friendly blocks that evenly divide the sequence lengths,
    or None if none exists (→ fall back to the XLA path rather than crash
    on unpadded/bucketed lengths)."""
    bq = next((b for b in (block_q, 64, 32, 16, 8) if sq % b == 0), None)
    bk = next((b for b in (block_k, 64, 32, 16, 8) if sk % b == 0), None)
    if bq is None or bk is None:
        return None
    return min(bq, sq), min(bk, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    use, interpret = _use_pallas(q)
    if q.dtype == jnp.float16 and not interpret:
        use = False  # Mosaic has no f16; XLA reference path handles it
    if use and _HAVE_PALLAS:
        b, h, s, d = q.shape
        blocks = _pallas_blocks(s, k.shape[2])
        if blocks is not None:
            out = _flash_fwd_pallas(
                q.reshape(b * h, s, d), k.reshape(b * h, -1, d), v.reshape(b * h, -1, d),
                causal, scale, interpret, block_q=blocks[0], block_k=blocks[1],
            )
            return out.reshape(b, h, s, d)
    return attention_reference(q, k, v, causal, scale)


def _flash_fwd(q, k, v, causal, scale):
    return _flash(q, k, v, causal, scale), (q, k, v)


def _flash_bwd(causal, scale, res, do):
    """Rematerialized backward (standard flash-attention gradient algebra)."""
    q, k, v = res
    qf = q.astype(jnp.float32) * scale
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    s = jnp.einsum("...qd,...kd->...qk", qf, kf)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    dof = do.astype(jnp.float32)
    o = jnp.einsum("...qk,...kd->...qd", p, vf)
    dv = jnp.einsum("...qk,...qd->...kd", p, dof)
    dp = jnp.einsum("...qd,...kd->...qk", dof, vf)
    delta = jnp.sum(dof * o, axis=-1, keepdims=True)
    ds = p * (dp - delta)
    dq = jnp.einsum("...qk,...kd->...qd", ds, kf) * scale
    dk = jnp.einsum("...qk,...qd->...kd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """Fused attention on [B, H, S, D] arrays; differentiable; bf16-safe."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, float(scale))


from .registry import register  # noqa: E402


@register("fused_attention")
def fused_attention(q, k, v, num_heads=1, causal=False, scale=None):
    """[B, S, D] convenience form: split heads → flash attention → merge.
    Registered so it is reachable as ``nd.fused_attention`` /
    ``nd.contrib.fused_attention`` (the role cuDNN fused MHA plays for the
    reference's GPU builds)."""
    b, s, d = q.shape
    h = num_heads
    if d % h:
        raise ValueError(f"feature dim {d} not divisible by num_heads {h}")

    def split(x):
        return x.reshape(b, x.shape[1], h, d // h).transpose(0, 2, 1, 3)

    out = flash_attention(split(q), split(k), split(v), causal=causal, scale=scale)
    return out.transpose(0, 2, 1, 3).reshape(b, s, d)
