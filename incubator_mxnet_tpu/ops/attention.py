"""Fused scaled-dot-product attention — the TPU answer to cuDNN fused
attention (the reference has no fused attention at all; its transformer
support lived out-of-repo in GluonNLP.  SURVEY.md §5 marks this as the one
area where this framework intentionally EXCEEDS the reference).

Three tiers, chosen by :func:`flash_attention`:

1. **Pallas flash kernel** (TPU, and CPU tests via ``interpret=True``):
   blockwise online-softmax forward — queries tiled over the grid, K/V
   streamed through VMEM in ``block_k`` chunks, so the S×S score matrix is
   never materialized in HBM.  Accumulation in fp32 on the MXU
   (``preferred_element_type``), inputs may be bf16.
2. **XLA reference path** (non-TPU backends / ``MXNET_TPU_FLASH=off``):
   same math as one fused jnp expression; XLA fuses adequately for short
   sequences.
3. **Ring attention** (``parallel/ring.py``) for sequence-parallel long
   context — built on the same online-softmax update.

Gradients: ``jax.custom_vjp`` — backward recomputes attention probabilities
from the saved (q, k, v), so no S×S residual is stored *between* fwd and
bwd.  The backward is seq-length gated (thresholds below): short sequences
take a rematerialized XLA backward (one fused S×S program — faster when
S×S fits comfortably), long sequences take the two-pass blockwise Pallas
backward (`_flash_bwd_pallas`) whose memory stays linear in S.

Layout: :func:`fused_qkv_attention` / :func:`fused_kv_attention` keep the
``[B, S, H, Dh]`` layout end-to-end on the short-sequence XLA path so the
head split/merge is a free reshape of the QKV matmul output and XLA folds
the remaining dimension shuffles into the attention dot_generals — no
materialized head transposes (docs/PERF_NOTES.md round-3 win).  The Pallas
kernels want ``[B·H, S, Dh]`` physically, so the long-context path pays
the two transposes (negligible against O(S²) attention work there).
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["flash_attention", "attention_reference"]

# TPU lane width: row statistics (lse) are replicated across a 128-lane
# trailing dim so their blocks satisfy Mosaic's (8, 128) tiling rule.
_LANE = 128


def _use_pallas(x=None):
    mode = os.environ.get("MXNET_TPU_FLASH", "auto")
    if mode == "off":
        return False, False
    if mode == "interpret":
        return True, True
    from ..util import resolve_platform

    on_tpu = resolve_platform(x) == "tpu"
    if mode == "on":
        return True, not on_tpu
    return on_tpu, False  # auto


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def online_softmax_update(o, m, l, s, v, matmul):
    """One blockwise online-softmax accumulation step (shared by the Pallas
    kernel below and parallel/ring.py).  ``m``/``l`` carry a trailing
    keepdim; ``s`` may contain -inf for masked entries; fully-masked rows
    keep zero mass (caller fixes l==0 before the final divide)."""
    m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(s - m_safe)
    p = jnp.where(jnp.isneginf(s), 0.0, p)
    corr = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = l * corr + p.sum(axis=-1, keepdims=True)
    o_new = o * corr + matmul(p, v)
    return o_new, m_new, l_new


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k, causal, scale):
    """One (batch·head, q-block) grid cell: stream K/V blocks, online
    softmax in fp32.  Shapes: q_ref [1, Bq, D], k/v_ref [1, Sk, D].

    Operands stay in their input dtype (bf16 rides the MXU at full rate)
    with fp32 accumulation via preferred_element_type; matmul precision is
    pinned per-dtype because the package-global 'highest' default would
    request an fp32 contraction on bf16 operands, which Mosaic rejects."""
    i = _pl.program_id(1)
    block_q = q_ref.shape[1]
    d = q_ref.shape[2]
    seq_k = k_ref.shape[1]
    nk = seq_k // block_k
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)  # bf16 AND fp16 operands

    q = q_ref[0]  # [Bq, D], native dtype
    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, _pl.ds(j * block_k, block_k), :]
        v = v_ref[0, _pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec,
        ) * scale  # [Bq, Bk], fp32 accumulate then scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        acc_new, m_new, l_new = online_softmax_update(
            acc, m, l, s, v,
            lambda p, v: jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec,
            ),
        )
        return m_new, l_new, acc_new

    if causal:
        # Skip K/V blocks entirely in the masked future: q-block i only
        # attends to k positions < (i+1)*block_q (halves FLOPs/bandwidth
        # for decoder self-attention vs. streaming all nk blocks).
        nk_bound = jnp.minimum(nk, ((i + 1) * block_q + block_k - 1) // block_k)
    else:
        nk_bound = nk
    m, l, acc = lax.fori_loop(0, nk_bound, body, (m0, l0, acc0))
    l = jnp.where(l == 0.0, 1.0, l)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # log-sum-exp per query row, saved for the blockwise backward:
        # p = exp(s - lse) reproduces softmax without re-running the
        # online rescaling.  Replicated across a 128-lane trailing dim to
        # satisfy TPU tiling (same layout as jax's reference TPU kernel).
        # Fully-masked rows get lse = 0 (m_safe), so exp(-inf - 0) = 0
        # keeps their gradient contributions zero.
        m_safe = jnp.where(jnp.isneginf(m), 0.0, m)
        lse_ref[0] = jnp.broadcast_to(m_safe + jnp.log(l), lse_ref.shape[1:])


try:  # pallas import is deferred-safe: CPU-only jax builds still have it
    from jax.experimental import pallas as _pl
    from jax.experimental.pallas import tpu as _pltpu

    _HAVE_PALLAS = True
except Exception:  # pragma: no cover
    _pl = None
    _pltpu = None
    _HAVE_PALLAS = False


def _flash_fwd_pallas(q, k, v, causal, scale, interpret, block_q=128, block_k=128,
                      with_lse=False):
    """q/k/v: [BH, S, D] (batch·heads flattened).  ``with_lse=True`` also
    returns the per-row log-sum-exp [BH, S] for the blockwise backward."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"sequence lengths ({sq},{sk}) must divide blocks ({block_q},{block_k})")
    grid = (bh, sq // block_q)
    if with_lse:
        kernel = functools.partial(_fwd_kernel, block_k=block_k, causal=causal, scale=scale)
        out_shape = (jax.ShapeDtypeStruct(q.shape, q.dtype),
                     jax.ShapeDtypeStruct((bh, sq, _LANE), jnp.float32))
        out_specs = (_pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
                     _pl.BlockSpec((1, block_q, _LANE), lambda b, i: (b, i, 0)))
    else:
        def kernel(q_ref, k_ref, v_ref, o_ref, **_):
            _fwd_kernel(q_ref, k_ref, v_ref, o_ref, None,
                        block_k=block_k, causal=causal, scale=scale)
        out_shape = jax.ShapeDtypeStruct(q.shape, q.dtype)
        out_specs = _pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))
    return _pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=[
            _pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            _pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
            _pl.BlockSpec((1, sk, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=out_specs,
        interpret=interpret,
    )(q, k, v)


# ---------------------------------------------------------------------------
# Pallas backward kernels (standard two-pass flash gradient: a dq pass
# gridded over q blocks and a dk/dv pass gridded over k blocks, both
# streaming the opposite operand — the S×S score matrix never exists in HBM)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref, dq_ref, *,
                   block_k, causal, scale):
    i = _pl.program_id(1)
    block_q, d = q_ref.shape[1], q_ref.shape[2]
    seq_k = k_ref.shape[1]
    nk = seq_k // block_k
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    q = q_ref[0]                       # [Bq, D] native dtype
    do = do_ref[0]                     # [Bq, D]
    lse = lse_ref[0][:, :1]            # [Bq, 1] fp32 (lane-replicated buffer)
    # delta = rowsum(do ⊙ o): cheap elementwise reduce done in-kernel so no
    # extra HBM buffer/pass is needed
    delta = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                    axis=-1, keepdims=True)

    def body(j, acc):
        k = k_ref[0, _pl.ds(j * block_k, block_k), :]
        v = v_ref[0, _pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec) * scale
        if causal:
            q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse)           # [Bq, Bk]; masked → exp(-inf) = 0
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec)
        ds = (p * (dp - delta)).astype(k.dtype)
        return acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec)

    if causal:
        nk_bound = jnp.minimum(nk, ((i + 1) * block_q + block_k - 1) // block_k)
    else:
        nk_bound = nk
    acc = lax.fori_loop(0, nk_bound, body,
                        jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, o_ref, lse_ref,
                    dk_ref, dv_ref, *, block_q, causal, scale):
    i = _pl.program_id(1)
    block_k, d = k_ref.shape[1], k_ref.shape[2]
    seq_q = q_ref.shape[1]
    nq = seq_q // block_q
    prec = (jax.lax.Precision.HIGHEST if q_ref.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)

    k = k_ref[0]                       # [Bk, D]
    v = v_ref[0]                       # [Bk, D]

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, _pl.ds(j * block_q, block_q), :]
        do = do_ref[0, _pl.ds(j * block_q, block_q), :]
        lse = lse_ref[0, _pl.ds(j * block_q, block_q), :1]
        delta = jnp.sum(
            do.astype(jnp.float32)
            * o_ref[0, _pl.ds(j * block_q, block_q), :].astype(jnp.float32),
            axis=-1, keepdims=True)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec) * scale    # [Bq, Bk]
        if causal:
            q_pos = j * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, -jnp.inf)
        p = jnp.exp(s - lse)
        dv = dv + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
            precision=prec)
        return dk, dv

    j0 = (i * block_k) // block_q if causal else 0
    dk, dv = lax.fori_loop(
        j0, nq, body,
        (jnp.zeros((block_k, d), jnp.float32), jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, do, o, lse, causal, scale, interpret,
                      block_q=128, block_k=128):
    """q/k/v/do/o: [BH, S, D]; lse: [BH, Sq, _LANE] fp32 → (dq, dk, dv)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    grid_q = (bh, sq // block_q)
    grid_k = (bh, sk // block_k)

    qkv_full = lambda s: _pl.BlockSpec((1, s, d), lambda b, i: (b, 0, 0))
    qblk = _pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0))

    dq = _pl.pallas_call(
        functools.partial(_bwd_dq_kernel, block_k=block_k, causal=causal, scale=scale),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        grid=grid_q,
        in_specs=[
            qblk,                                                     # q
            qkv_full(sk),                                             # k
            qkv_full(sk),                                             # v
            qblk,                                                     # do
            qblk,                                                     # o
            _pl.BlockSpec((1, block_q, _LANE), lambda b, i: (b, i, 0)),  # lse
        ],
        out_specs=qblk,
        interpret=interpret,
    )(q, k, v, do, o, lse)

    dk, dv = _pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, block_q=block_q, causal=causal, scale=scale),
        out_shape=(jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)),
        grid=grid_k,
        in_specs=[
            qkv_full(sq),                                             # q
            _pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),   # k
            _pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),   # v
            qkv_full(sq),                                             # do
            qkv_full(sq),                                             # o
            _pl.BlockSpec((1, sq, _LANE), lambda b, i: (b, 0, 0)),    # lse
        ],
        out_specs=(_pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
                   _pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0))),
        interpret=interpret,
    )(q, k, v, do, o, lse)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Reference path (XLA-fused) + custom VJP
# ---------------------------------------------------------------------------


def attention_reference(q, k, v, causal=False, scale=None):
    """Plain jnp attention: q/k/v [B, H, S, D] (or [BH, S, D]).

    Operands stay in their input dtype (bf16 rides the MXU at full rate)
    with fp32 accumulation via ``preferred_element_type``; only the softmax
    itself runs in fp32.  Upcasting the operands would halve MXU rate and
    double score-matrix HBM traffic for no accuracy the fp32 accumulate
    doesn't already provide."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    s = jnp.einsum("...qd,...kd->...qk", q, k,
                   preferred_element_type=jnp.float32, precision=prec) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32,
                      precision=prec).astype(v.dtype)


def _pallas_blocks(sq, sk, block_q=128, block_k=128):
    """Largest MXU-friendly blocks that evenly divide the sequence lengths,
    or None if none exists (→ fall back to the XLA path rather than crash
    on unpadded/bucketed lengths)."""
    bq = next((b for b in (block_q, 64, 32, 16, 8) if sq % b == 0), None)
    bk = next((b for b in (block_k, 64, 32, 16, 8) if sk % b == 0), None)
    if bq is None or bk is None:
        return None
    return min(bq, sq), min(bk, sk)


# Below this sequence length the XLA attention (batched matmuls + fused
# softmax over a small S×S) beats the Pallas kernel: at S=128 the grid
# degenerates to one K block per cell and Mosaic per-cell overhead
# dominates (profiled on v5e @ BERT-base: 3.9 ms pallas vs ~1 ms XLA fwd).
# The kernel's job is long context, where S×S cannot exist in HBM.
_PALLAS_FWD_MIN_SEQ = int(os.environ.get("MXNET_TPU_FLASH_FWD_MIN_SEQ", "1024"))


def _should_use_pallas(q, k, seq_axis=2):
    """One predicate for the primal AND the VJP forward — custom_vjp needs
    both to pick the same kernel path or eval/train numerics diverge.
    ``seq_axis`` lets bshd-layout callers gate without materializing a
    transpose.  Returns (use, interpret, blocks)."""
    sq, sk = q.shape[seq_axis], k.shape[seq_axis]
    use, interpret = _use_pallas(q)
    if q.dtype == jnp.float16 and not interpret:
        use = False  # Mosaic has no f16; XLA reference path handles it
    if use and not interpret and max(sq, sk) < _PALLAS_FWD_MIN_SEQ:
        use = False
    blocks = _pallas_blocks(sq, sk) if use and _HAVE_PALLAS else None
    return use and _HAVE_PALLAS and blocks is not None, interpret, blocks


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, causal, scale):
    use, interpret, blocks = _should_use_pallas(q, k)
    if use:
        b, h, s, d = q.shape
        out = _flash_fwd_pallas(
            q.reshape(b * h, s, d), k.reshape(b * h, -1, d), v.reshape(b * h, -1, d),
            causal, scale, interpret, block_q=blocks[0], block_k=blocks[1],
        )
        return out.reshape(b, h, s, d)
    return attention_reference(q, k, v, causal, scale)


# Below this query length the XLA backward (one fused S×S program) beats
# the two-pass blockwise kernel, and above it the blockwise kernel wins on
# both time and (crucially) memory — the XLA path's S×S residuals grow
# quadratically.  Measured on v5e (bf16, causal, D=64): S=128 BERT step
# 809 vs 913 samples/s (XLA wins), S=2048 14.9 vs 11.6 ms, S=4096 16.6 vs
# 14.9 ms, S=8192 25.9 vs 31.1 ms (blockwise wins).
_PALLAS_BWD_MIN_SEQ = int(os.environ.get("MXNET_TPU_FLASH_BWD_MIN_SEQ", "8192"))


def _flash_fwd(q, k, v, causal, scale):
    """VJP forward: on the Pallas path, also save (o, lse) so the backward
    can run blockwise without ever materializing S×S."""
    use, interpret, blocks = _should_use_pallas(q, k)
    if use:
        b, h, s, d = q.shape
        with_lse = max(s, k.shape[2]) >= _PALLAS_BWD_MIN_SEQ
        res = _flash_fwd_pallas(
            q.reshape(b * h, s, d), k.reshape(b * h, -1, d), v.reshape(b * h, -1, d),
            causal, scale, interpret, block_q=blocks[0], block_k=blocks[1],
            with_lse=with_lse)
        if with_lse:
            out, lse = res
            out = out.reshape(b, h, s, d)
            return out, (q, k, v, out, lse, interpret)
        return res.reshape(b, h, s, d), (q, k, v, None, None, False)
    out = attention_reference(q, k, v, causal, scale)
    return out, (q, k, v, None, None, False)


def _flash_bwd(causal, scale, res, do):
    q, k, v, o, lse, interpret = res
    if lse is not None:
        b, h, s, d = q.shape
        sk = k.shape[2]
        blocks = _pallas_blocks(s, sk)
        dq, dk, dv = _flash_bwd_pallas(
            q.reshape(b * h, s, d), k.reshape(b * h, sk, d),
            v.reshape(b * h, sk, d), do.reshape(b * h, s, d),
            o.reshape(b * h, s, d), lse, causal, scale, interpret,
            block_q=blocks[0], block_k=blocks[1])
        return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))
    return _flash_bwd_xla(causal, scale, (q, k, v), do)


def _flash_bwd_xla(causal, scale, res, do):
    """Rematerialized backward (standard flash-attention gradient algebra);
    XLA fallback — materializes S×S, fine at short sequence lengths.
    bf16 operands / fp32 accumulation, same rationale as
    :func:`attention_reference`."""
    q, k, v = res
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    mm = functools.partial(jnp.einsum, preferred_element_type=jnp.float32,
                           precision=prec)
    s = mm("...qd,...kd->...qk", q, k) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)                   # fp32 [.., Sq, Sk]
    pc = p.astype(v.dtype)
    o = mm("...qk,...kd->...qd", pc, v)              # fp32 accum
    dv = mm("...qk,...qd->...kd", pc, do)
    dp = mm("...qd,...kd->...qk", do, v)
    delta = jnp.sum(do.astype(jnp.float32) * o, axis=-1, keepdims=True)
    ds = (p * (dp - delta)).astype(q.dtype)
    dq = mm("...qk,...kd->...qd", ds, k) * scale
    dk = mm("...qk,...qd->...kd", ds, q) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None):
    """Fused attention on [B, H, S, D] arrays; differentiable; bf16-safe."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _flash(q, k, v, causal, float(scale))


# ---------------------------------------------------------------------------
# [B, S, H, Dh] layout path — no materialized head transposes (short-seq XLA
# tier; the layout shuffles live inside the dot_generals where the MXU's
# layout assignment absorbs them)
# ---------------------------------------------------------------------------


def _causal_mask(s):
    sq, sk = s.shape[-2], s.shape[-1]
    mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
    return jnp.where(mask, s, -jnp.inf)


# Score-tensor layout for the bshd XLA path: 'bhqk' (default — heads on
# the major axes) or 'bqhk' (heads inboard; an A/B candidate for the
# profiled head-split relayout copies on TPU — numerically identical,
# pinned by test).  Fixed at import; ONE code path parameterized by the
# einsum subscript so the math cannot diverge between layouts.
_SL = ("bqhk" if os.environ.get("MXNET_TPU_ATTN_SCORE_LAYOUT", "bhqk")
       == "bqhk" else "bhqk")


def _causal_mask_bqhk(s):
    sq, sk = s.shape[1], s.shape[-1]
    mask = (jnp.arange(sq)[:, None, None] >= jnp.arange(sk)[None, None, :])
    return jnp.where(mask, s, -jnp.inf)


_SCORE_MASK = _causal_mask_bqhk if _SL == "bqhk" else _causal_mask


def attention_reference_bshd(q, k, v, causal=False, scale=None):
    """Plain jnp attention over [B, S, H, Dh] operands (head axis stays in
    place; same fp32-accumulate / fp32-softmax policy as
    :func:`attention_reference`)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    s = jnp.einsum(f"bqhd,bkhd->{_SL}", q, k,
                   preferred_element_type=jnp.float32, precision=prec) * scale
    if causal:
        s = _SCORE_MASK(s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(f"{_SL},bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32,
                      precision=prec).astype(v.dtype)


# Probs-saving backward: below this many elements in the [B, H, Sq, Sk]
# score tensor, the fwd saves bf16 probabilities and the backward reuses
# them instead of recomputing scores+softmax.  Default 0 = ALWAYS
# rematerialize: measured on-chip (BERT-base B=64 S=128) saving probs
# LOST ~3% end-to-end (1367 vs 1407 samples/s) — the saved tensor's
# write+read broke XLA's fusion of the recompute into the backward
# matmuls, costing more than the recompute it avoided.  The knob stays
# for configs where the trade flips.
_SAVE_PROBS_MAX_ELEMS = int(os.environ.get(
    "MXNET_TPU_ATTN_SAVE_PROBS_MAX_ELEMS", "0"))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bshd(q, k, v, causal, scale):
    return attention_reference_bshd(q, k, v, causal, scale)


def _save_probs(q, k):
    b, sq, h, _ = q.shape
    return b * h * sq * k.shape[1] <= _SAVE_PROBS_MAX_ELEMS


def _flash_bshd_fwd(q, k, v, causal, scale):
    if not _save_probs(q, k):
        return attention_reference_bshd(q, k, v, causal, scale), (q, k, v, None)
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    mm = functools.partial(jnp.einsum, preferred_element_type=jnp.float32,
                           precision=prec)
    s = mm(f"bqhd,bkhd->{_SL}", q, k) * scale
    if causal:
        s = _SCORE_MASK(s)
    pc = jax.nn.softmax(s, axis=-1).astype(v.dtype)  # bf16 probs, saved
    o = mm(f"{_SL},bkhd->bqhd", pc, v).astype(v.dtype)
    return o, (q, k, v, pc)


def _flash_bshd_bwd(causal, scale, res, do):
    """bshd attention backward.  With saved probs (short seq): classic
    gradient algebra, delta via the flash identity rowsum(dp∘p) — no
    recompute, no fp32 S×S round-trips, and ``o`` need not be saved.
    Without (long seq): rematerialize, the bshd twin of
    :func:`_flash_bwd_xla`."""
    q, k, v, pc = res
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
            else jax.lax.Precision.DEFAULT)
    mm = functools.partial(jnp.einsum, preferred_element_type=jnp.float32,
                           precision=prec)
    if pc is None:
        s = mm(f"bqhd,bkhd->{_SL}", q, k) * scale
        if causal:
            s = _SCORE_MASK(s)
        p = jax.nn.softmax(s, axis=-1)               # fp32, _SL layout
        pc = p.astype(v.dtype)
    else:
        p = pc
    dv = mm(f"{_SL},bqhd->bkhd", pc, do)
    dp = mm(f"bqhd,bkhd->{_SL}", do, v)
    # delta_q = Σ_k dp∘p  (== Σ_d do∘o, the flash identity — saves o)
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = (p * (dp - delta)).astype(q.dtype)
    dq = mm(f"{_SL},bkhd->bqhd", ds, k) * scale
    dk = mm(f"{_SL},bqhd->bkhd", ds, q) * scale
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash_bshd.defvjp(_flash_bshd_fwd, _flash_bshd_bwd)


def _attend_bshd(q, k, v, causal, scale):
    """Dispatch [B, S, H, Dh] attention: bshd XLA path at short sequence
    lengths, transpose + Pallas flash kernel at long ones (where the two
    transposes are noise against O(S²) attention)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    # one shared gate with the bhsd path (seq_axis=1 in this layout) so
    # interpret-mode/f16/threshold behavior cannot drift; transposes only
    # happen on the Pallas branch
    use, _, _ = _should_use_pallas(q, k, seq_axis=1)
    if use:
        t = lambda x: x.transpose(0, 2, 1, 3)
        out = _flash(t(q), t(k), t(v), causal, float(scale))
        return out.transpose(0, 2, 1, 3)
    return _flash_bshd(q, k, v, causal, float(scale))


from .registry import register  # noqa: E402


@register("fused_attention")
def fused_attention(q, k, v, num_heads=1, causal=False, scale=None):
    """[B, S, D] convenience form: split heads → flash attention → merge.
    Registered so it is reachable as ``nd.fused_attention`` /
    ``nd.contrib.fused_attention`` (the role cuDNN fused MHA plays for the
    reference's GPU builds)."""
    b, s, d = q.shape
    h = num_heads
    if d % h:
        raise ValueError(f"feature dim {d} not divisible by num_heads {h}")

    def split(x):
        return x.reshape(b, x.shape[1], h, d // h)

    out = _attend_bshd(split(q), split(k), split(v), causal, scale)
    return out.reshape(b, s, d)


@register("fused_qkv_attention")
def fused_qkv_attention(qkv, num_heads=1, causal=False, scale=None):
    """Self-attention straight from the fused QKV projection output
    [B, S, 3·D]: the q/k/v split AND the head split are one free reshape
    ([B, S, 3, H, Dh] decomposes the projection's output columns exactly),
    and the bshd attention core never materializes a head transpose."""
    b, s, d3 = qkv.shape
    h = num_heads
    d = d3 // 3
    if d % h or d3 % 3:
        raise ValueError(f"qkv dim {d3} not divisible into 3 heads×{h}")
    x = qkv.reshape(b, s, 3, h, d // h)
    out = _attend_bshd(x[:, :, 0], x[:, :, 1], x[:, :, 2], causal, scale)
    return out.reshape(b, s, d)


@register("fused_kv_attention")
def fused_kv_attention(q, kv, num_heads=1, causal=False, scale=None):
    """Cross-attention twin of :func:`fused_qkv_attention`: q [B, Sq, D]
    from the decoder, kv [B, Sk, 2·D] from the fused KV projection of the
    encoder memory."""
    b, sq, d = q.shape
    h = num_heads
    if d % h or kv.shape[-1] != 2 * d:
        raise ValueError(f"kv dim {kv.shape[-1]} must be 2×{d}, heads {h}")
    dh = d // h
    x = kv.reshape(b, kv.shape[1], 2, h, dh)
    out = _attend_bshd(q.reshape(b, sq, h, dh), x[:, :, 0], x[:, :, 1],
                       causal, scale)
    return out.reshape(b, sq, d)


# ---------------------------------------------------------------------------
# interleaved_matmul_* (parity: [U:src/operator/contrib/transformer.cc], the
# GluonNLP 0.x fused-MHA fast path).  Layout convention: projections are
# [S, B, H·3·Dh] (self-attn, q/k/v interleaved PER HEAD) or [S, B, H·2·Dh]
# (enc-dec k/v).  On TPU these are einsum forms — XLA's layout assignment
# does what the reference's hand-written interleaved GEMMs do by hand.
# ---------------------------------------------------------------------------


def _deinterleave(proj, heads, parts):
    s, b, hpd = proj.shape
    if hpd % (heads * parts):
        raise ValueError(
            f"interleaved projection width {hpd} is not divisible by "
            f"heads({heads})×{parts}")
    dh = hpd // (heads * parts)
    x = proj.reshape(s, b, heads, parts, dh)
    return tuple(x[:, :, :, i] for i in range(parts))  # each [S, B, H, Dh]


@register("_contrib_interleaved_matmul_selfatt_qk")
def interleaved_matmul_selfatt_qk(queries_keys_values, heads=1):
    """scores[B·H, Sq, Sk] = Q·Kᵀ/√Dh from the interleaved projection."""
    q, k, _ = _deinterleave(queries_keys_values, heads, 3)
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("qbhd,kbhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    b, h, sq, sk = s.shape
    return s.reshape(b * h, sq, sk).astype(queries_keys_values.dtype)


@register("_contrib_interleaved_matmul_selfatt_valatt")
def interleaved_matmul_selfatt_valatt(queries_keys_values, attention, heads=1):
    """context [S, B, H·Dh] = attention · V with V from the interleaved
    projection; attention is [B·H, Sq, Sk]."""
    _, _, v = _deinterleave(queries_keys_values, heads, 3)  # [Sk, B, H, Dh]
    sk, b, h, dh = v.shape
    att = attention.reshape(b, h, -1, sk)
    out = jnp.einsum("bhqk,kbhd->qbhd", att.astype(jnp.float32),
                     v.astype(jnp.float32))
    sq = out.shape[0]
    return out.reshape(sq, b, h * dh).astype(queries_keys_values.dtype)


@register("_contrib_interleaved_matmul_encdec_qk")
def interleaved_matmul_encdec_qk(queries, keys_values, heads=1):
    """Cross-attention scores from separate Q [Sq, B, H·Dh] and interleaved
    KV [Sk, B, H·2·Dh]."""
    sq, b, hd = queries.shape
    if hd % heads:
        raise ValueError(f"query width {hd} not divisible by heads({heads})")
    dh = hd // heads
    q = queries.reshape(sq, b, heads, dh)
    k, _ = _deinterleave(keys_values, heads, 2)
    scale = 1.0 / math.sqrt(dh)
    s = jnp.einsum("qbhd,kbhd->bhqk", q.astype(jnp.float32) * scale,
                   k.astype(jnp.float32))
    return s.reshape(b * heads, sq, -1).astype(queries.dtype)


@register("_contrib_interleaved_matmul_encdec_valatt")
def interleaved_matmul_encdec_valatt(keys_values, attention, heads=1):
    _, v = _deinterleave(keys_values, heads, 2)  # [Sk, B, H, Dh]
    sk, b, h, dh = v.shape
    att = attention.reshape(b, h, -1, sk)
    out = jnp.einsum("bhqk,kbhd->qbhd", att.astype(jnp.float32),
                     v.astype(jnp.float32))
    return out.reshape(out.shape[0], b, h * dh).astype(keys_values.dtype)
