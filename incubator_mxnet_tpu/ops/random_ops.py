"""Random-sampling operators.

Parity targets: [U:src/operator/random/sample_op.cc] (``_random_uniform`` …)
and [U:src/operator/random/multisample_op.cc] (``_sample_uniform`` … — one
draw-batch per row of the distribution-parameter tensors).  The reference
pulls per-device RNG streams from the Resource manager; here every sampler
is a pure function of an explicit PRNG key threaded from :mod:`..random`
(trace-safe under jit; the hardware ``rbg`` generator is the package
default on TPU — config.py).

Multisample shape convention (the reference's): output shape is
``params.shape + shape`` — each scalar parameter row yields an independent
``shape``-shaped draw batch.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import _as_np_dtype
from .registry import register

__all__ = []


def _key(key):
    if key is not None:
        return key
    from ..random import get_key

    return get_key()


def _threefry(key):
    """jax.random.poisson supports only the threefry impl; under the
    package's hardware-PRNG (rbg) default, fold the key bits into a
    threefry key (counter-based samplers stay deterministic per key)."""
    if jax.dtypes.issubdtype(key.dtype, jax.dtypes.prng_key):
        data = jax.random.key_data(key)
    else:
        data = key
    flat = data.reshape(-1).astype(jnp.uint32)
    if flat.shape[0] == 2:
        words = flat
    else:
        words = jnp.stack([flat[0] ^ flat[-2], flat[1] ^ flat[-1]])
    return jax.random.wrap_key_data(words, impl="threefry2x32")


def _poisson(key, lam, shape):
    return jax.random.poisson(_threefry(key), lam, shape)


def _shape_tuple(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


# ---------------------------------------------------------------------------
# _random_* — tensor-shaped draws with scalar parameters
# ---------------------------------------------------------------------------


@register("_random_uniform", differentiable=False)
def _random_uniform(low=0.0, high=1.0, shape=(1,), dtype="float32", key=None):
    return jax.random.uniform(_key(key), _shape_tuple(shape),
                              dtype=_as_np_dtype(dtype), minval=low, maxval=high)


@register("_random_normal", differentiable=False)
def _random_normal(loc=0.0, scale=1.0, shape=(1,), dtype="float32", key=None):
    dt = _as_np_dtype(dtype)
    return loc + scale * jax.random.normal(_key(key), _shape_tuple(shape), dtype=dt)


@register("_random_gamma", differentiable=False)
def _random_gamma(alpha=1.0, beta=1.0, shape=(1,), dtype="float32", key=None):
    dt = _as_np_dtype(dtype)
    return beta * jax.random.gamma(_key(key), alpha, _shape_tuple(shape), dtype=dt)


@register("_random_exponential", differentiable=False)
def _random_exponential(lam=1.0, shape=(1,), dtype="float32", key=None):
    dt = _as_np_dtype(dtype)
    return jax.random.exponential(_key(key), _shape_tuple(shape), dtype=dt) / lam


@register("_random_poisson", differentiable=False)
def _random_poisson(lam=1.0, shape=(1,), dtype="float32", key=None):
    out = _poisson(_key(key), lam, _shape_tuple(shape))
    return out.astype(_as_np_dtype(dtype))


@register("_random_negative_binomial", differentiable=False)
def _random_negative_binomial(k=1, p=0.5, shape=(1,), dtype="float32", key=None):
    """Gamma–Poisson mixture: X ~ Poisson(Gamma(k, (1-p)/p)) — failures
    before the k-th success."""
    kg, kp = jax.random.split(_key(key))
    lam = jax.random.gamma(kg, float(k), _shape_tuple(shape)) * ((1.0 - p) / p)
    return _poisson(kp, lam, None).astype(_as_np_dtype(dtype))


@register("_random_generalized_negative_binomial", differentiable=False)
def _random_generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(1,), dtype="float32", key=None):
    """NB(mu, alpha): Poisson with Gamma(1/alpha, mu*alpha) rate; alpha→0
    degenerates to Poisson(mu)."""
    if alpha <= 0:
        return _poisson(
            _key(key), mu, _shape_tuple(shape)).astype(_as_np_dtype(dtype))
    kg, kp = jax.random.split(_key(key))
    lam = jax.random.gamma(kg, 1.0 / alpha, _shape_tuple(shape)) * (mu * alpha)
    return _poisson(kp, lam, None).astype(_as_np_dtype(dtype))


@register("_random_randint", differentiable=False)
def _random_randint(low=0, high=1, shape=(1,), dtype="int32", key=None):
    return jax.random.randint(_key(key), _shape_tuple(shape), int(low), int(high),
                              dtype=_as_np_dtype(dtype))


# ---------------------------------------------------------------------------
# _sample_* — per-row parameter tensors (multisample_op)
# ---------------------------------------------------------------------------


def _multi(params, shape):
    """Broadcast distribution-parameter tensors to a common shape and return
    (broadcast params, draw shape = common + shape)."""
    common = jnp.broadcast_shapes(*[jnp.shape(p) for p in params])
    out = [jnp.broadcast_to(jnp.asarray(p), common) for p in params]
    return out, common + _shape_tuple(shape)


def _expand(p, shape):
    """Append axes so p broadcasts against the draw shape."""
    extra = len(shape) - jnp.ndim(p)
    return jnp.reshape(p, jnp.shape(p) + (1,) * extra)


@register("_sample_uniform", differentiable=False)
def _sample_uniform(low, high, shape=(), dtype=None, key=None):
    (low, high), full = _multi([low, high], shape)
    dt = _as_np_dtype(dtype) if dtype else jnp.result_type(low)
    u = jax.random.uniform(_key(key), full, dtype=dt)
    return _expand(low, full) + u * (_expand(high, full) - _expand(low, full))


@register("_sample_normal", differentiable=False)
def _sample_normal(mu, sigma, shape=(), dtype=None, key=None):
    (mu, sigma), full = _multi([mu, sigma], shape)
    dt = _as_np_dtype(dtype) if dtype else jnp.result_type(mu)
    z = jax.random.normal(_key(key), full, dtype=dt)
    return _expand(mu, full) + _expand(sigma, full) * z


@register("_sample_gamma", differentiable=False)
def _sample_gamma(alpha, beta, shape=(), dtype=None, key=None):
    (alpha, beta), full = _multi([alpha, beta], shape)
    dt = _as_np_dtype(dtype) if dtype else jnp.result_type(alpha)
    g = jax.random.gamma(_key(key), _expand(alpha, full), full, dtype=dt)
    return g * _expand(beta, full)


@register("_sample_exponential", differentiable=False)
def _sample_exponential(lam, shape=(), dtype=None, key=None):
    (lam,), full = _multi([lam], shape)
    dt = _as_np_dtype(dtype) if dtype else jnp.result_type(lam)
    e = jax.random.exponential(_key(key), full, dtype=dt)
    return e / _expand(lam, full)


@register("_sample_poisson", differentiable=False)
def _sample_poisson(lam, shape=(), dtype="float32", key=None):
    (lam,), full = _multi([lam], shape)
    out = _poisson(_key(key), _expand(lam, full), full)
    return out.astype(_as_np_dtype(dtype))


@register("_sample_negative_binomial", differentiable=False)
def _sample_negative_binomial(k, p, shape=(), dtype="float32", key=None):
    (k, p), full = _multi([k, p], shape)
    kg, kp = jax.random.split(_key(key))
    kb, pb = _expand(k, full), _expand(p, full)
    lam = jax.random.gamma(kg, kb.astype(jnp.float32), full) * ((1.0 - pb) / pb)
    return _poisson(kp, lam, None).astype(_as_np_dtype(dtype))


@register("_sample_generalized_negative_binomial", differentiable=False)
def _sample_generalized_negative_binomial(mu, alpha, shape=(), dtype="float32", key=None):
    (mu, alpha), full = _multi([mu, alpha], shape)
    kg, kp = jax.random.split(_key(key))
    mub, ab = _expand(mu, full), _expand(alpha, full)
    safe = jnp.maximum(ab, 1e-12)
    lam = jax.random.gamma(kg, 1.0 / safe, full) * (mub * safe)
    lam = jnp.where(ab <= 0, mub, lam)  # alpha==0 rows degenerate to Poisson(mu)
    return _poisson(kp, lam, None).astype(_as_np_dtype(dtype))


@register("_sample_multinomial", differentiable=False)
def _sample_multinomial(data, shape=(), get_prob=False, dtype="int32", key=None):
    """Categorical draws from probability rows ([U:src/operator/random/
    sample_multinomial_op.cc]).  data: [..., k] probabilities; output
    ``data.shape[:-1] + shape`` int samples (+ log-prob tensor if
    ``get_prob`` — the REINFORCE helper the reference documents)."""
    batch = data.shape[:-1]
    full = batch + _shape_tuple(shape)
    logits = jnp.log(jnp.maximum(data, 1e-37))
    extra = len(full) - len(batch)
    lg = jnp.reshape(logits, batch + (1,) * extra + logits.shape[-1:])
    samples = jax.random.categorical(_key(key), lg, axis=-1, shape=full)
    samples = samples.astype(_as_np_dtype(dtype))
    if not get_prob:
        return samples
    logp = jnp.take_along_axis(
        jnp.broadcast_to(lg, full + logits.shape[-1:]),
        samples[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return samples, logp


@register("_random_uniform_like", differentiable=False)
def _random_uniform_like(data, low=0.0, high=1.0, key=None):
    return jax.random.uniform(_key(key), data.shape, dtype=data.dtype,
                              minval=low, maxval=high)


@register("_random_normal_like", differentiable=False)
def _random_normal_like(data, loc=0.0, scale=1.0, key=None):
    return loc + scale * jax.random.normal(_key(key), data.shape, dtype=data.dtype)


@register("shuffle", differentiable=False)
def shuffle(data, key=None):
    """Random permutation along the first axis (parity: [U:src/operator/
    random/shuffle_op.cc])."""
    return jax.random.permutation(_key(key), data, axis=0)
