"""Compilation observability (ISSUE 10): the global compile registry,
recompile attribution (each drift kind named exactly), the steady-state
compile guard (warn fires once, raise raises), per-site wiring of the jit
sites, serving warmup accounting, and the compile_report CLI."""
import io
import json
import logging
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import engine, profiler
from incubator_mxnet_tpu.gluon import nn
import incubator_mxnet_tpu.symbol as S

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_compiles():
    """Fresh registry + disarmed guard before AND after (module-global
    state; a leftover armed guard would tag every later test's compiles
    as steady-state violations)."""
    profiler.reset_compiles()
    profiler.disarm_compile_guard()
    profiler.set_config(compile_guard=None, compile_warmup_steps=None,
                        compile_cost=None)
    profiler.reset_counters()
    yield
    profiler.reset_compiles()
    profiler.disarm_compile_guard()
    profiler.set_config(compile_guard=None, compile_warmup_steps=None,
                        compile_cost=None)
    profiler.reset_counters()


def _arr(shape, dtype="float32", sharding=None):
    tok = {"k": "array", "shape": tuple(shape), "dtype": dtype}
    if sharding is not None:
        tok["sharding"] = sharding
    return tok


class TestSignatureDiff:
    """Each drift kind must be named correctly — the attribution contract."""

    def test_shape_drift(self):
        f = profiler.diff_signatures({"x": _arr((4, 8))}, {"x": _arr((4, 16))})
        assert f == [{"arg": "x", "kind": "shape",
                      "old": "float32[4x8]", "new": "float32[4x16]"}]

    def test_dtype_flip(self):
        f = profiler.diff_signatures({"x": _arr((4, 8))},
                                     {"x": _arr((4, 8), "bfloat16")})
        assert f[0]["kind"] == "dtype" and f[0]["arg"] == "x"

    def test_static_value_drift(self):
        f = profiler.diff_signatures({"k": profiler.sig_static(3)},
                                     {"k": profiler.sig_static(4)})
        assert f == [{"arg": "k", "kind": "static", "old": "3", "new": "4"}]

    def test_sharding_change(self):
        f = profiler.diff_signatures(
            {"x": _arr((4, 8), sharding="PartitionSpec('dp',)")},
            {"x": _arr((4, 8), sharding="PartitionSpec(None,)")})
        assert f[0]["kind"] == "sharding"

    def test_added_and_removed(self):
        f = profiler.diff_signatures({"a": _arr((2,))},
                                     {"b": _arr((2,))})
        kinds = {x["arg"]: x["kind"] for x in f}
        assert kinds == {"a": "removed", "b": "added"}

    def test_program_key_ignored(self):
        assert profiler.diff_signatures({"__program__": "f"},
                                        {"__program__": "g"}) == []


class TestRecordCompile:
    def test_first_compile_is_not_a_recompile(self, clean_compiles):
        r = profiler.record_compile("t.site", {"__program__": "p",
                                               "x": _arr((2, 2))}, 5.0)
        assert not r["recompile"] and r["attribution"] is None
        assert profiler.counters()["compile_total"] == 1
        assert profiler.counters()["compile_ms_total"] == 5

    def test_recompile_names_exact_argument(self, clean_compiles):
        profiler.record_compile("t.site", {"__program__": "p",
                                           "a": _arr((2, 2)),
                                           "b": _arr((3, 3))}, 1.0)
        r = profiler.record_compile("t.site", {"__program__": "p",
                                               "a": _arr((2, 2)),
                                               "b": _arr((3, 5))}, 1.0)
        assert r["recompile"]
        assert "argument 'b'" in r["attribution"]
        assert "shape drift" in r["attribution"]
        assert "'a'" not in r["attribution"]

    def test_different_program_is_not_a_recompile(self, clean_compiles):
        profiler.record_compile("t.site", {"__program__": "p",
                                           "x": _arr((2, 2))}, 1.0)
        r = profiler.record_compile("t.site", {"__program__": "q",
                                               "x": _arr((4, 4))}, 1.0)
        assert not r["recompile"]

    def test_nearest_signature_wins(self, clean_compiles):
        # dtype flip must diff against the SAME-shape cached signature,
        # not the older different-shape one
        profiler.record_compile("t.site", {"__program__": "p",
                                           "x": _arr((4, 8))}, 1.0)
        profiler.record_compile("t.site", {"__program__": "p",
                                           "x": _arr((4, 16))}, 1.0)
        r = profiler.record_compile(
            "t.site", {"__program__": "p",
                       "x": _arr((4, 16), "bfloat16")}, 1.0)
        assert "dtype flip" in r["attribution"]
        assert "float32[4x16]" in r["attribution"]

    def test_identical_signature_recompile(self, clean_compiles):
        sig = {"__program__": "p", "x": _arr((2, 2))}
        profiler.record_compile("t.site", sig, 1.0)
        r = profiler.record_compile("t.site", sig, 1.0)
        assert r["recompile"]
        assert "evicted" in r["attribution"]

    def test_compile_site_override(self, clean_compiles):
        with profiler.compile_site("outer.phase"):
            r = profiler.record_compile("inner.site", {"x": _arr((1,))}, 1.0)
        assert r["site"] == "outer.phase"
        r2 = profiler.record_compile("inner.site", {"x": _arr((1,))}, 1.0)
        assert r2["site"] == "inner.site"

    def test_registry_and_provider(self, clean_compiles):
        profiler.record_compile("prov.site", {"x": _arr((1,))}, 2.0)
        reg = profiler.compile_registry()
        assert reg["sites"]["prov.site"]["count"] == 1
        assert len(reg["records"]) == 1
        prov = profiler.metrics_snapshot()["providers"]["compile"]
        assert prov["prov_site_total"] == 1
        assert prov["total"] == 1

    def test_dump_embeds_registry(self, clean_compiles, tmp_path):
        profiler.set_config(filename=str(tmp_path / "t.json"))
        profiler.record_compile("d.site", {"x": _arr((1,))}, 2.0)
        profiler.start()
        path = profiler.dump()
        with open(path) as f:
            doc = json.load(f)
        assert "d.site" in doc["otherData"]["compiles"]["sites"]
        assert doc["otherData"]["compile_guard"]["armed"] is False

    def test_cost_extraction_opt_in(self, clean_compiles):
        import jax
        import jax.numpy as jnp

        f = jax.jit(lambda x: (x @ x).sum())
        x = jnp.ones((16, 16))
        f(x)
        r = profiler.record_compile("c.site", {"x": profiler.sig_array(x)},
                                    1.0, fn=f, args=(x,))
        assert r["cost"] is None  # off by default
        profiler.set_config(compile_cost=True)
        r2 = profiler.record_compile("c.site", {"x": profiler.sig_array(x),
                                                "v": profiler.sig_static(2)},
                                     1.0, fn=f, args=(x,))
        assert r2["cost"] and r2["cost"]["flops"] > 0


class TestCompileGuard:
    def test_counts_only_when_armed(self, clean_compiles):
        profiler.record_compile("g.site", {"x": _arr((1,))}, 1.0)
        assert profiler.counters()["recompile_steady_state"] == 0
        profiler.arm_compile_guard("test")
        profiler.record_compile("g.site", {"x": _arr((2,))}, 1.0)
        assert profiler.counters()["recompile_steady_state"] == 1

    def test_warn_fires_exactly_once(self, clean_compiles, caplog):
        profiler.set_config(compile_guard="warn")
        profiler.arm_compile_guard("test")
        with caplog.at_level(logging.WARNING, logger=profiler.__name__):
            profiler.record_compile("g.site", {"x": _arr((1,))}, 1.0)
            profiler.record_compile("g.site", {"x": _arr((2,))}, 1.0)
            profiler.record_compile("g.site", {"x": _arr((3,))}, 1.0)
        warns = [r for r in caplog.records
                 if "steady-state compile guard" in r.message]
        assert len(warns) == 1
        assert "armed by test" in warns[0].message
        # every violation still counts, silently
        assert profiler.counters()["recompile_steady_state"] == 3

    def test_raise_mode_raises(self, clean_compiles):
        profiler.set_config(compile_guard="raise")
        profiler.arm_compile_guard("test")
        with pytest.raises(profiler.CompileGuardError) as ei:
            profiler.record_compile("g.site", {"x": _arr((1,))}, 1.0)
        assert "g.site" in str(ei.value)
        # the record was still appended before raising
        assert profiler.compile_registry()["sites"]["g.site"]["count"] == 1

    def test_guard_paused_exempts(self, clean_compiles):
        profiler.set_config(compile_guard="raise")
        profiler.arm_compile_guard("test")
        with profiler.compile_guard_paused():
            profiler.record_compile("g.site", {"x": _arr((1,))}, 1.0)
        assert profiler.counters()["recompile_steady_state"] == 0

    def test_auto_arm_after_warmup_steps(self, clean_compiles):
        profiler.set_config(compile_guard="warn", compile_warmup_steps=2)
        assert not profiler.compile_guard_state()["armed"]
        profiler.step_boundary()
        assert not profiler.compile_guard_state()["armed"]
        profiler.step_boundary()
        st = profiler.compile_guard_state()
        assert st["armed"] and st["armed_by"] == "warmup_steps"

    def test_no_auto_arm_without_mode(self, clean_compiles):
        profiler.set_config(compile_warmup_steps=1)
        profiler.step_boundary()
        profiler.step_boundary()
        assert not profiler.compile_guard_state()["armed"]

    def test_config_off_overrides_env(self, clean_compiles, monkeypatch):
        # set_config wins over the env: "off" must silence an exported
        # MXNET_COMPILE_GUARD=raise (deliberate re-shape phases)
        monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
        profiler.arm_compile_guard("test")
        profiler.set_config(compile_guard="off")
        profiler.record_compile("g.site", {"x": _arr((1,))}, 1.0)  # no raise
        assert profiler.compile_guard_state()["mode"] is None
        profiler.set_config(compile_guard=None)   # defer to env again
        assert profiler.compile_guard_state()["mode"] == "raise"


class TestSiteWiring:
    def test_dispatch_site_registers(self, clean_compiles):
        a = mx.nd.array(np.ones((5, 7), np.float32))
        for _ in range(3):
            (a + a).asnumpy()   # warmup=1: second sighting compiles
        sites = profiler.compile_stats()
        assert sites.get("ops.dispatch", {}).get("count", 0) >= 1

    def test_bulk_site_registers(self, clean_compiles):
        a = mx.nd.array(np.ones((3, 3), np.float32))
        with engine.bulk(8):
            b = a + 7.0
            c = b * 3.0
        c.asnumpy()
        sites = profiler.compile_stats()
        assert sites.get("engine.bulk", {}).get("count", 0) >= 1

    def test_predictor_recompile_attributed_to_input(self, clean_compiles):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=3, name="fc1")
        params = {"arg:fc1_weight": mx.nd.array(
                      np.ones((3, 4), np.float32)),
                  "arg:fc1_bias": mx.nd.array(np.zeros(3, np.float32))}
        from incubator_mxnet_tpu.predictor import Predictor

        pred = Predictor(fc, params, {"data": (2, 4)})
        pred.predict(data=np.ones((2, 4), np.float32))
        pred.reshape({"data": (6, 4)})
        pred.predict(data=np.ones((6, 4), np.float32))
        recs = [r for r in profiler.compile_registry()["records"]
                if r["site"] == "predictor.forward" and r["recompile"]]
        assert recs, "reshape-driven recompile not registered"
        assert "argument 'data'" in recs[-1]["attribution"]
        assert "shape drift" in recs[-1]["attribution"]

    def test_pytree_token_expands_to_leaves(self, clean_compiles):
        # a list-of-arrays positional ("t" cache-key token) must expand
        # into per-leaf signature entries so a drift inside the list
        # attributes at the leaf with its real kind, not as an opaque
        # static value
        from incubator_mxnet_tpu.ops.registry import _compile_sig

        def fake_op():
            pass

        tok = ("t", "list", (("a", (2, 3), np.dtype("float32"), False, None),
                             ("a", (4,), np.dtype("float32"), False, None)))
        sig = _compile_sig(fake_op, (tok,), ())
        assert sig["arg0[0]"]["shape"] == (2, 3)
        assert sig["arg0[1]"]["shape"] == (4,)
        tok2 = ("t", "list", (("a", (2, 3), np.dtype("float32"), False, None),
                              ("a", (9,), np.dtype("float32"), False, None)))
        sig2 = _compile_sig(fake_op, (tok2,), ())
        f = profiler.diff_signatures(sig, sig2)
        assert f == [{"arg": "arg0[1]", "kind": "shape",
                      "old": "float32[4]", "new": "float32[9]"}]

    def test_raise_during_donating_fused_step_keeps_weights(
            self, clean_compiles):
        # the guard fires AFTER the donated group dispatch: the new
        # buffers must still be wired into the weights before the error
        # surfaces, or the whole group would be left pointing at deleted
        # jax buffers
        from incubator_mxnet_tpu.optimizer import fused as F

        rng = np.random.RandomState(7)
        w_np = [rng.rand(3, 4).astype(np.float32),
                rng.rand(5).astype(np.float32)]
        g_np = [rng.rand(3, 4).astype(np.float32),
                rng.rand(5).astype(np.float32)]
        ws = [mx.nd.array(a) for a in w_np]
        gs = [mx.nd.array(a) for a in g_np]
        opt = mx.optimizer.create("sgd", learning_rate=0.1, wd=0.0)
        opt.aggregate_num = 100
        items = [(i, w, g) for i, (w, g) in enumerate(zip(ws, gs))]
        states = {0: None, 1: None}
        profiler.set_config(compile_guard="raise")
        profiler.arm_compile_guard("test")
        with pytest.raises(profiler.CompileGuardError):
            F.fused_update(opt, items, states)  # fresh group -> compile
        profiler.set_config(compile_guard=None)
        profiler.disarm_compile_guard()
        # the donated-and-replaced weights took the SGD update exactly
        for w, wn, gn in zip(ws, w_np, g_np):
            np.testing.assert_allclose(w.asnumpy(), wn - 0.1 * gn,
                                       rtol=1e-6, atol=1e-7)

    def test_group_apply_site(self, clean_compiles):
        import jax.numpy as jnp

        from incubator_mxnet_tpu.ops import optimizer_ops as K

        ws = [jnp.ones((4, 4)), jnp.ones((6,))]
        K.group_apply(K.sgd_step, ws, ws, [(), ()], [0.1, 0.1],
                      [0.0, 0.0], [0, 0], {"rescale": 1.0, "clip": -1.0})
        assert "optimizer.group_apply" in profiler.compile_stats()


def _serving_model():
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=6, flatten=False, name="fc1")
    sym = S.Activation(fc, act_type="tanh", name="t1")
    rng = np.random.RandomState(0)
    params = {"arg:fc1_weight": mx.nd.array(rng.randn(6, 4)
                                            .astype(np.float32)),
              "arg:fc1_bias": mx.nd.array(rng.randn(6).astype(np.float32))}
    return sym, params


class TestServingCompiles:
    def test_warmup_registered_per_bucket_zero_steady(self, clean_compiles):
        from incubator_mxnet_tpu.serving import InferenceServer

        sym, params = _serving_model()
        srv = InferenceServer(sym, params, {"data": (None, 4)},
                              max_batch_size=4, max_queue_ms=20.0,
                              length_buckets=[8, 16], batch_buckets=[2, 4],
                              name="compile_test")
        try:
            sites = profiler.compile_stats()
            # 2 batch buckets x 2 length buckets, all under serving.warmup
            assert sites["serving.warmup"]["count"] >= 4
            assert profiler.compile_guard_state()["armed_by"] == "serving"
            before_total = profiler.counters()["compile_total"]
            before_steady = profiler.counters()["recompile_steady_state"]
            rng = np.random.RandomState(1)
            for L in (3, 8, 11, 16):
                out = srv.infer({"data": rng.rand(L, 4).astype(np.float32)},
                                timeout=30.0)
                assert out.shape == (L, 6)
            # in-bucket steady traffic: NOTHING compiled, guard silent
            assert profiler.counters()["compile_total"] == before_total
            assert (profiler.counters()["recompile_steady_state"]
                    == before_steady)
        finally:
            srv.close()

    def test_warmup_exempt_from_prearmed_guard(self, clean_compiles):
        from incubator_mxnet_tpu.serving import InferenceServer

        profiler.set_config(compile_guard="raise")
        profiler.arm_compile_guard("elsewhere")
        sym, params = _serving_model()
        # warmup compiles run under compile_guard_paused(): no raise
        srv = InferenceServer(sym, params, {"data": (None, 4)},
                              max_batch_size=2, max_queue_ms=20.0,
                              length_buckets=[8], name="compile_test2")
        srv.close()


class TestCompileReportCLI:
    def _dump(self, tmp_path):
        profiler.record_compile("spmd.step",
                                {"__program__": "step",
                                 "input0": _arr((16, 12)),
                                 "label": _arr((16,))}, 50.0)
        profiler.record_compile("spmd.step",
                                {"__program__": "step",
                                 "input0": _arr((24, 12)),
                                 "label": _arr((24,))}, 40.0)
        path = tmp_path / "reg.json"
        with open(path, "w") as f:
            json.dump(profiler.compile_registry(), f)
        return str(path)

    def test_report_lists_site_and_culprit(self, clean_compiles, tmp_path):
        path = self._dump(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "compile_report.py"), path],
            capture_output=True, text=True, cwd=_REPO)
        assert out.returncode == 0, out.stderr
        assert "spmd.step" in out.stdout
        assert "input0" in out.stdout          # exact culprit argument
        assert "shape" in out.stdout
        assert "90.0 ms total" in out.stdout

    def test_json_summary(self, clean_compiles, tmp_path):
        path = self._dump(tmp_path)
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "compile_report.py"), path,
             "--json"],
            capture_output=True, text=True, cwd=_REPO)
        assert out.returncode == 0, out.stderr
        summ = json.loads(out.stdout)
        assert summ["total_compiles"] == 2
        cu = summ["culprits"][0]
        assert (cu["site"], cu["arg"], cu["kind"]) == ("spmd.step",
                                                       "input0", "shape")

    def test_empty_registry_exits_2(self, clean_compiles, tmp_path):
        path = tmp_path / "empty.json"
        with open(path, "w") as f:
            json.dump({"sites": {}, "records": []}, f)
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools",
                                          "compile_report.py"), str(path)],
            capture_output=True, text=True, cwd=_REPO)
        assert out.returncode == 2
        assert "empty" in out.stderr

def test_load_registry_from_trace(tmp_path, clean_compiles):
    sys.path.insert(0, os.path.join(_REPO, "tools"))
    try:
        import compile_report
    finally:
        sys.path.pop(0)
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.record_compile("x.site", {"x": _arr((2,))}, 3.0)
    profiler.start()
    trace = profiler.dump()
    reg = compile_report.load_registry(trace)
    assert "x.site" in reg["sites"]
    buf = io.StringIO()
    compile_report.report(reg, out=buf)
    assert "x.site" in buf.getvalue()


class TestSPMDTrainerGuard:
    def test_first_step_arms_and_drift_attributed(self, clean_compiles):
        from incubator_mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
        from incubator_mxnet_tpu.parallel import SPMDTrainer

        mx.random.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 12)))
        spmd = SPMDTrainer(net, SoftmaxCrossEntropyLoss(), "sgd",
                           {"learning_rate": 0.01})
        rng = np.random.RandomState(0)
        x = rng.randn(16, 12).astype(np.float32)
        y = rng.randint(0, 4, size=(16,)).astype(np.float32)
        spmd.step(x, y)
        assert profiler.compile_guard_state()["armed_by"] == "spmd.trainer"
        steady0 = profiler.counters()["recompile_steady_state"]
        spmd.step(x, y)   # warm replay: no compile
        assert profiler.counters()["recompile_steady_state"] == steady0
        spmd.step(rng.randn(24, 12).astype(np.float32),
                  rng.randint(0, 4, size=(24,)).astype(np.float32))
        assert profiler.counters()["recompile_steady_state"] == steady0 + 1
        recs = [r for r in profiler.compile_registry()["records"]
                if r["site"] == "spmd.step" and r["recompile"]]
        assert recs and "argument 'input0'" in recs[-1]["attribution"]
        assert "shape drift" in recs[-1]["attribution"]
