"""INT8 quantization (parity: [U:tests/python/quantization/test_quantization.py]).

quantize_v2/dequantize round-trip, int8 FC/conv vs fp32 tolerance, and the
quantize_net calibrate-and-swap flow on a small MLP and convnet."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.contrib.quantization import quantize_net

RNG = np.random.RandomState(11)


class TestQuantizeOps:
    def test_quantize_dequantize_roundtrip(self):
        x = mx.nd.array(RNG.randn(6, 8).astype(np.float32) * 3)
        q, mn, mxr = mx.nd.quantize_v2(x)
        assert str(q.dtype) == "int8"
        back = mx.nd.dequantize(q, mn, mxr)
        amax = np.abs(x.asnumpy()).max()
        np.testing.assert_allclose(back.asnumpy(), x.asnumpy(),
                                   atol=amax / 127 + 1e-6)

    def test_quantize_with_calib_range_saturates(self):
        x = mx.nd.array(np.array([[-5.0, 0.0, 0.5, 5.0]], np.float32))
        q, mn, mxr = mx.nd.quantize_v2(x, min_calib_range=-1.0, max_calib_range=1.0)
        qn = q.asnumpy()
        assert qn[0, 0] == -127 and qn[0, 3] == 127  # saturating cast
        assert abs(qn[0, 2] - 64) <= 1

    def test_quantized_fc_matches_fp32(self):
        x = RNG.randn(5, 16).astype(np.float32)
        w = RNG.randn(8, 16).astype(np.float32)
        b = RNG.randn(8).astype(np.float32)
        fp32 = x @ w.T + b
        xq, xmn, xmx = mx.nd.quantize_v2(mx.nd.array(x))
        wq, wmn, wmx = mx.nd.quantize_v2(mx.nd.array(w))
        out = mx.nd.quantized_fully_connected(
            xq, wq, mx.nd.array(b), xmn, xmx, wmn, wmx, num_hidden=8)
        scale = np.abs(fp32).max()
        np.testing.assert_allclose(out.asnumpy(), fp32, atol=scale * 0.05)

    def test_quantized_conv_matches_fp32(self):
        x = RNG.randn(2, 3, 8, 8).astype(np.float32)
        w = RNG.randn(4, 3, 3, 3).astype(np.float32)
        fp32 = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), None,
                                 kernel=(3, 3), num_filter=4, pad=(1, 1),
                                 no_bias=True).asnumpy()
        xq, xmn, xmx = mx.nd.quantize_v2(mx.nd.array(x))
        wq, wmn, wmx = mx.nd.quantize_v2(mx.nd.array(w))
        out = mx.nd.quantized_conv(xq, wq, None, xmn, xmx, wmn, wmx,
                                   kernel=(3, 3), num_filter=4, pad=(1, 1),
                                   no_bias=True)
        scale = np.abs(fp32).max()
        np.testing.assert_allclose(out.asnumpy(), fp32, atol=scale * 0.05)

    def test_requantize(self):
        acc = mx.nd.array(np.array([[1000, -2000, 30000]], np.float32)).astype("int32")
        mn, mxr = mx.nd.array([-1.0]), mx.nd.array([1.0])
        q, qmn, qmx = mx.nd.requantize(acc, mn, mxr)
        assert str(q.dtype) == "int8"
        real = acc.asnumpy().astype(np.float32) * (1.0 / 127)
        back = q.asnumpy().astype(np.float32) * (
            max(abs(float(qmn.asnumpy()[0])), abs(float(qmx.asnumpy()[0]))) / 127)
        np.testing.assert_allclose(back, real, rtol=0.02, atol=np.abs(real).max() / 100)


class TestQuantizeNet:
    def test_mlp_within_tolerance(self):
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(10))
        net.initialize()
        calib = [mx.nd.array(RNG.rand(8, 16).astype(np.float32)) for _ in range(4)]
        ref = net(calib[0]).asnumpy()
        quantize_net(net, calib)
        out = net(calib[0]).asnumpy()
        assert getattr(net._children["0"], "_quantized", False)
        scale = np.abs(ref).max()
        np.testing.assert_allclose(out, ref, atol=scale * 0.06)
        # argmax preserved on most rows (classification survives int8)
        agree = (out.argmax(1) == ref.argmax(1)).mean()
        assert agree >= 0.75, agree

    def test_convnet_and_exclusion(self):
        mx.random.seed(1)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
                gluon.nn.Conv2D(4, kernel_size=3, padding=1))
        net.initialize()
        calib = [mx.nd.array(RNG.rand(2, 3, 8, 8).astype(np.float32)) for _ in range(2)]
        ref = net(calib[0]).asnumpy()
        first_name = net._children["0"].name
        quantize_net(net, calib, excluded_layers=(first_name,))
        assert not getattr(net._children["0"], "_quantized", False)
        assert getattr(net._children["1"], "_quantized", False)
        out = net(calib[0]).asnumpy()
        scale = np.abs(ref).max()
        np.testing.assert_allclose(out, ref, atol=scale * 0.06)


class TestEntropyCalibration:
    """calib_mode='entropy': the KL threshold sweep of
    [U:python/mxnet/contrib/quantization.py] _get_optimal_threshold."""

    def test_optimal_threshold_clips_outliers(self):
        from incubator_mxnet_tpu.contrib.quantization import optimal_threshold

        rng = np.random.RandomState(0)
        x = rng.randn(200000).astype(np.float32)  # bulk in ~[-4, 4]
        x[:20] = 500.0                            # rare huge outliers
        th = optimal_threshold(x)
        assert 2.0 < th < 100.0, th  # clipped far below the 500 max
        # clean gaussian: threshold stays near the true range
        th_clean = optimal_threshold(rng.randn(200000).astype(np.float32))
        assert th_clean > 2.5, th_clean

    def test_entropy_differs_from_naive_and_wins_on_skewed(self):
        mx.random.seed(3)
        rng = np.random.RandomState(3)
        net_a = gluon.nn.HybridSequential()
        net_a.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
        net_a.initialize()
        # skewed calibration data: mostly small values + rare big outliers
        def make_batch():
            d = rng.randn(64, 16).astype(np.float32)
            d[rng.rand(64) < 0.02] *= 60.0
            return mx.nd.array(d)
        calib = [make_batch() for _ in range(4)]
        # clean eval batch (the bulk distribution)
        test_x = mx.nd.array(rng.randn(64, 16).astype(np.float32))
        ref = net_a(test_x).asnumpy()

        import copy
        # clone the net for the naive run by rebuilding with same params
        net_b = gluon.nn.HybridSequential()
        net_b.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(10))
        net_b.initialize()
        for pa, pb in zip(sorted(net_a.collect_params().values(), key=lambda p: p.name),
                          sorted(net_b.collect_params().values(), key=lambda p: p.name)):
            pb.set_data(pa.data())

        quantize_net(net_a, calib, calib_mode="entropy")
        quantize_net(net_b, calib, calib_mode="naive")
        out_e = net_a(test_x).asnumpy()
        out_n = net_b(test_x).asnumpy()
        err_e = np.abs(out_e - ref).mean()
        err_n = np.abs(out_n - ref).mean()
        # KL calibration must beat minmax on the outlier-skewed stream
        assert err_e < err_n, (err_e, err_n)
        # and land within a few percent of fp32 on the bulk data
        assert err_e <= 0.05 * np.abs(ref).max(), (err_e, np.abs(ref).max())

    def test_entropy_mode_rejects_bad_mode(self):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(4))
        net.initialize()
        calib = [mx.nd.array(RNG.rand(2, 3).astype(np.float32))]
        try:
            quantize_net(net, calib, calib_mode="percentile")
        except ValueError:
            pass
        else:
            raise AssertionError("expected ValueError for unknown calib_mode")


def test_legacy_quantize_explicit_range():
    """quantize (v1, explicit range) vs numpy for both out_types
    ([U:src/operator/quantization/quantize.cc])."""
    x = np.linspace(-2.0, 2.0, 9).astype(np.float32)[None]
    q, mn, mx_ = mx.nd.quantize(mx.nd.array(x), mx.nd.array([-1.0]),
                                mx.nd.array([1.0]), out_type="uint8")
    expect = np.clip(np.round((np.clip(x, -1, 1) + 1) * 127.5), 0, 255)
    np.testing.assert_allclose(q.asnumpy().astype(np.float32), expect)
    assert float(mn.asnumpy()[0]) == -1.0 and float(mx_.asnumpy()[0]) == 1.0
    q8, _, _ = mx.nd.quantize(mx.nd.array(x), mx.nd.array([-1.0]),
                              mx.nd.array([1.0]), out_type="int8")
    np.testing.assert_allclose(q8.asnumpy().astype(np.float32),
                               np.clip(np.round(x * 127.0), -127, 127))
