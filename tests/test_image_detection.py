"""Detection image pipeline (parity: [U:python/mxnet/image/detection.py]
tests — augmenters must transform images and boxes TOGETHER)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.image import (CreateDetAugmenter,
                                       DetHorizontalFlipAug,
                                       DetRandomCropAug, ImageDetIter)


def _sample(seed=0, h=60, w=80):
    rng = np.random.RandomState(seed)
    img = rng.randint(0, 255, (h, w, 3), np.uint8)
    label = np.full((4, 5), -1.0, np.float32)
    label[0] = [1, 0.10, 0.20, 0.50, 0.60]
    label[1] = [3, 0.55, 0.30, 0.90, 0.80]
    return img, label


class TestDetAugmenters:
    def test_flip_mirrors_boxes(self):
        img, label = _sample()
        aug = DetHorizontalFlipAug(p=1.0)
        out, lab = aug(img, label)
        np.testing.assert_array_equal(np.asarray(out), img[:, ::-1])
        np.testing.assert_allclose(lab[0, 1:5], [0.50, 0.20, 0.90, 0.60], atol=1e-6)
        assert lab[2, 0] == -1  # padding untouched

    def test_flip_identity_at_p0(self):
        img, label = _sample()
        out, lab = DetHorizontalFlipAug(p=0.0)(img, label)
        np.testing.assert_array_equal(np.asarray(out), img)
        np.testing.assert_array_equal(lab, label)

    def test_random_crop_keeps_covered_boxes_normalized(self):
        np.random.seed(7)
        img, label = _sample()
        aug = DetRandomCropAug(min_object_covered=0.5, area_range=(0.5, 0.9))
        out, lab = aug(img, label)
        valid = lab[lab[:, 0] >= 0]
        assert len(valid) >= 1
        assert (valid[:, 1:5] >= 0).all() and (valid[:, 1:5] <= 1).all()
        assert (valid[:, 3] > valid[:, 1]).all() and (valid[:, 4] > valid[:, 2]).all()


class TestImageDetIter:
    def test_batches_and_shapes(self):
        samples = []
        for i in range(6):
            img, label = _sample(seed=i)
            samples.append((label, img))
        it = ImageDetIter(samples, batch_size=3, data_shape=(3, 32, 32),
                          max_objects=4, rand_mirror=True, rand_crop=1,
                          mean=np.array([0.5, 0.5, 0.5], np.float32))
        batches = list(it)
        assert len(batches) == 2
        b = batches[0]
        assert b.data[0].shape == (3, 3, 32, 32)
        assert b.label[0].shape == (3, 4, 5)
        lab = b.label[0].asnumpy()
        valid = lab[lab[:, :, 0] >= 0]
        assert (valid[:, 1:5] >= 0).all() and (valid[:, 1:5] <= 1).all()

    def test_feeds_multibox_target(self):
        """The det pipeline must compose with the SSD target op."""
        import jax.numpy as jnp

        from incubator_mxnet_tpu.ops.detection import (multibox_prior,
                                                       multibox_target)

        samples = [(np.array([[1, 0.1, 0.1, 0.6, 0.6]], np.float32),
                    _sample(seed=9)[0]) for _ in range(2)]
        it = ImageDetIter(samples, batch_size=2, data_shape=(3, 32, 32),
                          max_objects=4)
        batch = next(iter(it))
        anchors = multibox_prior(jnp.zeros((1, 3, 8, 8)),
                                 sizes=(0.5,), ratios=(1.0,))
        cls_preds = jnp.zeros((2, 3, anchors.shape[1]))  # [B, C+1, N]
        bt, bm, ct = multibox_target(anchors, batch.label[0]._data, cls_preds)
        assert np.isfinite(np.asarray(bt)).all()
        assert int(np.asarray((ct > 0).sum())) > 0  # some anchors matched

    def test_empty_label_and_partial_batch(self):
        """Background-only samples (zero boxes) and a trailing partial
        batch must both work (review-caught: empty-list crash + silent
        batch drop)."""
        rng = np.random.RandomState(1)
        samples = [([], rng.randint(0, 255, (40, 40, 3), np.uint8))
                   for _ in range(5)]
        it = ImageDetIter(samples, batch_size=2, data_shape=(3, 16, 16),
                          max_objects=3)
        batches = list(it)
        assert len(batches) == 3  # 2+2+1(padded), not 2 dropped-batches
        assert (batches[-1].label[0].asnumpy()[:, :, 0] == -1).all()

    def test_batch_larger_than_dataset_raises(self):
        img, label = _sample()
        with pytest.raises(ValueError, match="exceeds dataset size"):
            ImageDetIter([(label, img)], batch_size=4, data_shape=(3, 16, 16))
