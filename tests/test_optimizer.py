"""Optimizer tests (parity model: [U:tests/python/unittest/test_optimizer.py]):
each optimizer is validated against a pure-numpy reference update."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal

from common import with_seed


def _run_steps(opt_name, np_update, steps=5, shape=(4, 3), **opt_args):
    np.random.seed(0)
    w0 = np.random.uniform(-1, 1, shape).astype("float32")
    grads = [np.random.uniform(-1, 1, shape).astype("float32") for _ in range(steps)]

    opt = mx.optimizer.create(opt_name, **opt_args)
    w = mx.nd.array(w0)
    state = opt.create_state(0, w)
    for g in grads:
        opt.update(0, w, mx.nd.array(g), state)

    w_ref, aux = w0.copy(), {}
    for t, g in enumerate(grads, 1):
        w_ref = np_update(w_ref, g, t, aux)
    assert_almost_equal(w, w_ref, rtol=1e-4, atol=1e-5)


def test_sgd():
    lr, wd = 0.1, 0.01

    def upd(w, g, t, aux):
        return w - lr * (g + wd * w)

    _run_steps("sgd", upd, learning_rate=lr, wd=wd)


def test_sgd_momentum():
    lr, mom, wd = 0.1, 0.9, 0.0

    def upd(w, g, t, aux):
        m = aux.setdefault("m", np.zeros_like(w))
        m[:] = mom * m - lr * (g + wd * w)
        return w + m

    _run_steps("sgd", upd, learning_rate=lr, momentum=mom, wd=wd)


def test_nag():
    lr, mom = 0.1, 0.9

    def upd(w, g, t, aux):
        m = aux.setdefault("m", np.zeros_like(w))
        m[:] = mom * m + g
        return w - lr * (mom * m + g)

    _run_steps("nag", upd, learning_rate=lr, momentum=mom, wd=0.0)


def test_adam():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8

    def upd(w, g, t, aux):
        m = aux.setdefault("m", np.zeros_like(w))
        v = aux.setdefault("v", np.zeros_like(w))
        m[:] = b1 * m + (1 - b1) * g
        v[:] = b2 * v + (1 - b2) * g * g
        lr_t = lr * np.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        return w - lr_t * m / (np.sqrt(v) + eps)

    _run_steps("adam", upd, learning_rate=lr, wd=0.0)


def test_rmsprop():
    lr, rho, eps = 0.01, 0.9, 1e-8

    def upd(w, g, t, aux):
        n = aux.setdefault("n", np.zeros_like(w))
        n[:] = rho * n + (1 - rho) * g * g
        return w - lr * g / np.sqrt(n + eps)

    _run_steps("rmsprop", upd, learning_rate=lr, rho=rho, epsilon=eps, wd=0.0)


def test_adagrad():
    lr, eps = 0.05, 1e-7

    def upd(w, g, t, aux):
        h = aux.setdefault("h", np.zeros_like(w))
        h[:] = h + g * g
        return w - lr * g / (np.sqrt(h) + eps)

    _run_steps("adagrad", upd, learning_rate=lr, wd=0.0)


def test_signum():
    lr, mom = 0.01, 0.9

    def upd(w, g, t, aux):
        m = aux.setdefault("m", np.zeros_like(w))
        m[:] = mom * m - (1 - mom) * g
        return w + lr * np.sign(m)

    _run_steps("signum", upd, learning_rate=lr, momentum=mom, wd=0.0)


def test_lamb_decreases_loss():
    opt = mx.optimizer.create("lamb", learning_rate=0.1)
    w = mx.nd.array(np.full((4, 4), 5.0, dtype="float32"))
    state = opt.create_state(0, w)
    for _ in range(50):
        grad = 2 * w
        opt.update(0, w, grad, state)
    assert float(w.abs().mean().asscalar()) < 1.0


def test_clip_gradient():
    opt = mx.optimizer.create("sgd", learning_rate=1.0, clip_gradient=0.1)
    w = mx.nd.zeros((2,))
    opt.update(0, w, mx.nd.array([10.0, -10.0]), None)
    assert_almost_equal(w, np.array([-0.1, 0.1]), rtol=1e-5, atol=1e-6)


def test_multi_precision_bf16():
    opt = mx.optimizer.create("sgd", learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = mx.nd.ones((4,), dtype="bfloat16")
    state = opt.create_state_multi_precision(0, w)
    g = mx.nd.ones((4,), dtype="bfloat16") * 0.001
    for _ in range(10):
        opt.update_multi_precision(0, w, g, state)
    # fp32 master accumulates small updates that bf16 alone would lose
    _, w32 = state
    assert float(w32.asnumpy()[0]) < 1.0


def test_lr_scheduler_factor():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert sched(5) == 1.0
    assert sched(15) == 0.5
    assert sched(25) == 0.25


def test_lr_scheduler_warmup_cosine():
    sched = mx.lr_scheduler.CosineScheduler(100, base_lr=1.0, warmup_steps=10)
    assert sched(5) == pytest.approx(0.5)
    assert sched(10) == pytest.approx(1.0)
    assert sched(100) == pytest.approx(0.0, abs=1e-6)


def test_optimizer_lr_wd_mult():
    opt = mx.optimizer.create("sgd", learning_rate=1.0, param_idx2name={0: "a_weight", 1: "b_bias"}, wd=0.1)
    opt.set_wd_mult({})
    # bias gets wd_mult 0 automatically
    assert opt._get_wd(1) == 0.0
    assert opt._get_wd(0) == pytest.approx(0.1)
    opt.set_lr_mult({"a_weight": 0.5})
    assert opt._get_lr(0) == pytest.approx(0.5)
    assert opt._get_lr(1) == pytest.approx(1.0)


def test_lars_optimizer():
    """LARS (round-5 tail): trust-ratio-scaled momentum SGD vs a numpy
    replication; zero-norm fallback; full-zoo export check."""
    from incubator_mxnet_tpu import optimizer as opt

    o = opt.create("lars", learning_rate=0.1, momentum=0.9, eta=0.01, wd=1e-4)
    rng = np.random.RandomState(0)
    w = mx.nd.array(rng.randn(5, 4).astype(np.float32))
    g = mx.nd.array(rng.randn(5, 4).astype(np.float32))
    st = o.create_state(0, w)
    w0, g0 = w.asnumpy().copy(), g.asnumpy().copy()
    o.update(0, w, g, st)
    wn, gn = np.linalg.norm(w0), np.linalg.norm(g0)
    trust = 0.01 * wn / (gn + 1e-4 * wn + 1e-8)
    mom = trust * 0.1 * (g0 + 1e-4 * w0)
    np.testing.assert_allclose(w.asnumpy(), w0 - mom, rtol=1e-5)

    # zero weight norm -> plain-lr fallback, no NaN
    wz = mx.nd.zeros((3,))
    o2 = opt.create("lars", learning_rate=0.1)
    o2.update(1, wz, mx.nd.array(np.ones(3, np.float32)), None)
    assert np.isfinite(wz.asnumpy()).all()
    np.testing.assert_allclose(wz.asnumpy(), -0.1 * np.ones(3), rtol=1e-6)

    # the full optimizer zoo is importable by its reference names
    from incubator_mxnet_tpu.optimizer import (  # noqa: F401
        Nadam, FTML, SGLD, DCASGD, Adamax, LBSGD, LARS, GroupAdaGrad)
