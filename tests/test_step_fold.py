"""Step-fold tier (ISSUE 15): one compiled program per training step.

Folded-vs-unfused EXACT parity (same seeds, same per-step PRNG keys, same
fused step adapters — differences bounded by XLA fusion reassociation
only), the single-dispatch steady state under the compile guard, the
escape hatches, save/load_states mid-run, and the grad-readiness overlap
hook (correctness + loud failure under the PR 5 fault-injection tier).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon, profiler  # noqa: E402
from incubator_mxnet_tpu.gluon import step_fold  # noqa: E402
from incubator_mxnet_tpu.kvstore import KVStore  # noqa: E402

L2 = gluon.loss.L2Loss()

# fold-vs-unfused runs the same adapter math through differently-fused XLA
# programs: bounded by reassociation noise, not bit layout
TOL = dict(rtol=2e-5, atol=2e-7)


@pytest.fixture(autouse=True)
def _fresh_guard():
    # folds arm the process-global steady-state compile guard; a fresh
    # net's CachedOp build in the NEXT test must not trip a stale arm
    profiler.disarm_compile_guard()
    yield
    profiler.disarm_compile_guard()


def _mlp(seed, dropout=0.3, bn=True, dtype="float32"):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    # no bias on the layer feeding BN: BN cancels input shifts, so that
    # bias's gradient is ~0 and Adam's m/(sqrt(v)+eps) on it amplifies
    # float reassociation noise unboundedly — a model pathology, not a
    # parity signal
    net.add(gluon.nn.Dense(16, activation="relu", use_bias=not bn))
    if bn:
        net.add(gluon.nn.BatchNorm())
    if dropout:
        net.add(gluon.nn.Dropout(dropout))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
    y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
    net(x)  # materialize deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x, y = x.astype(dtype), y.astype(dtype)
    return net, x, y


def _params_of(net):
    return sorted(net.collect_params().values(), key=lambda p: p.name)


def _assert_params_equal(a, b, **tol):
    tol = tol or TOL
    for pa, pb in zip(_params_of(a), _params_of(b)):
        np.testing.assert_allclose(
            pa.data().asnumpy().astype(np.float32),
            pb.data().asnumpy().astype(np.float32),
            err_msg=f"{pa.name} vs {pb.name}", **tol)


def _run_unfused(net, trainer, x, y, steps, batch_size=8):
    losses = []
    for _ in range(steps):
        with autograd.record():
            loss = L2(net(x), y)
        loss.backward()
        trainer.step(batch_size)
        losses.append(float(loss.mean().asscalar()))
    return losses


def _run_folded(program, x, y, steps):
    return [float(program(x, y).mean().asscalar()) for _ in range(steps)]


class _BucketingStore(KVStore):
    """In-process store that accepts bucketed pushpulls (the dist wire
    without processes) — lets single-process tests drive the bucket plan,
    the overlap hook, and the fault point."""

    def __init__(self):
        super().__init__("stub_bucketing")
        self.pushpull_keys = []

    def supports_grad_bucketing(self):
        return True

    def pushpull(self, key, value, out=None, priority=0):
        self.pushpull_keys.append(key)
        super().pushpull(key, value, out=out, priority=priority)


# ---------------------------------------------------------------------------
# folded vs unfused parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("opt,oargs", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_fold_parity_bn_dropout(opt, oargs):
    """BN running stats, dropout PRNG streams, loss values and params all
    match the unfused record/backward/step path, step for step."""
    net1, x, y = _mlp(7)
    tr1 = gluon.Trainer(net1.collect_params(), opt, dict(oargs),
                        kvstore=None)
    mx.random.seed(123)
    l1 = _run_unfused(net1, tr1, x, y, 5)

    net2, x2, y2 = _mlp(7)
    tr2 = gluon.Trainer(net2.collect_params(), opt, dict(oargs),
                        kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(123)
    l2 = _run_folded(program, x2, y2, 5)

    assert program.folded, program.fallback_reason
    np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-7)
    _assert_params_equal(net1, net2)
    # BN aux (moving_mean/var) ride the same parity check via params_of


def test_fold_parity_mixed_groups():
    """Two fused groups (fp32 + bf16 params) in one folded program."""
    def build():
        mx.random.seed(11)
        np.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(4, 6).astype("float32"))
        y = mx.nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
        net(x)
        # cast ONE layer to bf16: plan_groups must produce two groups
        for p in net[1].collect_params().values():
            p.cast("bfloat16")
        return net, x, y

    net1, x, y = build()
    tr1 = gluon.Trainer(net1.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=None)
    mx.random.seed(5)
    _run_unfused(net1, tr1, x, y, 4, batch_size=4)

    net2, x2, y2 = build()
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(5)
    _run_folded(program, x2, y2, 4)
    assert program.folded, program.fallback_reason
    # bf16 params quantize harder: compare at bf16 resolution
    _assert_params_equal(net1, net2, rtol=2e-2, atol=2e-3)


def test_fold_interleaved_foreign_aux_frozen_with_warning():
    """Owned-BN -> FOREIGN-BN (params the trainer doesn't hold) ->
    owned-BN: owned stats land on their OWN parameters (the positional
    pairing regression) and match the unfused path; the foreign BN's
    stats stay FROZEN (its old value is a baked trace constant — a
    write-back would re-derive from the original stats forever) with one
    loud warning at build."""
    def build():
        mx.random.seed(41)
        np.random.seed(41)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.BatchNorm(),            # owned
                gluon.nn.Dense(6, use_bias=False),
                gluon.nn.BatchNorm(),            # FOREIGN (not in trainer)
                gluon.nn.Dense(4, use_bias=False),
                gluon.nn.BatchNorm())            # owned
        net.initialize()
        net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
        y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
        net(x)
        foreign = sorted(net[2].collect_params().keys())
        owned = [p for k, p in net.collect_params().items()
                 if k not in foreign]
        return net, owned, foreign, x, y

    net1, owned1, foreign1, x, y = build()
    tr1 = gluon.Trainer(owned1, "sgd", {"learning_rate": 0.05},
                        kvstore=None)
    mx.random.seed(9)
    for _ in range(3):
        with autograd.record():
            loss = L2(net1(x), y)
        loss.backward()
        tr1.step(8)

    net2, owned2, foreign2, x2, y2 = build()
    frozen = {k: net2.collect_params()[k].data().asnumpy().copy()
              for k in foreign2}
    tr2 = gluon.Trainer(owned2, "sgd", {"learning_rate": 0.05},
                        kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(9)
    with pytest.warns(UserWarning, match="stay FROZEN"):
        program(x2, y2)
    _run_folded(program, x2, y2, 2)
    assert program.folded, program.fallback_reason
    all2 = net2.collect_params()
    for k in foreign2:   # frozen, not silently corrupted
        np.testing.assert_array_equal(frozen[k], all2[k].data().asnumpy(),
                                      err_msg=k)
    # OWNED params (incl. both owned BNs' stats) match the unfused run
    for pa, pb in zip(sorted(owned1, key=lambda p: p.name),
                      sorted(owned2, key=lambda p: p.name)):
        np.testing.assert_allclose(
            pa.data().asnumpy(), pb.data().asnumpy(),
            err_msg=f"{pa.name} vs {pb.name}", **TOL)


def test_fold_save_load_states_mid_run():
    """save_states / load_states mid-run round-trips the folded
    trajectory exactly (Adam: t must stay monotonic through the fold)."""
    import tempfile

    net, x, y = _mlp(9, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(77)
    _run_folded(program, x, y, 3)
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        tr.save_states(fname)
        snap = {p.name: p.data().asnumpy().copy() for p in _params_of(net)}
        cont = _run_folded(program, x, y, 2)
        # restore & replay: same two steps must reproduce exactly
        tr.load_states(fname)
        for p in _params_of(net):
            p.set_data(mx.nd.array(snap[p.name]))
        replay = _run_folded(program, x, y, 2)
    np.testing.assert_allclose(cont, replay, rtol=1e-6, atol=1e-8)
    assert program.folded, program.fallback_reason


# ---------------------------------------------------------------------------
# steady state: one dispatch, zero recompiles
# ---------------------------------------------------------------------------


def test_fold_single_dispatch_steady_state():
    net, x, y = _mlp(13)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(1)
    _run_folded(program, x, y, 2)  # warmup: build + arm the guard
    c0 = profiler.counters()
    for _ in range(3):
        # NOTHING but the folded call: even a .mean() on the loss would
        # be one more (cached) eager dispatch and fail the exact count
        program(x, y)
    c1 = profiler.counters()
    assert c1["step_fold_call"] - c0["step_fold_call"] == 3
    # EXACTLY one host-issued device dispatch per steady-state step
    assert (step_fold.host_dispatch_total(c1)
            - step_fold.host_dispatch_total(c0)) == 3
    assert c1["recompile_steady_state"] == c0["recompile_steady_state"]


def test_fold_zero_recompiles_under_guard_raise(monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    net, x, y = _mlp(17)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(2)
    _run_folded(program, x, y, 1)   # builds, then arms the guard
    _run_folded(program, x, y, 4)   # must not raise CompileGuardError
    assert program.folded


# ---------------------------------------------------------------------------
# escape hatches / fallbacks
# ---------------------------------------------------------------------------


def test_fold_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("MXNET_STEP_FOLD", "0")
    net, x, y = _mlp(19, dropout=0.0, bn=False)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    assert not program.folded and "MXNET_STEP_FOLD" in program.fallback_reason
    c0 = profiler.counters()
    loss = program(x, y)   # still works — eager path
    assert np.isfinite(float(loss.mean().asscalar()))
    c1 = profiler.counters()
    assert c1["step_fold_call"] == c0["step_fold_call"]
    # every eager execution through the program counts
    assert c1["step_fold_fallback"] == c0["step_fold_fallback"] + 1


def test_fold_block_opt_out():
    net, x, y = _mlp(23, dropout=0.0, bn=False)
    net._step_fold_opt_out = True
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    assert not program.folded and "opt-out" in program.fallback_reason
    loss = program(x, y)
    assert np.isfinite(float(loss.mean().asscalar()))


def test_fold_unsupported_optimizer_falls_back():
    net, x, y = _mlp(29, dropout=0.0, bn=False)
    tr = gluon.Trainer(net.collect_params(), "ftrl",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    c0 = profiler.counters()["step_fold_fallback"]
    with pytest.warns(UserWarning, match="step fold disabled"):
        loss = program(x, y)
    assert not program.folded
    assert profiler.counters()["step_fold_fallback"] > c0
    assert np.isfinite(float(loss.mean().asscalar()))
    # the fallback still trains (eager step ran)
    loss2 = program(x, y)
    assert np.isfinite(float(loss2.mean().asscalar()))


def test_fold_step_fast_path_tail(monkeypatch):
    """MXNET_STEP_FOLD=1: Trainer.step folds every optimizer group into
    ONE donated dispatch (fold_update) — numerics identical."""
    net1, x, y = _mlp(31, dropout=0.0)
    tr1 = gluon.Trainer(net1.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    mx.random.seed(3)
    _run_unfused(net1, tr1, x, y, 4)

    monkeypatch.setenv("MXNET_STEP_FOLD", "1")
    net2, x2, y2 = _mlp(31, dropout=0.0)
    tr2 = gluon.Trainer(net2.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    c0 = profiler.counters()
    mx.random.seed(3)
    _run_unfused(net2, tr2, x2, y2, 4)
    c1 = profiler.counters()
    assert c1["fused_step_call"] - c0["fused_step_call"] == 4
    _assert_params_equal(net1, net2)


# ---------------------------------------------------------------------------
# the grad-readiness overlap hook
# ---------------------------------------------------------------------------


def _overlap_net(seed, kv):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
    y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=kv)
    return net, tr, x, y


def test_overlap_matches_sequential(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net1, tr1, x, y = _overlap_net(3, _BucketingStore())
    for _ in range(3):
        with autograd.record():
            loss = L2(net1(x), y)
        loss.backward()
        tr1.step(8)

    net2, tr2, x2, y2 = _overlap_net(3, _BucketingStore())
    c0 = profiler.counters()["allreduce_overlap_launched"]
    for _ in range(3):
        with autograd.record():
            loss = L2(net2(x2), y2)
        tr2.backward(loss)   # buckets launch DURING this call
        tr2.step(8)
    launched = profiler.counters()["allreduce_overlap_launched"] - c0
    assert launched >= 6   # several buckets per step actually overlapped
    _assert_params_equal(net1, net2, rtol=1e-6, atol=1e-7)


def test_overlap_hook_fires_during_backward(monkeypatch):
    """Buckets must launch BEFORE backward returns — asserted by spying
    execute_bucket from inside the hook window."""
    from incubator_mxnet_tpu import kvstore as kv_mod

    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(5, _BucketingStore())
    seen = []
    orig = kv_mod.execute_bucket

    def spy(kv, bucket, items, policy, feedback):
        seen.append(bucket["key"])
        return orig(kv, bucket, items, policy, feedback)

    monkeypatch.setattr(kv_mod, "execute_bucket", spy)
    # trainer.backward resolves execute_bucket through the kv_mod facade
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)
    assert len(seen) >= 2, "no buckets launched from the readiness hook"
    tr.step(8)


def test_overlap_disabled_env(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    monkeypatch.setenv("MXNET_ALLREDUCE_OVERLAP", "0")
    net, tr, x, y = _overlap_net(7, _BucketingStore())
    c0 = profiler.counters()["allreduce_overlap_launched"]
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)   # plain backward
    tr.step(8)
    assert profiler.counters()["allreduce_overlap_launched"] == c0


def test_overlap_dropped_bucket_reply_errors_loudly(monkeypatch):
    """PR 5 fault-injection tier: a dropped bucket reply during backward
    raises out of Trainer.backward, and the failed bucket's grads keep
    their pre-exchange values (never half-written)."""
    from incubator_mxnet_tpu.utils import faultinject

    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(9, _BucketingStore())
    with autograd.record():
        loss = L2(net(x), y)
    faultinject.configure("kvstore.bucket_drop_reply:n=1")
    try:
        with pytest.raises(ConnectionError):
            tr.backward(loss)
    finally:
        faultinject.configure("")
    # the step is poisoned for the failed bucket only; a FRESH backward
    # must recover cleanly end to end
    for p in net.collect_params().values():
        p.zero_grad()
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)
    tr.step(8)
    assert np.isfinite(float(loss.mean().asscalar()))


def test_overlap_stale_plan_discarded(monkeypatch):
    """An overlap backward whose step() never ran must NOT poison the
    next plain-backward step: the versions recorded at launch no longer
    match, so step() discards the plan and re-reduces EVERY bucket."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(13, _BucketingStore())
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)      # plan stored, buckets pushed ... and abandoned
    with autograd.record():
        loss = L2(net(x), y)
    loss.backward()        # fresh grads, plain backward
    c0 = profiler.counters()["allreduce_bucket"]
    tr.step(8)             # stale plan must be discarded → full re-reduce
    executed = profiler.counters()["allreduce_bucket"] - c0
    assert executed >= 3, f"stale overlap plan skipped buckets ({executed})"


def test_grad_ready_hook_order_and_parity():
    """The hook finalizes leaves in reverse-layer order mid-walk, with
    gradients exactly equal to a hookless backward."""
    net, _, x, y = _overlap_net(11, None)
    params = _params_of(net)
    with autograd.record():
        loss = L2(net(x), y)
    loss.backward()
    ref = {p.name: p.grad().asnumpy().copy() for p in params}
    for p in params:
        p.zero_grad()
    order = []
    id2name = {id(p._data): p.name for p in params}
    with autograd.record():
        loss = L2(net(x), y)
    autograd.backward(
        [loss], grad_ready_hook=lambda leaf: order.append(id2name[id(leaf)]))
    for p in params:
        np.testing.assert_allclose(ref[p.name], p.grad().asnumpy(),
                                   rtol=1e-6, atol=0, err_msg=p.name)
    assert set(order) == set(ref)
    # last layer's weight must be ready before the first layer's
    assert order.index("dense5_weight" if "dense5_weight" in ref
                       else sorted(ref)[-2]) < order.index(sorted(ref)[0]) \
        or order[0] != sorted(ref)[0]


# ---------------------------------------------------------------------------
# 2-process tiers (launch_local, like tests/test_dist.py)
# ---------------------------------------------------------------------------

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dist_in_fold_two_workers():
    """The IN-FOLD gradient exchange (per-bucket psum nodes inside one
    shard_map'd compiled step) trains to the out-of-fold trajectory at
    process_count=2, with zero steady-state recompiles."""
    import subprocess

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch_local.py"),
         "-n", "2", sys.executable,
         os.path.join(ROOT, "tests", "fold_worker.py")],
        env=env, capture_output=True, text=True, timeout=280)
    sys.stdout.write(proc.stdout[-4000:])
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, f"fold workers failed (rc={proc.returncode})"
    assert proc.stdout.count("all assertions passed") == 2


@pytest.mark.slow
def test_dist_overlap_two_workers():
    """The out-of-fold overlap path at process_count=2: hooked pushpulls
    must converge identically to sequential allreduce-after-backward and
    not be slower beyond noise (the full acceptance — overlap strictly
    faster — is the opperf harness / evidence JSON, which runs at the
    tuned size; this keeps the wiring honest in CI)."""
    sys.path.insert(0, os.path.join(ROOT, "benchmark", "opperf"))
    import importlib

    bench = importlib.import_module("step_fold")
    res = bench.run_dist(layers=6, width=64, batch=16, iters=3, warmup=1,
                         bucket_kb=32)
    assert res["returncode"] == 0
    assert res["convergence"]["parity"], res["convergence"]
    assert res["overlap_buckets_launched"] > 0


# ---------------------------------------------------------------------------
# harness smoke
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_step_fold_bench_smoke():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark", "opperf"))
    import importlib

    bench = importlib.import_module("step_fold")
    res = bench.run(layers=3, width=32, batch=8, iters=2, warmup=1,
                    repeats=1)
    assert res["recompiles_steady_state"] == 0
    assert res["folded_dispatches_per_step"] == 1
    assert res["steps_per_sec"]["folded"] > 0
