"""Step-fold tier (ISSUE 15): one compiled program per training step.

Folded-vs-unfused EXACT parity (same seeds, same per-step PRNG keys, same
fused step adapters — differences bounded by XLA fusion reassociation
only), the single-dispatch steady state under the compile guard, the
escape hatches, save/load_states mid-run, and the grad-readiness overlap
hook (correctness + loud failure under the PR 5 fault-injection tier).
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import incubator_mxnet_tpu as mx  # noqa: E402
from incubator_mxnet_tpu import autograd, gluon, profiler  # noqa: E402
from incubator_mxnet_tpu.gluon import step_fold  # noqa: E402
from incubator_mxnet_tpu.kvstore import KVStore  # noqa: E402

L2 = gluon.loss.L2Loss()

# fold-vs-unfused runs the same adapter math through differently-fused XLA
# programs: bounded by reassociation noise, not bit layout
TOL = dict(rtol=2e-5, atol=2e-7)


@pytest.fixture(autouse=True)
def _fresh_guard():
    # folds arm the process-global steady-state compile guard; a fresh
    # net's CachedOp build in the NEXT test must not trip a stale arm
    profiler.disarm_compile_guard()
    yield
    profiler.disarm_compile_guard()


def _mlp(seed, dropout=0.3, bn=True, dtype="float32"):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    # no bias on the layer feeding BN: BN cancels input shifts, so that
    # bias's gradient is ~0 and Adam's m/(sqrt(v)+eps) on it amplifies
    # float reassociation noise unboundedly — a model pathology, not a
    # parity signal
    net.add(gluon.nn.Dense(16, activation="relu", use_bias=not bn))
    if bn:
        net.add(gluon.nn.BatchNorm())
    if dropout:
        net.add(gluon.nn.Dropout(dropout))
    net.add(gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
    y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
    net(x)  # materialize deferred shapes
    if dtype != "float32":
        net.cast(dtype)
        x, y = x.astype(dtype), y.astype(dtype)
    return net, x, y


def _params_of(net):
    # insertion order, NOT a lexical name sort: identically-built nets
    # pair positionally, while name sorting scrambles the cross-net
    # pairing once the gluon auto-name counters pass dense9
    # ("dense10" < "dense9") — which depends on what ran earlier in the
    # process.
    return list(net.collect_params().values())


def _assert_params_equal(a, b, **tol):
    tol = tol or TOL
    for pa, pb in zip(_params_of(a), _params_of(b)):
        np.testing.assert_allclose(
            pa.data().asnumpy().astype(np.float32),
            pb.data().asnumpy().astype(np.float32),
            err_msg=f"{pa.name} vs {pb.name}", **tol)


def _run_unfused(net, trainer, x, y, steps, batch_size=8):
    losses = []
    for _ in range(steps):
        with autograd.record():
            loss = L2(net(x), y)
        loss.backward()
        trainer.step(batch_size)
        losses.append(float(loss.mean().asscalar()))
    return losses


def _run_folded(program, x, y, steps):
    return [float(program(x, y).mean().asscalar()) for _ in range(steps)]


class _BucketingStore(KVStore):
    """In-process store that accepts bucketed pushpulls (the dist wire
    without processes) — lets single-process tests drive the bucket plan,
    the overlap hook, and the fault point."""

    def __init__(self):
        super().__init__("stub_bucketing")
        self.pushpull_keys = []

    def supports_grad_bucketing(self):
        return True

    def pushpull(self, key, value, out=None, priority=0):
        self.pushpull_keys.append(key)
        super().pushpull(key, value, out=out, priority=priority)


# ---------------------------------------------------------------------------
# folded vs unfused parity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("opt,oargs", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_fold_parity_bn_dropout(opt, oargs):
    """BN running stats, dropout PRNG streams, loss values and params all
    match the unfused record/backward/step path, step for step."""
    net1, x, y = _mlp(7)
    tr1 = gluon.Trainer(net1.collect_params(), opt, dict(oargs),
                        kvstore=None)
    mx.random.seed(123)
    l1 = _run_unfused(net1, tr1, x, y, 5)

    net2, x2, y2 = _mlp(7)
    tr2 = gluon.Trainer(net2.collect_params(), opt, dict(oargs),
                        kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(123)
    l2 = _run_folded(program, x2, y2, 5)

    assert program.folded, program.fallback_reason
    np.testing.assert_allclose(l1, l2, rtol=1e-5, atol=1e-7)
    _assert_params_equal(net1, net2)
    # BN aux (moving_mean/var) ride the same parity check via params_of


def test_fold_parity_mixed_groups():
    """Two fused groups (fp32 + bf16 params) in one folded program."""
    def build():
        mx.random.seed(11)
        np.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(4, 6).astype("float32"))
        y = mx.nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
        net(x)
        # cast ONE layer to bf16: plan_groups must produce two groups
        for p in net[1].collect_params().values():
            p.cast("bfloat16")
        return net, x, y

    net1, x, y = build()
    tr1 = gluon.Trainer(net1.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=None)
    mx.random.seed(5)
    _run_unfused(net1, tr1, x, y, 4, batch_size=4)

    net2, x2, y2 = build()
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(5)
    _run_folded(program, x2, y2, 4)
    assert program.folded, program.fallback_reason
    # bf16 params quantize harder: compare at bf16 resolution
    _assert_params_equal(net1, net2, rtol=2e-2, atol=2e-3)


def test_fold_interleaved_foreign_aux_frozen_with_warning():
    """Owned-BN -> FOREIGN-BN (params the trainer doesn't hold) ->
    owned-BN: owned stats land on their OWN parameters (the positional
    pairing regression) and match the unfused path; the foreign BN's
    stats stay FROZEN (its old value is a baked trace constant — a
    write-back would re-derive from the original stats forever) with one
    loud warning at build."""
    def build():
        mx.random.seed(41)
        np.random.seed(41)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.BatchNorm(),            # owned
                gluon.nn.Dense(6, use_bias=False),
                gluon.nn.BatchNorm(),            # FOREIGN (not in trainer)
                gluon.nn.Dense(4, use_bias=False),
                gluon.nn.BatchNorm())            # owned
        net.initialize()
        net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
        y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
        net(x)
        foreign = sorted(net[2].collect_params().keys())
        owned = [p for k, p in net.collect_params().items()
                 if k not in foreign]
        return net, owned, foreign, x, y

    net1, owned1, foreign1, x, y = build()
    tr1 = gluon.Trainer(owned1, "sgd", {"learning_rate": 0.05},
                        kvstore=None)
    mx.random.seed(9)
    for _ in range(3):
        with autograd.record():
            loss = L2(net1(x), y)
        loss.backward()
        tr1.step(8)

    net2, owned2, foreign2, x2, y2 = build()
    frozen = {k: net2.collect_params()[k].data().asnumpy().copy()
              for k in foreign2}
    tr2 = gluon.Trainer(owned2, "sgd", {"learning_rate": 0.05},
                        kvstore=None)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    mx.random.seed(9)
    with pytest.warns(UserWarning, match="stay FROZEN"):
        program(x2, y2)
    _run_folded(program, x2, y2, 2)
    assert program.folded, program.fallback_reason
    all2 = net2.collect_params()
    for k in foreign2:   # frozen, not silently corrupted
        np.testing.assert_array_equal(frozen[k], all2[k].data().asnumpy(),
                                      err_msg=k)
    # OWNED params (incl. both owned BNs' stats) match the unfused run.
    # Pair positionally: both lists come from the same insertion-ordered
    # collect_params() walk, while a lexical name sort scrambles the
    # pairing once earlier tests push the auto-name counters past
    # dense9 ("dense10" < "dense9").
    for pa, pb in zip(owned1, owned2):
        np.testing.assert_allclose(
            pa.data().asnumpy(), pb.data().asnumpy(),
            err_msg=f"{pa.name} vs {pb.name}", **TOL)


def test_fold_save_load_states_mid_run():
    """save_states / load_states mid-run round-trips the folded
    trajectory exactly (Adam: t must stay monotonic through the fold)."""
    import tempfile

    net, x, y = _mlp(9, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(77)
    _run_folded(program, x, y, 3)
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        tr.save_states(fname)
        snap = {p.name: p.data().asnumpy().copy() for p in _params_of(net)}
        cont = _run_folded(program, x, y, 2)
        # restore & replay: same two steps must reproduce exactly
        tr.load_states(fname)
        for p in _params_of(net):
            p.set_data(mx.nd.array(snap[p.name]))
        replay = _run_folded(program, x, y, 2)
    np.testing.assert_allclose(cont, replay, rtol=1e-6, atol=1e-8)
    assert program.folded, program.fallback_reason


# ---------------------------------------------------------------------------
# steady state: one dispatch, zero recompiles
# ---------------------------------------------------------------------------


def test_fold_single_dispatch_steady_state():
    net, x, y = _mlp(13)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(1)
    _run_folded(program, x, y, 2)  # warmup: build + arm the guard
    c0 = profiler.counters()
    for _ in range(3):
        # NOTHING but the folded call: even a .mean() on the loss would
        # be one more (cached) eager dispatch and fail the exact count
        program(x, y)
    c1 = profiler.counters()
    assert c1["step_fold_call"] - c0["step_fold_call"] == 3
    # EXACTLY one host-issued device dispatch per steady-state step
    assert (step_fold.host_dispatch_total(c1)
            - step_fold.host_dispatch_total(c0)) == 3
    assert c1["recompile_steady_state"] == c0["recompile_steady_state"]


def test_fold_zero_recompiles_under_guard_raise(monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    net, x, y = _mlp(17)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    mx.random.seed(2)
    _run_folded(program, x, y, 1)   # builds, then arms the guard
    _run_folded(program, x, y, 4)   # must not raise CompileGuardError
    assert program.folded


# ---------------------------------------------------------------------------
# escape hatches / fallbacks
# ---------------------------------------------------------------------------


def test_fold_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("MXNET_STEP_FOLD", "0")
    net, x, y = _mlp(19, dropout=0.0, bn=False)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    assert not program.folded and "MXNET_STEP_FOLD" in program.fallback_reason
    c0 = profiler.counters()
    loss = program(x, y)   # still works — eager path
    assert np.isfinite(float(loss.mean().asscalar()))
    c1 = profiler.counters()
    assert c1["step_fold_call"] == c0["step_fold_call"]
    # every eager execution through the program counts
    assert c1["step_fold_fallback"] == c0["step_fold_fallback"] + 1


def test_fold_block_opt_out():
    net, x, y = _mlp(23, dropout=0.0, bn=False)
    net._step_fold_opt_out = True
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    assert not program.folded and "opt-out" in program.fallback_reason
    loss = program(x, y)
    assert np.isfinite(float(loss.mean().asscalar()))


def test_fold_unsupported_optimizer_falls_back():
    net, x, y = _mlp(29, dropout=0.0, bn=False)
    tr = gluon.Trainer(net.collect_params(), "ftrl",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    c0 = profiler.counters()["step_fold_fallback"]
    with pytest.warns(UserWarning, match="step fold disabled"):
        loss = program(x, y)
    assert not program.folded
    assert profiler.counters()["step_fold_fallback"] > c0
    assert np.isfinite(float(loss.mean().asscalar()))
    # the fallback still trains (eager step ran)
    loss2 = program(x, y)
    assert np.isfinite(float(loss2.mean().asscalar()))


def test_fold_step_fast_path_tail(monkeypatch):
    """MXNET_STEP_FOLD=1: Trainer.step folds every optimizer group into
    ONE donated dispatch (fold_update) — numerics identical."""
    net1, x, y = _mlp(31, dropout=0.0)
    tr1 = gluon.Trainer(net1.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    mx.random.seed(3)
    _run_unfused(net1, tr1, x, y, 4)

    monkeypatch.setenv("MXNET_STEP_FOLD", "1")
    net2, x2, y2 = _mlp(31, dropout=0.0)
    tr2 = gluon.Trainer(net2.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    c0 = profiler.counters()
    mx.random.seed(3)
    _run_unfused(net2, tr2, x2, y2, 4)
    c1 = profiler.counters()
    assert c1["fused_step_call"] - c0["fused_step_call"] == 4
    _assert_params_equal(net1, net2)


# ---------------------------------------------------------------------------
# the grad-readiness overlap hook
# ---------------------------------------------------------------------------


def _overlap_net(seed, kv):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype("float32"))
    y = mx.nd.array(np.random.RandomState(1).rand(8, 4).astype("float32"))
    net(x)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=kv)
    return net, tr, x, y


def test_overlap_matches_sequential(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net1, tr1, x, y = _overlap_net(3, _BucketingStore())
    for _ in range(3):
        with autograd.record():
            loss = L2(net1(x), y)
        loss.backward()
        tr1.step(8)

    net2, tr2, x2, y2 = _overlap_net(3, _BucketingStore())
    c0 = profiler.counters()["allreduce_overlap_launched"]
    for _ in range(3):
        with autograd.record():
            loss = L2(net2(x2), y2)
        tr2.backward(loss)   # buckets launch DURING this call
        tr2.step(8)
    launched = profiler.counters()["allreduce_overlap_launched"] - c0
    assert launched >= 6   # several buckets per step actually overlapped
    _assert_params_equal(net1, net2, rtol=1e-6, atol=1e-7)


def test_overlap_hook_fires_during_backward(monkeypatch):
    """Buckets must launch BEFORE backward returns — asserted by spying
    execute_bucket from inside the hook window."""
    from incubator_mxnet_tpu import kvstore as kv_mod

    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(5, _BucketingStore())
    seen = []
    orig = kv_mod.execute_bucket

    def spy(kv, bucket, items, policy, feedback):
        seen.append(bucket["key"])
        return orig(kv, bucket, items, policy, feedback)

    monkeypatch.setattr(kv_mod, "execute_bucket", spy)
    # trainer.backward resolves execute_bucket through the kv_mod facade
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)
    assert len(seen) >= 2, "no buckets launched from the readiness hook"
    tr.step(8)


def test_overlap_disabled_env(monkeypatch):
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    monkeypatch.setenv("MXNET_ALLREDUCE_OVERLAP", "0")
    net, tr, x, y = _overlap_net(7, _BucketingStore())
    c0 = profiler.counters()["allreduce_overlap_launched"]
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)   # plain backward
    tr.step(8)
    assert profiler.counters()["allreduce_overlap_launched"] == c0


def test_overlap_dropped_bucket_reply_errors_loudly(monkeypatch):
    """PR 5 fault-injection tier: a dropped bucket reply during backward
    raises out of Trainer.backward, and the failed bucket's grads keep
    their pre-exchange values (never half-written)."""
    from incubator_mxnet_tpu.utils import faultinject

    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(9, _BucketingStore())
    with autograd.record():
        loss = L2(net(x), y)
    faultinject.configure("kvstore.bucket_drop_reply:n=1")
    try:
        with pytest.raises(ConnectionError):
            tr.backward(loss)
    finally:
        faultinject.configure("")
    # the step is poisoned for the failed bucket only; a FRESH backward
    # must recover cleanly end to end
    for p in net.collect_params().values():
        p.zero_grad()
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)
    tr.step(8)
    assert np.isfinite(float(loss.mean().asscalar()))


def test_overlap_stale_plan_discarded(monkeypatch):
    """An overlap backward whose step() never ran must NOT poison the
    next plain-backward step: the versions recorded at launch no longer
    match, so step() discards the plan and re-reduces EVERY bucket."""
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "2048")
    net, tr, x, y = _overlap_net(13, _BucketingStore())
    with autograd.record():
        loss = L2(net(x), y)
    tr.backward(loss)      # plan stored, buckets pushed ... and abandoned
    with autograd.record():
        loss = L2(net(x), y)
    loss.backward()        # fresh grads, plain backward
    c0 = profiler.counters()["allreduce_bucket"]
    tr.step(8)             # stale plan must be discarded → full re-reduce
    executed = profiler.counters()["allreduce_bucket"] - c0
    assert executed >= 3, f"stale overlap plan skipped buckets ({executed})"


def test_grad_ready_hook_order_and_parity():
    """The hook finalizes leaves in reverse-layer order mid-walk, with
    gradients exactly equal to a hookless backward."""
    net, _, x, y = _overlap_net(11, None)
    params = _params_of(net)
    with autograd.record():
        loss = L2(net(x), y)
    loss.backward()
    ref = {p.name: p.grad().asnumpy().copy() for p in params}
    for p in params:
        p.zero_grad()
    order = []
    id2name = {id(p._data): p.name for p in params}
    with autograd.record():
        loss = L2(net(x), y)
    autograd.backward(
        [loss], grad_ready_hook=lambda leaf: order.append(id2name[id(leaf)]))
    for p in params:
        np.testing.assert_allclose(ref[p.name], p.grad().asnumpy(),
                                   rtol=1e-6, atol=0, err_msg=p.name)
    assert set(order) == set(ref)
    # last layer's weight must be ready before the first layer's
    assert order.index("dense5_weight" if "dense5_weight" in ref
                       else sorted(ref)[-2]) < order.index(sorted(ref)[0]) \
        or order[0] != sorted(ref)[0]


# ---------------------------------------------------------------------------
# 2-process tiers (launch_local, like tests/test_dist.py)
# ---------------------------------------------------------------------------
# the K-step fold (ISSUE 17): one dispatch per K logical steps
# ---------------------------------------------------------------------------


def _window(nd, k):
    """[K, batch, ...] stacked window of the same batch — the
    stage_window layout."""
    return mx.nd.array(np.repeat(nd.asnumpy()[None], k, axis=0),
                       dtype=str(nd.dtype))


def _states_np(tr):
    from incubator_mxnet_tpu.gluon.trainer import _states_to_numpy
    return {i: _states_to_numpy(st) for i, st in sorted(tr._states.items())}


def _assert_states_bit_exact(a, b):
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for va, vb in zip(la, lb):
        assert np.array_equal(np.asarray(va), np.asarray(vb)), \
            "optimizer state diverged between folded widths"


def _window_losses(fold, xw, yw):
    """Per-logical-step mean losses from one [K, ...] window dispatch."""
    out = np.asarray(fold(xw, yw).asnumpy(), dtype=np.float64)
    return list(out.reshape(out.shape[0], -1).mean(axis=1))


@pytest.mark.parametrize("k", [1, 2, 4])
def test_fold_k_parity_bit_exact(k):
    """K-window fold == K unfolded (single-step folded) steps, BIT-exact:
    per-step losses, params incl. BN aux, dropout PRNG streams, and Adam
    opt-state.  The scan body IS the K=1 program — same key/hyper staging
    order, so np.array_equal, not allclose."""
    steps = 4
    net1, x, y = _mlp(61)
    tr1 = gluon.Trainer(net1.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    ref = tr1.fold_step(lambda a, b: L2(net1(a), b), block=net1)
    mx.random.seed(55)
    l1 = [float(np.asarray(ref(x, y).asnumpy(), np.float64).mean())
          for _ in range(steps)]
    assert ref.folded, ref.fallback_reason

    net2, x2, y2 = _mlp(61)
    tr2 = gluon.Trainer(net2.collect_params(), "adam",
                        {"learning_rate": 0.01}, kvstore=None)
    fold = tr2.fold_steps(lambda a, b: L2(net2(a), b), k=k, block=net2)
    assert fold.k == k
    mx.random.seed(55)
    if k == 1:
        l2 = [float(np.asarray(fold(x2, y2).asnumpy(), np.float64).mean())
              for _ in range(steps)]
    else:
        xw, yw = _window(x2, k), _window(y2, k)
        l2 = []
        for _ in range(steps // k):
            l2.extend(_window_losses(fold, xw, yw))
    assert fold.folded, fold.fallback_reason
    assert fold.logical_steps == steps

    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))
    for pa, pb in zip(_params_of(net1), _params_of(net2)):
        assert np.array_equal(pa.data().asnumpy(), pb.data().asnumpy()), \
            f"{pa.name} vs {pb.name}"
    _assert_states_bit_exact(_states_np(tr1), _states_np(tr2))


@pytest.mark.parametrize("k", [2, 4])
def test_fold_k_parity_mixed_groups(k):
    """Mixed fp32 + bf16 fused groups through the K-step scan: bit-exact
    vs the K=1 folded program (both run identical group adapters)."""
    def build():
        mx.random.seed(11)
        np.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        x = mx.nd.array(np.random.RandomState(0).rand(4, 6).astype("float32"))
        y = mx.nd.array(np.random.RandomState(1).rand(4, 4).astype("float32"))
        net(x)
        for p in net[1].collect_params().values():
            p.cast("bfloat16")
        return net, x, y

    net1, x, y = build()
    tr1 = gluon.Trainer(net1.collect_params(), "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9},
                        kvstore=None)
    ref = tr1.fold_step(lambda a, b: L2(net1(a), b), block=net1)
    mx.random.seed(5)
    _run_folded(ref, x, y, k)
    assert ref.folded, ref.fallback_reason

    net2, x2, y2 = build()
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.05, "momentum": 0.9},
                        kvstore=None)
    fold = tr2.fold_steps(lambda a, b: L2(net2(a), b), k=k, block=net2)
    mx.random.seed(5)
    fold(_window(x2, k), _window(y2, k))
    assert fold.folded, fold.fallback_reason
    for pa, pb in zip(_params_of(net1), _params_of(net2)):
        assert np.array_equal(pa.data().asnumpy(), pb.data().asnumpy()), \
            f"{pa.name} vs {pb.name}"
    _assert_states_bit_exact(_states_np(tr1), _states_np(tr2))


def test_fold_k_dispatch_count_ceil():
    """N logical steps through a K=4 fold land in EXACTLY ceil(N/K)
    dispatches — full windows plus one shorter tail window."""
    net, x, y = _mlp(67, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=4, block=net)
    xw, yw = _window(x, 4), _window(y, 4)
    xt, yt = _window(x, 2), _window(y, 2)
    mx.random.seed(9)
    fold(xw, yw)   # warmup: build the full-window program
    fold(xt, yt)   # ... and the tail-window program
    assert fold.folded, fold.fallback_reason
    c0 = profiler.counters()
    # N=10 logical steps at K=4: two full windows + one 2-step tail
    fold(xw, yw)
    fold(xw, yw)
    fold(xt, yt)
    c1 = profiler.counters()
    assert c1["step_fold_call"] - c0["step_fold_call"] == 3  # == ceil(10/4)
    assert (step_fold.host_dispatch_total(c1)
            - step_fold.host_dispatch_total(c0)) == 3
    assert c1["recompile_steady_state"] == c0["recompile_steady_state"]
    assert fold.logical_steps == 16  # 6 warmup + 10 measured


def test_fold_k_zero_recompiles_under_guard_raise(monkeypatch):
    """Steady-state K-windows, a shorter tail window, and the step_one
    escape hatch all stay silent under MXNET_COMPILE_GUARD=raise (tail
    and step_one programs register as declared warmups)."""
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    net, x, y = _mlp(71, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=4, block=net)
    xw, yw = _window(x, 4), _window(y, 4)
    mx.random.seed(10)
    fold(xw, yw)                    # builds, then arms the guard
    for _ in range(3):
        fold(xw, yw)                # must not raise CompileGuardError
    fold(_window(x, 3), _window(y, 3))   # tail: declared warmup
    for _ in range(4):
        fold.step_one(x, y)         # escape hatch: declared warmup
    assert fold.folded, fold.fallback_reason


def test_fold_k_mid_window_save_refusal_and_cursor():
    """save_states refuses between K boundaries with a clear error; at a
    boundary the payload carries the fold cursor and load_states restores
    the logical-step count (PR 16 exact resume through RunCheckpoint)."""
    import tempfile

    net, x, y = _mlp(73, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=3, block=net)
    mx.random.seed(21)
    for _ in range(3):
        fold.step_one(x, y)         # one full window -> back on boundary
    assert fold.window_pos == 0 and fold.logical_steps == 3
    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, "trainer.states")
        tr.save_states(fname)       # boundary: allowed
        fold.step_one(x, y)         # 1 past the boundary
        assert fold.window_pos == 1
        with pytest.raises(RuntimeError, match="K boundar"):
            tr.save_states(fname)
        fold.step_one(x, y)
        fold.step_one(x, y)         # back on a boundary
        assert fold.window_pos == 0 and fold.logical_steps == 6
        tr.save_states(fname)
        for _ in range(3):
            fold.step_one(x, y)     # advance past the snapshot...
        assert fold.logical_steps == 9
        tr.load_states(fname)       # ...and restore the cursor
    assert fold.logical_steps == 6 and fold.window_pos == 0


def test_fold_k_one_reduces_to_single_step_program():
    """K=1 (the MXNET_STEP_FOLD_K default) must BE the PR 15 program:
    same compile site, one dispatch per step, no window ceremony."""
    net, x, y = _mlp(79, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=1, block=net)
    mx.random.seed(31)
    fold(x, y)
    c0 = profiler.counters()
    fold(x, y)
    c1 = profiler.counters()
    assert fold.k == 1 and fold.folded
    assert c1["step_fold_call"] - c0["step_fold_call"] == 1
    assert (step_fold.host_dispatch_total(c1)
            - step_fold.host_dispatch_total(c0)) == 1


def test_fold_k_env_default(monkeypatch):
    """MXNET_STEP_FOLD_K configures the default fold width."""
    monkeypatch.setenv("MXNET_STEP_FOLD_K", "4")
    net, x, y = _mlp(83, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), block=net)
    assert fold.k == 4


def test_fold_fallback_reason_labels(monkeypatch):
    """step_fold_fallback carries a per-reason label, surfaced through
    counter_labels() and the metrics provider (docs/observability.md)."""
    monkeypatch.setenv("MXNET_STEP_FOLD", "0")
    net, x, y = _mlp(89, dropout=0.0, bn=False)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    base = dict(profiler.counter_labels("step_fold_fallback") or {})
    program(x, y)
    labels = profiler.counter_labels("step_fold_fallback")
    assert labels.get("env-off", 0) == base.get("env-off", 0) + 1
    monkeypatch.delenv("MXNET_STEP_FOLD")

    net2, x2, y2 = _mlp(89, dropout=0.0, bn=False)
    tr2 = gluon.Trainer(net2.collect_params(), "ftrl",
                        {"learning_rate": 0.05}, kvstore=None)
    program2 = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    with pytest.warns(UserWarning, match="step fold disabled"):
        program2(x2, y2)
    labels = profiler.counter_labels("step_fold_fallback")
    assert labels.get("unsupported-optimizer", 0) \
        == base.get("unsupported-optimizer", 0) + 1
    # every label the fold can emit is a known, documented reason
    for lbl in labels:
        assert lbl in step_fold.FALLBACK_LABELS, lbl
    snap = profiler.metrics_snapshot()
    assert "step_fold_fallback" in snap.get("counter_labels", {})


def test_stage_window_feeds_fold():
    """io.DataPipeline.stage_window(k) hands the fold [K, batch, ...]
    stacked windows (epoch tail shorter), and N source batches land in
    exactly ceil(N/K) fold dispatches."""
    from incubator_mxnet_tpu.io import DataPipeline, NDArrayIter

    rs = np.random.RandomState(3)
    xs = rs.rand(40, 6).astype("float32")      # 10 batches of 4
    ys = rs.rand(40, 4).astype("float32")
    net, _, _ = _mlp(97, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=4, block=net)
    pipe = DataPipeline(NDArrayIter(xs, ys, batch_size=4))
    try:
        mx.random.seed(12)
        widths, calls = [], 0
        while True:
            try:
                window = pipe.stage_window(4)
            except StopIteration:
                break
            data, label = window.data[0], window.label[0]
            widths.append(int(data.shape[0]))
            fold(data, label)
            calls += 1
        assert widths == [4, 4, 2]             # epoch tail is shorter
        assert calls == 3                      # == ceil(10/4)
        assert fold.logical_steps == 10
        assert fold.folded, fold.fallback_reason
        assert pipe.window == 4
        assert pipe.stats()["batches"] >= 10   # logical-batch accounting
    finally:
        pipe.close()


def test_fold_eval_parity_and_single_read():
    """fold_eval accumulates in-program (eval mode: BN running stats,
    dropout identity) and reads the host ONCE per pass; the mean matches
    the eager eval-mode loss, and a [K, ...] window run matches K
    per-batch calls exactly."""
    net, x, y = _mlp(101, dropout=0.5)
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=None)

    with autograd.pause():
        ref = float(np.asarray(L2(net(x), y).asnumpy(),
                               np.float64).mean())

    ev = tr.fold_eval(lambda a, b: L2(net(a), b), block=net)
    c0 = profiler.counters()
    for _ in range(3):
        ev(x, y)
    c1 = profiler.counters()
    got = ev.result()
    assert c1["fold_eval_call"] - c0["fold_eval_call"] == 3
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-8)

    # K=4 windowed eval: one dispatch per window, same accumulator math
    ev4 = tr.fold_eval(lambda a, b: L2(net(a), b), block=net, k=4)
    xw, yw = _window(x, 4), _window(y, 4)
    c0 = profiler.counters()
    ev4(xw, yw)
    c1 = profiler.counters()
    assert c1["fold_eval_call"] - c0["fold_eval_call"] == 1
    np.testing.assert_allclose(ev4.result(), ref, rtol=1e-6, atol=1e-8)
    assert ev.folded and ev4.folded


def test_fold_eval_no_recompile_under_guard_raise(monkeypatch):
    """Eval builds are declared warmups: creating/running fold_eval after
    the TRAIN guard armed must not raise in raise mode."""
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    net, x, y = _mlp(103, dropout=0.0)
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 0.01}, kvstore=None)
    fold = tr.fold_steps(lambda a, b: L2(net(a), b), k=2, block=net)
    xw, yw = _window(x, 2), _window(y, 2)
    mx.random.seed(41)
    fold(xw, yw)                 # builds + arms gluon.step_fold_k
    fold(xw, yw)
    ev = tr.fold_eval(lambda a, b: L2(net(a), b), block=net)
    ev(x, y)                     # fresh eval build: declared warmup
    ev(x, y)                     # steady state: cached, no compile
    assert np.isfinite(ev.result())
    assert fold.folded and ev.folded


# ---------------------------------------------------------------------------

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_dist_in_fold_two_workers():
    """The IN-FOLD gradient exchange (per-bucket psum nodes inside one
    shard_map'd compiled step) trains to the out-of-fold trajectory at
    process_count=2, with zero steady-state recompiles."""
    import subprocess

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch_local.py"),
         "-n", "2", sys.executable,
         os.path.join(ROOT, "tests", "fold_worker.py")],
        env=env, capture_output=True, text=True, timeout=280)
    sys.stdout.write(proc.stdout[-4000:])
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, f"fold workers failed (rc={proc.returncode})"
    assert proc.stdout.count("all assertions passed") == 2


@pytest.mark.slow
def test_dist_overlap_two_workers():
    """The out-of-fold overlap path at process_count=2: hooked pushpulls
    must converge identically to sequential allreduce-after-backward and
    not be slower beyond noise (the full acceptance — overlap strictly
    faster — is the opperf harness / evidence JSON, which runs at the
    tuned size; this keeps the wiring honest in CI)."""
    sys.path.insert(0, os.path.join(ROOT, "benchmark", "opperf"))
    import importlib

    bench = importlib.import_module("step_fold")
    res = bench.run_dist(layers=6, width=64, batch=16, iters=3, warmup=1,
                         bucket_kb=32)
    assert res["returncode"] == 0
    assert res["convergence"]["parity"], res["convergence"]
    assert res["overlap_buckets_launched"] > 0


# ---------------------------------------------------------------------------
# harness smoke
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_step_fold_bench_smoke():
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark", "opperf"))
    import importlib

    bench = importlib.import_module("step_fold")
    res = bench.run(layers=3, width=32, batch=8, iters=2, warmup=1,
                    repeats=1)
    assert res["recompiles_steady_state"] == 0
    assert res["folded_dispatches_per_step"] == 1
    assert res["steps_per_sec"]["folded"] > 0
