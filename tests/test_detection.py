"""Detection op + SSD tests (parity idioms: the reference's
test_contrib_* detection tests — numpy-reference checks for anchors,
target assignment and NMS, plus an end-to-end jitted SSD train step)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import nd


def np_iou(a, b):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    inter = np.clip(ix2 - ix1, 0, None) * np.clip(iy2 - iy1, 0, None)
    aa = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    bb = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / np.maximum(aa[:, None] + bb[None, :] - inter, 1e-12)


class TestBoxOps:
    def test_box_iou_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = np.sort(rng.rand(7, 2, 2), axis=1).transpose(0, 2, 1).reshape(7, 4).astype(np.float32)
        b = np.sort(rng.rand(5, 2, 2), axis=1).transpose(0, 2, 1).reshape(5, 4).astype(np.float32)
        got = nd.contrib.box_iou(mx.nd.array(a), mx.nd.array(b)).asnumpy()
        np.testing.assert_allclose(got, np_iou(a, b), rtol=1e-5, atol=1e-6)

    def test_multibox_prior_anchors(self):
        data = mx.nd.zeros((1, 8, 4, 4))
        anchors = nd.contrib.MultiBoxPrior(data, sizes=(0.5, 0.25), ratios=(1.0, 2.0))
        # A = 2 + 2 - 1 = 3 anchors per pixel
        assert anchors.shape == (1, 4 * 4 * 3, 4)
        a = anchors.asnumpy()[0].reshape(4, 4, 3, 4)
        # first pixel center is ((0+.5)/4, (0+.5)/4) = (.125, .125)
        np.testing.assert_allclose(
            a[0, 0, 0], [0.125 - 0.25, 0.125 - 0.25, 0.125 + 0.25, 0.125 + 0.25],
            atol=1e-6)
        # second anchor: size 0.25, ratio 1
        np.testing.assert_allclose(
            a[0, 0, 1], [0.125 - 0.125, 0.125 - 0.125, 0.25, 0.25], atol=1e-6)
        # third anchor: size 0.5, ratio 2 → w = .5·√2, h = .5/√2
        w, h = 0.5 * np.sqrt(2), 0.5 / np.sqrt(2)
        np.testing.assert_allclose(
            a[0, 0, 2], [0.125 - w / 2, 0.125 - h / 2, 0.125 + w / 2, 0.125 + h / 2],
            atol=1e-6)

    def test_multibox_target_assignment(self):
        # 3 anchors, 2 gt; anchor0 ↔ gt0 high IoU, anchor2 ↔ gt1 forced
        anchors = mx.nd.array(np.array([[[0.0, 0.0, 0.4, 0.4],
                                         [0.3, 0.3, 0.7, 0.7],
                                         [0.6, 0.6, 1.0, 1.0]]], np.float32))
        # labels: (cls, x1, y1, x2, y2); second row padding
        label = mx.nd.array(np.array([[[1, 0.02, 0.02, 0.42, 0.42],
                                       [0, 0.58, 0.58, 0.98, 0.98]]], np.float32))
        cls_pred = mx.nd.zeros((1, 3, 3))
        bt, bm, ct = nd.contrib.MultiBoxTarget(anchors, label, cls_pred,
                                               overlap_threshold=0.5)
        ct = ct.asnumpy()[0]
        assert ct[0] == 2  # gt class 1 → target 2 (bg is 0)
        assert ct[1] == 0  # background
        assert ct[2] == 1  # gt class 0 → target 1
        bm = bm.asnumpy()[0].reshape(3, 4)
        np.testing.assert_array_equal(bm[0], 1)
        np.testing.assert_array_equal(bm[1], 0)
        np.testing.assert_array_equal(bm[2], 1)
        # encoded offset for a perfectly-centred match is ~0 in cx/cy
        bt = bt.asnumpy()[0].reshape(3, 4)
        assert abs(bt[0, 0]) < 1.0 and abs(bt[0, 1]) < 1.0

    def test_box_nms_suppresses_overlaps(self):
        # records: (cls, score, x1, y1, x2, y2)
        recs = np.array([[0, 0.9, 0.0, 0.0, 0.5, 0.5],
                         [0, 0.8, 0.01, 0.01, 0.51, 0.51],   # overlaps 1st
                         [0, 0.7, 0.6, 0.6, 0.9, 0.9],
                         [1, 0.6, 0.02, 0.02, 0.52, 0.52]],  # other class
                        np.float32)[None]
        out = nd.contrib.box_nms(mx.nd.array(recs), overlap_thresh=0.5,
                                 coord_start=2, score_index=1, id_index=0).asnumpy()[0]
        kept = out[out[:, 1] > 0]
        assert len(kept) == 3
        np.testing.assert_allclose(sorted(kept[:, 1]), [0.6, 0.7, 0.9], atol=1e-6)

    def test_multibox_detection_decodes_and_nms(self):
        anchors = mx.nd.array(np.array([[[0.1, 0.1, 0.5, 0.5],
                                         [0.12, 0.12, 0.52, 0.52],
                                         [0.6, 0.6, 0.9, 0.9]]], np.float32))
        # class probs: [B, C+1, N]; anchor0/1 → class 1, anchor2 → class 2
        cls_prob = mx.nd.array(np.array([[[0.1, 0.2, 0.1],
                                          [0.8, 0.7, 0.1],
                                          [0.1, 0.1, 0.8]]], np.float32))
        loc_pred = mx.nd.zeros((1, 12))
        out = nd.contrib.MultiBoxDetection(cls_prob, loc_pred, anchors,
                                           nms_threshold=0.5).asnumpy()[0]
        valid = out[out[:, 0] >= 0]
        assert len(valid) == 2  # anchor1 suppressed by anchor0
        by_cls = {int(r[0]): r for r in valid}
        assert 0 in by_cls and 1 in by_cls
        np.testing.assert_allclose(by_cls[0][2:], [0.1, 0.1, 0.5, 0.5], atol=1e-5)
        assert abs(by_cls[0][1] - 0.8) < 1e-5

    def test_detection_ops_jit(self):
        """The whole decode+NMS pipeline must compile (static shapes)."""
        import jax
        from incubator_mxnet_tpu.ops.detection import multibox_detection

        def fn(cp, lp, an):
            return multibox_detection(cp, lp, an, nms_topk=8)

        rng = np.random.RandomState(0)
        cp = jax.nn.softmax(jax.numpy.asarray(rng.rand(2, 4, 8)), axis=1)
        lp = jax.numpy.asarray(rng.randn(2, 32).astype(np.float32) * 0.1)
        an = jax.numpy.asarray(
            np.tile(np.array([[0.1, 0.1, 0.3, 0.3]], np.float32), (8, 1))[None]
            + np.linspace(0, 0.6, 8, dtype=np.float32)[None, :, None])
        out = jax.jit(fn)(cp, lp, an)
        assert out.shape == (2, 8, 6)


class TestSSD:
    def test_ssd_forward_shapes(self):
        from incubator_mxnet_tpu.gluon.model_zoo.ssd import SSD, SSDAnchorScales
        from incubator_mxnet_tpu.gluon import nn

        feat = nn.HybridSequential()
        feat.add(nn.Conv2D(16, kernel_size=3, strides=2, padding=1))
        feat.add(nn.Activation("relu"))
        net = SSD(feat, num_classes=3, scales=SSDAnchorScales[:3], channels=32)
        net.initialize()
        x = mx.nd.zeros((2, 3, 64, 64))
        anchors, cls_preds, box_preds = net(x)
        n = anchors.shape[1]
        assert anchors.shape == (1, n, 4)
        assert cls_preds.shape == (2, n, 4)
        assert box_preds.shape == (2, n * 4)

    def test_ssd_train_step_jits(self):
        """SSD forward + MultiBoxTarget + CE/L1 loss in one jitted step."""
        import jax
        import jax.numpy as jnp
        from incubator_mxnet_tpu.gluon.model_zoo.ssd import SSD, SSDAnchorScales
        from incubator_mxnet_tpu.gluon import nn
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
        from incubator_mxnet_tpu.ndarray.ndarray import NDArray
        from incubator_mxnet_tpu.ops.detection import multibox_target
        from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce

        feat = nn.HybridSequential()
        feat.add(nn.Conv2D(8, kernel_size=3, strides=4, padding=1))
        feat.add(nn.Activation("relu"))
        net = SSD(feat, num_classes=2, scales=SSDAnchorScales[:2], channels=16)
        net.initialize()
        B = 8
        x = mx.nd.array(np.random.RandomState(0).rand(B, 3, 32, 32).astype(np.float32))
        label = np.full((B, 2, 5), -1, np.float32)
        label[:, 0] = [1, 0.1, 0.1, 0.6, 0.6]
        label = mx.nd.array(label)
        net(x)  # materialize deferred shapes

        def ssd_loss(out, lab):
            anchors, cls_preds, box_preds = out
            bt, bm, ct = multibox_target(
                anchors._data, lab._data,
                jnp.swapaxes(cls_preds._data, 1, 2))
            ce = streaming_softmax_ce(cls_preds._data, ct).mean(axis=-1)
            l1 = (jnp.abs(box_preds._data - bt) * bm).mean(axis=-1)
            return NDArray(ce + l1)

        trainer = SPMDTrainer(net, ssd_loss, "sgd", {"learning_rate": 0.01},
                              mesh=make_mesh())
        loss0 = float(trainer.step(x, label).asnumpy())
        loss1 = float(trainer.step(x, label).asnumpy())
        assert np.isfinite(loss0) and np.isfinite(loss1)
