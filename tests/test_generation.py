"""Generation tier (ISSUE 11): iteration-level continuous batching over a
device-resident slot KV cache.

The acceptance contracts:

* **equivalence** — tokens from a request decoded inside a churning
  mixed batch (requests joining and leaving around it) exactly match the
  same request decoded alone (greedy);
* **cancellation** — mid-stream ``cancel()`` frees the slot and a queued
  request takes it over;
* **zero steady-state recompiles** — a mixed-length join/leave workload
  completes with ``MXNET_COMPILE_GUARD=raise`` armed post-warmup;
* **admission control** — queue-depth load shedding raises
  ``AdmissionError`` at ``submit()``; per-tenant accounting is exported
  through the metrics provider.
"""
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (Transformer,
                                                             greedy_search)
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
from incubator_mxnet_tpu.predictor import StatefulExecutor
from incubator_mxnet_tpu.serving import (AdmissionError, GenerationServer,
                                         KVCacheLadder, ShapeBucketer,
                                         SlotKVCache)

VOCAB, BOS, EOS = 17, 1, 2


@pytest.fixture(autouse=True)
def _clean_guard():
    """Server start() arms the module-global compile guard; a leftover
    armed guard would tag every later test's compiles as steady-state
    violations."""
    profiler.disarm_compile_guard()
    profiler.set_config(compile_guard=None)
    yield
    profiler.disarm_compile_guard()
    profiler.set_config(compile_guard=None)


def _materialize(net, S=8):
    net(mx.nd.array(np.ones((1, S), np.int32), dtype="int32"),
        mx.nd.array(np.ones((1, 1), np.int32), dtype="int32"))
    return net


@pytest.fixture(scope="module")
def tiny_net():
    """Untrained (but materialized) 2+2-layer transformer."""
    mx.random.seed(0)
    net = Transformer(VOCAB, units=24, hidden_size=48, num_heads=2,
                      num_encoder_layers=2, num_decoder_layers=2,
                      dropout=0.0, max_length=64)
    net.initialize()
    return _materialize(net)


@pytest.fixture(scope="module")
def trained_net():
    """Copy-with-EOS task: greedy decode of a length-8 prompt copies its
    first 7 tokens then emits EOS — diverse tokens and a REAL eos path,
    so equivalence failures can't hide behind degenerate outputs."""
    mx.random.seed(0)
    net = Transformer(VOCAB, units=24, hidden_size=48, num_heads=2,
                      num_encoder_layers=1, num_decoder_layers=1,
                      dropout=0.0, max_length=64)
    net.initialize()

    def batch(B, S, seed):
        rng = np.random.RandomState(seed)
        src = rng.randint(3, VOCAB, (B, S)).astype(np.int32)
        tgt_out = np.concatenate(
            [src[:, :-1], np.full((B, 1), EOS, np.int32)], axis=1)
        tgt_in = np.concatenate(
            [np.full((B, 1), BOS, np.int32), tgt_out[:, :-1]], axis=1)
        return src, tgt_in, tgt_out

    def loss_fn(out, label):
        return NDArray(
            streaming_softmax_ce(out._data, label._data).mean(axis=-1))

    B, S = 16, 8
    s0, t0, _ = batch(B, S, 0)
    net(mx.nd.array(s0, dtype="int32"), mx.nd.array(t0, dtype="int32"))
    trainer = SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 5e-3},
                          mesh=make_mesh())
    for i in range(150):
        src, tgt_in, tgt_out = batch(B, S, i)
        trainer.step((mx.nd.array(src, dtype="int32"),
                      mx.nd.array(tgt_in, dtype="int32")),
                     mx.nd.array(tgt_out, dtype="int32"))
    trainer.sync_to_block()
    return net


def _server(net, **kw):
    kw.setdefault("bos", BOS)
    kw.setdefault("eos", EOS)
    kw.setdefault("max_prompt_length", 16)
    kw.setdefault("max_new_tokens", 24)
    kw.setdefault("decode_buckets", [24])
    kw.setdefault("slots_per_bucket", 4)
    kw.setdefault("name", "gen_test")
    return GenerationServer(net, **kw)


def _prompt(n, seed):
    return np.random.RandomState(seed).randint(3, VOCAB, n).astype(np.int32)


# ---------------------------------------------------------------------------
# units: bucketer ceiling, slot cache, stateful executor
# ---------------------------------------------------------------------------


class TestShapeBucketerCeiling:
    def test_explicit_buckets_with_ceiling(self):
        b = ShapeBucketer(buckets=[8, 16, 32], max_length=20)
        assert b.max_length == 20
        assert b.bucket_for(17) == 32       # bucket above the ceiling is
        with pytest.raises(ValueError) as e:  # fine for lengths under it
            b.bucket_for(21)
        assert "max_length" in str(e.value)

    def test_ceiling_above_top_bucket_rejected(self):
        with pytest.raises(ValueError) as e:
            ShapeBucketer(buckets=[8, 16], max_length=64)
        assert "top bucket" in str(e.value)

    def test_default_ceiling_is_top_bucket(self):
        b = ShapeBucketer(buckets=[8, 16])
        assert b.max_length == 16
        b2 = ShapeBucketer(max_length=100, min_bucket=8)
        assert b2.max_length == 100


class TestSlotKVCache:
    def test_alloc_free_cycle(self):
        c = SlotKVCache(layers=2, slots=2, bucket=8, mem_width=8,
                        heads=2, head_dim=4)
        s0 = c.alloc("a", mem_len=3, first_token=BOS)
        s1 = c.alloc("b", mem_len=5, first_token=BOS)
        assert {s0, s1} == {0, 1} and c.n_active == 2
        assert c.alloc("c", 1, BOS) is None          # exhausted
        c.free(s0)
        assert c.n_free == 1 and c.owners[s0] is None
        assert c.mem_len[s0] == 1                    # NaN guard floor
        with pytest.raises(ValueError):
            c.free(s0)                               # double free is loud
        s2 = c.alloc("c", 2, BOS)
        assert s2 == s0 and c.joins == 3 and c.leaves == 1

    def test_ladder_walks_up_when_tight_pool_full(self):
        lad = KVCacheLadder(layers=1, heads=2, head_dim=4, mem_width=8,
                            buckets=[8, 16], slots_per_bucket=1)
        p0, _ = lad.try_alloc(6, "a", 1, BOS)
        assert p0.bucket == 8
        p1, _ = lad.try_alloc(6, "b", 1, BOS)        # 8-pool full -> 16
        assert p1.bucket == 16
        assert lad.try_alloc(6, "c", 1, BOS) is None
        with pytest.raises(ValueError):
            lad.bucket_for(17)


class TestStatefulExecutor:
    def test_state_advances_and_warms(self):
        import jax.numpy as jnp

        exe = StatefulExecutor({"x": jnp.zeros(4)}, name="t",
                               compile_site="test.stateful")

        def step(state, inputs):
            x = state["x"] + inputs["d"]
            return x.sum(), {"x": x}

        exe.add_program("step", step)
        assert not exe.is_warm("step")
        s1 = exe.run("step", d=np.float32(1.0))
        assert float(s1) == 4.0 and exe.is_warm("step")
        s2 = exe.run("step", d=np.float32(1.0))
        assert float(s2) == 8.0                      # state carried over
        st = exe.compile_stats()
        assert st["calls"]["step"] == 2 and st["entries"] >= 1

    def test_dropped_state_key_is_loud(self):
        import jax.numpy as jnp

        exe = StatefulExecutor({"x": jnp.zeros(2), "y": jnp.zeros(2)})
        exe.add_program("bad", lambda s, i: (s["x"], {"x": s["x"]}))
        with pytest.raises(RuntimeError) as e:
            exe.run("bad")
        assert "y" in str(e.value)


# ---------------------------------------------------------------------------
# the server
# ---------------------------------------------------------------------------


class TestGenerationBasics:
    def test_single_request_matches_greedy_oracle(self, trained_net):
        srv = _server(trained_net)
        try:
            p = _prompt(8, 3)
            toks = srv.submit(p, max_new_tokens=12).result(timeout=60.0)
            gt, gl = greedy_search(trained_net,
                                   mx.nd.array(p[None], dtype="int32"),
                                   bos=BOS, eos=EOS, max_length=13)
            # greedy_search tokens include the BOS prime at position 0
            want = gt[0, 1:int(gl[0])]
            np.testing.assert_array_equal(toks, want)
            assert toks[-1] == EOS
        finally:
            srv.close()

    def test_finish_reasons_and_latency_fields(self, trained_net):
        srv = _server(trained_net)
        try:
            res_eos = srv.submit(_prompt(8, 4))
            res_len = srv.submit(_prompt(8, 5), max_new_tokens=3)
            assert res_eos.result(60.0)[-1] == EOS
            assert res_eos.finish_reason == "eos"
            assert len(res_len.result(60.0)) == 3
            assert res_len.finish_reason == "length"
            assert res_eos.ttft_ms is not None and res_eos.ttft_ms > 0
            assert res_eos.tpot_ms is not None
        finally:
            srv.close()

    def test_submit_rejects_oversized_at_the_door(self, tiny_net):
        srv = _server(tiny_net)
        try:
            with pytest.raises(ValueError) as e:
                srv.submit(_prompt(17, 0))           # prompt ceiling is 16
            assert "max_prompt_length" in str(e.value)
            with pytest.raises(ValueError) as e:
                srv.submit(_prompt(4, 0), max_new_tokens=25)
            assert "decode ladder" in str(e.value)
            with pytest.raises(ValueError):
                srv.submit(np.zeros(0, np.int32))
            with pytest.raises(ValueError):
                srv.submit(_prompt(4, 0), tenant="nope")
        finally:
            srv.close()

    def test_streaming_yields_before_done(self, tiny_net):
        srv = _server(tiny_net)
        try:
            res = srv.submit(_prompt(6, 7), max_new_tokens=24)
            seen = []
            for tok in res.stream(timeout=30.0):
                if not seen:
                    assert not res.done()            # mid-stream, not a
                seen.append(tok)                     # batch done+replay
            assert len(seen) >= 1
            np.testing.assert_array_equal(seen, res.result(1.0))
        finally:
            srv.close()

    def test_on_token_callback(self, tiny_net):
        srv = _server(tiny_net)
        try:
            got = []
            res = srv.submit(_prompt(6, 8), max_new_tokens=5,
                             on_token=lambda r, t: got.append(t))
            toks = res.result(60.0)
            np.testing.assert_array_equal(got, toks)
        finally:
            srv.close()


class TestContinuousBatchingEquivalence:
    def test_churning_mixed_batch_matches_alone(self, trained_net):
        """THE acceptance contract: the target request's tokens must be
        bit-identical whether it decodes alone or inside a batch with
        requests of other lengths joining and leaving around it."""
        srv = _server(trained_net, slots_per_bucket=3,
                      max_prefills_per_iter=2)
        try:
            target = _prompt(8, 42)
            alone = srv.submit(target, max_new_tokens=20).result(60.0)

            # churn: 3 slots, 9 live requests with staggered lifetimes
            # (mixed prompt lengths AND mixed max_new), target in the
            # middle of the wave — joins and leaves happen around it
            others = [srv.submit(_prompt(3 + (i % 9), 100 + i),
                                 max_new_tokens=3 + 2 * i)
                      for i in range(4)]
            res_t = srv.submit(target, max_new_tokens=20)
            others += [srv.submit(_prompt(3 + (i % 9), 200 + i),
                                  max_new_tokens=3 + 2 * i)
                       for i in range(4)]
            churned = res_t.result(120.0)
            for r in others:
                r.result(120.0)
            np.testing.assert_array_equal(churned, alone)
            st = srv.stats()
            assert st["completed"] == 10
            # the batch genuinely churned: more joins than slots
            joins = sum(p["joins"] for p in st["pools"].values())
            assert joins == 10 > srv._ladder.n_slots
        finally:
            srv.close()

    def test_static_mode_also_correct(self, trained_net):
        """Drain-and-refill (the benchmark baseline) produces the same
        tokens — it is slower, not different."""
        srv = _server(trained_net, batching="static", slots_per_bucket=2)
        try:
            target = _prompt(8, 42)
            alone = srv.submit(target, max_new_tokens=20).result(60.0)
            rs = [srv.submit(_prompt(5, 300 + i), max_new_tokens=6)
                  for i in range(3)]
            res = srv.submit(target, max_new_tokens=20)
            np.testing.assert_array_equal(res.result(120.0), alone)
            for r in rs:
                r.result(120.0)
        finally:
            srv.close()


class TestCancellation:
    def test_cancel_frees_slot_for_queued_request(self, tiny_net):
        """Mid-stream cancellation: the slot comes back and the queued
        request takes it over (the disconnected-client contract)."""
        srv = _server(tiny_net, slots_per_bucket=1, decode_buckets=[24])
        try:
            a = srv.submit(_prompt(6, 1), max_new_tokens=24)
            b = srv.submit(_prompt(6, 2), max_new_tokens=4)  # queued: 1 slot
            it = a.stream(timeout=30.0)
            next(it)
            next(it)
            a.cancel()
            b_toks = b.result(60.0)                  # b got the slot
            assert len(b_toks) == 4
            with pytest.raises(StopIteration):       # a's stream ended
                while True:
                    next(it)
            assert a.finish_reason == "cancelled"
            assert a.cancelled() and len(a.tokens_so_far()) < 24
            st = srv.stats()
            assert st["active_slots"] == 0
            assert st["tenants"]["default"]["cancelled"] == 1
        finally:
            srv.close()


class TestAdmissionControl:
    def test_queue_depth_load_shedding(self, tiny_net):
        srv = _server(tiny_net,
                      tenants={"capped": dict(max_queue=2, max_slots=0)})
        try:
            c0 = profiler.counters()["generation_shed"]
            srv.submit(_prompt(4, 0), tenant="capped")
            srv.submit(_prompt(4, 1), tenant="capped")
            with pytest.raises(AdmissionError):
                srv.submit(_prompt(4, 2), tenant="capped")
            assert profiler.counters()["generation_shed"] == c0 + 1
            st = srv.stats()["tenants"]["capped"]
            assert st["shed"] == 1 and st["submitted"] == 2
            # default tenant is unaffected by the capped tenant's backlog
            assert len(srv.submit(_prompt(4, 3), max_new_tokens=2)
                       .result(60.0)) == 2
        finally:
            srv.close(drain=False)

    def test_tenant_slot_cap_respected(self, tiny_net):
        srv = _server(tiny_net, slots_per_bucket=4,
                      tenants={"small": dict(max_slots=1)})
        try:
            peak = {"v": 0}

            def watch(r, t):
                peak["v"] = max(peak["v"],
                                srv.stats()["tenants"]["small"]
                                ["active_slots"])

            rs = [srv.submit(_prompt(4, i), tenant="small",
                             max_new_tokens=6, on_token=watch)
                  for i in range(3)]
            for r in rs:
                r.result(60.0)
            assert peak["v"] == 1
        finally:
            srv.close()

    def test_per_tenant_slo_accounting(self, tiny_net):
        # an SLO of 0 ms is violated by construction — the accounting,
        # not the latency, is under test
        srv = _server(tiny_net,
                      tenants={"strict": dict(slo_ttft_ms=0.0,
                                              slo_tpot_ms=0.0)})
        try:
            c0 = profiler.counters()["generation_slo_violation"]
            srv.submit(_prompt(4, 0), tenant="strict",
                       max_new_tokens=3).result(60.0)
            srv.submit(_prompt(4, 1), max_new_tokens=3).result(60.0)
            assert profiler.counters()["generation_slo_violation"] == c0 + 1
            assert srv.stats()["tenants"]["strict"]["slo_violations"] == 1
            assert srv.stats()["tenants"]["default"]["slo_violations"] == 0
        finally:
            srv.close()


class TestSteadyStateCompileGuard:
    def test_churn_workload_zero_recompiles_guard_raise(self, trained_net):
        """The tentpole acceptance: with the PR 9 guard armed in raise
        mode post-warmup, a mixed-length workload with requests joining
        and leaving the decode batch completes without a single compile
        — slot join/leave is pure buffer indexing."""
        profiler.set_config(compile_guard="raise")
        srv = _server(trained_net, slots_per_bucket=2)
        try:
            c0 = profiler.counters()["recompile_steady_state"]
            comp0 = srv.compile_stats()["compiles"]
            rng = np.random.RandomState(0)
            rs = []
            for i in range(12):                      # mixed, staggered
                rs.append(srv.submit(
                    _prompt(int(rng.randint(2, 16)), 1000 + i),
                    max_new_tokens=int(rng.randint(2, 24))))
                if i % 3 == 0:
                    time.sleep(0.01)                 # joins mid-decode
            for r in rs:
                r.result(120.0)                      # raise mode: a compile
            assert profiler.counters()["recompile_steady_state"] == c0
            assert srv.compile_stats()["compiles"] == comp0
            assert profiler.compile_guard_state()["armed"]
        finally:
            srv.close()

    def test_warmup_compiles_are_declared(self, tiny_net):
        profiler.reset_compiles()
        srv = _server(tiny_net, decode_buckets=[8, 24])
        try:
            reg = profiler.compile_registry()["sites"]
            assert "generation.warmup" in reg
            # 2 prompt buckets (8,16) + 2 pools x (decode+insert)
            assert reg["generation.warmup"]["count"] == 6
            assert "generation.decode" not in reg    # nothing outside warmup
        finally:
            srv.close()


class TestObservability:
    def test_metrics_provider_and_counters(self, tiny_net):
        c0 = dict(profiler.counters())
        srv = _server(tiny_net, name="gen_metrics")
        try:
            srv.submit(_prompt(5, 0), max_new_tokens=4).result(60.0)
            snap = profiler.metrics_snapshot()
            prov = snap["providers"]["gen_metrics"]
            assert prov["tenant_default_completed"] == 1
            assert prov["tenant_default_tokens"] == 4
            assert prov["active_slots"] == 0
            c = profiler.counters()
            assert c["generation_request"] == c0["generation_request"] + 1
            assert c["generation_token"] >= c0["generation_token"] + 4
            assert c["generation_slot_join"] == c0["generation_slot_join"] + 1
            assert (c["generation_slot_leave"]
                    == c0["generation_slot_leave"] + 1)
        finally:
            srv.close()
        assert "gen_metrics" not in profiler.metrics_snapshot()["providers"]

    def test_generation_spans_in_trace(self, tiny_net, tmp_path):
        srv = _server(tiny_net, name="gen_spans")
        try:
            profiler.set_config(filename=str(tmp_path / "gen_trace.json"))
            profiler.start()
            srv.submit(_prompt(5, 0), max_new_tokens=3).result(60.0)
            profiler.stop()
        finally:
            srv.close()
        import json

        with open(profiler.dump()) as f:
            names = {e.get("name") for e in json.load(f)["traceEvents"]}
        for want in ("generation.enqueue", "generation.prefill",
                     "generation.step", "generation.complete"):
            assert want in names, names


class TestLifecycle:
    def test_close_drains(self, tiny_net):
        srv = _server(tiny_net, slots_per_bucket=1)
        rs = [srv.submit(_prompt(4, i), max_new_tokens=3) for i in range(4)]
        srv.close(drain=True)
        for r in rs:
            assert len(r.result(1.0)) == 3
        with pytest.raises(RuntimeError):
            srv.submit(_prompt(4, 9))

    def test_drain_close_with_unadmittable_queue_returns(self, tiny_net):
        """A zero-slot tenant's queued request can never run: the
        scheduler must idle-wait (not busy-spin) on it, and
        close(drain=True) must fail it and return promptly instead of
        hanging until the join timeout."""
        srv = _server(tiny_net, tenants={"frozen": dict(max_slots=0)})
        res = srv.submit(_prompt(4, 0), tenant="frozen")
        time.sleep(0.2)              # scheduler parks instead of spinning
        assert srv.stats()["iterations"] <= 2
        t0 = time.perf_counter()
        srv.close(drain=True, timeout=30.0)
        assert time.perf_counter() - t0 < 10.0
        with pytest.raises(RuntimeError) as e:
            res.result(1.0)
        assert "slot-capped" in str(e.value)

    def test_close_no_drain_fails_queued(self, tiny_net):
        srv = _server(tiny_net, slots_per_bucket=1)
        rs = [srv.submit(_prompt(4, i), max_new_tokens=24)
              for i in range(4)]
        srv.close(drain=False)
        outcomes = []
        for r in rs:
            try:
                r.result(5.0)
                outcomes.append(r.finish_reason)
            except RuntimeError:
                outcomes.append("error")
        assert all(o in ("error", "cancelled", "eos", "length")
                   for o in outcomes)
        assert "error" in outcomes                  # the queued tail failed
