"""External op libraries over XLA FFI (lib_api parity:
[U:example/extensions/lib_custom_op/] loaded via mx.library.load)."""
import os
import subprocess

import numpy as np
import pytest

import incubator_mxnet_tpu as mx

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(ROOT, "native", "libmxtpu_custom_op.so")


@pytest.fixture(scope="module")
def custom_lib():
    if not os.path.exists(LIB):
        r = subprocess.run(["make", "-C", os.path.join(ROOT, "native"),
                            "libmxtpu_custom_op.so"], capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"cannot build custom op lib: {r.stderr[-300:]}")
    return LIB


def test_load_and_dispatch(custom_lib):
    names = mx.library.load(custom_lib, verbose=False)
    assert set(names) >= {"ext_square", "ext_softsign"}
    x = mx.nd.array(np.array([-2.0, 0.5, 3.0], np.float32))
    np.testing.assert_allclose(mx.nd.ext_square(x).asnumpy(), [4.0, 0.25, 9.0])
    np.testing.assert_allclose(
        mx.nd.ext_softsign(x).asnumpy(),
        [-2 / 3, 0.5 / 1.5, 3 / 4], rtol=1e-6)


def test_works_under_jit(custom_lib):
    import jax
    import jax.numpy as jnp

    mx.library.load(custom_lib, verbose=False)
    from incubator_mxnet_tpu.ops.registry import get_op

    fn = get_op("ext_square").fn

    @jax.jit
    def f(x):
        return fn(x) + 1.0

    out = f(jnp.asarray([3.0], jnp.float32))
    np.testing.assert_allclose(np.asarray(out), [10.0])
