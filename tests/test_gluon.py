"""Gluon Block/HybridBlock/Parameter/Trainer tests
(parity model: [U:tests/python/unittest/test_gluon.py])."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal

from common import with_seed


def test_parameter_basic():
    p = gluon.Parameter("weight", shape=(3, 4))
    p.initialize()
    assert p.data().shape == (3, 4)
    assert p.grad().shape == (3, 4)
    assert p.list_ctx() == [mx.current_context()]
    p.set_data(mx.nd.ones((3, 4)))
    assert_almost_equal(p.data(), np.ones((3, 4)))


def test_parameter_deferred_init():
    p = gluon.Parameter("weight", shape=(4, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(mx.DeferredInitializationError):
        p.data()
    p._finish_deferred_init((4, 7))
    assert p.data().shape == (4, 7)


def test_parameter_shape_mismatch():
    p = gluon.Parameter("weight", shape=(3, 4))
    with pytest.raises(ValueError):
        p.shape = (3, 5)


def test_dense_deferred_and_explicit():
    net = nn.Dense(8, in_units=4)
    net.initialize()
    assert net.weight.shape == (8, 4)
    net2 = nn.Dense(8)
    net2.initialize()
    out = net2(mx.nd.ones((2, 5)))
    assert out.shape == (2, 8)
    assert net2.weight.shape == (8, 5)


def test_dense_flatten_false():
    net = nn.Dense(6, flatten=False)
    net.initialize()
    out = net(mx.nd.ones((2, 3, 4)))
    assert out.shape == (2, 3, 6)


def test_collect_params_and_naming():
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(4))
        net.add(nn.Dense(2))
    params = net.collect_params()
    names = list(params.keys())
    assert all(n.startswith("model_") for n in names)
    assert any("dense0_weight" in n for n in names)
    sel = net.collect_params(".*weight")
    assert all(n.endswith("weight") for n in sel.keys())


def test_hybridize_matches_eager():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.random.uniform(shape=(3, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-6)


def test_hybridize_gradients_match():
    def make():
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="tanh"), nn.Dense(1))
        return net

    mx.random.seed(3)
    net = make()
    net.initialize(mx.init.Xavier())
    x = mx.nd.random.uniform(shape=(4, 8))
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_eager = net[0].weight.grad().asnumpy().copy()
    net.hybridize()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    g_hybrid = net[0].weight.grad().asnumpy()
    assert_almost_equal(g_eager, g_hybrid, rtol=1e-4, atol=1e-5)


def test_trainer_step_updates():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    x = mx.nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    w0 = net.weight.data().asnumpy().copy()
    trainer.step(1)
    # d loss/d w = x = 1 -> w_new = w - 1
    assert_almost_equal(net.weight.data(), w0 - 1.0, rtol=1e-5, atol=1e-6)


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(1)
    f = str(tmp_path / "trainer.states")
    tr.save_states(f)
    tr2 = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1, "momentum": 0.9})
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr2.load_states(f)
    tr2.step(1)  # should use loaded momentum


def test_conv2d_shapes():
    net = nn.Conv2D(8, kernel_size=3, padding=1)
    net.initialize()
    out = net(mx.nd.ones((2, 3, 16, 16)))
    assert out.shape == (2, 8, 16, 16)
    assert net.weight.shape == (8, 3, 3, 3)
    net = nn.Conv2D(8, kernel_size=3, strides=2)
    net.initialize()
    assert net(mx.nd.ones((2, 3, 16, 16))).shape == (2, 8, 7, 7)


def test_conv2d_groups_and_transpose():
    net = nn.Conv2D(8, kernel_size=3, padding=1, groups=2)
    net.initialize()
    assert net(mx.nd.ones((1, 4, 8, 8))).shape == (1, 8, 8, 8)
    dconv = nn.Conv2DTranspose(4, kernel_size=2, strides=2)
    dconv.initialize()
    assert dconv(mx.nd.ones((1, 3, 8, 8))).shape == (1, 4, 16, 16)


def test_pooling_layers():
    x = mx.nd.random.uniform(shape=(2, 3, 8, 8))
    assert nn.MaxPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.MaxPool2D(3, 2, ceil_mode=True)(x).shape == (2, 3, 4, 4)
    # avg pool correctness
    v = nn.AvgPool2D(2)(mx.nd.ones((1, 1, 4, 4)))
    assert_almost_equal(v, np.ones((1, 1, 2, 2)))


def test_batchnorm_train_vs_eval():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    x = mx.nd.random.normal(3, 5, shape=(4, 3, 2, 2))
    with autograd.record():
        out_train = bn(x)
    # batch-normalized output should be ~zero-mean
    assert abs(float(out_train.mean().asscalar())) < 0.2
    # eval mode uses running stats: after one update they are still close to
    # their init (mean 0, var 1 with momentum 0.9), so the output mean stays
    # far from zero — distinctly NOT batch-normalized
    out_eval = bn(x)
    assert abs(float(out_eval.mean().asscalar())) > 0.5
    # manual check: (x - running_mean)/sqrt(running_var + eps)
    rm = bn.running_mean.data().asnumpy().reshape(1, 3, 1, 1)
    rv = bn.running_var.data().asnumpy().reshape(1, 3, 1, 1)
    expect = (x.asnumpy() - rm) / np.sqrt(rv + 1e-5)
    assert_almost_equal(out_eval, expect, rtol=1e-4, atol=1e-4)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = mx.nd.array([[1, 2], [3, 4]], dtype="int32")
    out = emb(idx)
    assert out.shape == (2, 2, 4)
    assert_almost_equal(out[0, 0], emb.weight.data()[1])


def test_block_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    net(mx.nd.ones((1, 3)))  # materialize deferred shapes
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4), nn.Dense(2))
    net2.load_parameters(f)
    x = mx.nd.ones((1, 3))
    assert_almost_equal(net(x), net2(x), rtol=1e-6, atol=1e-7)


def test_sequential_getitem_len():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(3), nn.Dense(2))
    assert len(net) == 3
    assert isinstance(net[1], nn.Dense)
    assert len(net[1:]) == 2


def test_custom_hybrid_block():
    class Net(gluon.HybridBlock):
        def __init__(self, **kwargs):
            super().__init__(**kwargs)
            with self.name_scope():
                self.fc = nn.Dense(4)
                self.scale = self.params.get("scale", shape=(1,), init=mx.init.One())

        def hybrid_forward(self, F, x, scale):
            return self.fc(x) * scale

    net = Net()
    net.initialize()
    x = mx.nd.ones((2, 3))
    out = net(x)
    assert out.shape == (2, 4)
    net.hybridize()
    out2 = net(x)
    assert_almost_equal(out, out2, rtol=1e-5, atol=1e-6)


def test_grad_req_null_param_not_updated():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.weight.grad_req = "null"
    with autograd.record():
        loss = net(mx.nd.ones((1, 2))).sum()
    loss.backward()
    assert float(net.bias.grad().abs().sum().asscalar()) > 0


def test_zoneout_split_utils():
    arrs = gluon.utils.split_and_load(mx.nd.arange(0, 12).reshape((6, 2)), [mx.cpu()])
    assert len(arrs) == 1 and arrs[0].shape == (6, 2)
    total = gluon.utils.clip_global_norm([mx.nd.ones((2, 2)) * 3], 1.0)
    assert total == pytest.approx(6.0, rel=1e-4)


@with_seed()
def test_activations_block():
    x = mx.nd.array([[-1.0, 0.0, 1.0]])
    assert_almost_equal(nn.LeakyReLU(0.1)(x), np.array([[-0.1, 0.0, 1.0]]), rtol=1e-5, atol=1e-6)
    prelu = nn.PReLU()
    prelu.initialize()
    assert_almost_equal(prelu(x), np.array([[-0.25, 0.0, 1.0]]), rtol=1e-5, atol=1e-6)
    selu = nn.SELU()(x).asnumpy()
    assert selu[0, 2] == pytest.approx(1.0507, rel=1e-3)


class _Squares:
    """Module-level so spawn workers can pickle it."""

    def __len__(self):
        return 12

    def __getitem__(self, i):
        return np.full((3,), i * i, np.float32), np.int32(i)


def test_dataloader_process_workers():
    """Process-worker path (the reference's default worker model): spawn
    workers return numpy batches the parent re-wraps; order preserved."""
    from incubator_mxnet_tpu.gluon.data import dataloader as dl_mod

    loader = dl_mod.DataLoader(_Squares(), batch_size=4, num_workers=1)
    seen = []
    for data, label in loader:
        assert data.shape == (4, 3)
        seen.extend(label.asnumpy().tolist())
    assert seen == list(range(12))
    # second epoch reuses the pool
    assert sum(1 for _ in loader) == 3


class TestExportJittable:
    """Block.export_jittable — the supported pure-function export surface
    (the driver's __graft_entry__.entry builds on it)."""

    def test_matches_eager_and_jits(self):
        import jax
        import numpy as np

        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        x = mx.nd.array(np.random.RandomState(0).rand(3, 8).astype(np.float32))
        ref = net(x).asnumpy()
        fn, params = net.export_jittable()
        out = np.asarray(fn(params, x._data))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
        jitted = jax.jit(fn)
        out_j = np.asarray(jitted(params, x._data))
        np.testing.assert_allclose(out_j, ref, rtol=1e-5, atol=1e-6)
        # pure in params: zeroing the passed arrays changes the output,
        # proving the fn reads param_arrays, not the block's buffers
        zeros = [p * 0 for p in params]
        out_z = np.asarray(jitted(zeros, x._data))
        assert not np.allclose(out_z, ref)
        # and the block's own state is untouched
        np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=1e-6, atol=1e-7)

    def test_grad_flows_and_multi_output(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        mx.random.seed(1)

        class TwoHead(gluon.HybridBlock):
            def __init__(self, **kw):
                super().__init__(**kw)
                with self.name_scope():
                    self.a = nn.Dense(3)
                    self.b = nn.Dense(2)

            def forward(self, x):
                return self.a(x), self.b(x)

        net = TwoHead()
        net.initialize()
        x = mx.nd.array(np.random.RandomState(1).rand(4, 5).astype(np.float32))
        net(x)
        fn, params = net.export_jittable()

        def loss(ps, xd):
            a, b = fn(ps, xd)
            return jnp.sum(a ** 2) + jnp.sum(b ** 2)

        grads = jax.grad(loss)(params, x._data)
        assert len(grads) == len(params)
        assert all(float(jnp.abs(g).sum()) > 0 for g in grads)

    def test_unmaterialized_raises(self):
        net = nn.Dense(4)
        net.initialize()  # deferred: no forward yet → in_units unknown
        try:
            net.export_jittable()
        except ValueError as e:
            assert "materialized" in str(e)
        else:
            raise AssertionError("expected ValueError for deferred params")

    def test_training_mode_dropout(self):
        import numpy as np

        mx.random.seed(2)
        net = nn.HybridSequential()
        net.add(nn.Dense(32), nn.Dropout(0.5), nn.Dense(8))
        net.initialize()
        x = mx.nd.array(np.random.RandomState(2).rand(6, 10).astype(np.float32))
        net(x)
        fn_eval, params = net.export_jittable(training=False)
        fn_train, _ = net.export_jittable(training=True)
        a = np.asarray(fn_eval(params, x._data))
        b = np.asarray(fn_train(params, x._data))
        assert not np.allclose(a, b)  # dropout live only in training mode
        # deterministic: same key → same output
        c = np.asarray(fn_train(params, x._data))
        np.testing.assert_allclose(b, c, rtol=0, atol=0)


def test_filter_sampler():
    """gluon.data.FilterSampler (round-5 parity tail)."""
    from incubator_mxnet_tpu.gluon import data

    ds = data.SimpleDataset(list(range(10)))
    s = data.FilterSampler(lambda x: x % 2 == 0, ds)
    assert list(s) == [0, 2, 4, 6, 8]
    assert len(s) == 5
    loader = data.DataLoader(ds, batch_size=2,
                             sampler=data.FilterSampler(lambda x: x < 4, ds))
    got = [b.asnumpy().tolist() for b in loader]
    assert got == [[0, 1], [2, 3]]
