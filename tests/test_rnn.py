"""RNN cell/layer tests (parity model: gluon rnn coverage in
[U:tests/python/unittest/test_gluon_rnn.py])."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal

from common import with_seed


def test_rnn_cell_unroll():
    cell = gluon.rnn.RNNCell(8)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 8)
    assert states[0].shape == (2, 8)


def test_lstm_cell_step_and_state_info():
    cell = gluon.rnn.LSTMCell(6)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(3, 4))
    states = cell.begin_state(batch_size=3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 6)
    assert len(new_states) == 2
    info = cell.state_info(3)
    assert info[0]["shape"] == (3, 6)


def test_gru_cell():
    cell = gluon.rnn.GRUCell(6)
    cell.initialize()
    x = mx.nd.random.uniform(shape=(3, 4))
    out, states = cell(x, cell.begin_state(batch_size=3))
    assert out.shape == (3, 6)


def test_sequential_cell_stack():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(8))
    stack.add(gluon.rnn.LSTMCell(8))
    stack.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 4))
    outputs, states = stack.unroll(3, x, layout="NTC")
    assert outputs.shape == (2, 3, 8)
    assert len(states) == 4


def test_bidirectional_cell():
    bi = gluon.rnn.BidirectionalCell(gluon.rnn.LSTMCell(4), gluon.rnn.LSTMCell(4))
    bi.initialize()
    x = mx.nd.random.uniform(shape=(2, 3, 5))
    outputs, states = bi.unroll(3, x, layout="NTC")
    assert outputs.shape == (2, 3, 8)


@with_seed()
def test_fused_lstm_matches_cell_unroll():
    """The lax.scan fused layer must agree with the step-by-step cell."""
    hidden, T, B, C = 5, 4, 2, 3
    layer = gluon.rnn.LSTM(hidden, input_size=C)
    layer.initialize()
    cell = gluon.rnn.LSTMCell(hidden, input_size=C)
    cell.initialize()
    # copy weights layer -> cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    x = mx.nd.random.uniform(shape=(T, B, C))
    fused = layer(x)  # TNC
    x_ntc = mx.nd.swapaxes(x, 0, 1)
    unrolled, _ = cell.unroll(T, x_ntc, layout="NTC")
    assert_almost_equal(fused, mx.nd.swapaxes(unrolled, 0, 1), rtol=1e-4, atol=1e-5)


def test_lstm_layer_with_states_and_grad():
    layer = gluon.rnn.LSTM(8, num_layers=2)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(6, 2, 4))
    states = layer.begin_state(batch_size=2)
    out, new_states = layer(x, states)
    assert out.shape == (6, 2, 8)
    assert new_states[0].shape == (2, 2, 8)
    layer.collect_params()  # all params exist
    with autograd.record():
        out = layer(x)
        loss = out.sum()
    loss.backward()
    g = layer.l0_i2h_weight.grad()
    assert float(g.abs().sum().asscalar()) > 0


def test_gru_layer_ntc():
    layer = gluon.rnn.GRU(8, layout="NTC")
    layer.initialize()
    x = mx.nd.random.uniform(shape=(2, 6, 4))
    assert layer(x).shape == (2, 6, 8)


def test_rnn_relu_layer():
    layer = gluon.rnn.RNN(8, activation="relu")
    layer.initialize()
    x = mx.nd.random.uniform(shape=(3, 2, 4))
    assert layer(x).shape == (3, 2, 8)


def test_dropout_and_residual_cells():
    base = gluon.rnn.RNNCell(4, input_size=4)
    res = gluon.rnn.ResidualCell(base)
    res.initialize()
    x = mx.nd.random.uniform(shape=(2, 4))
    out, _ = res(x, base.begin_state(batch_size=2))
    d = gluon.rnn.DropoutCell(0.5)
    out2, _ = d(x, [])
    assert out.shape == (2, 4)
    assert out2.shape == (2, 4)


# ===========================================================================
# the reference RNN mega-op (packed flat parameter vector)
# ===========================================================================


def _pack_layer_params(layer, num_layers, dirs):
    """Flatten a gluon fused layer's named weights into the cuDNN packed
    layout the RNN mega-op consumes: all i2h/h2h weights layer-major,
    direction-minor, then all biases in the same order."""
    names = [f"{j}{i}_" for i in range(num_layers) for j in ["l", "r"][:dirs]]
    chunks = []
    for n in names:
        chunks.append(getattr(layer, f"{n}i2h_weight").data().asnumpy().ravel())
        chunks.append(getattr(layer, f"{n}h2h_weight").data().asnumpy().ravel())
    for n in names:
        chunks.append(getattr(layer, f"{n}i2h_bias").data().asnumpy().ravel())
        chunks.append(getattr(layer, f"{n}h2h_bias").data().asnumpy().ravel())
    return np.concatenate(chunks)


@with_seed()
@pytest.mark.parametrize("mode,bidirectional", [
    ("lstm", False), ("lstm", True), ("gru", True), ("rnn_tanh", False)])
def test_rnn_megaop_matches_fused_layer(mode, bidirectional):
    """mx.nd.RNN with the packed parameter vector must reproduce the gluon
    fused layer (itself validated against step-by-step cells) — stacked 2
    layers, optionally bidirectional."""
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    T, B, C, H, L = 5, 3, 4, 6, 2
    dirs = 2 if bidirectional else 1
    cls = {"lstm": gluon.rnn.LSTM, "gru": gluon.rnn.GRU}.get(mode)
    if cls is None:
        layer = gluon.rnn.RNN(H, num_layers=L, activation=mode[4:],
                              bidirectional=bidirectional, input_size=C)
    else:
        layer = cls(H, num_layers=L, bidirectional=bidirectional, input_size=C)
    layer.initialize()
    x = mx.nd.random.uniform(shape=(T, B, C))
    layer(x)  # materialize params

    flat = _pack_layer_params(layer, L, dirs)
    assert flat.size == rnn_param_size(mode, C, H, L, bidirectional)
    h0 = mx.nd.zeros((L * dirs, B, H))
    kw = dict(mode=mode, state_size=H, num_layers=L,
              bidirectional=bidirectional, state_outputs=True)
    if mode == "lstm":
        out = mx.nd.RNN(x, mx.nd.array(flat), h0, mx.nd.zeros((L * dirs, B, H)), **kw)
        assert len(out) == 3 and out[2].shape == (L * dirs, B, H)
    else:
        out = mx.nd.RNN(x, mx.nd.array(flat), h0, **kw)
        assert len(out) == 2
    expect, states = layer(x, layer.begin_state(B))
    assert_almost_equal(out[0], expect, rtol=1e-4, atol=1e-5)
    assert_almost_equal(out[1], states[0], rtol=1e-4, atol=1e-5)


def test_rnn_megaop_output_only_and_validation():
    T, B, C, H = 3, 2, 4, 5
    x = mx.nd.random.uniform(shape=(T, B, C))
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size
    n = rnn_param_size("gru", C, H)
    out = mx.nd.RNN(x, mx.nd.random.uniform(shape=(n,)), mx.nd.zeros((1, B, H)),
                    mode="gru", state_size=H, num_layers=1)
    assert out.shape == (T, B, H)  # state_outputs=False -> output alone
    with pytest.raises(ValueError):
        mx.nd.RNN(x, mx.nd.zeros((n + 1,)), mx.nd.zeros((1, B, H)),
                  mode="gru", state_size=H, num_layers=1)
    # states omitted -> zero initial states are synthesized (ONNX default)
    out_nostate = mx.nd.RNN(x, mx.nd.zeros((rnn_param_size("lstm", C, H),)),
                            mode="lstm", state_size=H)
    assert out_nostate.shape == (T, B, H)


def test_rnn_megaop_unsupported_reference_kwargs():
    """Reference signature extras with no TPU equivalent must raise with
    guidance, not TypeError or silent ignore."""
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    x = mx.nd.random.uniform(shape=(3, 2, 4))
    p = mx.nd.zeros((rnn_param_size("lstm", 4, 5),))
    for kw in ({"projection_size": 3}, {"lstm_state_clip_min": -8.0},
               {"use_sequence_length": True}):
        with pytest.raises(NotImplementedError):
            mx.nd.RNN(x, p, mode="lstm", state_size=5, **kw)


@with_seed()
def test_rnn_megaop_bf16():
    """bf16 inputs: fused path stays in bf16 and tracks the fp32 result."""
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    T, B, C, H = 5, 2, 3, 4
    n = rnn_param_size("gru", C, H)
    rng = np.random.RandomState(0)
    xv = rng.uniform(-1, 1, (T, B, C)).astype(np.float32)
    pv = rng.uniform(-0.3, 0.3, (n,)).astype(np.float32)
    out32 = mx.nd.RNN(mx.nd.array(xv), mx.nd.array(pv),
                      mode="gru", state_size=H).asnumpy()
    x16 = mx.nd.array(xv, dtype="bfloat16")
    p16 = mx.nd.array(pv, dtype="bfloat16")
    out16 = mx.nd.RNN(x16, p16, mode="gru", state_size=H)
    assert str(out16.dtype) in ("bfloat16",)
    assert_almost_equal(out16.asnumpy().astype(np.float32), out32,
                        rtol=5e-2, atol=5e-2)
