"""Eager dispatch accelerator: level-1 cached jit + level-2 op-bulking.

Covers the ISSUE 2 acceptance surface: hit/miss counting across repeated
shapes, dtype/shape re-specialization, correctness under autograd.record(),
engine.bulk flush-on-read semantics, and NaiveEngine bypassing both levels —
all asserted through the profiler counters so the observability contract is
tested too.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, profiler
from incubator_mxnet_tpu.ops import registry

nd = mx.nd


@pytest.fixture(autouse=True)
def fresh_cache():
    """Deterministic cache state per test: compile on first sighting
    (warmup=0), empty cache, zeroed counters; restore afterwards."""
    prev = registry.set_dispatch_cache(enabled=True, warmup=0)
    registry.clear_dispatch_cache()
    profiler.reset_counters()
    yield
    registry.set_dispatch_cache(enabled=prev[0], max_entries=prev[1],
                                warmup=prev[2])
    registry.clear_dispatch_cache()
    profiler.reset_counters()


def _c():
    return profiler.counters()


# ---------------------------------------------------------------------------
# level 1: cached jit dispatch
# ---------------------------------------------------------------------------


def test_hit_miss_counting_repeated_shapes():
    a = nd.array(np.ones((4, 5)))
    b = nd.array(np.ones((4, 5)))
    (a + b).wait_to_read()
    assert _c()["dispatch_cache_miss"] == 1
    assert _c()["dispatch_cache_hit"] == 0
    for _ in range(5):
        (a + b).wait_to_read()
    assert _c()["dispatch_cache_miss"] == 1  # same key: no re-specialization
    assert _c()["dispatch_cache_hit"] == 5
    assert registry.dispatch_cache_stats()["entries"] == 1


def test_shape_and_dtype_respecialization():
    a = nd.array(np.ones((4, 5)))
    (a * 2.0).wait_to_read()
    m0 = _c()["dispatch_cache_miss"]
    # new shape => new entry (miss), then hits
    b = nd.array(np.ones((8, 3)))
    (b * 2.0).wait_to_read()
    assert _c()["dispatch_cache_miss"] == m0 + 1
    (b * 2.0).wait_to_read()
    # new dtype => another entry
    c = nd.array(np.ones((8, 3)), dtype="float64") if False else \
        nd.array(np.ones((8, 3), dtype=np.int32))
    (c * 2).wait_to_read()
    assert _c()["dispatch_cache_miss"] >= m0 + 2
    assert _c()["dispatch_cache_hit"] >= 1


def test_static_kwargs_key():
    a = nd.array(np.arange(12.0).reshape(3, 4))
    s0 = a.sum(axis=0)
    s1 = a.sum(axis=1)
    assert _c()["dispatch_cache_miss"] == 2  # axis is part of the key
    np.testing.assert_allclose(s0.asnumpy(), np.arange(12.0).reshape(3, 4).sum(0))
    np.testing.assert_allclose(s1.asnumpy(), np.arange(12.0).reshape(3, 4).sum(1))
    a.sum(axis=0)
    assert _c()["dispatch_cache_hit"] == 1


def test_warmup_defers_compilation():
    registry.set_dispatch_cache(warmup=1)
    a = nd.array(np.ones((6, 6)))
    (a + 1.0).wait_to_read()
    assert registry.dispatch_cache_stats()["entries"] == 0  # first sighting: raw
    assert _c()["dispatch_cache_miss"] == 1
    (a + 1.0).wait_to_read()
    assert registry.dispatch_cache_stats()["entries"] == 1  # hot now: compiled
    (a + 1.0).wait_to_read()
    assert _c()["dispatch_cache_hit"] == 1


def test_alias_shares_cache_entry():
    assert registry.get_op("elemwise_add") is registry.get_op("broadcast_add")
    assert registry.get_op("elemwise_add").fn is registry.get_op("broadcast_add").fn
    a = nd.array(np.ones((3, 3)))
    b = nd.array(np.full((3, 3), 2.0))
    r1 = nd.broadcast_add(a, b)
    r2 = nd.elemwise_add(a, b)  # alias: same fn => same entry => hit
    assert _c()["dispatch_cache_miss"] == 1
    assert _c()["dispatch_cache_hit"] == 1
    np.testing.assert_allclose(r1.asnumpy(), r2.asnumpy())


def test_correctness_under_record():
    x = nd.array(np.arange(8.0).reshape(2, 4))
    x.attach_grad()
    for it in range(3):
        with autograd.record():
            y = ((x * 3.0) + 1.0).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), np.full((2, 4), 3.0))
    # recorded ops went through the cache: 3 distinct keys, hits on later iters
    assert _c()["dispatch_cache_miss"] == 3
    assert _c()["dispatch_cache_hit"] == 6


def test_record_matches_uncached_gradients():
    data = np.random.RandomState(0).randn(4, 3).astype(np.float32)

    def grad_of(enabled):
        registry.clear_dispatch_cache()
        registry.set_dispatch_cache(enabled=enabled, warmup=0)
        x = nd.array(data)
        x.attach_grad()
        with autograd.record():
            y = (x * x).sigmoid().sum()
        y.backward()
        return x.grad.asnumpy()

    g_cached = grad_of(True)
    g_raw = grad_of(False)
    np.testing.assert_allclose(g_cached, g_raw, rtol=1e-6)


def test_prng_ops_bypass_cache():
    x = nd.array(np.ones((64,)))
    with autograd.train_mode():
        m1 = nd.Dropout(x, p=0.5).asnumpy()
        m2 = nd.Dropout(x, p=0.5).asnumpy()
    assert (m1 != m2).any()  # randomness NOT frozen into a compiled entry
    assert _c()["dispatch_cache_bypass"] >= 2
    assert registry.dispatch_cache_stats()["entries"] == 0


def test_lru_eviction():
    registry.set_dispatch_cache(max_entries=2)
    a = nd.array(np.ones((2, 2)))
    (a + 1.0).wait_to_read()
    (a * 2.0).wait_to_read()
    (a - 3.0).wait_to_read()
    assert registry.dispatch_cache_stats()["entries"] == 2


# ---------------------------------------------------------------------------
# level 2: op-bulking
# ---------------------------------------------------------------------------


def test_bulk_defers_and_flushes_on_scope_exit():
    a = nd.array(np.full((3, 3), 2.0))
    with engine.bulk(10):
        x = a + 1.0
        y = x * 4.0
        assert _c()["bulk_flush"] == 0  # nothing read yet: still pending
        assert y.shape == (3, 3)        # metadata needs no flush
        assert _c()["bulk_flush"] == 0
    assert _c()["bulk_flush"] == 1
    assert _c()["bulk_ops_flushed"] == 2
    np.testing.assert_allclose(y.asnumpy(), np.full((3, 3), 12.0))


def test_bulk_flush_on_read():
    a = nd.array(np.ones((2, 2)))
    with engine.bulk(10):
        x = a * 5.0
        assert _c()["bulk_flush"] == 0
        np.testing.assert_allclose(x.asnumpy(), np.full((2, 2), 5.0))  # forces it
        assert _c()["bulk_flush"] == 1
        y = x + 1.0
        y.wait_to_read()  # wait_to_read is also a flush trigger
        assert _c()["bulk_flush"] == 2
    np.testing.assert_allclose(y.asnumpy(), np.full((2, 2), 6.0))


def test_bulk_flush_on_size_cap():
    a = nd.array(np.ones((2,)))
    with engine.bulk(3):
        x = a + 1.0
        x = x + 1.0
        assert _c()["bulk_flush"] == 0
        x = x + 1.0  # hits the cap
        assert _c()["bulk_flush"] == 1
        assert _c()["bulk_ops_flushed"] == 3
    np.testing.assert_allclose(x.asnumpy(), np.full((2,), 4.0))


def test_bulk_chain_matches_eager():
    rs = np.random.RandomState(1)
    data = rs.randn(4, 4).astype(np.float32)
    a = nd.array(data)
    with engine.bulk(64):
        z = ((a * 2.0 + 1.0).tanh() - 0.5).square()
    eager = ((np.tanh(data * 2.0 + 1.0)) - 0.5) ** 2
    # fused one-program execution may reassociate vs. op-at-a-time eager
    np.testing.assert_allclose(z.asnumpy(), eager, rtol=1e-5, atol=1e-6)


def test_bulk_repr_forces_flush():
    a = nd.array(np.ones((2,)))
    with engine.bulk(10):
        x = a + 41.0
        assert "42." in repr(x)
        assert _c()["bulk_flush"] == 1


def test_deferred_data_supports_direct_consumers():
    """Code that reaches into NDArray._data without going through invoke()
    (sparse kernels index/slice it, autograd adds grads, executor copies)
    must work on a pending DeferredArray: the dunders resolve-and-forward."""
    import jax.numpy as jnp

    data = np.arange(6, dtype=np.float32).reshape(2, 3)
    a = nd.array(data)
    with engine.bulk(10):
        x = a + 1.0
        raw = x._data
        assert type(raw) is engine.DeferredArray
        np.testing.assert_allclose(np.asarray(raw[0]), data[0] + 1.0)
        assert _c()["bulk_flush"] == 1  # __getitem__ forced the flush
        y = a * 2.0
        s = x._data + y._data  # both operands deferred: resolve, no host trip
        assert isinstance(s, jnp.ndarray)
        np.testing.assert_allclose(np.asarray(s), (data + 1.0) + data * 2.0)
        z = a + 0.5
        assert len(z._data) == 2
        assert float(jnp.sum(z._data == z._data)) == data.size  # __eq__ forwards
    # identity hashing must survive the __eq__ setattr (engine weakrefs
    # key pending deferreds by object identity)
    assert engine.DeferredArray.__hash__ is object.__hash__


def test_csr_row_read_inside_bulk():
    """The review repro: a CSRNDArray built from a bulk-deferred data array,
    row-sliced while still pending — exercises _data[lo:hi] on a deferred."""
    from incubator_mxnet_tpu.ndarray import sparse

    dense = np.array([[0.0, 1.0], [2.0, 0.0]], np.float32)
    vals = nd.array(np.array([1.0, 2.0], np.float32))
    indices = nd.array(np.array([1, 0], np.int64))
    indptr = nd.array(np.array([0, 1, 2], np.int64))
    with engine.bulk(10):
        d = vals * 1.0  # deferred data payload
        csr = sparse.CSRNDArray(d, indices, indptr, dense.shape)
        row = csr[0]
    np.testing.assert_allclose(row.asnumpy().ravel(), dense[0])


def test_backward_with_bulk_deferred_head_grad():
    # an out-grad built inside a bulk scope is a pending DeferredArray;
    # backward() must resolve it before seeding the tape walk
    x = nd.array(np.full((3,), 2.0))
    x.attach_grad()
    with engine.bulk(10):
        hg = nd.array(np.ones((3,))) * 0.5  # deferred
        with autograd.record():
            y = x * x
        y.backward(hg)
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((3,), 2.0))  # 2x*0.5


def test_no_grad_ops_inside_record_still_use_cache():
    # label/mask/metric math inside record() with no grad-needing inputs is
    # an ordinary eager call and must not bypass the level-1 cache
    x = nd.array(np.ones((4,)))
    x.attach_grad()
    lbl = nd.array(np.arange(4.0))
    with autograd.record():
        (lbl * 2.0).wait_to_read()  # constant op: node is None
        loss = (x * lbl).sum()
    loss.backward()
    with autograd.record():
        (lbl * 2.0).wait_to_read()  # repeat: must HIT, not raw-path
        loss = (x * lbl).sum()
    loss.backward()
    hits = _c()["dispatch_cache_hit"]
    assert hits >= 3  # second iteration: lbl*2, x*lbl, sum all cached
    np.testing.assert_allclose(x.grad.asnumpy(), np.arange(4.0))


def test_bulk_feeds_record_via_resolution():
    a = nd.array(np.full((3,), 2.0))
    with engine.bulk(10):
        pre = a * 3.0  # deferred
        w = nd.array(np.ones((3,)))
        w.attach_grad()
        with autograd.record():
            loss = (w * pre).sum()  # recording: pre must resolve first
        loss.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), np.full((3,), 6.0))
    assert _c()["bulk_flush"] >= 1


def test_nested_ops_inside_bulk_share_graph():
    # two identical scopes reuse one compiled flush program (no counter for
    # that, but results must stay correct and flushes counted per scope)
    a = nd.array(np.ones((2, 2)))
    for i in range(2):
        with engine.bulk(10):
            y = (a + 1.0) * (i + 1.0)
        y.wait_to_read()
    assert _c()["bulk_flush"] == 2


def test_np_scalar_negative_zero_not_conflated():
    # np.float32(0.0) and np.float32(-0.0) hash/compare equal; a shared
    # cache key would bake the wrong zero into the entry and flip signs
    x = nd.array(np.ones((4,)))
    pos = nd.broadcast_div(x, np.float32(0.0)).asnumpy()
    neg = nd.broadcast_div(x, np.float32(-0.0)).asnumpy()
    assert np.all(np.isposinf(pos))
    assert np.all(np.isneginf(neg))
    # same for np.float64, which subclasses python float
    pos64 = nd.broadcast_div(x, np.float64(0.0)).asnumpy()
    neg64 = nd.broadcast_div(x, np.float64(-0.0)).asnumpy()
    assert np.all(np.isposinf(pos64))
    assert np.all(np.isneginf(neg64))


def test_hybridized_block_inside_bulk_scope():
    # the CachedOp path consumes raw jax arrays directly; a pending
    # DeferredArray input must be resolved, not fed into jax.jit
    net = mx.gluon.nn.Dense(3, in_units=3)
    net.initialize()
    net.hybridize()
    x = nd.array(np.ones((2, 3), np.float32))
    with engine.bulk(8):
        y = x + 1.0  # deferred
        out = net(y)
    ref = net(nd.array(np.full((2, 3), 2.0, np.float32)))
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-6)


def test_explicit_ctx_construction_inside_bulk_places_data():
    # copyto(Context)/as_in_context(other) route through NDArray(data, ctx=…);
    # a pending deferred must be resolved there so the placement request is
    # honored rather than silently dropped
    from incubator_mxnet_tpu.context import current_context

    x = nd.array(np.ones((2, 2)))
    with engine.bulk(8):
        y = x * 2.0  # deferred
        # copy()/detach() are same-ctx: they must NOT flush the micro-graph
        kept = y.copy().detach()
        assert isinstance(kept._data, engine.DeferredArray)
        assert kept._data._concrete is None  # still pending: no flush
        z = y.copyto(current_context())  # explicit placement: flushes
        assert not isinstance(z._data, engine.DeferredArray)
    np.testing.assert_allclose(z.asnumpy(), np.full((2, 2), 2.0))
    np.testing.assert_allclose(kept.asnumpy(), np.full((2, 2), 2.0))


def test_custom_op_not_cacheable():
    from incubator_mxnet_tpu.ops.registry import _CACHEABLE_FNS, get_op

    assert get_op("Custom").fn not in _CACHEABLE_FNS


def test_static_kwarg_type_distinguishes_key():
    # 1 vs 1.0 vs True are ==/hash-equal; a shared key would replay an
    # entry compiled with the wrong baked constant (wrong promotion/dtype)
    from incubator_mxnet_tpu.ops.registry import _static_token

    toks = {_static_token(1), _static_token(1.0), _static_token(True),
            _static_token(np.float64(1.0))}
    assert len(toks) == 4
    x = nd.array(np.arange(4, dtype=np.int32))
    r_int = (x * 2).asnumpy()
    r_float = (x * 2.0).asnumpy()
    assert r_int.dtype == np.int32
    np.testing.assert_allclose(r_float, r_int)


def test_cross_thread_deferred_consumption():
    # thread B bulk-enqueues an op consuming thread A's pending deferred:
    # the foreign deferred must resolve (flushing A's queue) without B
    # holding its own queue lock — a regression here deadlocks, so run the
    # whole exchange on daemon threads with a bounded join
    import threading

    a_out, b_out, errs = {}, {}, []
    a_ready = threading.Event()
    b_done = threading.Event()

    def thread_a():
        try:
            x = nd.array(np.ones((4,)))
            with engine.bulk(16):
                a_out["d"] = x * 3.0  # stays pending: cap not hit
                a_ready.set()
                if not b_done.wait(timeout=30):
                    raise RuntimeError("thread B never finished")
        except Exception as e:  # pragma: no cover - failure diagnostics
            errs.append(e)
            a_ready.set()

    def thread_b():
        try:
            if not a_ready.wait(timeout=30):
                raise RuntimeError("thread A never produced its deferred")
            with engine.bulk(16):
                b_out["r"] = (a_out["d"] + 1.0).asnumpy()
        except Exception as e:  # pragma: no cover - failure diagnostics
            errs.append(e)
        finally:
            b_done.set()

    ta = threading.Thread(target=thread_a, daemon=True)
    tb = threading.Thread(target=thread_b, daemon=True)
    ta.start(); tb.start()
    ta.join(timeout=60); tb.join(timeout=60)
    assert not ta.is_alive() and not tb.is_alive(), "cross-thread bulk deadlock"
    assert not errs, errs
    np.testing.assert_allclose(b_out["r"], np.full((4,), 4.0))


# ---------------------------------------------------------------------------
# NaiveEngine: both levels off
# ---------------------------------------------------------------------------


def test_naive_engine_bypasses_both_levels():
    prev = engine.set_engine_type("NaiveEngine")
    try:
        a = nd.array(np.ones((3,)))
        for _ in range(3):
            (a + a).wait_to_read()
        with engine.bulk(10):
            z = a * 2.0
            assert not isinstance(z._data, engine.DeferredArray)
        z.wait_to_read()
        c = _c()
        assert c["dispatch_cache_hit"] == 0
        assert c["dispatch_cache_miss"] == 0
        assert c["bulk_flush"] == 0
        assert registry.dispatch_cache_stats()["entries"] == 0
        np.testing.assert_allclose(z.asnumpy(), np.full((3,), 2.0))
    finally:
        engine.set_engine_type(prev)


# ---------------------------------------------------------------------------
# observability + CI smoke of the microbenchmark
# ---------------------------------------------------------------------------


def test_counters_surface_in_profiler_dumps():
    a = nd.array(np.ones((2, 2)))
    (a + a).wait_to_read()
    (a + a).wait_to_read()
    text = profiler.dumps()
    assert "dispatch_cache_hit" in text
    assert "bulk_flush" in text


def test_dumps_reset_also_clears_counters():
    """dumps(reset=True) must reset everything it printed — a monitoring
    loop computing per-interval hit rates from successive dumps would
    otherwise see cumulative cache counters next to fresh marker stats."""
    a = nd.array(np.ones((2, 2)))
    (a + a).wait_to_read()
    (a + a).wait_to_read()
    assert profiler.counters()["dispatch_cache_hit"] > 0
    profiler.dumps(reset=True)
    assert all(v == 0 for v in profiler.counters().values())


def test_eager_dispatch_benchmark_smoke():
    """Tier-1-adjacent smoke of benchmark/opperf/eager_dispatch.py: tiny
    sizes, just proves the harness runs end-to-end on the CPU backend and
    emits the JSON contract (the 2x acceptance number is measured by the
    full run, not here)."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "benchmark", "opperf", "eager_dispatch.py")
    spec = importlib.util.spec_from_file_location("eager_dispatch_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    line = mod.run(n_ops=6, iters=2, shape=(4, 4), warmup=1)
    assert line["bench"] == "eager_dispatch"
    for mode in ("uncached", "cached_jit", "bulked"):
        assert line["ops_per_sec"][mode]["elemwise"] > 0
        assert line["ops_per_sec"][mode]["sgd_update"] > 0
    assert "speedup_cached" in line and "speedup_bulked" in line
