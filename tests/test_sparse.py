"""Sparse NDArray tests (parity idioms: test_sparse_ndarray.py /
test_sparse_operator.py in the reference — roundtrips, dot, retain)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray import sparse


def _dense_rs(n=6, m=4, seed=0):
    rng = np.random.RandomState(seed)
    d = rng.randn(n, m).astype(np.float32)
    d[[1, 3, 4]] = 0.0  # sparse rows
    return d


class TestRowSparse:
    def test_roundtrip(self):
        d = _dense_rs()
        rs = sparse.row_sparse_array(d)
        assert rs.stype == "row_sparse"
        assert rs.indices.asnumpy().tolist() == [0, 2, 5]
        np.testing.assert_allclose(rs.asnumpy(), d)
        np.testing.assert_allclose(rs.tostype("default").asnumpy(), d)

    def test_from_data_indices(self):
        vals = np.ones((2, 3), np.float32)
        rs = sparse.row_sparse_array((vals, [1, 4]), shape=(6, 3))
        dense = rs.asnumpy()
        assert dense[1].sum() == 3 and dense[4].sum() == 3 and dense.sum() == 6

    def test_nd_tostype(self):
        d = mx.nd.array(_dense_rs())
        rs = d.tostype("row_sparse")
        assert rs.stype == "row_sparse"
        np.testing.assert_allclose(rs.asnumpy(), d.asnumpy())

    def test_add_merges_rows(self):
        a = sparse.row_sparse_array((np.ones((2, 3), np.float32), [0, 2]), shape=(5, 3))
        b = sparse.row_sparse_array((np.ones((2, 3), np.float32) * 2, [2, 4]), shape=(5, 3))
        c = sparse.add(a, b)
        assert c.stype == "row_sparse"
        dense = c.asnumpy()
        np.testing.assert_allclose(dense[2], np.full(3, 3.0))
        np.testing.assert_allclose(dense[0], np.ones(3))
        np.testing.assert_allclose(dense[4], np.full(3, 2.0))
        assert dense[1].sum() == 0

    def test_retain(self):
        d = _dense_rs()
        rs = sparse.row_sparse_array(d)
        kept = sparse.retain(rs, [0, 5])
        dense = kept.asnumpy()
        np.testing.assert_allclose(dense[0], d[0])
        np.testing.assert_allclose(dense[5], d[5])
        assert np.abs(dense[2]).sum() == 0


class TestCSR:
    def test_roundtrip(self):
        d = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
        cs = sparse.csr_matrix(d)
        assert cs.stype == "csr"
        np.testing.assert_allclose(cs.asnumpy(), d)
        np.testing.assert_allclose(cs[1].asnumpy(), d[1])

    def test_from_triple(self):
        cs = sparse.csr_matrix((np.array([1., 2.], np.float32),
                                np.array([0, 2]), np.array([0, 1, 2])),
                               shape=(2, 3))
        np.testing.assert_allclose(cs.asnumpy(),
                                   [[1, 0, 0], [0, 0, 2]])

    def test_dot_dense(self):
        rng = np.random.RandomState(1)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(7, 3).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w))
        np.testing.assert_allclose(out.asnumpy(), d @ w, rtol=1e-5, atol=1e-5)

    def test_dot_transpose(self):
        rng = np.random.RandomState(2)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(5, 3).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w), transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), d.T @ w, rtol=1e-5, atol=1e-5)

    def test_sparse_zeros(self):
        z = sparse.zeros("csr", (4, 5))
        assert z.asnumpy().sum() == 0
        z2 = sparse.zeros("row_sparse", (4, 5))
        assert z2.asnumpy().shape == (4, 5)

    def test_dot_transpose_b(self):
        rng = np.random.RandomState(3)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(3, 7).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w), transpose_b=True)
        assert out.shape == (5, 3)
        np.testing.assert_allclose(out.asnumpy(), d @ w.T, rtol=1e-5, atol=1e-5)


class TestRowSparseLazyUpdate:
    """Lazy row_sparse optimizer semantics (parity:
    [U:src/operator/optimizer_op.cc] sparse sgd_mom/adam): rows untouched
    by a batch skip momentum decay and weight decay entirely."""

    def _embed_net(self, sparse_grad):
        from incubator_mxnet_tpu import gluon

        mx.random.seed(0)
        net = gluon.nn.Embedding(10, 4, sparse_grad=sparse_grad)
        net.initialize()
        net(mx.nd.array([[0]], dtype="int32"))  # materialize
        return net

    def _one_step(self, net, trainer, rows):
        from incubator_mxnet_tpu import autograd

        with autograd.record():
            out = net(mx.nd.array([rows], dtype="int32"))
            loss = (out * out).sum()
        loss.backward()
        trainer.step(1)

    def test_sgd_momentum_skips_untouched_rows(self):
        from incubator_mxnet_tpu import gluon

        net = self._embed_net(sparse_grad=True)
        assert net.weight.stype == "row_sparse"
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01})
        self._one_step(net, trainer, [1, 3])   # builds momentum on rows 1,3
        w_after1 = net.weight.data().asnumpy().copy()
        self._one_step(net, trainer, [2])      # touches only row 2
        w_after2 = net.weight.data().asnumpy()
        # rows 1,3 carry momentum but were NOT touched: lazy keeps them fixed
        np.testing.assert_array_equal(w_after2[1], w_after1[1])
        np.testing.assert_array_equal(w_after2[3], w_after1[3])
        assert np.abs(w_after2[2] - w_after1[2]).max() > 0

    def test_dense_counterpart_does_update_untouched_rows(self):
        from incubator_mxnet_tpu import gluon

        net = self._embed_net(sparse_grad=False)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01})
        self._one_step(net, trainer, [1, 3])
        w_after1 = net.weight.data().asnumpy().copy()
        self._one_step(net, trainer, [2])
        w_after2 = net.weight.data().asnumpy()
        # dense momentum+wd keep moving rows 1,3 even with zero grad
        assert np.abs(w_after2[1] - w_after1[1]).max() > 0

    def test_adam_lazy_state(self):
        from incubator_mxnet_tpu import gluon

        net = self._embed_net(sparse_grad=True)
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.01})
        self._one_step(net, trainer, [1, 3])
        w_after1 = net.weight.data().asnumpy().copy()
        self._one_step(net, trainer, [2])
        w_after2 = net.weight.data().asnumpy()
        np.testing.assert_array_equal(w_after2[1], w_after1[1])
        assert np.abs(w_after2[2] - w_after1[2]).max() > 0

    def test_sgd_no_momentum_skips_wd_on_untouched_rows(self):
        """The review-caught gap: plain SGD (momentum=0) with weight decay
        must also honor lazy semantics for row_sparse params."""
        from incubator_mxnet_tpu import gluon

        net = self._embed_net(sparse_grad=True)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "wd": 0.1})
        self._one_step(net, trainer, [1])
        w1 = net.weight.data().asnumpy().copy()
        self._one_step(net, trainer, [2])
        w2 = net.weight.data().asnumpy()
        np.testing.assert_array_equal(w2[1], w1[1])  # no wd decay on row 1
        assert np.abs(w2[2] - w1[2]).max() > 0


def test_compression_code_sums_exact_at_any_worker_count():
    """The cross-worker code reduction accumulates in int32 (jnp.sum's
    integer promotion), so 2-bit code sums cannot saturate regardless of
    worker count — verified by summing 300 simulated workers' int8 codes
    through the same jnp.sum path the allreduce jits."""
    import jax.numpy as jnp

    codes = jnp.ones((300, 8), dtype=jnp.int8)  # 300 workers all vote +1
    total = jnp.sum(codes, axis=0)
    assert total.dtype == jnp.int32
    assert (np.asarray(total) == 300).all()  # > int8 max, no wraparound

    import incubator_mxnet_tpu as mx

    kv = mx.kv.create("local")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(1, mx.nd.zeros((4,)))
    kv.push(1, mx.nd.array(np.array([1.0, -1.0, 0.1, 0.7], np.float32)))
    assert kv._last_wire_dtype == "int8", kv._last_wire_dtype


def test_csr_dot_bcoo_backend_matches():
    """MXNET_TPU_SPARSE_BACKEND=bcoo: jax.experimental.sparse lowering must
    agree with the gather/scatter path (incl. transpose_a)."""
    import os

    from incubator_mxnet_tpu.ndarray import sparse as sp

    rng = np.random.RandomState(0)
    dense = rng.rand(6, 5).astype(np.float32)
    dense[dense < 0.7] = 0
    csr = sp.csr_matrix(dense)
    rhs = mx.nd.array(rng.rand(5, 3).astype(np.float32))
    rhs_t = mx.nd.array(rng.rand(6, 3).astype(np.float32))
    ref = sp.dot(csr, rhs).asnumpy()
    ref_t = sp.dot(csr, rhs_t, transpose_a=True).asnumpy()
    os.environ["MXNET_TPU_SPARSE_BACKEND"] = "bcoo"
    try:
        out = sp.dot(csr, rhs).asnumpy()
        out_t = sp.dot(csr, rhs_t, transpose_a=True).asnumpy()
    finally:
        del os.environ["MXNET_TPU_SPARSE_BACKEND"]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(out_t, ref_t, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ref, dense @ rhs.asnumpy(), rtol=1e-5, atol=1e-6)
