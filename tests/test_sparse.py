"""Sparse NDArray tests (parity idioms: test_sparse_ndarray.py /
test_sparse_operator.py in the reference — roundtrips, dot, retain)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.ndarray import sparse


def _dense_rs(n=6, m=4, seed=0):
    rng = np.random.RandomState(seed)
    d = rng.randn(n, m).astype(np.float32)
    d[[1, 3, 4]] = 0.0  # sparse rows
    return d


class TestRowSparse:
    def test_roundtrip(self):
        d = _dense_rs()
        rs = sparse.row_sparse_array(d)
        assert rs.stype == "row_sparse"
        assert rs.indices.asnumpy().tolist() == [0, 2, 5]
        np.testing.assert_allclose(rs.asnumpy(), d)
        np.testing.assert_allclose(rs.tostype("default").asnumpy(), d)

    def test_from_data_indices(self):
        vals = np.ones((2, 3), np.float32)
        rs = sparse.row_sparse_array((vals, [1, 4]), shape=(6, 3))
        dense = rs.asnumpy()
        assert dense[1].sum() == 3 and dense[4].sum() == 3 and dense.sum() == 6

    def test_nd_tostype(self):
        d = mx.nd.array(_dense_rs())
        rs = d.tostype("row_sparse")
        assert rs.stype == "row_sparse"
        np.testing.assert_allclose(rs.asnumpy(), d.asnumpy())

    def test_add_merges_rows(self):
        a = sparse.row_sparse_array((np.ones((2, 3), np.float32), [0, 2]), shape=(5, 3))
        b = sparse.row_sparse_array((np.ones((2, 3), np.float32) * 2, [2, 4]), shape=(5, 3))
        c = sparse.add(a, b)
        assert c.stype == "row_sparse"
        dense = c.asnumpy()
        np.testing.assert_allclose(dense[2], np.full(3, 3.0))
        np.testing.assert_allclose(dense[0], np.ones(3))
        np.testing.assert_allclose(dense[4], np.full(3, 2.0))
        assert dense[1].sum() == 0

    def test_retain(self):
        d = _dense_rs()
        rs = sparse.row_sparse_array(d)
        kept = sparse.retain(rs, [0, 5])
        dense = kept.asnumpy()
        np.testing.assert_allclose(dense[0], d[0])
        np.testing.assert_allclose(dense[5], d[5])
        assert np.abs(dense[2]).sum() == 0


class TestCSR:
    def test_roundtrip(self):
        d = np.array([[0, 1, 0], [2, 0, 3], [0, 0, 0]], np.float32)
        cs = sparse.csr_matrix(d)
        assert cs.stype == "csr"
        np.testing.assert_allclose(cs.asnumpy(), d)
        np.testing.assert_allclose(cs[1].asnumpy(), d[1])

    def test_from_triple(self):
        cs = sparse.csr_matrix((np.array([1., 2.], np.float32),
                                np.array([0, 2]), np.array([0, 1, 2])),
                               shape=(2, 3))
        np.testing.assert_allclose(cs.asnumpy(),
                                   [[1, 0, 0], [0, 0, 2]])

    def test_dot_dense(self):
        rng = np.random.RandomState(1)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(7, 3).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w))
        np.testing.assert_allclose(out.asnumpy(), d @ w, rtol=1e-5, atol=1e-5)

    def test_dot_transpose(self):
        rng = np.random.RandomState(2)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(5, 3).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w), transpose_a=True)
        np.testing.assert_allclose(out.asnumpy(), d.T @ w, rtol=1e-5, atol=1e-5)

    def test_sparse_zeros(self):
        z = sparse.zeros("csr", (4, 5))
        assert z.asnumpy().sum() == 0
        z2 = sparse.zeros("row_sparse", (4, 5))
        assert z2.asnumpy().shape == (4, 5)

    def test_dot_transpose_b(self):
        rng = np.random.RandomState(3)
        d = rng.randn(5, 7).astype(np.float32)
        d[d < 0.5] = 0
        w = rng.randn(3, 7).astype(np.float32)
        cs = sparse.csr_matrix(d)
        out = sparse.dot(cs, mx.nd.array(w), transpose_b=True)
        assert out.shape == (5, 3)
        np.testing.assert_allclose(out.asnumpy(), d @ w.T, rtol=1e-5, atol=1e-5)
