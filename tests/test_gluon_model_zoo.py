"""Model-zoo completeness tier (parity:
[U:tests/python/unittest/test_gluon_model_zoo.py] — every zoo entry must
build, initialize, and produce the right classifier shape).

Box-aware design: full numeric forwards of all 34 CNNs would take minutes
on a 1-core CPU, so every model is *materialized* at the smallest spatial
size its architecture permits (FC-over-flatten families need the real
224/299), then the full-size graph is validated with ``jax.eval_shape``
— exact shape algebra through every layer, zero FLOPs.  One
representative per family also runs a real hybridized forward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.model_zoo import vision

# (materialization size, eval size) per family; None -> same as eval
_SIZES = {
    "alexnet": (224, 224),        # Flatten->Dense pins the input size
    "vgg": (224, 224),
    "inception": (299, 299),      # stem strides assume 299
    "densenet": (224, 224),       # fixed 7x7 tail pool, not global
}


def _sizes_for(name):
    for prefix, sz in _SIZES.items():
        if name.startswith(prefix):
            return sz
    return (64, 224)  # global-pooled families: materialize tiny


_ALL = sorted(n for n in vision.__all__ if n != "get_model")


@pytest.mark.parametrize("name", _ALL)
def test_zoo_builds_and_classifier_shape(name):
    mx.random.seed(0)
    net = vision.get_model(name)
    net.initialize()
    mat, full = _sizes_for(name)
    net(mx.nd.zeros((1, 3, mat, mat)))  # materialize deferred shapes
    fn, params = net.export_jittable()
    out = jax.eval_shape(
        fn, [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in params],
        jax.ShapeDtypeStruct((2, 3, full, full), jnp.float32))
    assert tuple(out.shape) == (2, 1000), (name, out.shape)


@pytest.mark.parametrize("name", [
    "resnet18_v1", "mobilenetv2_1.0", "squeezenet1.1", "densenet121",
    "alexnet",
])
def test_zoo_representative_forward(name):
    mx.random.seed(0)
    net = vision.get_model(name)
    net.initialize()
    mat, _ = _sizes_for(name)
    x = mx.nd.array(np.random.RandomState(0).rand(1, 3, mat, mat)
                    .astype(np.float32))
    net(x)  # materialize
    net.hybridize()
    out = net(x).asnumpy()
    assert out.shape == (1, 1000)
    assert np.isfinite(out).all()


def test_zoo_classes_kwarg():
    net = vision.get_model("resnet18_v1", classes=10)
    net.initialize()
    out = net(mx.nd.zeros((2, 3, 64, 64)))
    assert out.shape == (2, 10)


def test_zoo_unknown_name():
    with pytest.raises(ValueError):
        vision.get_model("resnet999_v9")
