"""Shared test helpers (parity: [U:tests/python/unittest/common.py]).

``with_seed`` — reproducible-but-rotating RNG seeds with the seed printed on
failure, the reference's core test idiom."""
import functools
import os
import random as pyrandom

import numpy as np

import incubator_mxnet_tpu as mx


def with_seed(seed=None):
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if seed is not None:
                this_seed = seed
            elif "MXNET_TEST_SEED" in os.environ:
                this_seed = int(os.environ["MXNET_TEST_SEED"])
            else:
                this_seed = np.random.randint(0, 2 ** 31)
            np.random.seed(this_seed)
            mx.random.seed(this_seed)
            pyrandom.seed(this_seed)
            try:
                return fn(*args, **kwargs)
            except BaseException:
                print(f"*** test failed with seed {this_seed}: "
                      f"set MXNET_TEST_SEED={this_seed} to reproduce ***")
                raise

        return wrapper

    return deco
