"""Worker body for the multi-host-SHAPED tier: N processes × 4 virtual CPU
devices each, one GLOBAL mesh spanning all of them through
``parallel.init_distributed`` (jax.distributed) — the topology a real
multi-host TPU pod presents, where the mesh's outer axis crosses the DCN
boundary and collectives span processes.

Covers what tests/dist_worker.py (1 device/process, kvstore transport)
cannot: ``make_array_from_process_local_data`` batch staging, cross-process
psum inside one jitted SPMD step, and a full SPMDTrainer step whose dp axis
spans hosts.  Exact-value assertions throughout.

Invoked by tests/test_dist.py via tools/launch_local.py.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=4"

import numpy as np


def main():
    import jax

    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import parallel

    # DMLC_* env (set by launch_local.py) → jax.distributed.initialize
    parallel.init_distributed()
    rank = jax.process_index()
    nproc = jax.process_count()
    assert nproc == int(os.environ["DMLC_NUM_WORKER"]), (
        nproc, os.environ["DMLC_NUM_WORKER"])
    assert len(jax.local_devices()) == 4
    n_global = len(jax.devices())
    assert n_global == 4 * nproc, f"global devices {n_global} != {4 * nproc}"

    # --- global dp×tp mesh with dp crossing the process boundary --------
    mesh = parallel.make_mesh(tp=2)  # dp = n_global // 2 spans hosts
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    # cross-process psum inside one jitted program: every process
    # contributes its rank+1 per local device slot
    local = np.full((4, 8), float(rank + 1), np.float32)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("dp")), local)  # dp-sharded over axis 0

    @jax.jit
    def global_sum(x):
        return jnp.sum(x)

    total = float(global_sum(arr))
    # each process contributes 4*8*(rank+1) but the dp axis has
    # n_global//2 shards of 2 rows... simpler invariant: the GLOBAL array
    # concatenates the per-process local blocks over dp — total is the sum
    # over processes of 4*8*(rank+1)
    expect = sum(4 * 8 * (r + 1) for r in range(nproc))
    assert total == expect, (total, expect)

    # --- SPMDTrainer step with dp spanning hosts ------------------------
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.ndarray.ndarray import NDArray

    mx.random.seed(0)  # identical params on every process
    net = nn.Dense(4)
    net.initialize()
    net(mx.nd.zeros((2, 8)))

    def loss_fn(out, label):
        return (out - label) * (out - label)

    trainer = parallel.SPMDTrainer(net, loss_fn, "sgd",
                                   {"learning_rate": 0.1}, mesh=mesh)
    B_local = 8  # global batch = 8 * nproc, dp-sharded
    rng = np.random.RandomState(100 + rank)  # different data per host
    x_local = rng.rand(B_local, 8).astype(np.float32)
    y_local = rng.rand(B_local, 4).astype(np.float32)
    loss = trainer.step(NDArray(jnp.asarray(x_local)), NDArray(jnp.asarray(y_local)))
    val = float(np.asarray(loss._data))
    assert np.isfinite(val)
    # the updated parameters must be IDENTICAL on all processes (grad psum
    # across the dp axis, which spans hosts): gather each process's local
    # checksum onto a dp-sharded array and assert zero spread globally
    p0 = trainer._param_arrays[0]
    local_c = float(np.asarray(p0.addressable_data(0), dtype=np.float64).sum())
    dp_mesh = parallel.make_mesh()  # pure-dp over all global devices
    cs = jax.make_array_from_process_local_data(
        NamedSharding(dp_mesh, P("dp")),
        np.full((4, 1), local_c, np.float32))  # one row per local device

    @jax.jit
    def spread(x):
        return jnp.max(x) - jnp.min(x)

    s = float(spread(cs))
    assert s == 0.0, f"params diverged across hosts: spread={s}"
    print(f"rank {rank}/{nproc}: multihost assertions passed "
          f"(global_sum={total}, loss={val:.5f}, checksum={local_c:.3f})")


if __name__ == "__main__":
    main()
