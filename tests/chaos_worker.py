"""Worker for the subprocess chaos tier (tests/test_chaos.py): pushes a
fixed workload against a STANDALONE parameter server
(``python -m incubator_mxnet_tpu.kvstore.async_ps``) that the test
SIGKILLs and restarts mid-run.

Resume discipline (the idempotent-retry contract end to end): the worker
treats the SERVER's applied-push count as the source of truth — each
iteration re-reads ``counts[rank]`` and pushes only while it is below the
target.  A server crash that rolls back to an older snapshot (losing
acked-but-unsnapshotted pushes) is therefore repaired by re-pushing, and a
push can never be applied twice (the dedup window absorbs replays), so the
run ends with counts == TOTAL exactly and the accumulated value exact.

Env (set by the test): MXNET_ASYNC_PS_EXTERNAL=1, MXNET_ASYNC_PS_PORT,
DMLC_WORKER_ID, DMLC_NUM_WORKER, short MXNET_KVSTORE_REQUEST_TIMEOUT so
the kill window is crossed quickly.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

TOTAL = 30


def main():
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.kvstore import PSKeyError

    kv = mx.kv.create("dist_async")
    assert kv._server is None, "worker must NOT self-host (external PS mode)"
    rank, nw = kv.rank, kv.num_workers

    if rank == 0:
        kv.init("acc", mx.nd.zeros((4,)))
    else:
        # no barrier: under elastic membership a counting barrier is the
        # wrong sync primitive across a server restart — poll for the key
        deadline = time.monotonic() + 60
        while True:
            try:
                kv.pull("acc", out=mx.nd.zeros((4,)))
                break
            except PSKeyError:
                assert time.monotonic() < deadline, "init never appeared"
                time.sleep(0.1)

    # push until the SERVER says TOTAL of ours were applied: survives the
    # mid-run SIGKILL+restart (rollback to the last snapshot) without ever
    # over- or under-pushing
    deadline = time.monotonic() + 120
    while True:
        applied = kv.push_counts()[rank]
        if applied >= TOTAL:
            break
        assert time.monotonic() < deadline, f"rank {rank} stuck at {applied}"
        kv.push("acc", mx.nd.ones((4,)))
        time.sleep(0.04)

    # wait for every peer to finish (counts are server-authoritative)
    deadline = time.monotonic() + 120
    while True:
        counts = kv.push_counts()
        if all(c >= TOTAL for c in counts[:nw]):
            break
        assert time.monotonic() < deadline, f"peers stuck: {counts}"
        time.sleep(0.2)

    assert counts[:nw] == [TOTAL] * nw, counts
    out = mx.nd.zeros((4,))
    kv.pull("acc", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), float(TOTAL * nw)))
    kv.close()
    print(f"CHAOS_OK rank {rank} counts {counts[:nw]}", flush=True)


if __name__ == "__main__":
    main()
