"""Chaos tier: deterministic fault injection against the async PS
(``tools/ci.sh chaos``, fixed ``MXNET_FAULT_SEED``).

Every test drives the REAL recovery paths — the injected "drops" actually
close sockets (utils/faultinject.py), so what is under test is the
production reconnect/replay/dedup/eviction machinery, not mocks:

* wire faults (drop before/after send, duplicate delivery, dropped
  replies) with exactly-once push accounting,
* replay across a server kill+restart (snapshot restore + persisted dedup
  window),
* the acceptance scenario: a 2-worker SSP training run with drops+dups,
  one worker killed mid-SSP (rejoining via server-side counts), and one
  server kill+restart — completes, converges to the fault-free loss,
  no push applied twice, survivors unblocked within the eviction window,
* a subprocess tier: SIGKILL of a standalone server process mid-run,
  workers resyncing from server-authoritative counts (chaos_worker.py —
  the PS-side complement of preempt_worker.py's trainer preemption).
"""
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu.kvstore.async_ps import (
    AsyncClient, HeartbeatThread, ParameterServer, _recv_msg, _send_msg)
from incubator_mxnet_tpu.utils import faultinject

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fault_schedule_isolation():
    yield
    faultinject.configure("")  # never leak a schedule into later tests


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_drop_before_send_retries_transparently():
    ps = ParameterServer(num_workers=1, port=0)
    try:
        c = AsyncClient(*ps.address, attempt_timeout=2.0, deadline_s=30.0)
        c.request("init", "k", np.zeros(2, np.float32))
        r0 = profiler.counters()["ps_retry"]
        faultinject.configure("client.drop_before_send:n=2", seed=0)
        c.request("push", "k", np.ones(2, np.float32), 0)
        stats = faultinject.stats()
        faultinject.configure("")
        assert stats["client.drop_before_send"][1] == 2
        assert profiler.counters()["ps_retry"] >= r0 + 2
        assert c.request("counts") == [1]  # applied exactly once
        np.testing.assert_allclose(c.request("pull", "k"), [1, 1])
    finally:
        ps.stop()


def test_drop_after_send_replays_without_double_apply():
    """The hard case: the server APPLIED the push but the ack was lost.
    The replay must hit the dedup window, not the store."""
    ps = ParameterServer(num_workers=1, port=0)
    try:
        c = AsyncClient(*ps.address, attempt_timeout=2.0, deadline_s=30.0)
        c.request("init", "k", np.zeros(2, np.float32))
        d0 = profiler.counters()["ps_dedup_hit"]
        faultinject.configure("client.drop_after_send:n=1", seed=0)
        c.request("push", "k", np.ones(2, np.float32), 0)
        faultinject.configure("")
        assert c.request("counts") == [1]
        np.testing.assert_allclose(c.request("pull", "k"), [1, 1])
        assert profiler.counters()["ps_dedup_hit"] >= d0 + 1
    finally:
        ps.stop()


def test_duplicate_delivery_applies_once():
    ps = ParameterServer(num_workers=1, port=0)
    try:
        c = AsyncClient(*ps.address, attempt_timeout=2.0, deadline_s=30.0)
        c.request("init", "k", np.zeros(2, np.float32))
        faultinject.configure("client.dup_send:n=3", seed=0)
        for _ in range(3):
            c.request("push", "k", np.ones(2, np.float32), 0)
        faultinject.configure("")
        assert c.request("counts") == [3]
        np.testing.assert_allclose(c.request("pull", "k"), [3, 3])
    finally:
        ps.stop()


def test_server_dropped_reply_recovers():
    ps = ParameterServer(num_workers=1, port=0)
    try:
        c = AsyncClient(*ps.address, attempt_timeout=2.0, deadline_s=30.0)
        c.request("init", "k", np.zeros(2, np.float32))
        faultinject.configure("server.drop_reply:n=1", seed=0)
        c.request("push", "k", np.ones(2, np.float32), 0)
        faultinject.configure("")
        assert c.request("counts") == [1]
    finally:
        ps.stop()


def test_replay_across_server_restart_dedups(tmp_path):
    """A push acked+snapshotted by the old server must not re-apply when
    its (client_id, seq) is replayed against the restarted server: the
    dedup window rides the snapshot."""
    snap = str(tmp_path / "ps.snap")
    port = _free_port()
    ps = ParameterServer(num_workers=1, port=port, snapshot_path=snap,
                         snapshot_every_s=0)
    env = ("req", "restart-client", 7,
           ("push", "k", np.ones(2, np.float32), 0))
    raw = socket.create_connection(("127.0.0.1", port))
    try:
        _send_msg(raw, ("req", "restart-client", 6,
                        ("init", "k", np.zeros(2, np.float32))))
        assert _recv_msg(raw)[2] == ("ok",)
        _send_msg(raw, env)
        assert _recv_msg(raw) == ("rep", 7, ("ok",))
    finally:
        raw.close()
    ps.snapshot()
    ps.stop(final_snapshot=False)  # crash

    ps2 = ParameterServer(num_workers=1, port=port, snapshot_path=snap,
                          snapshot_every_s=0)
    raw2 = socket.create_connection(("127.0.0.1", port))
    try:
        _send_msg(raw2, env)  # the client never saw the ack: it replays
        assert _recv_msg(raw2) == ("rep", 7, ("ok",))
        c = AsyncClient("127.0.0.1", port)
        assert c.request("counts") == [1]  # NOT 2
        np.testing.assert_allclose(c.request("pull", "k"), [1, 1])
    finally:
        raw2.close()
        ps2.stop()


# ---------------------------------------------------------------------------
# Acceptance scenario (ISSUE 6): 2-worker SSP training under chaos.
# ---------------------------------------------------------------------------

_TOTAL = 40          # pushes per worker
_DIM = 4
_LR = 0.1
_STALE = 2
_LEASE = 0.5
_TARGET = np.linspace(0.5, 2.0, _DIM).astype(np.float32)


def _train_worker(port, rank, start, gaps=None, die_at=None,
                  pause_at=None, paused_evt=None, resume_evt=None,
                  errors=None):
    """One SSP worker on a strongly-convex quadratic: pull w, push
    grad = w - target (server-side SGD applies w -= lr*grad).  Any
    interleaving converges to the same optimum — the 'same loss within
    tolerance' acceptance is meaningful under chaos."""
    try:
        c = AsyncClient("127.0.0.1", port, attempt_timeout=1.0,
                        deadline_s=60.0)
        c.request("register", rank)
        hb = HeartbeatThread("127.0.0.1", port, rank, interval=_LEASE / 3)
        hb.start()
        last = time.monotonic()
        for i in range(start, _TOTAL):
            if die_at is not None and i == die_at:
                # crash, not a clean leave: heartbeats just stop
                hb.stop()
                c.close()
                return
            if pause_at is not None and i == pause_at:
                paused_evt.set()
                assert resume_evt.wait(timeout=60)
                last = time.monotonic()  # the pause is not an SSP gap
            w = np.asarray(c.request("pull", "w"), np.float32)
            c.request("push", "w", (w - _TARGET).astype(np.float32), rank)
            now = time.monotonic()
            if gaps is not None:
                gaps.append(now - last)
            last = now
        hb.stop()
        c.close()
    except Exception as e:  # surface into the test thread
        if errors is not None:
            errors.append(e)
        raise


def _run_training(port, make_server, chaos):
    """Run the 2-worker job; returns (final_w, counts).  With ``chaos``:
    wire faults on, worker 1 dies mid-SSP and rejoins from server counts,
    and the server is killed+restarted while worker 0 is at a rendezvous."""
    ps = make_server()
    admin = AsyncClient("127.0.0.1", port, attempt_timeout=1.0,
                        deadline_s=60.0)
    admin.request("init", "w", np.zeros(_DIM, np.float32))
    import pickle

    import incubator_mxnet_tpu.optimizer as opt_mod

    admin.request("set_optimizer",
                  pickle.dumps(opt_mod.create("sgd", learning_rate=_LR)))
    errors = []
    gaps_a = []
    threads = []
    try:
        if not chaos:
            for rank in (0, 1):
                t = threading.Thread(target=_train_worker,
                                     args=(port, rank, 0),
                                     kwargs={"errors": errors}, daemon=True)
                t.start()
                threads.append(t)
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive()
        else:
            faultinject.configure(
                "client.drop_before_send:p=0.04,"
                "client.drop_after_send:p=0.04,"
                "client.dup_send:p=0.06", seed=0)
            paused, resume = threading.Event(), threading.Event()
            a = threading.Thread(
                target=_train_worker, args=(port, 0, 0),
                kwargs={"gaps": gaps_a, "pause_at": 2 * _TOTAL // 3,
                        "paused_evt": paused, "resume_evt": resume,
                        "errors": errors},
                daemon=True)
            b = threading.Thread(
                target=_train_worker, args=(port, 1, 0),
                kwargs={"die_at": _TOTAL // 4, "errors": errors},
                daemon=True)
            a.start()
            b.start()
            b.join(timeout=60)          # worker 1 dies mid-SSP...
            assert not b.is_alive()
            assert paused.wait(timeout=60)   # ...worker 0 got evict-unblocked
            # worker 0 is quiescent at the rendezvous: kill the server (no
            # acked-push can land between the snapshot and the kill)
            admin.request("snapshot")
            ps.stop(final_snapshot=False)
            time.sleep(0.2)
            ps = make_server()               # reborn from the snapshot
            resume.set()
            # worker 1 "restarts": a fresh process-equivalent (new client
            # identity) resuming from the server-authoritative count
            start_b = int(AsyncClient("127.0.0.1", port, attempt_timeout=1.0,
                                      deadline_s=60.0).request("counts")[1])
            b2 = threading.Thread(target=_train_worker,
                                  args=(port, 1, start_b),
                                  kwargs={"errors": errors}, daemon=True)
            b2.start()
            for t in (a, b2):
                t.join(timeout=120)
                assert not t.is_alive()
            faultinject.configure("")
        assert not errors, errors
        admin2 = AsyncClient("127.0.0.1", port, attempt_timeout=1.0,
                             deadline_s=60.0)
        counts = admin2.request("counts")
        w = np.asarray(admin2.request("pull", "w"), np.float32)
        return w, counts, gaps_a
    finally:
        faultinject.configure("")
        ps.stop(final_snapshot=False)


def test_chaos_training_run_converges_exactly_once(tmp_path):
    """The ISSUE-6 acceptance criterion, end to end and deterministic
    (fixed fault seed): drops+dups on the wire, one worker killed mid-SSP
    (rejoins from server counts), one server kill+restart (snapshot
    restore) — the 2-worker run completes, reaches the fault-free loss
    within tolerance, applies every push exactly once, and the surviving
    pusher's longest stall stays within the eviction window."""
    port_ref = _free_port()
    w_ref, counts_ref, _ = _run_training(
        port_ref,
        lambda: ParameterServer(2, port=port_ref, staleness=_STALE,
                                lease_s=_LEASE),
        chaos=False)
    assert counts_ref == [_TOTAL, _TOTAL]
    loss_ref = float(np.max(np.abs(w_ref - _TARGET)))
    assert loss_ref < 0.05  # the fault-free run converges

    snap = str(tmp_path / "chaos.snap")
    port = _free_port()
    w_chaos, counts_chaos, gaps_a = _run_training(
        port,
        lambda: ParameterServer(2, port=port, staleness=_STALE,
                                lease_s=_LEASE, snapshot_path=snap,
                                snapshot_every_s=0),
        chaos=True)
    # no push applied twice, none lost: counts match the issued pushes
    assert counts_chaos == [_TOTAL, _TOTAL]
    # converges to the same loss as the fault-free run within tolerance
    loss_chaos = float(np.max(np.abs(w_chaos - _TARGET)))
    assert abs(loss_chaos - loss_ref) < 0.05, (loss_chaos, loss_ref)
    # the surviving pusher's longest SSP stall (worker 1's death) resolved
    # within the eviction window, not the 300 s SSP timeout: lease + reaper
    # tick + retry backoff, with margin for the server-restart reconnect
    assert gaps_a and max(gaps_a) < 8 * _LEASE + 2.0, max(gaps_a)


def test_subprocess_server_sigkill_and_resume(tmp_path):
    """Standalone-PS deployment (the restartable topology): SIGKILL the
    server process mid-run; a restarted server resumes from its periodic
    snapshot and the worker subprocesses complete with exact counts —
    the PS-side complement of preempt_worker.py's trainer preemption."""
    port = _free_port()
    snap = str(tmp_path / "ps.snap")
    server_cmd = [sys.executable, "-m",
                  "incubator_mxnet_tpu.kvstore.async_ps",
                  "--num-workers", "2", "--port", str(port),
                  "--snapshot", snap, "--snapshot-every-s", "0.2",
                  "--lease-s", "1.0"]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    def spawn_server():
        p = subprocess.Popen(server_cmd, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
        line = p.stdout.readline()
        assert "PS_READY" in line, (line, p.stderr.read() if p.poll() else "")
        return p

    srv = spawn_server()
    workers = []
    try:
        for rank in (0, 1):
            wenv = dict(env)
            wenv.update(MXNET_ASYNC_PS_EXTERNAL="1",
                        MXNET_ASYNC_PS_PORT=str(port),
                        DMLC_WORKER_ID=str(rank), DMLC_NUM_WORKER="2",
                        MXNET_KVSTORE_REQUEST_TIMEOUT="2",
                        MXNET_KVSTORE_REQUEST_DEADLINE="90")
            workers.append(subprocess.Popen(
                [sys.executable, os.path.join(ROOT, "tests",
                                              "chaos_worker.py")],
                env=wenv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True))
        time.sleep(4.0)  # workers mid-run (they pace ~25 pushes/s)
        srv.send_signal(signal.SIGKILL)
        srv.wait(timeout=10)
        time.sleep(0.5)
        srv = spawn_server()  # reborn from the periodic snapshot
        for w in workers:
            out, err = w.communicate(timeout=180)
            sys.stdout.write(out[-2000:])
            sys.stderr.write(err[-2000:])
            assert w.returncode == 0, f"worker rc={w.returncode}"
            assert "CHAOS_OK" in out
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
        if srv.poll() is None:
            srv.kill()
