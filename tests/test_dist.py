"""Multi-process distributed tier (SURVEY.md §4 'Distributed (nightly)').

Launches tests/dist_worker.py at process_count=2 through
tools/launch_local.py — the [U:tools/launch.py] --launcher local analog —
so KVStoreDist/_allreduce/compression actually execute over
jax.distributed, which single-process tests cannot cover.

Two environmental failure modes bit this tier historically, both fixed:
the CPU backend ships no cross-process collectives by default
("Multiprocess computations aren't implemented on the CPU backend") —
``parallel.mesh.init_distributed`` now selects the gloo implementation
before backend init — and the async PS listened on coordinator_port+1000,
which collided with unrelated listeners; ``launch_local.py`` now exports
a per-run ephemeral ``MXNET_ASYNC_PS_PORT`` instead.
"""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dist(n, script="dist_worker.py", marker="all assertions passed"):
    env = dict(os.environ)
    # children must boot their own CPU backend (workers set their own
    # device-count flags), not inherit the pytest 8-device virtual mesh or
    # the tunneled TPU
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch_local.py"),
         "-n", str(n), sys.executable,
         os.path.join(ROOT, "tests", script)],
        env=env, capture_output=True, text=True, timeout=280,
    )
    sys.stdout.write(proc.stdout[-4000:])
    sys.stderr.write(proc.stderr[-4000:])
    assert proc.returncode == 0, f"dist workers failed (rc={proc.returncode})"
    assert proc.stdout.count(marker) == n


def test_dist_sync_kvstore_two_workers():
    _run_dist(2)


def test_dist_sync_kvstore_four_workers():
    """The dist_sync math must hold at process_count>2 (exact aggregated
    values scale with the worker count — the [U:tests/nightly/
    dist_sync_kvstore.py] multi-worker discipline)."""
    _run_dist(4)


def test_dist_sync_kvstore_eight_workers():
    """Scale-out past the round-3 ceiling: the same exact-value assertions
    at 8 single-device processes (VERDICT r3 item 8)."""
    _run_dist(8)


def test_dist_async_straggler_tolerance_eight_workers():
    """True dist_async (round-5): 8 workers against the worker-0 parameter
    server; the last rank straggles 3 s, the other 7 must finish their
    barrier-free pushes+pulls well before it wakes, and the final pull is
    the exact full sum with server-side SGD verified."""
    _run_dist(8, script="async_worker.py", marker="async assertions passed")


def test_multihost_mesh_two_processes_four_devices():
    """Multi-host-SHAPED topology: 2 processes × 4 virtual devices, one
    global mesh via parallel.init_distributed — the dp axis crosses the
    process (DCN) boundary, exercising make_array_from_process_local_data
    staging, cross-process psum in a jitted step, and SPMDTrainer grad
    sync spanning hosts."""
    _run_dist(2, script="multihost_worker.py",
              marker="multihost assertions passed")


def test_cluster_launcher_dry_run():
    """tools/launch.py ([U:tools/launch.py] analog): ssh and tpu-pod modes
    emit the right fan-out commands (dry-run — no remote targets exist
    here); local mode delegates to the tested launch_local tier."""
    hosts = os.path.join(ROOT, "tools", "__test_hosts.txt")
    with open(hosts, "w") as f:
        f.write("host-a\nhost-b\n")
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
             "--launcher", "ssh", "--hostfile", hosts, "-n", "2",
             "--dry-run", "--", "python", "train.py"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert out.stdout.count("ssh -o StrictHostKeyChecking=no") == 2
        assert "DMLC_WORKER_ID=1" in out.stdout
        assert "DMLC_NUM_WORKER=2" in out.stdout
        out = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
             "--launcher", "tpu-pod", "--tpu-name", "pod0", "--zone", "z",
             "--dry-run", "--", "python", "train.py"],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert "gcloud compute tpus tpu-vm ssh pod0 --worker=all" in out.stdout
    finally:
        os.remove(hosts)
