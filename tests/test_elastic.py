"""Preemption-tolerant elastic training (ISSUE 16).

Covers the acceptance checklist: the collective watchdog fires exactly
once with one structured ``ELASTIC_HANG`` report, two-phase run
snapshots restore EXACTLY (params + optimizer + data cursor + RNG — a
resumed run replays the uninterrupted trajectory step for step),
restore refuses uncommitted snapshots no matter where a SIGKILL landed
(torn-restore, injected at every ``elastic.kill_*`` point), snapshot GC
keys on commit markers (never mtime), the supervisor honors its restart
budget with exactly one ``ELASTIC_RESTART`` line per re-formation, and
the full chaos acceptance: a 2-proc dist_sync FOLDED run loses a worker
mid-run, the supervisor re-forms the job, and the resumed run lands on
the fault-free final loss with zero steady-state recompiles
(``MXNET_COMPILE_GUARD=raise``).
"""
import io
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon, profiler
from incubator_mxnet_tpu.io.io import NDArrayIter
from incubator_mxnet_tpu.parallel import elastic
from incubator_mxnet_tpu.utils import faultinject as fi

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _subproc_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MXNET_FAULT_SPEC", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


# ---------------------------------------------------------------------------
# collective watchdog
# ---------------------------------------------------------------------------


@pytest.fixture
def fast_watchdog(monkeypatch):
    """Watchdog knobs scaled for a unit test (the default first-window
    warmup covers real XLA compiles and is 300 s)."""
    monkeypatch.setenv("MXNET_COLLECTIVE_WARMUP_S", "0.15")
    monkeypatch.setenv("MXNET_COLLECTIVE_WARMUP_ARMS", "1")


class TestCollectiveWatchdog:
    def test_fires_exactly_once_with_one_report(self, fast_watchdog):
        stream = io.StringIO()
        fired = []
        c0 = profiler.counters()["collective_timeout"]
        wd = elastic.CollectiveWatchdog(timeout_s=0.15,
                                        on_expire=fired.append,
                                        report_stream=stream,
                                        poll_s=0.01, rank=3)
        wd.start()
        try:
            wd.arm("kvstore.bucket")
            deadline = time.monotonic() + 5.0
            while not wd.fired and time.monotonic() < deadline:
                time.sleep(0.02)
            time.sleep(0.2)   # extra polls must not re-fire
            assert wd.fired
            assert fired == [43]
            lines = [l for l in stream.getvalue().splitlines()
                     if l.startswith("ELASTIC_HANG ")]
            assert len(lines) == 1
            report = json.loads(lines[0].split(" ", 1)[1])
            assert report["event"] == "collective_timeout"
            assert report["tag"] == "kvstore.bucket"
            assert report["rank"] == 3
            assert report["timeout_s"] == pytest.approx(0.15)
            assert "straggler" in report and "last_step" in report
            assert profiler.counters()["collective_timeout"] == c0 + 1
        finally:
            wd.stop()

    def test_disarm_cancels_the_deadline(self, fast_watchdog):
        fired = []
        wd = elastic.CollectiveWatchdog(timeout_s=0.1, on_expire=fired.append,
                                        report_stream=io.StringIO(),
                                        poll_s=0.01)
        wd.start()
        try:
            for _ in range(3):
                wd.arm("step")
                wd.disarm()
            time.sleep(0.4)
            assert not wd.fired and fired == []
        finally:
            wd.stop()

    def test_nested_arms_stay_armed_until_outermost_disarm(self,
                                                           fast_watchdog):
        fired = []
        wd = elastic.CollectiveWatchdog(timeout_s=0.15,
                                        on_expire=fired.append,
                                        report_stream=io.StringIO(),
                                        poll_s=0.01)
        wd.start()
        try:
            wd.arm("step_fold.call")      # outer
            wd.arm("kvstore.bucket")      # inner (nested)
            wd.disarm()                   # inner closes — still armed
            deadline = time.monotonic() + 5.0
            while not wd.fired and time.monotonic() < deadline:
                time.sleep(0.02)
            assert wd.fired and fired == [43]
        finally:
            wd.stop()

    def test_auto_timeout_scales_from_step_median(self, monkeypatch):
        monkeypatch.setenv("MXNET_COLLECTIVE_TIMEOUT_MIN_S", "0.5")
        monkeypatch.setenv("MXNET_COLLECTIVE_TIMEOUT_FACTOR", "8")
        monkeypatch.delenv("MXNET_COLLECTIVE_TIMEOUT_S", raising=False)
        wd = elastic.CollectiveWatchdog(report_stream=io.StringIO(),
                                        on_expire=lambda c: None)
        wd._arms = wd._warmup_arms    # past the warmup window
        monkeypatch.setattr(profiler, "step_stats",
                            lambda: [{"wall_ms": 250.0}] * 10)
        assert wd._resolve_timeout() == pytest.approx(8 * 0.25)
        # floor: a fast step median must not produce a hair-trigger
        monkeypatch.setattr(profiler, "step_stats",
                            lambda: [{"wall_ms": 1.0}] * 10)
        assert wd._resolve_timeout() == pytest.approx(0.5)

    def test_first_window_uses_compile_warmup(self, monkeypatch):
        monkeypatch.setenv("MXNET_COLLECTIVE_WARMUP_S", "123.0")
        wd = elastic.CollectiveWatchdog(timeout_s=5.0,
                                        report_stream=io.StringIO(),
                                        on_expire=lambda c: None)
        assert wd._resolve_timeout() == pytest.approx(123.0)
        wd._arms = 1
        assert wd._resolve_timeout() == pytest.approx(5.0)

    def test_module_hooks_are_noops_when_uninstalled(self):
        elastic.uninstall_watchdog()
        elastic.watchdog_arm("anything")   # must not raise
        elastic.watchdog_disarm()
        assert elastic.watchdog() is None

    def test_init_is_a_noop_without_supervisor_env(self, monkeypatch):
        monkeypatch.delenv("MXNET_ELASTIC_SOCKET", raising=False)
        assert not elastic.enabled()
        assert elastic.init() is None
        assert elastic.watchdog() is None


# ---------------------------------------------------------------------------
# fault gating (kill-rank-N-at-step-K / generation gates)
# ---------------------------------------------------------------------------


class TestFaultGating:
    def teardown_method(self):
        fi.configure(spec="")

    def test_rank_step_generation_gates(self, monkeypatch):
        monkeypatch.setenv("MXNET_ELASTIC_RESTART", "0")
        fi.configure(spec="proc.kill_rank:n=1:rank=1:at=3:gen=0")
        # wrong rank / wrong step: not counted, not fired
        assert not fi.fire_gated("proc.kill_rank", step=3, rank=0)
        assert not fi.fire_gated("proc.kill_rank", step=2, rank=1)
        assert fi.stats()["proc.kill_rank"] == (0, 0)
        # wrong generation
        monkeypatch.setenv("MXNET_ELASTIC_RESTART", "1")
        assert not fi.fire_gated("proc.kill_rank", step=3, rank=1)
        # exact match fires, and n=1 means never again
        monkeypatch.setenv("MXNET_ELASTIC_RESTART", "0")
        assert fi.fire_gated("proc.kill_rank", step=3, rank=1)
        assert not fi.fire_gated("proc.kill_rank", step=3, rank=1)
        assert fi.stats()["proc.kill_rank"] == (2, 1)

    def test_slow_rank_sleeps_param_seconds(self):
        fi.configure(spec="proc.slow_rank:n=1:s=0.05")
        t0 = time.perf_counter()
        fi.step_faults(0, rank=0)
        assert time.perf_counter() - t0 >= 0.05

    def test_step_faults_inactive_without_spec(self):
        fi.configure(spec="")
        fi.step_faults(0, rank=0)   # must not raise or sleep


# ---------------------------------------------------------------------------
# RunCheckpoint: exact resume, two-phase commit, GC
# ---------------------------------------------------------------------------


def _build_net(x, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.Dense(1)
    net.initialize()
    net(mx.nd.array(x[:4]))    # materialize deferred params
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    return net, tr


def _train(net, tr, steps, it):
    L = gluon.loss.L2Loss()
    losses = []
    for _ in range(steps):
        if not it.iter_next():
            it.reset()
            it.iter_next()
        a, b = it.getdata()[0], it.getlabel()[0]
        with autograd.record():
            loss = L(net(a), b)
        loss.backward()
        tr.step(4)
        losses.append(float(loss.asnumpy().mean()))
    return losses


class TestRunCheckpoint:
    def test_exact_resume_matches_uninterrupted_run(self, tmp_path):
        """Params + momentum + shuffled data cursor + RNG all ride the
        snapshot: 3 steps, save, rebuild from a DIFFERENT seed, restore,
        3 more — the 6 losses equal the uninterrupted run's exactly."""
        x = np.random.RandomState(0).randn(16, 5).astype(np.float32)
        y = np.random.RandomState(1).randn(16, 1).astype(np.float32)
        prefix = str(tmp_path / "run")

        net, tr = _build_net(x)
        it = NDArrayIter(x, y, batch_size=4, shuffle=True, seed=5)
        ref = _train(net, tr, 6, it)

        net1, tr1 = _build_net(x)
        it1 = NDArrayIter(x, y, batch_size=4, shuffle=True, seed=5)
        part1 = _train(net1, tr1, 3, it1)
        ck = elastic.RunCheckpoint(prefix, net=net1, trainer=tr1,
                                   rank=0, world=1)
        ck.save(3, epoch=0, data=it1)

        net2, tr2 = _build_net(x, seed=99)     # resume must overwrite this
        it2 = NDArrayIter(x, y, batch_size=4, shuffle=True, seed=5)
        ck2 = elastic.RunCheckpoint(prefix, net=net2, trainer=tr2,
                                    rank=0, world=1)
        payload = ck2.restore(data=it2)
        assert payload is not None and payload["step"] == 3
        part2 = _train(net2, tr2, 3, it2)
        np.testing.assert_allclose(part1 + part2, ref, rtol=0, atol=1e-7)

    def test_restore_refuses_uncommitted_snapshot(self, tmp_path):
        prefix = str(tmp_path / "run")
        ck = elastic.RunCheckpoint(prefix, rank=0, world=1)
        ck.save(3, extra={"w": 1})
        ck.save(5, extra={"w": 2})
        os.remove(ck._commit_path(5))          # torn: shard without commit
        assert ck.latest_step() == 3
        assert ck.restore(step=5) is None      # explicit ask still refused
        assert ck.restore()["extra"] == {"w": 1}

    def test_restore_refuses_world_size_mismatch(self, tmp_path):
        prefix = str(tmp_path / "run")
        elastic.RunCheckpoint(prefix, rank=0, world=1).save(4)
        ck2 = elastic.RunCheckpoint(prefix, rank=0, world=2)
        assert ck2.latest_step() is None
        assert ck2.restore() is None

    def test_gc_keeps_newest_committed_never_mtime(self, tmp_path):
        """An interrupted newer write (shard, no commit) must not age the
        newest COMMITTED snapshot out of the keep window."""
        prefix = str(tmp_path / "run")
        ck = elastic.RunCheckpoint(prefix, keep=2, rank=0, world=1)
        for s in (1, 2, 3):
            ck.save(s)
        steps = sorted(s for s, _ in ck._committed_steps())
        assert steps == [2, 3]
        # simulate a torn later write: shard landed, commit never did
        import pickle
        from incubator_mxnet_tpu.checkpoint import atomic_write_bytes
        atomic_write_bytes(ck._shard_path(9),
                           pickle.dumps({"step": 9, "world": 1}))
        ck.save(4)
        steps = sorted(s for s, _ in ck._committed_steps())
        assert steps == [3, 4]
        # the in-flight shard 9 (newer than the newest commit) survives GC
        assert os.path.exists(ck._shard_path(9))
        assert os.path.exists(ck._shard_path(3))
        assert not os.path.exists(ck._shard_path(2))


_TORN_CHILD = r"""
import os, sys
sys.path.insert(0, {root!r})
from incubator_mxnet_tpu.parallel.elastic import RunCheckpoint
from incubator_mxnet_tpu.utils import faultinject as fi
ck = RunCheckpoint({prefix!r}, rank=0, world=1)
ck.save(1, extra="first")     # committed baseline, fault-free
fi.configure(spec={spec!r})   # arm AFTER the baseline commit
ck.save(2, extra="second")    # SIGKILL lands somewhere in here
print("SURVIVED", flush=True)
"""


class TestTornRestore:
    """SIGKILL at every injection point in the two-phase save: the
    previous committed snapshot stays restorable, a shard without a
    commit marker is refused."""

    @pytest.mark.parametrize("point,committed", [
        ("elastic.kill_before_shard", 1),
        ("elastic.kill_after_shard", 1),
        ("elastic.kill_before_commit", 1),
        ("elastic.kill_after_commit", 2),   # commit landed: step 2 is real
    ])
    def test_kill_point_never_tears_restore(self, tmp_path, point,
                                            committed):
        prefix = str(tmp_path / "run")
        spec = f"{point}:n=1"
        child = _TORN_CHILD.format(root=ROOT, spec=spec, prefix=prefix)
        proc = subprocess.run([sys.executable, "-c", child],
                              env=_subproc_env(), capture_output=True,
                              text=True, timeout=120)
        assert proc.returncode == -signal.SIGKILL, (proc.returncode,
                                                    proc.stderr[-1000:])
        assert "SURVIVED" not in proc.stdout
        ck = elastic.RunCheckpoint(prefix, rank=0, world=1)
        assert ck.latest_step() == committed
        payload = ck.restore()
        assert payload["step"] == committed
        assert payload["extra"] == ("second" if committed == 2 else "first")


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


SUPERVISE = os.path.join(ROOT, "tools", "supervise.py")


class TestSupervisor:
    def test_clean_run_exits_zero_no_restart_lines(self):
        proc = subprocess.run(
            [sys.executable, SUPERVISE, "-n", "2", sys.executable, "-c",
             "print('worker ok')"],
            env=_subproc_env(), capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-1000:]
        assert proc.stdout.count("worker ok") == 2
        assert "ELASTIC_RESTART" not in proc.stderr
        assert "ELASTIC_GIVEUP" not in proc.stderr

    def test_restart_budget_one_line_per_reformation(self):
        """A rank that always dies: exactly max_restarts ELASTIC_RESTART
        lines (one per re-formation), then one ELASTIC_GIVEUP, non-zero
        exit."""
        proc = subprocess.run(
            [sys.executable, SUPERVISE, "-n", "1", "--max-restarts", "2",
             "--backoff", "0.01", sys.executable, "-c",
             "import sys; sys.exit(7)"],
            env=_subproc_env(), capture_output=True, text=True, timeout=120)
        assert proc.returncode == 7
        restarts = [l for l in proc.stderr.splitlines()
                    if l.startswith("ELASTIC_RESTART ")]
        giveups = [l for l in proc.stderr.splitlines()
                   if l.startswith("ELASTIC_GIVEUP ")]
        assert len(restarts) == 2 and len(giveups) == 1
        rep = json.loads(restarts[0].split(" ", 1)[1])
        assert rep["reason"] == "rank_exit"
        assert rep["exit_code"] == 7
        assert rep["generation"] == 0
        give = json.loads(giveups[0].split(" ", 1)[1])
        assert give["generation"] == 2 and give["restarts_left"] == 0

    def test_generation_env_increments_per_restart(self, tmp_path):
        """Workers see MXNET_ELASTIC_RESTART=g; a worker that fails only
        at g=0 recovers on the first restart."""
        marker = str(tmp_path / "gen.log")
        prog = ("import os,sys\n"
                f"open({marker!r},'a').write("
                "os.environ['MXNET_ELASTIC_RESTART']+'\\n')\n"
                "sys.exit(1 if os.environ['MXNET_ELASTIC_RESTART']=='0' "
                "else 0)\n")
        proc = subprocess.run(
            [sys.executable, SUPERVISE, "-n", "1", "--backoff", "0.01",
             sys.executable, "-c", prog],
            env=_subproc_env(), capture_output=True, text=True, timeout=120)
        assert proc.returncode == 0, proc.stderr[-1000:]
        assert proc.stderr.count("ELASTIC_RESTART ") == 1
        gens = open(marker).read().split()
        assert gens == ["0", "1"]

    def test_heartbeat_lease_reaps_a_wedged_rank(self):
        """A rank that heartbeats once and then wedges (no exit, no
        beats) is reaped by the lease, not waited on forever."""
        prog = (
            "import os, time\n"
            "from incubator_mxnet_tpu.parallel import elastic\n"
            "c = elastic.ElasticClient()\n"
            "c.heartbeat({})\n"
            "time.sleep(60)\n"     # wedged: no further beats
        )
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable, SUPERVISE, "-n", "1", "--max-restarts", "0",
             "--lease-s", "1.5", sys.executable, "-c", prog],
            env=_subproc_env(MXNET_ELASTIC_HEARTBEAT_S="600"),
            capture_output=True, text=True, timeout=120)
        elapsed = time.monotonic() - t0
        assert proc.returncode != 0
        assert "lease_expired" in proc.stderr
        assert elapsed < 45, elapsed


# ---------------------------------------------------------------------------
# chaos acceptance (2-proc dist_sync folded run, supervisor kill/resume)
# ---------------------------------------------------------------------------


def _run_supervised(tmp_path, name, fault_spec=None):
    env = _subproc_env(MXNET_COMPILE_WARMUP_STEPS="3",
                       MXNET_COMPILE_GUARD="raise",
                       MXNET_ELASTIC_BACKOFF_S="0.2",
                       MXNET_FAULT_SEED="0")
    if fault_spec:
        env["MXNET_FAULT_SPEC"] = fault_spec
    prefix = str(tmp_path / name / "run")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    proc = subprocess.run(
        [sys.executable, SUPERVISE, "-n", "2", sys.executable,
         os.path.join(ROOT, "tests", "elastic_worker.py"), prefix],
        env=env, capture_output=True, text=True, timeout=420)
    finals = {}
    for line in proc.stdout.splitlines():
        if line.startswith("ELASTIC_FINAL "):
            _, _, rank, loss = line.split()
            finals[int(rank)] = float(loss)
    return proc, finals


@pytest.mark.slow
def test_elastic_chaos_acceptance(tmp_path):
    """THE acceptance: a 2-proc dist_sync folded run is SIGKILL'd on one
    rank mid-run (fixed MXNET_FAULT_SEED), the supervisor kills the
    survivor, re-forms the job with a fresh coordinator, both ranks
    resume from the last committed snapshot, and the final losses equal
    the fault-free run's EXACTLY — with zero steady-state recompiles
    under MXNET_COMPILE_GUARD=raise and exactly one ELASTIC_RESTART
    report line."""
    ref_proc, ref = _run_supervised(tmp_path, "ref")
    assert ref_proc.returncode == 0, ref_proc.stderr[-3000:]
    assert sorted(ref) == [0, 1]
    assert "ELASTIC_RESTART" not in ref_proc.stderr

    proc, finals = _run_supervised(
        tmp_path, "chaos", fault_spec="proc.kill_rank:n=1:rank=1:at=3:gen=0")
    assert proc.returncode == 0, proc.stderr[-3000:]
    restarts = [l for l in proc.stderr.splitlines()
                if l.startswith("ELASTIC_RESTART ")]
    assert len(restarts) == 1, proc.stderr[-3000:]
    rep = json.loads(restarts[0].split(" ", 1)[1])
    assert rep["reason"] == "rank_exit" and rep["rank"] == 1
    assert rep["exit_code"] == -signal.SIGKILL
    assert proc.stdout.count("ELASTIC_RESUMED") == 2   # both ranks resumed
    assert sorted(finals) == [0, 1]
    for r in (0, 1):
        assert finals[r] == pytest.approx(ref[r], abs=1e-6), (r, finals, ref)
