"""Fused whole-group optimizer step + bucketed gradient allreduce (ISSUE 3).

Covers the acceptance surface: fused-vs-per-tensor numerical equivalence
across the supported optimizer zoo (SGD/NAG/Adam/AdamW, bf16 multi-
precision, per-param lr/wd mults), fallback routing (lazy row-sparse,
unsupported optimizers, NaiveEngine, env escape hatch), the grouped
``multi_*`` kernels' clip sentinel, stale-grad tracking, rescale_grad
clobber warning, save/load state monotonicity, kvstore gradient bucketing,
and the profiler counter contract — plus a CI smoke of the
``benchmark/opperf/trainer_step.py`` harness.

Tolerance contract (docs/optimizer_fusion.md): fused and per-tensor paths
run the SAME per-tensor kernels (inlined into one XLA program), but XLA may
refuse/reassociate differently inside the group, so equivalence is asserted
to 1e-6 relative (1e-2 for bf16 weights, whose storage rounding dominates).
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, gluon, profiler
from incubator_mxnet_tpu.gluon import Parameter
from incubator_mxnet_tpu.ops import optimizer_ops as K

nd = mx.nd


@pytest.fixture(autouse=True)
def fresh_counters():
    profiler.reset_counters()
    yield
    profiler.reset_counters()


def _c():
    return profiler.counters()


def _make_params(n, seed, dtype="float32", stype="default"):
    rs = np.random.RandomState(seed)
    params = []
    for k in range(n):
        p = Parameter(f"p{k}_weight", shape=(3, k % 3 + 2), dtype=dtype,
                      stype=stype)
        p.initialize()
        p.set_data(nd.array(rs.randn(*p.shape).astype(np.float32)))
        params.append(p)
    return params


def _run_steps(opt_name, opt_args, aggregate_num, n=6, dtype="float32",
               steps=3, seed=3, lr_mults=None, wd_mults=None, grads=None):
    """Run ``steps`` trainer steps with fixed grads; returns final weights
    (as float64 numpy) and the trainer."""
    params = _make_params(n, seed, dtype)
    if lr_mults:
        for p, m in zip(params, lr_mults):
            p.lr_mult = m
    if wd_mults:
        for p, m in zip(params, wd_mults):
            p.wd_mult = m
    trainer = gluon.Trainer(params, opt_name, dict(opt_args), kvstore=None)
    if aggregate_num is not None:
        trainer._optimizer.aggregate_num = aggregate_num
    rs = np.random.RandomState(seed + 1)
    gvals = grads or [rs.randn(*p.shape).astype(np.float32) for p in params]
    for _ in range(steps):
        for p, g in zip(params, gvals):
            p.grad()[:] = nd.array(g)
        trainer.step(2)
    return [p.data().asnumpy().astype(np.float64) for p in params], trainer


def _assert_equiv(opt_name, opt_args, tol=1e-6, **kw):
    ref, _ = _run_steps(opt_name, opt_args, 0, **kw)
    out, _ = _run_steps(opt_name, opt_args, 256, **kw)
    for a, b in zip(ref, out):
        np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


# ---------------------------------------------------------------------------
# fused vs per-tensor numerical equivalence
# ---------------------------------------------------------------------------


def test_equiv_sgd():
    _assert_equiv("sgd", {"learning_rate": 0.1, "wd": 0.01})


def test_equiv_sgd_momentum():
    _assert_equiv("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01})


def test_equiv_nag():
    _assert_equiv("nag", {"learning_rate": 0.1, "momentum": 0.9})


@pytest.mark.parametrize("name", ["adam", "adamw"])
def test_equiv_adam_family(name):
    _assert_equiv(name, {"learning_rate": 0.01, "wd": 0.01})


def test_equiv_clip_gradient():
    _assert_equiv("sgd", {"learning_rate": 0.1, "clip_gradient": 0.05})


def test_equiv_rmsprop():
    _assert_equiv("rmsprop", {"learning_rate": 0.01, "wd": 0.01}, tol=1e-5)


def test_equiv_rmsprop_centered():
    _assert_equiv("rmsprop", {"learning_rate": 0.01, "centered": True,
                              "momentum": 0.9}, tol=1e-5)


def test_equiv_lamb():
    _assert_equiv("lamb", {"learning_rate": 0.01, "wd": 0.01}, tol=1e-5)


def test_equiv_lamb_bounds_no_bias_correction():
    # the per-group norm handling: every parameter keeps its OWN trust
    # ratio inside the fused group, bounds applied per tensor
    _assert_equiv("lamb", {"learning_rate": 0.01, "bias_correction": False,
                           "lower_bound": 0.1, "upper_bound": 2.0}, tol=1e-5)


def test_rmsprop_lamb_take_fused_path():
    for name, kw in (("rmsprop", {"learning_rate": 0.01}),
                     ("lamb", {"learning_rate": 0.01})):
        profiler.reset_counters()
        _run_steps(name, kw, 256, n=6, steps=2)
        c = _c()
        assert c["fused_step_call"] == 2, name
        assert c["fused_step_params"] == 12, name
        assert c["fused_step_fallback_params"] == 0, name


@pytest.mark.parametrize("name,args", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "multi_precision": True}),
    ("sgd", {"learning_rate": 0.1, "multi_precision": True}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9, "multi_precision": True}),
    ("adam", {"learning_rate": 0.01, "multi_precision": True}),
    ("adamw", {"learning_rate": 0.01, "multi_precision": True}),
])
def test_equiv_bf16_multi_precision(name, args):
    _assert_equiv(name, args, tol=1e-2, dtype="bfloat16")


def test_equiv_lr_wd_mults():
    _assert_equiv("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.1},
                  lr_mults=[1.0, 0.5, 2.0, 0.0, 1.0, 1.0],
                  wd_mults=[1.0, 0.0, 1.0, 1.0, 3.0, 0.5])


def test_fused_is_default_and_counts_groups():
    # aggregate_num=None: the trainer runs with the optimizer's DEFAULT
    # aggregation — the fused path must engage without opt-in
    _, trainer = _run_steps("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                            None, n=6, steps=2)
    assert trainer._optimizer.aggregate_num > 1
    c = _c()
    assert c["fused_step_call"] == 2       # one group dispatch per step
    assert c["fused_step_params"] == 12    # 6 params x 2 steps
    assert c["fused_step_fallback_params"] == 0


def test_aggregate_num_chunks_groups():
    _run_steps("sgd", {"learning_rate": 0.1}, 4, n=10, steps=1)
    # 10 same-dtype params with a cap of 4 -> 3 fused dispatches
    assert _c()["fused_step_call"] == 3
    assert _c()["fused_step_params"] == 10


# ---------------------------------------------------------------------------
# fallback routing
# ---------------------------------------------------------------------------


def test_env_escape_hatch(monkeypatch):
    monkeypatch.setenv("MXNET_OPTIMIZER_AGGREGATION", "0")
    _run_steps("sgd", {"learning_rate": 0.1}, None, steps=1)
    assert _c()["fused_step_call"] == 0
    monkeypatch.setenv("MXNET_OPTIMIZER_AGGREGATION", "7")
    opt = mx.optimizer.create("sgd")
    assert opt.aggregate_num == 7


def test_unsupported_optimizer_falls_back():
    # ftrl has no fused group adapter (rmsprop/lamb graduated in ISSUE 10)
    ref, _ = _run_steps("ftrl", {"learning_rate": 0.01}, 0, steps=2)
    profiler.reset_counters()
    out, _ = _run_steps("ftrl", {"learning_rate": 0.01}, 256, steps=2)
    assert _c()["fused_step_call"] == 0
    assert _c()["fused_step_fallback_params"] > 0
    for a, b in zip(ref, out):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_naive_engine_bypasses_fusion():
    prev = engine.set_engine_type("NaiveEngine")
    try:
        out, _ = _run_steps("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                            256, steps=2)
        assert _c()["fused_step_call"] == 0
    finally:
        engine.set_engine_type(prev)
    ref, _ = _run_steps("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                        0, steps=2)
    for a, b in zip(ref, out):
        np.testing.assert_allclose(a, b, rtol=1e-6)


def test_lazy_row_sparse_falls_back_with_lazy_semantics():
    """row_sparse params keep their lazy per-tensor kernels: rows with zero
    grad must not decay/accumulate momentum, and the fused path must route
    them around the group dispatch."""
    def run(agg):
        p = _make_params(1, 5, stype="row_sparse")[0]
        dense = _make_params(1, 6)[0]
        tr = gluon.Trainer([p, dense], "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.1},
                           kvstore=None)
        tr._optimizer.aggregate_num = agg
        g = np.zeros(p.shape, np.float32)
        g[1] = 1.0  # touch only row 1
        for _ in range(2):
            p.grad()[:] = nd.array(g)
            dense.grad()[:] = nd.array(np.ones(dense.shape, np.float32))
            tr.step(1)
        return p.data().asnumpy(), dense.data().asnumpy()

    w_ref, d_ref = run(0)
    profiler.reset_counters()
    w_fused, d_fused = run(256)
    assert _c()["fused_step_fallback_params"] == 2  # row_sparse, both steps
    assert _c()["fused_step_params"] == 2           # dense param fused
    np.testing.assert_allclose(w_ref, w_fused, rtol=1e-6)
    np.testing.assert_allclose(d_ref, d_fused, rtol=1e-6)
    # lazy semantics: untouched row 0 never moved (no wd decay, no momentum)
    p0 = _make_params(1, 5, stype="row_sparse")[0]
    np.testing.assert_array_equal(w_fused[0], p0.data().asnumpy()[0])


# ---------------------------------------------------------------------------
# stale grads, rescale_grad clobber warning
# ---------------------------------------------------------------------------


def test_ignore_stale_grad_skips_unrefreshed_params():
    # kvstore='device' on purpose: allreduce_grads rewrites every grad
    # buffer (a version bump), and staleness must be judged BEFORE that
    # transport — keying it off the post-allreduce version would make the
    # check a silent no-op for every kvstore-backed trainer
    params = _make_params(2, 9)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore="device")
    for p in params:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    tr.step(1, ignore_stale_grad=True)
    w_after1 = [p.data().asnumpy().copy() for p in params]
    # refresh ONLY param 0's grad; param 1 is stale on the next step
    params[0].grad()[:] = nd.array(np.ones(params[0].shape, np.float32))
    tr.step(1, ignore_stale_grad=True)
    assert np.abs(params[0].data().asnumpy() - w_after1[0]).max() > 0
    np.testing.assert_array_equal(params[1].data().asnumpy(), w_after1[1])
    # without the flag the stale param is updated as before
    tr.step(1, ignore_stale_grad=False)
    assert np.abs(params[1].data().asnumpy() - w_after1[1]).max() > 0


def test_missing_grad_buffer_raises_unless_ignored():
    params = _make_params(2, 10)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    for p in params:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    params[1]._data._grad = None
    with pytest.raises(UserWarning):
        tr.step(1)
    tr.step(1, ignore_stale_grad=True)  # skips the missing-grad param


def test_user_set_rescale_grad_warns_before_clobber():
    params = _make_params(2, 11)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    for p in params:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    tr.step(4)  # no warning: first step, rescale untouched
    tr._optimizer.rescale_grad = 5.0
    with pytest.warns(UserWarning, match="rescale_grad"):
        tr.step(4)
    assert tr._optimizer.rescale_grad == pytest.approx(0.25)
    # a manual edit BEFORE the first step is clobbered too — and must warn
    params2 = _make_params(2, 11)
    tr2 = gluon.Trainer(params2, "sgd", {"learning_rate": 0.1}, kvstore=None)
    for p in params2:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    tr2._optimizer.rescale_grad = 7.0
    with pytest.warns(UserWarning, match="rescale_grad"):
        tr2.step(4)


# ---------------------------------------------------------------------------
# save/load states: Adam's t stays monotonic
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("aggregate_num", [0, 256])
def test_save_load_states_keeps_adam_t_monotonic(tmp_path, aggregate_num):
    f = str(tmp_path / "trainer.states")
    g = [np.full((3, 2), 0.3, np.float32), np.full((3, 3), -0.2, np.float32)]

    def fresh():
        params = _make_params(2, 12)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                           kvstore=None)
        tr._optimizer.aggregate_num = aggregate_num
        return params, tr

    def steps(params, tr, k):
        for _ in range(k):
            for p, gv in zip(params, g):
                p.grad()[:] = nd.array(gv)
            tr.step(1)

    params, tr = fresh()
    steps(params, tr, 3)
    w_mid = [p.data().asnumpy().copy() for p in params]
    tr.save_states(f)
    steps(params, tr, 2)
    ref = [p.data().asnumpy() for p in params]

    params2, tr2 = fresh()
    for p, w in zip(params2, w_mid):
        p.set_data(nd.array(w))
    tr2.load_states(f)
    # the roundtrip must restore the per-index counters, not reset t to 1
    assert dict(tr2._optimizer._index_update_count) == {0: 3, 1: 3}
    assert tr2._optimizer.begin_num_update == 3
    steps(params2, tr2, 2)
    assert dict(tr2._optimizer._index_update_count) == {0: 5, 1: 5}
    for a, b in zip(ref, [p.data().asnumpy() for p in params2]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


# ---------------------------------------------------------------------------
# grouped multi_* kernels: clip sentinel + single-dispatch machinery
# ---------------------------------------------------------------------------


def test_multi_sgd_clip_sentinel_matches_per_tensor():
    """clip_gradient=0.0 must CLIP (clamp to zero), not silently disable
    clipping; < 0 is the only no-clip sentinel (reference convention)."""
    import jax.numpy as jnp

    w = [jnp.zeros((2,))]
    g = [jnp.asarray([10.0, -10.0])]
    clipped = K.multi_sgd_update(w, g, [1.0], [0.0], clip_gradient=0.1)
    np.testing.assert_allclose(np.asarray(clipped[0]), [-0.1, 0.1], rtol=1e-6)
    zeroed = K.multi_sgd_update(w, g, [1.0], [0.0], clip_gradient=0.0)
    np.testing.assert_allclose(np.asarray(zeroed[0]), [0.0, 0.0])
    unclipped = K.multi_sgd_update(w, g, [1.0], [0.0], clip_gradient=-1.0)
    np.testing.assert_allclose(np.asarray(unclipped[0]), [-10.0, 10.0])


def test_multi_and_preloaded_match_per_tensor_kernels():
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    ws = [jnp.asarray(rs.randn(3, 2).astype(np.float32)) for _ in range(4)]
    gs = [jnp.asarray(rs.randn(3, 2).astype(np.float32)) for _ in range(4)]
    ms = [jnp.zeros((3, 2), jnp.float32) for _ in range(4)]
    lrs, wds = [0.1, 0.2, 0.3, 0.4], [0.0, 0.01, 0.0, 0.02]
    new_w, new_m = K.multi_sgd_mom_update(ws, gs, ms, lrs, wds, momentum=0.9,
                                          clip_gradient=-1.0)
    pre_w, pre_m = K.preloaded_multi_sgd_mom_update(
        ws, gs, ms, jnp.asarray(lrs), jnp.asarray(wds), momentum=0.9,
        clip_gradient=-1.0)
    for i in range(4):
        rw, rm = K.sgd_mom_update(ws[i], gs[i], ms[i], jnp.float32(lrs[i]),
                                  jnp.float32(wds[i]), jnp.float32(1.0),
                                  jnp.float32(-1.0), jnp.float32(0.9))
        np.testing.assert_allclose(np.asarray(new_w[i]), np.asarray(rw),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(new_m[i]), np.asarray(rm),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pre_w[i]), np.asarray(new_w[i]),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(pre_m[i]), np.asarray(new_m[i]),
                                   rtol=1e-6)
    # single-dispatch contract: the group ran through the shared jitted
    # group machinery (one compiled body per adapter), not a python loop
    assert any(step is K.sgd_mom_step for step, _ in K._GROUP_JIT)


# ---------------------------------------------------------------------------
# bucketed gradient allreduce
# ---------------------------------------------------------------------------


def test_bucketed_allreduce_preserves_grads(monkeypatch):
    params = _make_params(5, 13)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="dist_sync")
    gvals = [np.random.RandomState(i).randn(*p.shape).astype(np.float32)
             for i, p in enumerate(params)]
    for p, g in zip(params, gvals):
        p.grad()[:] = nd.array(g)
    tr.allreduce_grads()
    # single worker: the reduced value IS the local grad, now routed through
    # flatten -> pushpull -> unflatten
    for p, g in zip(params, gvals):
        np.testing.assert_allclose(p.grad().asnumpy(), g, rtol=1e-6)
    assert _c()["allreduce_bucket"] == 1
    assert _c()["allreduce_bucket_params"] == 5
    # a tiny byte cap splits the same grads into multiple buckets
    profiler.reset_counters()
    monkeypatch.setenv("MXNET_KVSTORE_BUCKET_BYTES", "40")
    for p, g in zip(params, gvals):
        p.grad()[:] = nd.array(g)
    tr.allreduce_grads()
    for p, g in zip(params, gvals):
        np.testing.assert_allclose(p.grad().asnumpy(), g, rtol=1e-6)
    assert _c()["allreduce_bucket"] > 1
    assert _c()["allreduce_bucket_params"] == 5


def test_local_kvstore_does_not_bucket():
    params = _make_params(3, 14)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore="device")
    for p in params:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    tr.allreduce_grads()
    assert _c()["allreduce_bucket"] == 0


def test_bucketing_disabled_for_server_side_optimizer():
    from incubator_mxnet_tpu import kvstore as kv_mod

    kv = kv_mod.create("dist_sync")
    assert kv.supports_grad_bucketing()
    kv.set_optimizer(mx.optimizer.create("sgd"))
    assert not kv.supports_grad_bucketing()
    # the async tier ACCUMULATES pushes per key server-side, so a reused
    # bucket key would pull back a running sum — never bucket it
    async_kv = object.__new__(kv_mod.KVStoreDistAsync)  # no server spawn
    assert not async_kv.supports_grad_bucketing()


# ---------------------------------------------------------------------------
# observability + CI smoke of the microbenchmark
# ---------------------------------------------------------------------------


def test_fused_counters_surface_in_profiler_dumps():
    _run_steps("sgd", {"learning_rate": 0.1}, 256, steps=1)
    text = profiler.dumps()
    assert "fused_step_call" in text
    assert "allreduce_bucket" in text


def test_trainer_step_benchmark_smoke():
    """Tier-1-adjacent smoke of benchmark/opperf/trainer_step.py: tiny
    sizes, proves the harness runs end-to-end on the CPU backend and emits
    the JSON contract (the 2x acceptance number is measured by the full
    run, not here)."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "benchmark", "opperf", "trainer_step.py")
    spec = importlib.util.spec_from_file_location("trainer_step_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    line = mod.run(n_params=6, shape=(4, 2), iters=2, warmup=1, repeats=1)
    assert line["bench"] == "trainer_step"
    for mode in ("per_tensor", "fused"):
        assert line["steps_per_sec"][mode] > 0
    assert "speedup_fused" in line
