"""SIGTERM graceful drain for the serving tier (ISSUE 16 satellite).

A preempted replica must stop admitting work (retriable
``ServerDrainingError``, a ``RuntimeError`` subclass for pre-drain
callers), drain in-flight requests under a deadline, fail the remainder
retriably instead of hanging clients, and leave load-balancer rotation
via ``/healthz`` (200 serving / 503 draining) the moment the drain
starts."""
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu.serving import (InferenceServer,
                                         ServerDrainingError,
                                         install_sigterm_drain)

FEAT = 4
HID = 6


def _model(seed=0):
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=HID, flatten=False, name="fc1")
    sym = S.Activation(fc, act_type="tanh", name="t1")
    rng = np.random.RandomState(seed)
    params = {
        "arg:fc1_weight": mx.nd.array(rng.randn(HID, FEAT).astype(np.float32)),
        "arg:fc1_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    return sym, params


def _server(**kw):
    sym, params = _model()
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_queue_ms", 30.0)
    kw.setdefault("max_length", 8)
    kw.setdefault("name", "drain_test")
    return InferenceServer(sym, params, {"data": (None, FEAT)}, **kw)


@pytest.fixture(autouse=True)
def serving_health():
    profiler.set_health("serving")
    yield
    profiler.set_health("serving")


class TestDrainingError:
    def test_submit_after_close_is_retriable_and_backcompat(self):
        srv = _server().start()
        srv.close()
        with pytest.raises(ServerDrainingError, match="retry"):
            srv.submit({"data": np.zeros((3, FEAT), np.float32)})
        # RuntimeError subclass: pre-drain callers keep working
        with pytest.raises(RuntimeError):
            srv.submit({"data": np.zeros((3, FEAT), np.float32)})

    def test_close_without_drain_fails_queued_retriably(self):
        srv = _server(max_queue_ms=10_000.0).start()
        # wedge the scheduler so submissions stay queued
        gate = threading.Event()
        orig = srv._pred.forward
        srv._pred.forward = lambda: (gate.wait(10), orig())[1]
        try:
            pending = [srv.submit({"data": np.zeros((3, FEAT), np.float32)})
                       for _ in range(4)]
            srv.close(drain=False, timeout=5.0)
            gate.set()
            failures = 0
            for p in pending:
                try:
                    p.result(timeout=10.0)
                except ServerDrainingError:
                    failures += 1
            assert failures >= 2   # whatever never dispatched failed fast
        finally:
            gate.set()

    def test_drain_deadline_fails_remainder_not_hangs(self):
        """In-flight work shares the drain deadline; whatever cannot
        finish fails with a retriable error instead of blocking close."""
        srv = _server(max_queue_ms=5.0, max_batch_size=1).start()
        orig = srv._pred.forward
        srv._pred.forward = lambda: (time.sleep(1.5), orig())[1]
        # one-request batches: the first dispatch wedges in-flight while
        # the rest sit in the queue past the drain deadline
        pending = [srv.submit({"data": np.zeros((3, FEAT), np.float32)})
                   for _ in range(4)]
        t0 = time.perf_counter()
        srv.close(drain=True, timeout=0.3)
        assert time.perf_counter() - t0 < 5.0   # close() itself returns
        outcomes = []
        for p in pending:
            try:
                p.result(timeout=10.0)
                outcomes.append("ok")
            except Exception as e:  # noqa: BLE001
                outcomes.append(e)
        assert any(isinstance(o, ServerDrainingError) for o in outcomes), \
            outcomes


class TestHealthz:
    def test_healthz_flips_with_health_state(self):
        port = profiler.start_metrics(port=0)
        try:
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10)
            assert body.status == 200
            assert body.read().decode().strip() == "serving"
            profiler.set_health("draining")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10)
            assert ei.value.code == 503
            assert ei.value.read().decode().strip() == "draining"
            # /metrics keeps serving 200 while draining (scrapes continue)
            assert urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=10).status == 200
        finally:
            profiler.stop_metrics()


class TestSigtermDrain:
    def test_sigterm_drains_flips_health_and_chains_prev_handler(self):
        chained = []
        prev = signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
        srv = _server().start()
        try:
            install_sigterm_drain(srv, deadline_s=2.0)
            os.kill(os.getpid(), signal.SIGTERM)
            deadline = time.monotonic() + 10.0
            while not chained and time.monotonic() < deadline:
                time.sleep(0.01)
            assert chained == [signal.SIGTERM]      # prev handler ran last
            assert profiler.health_state() == "draining"
            with pytest.raises(ServerDrainingError):
                srv.submit({"data": np.zeros((3, FEAT), np.float32)})
        finally:
            signal.signal(signal.SIGTERM, prev)
            srv.close()
