"""Metric tests (parity model: metric coverage in [U:tests/python/unittest/])."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def test_accuracy():
    m = mx.metric.Accuracy()
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m.update([label], [pred])
    name, acc = m.get()
    assert name == "accuracy"
    assert acc == pytest.approx(2 / 3)


def test_topk():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.7, 0.2, 0.1]])
    label = mx.nd.array([1, 2])
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_mse_rmse_mae():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([[1.5], [2.5]])
    m = mx.metric.MSE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.25)
    m = mx.metric.RMSE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)
    m = mx.metric.MAE()
    m.update([label], [pred])
    assert m.get()[1] == pytest.approx(0.5)


def test_perplexity():
    m = mx.metric.Perplexity()
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m.update([label], [pred])
    expected = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert m.get()[1] == pytest.approx(expected, rel=1e-4)


def test_f1():
    m = mx.metric.F1()
    pred = mx.nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 1, 0])
    m.update([label], [pred])
    # tp=1 fp=1 fn=1 -> f1 = 0.5
    assert m.get()[1] == pytest.approx(0.5)


def test_composite_and_create():
    m = mx.metric.create(["acc", "ce"])
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    m.update([label], [pred])
    names, values = m.get()
    assert "accuracy" in names[0]


def test_custom_metric():
    m = mx.metric.CustomMetric(lambda l, p: float(np.abs(l - p).sum()), name="absdiff")
    m.update([mx.nd.array([1.0])], [mx.nd.array([3.0])])
    assert m.get()[1] == pytest.approx(2.0)


def test_loss_metric():
    m = mx.metric.Loss()
    m.update([], [mx.nd.array([2.0, 4.0])])
    assert m.get()[1] == pytest.approx(3.0)


def test_mcc_against_sklearn_formula():
    m = mx.metric.MCC()
    pred = mx.nd.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7], [0.6, 0.4]])
    label = mx.nd.array([1, 0, 0, 1])
    m.update([label], [pred])
    # tp=1 (i0), tn=1 (i1), fp=1 (i2), fn=1 (i3)
    name, val = m.get()
    np.testing.assert_allclose(val, (1 * 1 - 1 * 1) / np.sqrt(2 * 2 * 2 * 2))
    m.reset()
    perfect = mx.nd.array([[0.1, 0.9], [0.8, 0.2]])
    m.update([mx.nd.array([1, 0])], [perfect])
    assert m.get()[1] == 1.0
    # degenerate (all one class predicted): 0 by convention
    m.reset()
    m.update([mx.nd.array([1, 1])], [mx.nd.array([[0.1, 0.9], [0.2, 0.8]])])
    assert m.get()[1] == 0.0
    # reachable through the registry
    assert isinstance(mx.metric.create("mcc"), mx.metric.MCC)


def test_binary_metrics_reject_multiclass():
    for name in ("f1", "mcc"):
        m = mx.metric.create(name)
        with pytest.raises(ValueError):
            m.update([mx.nd.array([0, 1, 2])],
                     [mx.nd.array([[0.2, 0.3, 0.5]] * 3)])


def test_pcc_metric():
    """metric.PCC: equals MCC for binary, 1.0 for perfect multiclass,
    streaming across updates."""
    rng = np.random.RandomState(1)
    y = rng.randint(0, 2, 300)
    p = np.where(rng.rand(300) < 0.75, y, 1 - y)
    probs = np.eye(2)[p]
    pcc, mcc = mx.metric.PCC(), mx.metric.MCC()
    # stream in two chunks — confusion matrix accumulates
    for sl in (slice(0, 100), slice(100, 300)):
        pcc.update([mx.nd.array(y[sl])], [mx.nd.array(probs[sl])])
        mcc.update([mx.nd.array(y[sl])], [mx.nd.array(probs[sl])])
    assert abs(pcc.get()[1] - mcc.get()[1]) < 1e-9
    pcc4 = mx.metric.PCC()
    y4 = rng.randint(0, 4, 200)
    pcc4.update([mx.nd.array(y4)], [mx.nd.array(np.eye(4)[y4])])
    assert pcc4.get()[1] == 1.0
    assert mx.metric.create("pcc").name == "pcc"
