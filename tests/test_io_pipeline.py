"""Async sharded input pipeline tests (io/pipeline.py + io.py satellites).

Covers the ISSUE-9 acceptance surface: ordered delivery under
multi-worker prep, exact sharded-union equivalence, device
placement/sharding of delivered batches, autotune (host-bound raise +
memory-cap backoff), exact stall counters, lifecycle (close() drains and
joins every thread), and the SPMDTrainer integration contract — batches
arrive device-resident with the mesh data-axis NamedSharding so the step
dispatch does zero per-step host→device work (no ``spmd.shard_batch``
span on the consumer thread).
"""
import gc
import threading
import time

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.io import DataPipeline, NDArrayIter, PrefetchingIter
from incubator_mxnet_tpu.parallel import batch_pspec, make_mesh, mesh_scope


def _pipeline_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("mxtpu-") and t.is_alive()]


def _wait_until(cond, timeout=10.0, msg="condition"):
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture
def clean_profiler(tmp_path):
    profiler.stop()
    profiler.set_config(filename=str(tmp_path / "trace.json"),
                        ring_size=65536, slow_step_ms=None)
    profiler.reset_counters()
    yield tmp_path
    profiler.stop()
    profiler.set_config(slow_step_ms=None, slow_step_auto=True)
    profiler.reset_counters()


@pytest.fixture(autouse=True)
def no_thread_leak():
    """Every test must leave zero pipeline threads behind — the leak the
    PrefetchingIter lifecycle fix exists for, enforced suite-wide."""
    yield
    gc.collect()
    _wait_until(lambda: not _pipeline_threads(), timeout=5.0,
                msg="pipeline threads to exit")


class TestDelivery:
    def test_plain_iterable_order_values_and_sharding(self):
        mesh = make_mesh()
        src = [np.full((8, 4), i, np.float32) for i in range(12)]
        with DataPipeline(src, mesh=mesh, num_workers=3) as pipe:
            got = list(pipe)
            assert len(got) == 12
            want = NamedSharding(mesh, batch_pspec(2))
            for i, a in enumerate(got):
                assert isinstance(a, jax.Array)
                assert a.sharding == want
                np.testing.assert_array_equal(np.asarray(a), src[i])

    def test_multiworker_prep_preserves_order(self):
        """Workers finish out of order (seeded random sleep); delivery
        must still be exactly source order, with prep applied."""
        rng = np.random.RandomState(0)
        delays = rng.uniform(0.0, 0.01, size=32)

        def prep(b):
            time.sleep(delays[int(b[0, 0])])
            return b * 2.0

        src = [np.full((4, 2), i, np.float32) for i in range(32)]
        with DataPipeline(src, mesh=make_mesh(), prep_fn=prep,
                          num_workers=4) as pipe:
            got = [np.asarray(a) for a in pipe]
        assert [int(a[0, 0]) for a in got] == [2 * i for i in range(32)]

    def test_databatch_source_wraps_ndarray_and_keeps_bookkeeping(self):
        mesh = make_mesh()
        it = NDArrayIter(np.arange(80, dtype=np.float32).reshape(20, 4),
                         np.arange(20, dtype=np.float32), batch_size=8)
        with DataPipeline(it, mesh=mesh) as pipe:
            batches = list(pipe)
        assert len(batches) == 3
        want = NamedSharding(mesh, batch_pspec(2))
        for b in batches:
            assert isinstance(b.data[0], mx.nd.NDArray)
            assert b.data[0]._data.sharding == want
            assert isinstance(b.label[0], mx.nd.NDArray)
        assert batches[-1].pad == 4  # 20 % 8 — pad bookkeeping survives

    def test_multi_epoch_reiteration_and_reset(self):
        src = [np.full((4, 2), i, np.float32) for i in range(6)]
        pipe = DataPipeline(src, mesh=make_mesh(), num_workers=2)
        try:
            e1 = [int(np.asarray(a)[0, 0]) for a in pipe]
            e2 = [int(np.asarray(a)[0, 0]) for a in pipe]  # auto re-open
            assert e1 == e2 == list(range(6))
            # mid-epoch reset: no stale pre-reset batch may survive
            it = iter(pipe)
            next(it)
            pipe.reset()
            e3 = [int(np.asarray(a)[0, 0]) for a in pipe]
            assert e3 == list(range(6))
        finally:
            pipe.close()

    def test_source_error_propagates_in_order(self):
        def gen():
            for i in range(3):
                yield np.full((2, 2), i, np.float32)
            raise ValueError("decode failed")

        pipe = DataPipeline(gen, mesh=make_mesh(), num_workers=2)
        try:
            got = []
            with pytest.raises(ValueError, match="decode failed"):
                for a in pipe:
                    got.append(int(np.asarray(a)[0, 0]))
            assert got == [0, 1, 2]  # every good batch delivered first
        finally:
            pipe.close()


class TestSharding:
    def test_sharded_union_equals_unsharded_stream(self):
        """Exact equivalence: the union of all parts' delivered samples is
        the unsharded stream's sample set, and parts are disjoint."""
        full = np.arange(24, dtype=np.float32).reshape(24, 1)
        unsharded = NDArrayIter(full, batch_size=4, shuffle=True, seed=7)
        ref = []
        for b in unsharded:
            ref.extend(int(v) for v in b.data[0].asnumpy().ravel())

        parts = []
        for pi in range(3):
            it = NDArrayIter(full, batch_size=4, shuffle=True, seed=7,
                             num_parts=3, part_index=pi)
            got = []
            for b in it:
                got.extend(int(v) for v in b.data[0].asnumpy().ravel())
            assert len(got) == 8  # equal share per host
            parts.append(got)
        flat = [v for p in parts for v in p]
        assert sorted(flat) == sorted(ref) == list(range(24))
        assert len(set(flat)) == 24  # disjoint

    def test_shuffle_is_epoch_aware_and_host_agreeing(self):
        full = np.arange(16, dtype=np.float32).reshape(16, 1)

        def epoch(it):
            out = []
            for b in it:
                out.extend(int(v) for v in b.data[0].asnumpy().ravel())
            return out

        a = NDArrayIter(full, batch_size=4, shuffle=True, seed=3,
                        num_parts=2, part_index=0)
        b = NDArrayIter(full, batch_size=4, shuffle=True, seed=3,
                        num_parts=2, part_index=1)
        a1, b1 = epoch(a), epoch(b)
        a.reset(), b.reset()
        a2, b2 = epoch(a), epoch(b)
        # per-epoch: hosts split the full set disjointly
        assert sorted(a1 + b1) == list(range(16))
        assert sorted(a2 + b2) == list(range(16))
        # epochs reshuffle (the RNG stream advances identically everywhere)
        assert a1 != a2

    def test_uneven_shard_raises_unless_allow_pad(self):
        full = np.arange(25, dtype=np.float32).reshape(25, 1)
        with pytest.raises(ValueError, match="allow_pad"):
            NDArrayIter(full, batch_size=4, num_parts=3, part_index=0)
        seen = []
        for pi in range(3):
            it = NDArrayIter(full, batch_size=3, num_parts=3, part_index=pi,
                             allow_pad=True)
            assert it.num_data == 9  # every host sees the same count
            for b in it:
                seen.extend(int(v) for v in b.data[0].asnumpy().ravel())
        assert set(seen) == set(range(25))  # wrap covers every sample

    def test_pipeline_rejects_mismatched_source_sharding(self):
        full = np.arange(16, dtype=np.float32).reshape(16, 1)
        it = NDArrayIter(full, batch_size=4, num_parts=2, part_index=0)
        with pytest.raises(ValueError, match="sharded"):
            DataPipeline(it, mesh=make_mesh(), num_parts=4, part_index=1)

    def test_pipeline_strides_plain_iterable(self):
        src = [np.full((2, 2), i, np.float32) for i in range(10)]
        got = {}
        for pi in range(2):
            with DataPipeline(src, mesh=make_mesh(), num_parts=2,
                              part_index=pi, name=f"io_part{pi}") as pipe:
                got[pi] = [int(np.asarray(a)[0, 0]) for a in pipe]
        assert got[0] == [0, 2, 4, 6, 8]
        assert got[1] == [1, 3, 5, 7, 9]


class TestAutotune:
    def test_depth_rises_while_host_bound(self, monkeypatch):
        monkeypatch.setenv("MXNET_IO_TUNE_INTERVAL", "1")
        hostbound = [{"wall_ms": 10.0, "host_ms": 9.0, "comms_ms": 0.0,
                      "device_ms": 1.0}] * 8

        def slow_gen():
            for i in range(64):
                yield np.full((4, 2), i, np.float32)

        pipe = DataPipeline(slow_gen, mesh=make_mesh(), depth=2, max_depth=6,
                            _step_stats_fn=lambda: hostbound,
                            _device_pressure_fn=lambda frac: False)
        try:
            it = iter(pipe)
            for _ in range(4):
                next(it)
            _wait_until(lambda: pipe.depth == 6, msg="depth to reach cap")
            assert pipe.stats()["depth_changes"] >= 4
        finally:
            pipe.close()

    def test_memory_budget_caps_depth(self, monkeypatch):
        monkeypatch.setenv("MXNET_IO_TUNE_INTERVAL", "1")
        hostbound = [{"wall_ms": 10.0, "host_ms": 9.0, "comms_ms": 0.0,
                      "device_ms": 1.0}] * 8
        batch_bytes = 4 * 2 * 4  # (4, 2) float32
        budget_mb = (3 * batch_bytes) / (1 << 20)  # room for exactly 3

        def gen():
            for i in range(64):
                yield np.full((4, 2), i, np.float32)

        pipe = DataPipeline(gen, mesh=make_mesh(), depth=2, max_depth=8,
                            memory_budget_mb=budget_mb,
                            _step_stats_fn=lambda: hostbound,
                            _device_pressure_fn=lambda frac: False)
        try:
            it = iter(pipe)
            for _ in range(16):
                next(it)
            _wait_until(lambda: pipe.depth == 3, msg="depth to settle at 3")
            for _ in range(16):
                next(it)
            assert pipe.depth == 3  # never raised past the budget
        finally:
            pipe.close()

    def test_device_pressure_backs_off(self, monkeypatch):
        monkeypatch.setenv("MXNET_IO_TUNE_INTERVAL", "1")

        def gen():
            for i in range(64):
                yield np.full((4, 2), i, np.float32)

        pipe = DataPipeline(gen, mesh=make_mesh(), depth=4, max_depth=8,
                            _step_stats_fn=lambda: [],
                            _device_pressure_fn=lambda frac: True)
        try:
            it = iter(pipe)
            for _ in range(16):
                next(it)
            _wait_until(lambda: pipe.depth == 2,
                        msg="depth to back off to the floor")
        finally:
            pipe.close()

    def test_epoch_boundary_stalls_do_not_ratchet_depth(self, monkeypatch):
        """The consumer's unavoidable arrival at a refilling epoch-start
        buffer is NOT an autotune signal: a healthy producer over many
        epochs must keep the double-buffer depth, not creep to the cap."""
        monkeypatch.setenv("MXNET_IO_TUNE_INTERVAL", "1")
        src = [np.full((4, 2), i, np.float32) for i in range(8)]
        pipe = DataPipeline(src, mesh=make_mesh(), depth=2, max_depth=8,
                            _step_stats_fn=lambda: [],
                            _device_pressure_fn=lambda frac: False)
        try:
            for _ in range(5):  # 5 epochs, each restarts with an empty buffer
                for _ in pipe:
                    time.sleep(0.002)  # consumer slower than producer
            assert pipe.depth == 2
            # phantom (epoch-refill) stalls are race-dependent; the
            # contract is that whatever occurred never fed the tuner
            assert pipe.stats()["stalls_warm"] == 0
        finally:
            pipe.close()

    def test_fixed_depth_when_autotune_off(self, monkeypatch):
        monkeypatch.setenv("MXNET_IO_TUNE_INTERVAL", "1")
        src = [np.full((4, 2), i, np.float32) for i in range(32)]
        with DataPipeline(src, mesh=make_mesh(), depth=3,
                          autotune=False) as pipe:
            list(pipe)
            assert pipe.depth == 3
            assert pipe.stats()["depth_changes"] == 0


class TestObservability:
    def test_stall_counters_exact(self, clean_profiler):
        """Each consumer arrival at an empty buffer is EXACTLY one stall:
        the producer is gated per-batch, and next() is always issued
        before the gate opens."""
        gate = threading.Semaphore(0)

        def prep(b):
            gate.acquire()
            return b

        src = [np.full((2, 2), i, np.float32) for i in range(4)]
        before = profiler.counters()["io_pipeline_stalls"]
        pipe = DataPipeline(src, mesh=make_mesh(), prep_fn=prep,
                            num_workers=1, autotune=False)
        try:
            it = iter(pipe)
            for _ in range(4):
                t = threading.Timer(0.05, gate.release)
                t.start()
                next(it)  # issued while the gate is shut -> one stall each
                t.join()
        finally:
            gate.release()  # let the epoch finish so close() is quick
            pipe.close()
        assert profiler.counters()["io_pipeline_stalls"] - before == 4
        st = pipe.stats()
        assert st["stalls"] == 4
        assert st["stall_ms_p50"] is not None
        assert st["stall_ms_p99"] >= st["stall_ms_p50"]

    def test_counters_spans_and_bytes(self, clean_profiler):
        profiler.start()
        src = [np.zeros((8, 4), np.float32) for _ in range(5)]
        with DataPipeline(src, mesh=make_mesh(),
                          prep_fn=lambda b: b + 1.0) as pipe:
            list(pipe)
        c = profiler.counters()
        assert c["io_pipeline_batches"] == 5
        assert c["io_pipeline_bytes"] == 5 * 8 * 4 * 4
        names = {e.get("name") for e in profiler._trace_events()
                 if e.get("ph") == "B"}
        assert "io.prep" in names
        assert "io.transfer" in names
        profiler.stop()

    def test_metrics_provider_lifecycle(self, clean_profiler):
        src = [np.zeros((4, 2), np.float32) for _ in range(3)]
        pipe = DataPipeline(src, mesh=make_mesh(), name="io_test_pipe")
        try:
            list(pipe)
            snap = profiler.metrics_snapshot()
            prov = snap["providers"]["io_test_pipe"]
            assert prov["batches"] == 3
            assert prov["depth"] >= 2
            assert "stall_ms_p99" in prov
        finally:
            pipe.close()
        assert "io_test_pipe" not in profiler.metrics_snapshot()["providers"]


class TestLifecycle:
    def test_close_drains_and_joins_all_threads(self):
        src = [np.zeros((4, 2), np.float32) for _ in range(100)]
        pipe = DataPipeline(src, mesh=make_mesh(), num_workers=3,
                            prep_fn=lambda b: b)
        it = iter(pipe)
        next(it)  # mid-epoch abandon: buffer full, workers busy
        assert _pipeline_threads()
        pipe.close()
        assert not _pipeline_threads()
        with pytest.raises(RuntimeError):
            next(it)

    def test_abandoned_pipeline_is_collected_without_leaking(self):
        src = [np.zeros((4, 2), np.float32) for _ in range(50)]
        pipe = DataPipeline(src, mesh=make_mesh(), num_workers=2)
        next(iter(pipe))
        del pipe
        gc.collect()
        _wait_until(lambda: not _pipeline_threads(), timeout=5.0,
                    msg="GC'd pipeline threads to exit")

    def test_prefetching_iter_close_and_context_manager(self):
        it = NDArrayIter(np.zeros((64, 4), np.float32), batch_size=4)
        pf = PrefetchingIter(it)
        pf.next()  # abandon mid-epoch: the worker holds queued batches
        worker = pf._thread
        assert worker.is_alive()
        pf.close()
        assert pf._thread is None and not worker.is_alive()
        pf.close()  # idempotent
        with pytest.raises(RuntimeError, match="closed"):
            pf.next()  # must error loudly, not hang on the drained queue
        with PrefetchingIter(NDArrayIter(np.zeros((8, 4), np.float32),
                                         batch_size=4)) as pf2:
            assert pf2.next() is not None
        assert pf2._thread is None

    def test_prefetching_iter_depth_env(self, monkeypatch):
        monkeypatch.setenv("MXNET_IO_PREFETCH_DEPTH", "5")
        pf = PrefetchingIter(NDArrayIter(np.zeros((8, 4), np.float32),
                                         batch_size=4))
        assert pf._queue.maxsize == 5
        pf.close()

    def test_prefetching_iter_reset_still_works(self):
        it = NDArrayIter(np.arange(16, dtype=np.float32).reshape(16, 1),
                         batch_size=4, last_batch_handle="discard")
        pf = PrefetchingIter(it)
        e1 = [b.data[0].asnumpy().ravel().tolist() for b in pf]
        pf.reset()
        e2 = [b.data[0].asnumpy().ravel().tolist() for b in pf]
        assert e1 == e2 and len(e1) == 4
        pf.close()


class TestTrainerIntegration:
    def test_spmd_batches_device_resident_no_per_step_transfer(
            self, clean_profiler):
        """The acceptance contract: pipeline batches carry the mesh
        data-axis NamedSharding BEFORE step dispatch, and the step does
        zero per-step host→device work on the consumer thread (no
        ``spmd.shard_batch`` span) — while the same loop fed numpy
        transfers every step."""
        from incubator_mxnet_tpu.parallel import SPMDTrainer

        mesh = make_mesh()
        mx.random.seed(11)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 8)))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        spmd = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1},
                           mesh=mesh)

        rng = np.random.RandomState(0)
        x = rng.randn(32, 8).astype(np.float32)
        y = rng.randint(0, 4, size=(32,)).astype(np.float32)
        it = NDArrayIter(x, y, batch_size=8)

        def shard_batch_spans():
            return [e for e in profiler._trace_events()
                    if e.get("ph") == "B"
                    and e.get("name") == "spmd.shard_batch"]

        want = NamedSharding(mesh, batch_pspec(2))
        with mesh_scope(mesh):
            pipe = DataPipeline(it, sp_axis=None)
        try:
            profiler.start()
            losses = []
            for b in pipe:
                xb, yb = b.data[0], b.label[0]
                assert xb._data.sharding == want  # placed BEFORE dispatch
                losses.append(float(spmd.step(xb, yb).asnumpy()))
            assert all(np.isfinite(l) for l in losses) and len(losses) == 4
            assert shard_batch_spans() == []  # zero per-step device_put

            # control: numpy feeding pays the per-step transfer
            spmd.step(x[:8], y[:8])
            assert len(shard_batch_spans()) == 2  # data + label
            profiler.stop()
        finally:
            pipe.close()

    def test_pipeline_without_mesh_feeds_gluon_eagerly(self):
        """No mesh (eager/gluon path): leaves land on the default device
        unsharded and train a gluon Trainer step end to end."""
        mx.random.seed(5)
        net = nn.Dense(2)
        net.initialize()
        net(mx.nd.zeros((2, 4)))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        loss_fn = gluon.loss.L2Loss()
        x = np.random.RandomState(1).randn(16, 4).astype(np.float32)
        y = np.zeros((16, 2), np.float32)
        it = NDArrayIter(x, y, batch_size=4)
        with DataPipeline(it, mesh=None, num_parts=1, part_index=0) as pipe:
            for b in pipe:
                with mx.autograd.record():
                    loss = loss_fn(net(b.data[0]), b.label[0])
                loss.backward()
                trainer.step(4)
        assert np.isfinite(float(loss.asnumpy().sum()))


@pytest.mark.slow
def test_bench_smoke():
    """The benchmark harness runs end to end in smoke mode and reports a
    sane result dict (the CI io tier runs this same path)."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "input_pipeline_bench",
        os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "benchmark", "opperf", "input_pipeline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    res = mod.run(steps=6, warmup=2, trials=1, host_ms=2.0, feat=32,
                  batch=8, layers=1)
    assert res["steps_per_sec"]["pipeline"] > 0
    assert res["steps_per_sec"]["off"] > 0
    assert "stalls_after_warmup" in res
