"""Worker for the SIGTERM-preemption resume test (tests/test_checkpoint.py).

Modes:
  uninterrupted  — train TOTAL steps straight through, print "FINAL <loss>"
  phase1         — train with per-step checkpoints + slow-down sleeps,
                   printing "TRAINING" once underway; SIGTERM triggers the
                   manager's synchronous save and kills the process
  phase1_killwrite — like phase1 but with checkpoint file writes SLOWED
                   (a sleep inside the save, after the tmp file is written
                   and before os.replace) and a "SAVING <step>" marker per
                   save, so the test can land a SIGKILL mid-write and
                   assert atomicity: restore() must load the last COMPLETE
                   checkpoint, never a torn one
  resume         — restore the newest checkpoint, train the remaining
                   steps, print "FINAL <loss>"

Training is deterministic (fixed data, no dropout), so a resumed run's
final loss equals the uninterrupted run's bit-for-bit modulo float tol.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.checkpoint import CheckpointManager

TOTAL = 40


def build():
    mx.random.seed(11)
    net = gluon.nn.Dense(1)
    net.initialize()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(16, 4).astype(np.float32))
    y = mx.nd.array((rng.rand(16, 1) * 2 - 1).astype(np.float32))
    net(x)  # materialize
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})
    return net, trainer, x, y


def step(net, trainer, x, y):
    with autograd.record():
        loss = ((net(x) - y) ** 2).mean()
    loss.backward()
    trainer.step(16)
    return float(loss.asscalar())


def main():
    prefix, mode = sys.argv[1], sys.argv[2]
    net, trainer, x, y = build()

    if mode == "uninterrupted":
        for _ in range(TOTAL):
            l = step(net, trainer, x, y)
        print("FINAL", l)
        return

    if mode == "phase1":
        mgr = CheckpointManager(prefix, net=net, trainer=trainer,
                                save_on_sigterm=True, async_write=True)
        for i in range(1, TOTAL + 1):
            step(net, trainer, x, y)
            mgr.save(i)
            if i == 2:
                print("TRAINING", flush=True)
            time.sleep(0.12)  # widen the window so SIGTERM lands mid-fit
        print("FINISHED", flush=True)
        return

    if mode == "phase1_killwrite":
        from incubator_mxnet_tpu.ndarray import utils as nd_utils

        orig_save = nd_utils.save

        def slow_save(fname, data, format=None):
            orig_save(fname, data, format=format)
            time.sleep(0.4)  # kill window: tmp written, os.replace pending

        nd_utils.save = slow_save
        mgr = CheckpointManager(prefix, net=net, trainer=trainer,
                                save_on_sigterm=False, async_write=False)
        for i in range(1, TOTAL + 1):
            step(net, trainer, x, y)
            print("SAVING", i, flush=True)
            mgr.save(i, blocking=True)
        print("FINISHED", flush=True)
        return

    if mode == "resume":
        mgr = CheckpointManager(prefix, net=net, trainer=trainer,
                                save_on_sigterm=False)
        start = mgr.restore() or 0
        assert start > 0, "no checkpoint found to resume from"
        assert start < TOTAL, f"phase1 already finished ({start})"
        l = None
        for _ in range(start, TOTAL):
            l = step(net, trainer, x, y)
        print("RESUMED_FROM", start, flush=True)
        print("FINAL", l)
        return

    raise SystemExit(f"unknown mode {mode}")


if __name__ == "__main__":
    main()
