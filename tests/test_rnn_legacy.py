"""mx.rnn — the legacy symbolic cell API (parity:
[U:tests/python/unittest/test_rnn.py], the pre-Gluon tier)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S


def _bind_fill(out_sym, data, seed=0, **extra):
    exe = out_sym.simple_bind(data=data.shape)
    rng = np.random.RandomState(seed)
    for k in exe.arg_dict:
        if k == "data":
            exe.arg_dict[k][:] = data
        elif k in extra:
            exe.arg_dict[k][:] = extra[k]
        else:
            exe.arg_dict[k][:] = rng.randn(*exe.arg_dict[k].shape).astype(np.float32) * 0.1
    return exe


class TestLegacyCells:
    def test_unroll_shares_parameters(self):
        S.symbol._reset_naming()
        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.LSTMCell(num_hidden=8, prefix="lstm_l0_"))
        stack.add(mx.rnn.LSTMCell(num_hidden=8, prefix="lstm_l1_"))
        out, states = stack.unroll(5, inputs=S.var("data"), merge_outputs=True)
        args = out.list_arguments()
        assert len(args) == len(set(args))
        assert "lstm_l0_i2h_weight" in args and "lstm_l1_h2h_bias" in args
        assert len(states) == 4  # 2 layers x (h, c)
        x = np.random.RandomState(1).randn(2, 5, 4).astype(np.float32)
        assert _bind_fill(out, x).forward(is_train=False)[0].shape == (2, 5, 8)

    def test_lstm_cell_matches_numpy(self):
        S.symbol._reset_naming()
        cell = mx.rnn.LSTMCell(num_hidden=4, prefix="l_", forget_bias=0.0)
        out, _ = cell.unroll(3, inputs=S.var("data"), merge_outputs=True)
        x = np.random.RandomState(2).randn(2, 3, 5).astype(np.float32)
        exe = _bind_fill(out, x, seed=3)
        got = exe.forward(is_train=False)[0].asnumpy()

        w_i = exe.arg_dict["l_i2h_weight"].asnumpy()
        b_i = exe.arg_dict["l_i2h_bias"].asnumpy()
        w_h = exe.arg_dict["l_h2h_weight"].asnumpy()
        b_h = exe.arg_dict["l_h2h_bias"].asnumpy()
        sig = lambda v: 1 / (1 + np.exp(-v))
        h = np.zeros((2, 4), np.float32)
        c = np.zeros((2, 4), np.float32)
        outs = []
        for t in range(3):
            g = x[:, t] @ w_i.T + b_i + h @ w_h.T + b_h
            i, f, cc, o = np.split(g, 4, axis=1)
            c = sig(f) * c + sig(i) * np.tanh(cc)
            h = sig(o) * np.tanh(c)
            outs.append(h)
        np.testing.assert_allclose(got, np.stack(outs, 1), rtol=1e-5, atol=1e-6)

    def test_gru_cell_matches_numpy(self):
        S.symbol._reset_naming()
        cell = mx.rnn.GRUCell(num_hidden=4, prefix="g_")
        out, _ = cell.unroll(3, inputs=S.var("data"), merge_outputs=True)
        x = np.random.RandomState(4).randn(2, 3, 5).astype(np.float32)
        exe = _bind_fill(out, x, seed=5)
        got = exe.forward(is_train=False)[0].asnumpy()

        w_i = exe.arg_dict["g_i2h_weight"].asnumpy()
        b_i = exe.arg_dict["g_i2h_bias"].asnumpy()
        w_h = exe.arg_dict["g_h2h_weight"].asnumpy()
        b_h = exe.arg_dict["g_h2h_bias"].asnumpy()
        sig = lambda v: 1 / (1 + np.exp(-v))
        h = np.zeros((2, 4), np.float32)
        outs = []
        for t in range(3):
            gi = x[:, t] @ w_i.T + b_i
            gh = h @ w_h.T + b_h
            ir, iz, inn = np.split(gi, 3, 1)
            hr, hz, hn = np.split(gh, 3, 1)
            r, z = sig(ir + hr), sig(iz + hz)
            n = np.tanh(inn + r * hn)
            h = (1 - z) * n + z * h
            outs.append(h)
        np.testing.assert_allclose(got, np.stack(outs, 1), rtol=1e-5, atol=1e-6)

    def test_fused_matches_unfused_with_packed_weights(self):
        rng = np.random.RandomState(6)
        x = rng.randn(2, 5, 4).astype(np.float32)
        cell_args = {"lstm_l0_i2h_weight": rng.randn(32, 4).astype(np.float32) * 0.1,
                     "lstm_l0_h2h_weight": rng.randn(32, 8).astype(np.float32) * 0.1,
                     "lstm_l0_i2h_bias": rng.randn(32).astype(np.float32) * 0.1,
                     "lstm_l0_h2h_bias": rng.randn(32).astype(np.float32) * 0.1}

        S.symbol._reset_naming()
        fused = mx.rnn.FusedRNNCell(8, num_layers=1, mode="lstm", prefix="lstm_")
        fo, _ = fused.unroll(5, inputs=S.var("data"), layout="NTC")
        packed = fused.pack_weights(
            {k: mx.nd.array(v) for k, v in cell_args.items()})
        fexe = _bind_fill(fo, x, lstm_parameters=packed["lstm_parameters"].asnumpy())
        fout = fexe.forward(is_train=False)[0].asnumpy()

        S.symbol._reset_naming()
        single = mx.rnn.LSTMCell(num_hidden=8, prefix="lstm_l0_", forget_bias=0.0)
        so, _ = single.unroll(5, inputs=S.var("data"), merge_outputs=True)
        sexe = _bind_fill(so, x, **cell_args)
        sout = sexe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(fout, sout, rtol=1e-5, atol=1e-6)

        # pack -> unpack roundtrip is exact
        rt = fused.unpack_weights(packed)
        for k, v in cell_args.items():
            np.testing.assert_allclose(rt[k].asnumpy(), v)

    def test_bidirectional_and_modifiers(self):
        S.symbol._reset_naming()
        bi = mx.rnn.BidirectionalCell(
            mx.rnn.LSTMCell(num_hidden=4, prefix="fw_"),
            mx.rnn.LSTMCell(num_hidden=4, prefix="bw_"))
        out, states = bi.unroll(3, inputs=S.var("data"), merge_outputs=True)
        x = np.random.RandomState(7).randn(2, 3, 5).astype(np.float32)
        got = _bind_fill(out, x).forward(is_train=False)[0]
        assert got.shape == (2, 3, 8)  # fw|bw concat
        assert len(states) == 4

        S.symbol._reset_naming()
        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.ResidualCell(mx.rnn.RNNCell(num_hidden=5, prefix="r0_")))
        stack.add(mx.rnn.DropoutCell(0.0))
        out, _ = stack.unroll(3, inputs=S.var("data"), merge_outputs=True)
        got = _bind_fill(out, np.random.RandomState(8).randn(2, 3, 5)
                         .astype(np.float32)).forward(is_train=False)[0]
        assert got.shape == (2, 3, 5)

    def test_begin_state_contract(self):
        cell = mx.rnn.LSTMCell(num_hidden=4, prefix="bs_")
        states = cell.begin_state(batch_size=3)
        assert len(states) == 2
        with pytest.raises(ValueError, match="batch_size"):
            cell.begin_state()


def test_bucket_sentence_iter():
    sentences = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [1, 1, 1], [2, 2],
                 [3, 3, 3, 3], [5, 5, 5], [7, 7]]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=2, buckets=[3, 4],
                                   invalid_label=-1)
    seen = 0
    for batch in it:
        assert batch.data[0].shape[0] == 2
        assert batch.data[0].shape[1] == batch.bucket_key
        assert batch.label[0].shape == batch.data[0].shape
        # label is data shifted left, padded with invalid
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])
        assert (l[:, -1] == -1).all()
        seen += 1
    assert seen >= 2
    it.reset()
    assert next(iter(it)) is not None


def test_forget_bias_via_initializer_and_fused_parity_default():
    """forget_bias flows through the LSTMBias init attr (reference
    semantics — forward adds nothing), so fused/unfused parity holds at
    the DEFAULT forget_bias too."""
    S.symbol._reset_naming()
    cell = mx.rnn.LSTMCell(num_hidden=4, prefix="fb_", forget_bias=2.5)
    out, _ = cell.unroll(2, inputs=S.var("data"), merge_outputs=True)
    pred = S.FullyConnected(S.Reshape(out, shape=(-1, 4)), num_hidden=2,
                            name="p")
    smx = S.SoftmaxOutput(pred, S.var("softmax_label"), name="softmax")
    mod = mx.mod.Module(smx, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (2, 2, 3))],
             label_shapes=[("softmax_label", (2, 2))])
    mod.init_params(mx.initializer.Xavier())
    b = mod.get_params()[0]["fb_i2h_bias"].asnumpy()
    assert (b[4:8] == 2.5).all() and (b[:4] == 0).all()

    # default-forget-bias cells share weights with the fused kernel exactly
    rng = np.random.RandomState(9)
    x = rng.randn(2, 4, 3).astype(np.float32)
    cell_args = {"lstm_l0_i2h_weight": rng.randn(16, 3).astype(np.float32) * 0.1,
                 "lstm_l0_h2h_weight": rng.randn(16, 4).astype(np.float32) * 0.1,
                 "lstm_l0_i2h_bias": rng.randn(16).astype(np.float32) * 0.1,
                 "lstm_l0_h2h_bias": rng.randn(16).astype(np.float32) * 0.1}
    S.symbol._reset_naming()
    fused = mx.rnn.FusedRNNCell(4, num_layers=1, mode="lstm", prefix="lstm_")
    fo, _ = fused.unroll(4, inputs=S.var("data"), layout="NTC")
    packed = fused.pack_weights({k: mx.nd.array(v) for k, v in cell_args.items()})
    fexe = _bind_fill(fo, x, lstm_parameters=packed["lstm_parameters"].asnumpy())
    fout = fexe.forward(is_train=False)[0].asnumpy()
    S.symbol._reset_naming()
    single = mx.rnn.LSTMCell(num_hidden=4, prefix="lstm_l0_")  # default fb
    so, _ = single.unroll(4, inputs=S.var("data"), merge_outputs=True)
    sexe = _bind_fill(so, x, **cell_args)
    np.testing.assert_allclose(fexe.forward(is_train=False)[0].asnumpy(),
                               sexe.forward(is_train=False)[0].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_fused_begin_state_shapes():
    fused = mx.rnn.FusedRNNCell(6, num_layers=2, mode="lstm", prefix="f2_")
    states = fused.begin_state(batch_size=3)
    assert len(states) == 2
    for st in states:
        _, outs, _ = st.infer_shape_partial()
        assert outs == [(2, 3, 6)], outs


def test_cell_graph_json_roundtrip():
    """An unrolled cell graph serializes/deserializes (tojson/load_json)
    with identical numerics AND the LSTMBias __init__ attr intact."""
    S.symbol._reset_naming()
    cell = mx.rnn.LSTMCell(num_hidden=4, prefix="l_")
    out, _ = cell.unroll(3, inputs=S.var("data"), merge_outputs=True)
    out2 = S.load_json(out.tojson())
    x = np.random.RandomState(0).randn(2, 3, 5).astype(np.float32)

    def run(sym):
        exe = _bind_fill(sym, x, seed=1)
        return exe.forward(is_train=False)[0].asnumpy()

    np.testing.assert_allclose(run(out), run(out2), rtol=1e-6)
    attrs = {n.name: n.attrs for n in out2._topo() if n.op is None}
    assert "__init__" in attrs["l_i2h_bias"]
