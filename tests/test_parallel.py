"""Parallel layer tests on the 8-device virtual CPU mesh (SURVEY.md §4:
the reference's single-host multi-process dist tests → virtual mesh)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.parallel import (
    MeshConfig,
    make_mesh,
    SPMDTrainer,
    ShardingRules,
    default_rules,
    ring_attention_sharded,
    fsdp_rules,
)

from jax.sharding import PartitionSpec as P

import jax
import jax.numpy as jnp


def _mlp(seed=7, in_dim=12):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, in_dim)))  # materialize deferred shapes
    return net



def _assert_params_close(net_a, net_b, rtol=2e-4, atol=2e-5):
    pa = net_a._collect_params_with_prefix()
    pb = net_b._collect_params_with_prefix()
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_allclose(
            pa[k].data().asnumpy(), pb[k].data().asnumpy(), rtol=rtol, atol=atol,
            err_msg=k,
        )

def _data(n=64, d=12, seed=3):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    y = rng.randint(0, 4, size=(n,)).astype(np.float32)
    return x, y


class TestMesh:
    def test_make_mesh_fills_dp(self):
        mesh = make_mesh(tp=2)
        assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
        assert set(mesh.axis_names) == {"dp", "fsdp", "tp", "pp", "sp", "ep"}

    def test_bad_divisor_raises(self):
        with pytest.raises(ValueError):
            MeshConfig(tp=3).resolve(8)

    def test_explicit_all_axes(self):
        mesh = make_mesh(dp=2, fsdp=2, tp=2)
        assert mesh.devices.size == 8


class TestShardingRules:
    def test_first_match_wins_and_fallback(self):
        mesh = make_mesh(tp=2)
        rules = ShardingRules([(r"weight$", P("tp", None))])
        assert rules.spec_for("dense0_weight", (32, 12), mesh) == P("tp", None)
        # 7 not divisible by tp=2 → replicate that axis
        assert rules.spec_for("dense1_weight", (7, 12), mesh) == P(None, None)
        assert rules.spec_for("dense0_bias", (32,), mesh) == P(None)


class TestSPMDTrainer:
    def test_matches_imperative_trainer(self):
        """The fused sharded step must produce the same params as the
        imperative Trainer path (check_consistency idiom: same model, same
        data, two execution paths)."""
        x, y = _data()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        net_a = _mlp(seed=11)
        net_b = _mlp(seed=11)
        _assert_params_close(net_a, net_b, rtol=0, atol=0)

        # path A: imperative autograd + Trainer
        trainer = gluon.Trainer(net_a.collect_params(), "sgd", {"learning_rate": 0.1, "momentum": 0.9})
        for _ in range(3):
            xa, ya = mx.nd.array(x), mx.nd.array(y)
            with mx.autograd.record():
                loss = loss_fn(net_a(xa), ya)
            loss.backward()
            trainer.step(x.shape[0])

        # path B: one jitted SPMD step on the dp mesh
        spmd = SPMDTrainer(
            net_b, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
            mesh=make_mesh(),
        )
        for _ in range(3):
            spmd.step(mx.nd.array(x), mx.nd.array(y))
        spmd.sync_to_block()

        _assert_params_close(net_a, net_b)

    def test_step_bulk_matches_sequential(self):
        """k bulked steps (one lax.scan dispatch — the engine-bulking
        analog) must equal k sequential step() calls: same params, same
        num_update, same key schedule."""
        x, y = _data()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        net_a = _mlp(seed=23)
        net_b = _mlp(seed=23)
        xa, ya = mx.nd.array(x), mx.nd.array(y)

        mx.random.seed(5)
        seq = SPMDTrainer(net_a, loss_fn, "adam", {"learning_rate": 0.01},
                          mesh=make_mesh())
        for _ in range(6):
            seq.step(xa, ya)
        seq.sync_to_block()

        mx.random.seed(5)
        blk = SPMDTrainer(net_b, loss_fn, "adam", {"learning_rate": 0.01},
                          mesh=make_mesh())
        blk.step_bulk(xa, ya, 3)
        blk.step_bulk(xa, ya, 3)
        blk.sync_to_block()

        assert blk.num_update == seq.num_update == 6
        _assert_params_close(net_a, net_b)

    def test_adam_bias_correction_not_frozen(self):
        """t must be traced, not baked: two Adam steps from zero state give
        different deltas than one (catches a constant-t recompile bug)."""
        x, y = _data(n=16)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        net = _mlp(seed=5)
        ref = _mlp(seed=5)
        spmd = SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 0.01})
        tr = gluon.Trainer(ref.collect_params(), "adam", {"learning_rate": 0.01})
        for _ in range(4):
            spmd.step(mx.nd.array(x), mx.nd.array(y))
            xa, ya = mx.nd.array(x), mx.nd.array(y)
            with mx.autograd.record():
                l = loss_fn(ref(xa), ya)
            l.backward()
            tr.step(x.shape[0])
        spmd.sync_to_block()
        _assert_params_close(net, ref)

    def test_fsdp_sharding_runs_and_learns(self):
        x, y = _data(n=64, d=16)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        net = _mlp(seed=9, in_dim=16)
        mesh = make_mesh(dp=2, fsdp=4)
        spmd = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.5}, mesh=mesh, rules=fsdp_rules())
        first = float(spmd.step(mx.nd.array(x), mx.nd.array(y)).asnumpy())
        for _ in range(20):
            last = float(spmd.step(mx.nd.array(x), mx.nd.array(y)).asnumpy())
        assert last < first
        # param state really is sharded over fsdp
        sh = spmd._param_arrays[0].sharding
        assert sh.spec[0] == "fsdp" or sh.spec[0] == ("fsdp",)

    def test_tp_rules_match_replicated(self):
        """Tensor-parallel sharded weights give the same training result as
        replicated (XLA inserts the collectives; math must not change)."""
        x, y = _data(n=32, d=16)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        net_r = _mlp(seed=21, in_dim=16)
        net_t = _mlp(seed=21, in_dim=16)
        rules = ShardingRules([(r"weight$", P("tp", None))])
        a = SPMDTrainer(net_r, loss_fn, "sgd", {"learning_rate": 0.1}, mesh=make_mesh())
        b = SPMDTrainer(net_t, loss_fn, "sgd", {"learning_rate": 0.1}, mesh=make_mesh(tp=4), rules=rules)
        for _ in range(2):
            a.step(mx.nd.array(x), mx.nd.array(y))
            b.step(mx.nd.array(x), mx.nd.array(y))
        a.sync_to_block()
        b.sync_to_block()
        _assert_params_close(net_r, net_t)

    def test_3d_mesh_dp_tp_sp_matches_replicated(self):
        """The full 3-D composition on one mesh — dp x tp x sp (2x2x2,
        sequence axis sharded over 'sp') — trains identically to the
        replicated single-rule run.  The dryrun validates compile; this
        pins NUMERICS of the composed shardings."""
        mx.random.seed(11)
        rng = np.random.RandomState(11)
        B, S, D = 8, 4, 16
        x = rng.randn(B, S, D).astype(np.float32)
        y = rng.randint(0, 4, (B,)).astype(np.float32)

        def build(seed):
            mx.random.seed(seed)
            net = nn.HybridSequential()
            net.add(nn.Dense(32, flatten=False),
                    nn.Dense(4, flatten=False))
            net.initialize()
            net(mx.nd.zeros((2, S, D)))
            return net

        def loss_fn(out, label):
            # pool the sequence axis then softmax-CE over 4 classes
            from incubator_mxnet_tpu.gluon import loss as loss_mod
            pooled = out.mean(axis=1)
            return loss_mod.SoftmaxCrossEntropyLoss()(pooled, label)

        net_r = build(22)
        net_m = build(22)
        rules = ShardingRules([(r"weight$", P("tp", None))])
        a = SPMDTrainer(net_r, loss_fn, "sgd", {"learning_rate": 0.1},
                        mesh=make_mesh())
        b = SPMDTrainer(net_m, loss_fn, "sgd", {"learning_rate": 0.1},
                        mesh=make_mesh(dp=2, tp=2, sp=2), rules=rules,
                        sp_axis=1)
        for _ in range(2):
            a.step(mx.nd.array(x), mx.nd.array(y))
            b.step(mx.nd.array(x), mx.nd.array(y))
        a.sync_to_block()
        b.sync_to_block()
        _assert_params_close(net_r, net_m)

    def test_batchnorm_aux_updates_inside_step(self):
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(16), nn.BatchNorm(), nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 8)))
        x, y = _data(n=32, d=8)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        spmd = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1})
        params = net.collect_params()
        rm_name = [k for k in params if "running_mean" in k][0]
        before = params[rm_name].data().asnumpy().copy()
        spmd.step(mx.nd.array(x), mx.nd.array(y))
        spmd.sync_to_block()
        after = params[rm_name].data().asnumpy()
        assert not np.allclose(before, after)


class TestRingAttention:
    def _ref_attention(self, q, k, v, causal):
        s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(q.shape[-1])
        if causal:
            S = q.shape[2]
            mask = np.tril(np.ones((S, S), bool))
            s = np.where(mask[None, None], s, -np.inf)
        s = s - s.max(-1, keepdims=True)
        p = np.exp(s)
        p = p / p.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense_reference(self, causal):
        rng = np.random.RandomState(0)
        B, H, S, D = 2, 4, 64, 16  # S sharded 8-way → chunks of 8
        q = rng.randn(B, H, S, D).astype(np.float32)
        k = rng.randn(B, H, S, D).astype(np.float32)
        v = rng.randn(B, H, S, D).astype(np.float32)
        mesh = make_mesh(dp=1, sp=8)
        out = ring_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, causal=causal
        )
        ref = self._ref_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_jits_inside_step(self):
        mesh = make_mesh(dp=1, sp=8)
        B, H, S, D = 1, 2, 32, 8
        q = jnp.ones((B, H, S, D))

        @jax.jit
        def f(q):
            return ring_attention_sharded(q, q, q, mesh, causal=True)

        out = f(q)
        assert out.shape == (B, H, S, D)


class TestPipelineParallel:
    """GPipe-style pipeline over the 'pp' axis (capability absent in the
    reference; 'pp' mesh axis finally exercised)."""

    def _setup(self, pp=4, dp=1):
        import jax.numpy as jnp
        from incubator_mxnet_tpu.parallel import (make_mesh, pipeline_apply,
                                                  stack_stage_params)

        mesh = make_mesh(pp=pp)
        rng = np.random.RandomState(0)
        D = 8
        stages = [
            {"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.3),
             "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
            for _ in range(pp)
        ]
        params = stack_stage_params(stages, mesh)
        x = jnp.asarray(rng.randn(16, D).astype(np.float32))

        def stage_fn(p, h):
            import jax
            return jax.nn.tanh(h @ p["w"] + p["b"])

        return mesh, stages, params, x, stage_fn, pipeline_apply

    def test_matches_sequential(self):
        import jax
        mesh, stages, params, x, stage_fn, pipeline_apply = self._setup()
        out = pipeline_apply(stage_fn, params, x, mesh, n_microbatches=4)
        ref = x
        for s in stages:
            ref = stage_fn(s, ref)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_flow_through_pipeline(self):
        import jax
        import jax.numpy as jnp
        mesh, stages, params, x, stage_fn, pipeline_apply = self._setup()

        def loss_pipe(p, x):
            return (pipeline_apply(stage_fn, p, x, mesh, n_microbatches=4) ** 2).sum()

        def loss_seq(stage_list, x):
            h = x
            for s in stage_list:
                h = stage_fn(s, h)
            return (h ** 2).sum()

        g_pipe = jax.grad(loss_pipe)(params, x)
        g_seq = jax.grad(loss_seq)(stages, x)
        for i in range(len(stages)):
            np.testing.assert_allclose(np.asarray(g_pipe["w"][i]),
                                       np.asarray(g_seq[i]["w"]),
                                       rtol=1e-4, atol=1e-5)

    def test_jit_compiles_once(self):
        import jax
        mesh, stages, params, x, stage_fn, pipeline_apply = self._setup(pp=2)
        fn = jax.jit(lambda p, x: pipeline_apply(stage_fn, p, x, mesh, 4))
        o1 = fn(params, x)
        o2 = fn(params, x)
        np.testing.assert_allclose(np.asarray(o1), np.asarray(o2))


def test_pipeline_microbatch_sweep_pp4():
    """GPipe pipeline at pp=4: every n_microbatches in the sweep must
    reproduce sequential stage application exactly (the bubble schedule
    changes, the math must not) — VERDICT r4 scale-out evidence."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.parallel import (
        make_mesh, pipeline_apply, stack_stage_params)

    P = 4
    mesh = make_mesh(pp=P, devices=jax.devices()[:P])
    rng = np.random.RandomState(0)
    stages = [{"w": jnp.asarray(rng.randn(16, 16).astype(np.float32) * 0.2),
               "b": jnp.asarray(rng.randn(16).astype(np.float32) * 0.1)}
              for _ in range(P)]
    params = stack_stage_params(stages, mesh)

    def stage_fn(p, h):
        return jax.nn.tanh(h @ p["w"] + p["b"])

    x = jnp.asarray(rng.randn(24, 16).astype(np.float32))
    ref = x
    for s in stages:
        ref = stage_fn(s, ref)

    for M in (1, 2, 3, 4, 6, 8, 12, 24):
        out = pipeline_apply(stage_fn, params, x, mesh, n_microbatches=M)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6, err_msg=f"M={M}")

    # and the backward pipeline: grads through the pipeline must match
    # grads through the sequential composition
    def loss_pipe(ps, xx):
        return jnp.sum(pipeline_apply(stage_fn, ps, xx, mesh,
                                      n_microbatches=4) ** 2)

    def loss_seq(stage_list, xx):
        h = xx
        for s in stage_list:
            h = stage_fn(s, h)
        return jnp.sum(h ** 2)

    gp_params, gp_x = jax.grad(loss_pipe, argnums=(0, 1))(params, x)
    gs_stages, gs_x = jax.grad(loss_seq, argnums=(0, 1))(stages, x)
    np.testing.assert_allclose(np.asarray(gp_x), np.asarray(gs_x),
                               rtol=1e-4, atol=1e-5)
    # stage-parameter grads: the stacked [P, ...] pipeline grads must match
    # each sequential stage's grads (weight updates are what training uses)
    for s_idx in range(P):
        for key in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(gp_params[key][s_idx]),
                np.asarray(gs_stages[s_idx][key]),
                rtol=1e-4, atol=1e-5, err_msg=f"stage {s_idx} {key}")


def test_pipeline_time_sliced_bound_matches_sequential():
    """The single-device time-sliced GPipe wavefront (VERDICT r4 weak #6
    sanity bound, tools/bench_pipeline.py) computes exactly the
    sequential composition across the M sweep."""
    import functools

    import jax.numpy as jnp

    from tools.bench_pipeline import _time_sliced

    P, width = 4, 16
    rng = np.random.RandomState(0)
    ws = jnp.asarray(rng.randn(P, width, width).astype(np.float32) * 0.05)

    def stage_fn_w(w, h):
        for _ in range(2):
            h = jnp.tanh(h @ w)
        return h

    x = jnp.asarray(rng.randn(16, width).astype(np.float32))
    ref = x
    for s in range(P):
        ref = stage_fn_w(ws[s], ref)
    for M in (1, 2, 4, 8, 16):
        out = _time_sliced(ws, x, stage_fn_w=stage_fn_w, P=P, M=M)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
