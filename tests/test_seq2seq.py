"""Seq2seq transformer tests: train step, causality, bucketed decode,
greedy + beam search (parity idiom: the reference's bucketing seq2seq
example tests + GluonNLP's beam-search unit tests)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.model_zoo.transformer import (
    Transformer, transformer_base, transformer_big, transformer_sharding_rules,
    greedy_search, beam_search)
from incubator_mxnet_tpu.ndarray.ndarray import NDArray
from incubator_mxnet_tpu.ops.nn import streaming_softmax_ce
from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

VOCAB, BOS, EOS = 23, 1, 2


def _tiny(dropout=0.0, seed=0):
    mx.random.seed(seed)
    net = Transformer(VOCAB, units=32, hidden_size=64, num_heads=2,
                      num_encoder_layers=2, num_decoder_layers=2,
                      dropout=dropout, max_length=64)
    net.initialize()
    return net


def _copy_batch(B, S, seed=0):
    """The classic sanity task: target = source."""
    rng = np.random.RandomState(seed)
    src = rng.randint(3, VOCAB, (B, S)).astype(np.int32)
    tgt_in = np.concatenate([np.full((B, 1), BOS, np.int32), src[:, :-1]], axis=1)
    return src, tgt_in, src  # (src, tgt_in, tgt_out)


class TestTransformerSeq2Seq:
    def test_forward_shapes(self):
        net = _tiny()
        src, tgt_in, _ = _copy_batch(2, 8)
        out = net(mx.nd.array(src, dtype="int32"), mx.nd.array(tgt_in, dtype="int32"))
        assert out.shape == (2, 8, VOCAB)

    def test_decoder_is_causal(self):
        """Changing tgt[t+1:] must not change logits at position t."""
        net = _tiny()
        src, tgt_in, _ = _copy_batch(1, 8)
        mem = net.encode(mx.nd.array(src, dtype="int32"))
        l1 = net.decode(mx.nd.array(tgt_in, dtype="int32"), mem).asnumpy()
        tgt2 = tgt_in.copy()
        tgt2[:, 5:] = (tgt2[:, 5:] + 7) % VOCAB
        l2 = net.decode(mx.nd.array(tgt2, dtype="int32"), mem).asnumpy()
        np.testing.assert_allclose(l1[:, :5], l2[:, :5], atol=1e-5)
        assert np.abs(l1[:, 5:] - l2[:, 5:]).max() > 1e-4

    def test_copy_task_trains_and_decodes(self):
        """Train on the copy task until greedy decode reproduces inputs.

        Deterministic by construction: every PRNG is seeded (model init
        via ``_tiny`` -> ``mx.random.seed(0)``, per-step batches by step
        index) and convergence is judged on a FIXED held-out batch — the
        old version asserted on whatever the last *random* training
        batch's loss happened to be, which sat right at the threshold
        (measured 0.54 vs 0.5 at step 150).  At 200 steps the held-out
        loss is 0.040; the 0.25 threshold leaves >6x margin."""
        mx.random.seed(0)
        net = _tiny()
        B, S = 16, 8

        def loss_fn(out, label):
            return NDArray(streaming_softmax_ce(out._data, label._data).mean(axis=-1))

        src0, tgt0, _ = _copy_batch(B, S)
        net(mx.nd.array(src0, dtype="int32"), mx.nd.array(tgt0, dtype="int32"))
        trainer = SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 3e-3},
                              mesh=make_mesh())
        for i in range(200):
            src, tgt_in, tgt_out = _copy_batch(B, S, seed=i)
            trainer.step((mx.nd.array(src, dtype="int32"),
                          mx.nd.array(tgt_in, dtype="int32")),
                         mx.nd.array(tgt_out, dtype="int32"))
        trainer.sync_to_block()
        src, tgt_in, tgt_out = _copy_batch(B, S, seed=9999)  # held out
        out = net(mx.nd.array(src, dtype="int32"),
                  mx.nd.array(tgt_in, dtype="int32"))
        final = float(loss_fn(out, mx.nd.array(tgt_out, dtype="int32"))
                      .asnumpy().mean())
        assert final < 0.25, final

        # greedy decode should now copy (teacher-free)
        src = np.array([[5, 9, 12, 7, 5, 11, 4, 8]], np.int32)
        toks, _ = greedy_search(net, mx.nd.array(src, dtype="int32"),
                                bos=BOS, eos=EOS, max_length=12)
        assert (toks[0, 1:1 + 4] == src[0, :4]).mean() >= 0.75, toks

    def test_beam_search_contract(self):
        """Beam results are sorted, beam-1 == greedy argmax path, shapes ok."""
        net = _tiny()
        src = np.array([[5, 9, 12, 7], [3, 4, 5, 6]], np.int32)
        toks, scores = beam_search(net, mx.nd.array(src, dtype="int32"),
                                   bos=BOS, eos=EOS, beam_size=3, max_length=10)
        assert toks.shape == (2, 3, 10) and scores.shape == (2, 3)
        assert (np.diff(scores, axis=1) <= 1e-9).all()  # sorted best-first
        assert (toks[:, :, 0] == BOS).all()

    def test_beam_search_beats_or_matches_greedy_score(self):
        """A wider beam can only improve the (length-penalized) model score."""
        net = _tiny(seed=3)
        src = np.array([[5, 9, 12, 7, 3, 10, 14, 6]], np.int32)
        _, s1 = beam_search(net, mx.nd.array(src, dtype="int32"),
                            bos=BOS, eos=EOS, beam_size=1, max_length=10)
        _, s4 = beam_search(net, mx.nd.array(src, dtype="int32"),
                            bos=BOS, eos=EOS, beam_size=4, max_length=10)
        assert s4[0, 0] >= s1[0, 0] - 1e-6

    def test_kv_cache_matches_rerun_greedy(self):
        """O(T) KV-cache decode must produce the same tokens as the
        re-run-the-prefix oracle."""
        net = _tiny(seed=5)
        src = np.random.RandomState(5).randint(3, VOCAB, (3, 8)).astype(np.int32)
        t_cache, l_cache = greedy_search(net, mx.nd.array(src, dtype="int32"),
                                         bos=BOS, eos=EOS, max_length=24,
                                         use_cache=True)
        t_rerun, l_rerun = greedy_search(net, mx.nd.array(src, dtype="int32"),
                                         bos=BOS, eos=EOS, max_length=24,
                                         use_cache=False)
        np.testing.assert_array_equal(t_cache, t_rerun)
        np.testing.assert_array_equal(l_cache, l_rerun)

    def test_kv_cache_matches_rerun_beam(self):
        net = _tiny(seed=6)
        src = np.random.RandomState(6).randint(3, VOCAB, (2, 8)).astype(np.int32)
        tk_c, s_c = beam_search(net, mx.nd.array(src, dtype="int32"),
                                bos=BOS, eos=EOS, beam_size=3, max_length=16,
                                use_cache=True)
        tk_r, s_r = beam_search(net, mx.nd.array(src, dtype="int32"),
                                bos=BOS, eos=EOS, beam_size=3, max_length=16,
                                use_cache=False)
        np.testing.assert_array_equal(tk_c, tk_r)
        np.testing.assert_allclose(s_c, s_r, rtol=1e-5, atol=1e-6)

    def test_kv_cache_speedup_at_S64(self):
        """VERDICT round-3 gate: cached beam decode ≥5× faster at S=64
        than the re-run-prefix path (steady-state, compile excluded)."""
        import time

        net = Transformer(VOCAB, units=128, hidden_size=256, num_heads=4,
                          num_encoder_layers=2, num_decoder_layers=4,
                          dropout=0.0, max_length=64)
        net.initialize()
        src = np.random.RandomState(7).randint(3, VOCAB, (4, 16)).astype(np.int32)
        args = dict(bos=BOS, eos=EOS, beam_size=4, max_length=64)
        # warm both jit caches (compile time excluded from the ratio)
        beam_search(net, mx.nd.array(src, dtype="int32"), use_cache=True, **args)
        beam_search(net, mx.nd.array(src, dtype="int32"), use_cache=False, **args)
        t0 = time.perf_counter()
        beam_search(net, mx.nd.array(src, dtype="int32"), use_cache=True, **args)
        t_cache = time.perf_counter() - t0
        t0 = time.perf_counter()
        beam_search(net, mx.nd.array(src, dtype="int32"), use_cache=False, **args)
        t_rerun = time.perf_counter() - t0
        assert t_rerun / t_cache >= 5.0, (t_rerun, t_cache)

    def test_transformer_big_config(self):
        net = transformer_big(vocab_size=100)
        assert net._units == 1024
        rules = transformer_sharding_rules()
        spec = rules.spec_for("enc_layer0_attn_qkv_weight", (96, 32), make_mesh())
        assert spec is not None and "tp" in str(spec)


class TestBucketedDecode:
    def test_bucketing_limits_jit_signatures(self):
        """Decode prefixes pad to power-of-two buckets so the jit cache
        stays small (the BucketingModule discipline for inference)."""
        from incubator_mxnet_tpu.gluon.model_zoo.transformer import _bucket
        assert [_bucket(t, 64) for t in (1, 7, 8, 9, 17, 40, 64)] == \
            [8, 8, 8, 16, 32, 64, 64]
