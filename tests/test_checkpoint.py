"""Checkpoint/resume tier: reference .params binary format round-trip +
preemption (SIGTERM) checkpointing with same-loss-curve resume.

Parity anchors: [U:src/ndarray/ndarray.cc] Save/Load binary layout,
[U:python/mxnet/model.py] save_checkpoint, SURVEY.md §5 preemption plan.
"""
import os
import signal
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, gluon
from incubator_mxnet_tpu.checkpoint import CheckpointManager

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestParamsFormat:
    def test_dict_roundtrip(self, tmp_path):
        f = str(tmp_path / "w.params")
        data = {
            "arg:fc1_weight": mx.nd.array(np.random.RandomState(0).randn(4, 3).astype(np.float32)),
            "aux:bn_mean": mx.nd.array(np.arange(5, dtype=np.float32)),
            "int_arr": mx.nd.array(np.arange(6).reshape(2, 3), dtype="int32"),
        }
        mx.nd.save(f, data)
        loaded = mx.nd.load(f)
        assert set(loaded) == set(data)
        for k in data:
            np.testing.assert_array_equal(loaded[k].asnumpy(), data[k].asnumpy())
            assert loaded[k].dtype == data[k].dtype

    def test_list_roundtrip(self, tmp_path):
        f = str(tmp_path / "l.params")
        data = [mx.nd.array(np.random.rand(3, 3).astype(np.float32)),
                mx.nd.array(np.random.rand(2).astype(np.float64))]
        mx.nd.save(f, data)
        loaded = mx.nd.load(f)
        assert isinstance(loaded, list) and len(loaded) == 2
        for a, b in zip(loaded, data):
            np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())

    def test_binary_layout_matches_reference_spec(self, tmp_path):
        """Byte-level check of the header the reference reader expects:
        list magic 0x112, V2 per-array magic, dense stype, int64 dims."""
        f = str(tmp_path / "h.params")
        mx.nd.save(f, {"w": mx.nd.ones((2, 3))})
        raw = open(f, "rb").read()
        magic, reserved, count = struct.unpack_from("<QQQ", raw, 0)
        assert magic == 0x112 and reserved == 0 and count == 1
        nd_magic, stype, ndim = struct.unpack_from("<Iii", raw, 24)
        assert nd_magic == 0xF993FAC9 and stype == 0 and ndim == 2
        d0, d1 = struct.unpack_from("<qq", raw, 36)
        assert (d0, d1) == (2, 3)

    def test_npz_still_loads(self, tmp_path):
        f = str(tmp_path / "w.npz")
        mx.nd.save(f, {"a": mx.nd.ones((2,))})
        loaded = mx.nd.load(f)
        np.testing.assert_array_equal(loaded["a"].asnumpy(), [1, 1])

    def test_gluon_save_parameters_params_ext(self, tmp_path):
        net = gluon.nn.Dense(3)
        net.initialize()
        net(mx.nd.ones((1, 4)))
        f = str(tmp_path / "net.params")
        net.save_parameters(f)
        # file must be readable by the reference-layout loader
        loaded = mx.nd.load(f)
        assert any("weight" in k for k in loaded)


class TestCheckpointManager:
    def _make(self, tmp_path):
        mx.random.seed(3)
        net = gluon.nn.Dense(1)
        net.initialize()
        net(mx.nd.ones((1, 2)))
        trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
        return net, trainer

    def test_save_restore_cycle(self, tmp_path):
        net, trainer = self._make(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"), net=net, trainer=trainer,
                                save_on_sigterm=False)
        w0 = net.weight.data().asnumpy().copy()
        t = mgr.save(5)
        if t:
            t.join()
        # perturb, then restore
        net.weight.data()[:] = 99.0
        assert mgr.restore() == 5
        np.testing.assert_allclose(net.weight.data().asnumpy(), w0)

    def test_keep_gc(self, tmp_path):
        net, trainer = self._make(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"), net=net, trainer=trainer,
                                save_on_sigterm=False, keep=2, async_write=False)
        for s in (1, 2, 3, 4):
            mgr.save(s, blocking=True)
        metas = [p for p in os.listdir(tmp_path) if p.endswith(".meta")]
        assert len(metas) == 2
        assert mgr.latest_step() == 4

    def _torn_meta(self, mgr, step):
        """Fabricate an interrupted write: a meta landed but the data files
        it references never did (killed between the two)."""
        import json
        pth, sth, mth = mgr._paths(step)
        with open(mth, "w") as f:
            json.dump({"step": step,
                       "params": os.path.basename(pth),
                       "states": os.path.basename(sth)}, f)

    def test_latest_step_skips_torn_meta(self, tmp_path):
        net, trainer = self._make(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"), net=net, trainer=trainer,
                                save_on_sigterm=False, async_write=False)
        w0 = net.weight.data().asnumpy().copy()
        mgr.save(2, blocking=True)
        self._torn_meta(mgr, 5)   # newest meta is torn
        assert mgr.latest_step() == 2
        net.weight.data()[:] = 99.0
        assert mgr.restore() == 2
        np.testing.assert_allclose(net.weight.data().asnumpy(), w0)

    def test_gc_counts_committed_not_files(self, tmp_path):
        """A torn later write must never age out the newest COMPLETE
        checkpoint: GC keeps by commit (complete meta), not by file count
        or mtime."""
        net, trainer = self._make(tmp_path)
        mgr = CheckpointManager(str(tmp_path / "ck"), net=net, trainer=trainer,
                                save_on_sigterm=False, keep=2, async_write=False)
        mgr.save(1, blocking=True)
        mgr.save(2, blocking=True)
        self._torn_meta(mgr, 3)   # interrupted write after step 2
        mgr.save(4, blocking=True)
        # keep=2 complete checkpoints: {2, 4}.  If the torn step-3 meta
        # counted, step 2 — the newest checkpoint that was committed when
        # the interruption hit — would have been deleted.
        steps = sorted(m["step"] for _, m in mgr._complete_metas())
        assert steps == [2, 4]
        assert mgr.latest_step() == 4
        # step 1's files are gone, step 2's survive
        assert not any(p.startswith("ck-0000001") for p in os.listdir(tmp_path))
        assert any(p.startswith("ck-0000002") and p.endswith(".meta")
                   for p in os.listdir(tmp_path))


def test_sigterm_mid_fit_resumes_same_curve(tmp_path):
    """kill -TERM a training process mid-fit; a fresh process restores and
    continues to the same loss curve as an uninterrupted run."""
    script = os.path.join(ROOT, "tests", "preempt_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"

    gold = subprocess.run(
        [sys.executable, script, str(tmp_path / "gold"), "uninterrupted"],
        env=env, capture_output=True, text=True, timeout=240)
    assert gold.returncode == 0, gold.stderr[-2000:]

    p = subprocess.Popen(
        [sys.executable, script, str(tmp_path / "pre"), "phase1"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # wait for the worker to report it is mid-training, then SIGTERM it
    line = p.stdout.readline()
    assert "TRAINING" in line, line
    time.sleep(0.3)
    p.send_signal(signal.SIGTERM)
    p.wait(timeout=120)

    resumed = subprocess.run(
        [sys.executable, script, str(tmp_path / "pre"), "resume"],
        env=env, capture_output=True, text=True, timeout=240)
    assert resumed.returncode == 0, resumed.stderr[-2000:]

    final_gold = float(gold.stdout.strip().splitlines()[-1].split()[-1])
    final_resumed = float(resumed.stdout.strip().splitlines()[-1].split()[-1])
    np.testing.assert_allclose(final_resumed, final_gold, rtol=1e-4, atol=1e-5)


def test_sigkill_mid_checkpoint_write_keeps_last_complete(tmp_path):
    """SIGKILL (no handler, no cleanup) landing MID-WRITE of a checkpoint:
    the tmp + os.replace discipline must leave the last COMPLETE
    checkpoint loadable — restore() never sees a torn file."""
    script = os.path.join(ROOT, "tests", "preempt_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    prefix = str(tmp_path / "kw")

    p = subprocess.Popen(
        [sys.executable, script, prefix, "phase1_killwrite"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    killed_during = None
    try:
        for line in p.stdout:
            if line.startswith("SAVING"):
                killed_during = int(line.split()[1])
                if killed_during >= 3:
                    break
        assert killed_during is not None, "worker never reached a save"
        time.sleep(0.15)  # inside the slowed write: tmp exists, no replace
        p.kill()          # SIGKILL: no signal handler can run
        p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()

    resumed = subprocess.run(
        [sys.executable, script, prefix, "resume"],
        env=env, capture_output=True, text=True, timeout=240)
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    start = int(resumed.stdout.split("RESUMED_FROM")[1].split()[0])
    # the restored step is a COMPLETE checkpoint at or just below the one
    # being written when the kill landed — never ahead of it
    assert 1 <= start <= killed_during, (start, killed_during)
    final = float(resumed.stdout.strip().splitlines()[-1].split()[-1])
    assert np.isfinite(final)


class TestShardedCheckpoint:
    """Sharded save/restore: every process writes only its addressable
    shards (no global gather) — SURVEY §5's sharded-async plan, exercised
    on the 8-device mesh with fsdp+tp sharded params."""

    def test_roundtrip_sharded_trainer_state(self, tmp_path):
        import jax
        import numpy as np_

        from incubator_mxnet_tpu import gluon
        from incubator_mxnet_tpu.checkpoint import restore_sharded, save_sharded
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu", flatten=False),
                gluon.nn.Dense(8, flatten=False))
        net.initialize()
        net(mx.nd.zeros((2, 8)))

        def loss_fn(out, label):
            return ((out - label) ** 2).mean(axis=-1)

        mesh = make_mesh(fsdp=2, tp=2)
        trainer = SPMDTrainer(net, loss_fn, "adam", {"learning_rate": 1e-2},
                              mesh=mesh)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.rand(8, 8).astype(np.float32))
        y = mx.nd.array(rng.rand(8, 8).astype(np.float32))
        for _ in range(3):
            trainer.step(x, y)

        ref_params = [np_.asarray(a) for a in trainer._param_arrays]
        ref_state0 = jax.tree_util.tree_map(np_.asarray, trainer._opt_states)
        prefix = str(tmp_path / "sh")
        save_sharded(prefix, 3, trainer)

        # keep training (diverges), then restore back to step 3
        for _ in range(2):
            trainer.step(x, y)
        assert restore_sharded(prefix, trainer) == 3
        assert trainer._t == 3 and trainer._optimizer.num_update == 3
        for got, want in zip(trainer._param_arrays, ref_params):
            np_.testing.assert_array_equal(np_.asarray(got), want)
        got_state0 = jax.tree_util.tree_map(np_.asarray, trainer._opt_states)
        jax.tree_util.tree_map(np_.testing.assert_array_equal, got_state0, ref_state0)
        # restored arrays keep their shardings and training continues
        l = trainer.step(x, y)
        assert np_.isfinite(float(np_.asarray(l._data)))

    def test_shard_files_hold_shards_not_replicas(self, tmp_path):
        import numpy as np_

        from incubator_mxnet_tpu import gluon
        from incubator_mxnet_tpu.checkpoint import save_sharded
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
        from incubator_mxnet_tpu.parallel.sharding import ShardingRules
        from jax.sharding import PartitionSpec as P

        mx.random.seed(1)
        net = gluon.nn.Dense(16, flatten=False)
        net.initialize()
        net(mx.nd.zeros((2, 32)))
        rules = ShardingRules([(r".*weight$", P("fsdp", None))], default=P())
        mesh = make_mesh(fsdp=8)
        trainer = SPMDTrainer(net, lambda o, l: ((o - l) ** 2).mean(axis=-1),
                              "sgd", {"learning_rate": 0.1}, mesh=mesh, rules=rules)
        prefix = str(tmp_path / "sh2")
        save_sharded(prefix, 1, trainer)
        with np_.load(prefix + "-0000001.shard0.npz") as z:
            # weight is (16, 32) sharded 8-way on axis 0 → 8 unique (2, 32)
            # shards; the replicated (16,) bias deduplicates to ONE copy
            weight_keys = [k for k in z.files if z[k].shape == (2, 32)]
            assert len(weight_keys) == 8
            bias_keys = [k for k in z.files if z[k].shape == (16,) and k.startswith("p")]
            assert len(bias_keys) == 1

    def test_layout_mismatch_raises_clearly(self, tmp_path):
        import numpy as np_
        import pytest as pytest_

        from incubator_mxnet_tpu import gluon
        from incubator_mxnet_tpu.checkpoint import restore_sharded, save_sharded
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
        from incubator_mxnet_tpu.parallel.sharding import ShardingRules
        from jax.sharding import PartitionSpec as P

        def build(fsdp):
            mx.random.seed(2)
            net = gluon.nn.Dense(16, flatten=False)
            net.initialize()
            net(mx.nd.zeros((2, 32)))
            rules = ShardingRules([(r".*weight$", P("fsdp", None))], default=P())
            return SPMDTrainer(net, lambda o, l: ((o - l) ** 2).mean(axis=-1),
                               "sgd", {"learning_rate": 0.1},
                               mesh=make_mesh(fsdp=fsdp), rules=rules)

        prefix = str(tmp_path / "mm")
        save_sharded(prefix, 1, build(fsdp=8))
        with pytest_.raises(ValueError, match="layout mismatch"):
            restore_sharded(prefix, build(fsdp=4))

    def test_keep_retention(self, tmp_path):
        import os as os_

        from incubator_mxnet_tpu import gluon
        from incubator_mxnet_tpu.checkpoint import save_sharded
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

        mx.random.seed(3)
        net = gluon.nn.Dense(4, flatten=False)
        net.initialize()
        net(mx.nd.zeros((2, 4)))
        trainer = SPMDTrainer(net, lambda o, l: ((o - l) ** 2).mean(axis=-1),
                              "sgd", {"learning_rate": 0.1}, mesh=make_mesh())
        prefix = str(tmp_path / "gc")
        for s in (1, 2, 3, 4):
            save_sharded(prefix, s, trainer, keep=2)
        metas = [p for p in os_.listdir(tmp_path) if p.endswith(".shmeta")]
        shards = [p for p in os_.listdir(tmp_path) if ".shard" in p]
        assert len(metas) == 2 and len(shards) == 2
