"""gluon.contrib nn/rnn extras (parity idioms:
tests/python/unittest/test_gluon_contrib.py in the reference —
pixelshuffle shape/value checks, variational-dropout mask reuse, LSTMP
state shapes)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.contrib import nn as cnn
from incubator_mxnet_tpu.gluon.contrib import rnn as crnn


class TestContribNN:
    def test_concurrent(self):
        net = cnn.HybridConcurrent(axis=1)
        net.add(nn.Dense(3), nn.Dense(5))
        net.initialize()
        out = net(mx.nd.ones((2, 4)))
        assert out.shape == (2, 8)
        net.hybridize()
        out2 = net(mx.nd.ones((2, 4)))
        np.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=1e-6)

    def test_identity(self):
        x = mx.nd.array(np.random.rand(3, 3))
        np.testing.assert_array_equal(cnn.Identity()(x).asnumpy(), x.asnumpy())

    def test_pixelshuffle1d(self):
        net = cnn.PixelShuffle1D(2)
        x = mx.nd.array(np.arange(12).reshape(1, 4, 3).astype(np.float32))
        y = net(x)
        assert y.shape == (1, 2, 6)
        # channel c, position w*f+j comes from input channel c*f+j
        xn = x.asnumpy()
        yn = y.asnumpy()
        for c in range(2):
            for w in range(3):
                for j in range(2):
                    assert yn[0, c, w * 2 + j] == xn[0, c * 2 + j, w]

    def test_pixelshuffle2d_matches_torch_semantics(self):
        # oracle: torch.nn.functional.pixel_shuffle
        torch = pytest.importorskip("torch")
        f = 2
        x = np.random.rand(2, 8, 3, 5).astype(np.float32)
        want = torch.nn.functional.pixel_shuffle(torch.from_numpy(x), f).numpy()
        got = cnn.PixelShuffle2D(f)(mx.nd.array(x)).asnumpy()
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_pixelshuffle3d_shape_and_volume(self):
        net = cnn.PixelShuffle3D((2, 1, 2))
        x = mx.nd.array(np.random.rand(1, 8, 2, 3, 4).astype(np.float32))
        y = net(x)
        assert y.shape == (1, 2, 4, 3, 8)
        assert np.allclose(np.sort(y.asnumpy().ravel()),
                           np.sort(x.asnumpy().ravel()))

    def test_pixelshuffle_hybridized(self):
        net = cnn.PixelShuffle2D(2)
        x = mx.nd.array(np.random.rand(2, 8, 3, 3).astype(np.float32))
        eager = net(x).asnumpy()
        net.hybridize()
        np.testing.assert_allclose(net(x).asnumpy(), eager, rtol=1e-6)

    def test_sync_batch_norm_is_batch_norm(self):
        net = cnn.SyncBatchNorm(in_channels=4, num_devices=8)
        net.initialize()
        x = mx.nd.array(np.random.rand(2, 4, 3, 3).astype(np.float32))
        ref = nn.BatchNorm(in_channels=4)
        ref.initialize()
        with mx.autograd.record():
            y = net(x)
            want = ref(x)
        np.testing.assert_allclose(y.asnumpy(), want.asnumpy(),
                                   rtol=1e-5, atol=1e-5)

    def test_sparse_embedding_row_sparse_contract(self):
        emb = cnn.SparseEmbedding(10, 4)
        emb.initialize()
        assert emb.weight.stype == "row_sparse"
        out = emb(mx.nd.array(np.array([[1, 2]], dtype=np.float32)))
        assert out.shape == (1, 2, 4)


class TestContribRNN:
    def test_variational_dropout_mask_reused_across_steps(self):
        mx.random.seed(7)
        cell = crnn.VariationalDropoutCell(
            gluon.rnn.RNNCell(8), drop_outputs=0.5)
        cell.base_cell.initialize()
        x = mx.nd.ones((4, 8))
        states = cell.begin_state(batch_size=4)
        with mx.autograd.record():
            o1, states = cell(x, states)
            o2, _ = cell(x, states)
        z1 = o1.asnumpy() == 0.0
        z2 = o2.asnumpy() == 0.0
        # same units dropped at both steps (the variational property)
        np.testing.assert_array_equal(z1, z2)
        assert z1.any()
        # a fresh sequence redraws the mask eventually
        cell.reset()
        assert cell._output_mask is None

    def test_variational_dropout_inference_is_identity(self):
        cell = crnn.VariationalDropoutCell(
            gluon.rnn.RNNCell(8), drop_inputs=0.5, drop_outputs=0.5)
        cell.base_cell.initialize()
        x = mx.nd.ones((2, 8))
        states = cell.begin_state(batch_size=2)
        base_out, _ = cell.base_cell(x, states)
        out, _ = cell(x, states)
        np.testing.assert_allclose(out.asnumpy(), base_out.asnumpy())

    def test_lstmp_shapes_and_unroll(self):
        cell = crnn.LSTMPCell(hidden_size=16, projection_size=6)
        cell.initialize()
        x = mx.nd.ones((3, 5))
        states = cell.begin_state(batch_size=3)
        assert states[0].shape == (3, 6)      # projected h
        assert states[1].shape == (3, 16)     # cell state
        out, next_states = cell(x, states)
        assert out.shape == (3, 6)
        assert next_states[0].shape == (3, 6)
        assert next_states[1].shape == (3, 16)
        outs, _ = cell.unroll(4, mx.nd.ones((3, 4, 5)), layout="NTC")
        assert outs.shape == (3, 4, 6)

    def test_lstmp_gradients_flow(self):
        cell = crnn.LSTMPCell(hidden_size=8, projection_size=4)
        cell.initialize()
        x = mx.nd.array(np.random.rand(2, 3).astype(np.float32))
        states = cell.begin_state(batch_size=2)
        with mx.autograd.record():
            out, _ = cell(x, states)
            loss = mx.nd.sum(out * out)
        loss.backward()
        for name, p in cell.collect_params().items():
            g = p.grad().asnumpy()
            assert np.isfinite(g).all(), name
        assert np.abs(cell.h2r_weight.grad().asnumpy()).sum() > 0


class TestConvRNNCells:
    """contrib.rnn Conv2D{RNN,LSTM,GRU}Cell (parity:
    [U:python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py])."""

    def test_conv_lstm_matches_gate_math(self):
        """One ConvLSTM step re-derived through mx.nd.Convolution + the
        LSTM gate equations must match the cell exactly."""
        from incubator_mxnet_tpu.gluon.contrib.rnn import Conv2DLSTMCell

        mx.random.seed(3)
        cell = Conv2DLSTMCell(input_shape=(2, 5, 5), hidden_channels=3,
                              i2h_pad=(1, 1))
        cell.initialize()
        rng = np.random.RandomState(3)
        x = mx.nd.array(rng.rand(2, 2, 5, 5).astype(np.float32))
        h0 = mx.nd.array(rng.rand(2, 3, 5, 5).astype(np.float32))
        c0 = mx.nd.array(rng.rand(2, 3, 5, 5).astype(np.float32))
        out, (h1, c1) = cell(x, [h0, c0])

        i2h = mx.nd.Convolution(x, cell.i2h_weight.data(), cell.i2h_bias.data(),
                                kernel=(3, 3), pad=(1, 1), num_filter=12)
        h2h = mx.nd.Convolution(h0, cell.h2h_weight.data(), cell.h2h_bias.data(),
                                kernel=(3, 3), pad=(1, 1), num_filter=12)
        g = (i2h + h2h).asnumpy()
        sig = lambda v: 1 / (1 + np.exp(-v))
        i, f, gg, o = np.split(g, 4, axis=1)
        c_ref = sig(f) * c0.asnumpy() + sig(i) * np.tanh(gg)
        h_ref = sig(o) * np.tanh(c_ref)
        np.testing.assert_allclose(h1.asnumpy(), h_ref, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(c1.asnumpy(), c_ref, rtol=1e-4, atol=1e-5)

    def test_cells_unroll_and_train(self):
        from incubator_mxnet_tpu.gluon.contrib.rnn import (
            Conv2DGRUCell, Conv2DLSTMCell, Conv2DRNNCell)

        for Cell in (Conv2DRNNCell, Conv2DLSTMCell, Conv2DGRUCell):
            mx.random.seed(1)
            cell = Cell(input_shape=(1, 4, 4), hidden_channels=2, i2h_pad=(1, 1))
            cell.initialize()
            seq = [mx.nd.array(np.random.RandomState(i).rand(2, 1, 4, 4)
                               .astype(np.float32)) for i in range(3)]
            outs, states = cell.unroll(3, seq, merge_outputs=False)
            assert len(outs) == 3 and outs[-1].shape == (2, 2, 4, 4)
            # grads flow to both conv weights through the unrolled graph
            with mx.autograd.record():
                outs, _ = cell.unroll(3, seq, merge_outputs=False)
                loss = outs[-1].sum()
            loss.backward()
            i2h_g = cell.i2h_weight.grad().asnumpy()
            h2h_g = cell.h2h_weight.grad().asnumpy()
            assert np.abs(i2h_g).sum() > 0 and np.abs(h2h_g).sum() > 0, Cell

    def test_upstream_valid_padding_default(self):
        """Default i2h_pad=(0,0): the state H/W is the i2h conv OUTPUT size
        (upstream convention — 16x16 input, 3x3 kernel -> 14x14 state)."""
        from incubator_mxnet_tpu.gluon.contrib.rnn import Conv2DRNNCell

        cell = Conv2DRNNCell(input_shape=(3, 16, 16), hidden_channels=8)
        assert cell.state_info(2)[0]["shape"] == (2, 8, 14, 14)
        cell.initialize()
        x = mx.nd.array(np.random.RandomState(0).rand(2, 3, 16, 16)
                        .astype(np.float32))
        out, (h1,) = cell(x, cell.begin_state(batch_size=2))
        assert out.shape == (2, 8, 14, 14)

    def test_even_kernel_rejected(self):
        from incubator_mxnet_tpu.gluon.contrib.rnn import Conv2DRNNCell

        try:
            Conv2DRNNCell(input_shape=(1, 4, 4), hidden_channels=2,
                          h2h_kernel=(2, 2))
        except ValueError as e:
            assert "odd h2h" in str(e)
        else:
            raise AssertionError("expected ValueError for even kernel")


def test_estimator_round5_handlers(tmp_path):
    """MetricHandler / ValidationHandler / StoppingHandler +
    callback.module_checkpoint (round-5 parity tail)."""
    from incubator_mxnet_tpu.gluon.contrib import estimator as est

    net = gluon.nn.Dense(2)
    net.initialize()
    rng = np.random.RandomState(0)
    X = mx.nd.array(rng.rand(48, 4).astype(np.float32))
    Y = mx.nd.array((rng.rand(48) > 0.5).astype(np.float32))
    batches = [(X[i:i + 12], Y[i:i + 12]) for i in range(0, 48, 12)]

    e = est.Estimator(net, loss=gluon.loss.SoftmaxCrossEntropyLoss())
    mh = est.MetricHandler(train_metrics=[mx.metric.Accuracy()])
    calls = []
    vh = est.ValidationHandler(batches, eval_fn=lambda d: calls.append(1),
                               epoch_period=2)
    stop = est.StoppingHandler(max_batch=9)
    e.fit(batches, epochs=10, event_handlers=[mh, vh, stop])
    assert e.stop_training
    assert e.current_epoch <= 3
    assert mh.train_metrics[0].get()[1] >= 0.0  # mirrored state readable
    assert len(calls) >= 1  # period-2 validation ran via eval_fn

    # module_checkpoint drives Module.save_checkpoint on period
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    sym = S.SoftmaxOutput(S.FullyConnected(S.var("data"), num_hidden=2,
                                           name="fc"),
                          S.var("softmax_label"), name="softmax")
    it = mx.io.NDArrayIter(X.asnumpy(), Y.asnumpy(), 12,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    cb = mx.callback.module_checkpoint(mod, str(tmp_path / "mc"), period=2)
    mod.fit(it, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1},
            epoch_end_callback=cb)
    import os
    assert os.path.exists(str(tmp_path / "mc") + "-0002.params")
    assert os.path.exists(str(tmp_path / "mc") + "-0004.params")
    assert not os.path.exists(str(tmp_path / "mc") + "-0003.params")
