"""Transformer layers, flash attention (pallas-interpret + reference), BERT."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.model_zoo import bert as bert_zoo
from incubator_mxnet_tpu.ops import attention as att

import jax
import jax.numpy as jnp


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_pallas_interpret_matches_reference(self, causal, monkeypatch):
        """Flash kernel (interpret mode on CPU) vs plain XLA attention."""
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(3, 2, 128, 32).astype(np.float32))
        k = jnp.asarray(rng.randn(3, 2, 128, 32).astype(np.float32))
        v = jnp.asarray(rng.randn(3, 2, 128, 32).astype(np.float32))
        ref = att.attention_reference(q, k, v, causal=causal)
        monkeypatch.setenv("MXNET_TPU_FLASH", "interpret")
        out = att.flash_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

    def test_gradients_match_reference(self):
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(2, 2, 64, 16).astype(np.float32))
        k = jnp.asarray(rng.randn(2, 2, 64, 16).astype(np.float32))
        v = jnp.asarray(rng.randn(2, 2, 64, 16).astype(np.float32))

        def f_flash(q, k, v):
            return att.flash_attention(q, k, v, causal=True).sum()

        def f_ref(q, k, v):
            return att.attention_reference(q, k, v, causal=True).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5)

    def test_nd_contrib_namespace(self):
        x = mx.nd.random.normal(shape=(2, 16, 32))
        out = mx.nd.contrib.fused_attention(x, x, x, num_heads=4)
        assert out.shape == (2, 16, 32)

    def test_bf16_supported(self):
        rng = np.random.RandomState(2)
        q = jnp.asarray(rng.randn(1, 2, 64, 16)).astype(jnp.bfloat16)
        out = att.flash_attention(q, q, q)
        assert out.dtype == jnp.bfloat16

    @pytest.mark.parametrize("causal", [False, True])
    def test_fused_qkv_matches_split_path(self, causal):
        """[B,S,H,Dh]-layout self-attention (fused_qkv_attention) must equal
        the split-heads bhsd path, values AND gradients."""
        rng = np.random.RandomState(3)
        b, s, h, dh = 2, 32, 4, 16
        d = h * dh
        qkv = jnp.asarray(rng.randn(b, s, 3 * d).astype(np.float32))

        from incubator_mxnet_tpu.ops.attention import fused_qkv_attention

        def split_path(qkv):
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def sp(x):
                return x.reshape(b, s, h, dh).transpose(0, 2, 1, 3)

            out = att.attention_reference(sp(q), sp(k), sp(v), causal=causal)
            return out.transpose(0, 2, 1, 3).reshape(b, s, d)

        out = fused_qkv_attention(qkv, num_heads=h, causal=causal)
        ref = split_path(qkv)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)

        g1 = jax.grad(lambda x: (fused_qkv_attention(x, num_heads=h, causal=causal) ** 2).sum())(qkv)
        g2 = jax.grad(lambda x: (split_path(x) ** 2).sum())(qkv)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=2e-4, atol=2e-5)

    def test_fused_kv_cross_attention(self):
        rng = np.random.RandomState(4)
        b, sq, sk, h, dh = 2, 8, 16, 2, 8
        d = h * dh
        q = jnp.asarray(rng.randn(b, sq, d).astype(np.float32))
        kv = jnp.asarray(rng.randn(b, sk, 2 * d).astype(np.float32))

        from incubator_mxnet_tpu.ops.attention import fused_kv_attention

        k, v = jnp.split(kv, 2, axis=-1)

        def sp(x, s):
            return x.reshape(b, s, h, dh).transpose(0, 2, 1, 3)

        ref = att.attention_reference(sp(q, sq), sp(k, sk), sp(v, sk))
        ref = ref.transpose(0, 2, 1, 3).reshape(b, sq, d)
        out = fused_kv_attention(q, kv, num_heads=h)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


class TestTransformerLayers:
    def test_encoder_cell_shapes_and_grad(self):
        mx.random.seed(0)
        cell = nn.TransformerEncoderCell(units=64, hidden_size=128, num_heads=4)
        cell.initialize()
        x = mx.nd.random.normal(shape=(2, 16, 64))
        with mx.autograd.record():
            y = cell(x)
            loss = (y * y).sum()
        loss.backward()
        assert y.shape == (2, 16, 64)
        g = cell.collect_params()[f"{cell.prefix}attn_qkv_weight"].grad()
        assert float((g.asnumpy() ** 2).sum()) > 0

    def test_encoder_hybridize_consistency(self):
        mx.random.seed(1)
        enc = nn.TransformerEncoder(num_layers=2, units=32, hidden_size=64, num_heads=2)
        enc.initialize()
        x = mx.nd.random.normal(shape=(2, 8, 32))
        eager = enc(x).asnumpy()
        enc.hybridize()
        jitted = enc(x).asnumpy()
        np.testing.assert_allclose(eager, jitted, rtol=2e-5, atol=2e-5)

    def test_decoder_cross_attention(self):
        mx.random.seed(2)
        dec = nn.TransformerDecoder(num_layers=1, units=32, hidden_size=64, num_heads=2)
        dec.initialize()
        tgt = mx.nd.random.normal(shape=(2, 6, 32))
        mem = mx.nd.random.normal(shape=(2, 10, 32))
        out = dec(tgt, mem)
        assert out.shape == (2, 6, 32)

    def test_causal_masking_in_mha(self):
        """Causal MHA output at position t must not depend on inputs > t."""
        mx.random.seed(3)
        mha = nn.MultiHeadAttention(units=16, num_heads=2, causal=True)
        mha.initialize()
        x1 = mx.nd.random.normal(shape=(1, 8, 16))
        y1 = mha(x1).asnumpy()
        x2 = x1.asnumpy().copy()
        x2[0, -1] = 99.0  # perturb the last position
        y2 = mha(mx.nd.array(x2)).asnumpy()
        np.testing.assert_allclose(y1[0, :-1], y2[0, :-1], rtol=1e-5, atol=1e-5)
        assert not np.allclose(y1[0, -1], y2[0, -1])

    def test_sinusoidal_positions(self):
        enc = nn.SinusoidalPositionalEncoding(units=32)
        x = mx.nd.zeros((1, 10, 32))
        out = enc(x).asnumpy()
        assert not np.allclose(out[0, 1], out[0, 2])

    def test_sinusoidal_odd_units(self):
        enc = nn.SinusoidalPositionalEncoding(units=31)
        out = enc(mx.nd.zeros((1, 4, 31))).asnumpy()
        assert out.shape == (1, 4, 31)

    def test_flash_unaligned_seq_falls_back(self, monkeypatch):
        """Non-power-of-two sequence lengths must not crash the pallas path
        (falls back to smaller blocks or the XLA reference)."""
        monkeypatch.setenv("MXNET_TPU_FLASH", "interpret")
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(1, 2, 200, 16).astype(np.float32))
        out = att.flash_attention(q, q, q, causal=True)
        ref = att.attention_reference(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


class TestBERT:
    def _tiny_bert(self, seed=0):
        mx.random.seed(seed)
        net = bert_zoo.BERTModel(
            vocab_size=128, units=32, hidden_size=64, num_layers=2,
            num_heads=2, max_length=64, dropout=0.0,
        )
        net.initialize()
        return net

    def test_forward_shapes(self):
        net = self._tiny_bert()
        ids = mx.nd.array(np.random.RandomState(0).randint(0, 128, (4, 16)), dtype="int32")
        types = mx.nd.zeros((4, 16), dtype="int32")
        seq, pooled = net(ids, types)
        assert seq.shape == (4, 16, 32)
        assert pooled.shape == (4, 32)

    def test_pretrain_heads_and_training_step(self):
        mx.random.seed(1)
        base = bert_zoo.BERTModel(vocab_size=64, units=32, hidden_size=64,
                                  num_layers=1, num_heads=2, max_length=32, dropout=0.0)
        model = bert_zoo.BERTForPretrain(base, vocab_size=64)
        model.initialize()
        rng = np.random.RandomState(0)
        ids = mx.nd.array(rng.randint(0, 64, (2, 8)), dtype="int32")
        labels = mx.nd.array(rng.randint(0, 64, (2, 8)), dtype="float32")
        trainer = gluon.Trainer(model.collect_params(), "adam", {"learning_rate": 1e-3})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        with mx.autograd.record():
            mlm, nsp = model(ids)
            loss = loss_fn(mlm.reshape((-1, 64)), labels.reshape((-1,)))
        loss.backward()
        trainer.step(ids.shape[0])
        assert mlm.shape == (2, 8, 64)
        assert nsp.shape == (2, 2)

    def test_bert_spmd_tp_training(self):
        """BERT with Megatron-style tp=2 sharding trains and matches the
        replicated result (XLA-inserted collectives)."""
        from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer

        def make(seed):
            mx.random.seed(seed)
            base = bert_zoo.BERTModel(vocab_size=64, units=32, hidden_size=64,
                                      num_layers=1, num_heads=2, max_length=32,
                                      dropout=0.0)
            model = bert_zoo.BERTForPretrain(base, vocab_size=64)
            model.initialize()
            model(mx.nd.zeros((2, 8), dtype="int32"))  # materialize deferred shapes
            return model

        rng = np.random.RandomState(0)
        ids = mx.nd.array(rng.randint(0, 64, (8, 8)), dtype="int32")
        labels = rng.randint(0, 64, (8, 8)).astype(np.float32)

        def loss_fn(out, label):
            mlm, nsp = out
            return gluon.loss.SoftmaxCrossEntropyLoss()(
                mlm.reshape((-1, 64)), label.reshape((-1,))
            )

        m_rep = make(7)
        m_tp = make(7)
        a = SPMDTrainer(m_rep, loss_fn, "adam", {"learning_rate": 1e-3},
                        mesh=make_mesh(dp=8))
        b = SPMDTrainer(m_tp, loss_fn, "adam", {"learning_rate": 1e-3},
                        mesh=make_mesh(dp=4, tp=2),
                        rules=bert_zoo.bert_sharding_rules())
        la = lb = None
        for _ in range(2):
            la = a.step(ids, mx.nd.array(labels))
            lb = b.step(ids, mx.nd.array(labels))
        np.testing.assert_allclose(
            la.asnumpy(), lb.asnumpy(), rtol=2e-4, atol=2e-5
        )
