"""Test harness config.

Tests run on a *virtual 8-device CPU mesh* (SURVEY.md §4: the reference's
single-host multi-process distributed tests map to
``xla_force_host_platform_device_count``), NOT the tunneled TPU chip — the
tunnel adds an RPC per eager op and hangs all of jax when it wedges.

The axon PJRT plugin registers itself from sitecustomize before conftest
runs (and jax is already imported), so env vars alone are too late: the
backend factory must be deregistered in-process, and jax_platforms set via
config.update (the env var was already parsed as 'axon').
"""
import os
import sys

# XLA flags are read when the CPU backend is *created* (lazily), so this is
# still early enough.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
except Exception:
    pass

assert jax.default_backend() == "cpu", f"tests must run on cpu, got {jax.default_backend()}"
assert len(jax.devices()) == 8, f"expected 8 virtual cpu devices, got {len(jax.devices())}"
