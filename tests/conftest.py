"""Test harness config.

Default: tests run on a *virtual 8-device CPU mesh* (SURVEY.md §4: the
reference's single-host multi-process distributed tests map to
``xla_force_host_platform_device_count``), NOT the tunneled TPU chip — the
tunnel adds an RPC per eager op and hangs all of jax when it wedges.

``MXNET_TEST_CTX=tpu`` flips the suite onto the REAL chip (the reference's
GPU tier reruns the unit suite under the accelerator context —
[U:tests/python/gpu/test_operator_gpu.py]); tests whose contract is the
8-device mesh are skipped there with an explicit marker (the machine
exposes one chip).

The axon PJRT plugin registers itself from sitecustomize before conftest
runs (and jax is already imported), so env vars alone are too late: the
backend factory must be deregistered in-process, and jax_platforms set via
config.update (the env var was already parsed as 'axon').
"""
import os
import sys

_TPU_TIER = os.environ.get("MXNET_TEST_CTX") == "tpu"

if not _TPU_TIER:
    # XLA flags are read when the CPU backend is *created* (lazily), so
    # this is still early enough.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    os.environ["JAX_PLATFORMS"] = "cpu"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")

if not _TPU_TIER:
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    assert jax.default_backend() == "cpu", f"tests must run on cpu, got {jax.default_backend()}"
    assert len(jax.devices()) == 8, f"expected 8 virtual cpu devices, got {len(jax.devices())}"
else:
    assert jax.default_backend() != "cpu", (
        "MXNET_TEST_CTX=tpu but no accelerator backend is active")


# Test files whose contract is the multi-device mesh or subprocess workers;
# on the single-chip tier they are skipped with this documented reason.
_MESH_ONLY_FILES = {
    "test_parallel.py": "dp/tp/sp/pp sharding needs the 8-device mesh",
    "test_dist.py": "multi-process kvstore tier (own launcher, CPU workers)",
    "test_checkpoint.py": "sharded/preemption checkpointing drives mesh shards",
    "test_examples.py": "example smoke tier spawns CPU-pinned subprocesses",
}

# Individual tests in otherwise chip-clean files that build explicit
# fixed-size meshes (make_mesh() with no sizes adapts to the device count
# and stays runnable).
_MESH_ONLY_TESTS = {
    "test_bert_spmd_tp_training": "builds explicit dp=8 / dp=4×tp=2 meshes",
}


def pytest_collection_modifyitems(config, items):
    if not _TPU_TIER:
        return
    import pytest

    if len(jax.devices()) >= 8:
        return
    n = len(jax.devices())
    for item in items:
        base = os.path.basename(str(getattr(item, "fspath", "")))
        reason = (_MESH_ONLY_FILES.get(base)
                  or _MESH_ONLY_TESTS.get(item.name.split("[", 1)[0]))
        if reason is not None:
            item.add_marker(pytest.mark.skip(
                reason=f"chip tier has {n} device(s): {reason}"))
