"""MXNET_TPU_PRNG / determinism interplay (config.py env contract)."""
import os
import subprocess
import sys

_PROBE = ("import sys; sys.path.insert(0, {root!r}); "
          "import incubator_mxnet_tpu, jax; "
          "print('IMPL=' + str(jax.config.jax_default_prng_impl))")


def _impl(extra_env):
    env = {k: v for k, v in os.environ.items()
           if k not in ("MXNET_TPU_PRNG", "MXNET_ENFORCE_DETERMINISM")}
    env.update(extra_env)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run([sys.executable, "-c", _PROBE.format(root=root)],
                         env=env, capture_output=True, text=True, timeout=240)
    for line in out.stdout.splitlines():
        if line.startswith("IMPL="):
            return line[5:]
    raise AssertionError(out.stdout + out.stderr)


def test_default_is_rbg():
    assert "rbg" in _impl({})


def test_determinism_implies_threefry():
    assert "threefry" in _impl({"MXNET_ENFORCE_DETERMINISM": "1"})


def test_invalid_value_falls_back_to_rbg():
    assert "rbg" in _impl({"MXNET_TPU_PRNG": "rgb"})
