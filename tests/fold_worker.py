"""Worker body for the 2-process step-fold tier: the IN-FOLD gradient
exchange (forward/backward per worker shard inside one shard_map over the
dist_sync worker mesh, per-bucket psum/codec allreduce nodes scheduled by
XLA against the remaining backward) must train to the same trajectory as
the out-of-fold path (eager backward + bucketed pushpull + fused update).

Run at process_count == 2 via tools/launch_local.py (tests/test_step_fold
launches it like tests/test_dist.py does its workers).  Exits non-zero on
any failure; prints the marker line once per rank on success.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_KVSTORE_BUCKET_BYTES", "2048")

import numpy as np


def main():
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import autograd, gluon, profiler

    L2 = gluon.loss.L2Loss()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw

    def build(seed):
        mx.random.seed(seed)
        np.random.seed(seed)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(4))
        net.initialize()
        net.hybridize()
        # per-rank local batch shard (different data per worker — the
        # exchange has to actually carry information)
        rs = np.random.RandomState(100 + rank)
        x = mx.nd.array(rs.rand(8, 6).astype(np.float32))
        y = mx.nd.array(rs.rand(8, 4).astype(np.float32))
        net(mx.nd.zeros((2, 6)))
        return net, x, y

    # --- out-of-fold reference: eager backward + bucketed pushpull ------
    net1, x, y = build(5)
    tr1 = gluon.Trainer(net1.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=kv)
    losses1 = []
    for _ in range(6):
        with autograd.record():
            loss = L2(net1(x), y)
        loss.backward()
        tr1.step(8)
        losses1.append(float(loss.mean().asscalar()))

    # --- in-fold: ONE compiled program incl. per-bucket allreduce -------
    kv2 = mx.kv.create("dist_sync")
    net2, x2, y2 = build(5)
    tr2 = gluon.Trainer(net2.collect_params(), "sgd",
                        {"learning_rate": 0.05}, kvstore=kv2)
    program = tr2.fold_step(lambda a, b: L2(net2(a), b), block=net2)
    c0 = profiler.counters()
    losses2 = []
    for _ in range(6):
        losses2.append(float(program(x2, y2).mean().asscalar()))
    c1 = profiler.counters()
    assert program.folded, program.fallback_reason
    assert c1["step_fold_call"] - c0["step_fold_call"] == 6
    assert c1["recompile_steady_state"] == c0["recompile_steady_state"], \
        "in-fold dist step recompiled in steady state"

    # local loss parity (this rank's shard, step for step) and global
    # param parity: grads crossed the wire inside the program.  The dist
    # fold holds params in donated global registers — sync them into the
    # live Parameters before reading.
    program.sync()
    np.testing.assert_allclose(losses1, losses2, rtol=1e-4, atol=1e-6)
    for pa, pb in zip(sorted(net1.collect_params().values(),
                             key=lambda p: p.name),
                      sorted(net2.collect_params().values(),
                             key=lambda p: p.name)):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(),
                                   rtol=2e-4, atol=2e-6, err_msg=pa.name)

    # save/load through the dist fold's global registers
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        fname = os.path.join(d, f"states_{rank}")
        tr2.save_states(fname)   # syncs fold registers first
        tr2.load_states(fname)   # invalidates → next call re-stages
    losses3 = [float(program(x2, y2).mean().asscalar()) for _ in range(2)]
    assert all(np.isfinite(v) for v in losses3)

    # --- K-step window through the dist fold (ISSUE 17) -----------------
    # k=2 windows with the int8-codec bucket nodes inside EACH scan
    # iteration: BIT-exact trajectory vs the same codec run per-step, in
    # half the dispatches (EF residuals ride the loop carry).
    # The IN-FOLD codec rides the env policy (MXNET_GRAD_COMPRESS), not
    # per-key store compression — that path keeps one key per param and
    # refuses bucketing.
    os.environ["MXNET_GRAD_COMPRESS"] = "int8"

    def codec_pair(k):
        kvn = mx.kv.create("dist_sync")
        netn, xn, yn = build(5)
        trn = gluon.Trainer(netn.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kvn)
        fold = trn.fold_steps(lambda a, b, n=netn: L2(n(a), b), k=k,
                              block=netn)
        return netn, fold, xn, yn

    net5, ref5, x5, y5 = codec_pair(1)
    mx.random.seed(9)
    losses5 = [float(ref5(x5, y5).mean().asscalar()) for _ in range(4)]
    assert ref5.folded, ref5.fallback_reason

    net6, fold6, x6, y6 = codec_pair(2)
    xw = mx.nd.array(np.repeat(x6.asnumpy()[None], 2, axis=0))
    yw = mx.nd.array(np.repeat(y6.asnumpy()[None], 2, axis=0))
    c0 = profiler.counters()
    mx.random.seed(9)
    losses6 = []
    for _ in range(2):                       # 2 windows == 4 logical steps
        out = np.asarray(fold6(xw, yw).asnumpy(), np.float64)
        losses6.extend(out.reshape(out.shape[0], -1).mean(axis=1))
    c1 = profiler.counters()
    assert fold6.folded, fold6.fallback_reason
    assert fold6.logical_steps == 4
    assert c1["step_fold_call"] - c0["step_fold_call"] == 2, \
        "k=2 window must be ONE dispatch per 2 logical steps"
    np.testing.assert_allclose(losses5, losses6, rtol=1e-6, atol=1e-8)
    ref5.sync()
    fold6.sync()
    # pair positionally: by this phase the gluon auto-name counters are
    # past dense9, and lexical name sort ("dense10" < "dense9") scrambles
    # cross-net pairing; collect_params() insertion order is stable
    for pa, pb in zip(list(net5.collect_params().values()),
                      list(net6.collect_params().values())):
        assert np.array_equal(pa.data().asnumpy(), pb.data().asnumpy()), \
            f"{pa.name} vs {pb.name} diverged"

    # --- ring algorithm through the dist fold (ISSUE 19) ----------------
    # same int8 codec, MXNET_GRAD_COMPRESS_ALGO=ring: the in-fold bucket
    # exchange becomes explicit encoded ppermute hops.  Pin that the fold
    # still builds, trains, recompiles nothing in steady state, and that
    # the hop/byte accounting lands in the counters (the per-hop evidence
    # for the K-fold dist leg).
    os.environ["MXNET_GRAD_COMPRESS_ALGO"] = "ring"
    net7, fold7, x7, y7 = codec_pair(2)
    mx.random.seed(9)
    losses7 = []

    def window():
        out = np.asarray(fold7(xw, yw).asnumpy(), np.float64)
        losses7.extend(out.reshape(out.shape[0], -1).mean(axis=1))

    window()                       # first window compiles the ring program
    c0 = profiler.counters()
    window()                       # second window must be steady state
    c1 = profiler.counters()
    assert fold7.folded, fold7.fallback_reason
    assert all(np.isfinite(v) for v in losses7)
    # ring int8 tracks the psum int8 trajectory within quantization slack
    np.testing.assert_allclose(losses6, losses7, rtol=5e-2, atol=5e-3)
    assert c1["recompile_steady_state"] == c0["recompile_steady_state"], \
        "ring dist fold recompiled in steady state"
    hops = c1["comms_ring_hops"] - c0["comms_ring_hops"]
    raw = c1["comms_bytes_raw"] - c0["comms_bytes_raw"]
    wire = c1["comms_bytes_wire"] - c0["comms_bytes_wire"]
    assert hops > 0 and hops % 4 == 0, hops  # 2(nw-1) per bucket * k=2
    # total wire ratio includes the exact fp32 opt-out buckets (biases),
    # which dominate at this toy scale — the tier acceptance bar is the
    # PER-HOP ratio of the compressed buckets, from the fold's hop plan
    assert raw / max(wire, 1) >= 3.0, (raw, wire)
    ca = next(e["comm_args"] for e in fold7._cache.values()
              if e.get("comm_args"))
    hop_ratio = ca["bytes_hop_fp32"] / max(ca["bytes_hop"], 1)
    assert hop_ratio >= 3.5, ca
    if rank == 0:
        import json

        print("fold_worker ring evidence: " + json.dumps(
            {"hops": int(hops), "bytes_raw": int(raw),
             "bytes_wire": int(wire),
             "byte_ratio": round(raw / max(wire, 1), 3),
             "bytes_per_hop": ca["bytes_hop"],
             "fp32_bytes_per_hop": ca["bytes_hop_fp32"],
             "hop_ratio_vs_fp32": round(hop_ratio, 3),
             "k": 2, "windows": 1, "workers": nw}), flush=True)
    os.environ.pop("MXNET_GRAD_COMPRESS_ALGO", None)

    kv.barrier()
    print(f"fold_worker rank {rank}/{nw}: all assertions passed",
          flush=True)


if __name__ == "__main__":
    main()
