"""SVRG optimization (parity idioms:
tests/python/unittest/test_contrib_svrg_module.py /
test_contrib_svrg_optimizer.py in the reference — full-grad math,
variance reduction at the snapshot, end-to-end convergence)."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
from incubator_mxnet_tpu.contrib.svrg_optimization import SVRGModule


def _linreg_sym():
    data = sym.Variable("data")
    label = sym.Variable("lin_label")
    fc = sym.FullyConnected(data, num_hidden=1, name="fc")
    return sym.LinearRegressionOutput(fc, label=label, name="lin")


def _toy_data(n=64, d=4, seed=0, noise=0.0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = np.arange(1, d + 1, dtype=np.float32)
    y = X @ w + 0.5 + noise * rng.randn(n).astype(np.float32)
    return X, y.astype(np.float32)


def _iter(X, y, batch_size):
    return mx.io.NDArrayIter(X, y, batch_size=batch_size, shuffle=False,
                             label_name="lin_label")


def test_update_full_grads_matches_dataset_mean():
    X, y = _toy_data(n=32, d=3)
    it = _iter(X, y, batch_size=8)
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",), update_freq=2)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.update_full_grads(it)

    # oracle: mean over the 4 batch gradients computed one by one
    accum = None
    it.reset()
    nb = 0
    for batch in it:
        mod._mod_aux.forward(batch, is_train=True)
        mod._mod_aux.backward()
        g = mod._mod_aux._exec.grad_dict["fc_weight"].asnumpy().copy()
        accum = g if accum is None else accum + g
        nb += 1
    np.testing.assert_allclose(mod._param_dict["fc_weight"].asnumpy(),
                               accum / nb, rtol=1e-5, atol=1e-6)


def test_variance_reduced_grad_at_snapshot_is_full_grad():
    # at w == w~ the corrected minibatch gradient equals mu exactly:
    # g - g_snap + mu = mu since both executors see identical weights
    X, y = _toy_data(n=32, d=3, seed=1)
    it = _iter(X, y, batch_size=8)
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",), update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.update_full_grads(it)
    it.reset()
    batch = next(iter(it))
    mod.forward_backward(batch)
    np.testing.assert_allclose(
        mod._exec.grad_dict["fc_weight"].asnumpy(),
        mod._param_dict["fc_weight"].asnumpy(), rtol=1e-5, atol=1e-6)


def test_svrg_fit_converges_with_constant_lr():
    X, y = _toy_data(n=64, d=4, seed=2, noise=0.01)
    it = _iter(X, y, batch_size=16)
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",), update_freq=2)
    mod.fit(it, num_epoch=30, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            eval_metric="mse")
    w = mod._exec.arg_dict["fc_weight"].asnumpy().ravel()
    b = mod._exec.arg_dict["fc_bias"].asnumpy().ravel()
    np.testing.assert_allclose(w, [1, 2, 3, 4], atol=0.15)
    np.testing.assert_allclose(b, [0.5], atol=0.15)


def test_corrected_grad_has_lower_variance_near_snapshot():
    # the variance-reduction claim, measured directly: with w close to the
    # snapshot w~, the corrected minibatch gradient g - g(w~) + mu tracks
    # the TRUE full gradient at w much better than the raw minibatch grad
    X, y = _toy_data(n=64, d=4, seed=3, noise=0.05)
    it = _iter(X, y, batch_size=16)
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",), update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 1e-3})
    mod.update_full_grads(it)
    # one small step so w != w~ but stays nearby
    it.reset()
    mod.forward_backward(next(iter(it)))
    mod.update()

    # true full gradient at the CURRENT w (oracle, via the main executor)
    it.reset()
    full = None
    nb = 0
    for batch in it:
        mx.mod.Module.forward(mod, batch, is_train=True)
        mx.mod.Module.backward(mod)
        g = mod._exec.grad_dict["fc_weight"].asnumpy().copy()
        full = g if full is None else full + g
        nb += 1
    full /= nb

    err_raw, err_vr = [], []
    it.reset()
    for batch in it:
        mx.mod.Module.forward(mod, batch, is_train=True)
        mx.mod.Module.backward(mod)
        raw = mod._exec.grad_dict["fc_weight"].asnumpy().copy()
        mod.forward_backward(batch)  # applies the SVRG correction
        vr = mod._exec.grad_dict["fc_weight"].asnumpy().copy()
        err_raw.append(np.linalg.norm(raw - full))
        err_vr.append(np.linalg.norm(vr - full))
    assert np.mean(err_vr) < 0.2 * np.mean(err_raw), (err_vr, err_raw)
