"""Worker body for the elastic chaos acceptance (tests/test_elastic.py).

A 2-process dist_sync FOLDED training run (one compiled program per step,
in-fold gradient exchange) that snapshots a :class:`RunCheckpoint` after
every step with ``kv.barrier`` as the two-phase ack.  On relaunch it
restores the newest COMMITTED snapshot and continues — under
``tools/supervise.py`` with a ``proc.kill_rank`` fault injected the run
loses a worker mid-run, the supervisor re-forms the job, and the resumed
trajectory must land on the fault-free final loss exactly (same seeds,
exact data-cursor/RNG/trainer resume).

Prints one ``ELASTIC_FINAL rank <r> <loss>`` marker per rank on success;
``ELASTIC_RESUMED rank <r> step <s>`` when a generation resumed.  Runs
with the compile guard armed (MXNET_COMPILE_WARMUP_STEPS small,
MXNET_COMPILE_GUARD=raise in the test env): a steady-state recompile
after resume fails the run.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_KVSTORE_BUCKET_BYTES", "2048")

import numpy as np

TOTAL = 8


def main():
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.io.io import NDArrayIter
    from incubator_mxnet_tpu.parallel import elastic
    from incubator_mxnet_tpu.utils import faultinject as fi

    prefix = sys.argv[1]
    L2 = gluon.loss.L2Loss()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw

    elastic.init()  # heartbeat lease + collective watchdog (no-op w/o env)

    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    net(mx.nd.zeros((2, 6)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=kv)

    # per-rank shard, shuffled — exercises the data-cursor resume
    rs = np.random.RandomState(100 + rank)
    x = rs.rand(32, 6).astype(np.float32)
    y = rs.rand(32, 4).astype(np.float32)
    it = NDArrayIter(x, y, batch_size=8, shuffle=True, seed=13 + rank)

    ck = elastic.RunCheckpoint(prefix, net=net, trainer=tr,
                               rank=rank, world=nw)
    start = 0
    payload = ck.restore(data=it)
    if payload is not None:
        start = payload["step"]
        print(f"ELASTIC_RESUMED rank {rank} step {start}", flush=True)

    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    loss = None
    for step in range(start, TOTAL):
        fi.step_faults(step, rank)   # proc.kill_rank / slow_rank gate here
        if not it.iter_next():
            it.reset()
            it.iter_next()
        a, b = it.getdata()[0], it.getlabel()[0]
        # reduce the local loss shard in numpy: an eager mean over the
        # fold's mesh-sharded output would compile AFTER the guard arms
        loss = float(np.asarray(program(a, b).asnumpy()).mean())
        ck.save(step + 1, data=it, barrier=kv.barrier)
    assert program.folded, program.fallback_reason
    c = profiler.counters()
    assert c["recompile_steady_state"] == 0, c["recompile_steady_state"]

    kv.barrier()
    print(f"ELASTIC_FINAL rank {rank} {loss:.6f}", flush=True)


if __name__ == "__main__":
    main()
