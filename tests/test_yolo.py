"""YOLOv3 model family (decode math vs hand computation, target
assignment vs a numpy oracle, NMS inference path, trainability)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.model_zoo import yolo


def _tiny_yolo(num_classes=3):
    # 2 scales worth of anchors but a small darknet for test speed
    net = yolo.YOLOV3(yolo.DarknetV3(layers=(1, 1, 1, 1, 1),
                                     channels=(8, 16, 32, 64, 128)),
                      num_classes=num_classes,
                      channels=(16, 32, 64))
    net.initialize()
    return net


class TestForward:
    def test_shapes_and_tables(self):
        net = _tiny_yolo()
        x = mx.nd.zeros((2, 3, 64, 64))
        preds, offsets, anchors, strides = net(x)
        # strides 8/16/32 on 64px: (8²+4²+2²)·3 anchors = 252 priors
        n = (64 + 16 + 4) * 3
        assert preds.shape == (2, n, 5 + 3)
        assert offsets.shape == (1, n, 2)
        assert anchors.shape == (1, n, 2)
        assert strides.shape == (1, n, 1)
        sv = np.unique(strides.asnumpy())
        np.testing.assert_array_equal(sv, [8.0, 16.0, 32.0])

    def test_hybridize_consistency(self):
        net = _tiny_yolo()
        x = mx.nd.array(np.random.rand(1, 3, 64, 64).astype(np.float32))
        eager = net(x)[0].asnumpy()
        net.hybridize()
        hybrid = net(x)[0].asnumpy()
        np.testing.assert_allclose(eager, hybrid, rtol=2e-4, atol=2e-4)


class TestDecode:
    def test_zero_logits_decode_to_anchor_boxes(self):
        # tx=ty=0 → σ=0.5 (cell center); tw=th=0 → wh = anchor
        N, C = 6, 2
        offsets = mx.nd.array(np.array([[[i, 0] for i in range(N)]],
                                       np.float32))
        anchors = mx.nd.array(np.full((1, N, 2), 20, np.float32))
        strides = mx.nd.array(np.full((1, N, 1), 8, np.float32))
        preds = mx.nd.zeros((1, N, 5 + C))
        ids, conf, boxes = yolo.yolo3_decode(preds, offsets, anchors,
                                             strides, C)
        b = boxes.asnumpy()
        for i in range(N):
            cx, cy = (i + 0.5) * 8, 0.5 * 8
            np.testing.assert_allclose(b[0, i],
                                       [cx - 10, cy - 10, cx + 10, cy + 10],
                                       rtol=1e-5)
        np.testing.assert_allclose(conf.asnumpy(), 0.25, rtol=1e-5)  # σ(0)²

    def test_nms_pipeline(self):
        net = _tiny_yolo()
        x = mx.nd.array(np.random.rand(2, 3, 64, 64).astype(np.float32))
        preds, offsets, anchors, strides = net(x)
        ids, conf, boxes = yolo.yolo3_decode(preds, offsets, anchors,
                                             strides, net.num_classes)
        dets = mx.nd.contrib.box_nms(
            mx.nd.concat(ids, conf, boxes, dim=-1),
            overlap_thresh=0.5, valid_thresh=0.01, topk=10)
        assert dets.shape[0] == 2 and dets.shape[2] == 6


class TestTargetsAndLoss:
    def test_assignment_matches_numpy_oracle(self):
        C = 3
        offsets = mx.nd.array(np.array(
            [[[i % 4, i // 4] for i in range(16)]], np.float32))
        anchors = mx.nd.array(np.full((1, 16, 2), 16, np.float32))
        strides = mx.nd.array(np.full((1, 16, 1), 16, np.float32))
        # one gt centered on cell (1, 2) → prior index 9, plus padding
        gt_boxes = mx.nd.array(np.array(
            [[[16, 32, 40, 52], [-1, -1, -1, -1]]], np.float32))
        gt_ids = mx.nd.array(np.array([[[1], [-1]]], np.float32))
        obj_t, box_t, cls_t, masks = yolo.yolo3_targets(
            gt_boxes, gt_ids, offsets, anchors, strides, C)
        assert masks.shape == (1, 16, 2)
        o = obj_t.asnumpy()[0, :, 0]
        assert o.sum() == 1.0
        idx = int(o.argmax())
        assert idx == 9  # cell x=1, y=2 → 2*4+1
        np.testing.assert_allclose(cls_t.asnumpy()[0, idx], [0, 1, 0])
        bt = box_t.asnumpy()[0, idx]
        # txy: center (28, 42)/16 - (1, 2) = (0.75, 0.625)
        np.testing.assert_allclose(bt[:2], [0.75, 0.625], rtol=1e-5)
        # twh: log(24/16), log(20/16)
        np.testing.assert_allclose(bt[2:], np.log([24 / 16, 20 / 16]),
                                   rtol=1e-5)

    def test_loss_decreases_training_to_one_box(self):
        mx.random.seed(0)
        net = _tiny_yolo(num_classes=2)
        from incubator_mxnet_tpu import gluon
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-3})
        x = mx.nd.array(np.random.rand(2, 3, 64, 64).astype(np.float32))
        gt_boxes = mx.nd.array(np.array(
            [[[8, 8, 30, 30]], [[20, 20, 50, 60]]], np.float32))
        gt_ids = mx.nd.array(np.array([[[0]], [[1]]], np.float32))
        first = None
        for _ in range(12):
            with mx.autograd.record():
                preds, offsets, anchors, strides = net(x)
                obj_t, box_t, cls_t, pos = yolo.yolo3_targets(
                    gt_boxes, gt_ids, offsets, anchors, strides, 2)
                loss = yolo.yolo3_loss(preds, obj_t, box_t, cls_t, pos, 2)
            loss.backward()
            trainer.step(2)
            v = loss.asscalar()
            assert np.isfinite(v)
            if first is None:
                first = v
        assert v < first, (first, v)

    def test_crowded_same_prior_highest_iou_wins(self):
        # two gts land on the same prior: the higher-IoU one must own it
        # outright (no summed encodings, no multi-hot classes)
        C = 3
        offsets = mx.nd.array(np.array(
            [[[i % 4, i // 4] for i in range(16)]], np.float32))
        anchors = mx.nd.array(np.full((1, 16, 2), 16, np.float32))
        strides = mx.nd.array(np.full((1, 16, 1), 16, np.float32))
        gt_boxes = mx.nd.array(np.array(
            [[[16, 32, 40, 52], [18, 34, 38, 50]]], np.float32))
        gt_ids = mx.nd.array(np.array([[[1], [2]]], np.float32))
        obj_t, box_t, cls_t, masks = yolo.yolo3_targets(
            gt_boxes, gt_ids, offsets, anchors, strides, C)
        idx = int(obj_t.asnumpy()[0, :, 0].argmax())
        bt = box_t.asnumpy()[0, idx]
        assert 0.0 < bt[0] < 1.0 and 0.0 < bt[1] < 1.0, bt  # valid σ range
        c = cls_t.asnumpy()[0, idx]
        assert c.sum() == 1.0, c  # single-hot, the winner's class
        # IoU vs the winning prior [16,32,32,48]: gt0 256/480=0.533,
        # gt1 196/380=0.516 — gt0 (class 1) owns the prior
        np.testing.assert_allclose(c, [0, 1, 0])
        np.testing.assert_allclose(bt[:2], [0.75, 0.625], rtol=1e-5)

    def test_ignore_band_excludes_near_hits_from_negatives(self):
        C = 2
        offsets = mx.nd.array(np.array(
            [[[i % 4, i // 4] for i in range(16)]], np.float32))
        # 24px anchors on a 16px grid: neighbor priors overlap the gt a
        # little (IoU ≈ 0.083), far priors not at all
        anchors = mx.nd.array(np.full((1, 16, 2), 24, np.float32))
        strides = mx.nd.array(np.full((1, 16, 1), 16, np.float32))
        gt_boxes = mx.nd.array(np.array([[[16, 16, 32, 32]]], np.float32))
        gt_ids = mx.nd.array(np.array([[[0]]], np.float32))
        obj_t, box_t, cls_t, masks = yolo.yolo3_targets(
            gt_boxes, gt_ids, offsets, anchors, strides, C,
            ignore_thresh=0.05)
        m = masks.asnumpy()[0]
        pos = int(obj_t.asnumpy()[0, :, 0].argmax())
        assert m[pos, 1] == 1.0  # positives always weighted
        # neighbors overlapping the gt above 0.2 IoU are ignored (weight 0)
        ignored = (m[:, 1] == 0).sum()
        assert ignored > 0
        # far-away priors remain negatives
        assert m[15, 1] == 1.0
