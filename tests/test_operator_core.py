"""Core-operator depth tests in the reference's test_operator idiom.

Parity target: [U:tests/python/unittest/test_operator.py] — numeric-gradient
checks, dtype matrices and edge-case coverage for the PRE-EXISTING operator
families (elemwise/broadcast/reduce/index/shape ops), complementing
``test_operator.py``'s coverage of the round-4 families.  Every check runs
against an independently computed numpy reference.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.utils.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
)

from common import with_seed


def _nd(x, dtype="float32"):
    return mx.nd.array(np.asarray(x, dtype=dtype))


# ===========================================================================
# elementwise unary family — value + gradient against closed forms
# ===========================================================================

_UNARY_CASES = [
    # (op name, numpy fn, analytic grad fn, domain lo, domain hi)
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)),
     lambda x: (1 / (1 + np.exp(-x))) * (1 - 1 / (1 + np.exp(-x))), -4, 4),
    ("tanh", np.tanh, lambda x: 1 - np.tanh(x) ** 2, -3, 3),
    ("relu", lambda x: np.maximum(x, 0), lambda x: (x > 0).astype(np.float64), -2, 2),
    ("softsign", lambda x: x / (1 + np.abs(x)),
     lambda x: 1 / (1 + np.abs(x)) ** 2, -3, 3),
    ("exp", np.exp, np.exp, -2, 2),
    ("log", np.log, lambda x: 1 / x, 0.1, 5),
    ("log2", np.log2, lambda x: 1 / (x * np.log(2)), 0.1, 5),
    ("log10", np.log10, lambda x: 1 / (x * np.log(10)), 0.1, 5),
    ("log1p", np.log1p, lambda x: 1 / (1 + x), -0.5, 5),
    ("expm1", np.expm1, np.exp, -2, 2),
    ("sqrt", np.sqrt, lambda x: 0.5 / np.sqrt(x), 0.1, 5),
    ("rsqrt", lambda x: 1 / np.sqrt(x), lambda x: -0.5 * x ** -1.5, 0.1, 5),
    ("cbrt", np.cbrt, lambda x: (np.cbrt(x) ** -2) / 3, 0.1, 5),
    ("rcbrt", lambda x: 1 / np.cbrt(x), lambda x: -1 / 3 * x ** (-4 / 3), 0.1, 5),
    ("square", np.square, lambda x: 2 * x, -3, 3),
    ("reciprocal", lambda x: 1 / x, lambda x: -1 / x ** 2, 0.2, 4),
    ("sin", np.sin, np.cos, -3, 3),
    ("cos", np.cos, lambda x: -np.sin(x), -3, 3),
    ("tan", np.tan, lambda x: 1 / np.cos(x) ** 2, -1, 1),
    ("arcsin", np.arcsin, lambda x: 1 / np.sqrt(1 - x ** 2), -0.9, 0.9),
    ("arccos", np.arccos, lambda x: -1 / np.sqrt(1 - x ** 2), -0.9, 0.9),
    ("arctan", np.arctan, lambda x: 1 / (1 + x ** 2), -3, 3),
    ("sinh", np.sinh, np.cosh, -2, 2),
    ("cosh", np.cosh, np.sinh, -2, 2),
    ("arcsinh", np.arcsinh, lambda x: 1 / np.sqrt(x ** 2 + 1), -3, 3),
    ("arccosh", np.arccosh, lambda x: 1 / np.sqrt(x ** 2 - 1), 1.2, 4),
    ("arctanh", np.arctanh, lambda x: 1 / (1 - x ** 2), -0.9, 0.9),
    ("erf", None, lambda x: 2 / np.sqrt(np.pi) * np.exp(-x ** 2), -2, 2),
    ("abs", np.abs, np.sign, 0.2, 3),
]


class TestUnaryOps:
    @with_seed()
    @pytest.mark.parametrize("name,fn,grad_fn,lo,hi", _UNARY_CASES,
                             ids=[c[0] for c in _UNARY_CASES])
    def test_value_and_grad(self, name, fn, grad_fn, lo, hi):
        x = np.random.uniform(lo, hi, size=(3, 4)).astype(np.float32)
        op = getattr(mx.nd, name)
        out = op(_nd(x)).asnumpy()
        if fn is None:
            import math

            fn = np.vectorize(math.erf)
        assert_almost_equal(out, fn(x.astype(np.float64)).astype(np.float32),
                            rtol=1e-4, atol=1e-5)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            y = op(xa)
        y.backward()
        assert_almost_equal(xa.grad.asnumpy(),
                            grad_fn(x.astype(np.float64)).astype(np.float32),
                            rtol=1e-3, atol=1e-4)

    @with_seed()
    def test_rounding_ops(self):
        x = np.array([-2.5, -1.5, -0.5, 0.5, 1.5, 2.5, 1.2, -1.2], np.float32)
        assert_almost_equal(mx.nd.floor(_nd(x)).asnumpy(), np.floor(x), rtol=0, atol=0)
        assert_almost_equal(mx.nd.ceil(_nd(x)).asnumpy(), np.ceil(x), rtol=0, atol=0)
        assert_almost_equal(mx.nd.trunc(_nd(x)).asnumpy(), np.trunc(x), rtol=0, atol=0)
        assert_almost_equal(mx.nd.rint(_nd(x)).asnumpy(), np.rint(x), rtol=0, atol=0)
        assert_almost_equal(mx.nd.fix(_nd(x)).asnumpy(), np.fix(x), rtol=0, atol=0)
        # round: MXNet rounds half away from zero
        r = mx.nd.round(_nd(x)).asnumpy()
        expect = np.where(np.abs(x - np.trunc(x)) == 0.5,
                          np.trunc(x) + np.sign(x), np.rint(x))
        assert_almost_equal(r, expect, rtol=0, atol=0)

    @with_seed()
    def test_special_value_predicates(self):
        x = np.array([1.0, np.inf, -np.inf, np.nan, 0.0], np.float32)
        assert mx.nd.isnan(_nd(x)).asnumpy().tolist() == [0, 0, 0, 1, 0]
        assert mx.nd.isinf(_nd(x)).asnumpy().tolist() == [0, 1, 1, 0, 0]
        assert mx.nd.isfinite(_nd(x)).asnumpy().tolist() == [1, 0, 0, 0, 1]

    @with_seed()
    def test_gamma_functions(self):
        import math

        x = np.random.uniform(0.5, 4.0, size=(10,)).astype(np.float32)
        g = mx.nd.gamma(_nd(x)).asnumpy()
        expect = np.array([math.gamma(v) for v in x], np.float32)
        assert_almost_equal(g, expect, rtol=1e-3, atol=1e-4)
        gl = mx.nd.gammaln(_nd(x)).asnumpy()
        expect = np.array([math.lgamma(v) for v in x], np.float32)
        assert_almost_equal(gl, expect, rtol=1e-3, atol=1e-4)

    @with_seed()
    def test_digamma_polygamma(self):
        from scipy import special as sp

        x = np.random.uniform(0.5, 4.0, size=(10,)).astype(np.float32)
        assert_almost_equal(mx.nd.digamma(_nd(x)).asnumpy(),
                            sp.digamma(x).astype(np.float32),
                            rtol=1e-4, atol=1e-5)
        for n in (1, 2):
            assert_almost_equal(mx.nd.polygamma(_nd(x), n=n).asnumpy(),
                                sp.polygamma(n, x).astype(np.float32),
                                rtol=1e-3, atol=1e-4)
        # polygamma(0) == digamma
        assert_almost_equal(mx.nd.polygamma(_nd(x), n=0).asnumpy(),
                            mx.nd.digamma(_nd(x)).asnumpy(), rtol=1e-6, atol=0)

    @with_seed()
    def test_erfinv_roundtrip(self):
        x = np.random.uniform(-0.9, 0.9, size=(16,)).astype(np.float32)
        y = mx.nd.erfinv(_nd(x))
        back = mx.nd.erf(y).asnumpy()
        assert_almost_equal(back, x, rtol=1e-3, atol=1e-4)

    @with_seed()
    def test_degrees_radians(self):
        x = np.random.uniform(-np.pi, np.pi, size=(8,)).astype(np.float32)
        assert_almost_equal(mx.nd.degrees(_nd(x)).asnumpy(), np.degrees(x),
                            rtol=1e-5, atol=1e-5)
        assert_almost_equal(mx.nd.radians(mx.nd.degrees(_nd(x))).asnumpy(), x,
                            rtol=1e-5, atol=1e-5)


# ===========================================================================
# broadcast binary family
# ===========================================================================

_BROADCAST_SHAPES = [
    ((3, 4), (3, 4)),
    ((3, 4), (1, 4)),
    ((3, 4), (3, 1)),
    ((3, 1, 5), (1, 4, 5)),
    ((1,), (3, 4)),
    ((2, 3, 4), (4,)),
]

_BINARY_CASES = [
    ("broadcast_add", np.add),
    ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply),
    ("broadcast_div", lambda a, b: a / b),
    ("broadcast_maximum", np.maximum),
    ("broadcast_minimum", np.minimum),
    ("broadcast_power", lambda a, b: np.power(np.abs(a) + 0.5, b)),
    ("broadcast_hypot", np.hypot),
]


class TestBroadcastOps:
    @with_seed()
    @pytest.mark.parametrize("shapes", _BROADCAST_SHAPES,
                             ids=[str(s) for s in _BROADCAST_SHAPES])
    @pytest.mark.parametrize("name,ref", _BINARY_CASES, ids=[c[0] for c in _BINARY_CASES])
    def test_values(self, name, ref, shapes):
        sa, sb = shapes
        a = np.random.uniform(0.5, 2.0, size=sa).astype(np.float32)
        b = np.random.uniform(0.5, 2.0, size=sb).astype(np.float32)
        if name == "broadcast_power":
            a_in = np.abs(a) + 0.5
            out = getattr(mx.nd, name)(_nd(a_in), _nd(b)).asnumpy()
            expect = np.power(a_in, b)
        else:
            out = getattr(mx.nd, name)(_nd(a), _nd(b)).asnumpy()
            expect = ref(a, b)
        assert_almost_equal(out, expect.astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_broadcast_grad(self):
        a = np.random.rand(3, 1).astype(np.float32) + 0.5
        b = np.random.rand(1, 4).astype(np.float32) + 0.5
        check_numeric_gradient(lambda x, y: mx.nd.broadcast_mul(x, y), [a, b])
        check_numeric_gradient(lambda x, y: mx.nd.broadcast_div(x, y), [a, b])
        check_numeric_gradient(lambda x, y: mx.nd.broadcast_hypot(x, y), [a, b])

    @with_seed()
    def test_comparison_ops(self):
        a = np.array([[1, 2, 3], [4, 5, 6]], np.float32)
        b = np.array([[3, 2, 1]], np.float32)
        assert_almost_equal(mx.nd.broadcast_equal(_nd(a), _nd(b)).asnumpy(),
                            (a == b).astype(np.float32), rtol=0, atol=0)
        assert_almost_equal(mx.nd.broadcast_greater(_nd(a), _nd(b)).asnumpy(),
                            (a > b).astype(np.float32), rtol=0, atol=0)
        assert_almost_equal(mx.nd.broadcast_lesser_equal(_nd(a), _nd(b)).asnumpy(),
                            (a <= b).astype(np.float32), rtol=0, atol=0)
        assert_almost_equal(mx.nd.broadcast_not_equal(_nd(a), _nd(b)).asnumpy(),
                            (a != b).astype(np.float32), rtol=0, atol=0)

    @with_seed()
    def test_logical_ops(self):
        a = np.array([0, 1, 0, 2], np.float32)
        b = np.array([0, 0, 3, 4], np.float32)
        assert mx.nd.logical_and(_nd(a), _nd(b)).asnumpy().tolist() == [0, 0, 0, 1]
        assert mx.nd.logical_or(_nd(a), _nd(b)).asnumpy().tolist() == [0, 1, 1, 1]
        assert mx.nd.logical_xor(_nd(a), _nd(b)).asnumpy().tolist() == [0, 1, 1, 0]
        assert mx.nd.logical_not(_nd(a)).asnumpy().tolist() == [1, 0, 1, 0]

    @with_seed()
    def test_broadcast_mod(self):
        a = np.array([[5.0, -5.0, 7.5]], np.float32)
        b = np.array([[3.0], [3.0]], np.float32)
        out = mx.nd.broadcast_mod(_nd(a), _nd(b)).asnumpy()
        assert_almost_equal(out, np.mod(a, b), rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_broadcast_like_and_to(self):
        a = np.random.rand(1, 4).astype(np.float32)
        ref = mx.nd.zeros((3, 4))
        out = mx.nd.broadcast_like(_nd(a), ref)
        assert out.shape == (3, 4)
        out2 = mx.nd.broadcast_to(_nd(a), shape=(3, 4))
        assert_almost_equal(out.asnumpy(), out2.asnumpy(), rtol=0, atol=0)

    @with_seed()
    def test_broadcast_axis(self):
        a = np.random.rand(1, 3, 1).astype(np.float32)
        out = mx.nd.broadcast_axis(_nd(a), axis=(0, 2), size=(2, 4))
        assert out.shape == (2, 3, 4)
        assert_almost_equal(out.asnumpy(), np.broadcast_to(a, (2, 3, 4)),
                            rtol=0, atol=0)


# ===========================================================================
# reductions
# ===========================================================================


class TestReduceOps:
    @with_seed()
    @pytest.mark.parametrize("axis", [None, 0, 1, (0, 2), -1])
    def test_sum_mean_prod(self, axis):
        x = np.random.rand(2, 3, 4).astype(np.float32) + 0.5
        kw = {} if axis is None else {"axis": axis}
        assert_almost_equal(mx.nd.sum(_nd(x), **kw).asnumpy(),
                            np.sum(x, axis=axis), rtol=1e-4, atol=1e-5)
        assert_almost_equal(mx.nd.mean(_nd(x), **kw).asnumpy(),
                            np.mean(x, axis=axis), rtol=1e-4, atol=1e-5)
        assert_almost_equal(mx.nd.prod(_nd(x), **kw).asnumpy(),
                            np.prod(x, axis=axis), rtol=1e-3, atol=1e-5)

    @with_seed()
    def test_keepdims(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        out = mx.nd.sum(_nd(x), axis=1, keepdims=True)
        assert out.shape == (2, 1, 4)
        assert_almost_equal(out.asnumpy(), x.sum(1, keepdims=True), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_min_max(self):
        x = np.random.randn(3, 5).astype(np.float32)
        assert_almost_equal(mx.nd.max(_nd(x), axis=1).asnumpy(), x.max(1), rtol=0, atol=0)
        assert_almost_equal(mx.nd.min(_nd(x), axis=0).asnumpy(), x.min(0), rtol=0, atol=0)
        assert float(mx.nd.max(_nd(x)).asnumpy()) == pytest.approx(x.max())

    @with_seed()
    def test_nansum_nanprod(self):
        x = np.array([[1.0, np.nan, 2.0], [np.nan, 3.0, 4.0]], np.float32)
        assert_almost_equal(mx.nd.nansum(_nd(x), axis=1).asnumpy(),
                            np.nansum(x, axis=1), rtol=1e-5, atol=1e-6)
        assert_almost_equal(mx.nd.nanprod(_nd(x), axis=0).asnumpy(),
                            np.nanprod(x, axis=0), rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_norm(self):
        x = np.random.randn(3, 4).astype(np.float32)
        assert float(mx.nd.norm(_nd(x)).asnumpy()) == pytest.approx(
            np.linalg.norm(x), rel=1e-4)
        out = mx.nd.norm(_nd(x), ord=1, axis=1)
        assert_almost_equal(out.asnumpy(), np.abs(x).sum(1), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_argmax_argmin_pick(self):
        x = np.random.randn(4, 6).astype(np.float32)
        assert (mx.nd.argmax(_nd(x), axis=1).asnumpy() == x.argmax(1)).all()
        assert (mx.nd.argmin(_nd(x), axis=0).asnumpy() == x.argmin(0)).all()
        idx = np.array([2, 0, 5, 1], np.float32)
        picked = mx.nd.pick(_nd(x), _nd(idx), axis=1).asnumpy()
        assert_almost_equal(picked, x[np.arange(4), idx.astype(int)], rtol=0, atol=0)

    @with_seed()
    def test_sum_grad(self):
        x = np.random.rand(3, 4).astype(np.float32)
        check_numeric_gradient(lambda a: mx.nd.sum(a, axis=1), [x])
        check_numeric_gradient(lambda a: mx.nd.mean(a), [x])
        check_numeric_gradient(lambda a: mx.nd.prod(a + 1.0, axis=0), [x])


# ===========================================================================
# indexing / gather / scatter
# ===========================================================================


class TestIndexingOps:
    @with_seed()
    def test_take_modes(self):
        x = np.random.randn(5, 3).astype(np.float32)
        idx = np.array([0, 4, 2], np.float32)
        out = mx.nd.take(_nd(x), _nd(idx)).asnumpy()
        assert_almost_equal(out, x[[0, 4, 2]], rtol=0, atol=0)
        # clip mode for out-of-range
        idx_oob = np.array([7, -1], np.float32)
        out = mx.nd.take(_nd(x), _nd(idx_oob), mode="clip").asnumpy()
        assert_almost_equal(out, x[[4, 0]], rtol=0, atol=0)

    @with_seed()
    def test_take_axis1_and_grad(self):
        x = np.random.randn(4, 6).astype(np.float32)
        idx = np.array([1, 3], np.float32)
        out = mx.nd.take(_nd(x), _nd(idx), axis=1).asnumpy()
        assert_almost_equal(out, x[:, [1, 3]], rtol=0, atol=0)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            y = mx.nd.take(xa, _nd(idx), axis=1)
        y.backward()
        g = xa.grad.asnumpy()
        expect = np.zeros_like(x)
        expect[:, [1, 3]] = 1
        assert_almost_equal(g, expect, rtol=0, atol=0)

    @with_seed()
    def test_batch_take(self):
        x = np.random.randn(4, 5).astype(np.float32)
        idx = np.array([0, 4, 2, 1], np.float32)
        out = mx.nd.batch_take(_nd(x), _nd(idx)).asnumpy()
        assert_almost_equal(out, x[np.arange(4), idx.astype(int)], rtol=0, atol=0)

    @with_seed()
    def test_gather_nd_scatter_nd(self):
        x = np.random.randn(3, 4, 5).astype(np.float32)
        # pick elements (0,1,:) and (2,3,:)
        indices = np.array([[0, 2], [1, 3]], np.float32)  # [ndim_idx, N]
        out = mx.nd.gather_nd(_nd(x), _nd(indices)).asnumpy()
        assert_almost_equal(out, x[[0, 2], [1, 3]], rtol=0, atol=0)
        data = np.random.randn(2, 5).astype(np.float32)
        scat = mx.nd.scatter_nd(_nd(data), _nd(indices), shape=(3, 4, 5)).asnumpy()
        expect = np.zeros((3, 4, 5), np.float32)
        expect[[0, 2], [1, 3]] = data
        assert_almost_equal(scat, expect, rtol=0, atol=0)

    @with_seed()
    def test_one_hot(self):
        idx = np.array([1, 0, 3], np.float32)
        out = mx.nd.one_hot(_nd(idx), depth=4).asnumpy()
        assert_almost_equal(out, np.eye(4, dtype=np.float32)[[1, 0, 3]], rtol=0, atol=0)
        out = mx.nd.one_hot(_nd(idx), depth=4, on_value=2.0, off_value=-1.0).asnumpy()
        expect = np.full((3, 4), -1.0, np.float32)
        expect[np.arange(3), [1, 0, 3]] = 2.0
        assert_almost_equal(out, expect, rtol=0, atol=0)

    @with_seed()
    def test_topk_and_sort(self):
        x = np.random.randn(3, 8).astype(np.float32)
        # ret_typ='indices' (default returns indices in MXNet)
        out = mx.nd.topk(_nd(x), k=3, axis=1, ret_typ="value").asnumpy()
        expect = -np.sort(-x, axis=1)[:, :3]
        assert_almost_equal(out, expect, rtol=0, atol=0)
        srt = mx.nd.sort(_nd(x), axis=1).asnumpy()
        assert_almost_equal(srt, np.sort(x, axis=1), rtol=0, atol=0)
        srt_d = mx.nd.sort(_nd(x), axis=1, is_ascend=False).asnumpy()
        assert_almost_equal(srt_d, -np.sort(-x, axis=1), rtol=0, atol=0)
        args = mx.nd.argsort(_nd(x), axis=1).asnumpy()
        assert (args == np.argsort(x, kind="stable", axis=1)).all()

    @with_seed()
    def test_where(self):
        cond = np.array([[1, 0], [0, 1]], np.float32)
        a = np.ones((2, 2), np.float32)
        b = np.zeros((2, 2), np.float32)
        out = mx.nd.where(_nd(cond), _nd(a), _nd(b)).asnumpy()
        assert_almost_equal(out, cond, rtol=0, atol=0)

    @with_seed()
    def test_slice_ops(self):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        out = mx.nd.slice(_nd(x), begin=(0, 1, 1), end=(2, 3, 3)).asnumpy()
        assert_almost_equal(out, x[0:2, 1:3, 1:3], rtol=0, atol=0)
        out = mx.nd.slice_axis(_nd(x), axis=2, begin=1, end=3).asnumpy()
        assert_almost_equal(out, x[:, :, 1:3], rtol=0, atol=0)
        like = mx.nd.zeros((2, 2, 2))
        out = mx.nd.slice_like(_nd(x), like).asnumpy()
        assert_almost_equal(out, x[:2, :2, :2], rtol=0, atol=0)

    @with_seed()
    def test_reverse_flip(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        out = mx.nd.reverse(_nd(x), axis=0).asnumpy()
        assert_almost_equal(out, x[::-1], rtol=0, atol=0)
        out = mx.nd.flip(_nd(x), axis=1).asnumpy()
        assert_almost_equal(out, x[:, ::-1], rtol=0, atol=0)


# ===========================================================================
# shape manipulation
# ===========================================================================


class TestShapeOps:
    @with_seed()
    def test_reshape_special_codes(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        # 0 = copy dim, -1 = infer
        out = mx.nd.reshape(_nd(x), shape=(0, -1))
        assert out.shape == (2, 12)
        out = mx.nd.reshape(_nd(x), shape=(-1, 4))
        assert out.shape == (6, 4)
        # -2 = copy remaining, -3 = merge two dims
        out = mx.nd.reshape(_nd(x), shape=(-3, -2))
        assert out.shape == (6, 4)

    @with_seed()
    def test_transpose_swapaxes(self):
        x = np.random.rand(2, 3, 4).astype(np.float32)
        out = mx.nd.transpose(_nd(x), axes=(2, 0, 1)).asnumpy()
        assert_almost_equal(out, x.transpose(2, 0, 1), rtol=0, atol=0)
        out = mx.nd.swapaxes(_nd(x), 0, 2).asnumpy()
        assert_almost_equal(out, x.swapaxes(0, 2), rtol=0, atol=0)
        out = mx.nd.SwapAxis(_nd(x), dim1=1, dim2=2).asnumpy()
        assert_almost_equal(out, x.swapaxes(1, 2), rtol=0, atol=0)

    @with_seed()
    def test_expand_squeeze(self):
        x = np.random.rand(3, 4).astype(np.float32)
        out = mx.nd.expand_dims(_nd(x), axis=1)
        assert out.shape == (3, 1, 4)
        back = mx.nd.squeeze(out)
        assert back.shape == (3, 4)

    @with_seed()
    def test_stack_concat_split(self):
        a = np.random.rand(2, 3).astype(np.float32)
        b = np.random.rand(2, 3).astype(np.float32)
        out = mx.nd.stack(_nd(a), _nd(b), axis=1).asnumpy()
        assert_almost_equal(out, np.stack([a, b], axis=1), rtol=0, atol=0)
        out = mx.nd.concat(_nd(a), _nd(b), dim=0).asnumpy()
        assert_almost_equal(out, np.concatenate([a, b], axis=0), rtol=0, atol=0)
        parts = mx.nd.split(_nd(np.concatenate([a, b], 1)), num_outputs=2, axis=1)
        assert_almost_equal(parts[0].asnumpy(), a, rtol=0, atol=0)
        assert_almost_equal(parts[1].asnumpy(), b, rtol=0, atol=0)

    @with_seed()
    def test_repeat_tile_pad(self):
        x = np.array([[1, 2], [3, 4]], np.float32)
        out = mx.nd.repeat(_nd(x), repeats=2, axis=1).asnumpy()
        assert_almost_equal(out, np.repeat(x, 2, axis=1), rtol=0, atol=0)
        out = mx.nd.tile(_nd(x), reps=(2, 3)).asnumpy()
        assert_almost_equal(out, np.tile(x, (2, 3)), rtol=0, atol=0)
        x4 = np.random.rand(1, 1, 2, 2).astype(np.float32)
        out = mx.nd.pad(_nd(x4), mode="constant", pad_width=(0, 0, 0, 0, 1, 1, 1, 1),
                        constant_value=9.0).asnumpy()
        assert out.shape == (1, 1, 4, 4)
        assert out[0, 0, 0, 0] == 9.0
        assert_almost_equal(out[0, 0, 1:3, 1:3], x4[0, 0], rtol=0, atol=0)
        out = mx.nd.pad(_nd(x4), mode="edge", pad_width=(0, 0, 0, 0, 1, 1, 1, 1)).asnumpy()
        assert out[0, 0, 0, 0] == x4[0, 0, 0, 0]

    @with_seed()
    def test_diag(self):
        x = np.random.rand(4, 4).astype(np.float32)
        assert_almost_equal(mx.nd.diag(_nd(x)).asnumpy(), np.diag(x), rtol=0, atol=0)
        v = np.array([1.0, 2.0, 3.0], np.float32)
        assert_almost_equal(mx.nd.diag(_nd(v)).asnumpy(), np.diag(v), rtol=0, atol=0)
        assert_almost_equal(mx.nd.diag(_nd(x), k=1).asnumpy(), np.diag(x, k=1),
                            rtol=0, atol=0)

    @with_seed()
    def test_shape_size_arrays(self):
        x = mx.nd.zeros((3, 4, 5))
        assert mx.nd.shape_array(x).asnumpy().tolist() == [3, 4, 5]
        assert int(mx.nd.size_array(x).asnumpy()) == 60

    @with_seed()
    def test_clip_grad(self):
        x = np.random.uniform(-2, 2, (4, 4)).astype(np.float32)
        out = mx.nd.clip(_nd(x), -1.0, 1.0).asnumpy()
        assert_almost_equal(out, np.clip(x, -1, 1), rtol=0, atol=0)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            y = mx.nd.clip(xa, -1.0, 1.0)
        y.backward()
        expect = ((x >= -1) & (x <= 1)).astype(np.float32)
        assert_almost_equal(xa.grad.asnumpy(), expect, rtol=0, atol=0)


# ===========================================================================
# dot / matmul family
# ===========================================================================


class TestDotOps:
    @with_seed()
    def test_dot_transpose_flags(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(4, 5).astype(np.float32)
        assert_almost_equal(mx.nd.dot(_nd(a), _nd(b)).asnumpy(), a @ b,
                            rtol=1e-4, atol=1e-5)
        assert_almost_equal(
            mx.nd.dot(_nd(a.T), _nd(b), transpose_a=True).asnumpy(), a @ b,
            rtol=1e-4, atol=1e-5)
        assert_almost_equal(
            mx.nd.dot(_nd(a), _nd(b.T), transpose_b=True).asnumpy(), a @ b,
            rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_batch_dot(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        assert_almost_equal(mx.nd.batch_dot(_nd(a), _nd(b)).asnumpy(), a @ b,
                            rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_dot_grad(self):
        a = np.random.randn(3, 2).astype(np.float32)
        b = np.random.randn(2, 4).astype(np.float32)
        check_numeric_gradient(lambda x, y: mx.nd.dot(x, y), [a, b])


# ===========================================================================
# softmax family
# ===========================================================================


class TestSoftmaxOps:
    @with_seed()
    @pytest.mark.parametrize("axis", [-1, 0, 1])
    def test_softmax_axis(self, axis):
        x = np.random.randn(4, 5).astype(np.float32)
        out = mx.nd.softmax(_nd(x), axis=axis).asnumpy()
        e = np.exp(x - x.max(axis=axis, keepdims=True))
        expect = e / e.sum(axis=axis, keepdims=True)
        assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_softmax_temperature(self):
        x = np.random.randn(3, 6).astype(np.float32)
        out = mx.nd.softmax(_nd(x), temperature=2.0).asnumpy()
        e = np.exp(x / 2.0 - (x / 2.0).max(-1, keepdims=True))
        assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_log_softmax_softmin(self):
        x = np.random.randn(3, 6).astype(np.float32)
        out = mx.nd.log_softmax(_nd(x)).asnumpy()
        e = np.exp(x - x.max(-1, keepdims=True))
        expect = np.log(e / e.sum(-1, keepdims=True))
        assert_almost_equal(out, expect, rtol=1e-4, atol=1e-4)
        out = mx.nd.softmin(_nd(x)).asnumpy()
        e = np.exp(-x - (-x).max(-1, keepdims=True))
        assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_softmax_grad(self):
        x = np.random.randn(3, 4).astype(np.float32)
        check_numeric_gradient(lambda a: mx.nd.softmax(a) ** 2, [x])

    @with_seed()
    def test_softmax_cross_entropy(self):
        x = np.random.randn(4, 5).astype(np.float32)
        label = np.array([0, 2, 4, 1], np.float32)
        out = mx.nd.softmax_cross_entropy(_nd(x), _nd(label)).asnumpy()
        e = np.exp(x - x.max(-1, keepdims=True))
        logp = np.log(e / e.sum(-1, keepdims=True))
        expect = -logp[np.arange(4), label.astype(int)].sum()
        assert_almost_equal(out, np.array([expect], np.float32).squeeze(),
                            rtol=1e-4, atol=1e-4)


# ===========================================================================
# activation blocks
# ===========================================================================


class TestActivationOps:
    @with_seed()
    def test_leaky_relu_variants(self):
        x = np.random.randn(4, 5).astype(np.float32)
        out = mx.nd.LeakyReLU(_nd(x), act_type="leaky", slope=0.1).asnumpy()
        assert_almost_equal(out, np.where(x > 0, x, 0.1 * x), rtol=1e-5, atol=1e-6)
        out = mx.nd.LeakyReLU(_nd(x), act_type="elu", slope=1.0).asnumpy()
        assert_almost_equal(out, np.where(x > 0, x, np.exp(x) - 1), rtol=1e-4, atol=1e-5)
        # gelu (erf formulation)
        import math

        out = mx.nd.LeakyReLU(_nd(x), act_type="gelu").asnumpy()
        erf = np.vectorize(math.erf)
        expect = 0.5 * x * (1 + erf(x / np.sqrt(2)))
        assert_almost_equal(out, expect.astype(np.float32), rtol=1e-3, atol=1e-4)

    @with_seed()
    def test_activation_op(self):
        x = np.random.randn(3, 4).astype(np.float32)
        for act, ref in [("relu", lambda v: np.maximum(v, 0)),
                         ("sigmoid", lambda v: 1 / (1 + np.exp(-v))),
                         ("tanh", np.tanh),
                         ("softsign", lambda v: v / (1 + np.abs(v)))]:
            out = mx.nd.Activation(_nd(x), act_type=act).asnumpy()
            assert_almost_equal(out, ref(x).astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_hard_sigmoid_smooth_l1(self):
        x = np.random.uniform(-4, 4, (10,)).astype(np.float32)
        out = mx.nd.hard_sigmoid(_nd(x)).asnumpy()
        assert_almost_equal(out, np.clip(0.2 * x + 0.5, 0, 1), rtol=1e-5, atol=1e-6)
        s = 1.0
        out = mx.nd.smooth_l1(_nd(x), scalar=s).asnumpy()
        expect = np.where(np.abs(x) < 1, 0.5 * x ** 2, np.abs(x) - 0.5)
        assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)


# ===========================================================================
# sequence ops depth
# ===========================================================================


class TestSequenceOps:
    @with_seed()
    def test_sequence_mask(self):
        x = np.random.randn(4, 3, 2).astype(np.float32)  # [T, B, ...]
        length = np.array([2, 4, 1], np.float32)
        out = mx.nd.SequenceMask(_nd(x), _nd(length), use_sequence_length=True,
                                 value=-7.0).asnumpy()
        for b, l in enumerate(length.astype(int)):
            assert_almost_equal(out[:l, b], x[:l, b], rtol=0, atol=0)
            assert (out[l:, b] == -7.0).all()

    @with_seed()
    def test_sequence_last(self):
        x = np.random.randn(5, 3, 2).astype(np.float32)
        length = np.array([1, 5, 3], np.float32)
        out = mx.nd.SequenceLast(_nd(x), _nd(length), use_sequence_length=True).asnumpy()
        for b, l in enumerate(length.astype(int)):
            assert_almost_equal(out[b], x[l - 1, b], rtol=0, atol=0)
        # without lengths: plain last step
        out = mx.nd.SequenceLast(_nd(x)).asnumpy()
        assert_almost_equal(out, x[-1], rtol=0, atol=0)

    @with_seed()
    def test_sequence_reverse(self):
        x = np.random.randn(4, 2, 3).astype(np.float32)
        length = np.array([2, 4], np.float32)
        out = mx.nd.SequenceReverse(_nd(x), _nd(length), use_sequence_length=True).asnumpy()
        assert_almost_equal(out[:2, 0], x[:2, 0][::-1], rtol=0, atol=0)
        assert_almost_equal(out[2:, 0], x[2:, 0], rtol=0, atol=0)  # tail untouched
        assert_almost_equal(out[:, 1], x[:, 1][::-1], rtol=0, atol=0)

    @with_seed()
    def test_sequence_mask_grad(self):
        x = np.random.randn(3, 2, 2).astype(np.float32)
        length = np.array([1, 3], np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            y = mx.nd.SequenceMask(xa, _nd(length), use_sequence_length=True)
        y.backward()
        g = xa.grad.asnumpy()
        assert (g[:1, 0] == 1).all() and (g[1:, 0] == 0).all()
        assert (g[:, 1] == 1).all()


# ===========================================================================
# dtype matrix across core families
# ===========================================================================


class TestCoreDtypes:
    @with_seed()
    @pytest.mark.parametrize("dtype", ["float16", "bfloat16", "float32"])
    def test_arithmetic_dtype_preserved(self, dtype):
        a = mx.nd.array(np.random.rand(3, 4), dtype=dtype)
        b = mx.nd.array(np.random.rand(3, 4), dtype=dtype)
        for op in (mx.nd.elemwise_add, mx.nd.elemwise_mul, mx.nd.broadcast_add):
            assert op(a, b).dtype == a.dtype
        assert mx.nd.sum(a, axis=1).dtype == a.dtype
        assert mx.nd.relu(a).dtype == a.dtype

    @with_seed()
    @pytest.mark.parametrize("dtype", ["int32", "int8", "uint8"])
    def test_integer_arithmetic(self, dtype):
        a = mx.nd.array(np.array([[1, 2], [3, 4]]), dtype=dtype)
        b = mx.nd.array(np.array([[5, 6], [7, 8]]), dtype=dtype)
        out = mx.nd.elemwise_add(a, b)
        assert str(out.dtype) == dtype
        assert out.asnumpy().tolist() == [[6, 8], [10, 12]]

    @with_seed()
    def test_cast_matrix(self):
        x = np.array([1.7, -2.3, 0.0], np.float32)
        for dt in ["float16", "bfloat16", "int32", "float32"]:
            out = mx.nd.Cast(_nd(x), dtype=dt)
            assert str(out.dtype) == dt
        assert mx.nd.Cast(_nd(x), dtype="int32").asnumpy().tolist() == [1, -2, 0]

    @with_seed()
    def test_embedding_dtype(self):
        w = mx.nd.array(np.random.rand(10, 4), dtype="bfloat16")
        idx = mx.nd.array(np.array([1, 5]), dtype="int32")
        out = mx.nd.Embedding(idx, w, input_dim=10, output_dim=4)
        assert out.dtype == w.dtype


class TestIndexingEdgeSemantics:
    """Reference edge semantics for the indexing/sorting family
    ([U:tests/python/unittest/test_operator.py] idioms): every case has an
    independent numpy expectation."""

    @with_seed()
    def test_topk_ret_typ_variants(self):
        x = np.array([[3.0, 1.0, 2.0, 5.0], [0.0, 4.0, 2.0, 1.0]], np.float32)
        xa = _nd(x)
        idx = mx.nd.topk(xa, k=2).asnumpy()            # indices, descending
        np.testing.assert_array_equal(idx, [[3, 0], [1, 2]])
        val = mx.nd.topk(xa, k=2, ret_typ="value").asnumpy()
        np.testing.assert_allclose(val, [[5, 3], [4, 2]])
        both = mx.nd.topk(xa, k=2, ret_typ="both")
        np.testing.assert_allclose(both[0].asnumpy(), val)
        np.testing.assert_array_equal(both[1].asnumpy(), idx)
        mask = mx.nd.topk(xa, k=2, ret_typ="mask").asnumpy()
        np.testing.assert_array_equal(mask, [[1, 0, 0, 1], [0, 1, 1, 0]])
        asc = mx.nd.topk(xa, k=1, ret_typ="value", is_ascend=True).asnumpy()
        np.testing.assert_allclose(asc, [[1.0], [0.0]])
        # axis=0
        v0 = mx.nd.topk(xa, axis=0, k=1, ret_typ="value").asnumpy()
        np.testing.assert_allclose(v0, [[3, 4, 2, 5]])

    @with_seed()
    def test_sort_argsort(self):
        x = np.random.RandomState(0).randn(3, 5).astype(np.float32)
        np.testing.assert_allclose(mx.nd.sort(_nd(x)).asnumpy(),
                                   np.sort(x, axis=-1))
        np.testing.assert_allclose(
            mx.nd.sort(_nd(x), is_ascend=False).asnumpy(),
            -np.sort(-x, axis=-1))
        np.testing.assert_array_equal(mx.nd.argsort(_nd(x)).asnumpy(),
                                      np.argsort(x, axis=-1))

    @with_seed()
    def test_pick_modes_and_axes(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([0, 9, 2], np.float32)  # 9 out of range -> clip to 3
        got = mx.nd.pick(_nd(x), _nd(idx)).asnumpy()
        np.testing.assert_allclose(got, [0.0, 7.0, 10.0])
        keep = mx.nd.pick(_nd(x), _nd(np.array([1, 1, 1], np.float32)),
                          keepdims=True)
        assert keep.shape == (3, 1)
        ax0 = mx.nd.pick(_nd(x), _nd(np.array([2, 0, 1, 2], np.float32)),
                         axis=0).asnumpy()
        np.testing.assert_allclose(ax0, [8.0, 1.0, 6.0, 11.0])

    @with_seed()
    def test_one_hot_on_off_dtype(self):
        idx = np.array([1, 0, 2], np.float32)
        oh = mx.nd.one_hot(_nd(idx), 3, on_value=5.0, off_value=-1.0,
                           dtype="int32")
        assert str(oh.dtype) == "int32"
        np.testing.assert_array_equal(
            oh.asnumpy(), [[-1, 5, -1], [5, -1, -1], [-1, -1, 5]])

    @with_seed()
    def test_gather_scatter_nd_roundtrip(self):
        data = np.random.RandomState(1).randn(3, 4, 2).astype(np.float32)
        indices = np.array([[0, 2, 1], [3, 1, 0]], np.float32)  # (M=2, N=3)
        picked = mx.nd.gather_nd(_nd(data), _nd(indices)).asnumpy()
        np.testing.assert_allclose(picked, data[[0, 2, 1], [3, 1, 0]])
        back = mx.nd.scatter_nd(_nd(picked), _nd(indices), shape=(3, 4, 2))
        want = np.zeros((3, 4, 2), np.float32)
        want[[0, 2, 1], [3, 1, 0]] = picked
        np.testing.assert_allclose(back.asnumpy(), want)

    @with_seed()
    def test_take_clip_and_wrap(self):
        x = np.arange(10, dtype=np.float32).reshape(5, 2)
        idx = np.array([-1, 0, 7], np.float32)
        clip = mx.nd.take(_nd(x), _nd(idx)).asnumpy()
        np.testing.assert_allclose(clip, x[[0, 0, 4]])
        wrap = mx.nd.take(_nd(x), _nd(idx), mode="wrap").asnumpy()
        np.testing.assert_allclose(wrap, x[[4, 0, 2]])

    @with_seed()
    def test_sequence_family_with_lengths(self):
        # data [T=4, B=2, D=3]
        x = np.random.RandomState(2).randn(4, 2, 3).astype(np.float32)
        lens = np.array([2, 4], np.float32)
        masked = mx.nd.SequenceMask(_nd(x), _nd(lens),
                                    use_sequence_length=True,
                                    value=-7.0).asnumpy()
        want = x.copy()
        want[2:, 0] = -7.0  # first batch element masked beyond length 2
        np.testing.assert_allclose(masked, want)
        last = mx.nd.SequenceLast(_nd(x), _nd(lens),
                                  use_sequence_length=True).asnumpy()
        np.testing.assert_allclose(last, np.stack([x[1, 0], x[3, 1]]))
        rev = mx.nd.SequenceReverse(_nd(x), _nd(lens),
                                    use_sequence_length=True).asnumpy()
        np.testing.assert_allclose(rev[:2, 0], x[[1, 0], 0])
        np.testing.assert_allclose(rev[2:, 0], x[2:, 0])  # tail untouched
        np.testing.assert_allclose(rev[:, 1], x[::-1, 1])


class TestSplitV2:
    @with_seed()
    def test_sections_and_indices(self):
        x = np.arange(24, dtype=np.float32).reshape(6, 4)
        parts = mx.nd.split_v2(_nd(x), 3)
        assert len(parts) == 3
        np.testing.assert_allclose(parts[1].asnumpy(), x[2:4])
        parts = mx.nd.split_v2(_nd(x), (1, 4), axis=0)
        assert [p.shape[0] for p in parts] == [1, 3, 2]
        np.testing.assert_allclose(parts[2].asnumpy(), x[4:])
        sq = mx.nd.split_v2(_nd(x), 6, axis=0, squeeze_axis=True)
        assert sq[0].shape == (4,)

    def test_symbolic_multi_output(self):
        import incubator_mxnet_tpu.symbol as S

        S.symbol._reset_naming()
        a = S.var("a")
        parts = S.split_v2(a, indices_or_sections=(2,), axis=1, name="sp")
        assert len(parts) == 2
        y = S.broadcast_add(parts[0], S.slice_axis(parts[1], axis=1, begin=0,
                                                   end=2), name="add")
        exe = y.simple_bind(a=(3, 5))
        av = np.random.RandomState(0).rand(3, 5).astype(np.float32)
        exe.arg_dict["a"][:] = av
        out = exe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, av[:, :2] + av[:, 2:4], rtol=1e-6)

    def test_invalid_indices_rejected(self):
        x = _nd(np.zeros((6, 4), np.float32))
        with pytest.raises(ValueError):
            mx.nd.split_v2(x, (1, 10), axis=0)
        with pytest.raises(ValueError):
            mx.nd.split_v2(x, (4, 2), axis=0)
        with pytest.raises(ValueError):
            mx.nd.split_v2(x, (-2,), axis=0)
