"""Worker body for the goodput-ledger acceptance (tests/test_goodput.py).

The elastic chaos worker (tests/elastic_worker.py) with the profiler
armed: a 2-process dist_sync folded run, RunCheckpoint after every step,
under ``tools/supervise.py`` with a ``proc.kill_rank`` fault — plus one
injected DATA STALL on rank 0 (a sleep reported exactly the way
``io.DataPipeline`` reports consumer stalls: one ``io.wait`` span).  At
the end each rank prints its run ledger::

    GOODPUT_SNAPSHOT rank <r> <goodput_snapshot() json>

The acceptance asserts the buckets sum to wall, the supervisor's restart
gap (ridden in on ``MXNET_ELASTIC_DOWNTIME_S``) lands in ``downtime``
with the ``elastic_restart`` reason, and the stall lands in
``data_wait`` — on the stalled rank only.

Knobs: ``MXNET_TEST_STALL_S`` (default 0.4), ``MXNET_TEST_STALL_AT``
(step, default 5), ``MXNET_TEST_STALL_RANK`` (default 0).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("MXNET_KVSTORE_BUCKET_BYTES", "2048")

import json

import numpy as np

TOTAL = 8


def main():
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, profiler
    from incubator_mxnet_tpu.io.io import NDArrayIter
    from incubator_mxnet_tpu.parallel import elastic
    from incubator_mxnet_tpu.utils import faultinject as fi

    prefix = sys.argv[1]
    stall_s = float(os.environ.get("MXNET_TEST_STALL_S", "0.4"))
    stall_at = int(os.environ.get("MXNET_TEST_STALL_AT", "5"))
    stall_rank = int(os.environ.get("MXNET_TEST_STALL_RANK", "0"))

    L2 = gluon.loss.L2Loss()
    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    assert nw == 2, nw

    # arm the profiler FIRST: the ledger's wall window opens here, and
    # elastic.init() below folds the supervisor's restart gap into it
    profiler.set_config(filename=f"{prefix}_trace_rank{rank}.json")
    profiler.start()
    elastic.init()

    mx.random.seed(7)
    np.random.seed(7)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net.hybridize()
    net(mx.nd.zeros((2, 6)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05}, kvstore=kv)

    rs = np.random.RandomState(100 + rank)
    x = rs.rand(32, 6).astype(np.float32)
    y = rs.rand(32, 4).astype(np.float32)
    it = NDArrayIter(x, y, batch_size=8, shuffle=True, seed=13 + rank)

    ck = elastic.RunCheckpoint(prefix, net=net, trainer=tr,
                               rank=rank, world=nw)
    start = 0
    payload = ck.restore(data=it)
    if payload is not None:
        start = payload["step"]
        print(f"ELASTIC_RESUMED rank {rank} step {start}", flush=True)

    program = tr.fold_step(lambda a, b: L2(net(a), b), block=net)
    for step in range(start, TOTAL):
        fi.step_faults(step, rank)   # proc.kill_rank gates here
        if step == stall_at and rank == stall_rank:
            # the data stall: producer starves the consumer for stall_s —
            # reported the same way DataPipeline reports a consumer stall
            # (one io.wait span covering the blocked wait)
            t0 = time.perf_counter()
            time.sleep(stall_s)
            profiler.record_span("io.wait", "io", t0)
        if not it.iter_next():
            it.reset()
            it.iter_next()
        a, b = it.getdata()[0], it.getlabel()[0]
        float(np.asarray(program(a, b).asnumpy()).mean())
        ck.save(step + 1, data=it, barrier=kv.barrier)
    assert program.folded, program.fallback_reason
    c = profiler.counters()
    assert c["recompile_steady_state"] == 0, c["recompile_steady_state"]

    kv.barrier()
    snap = profiler.goodput_snapshot()
    print(f"GOODPUT_SNAPSHOT rank {rank} {json.dumps(snap)}", flush=True)


if __name__ == "__main__":
    main()
