"""contrib.text (parity: [U:tests/python/unittest/test_contrib_text.py]):
vocabulary indexing + embedding file loading."""
import collections

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.contrib import text


class TestVocabulary:
    def test_count_and_index(self):
        counter = text.count_tokens_from_str("a b b c c c\nd d d d", to_lower=True)
        assert counter == collections.Counter({"d": 4, "c": 3, "b": 2, "a": 1})
        v = text.Vocabulary(counter, min_freq=2, reserved_tokens=["<pad>"])
        # <unk>, <pad>, then d,c,b by frequency (a dropped: freq 1)
        assert v.idx_to_token == ["<unk>", "<pad>", "d", "c", "b"]
        assert v.to_indices(["d", "b", "zzz"]) == [2, 4, 0]
        assert v.to_tokens([2, 0]) == ["d", "<unk>"]
        assert len(v) == 5

    def test_most_freq_count(self):
        v = text.Vocabulary(collections.Counter("aaabbc"), most_freq_count=2)
        assert v.idx_to_token == ["<unk>", "a", "b"]


class TestCustomEmbedding:
    def _file(self, tmp_path):
        p = tmp_path / "emb.txt"
        p.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
        return str(p)

    def test_load_and_lookup(self, tmp_path):
        emb = text.CustomEmbedding(self._file(tmp_path))
        assert emb.vec_len == 3 and len(emb) == 2 and "hello" in emb
        vecs = emb.get_vecs_by_tokens(["world", "missing"])
        np.testing.assert_allclose(vecs.asnumpy(), [[4, 5, 6], [0, 0, 0]])

    def test_vocab_indexed_table_feeds_embedding_layer(self, tmp_path):
        from incubator_mxnet_tpu import gluon

        v = text.Vocabulary(collections.Counter({"hello": 2, "world": 1}))
        emb = text.CustomEmbedding(self._file(tmp_path), vocabulary=v)
        assert emb.idx_to_vec.shape == (3, 3)
        layer = gluon.nn.Embedding(len(v), emb.vec_len)
        layer.initialize()
        layer(mx.nd.zeros((1, 1), dtype="int32"))
        layer.weight.set_data(mx.nd.array(emb.idx_to_vec))
        out = layer(mx.nd.array([[v.to_indices("hello")]], dtype="int32"))
        np.testing.assert_allclose(out.asnumpy()[0, 0], [1, 2, 3])

    def test_bad_file_raises(self, tmp_path):
        import pytest

        p = tmp_path / "bad.txt"
        p.write_text("tok 1.0 2.0\nother 1.0\n")
        with pytest.raises(ValueError, match="inconsistent"):
            text.CustomEmbedding(str(p))

    def test_pretrained_listing(self):
        import pytest

        assert "glove.6B.300d.txt" in text.get_pretrained_file_names("glove")
        with pytest.raises(KeyError):
            text.get_pretrained_file_names("nope")


class TestReviewRegressions:
    def test_cap_excludes_reserved_tokens(self):
        c = collections.Counter({"<pad>": 5, "a": 3, "b": 2})
        v = text.Vocabulary(c, most_freq_count=2, reserved_tokens=["<pad>"])
        assert v.idx_to_token == ["<unk>", "<pad>", "a", "b"]

    def test_numpy_integer_index(self):
        v = text.Vocabulary(collections.Counter("aab"))
        assert v.to_tokens(np.int64(1)) == "a"
        assert v.to_tokens(np.asarray([1, 0], np.int32)) == ["a", "<unk>"]

    def test_trailing_whitespace_lines(self, tmp_path):
        p = tmp_path / "ws.txt"
        p.write_text("hello 1.0 2.0 \nworld 3.0 4.0\t\n")
        emb = text.CustomEmbedding(str(p))
        np.testing.assert_allclose(
            emb.get_vecs_by_tokens("hello").asnumpy(), [1, 2])

    def test_regex_delims(self):
        c = text.count_tokens_from_str("a,b  c", token_delim="[ ,]")
        assert c == collections.Counter({"a": 1, "b": 1, "c": 1})

    def test_idx_to_vec_without_vocab(self, tmp_path):
        p = tmp_path / "emb2.txt"
        p.write_text("hello 1.0 2.0 3.0\nworld 4.0 5.0 6.0\n")
        emb = text.CustomEmbedding(str(p))
        assert emb.idx_to_vec.shape == (3, 3)  # <unk> + 2 tokens
        np.testing.assert_allclose(emb.idx_to_vec[0], 0.0)
