"""NN-operator depth tests (the [U:tests/python/unittest/test_operator.py]
normalization/conv/pool sections): every check against an independent
numpy reference, gradients by finite differences where cheap.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.utils.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
)

from common import with_seed


def _nd(x, dtype="float32"):
    return mx.nd.array(np.asarray(x, dtype=dtype))


class TestNormalizationOps:
    @with_seed()
    def test_batchnorm_training_stats(self):
        x = np.random.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
        gamma = np.random.rand(3).astype(np.float32) + 0.5
        beta = np.random.randn(3).astype(np.float32)
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        # the op computes batch statistics (returns out, batch_mean,
        # batch_var; the gluon layer owns running-stat mutation)
        out, bmean, bvar = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta),
                                           _nd(mean), _nd(var),
                                           fix_gamma=False)
        bm = x.mean(axis=(0, 2, 3))
        bv = x.var(axis=(0, 2, 3))
        assert_almost_equal(bmean.asnumpy(), bm, rtol=1e-4, atol=1e-4)
        assert_almost_equal(bvar.asnumpy(), bv, rtol=1e-3, atol=1e-4)
        expect = ((x - bm[None, :, None, None])
                  / np.sqrt(bv[None, :, None, None] + 1e-5)
                  * gamma[None, :, None, None] + beta[None, :, None, None])
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-3)

    @with_seed()
    def test_batchnorm_inference_uses_running(self):
        x = np.random.randn(2, 3, 4, 4).astype(np.float32)
        gamma = np.ones(3, np.float32)
        beta = np.zeros(3, np.float32)
        mean = np.array([0.5, -0.5, 1.0], np.float32)
        var = np.array([2.0, 1.0, 0.5], np.float32)
        out = mx.nd.BatchNorm(_nd(x), _nd(gamma), _nd(beta), _nd(mean),
                              _nd(var), fix_gamma=False,
                              use_global_stats=True)[0]
        expect = (x - mean[None, :, None, None]) / np.sqrt(
            var[None, :, None, None] + 1e-5)
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-3)

    @with_seed()
    def test_layernorm_vs_numpy(self):
        x = np.random.randn(3, 7).astype(np.float32)
        gamma = np.random.rand(7).astype(np.float32) + 0.5
        beta = np.random.randn(7).astype(np.float32)
        out = mx.nd.LayerNorm(_nd(x), _nd(gamma), _nd(beta), eps=1e-5)
        mu = x.mean(-1, keepdims=True)
        sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
        assert_almost_equal(out.asnumpy(), (x - mu) / sd * gamma + beta,
                            rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_layernorm_grad(self):
        x = np.random.randn(2, 5).astype(np.float32)
        g = np.random.rand(5).astype(np.float32) + 0.5
        b = np.random.randn(5).astype(np.float32)
        check_numeric_gradient(
            lambda a, gg, bb: mx.nd.LayerNorm(a, gg, bb) ** 2, [x, g, b],
            rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_groupnorm_instancenorm_rmsnorm(self):
        x = np.random.randn(2, 4, 3, 3).astype(np.float32)
        g = np.ones(4, np.float32)
        b = np.zeros(4, np.float32)
        # InstanceNorm: per-sample per-channel normalization
        out = mx.nd.InstanceNorm(_nd(x), _nd(g), _nd(b), eps=1e-5).asnumpy()
        mu = x.mean(axis=(2, 3), keepdims=True)
        sd = np.sqrt(x.var(axis=(2, 3), keepdims=True) + 1e-5)
        assert_almost_equal(out, (x - mu) / sd, rtol=1e-3, atol=1e-3)
        # GroupNorm with 2 groups
        out = mx.nd.GroupNorm(_nd(x), _nd(g), _nd(b), num_groups=2,
                              eps=1e-5).asnumpy()
        xr = x.reshape(2, 2, 2, 3, 3)
        mu = xr.mean(axis=(2, 3, 4), keepdims=True)
        sd = np.sqrt(xr.var(axis=(2, 3, 4), keepdims=True) + 1e-5)
        expect = ((xr - mu) / sd).reshape(x.shape)
        assert_almost_equal(out, expect, rtol=1e-3, atol=1e-3)
        # RMSNorm over the last axis
        xr2 = np.random.randn(3, 6).astype(np.float32)
        gw = np.random.rand(6).astype(np.float32) + 0.5
        out = mx.nd.RMSNorm(_nd(xr2), _nd(gw), eps=1e-6).asnumpy()
        rms = np.sqrt((xr2 ** 2).mean(-1, keepdims=True) + 1e-6)
        assert_almost_equal(out, xr2 / rms * gw, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_l2_normalization(self):
        x = np.random.randn(3, 5).astype(np.float32)
        out = mx.nd.L2Normalization(_nd(x), mode="instance").asnumpy()
        expect = x / np.sqrt((x ** 2).sum(-1, keepdims=True) + 1e-10)
        assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)
        x4 = np.random.randn(2, 3, 4, 4).astype(np.float32)
        out = mx.nd.L2Normalization(_nd(x4), mode="channel").asnumpy()
        expect = x4 / np.sqrt((x4 ** 2).sum(1, keepdims=True) + 1e-10)
        assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)


class TestConvPoolOps:
    @with_seed()
    def test_convolution_vs_numpy(self):
        x = np.random.randn(2, 2, 5, 5).astype(np.float32)
        w = np.random.randn(3, 2, 3, 3).astype(np.float32)
        b = np.random.randn(3).astype(np.float32)
        out = mx.nd.Convolution(_nd(x), _nd(w), _nd(b), kernel=(3, 3),
                                num_filter=3, pad=(1, 1)).asnumpy()
        xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
        expect = np.zeros((2, 3, 5, 5), np.float32)
        for n in range(2):
            for f in range(3):
                for i in range(5):
                    for j in range(5):
                        expect[n, f, i, j] = (
                            xp[n, :, i:i + 3, j:j + 3] * w[f]).sum() + b[f]
        assert_almost_equal(out, expect, rtol=1e-3, atol=1e-3)

    @with_seed()
    def test_convolution_stride_dilate_group(self):
        x = np.random.randn(1, 4, 8, 8).astype(np.float32)
        w = np.random.randn(4, 2, 3, 3).astype(np.float32)
        out = mx.nd.Convolution(_nd(x), _nd(w), kernel=(3, 3), num_filter=4,
                                stride=(2, 2), num_group=2, no_bias=True)
        assert out.shape == (1, 4, 3, 3)
        # grouped: filter f sees only its group's input channels
        g0 = out.asnumpy()[0, 0]
        xp = x[0, 0:2]
        expect = np.zeros((3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                expect[i, j] = (xp[:, 2 * i:2 * i + 3, 2 * j:2 * j + 3] * w[0]).sum()
        assert_almost_equal(g0, expect, rtol=1e-3, atol=1e-3)
        # dilation
        out = mx.nd.Convolution(_nd(x), _nd(w[:, :, :, :]), kernel=(3, 3),
                                num_filter=4, dilate=(2, 2), num_group=2,
                                no_bias=True)
        assert out.shape == (1, 4, 4, 4)

    @with_seed()
    def test_conv_grad(self):
        x = np.random.randn(1, 1, 4, 4).astype(np.float32)
        w = np.random.randn(2, 1, 3, 3).astype(np.float32)
        check_numeric_gradient(
            lambda a, ww: mx.nd.Convolution(a, ww, kernel=(3, 3), num_filter=2,
                                            pad=(1, 1), no_bias=True),
            [x, w], rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_deconvolution_shapes_and_values(self):
        x = np.random.randn(1, 2, 3, 3).astype(np.float32)
        w = np.random.randn(2, 3, 2, 2).astype(np.float32)
        out = mx.nd.Deconvolution(_nd(x), _nd(w), kernel=(2, 2), num_filter=3,
                                  stride=(2, 2), no_bias=True)
        assert out.shape == (1, 3, 6, 6)
        # each input pixel stamps w scaled by its value (stride=kernel → no overlap)
        expect = np.zeros((1, 3, 6, 6), np.float32)
        for c_in in range(2):
            for i in range(3):
                for j in range(3):
                    expect[0, :, 2 * i:2 * i + 2, 2 * j:2 * j + 2] += (
                        x[0, c_in, i, j] * w[c_in])
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-3, atol=1e-3)

    @with_seed()
    def test_pooling_modes(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2),
                            pool_type="max").asnumpy()
        expect = x.reshape(1, 2, 2, 2, 2, 2).max(axis=(3, 5))
        assert_almost_equal(out, expect, rtol=0, atol=0)
        out = mx.nd.Pooling(_nd(x), kernel=(2, 2), stride=(2, 2),
                            pool_type="avg").asnumpy()
        expect = x.reshape(1, 2, 2, 2, 2, 2).mean(axis=(3, 5))
        assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)
        out = mx.nd.Pooling(_nd(x), global_pool=True, pool_type="avg",
                            kernel=(1, 1)).asnumpy()
        assert_almost_equal(out[..., 0, 0], x.mean(axis=(2, 3)),
                            rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_maxpool_grad_routes_to_argmax(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            y = mx.nd.Pooling(xa, kernel=(2, 2), stride=(2, 2), pool_type="max")
        y.backward()
        g = xa.grad.asnumpy()[0, 0]
        expect = np.zeros((4, 4), np.float32)
        expect[1::2, 1::2] = 1  # max of each 2x2 block is bottom-right
        assert_almost_equal(g, expect, rtol=0, atol=0)

    @with_seed()
    def test_upsampling_nearest(self):
        x = np.array([[[[1.0, 2.0], [3.0, 4.0]]]], np.float32)
        out = mx.nd.UpSampling(_nd(x), scale=2, sample_type="nearest").asnumpy()
        expect = np.repeat(np.repeat(x, 2, axis=2), 2, axis=3)
        assert_almost_equal(out, expect, rtol=0, atol=0)


class TestEmbeddingAndHeads:
    @with_seed()
    def test_embedding_grad_accumulates(self):
        w = np.random.randn(10, 4).astype(np.float32)
        idx = np.array([1, 3, 1], np.float32)  # repeated row 1
        wa = _nd(w)
        wa.attach_grad()
        with autograd.record():
            out = mx.nd.Embedding(_nd(idx, dtype="int32"), wa,
                                  input_dim=10, output_dim=4)
        out.backward()
        g = wa.grad.asnumpy()
        assert (g[1] == 2).all()  # row 1 hit twice
        assert (g[3] == 1).all()
        assert g[[0, 2, 4, 5, 6, 7, 8, 9]].sum() == 0

    @with_seed()
    def test_fullyconnected_flatten_semantics(self):
        x = np.random.randn(2, 3, 4).astype(np.float32)
        w = np.random.randn(5, 12).astype(np.float32)
        b = np.zeros(5, np.float32)
        out = mx.nd.FullyConnected(_nd(x), _nd(w), _nd(b), num_hidden=5)
        assert out.shape == (2, 5)
        assert_almost_equal(out.asnumpy(), x.reshape(2, 12) @ w.T,
                            rtol=1e-4, atol=1e-4)
        w2 = np.random.randn(5, 4).astype(np.float32)
        out = mx.nd.FullyConnected(_nd(x), _nd(w2), _nd(b), num_hidden=5,
                                   flatten=False)
        assert out.shape == (2, 3, 5)
        assert_almost_equal(out.asnumpy(), x @ w2.T, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_dropout_statistics_and_determinism(self):
        x = np.ones((400, 100), np.float32)
        with autograd.record(train_mode=True):
            out = mx.nd.Dropout(_nd(x), p=0.3)
        o = out.asnumpy()
        keep_rate = (o != 0).mean()
        assert abs(keep_rate - 0.7) < 0.02
        # kept values rescaled by 1/keep
        kept = o[o != 0]
        assert abs(kept.mean() - 1.0 / 0.7) < 0.05
        # eval mode: identity
        out = mx.nd.Dropout(_nd(x), p=0.3)
        assert_almost_equal(out.asnumpy(), x, rtol=0, atol=0)

    @with_seed()
    def test_slice_channel(self):
        x = np.random.randn(2, 6, 3).astype(np.float32)
        parts = mx.nd.SliceChannel(_nd(x), num_outputs=3, axis=1)
        assert len(parts) == 3
        for k in range(3):
            assert_almost_equal(parts[k].asnumpy(), x[:, 2 * k:2 * k + 2],
                                rtol=0, atol=0)
        sq = mx.nd.SliceChannel(_nd(x[:, :3]), num_outputs=3, axis=1,
                                squeeze_axis=True)
        assert sq[0].shape == (2, 3)


class TestSoftmaxOutputNormalization:
    """Backward normalization modes of the legacy SoftmaxOutput head
    ([U:src/operator/softmax_output-inl.h]): 'valid' divides by the valid
    count — equal to the TOTAL label count when use_ignore is off (it is
    NOT a no-op there)."""

    def _grad(self, **kwargs):
        x = np.arange(12, dtype=np.float32).reshape(4, 3) * 0.1
        lab = np.array([0, 1, 2, 1], np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.SoftmaxOutput(xa, _nd(lab), **kwargs)
        out.backward()
        return xa.grad.asnumpy(), out.asnumpy()

    @with_seed()
    def test_valid_without_ignore_divides_by_count(self):
        g_null, p = self._grad()
        g_valid, _ = self._grad(normalization="valid")
        g_batch, _ = self._grad(normalization="batch")
        assert_almost_equal(g_valid, g_null / 4.0, rtol=1e-5, atol=1e-7)
        assert_almost_equal(g_batch, g_null / 4.0, rtol=1e-5, atol=1e-7)
        oh = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
        assert_almost_equal(g_null, p - oh, rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_valid_with_ignore_divides_by_valid_count(self):
        g, p = self._grad(normalization="valid", use_ignore=True,
                          ignore_label=1)
        oh = np.eye(3, dtype=np.float32)[[0, 1, 2, 1]]
        want = (p - oh)
        want[[1, 3]] = 0.0  # ignored rows contribute nothing
        assert_almost_equal(g, want / 2.0, rtol=1e-5, atol=1e-7)


class TestLayerNormCustomBwd:
    """MXNET_TPU_LN_CUSTOM_BWD=1: the hand-written VJP must match
    autodiff of the reference form for value and all three gradients."""

    @with_seed()
    def test_matches_autodiff(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        from incubator_mxnet_tpu.ops.nn import layer_norm, _layer_norm_ref

        monkeypatch.setenv("MXNET_TPU_LN_CUSTOM_BWD", "1")
        rng = np.random.RandomState(0)
        for dtype, tol in ((jnp.float32, 1e-5), (jnp.bfloat16, 3e-2)):
            x = jnp.asarray(rng.randn(4, 6, 16).astype(np.float32)).astype(dtype)
            g = jnp.asarray(rng.rand(16).astype(np.float32) + 0.5)
            b = jnp.asarray(rng.randn(16).astype(np.float32))

            def lc(x, g, b):
                return jnp.sum(jnp.sin(layer_norm(x, g, b).astype(jnp.float32)))

            def lr(x, g, b):
                return jnp.sum(jnp.sin(
                    _layer_norm_ref(x, g, b, -1, 1e-5).astype(jnp.float32)))

            # value_and_grad: the value flows through the custom fwd (the
            # primal alone would execute the reference), so this checks the
            # hand-written forward AND backward
            v1, g1 = jax.value_and_grad(lc, argnums=(0, 1, 2))(x, g, b)
            v2, g2 = jax.value_and_grad(lr, argnums=(0, 1, 2))(x, g, b)
            np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
            for a, c in zip(g1, g2):
                np.testing.assert_allclose(np.asarray(a, np.float32),
                                           np.asarray(c, np.float32),
                                           rtol=tol, atol=tol)
                # primal-dtype contract
            assert g1[0].dtype == x.dtype
            assert g1[1].dtype == g.dtype and g1[2].dtype == b.dtype

    @with_seed()
    def test_non_last_axis_falls_back(self, monkeypatch):
        monkeypatch.setenv("MXNET_TPU_LN_CUSTOM_BWD", "1")
        x = np.random.randn(3, 8, 5).astype(np.float32)
        out = mx.nd.LayerNorm(_nd(x), _nd(np.ones(8, np.float32)),
                              _nd(np.zeros(8, np.float32)), axis=1)
        m = x.mean(axis=1, keepdims=True)
        v = x.var(axis=1, keepdims=True)
        assert_almost_equal(out.asnumpy(), (x - m) / np.sqrt(v + 1e-5),
                            rtol=1e-4, atol=1e-5)


def test_attn_score_layout_ab_equivalence():
    """MXNET_TPU_ATTN_SCORE_LAYOUT=bqhk (the TPU relayout A/B) is
    numerically identical to the default bhqk — fwd and grads, causal."""
    import subprocess
    import sys
    import os as os_mod

    script = r"""
import numpy as np
import jax, jax.numpy as jnp
import incubator_mxnet_tpu.ops.attention as att
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(2, 16, 4, 8).astype(np.float32))
k = jnp.asarray(rng.randn(2, 16, 4, 8).astype(np.float32))
v = jnp.asarray(rng.randn(2, 16, 4, 8).astype(np.float32))
def f(q, k, v):
    return (att._flash_bshd(q, k, v, True, 0.35) * jnp.arange(8)).sum()
val, grads = jax.value_and_grad(f, argnums=(0, 1, 2))(q, k, v)
for g in grads:
    print(repr(float(np.abs(np.asarray(g)).sum())))
print(repr(float(val)))
"""
    outs = {}
    # also pin the saved-probs branch (MAX_ELEMS large enough to engage)
    for layout in ("bhqk", "bqhk", "bhqk-save", "bqhk-save"):
        env = dict(os_mod.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.update(JAX_PLATFORMS="cpu",
                   MXNET_TPU_ATTN_SCORE_LAYOUT=layout.split("-")[0])
        if layout.endswith("-save"):
            env["MXNET_TPU_ATTN_SAVE_PROBS_MAX_ELEMS"] = "10000000"
        r = subprocess.run([sys.executable, "-c", script], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-800:]
        outs[layout] = [float(x) for x in r.stdout.strip().splitlines()]
    for variant in ("bqhk", "bhqk-save", "bqhk-save"):
        np.testing.assert_allclose(outs["bhqk"], outs[variant], rtol=1e-5,
                                   err_msg=variant)
