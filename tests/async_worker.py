"""Worker body for the dist_async straggler-tolerance tier (the port of
the reference's [U:tests/nightly/dist_async_kvstore.py] discipline, plus
an explicit straggler-independence assertion the sync tier cannot make).

Run via tools/launch_local.py at DMLC_NUM_WORKER=N.  The LAST rank is a
deliberate straggler (sleeps before pushing); every other rank must
complete its pushes and pulls in far less than the straggler's sleep —
push/pull are barrier-free against the worker-0 parameter server.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

STRAGGLE_S = 3.0
PUSHES = 4


def main():
    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx

    kv = mx.kv.create("dist_async")
    assert kv.type == "dist_async"
    rank, nw = kv.rank, kv.num_workers
    expected = int(os.environ.get("DMLC_NUM_WORKER", "2"))
    assert nw == expected, f"worker count mismatch: {nw} != {expected}"
    straggler = nw - 1

    # --- async accumulation with a straggler ----------------------------
    kv.init("acc", mx.nd.zeros((4,)))
    kv.barrier()  # everyone sees the initialized key

    t0 = time.monotonic()
    if rank == straggler:
        time.sleep(STRAGGLE_S)
    for _ in range(PUSHES):
        kv.push("acc", mx.nd.ones((4,)) * (rank + 1))
    out = mx.nd.zeros((4,))
    kv.pull("acc", out=out)
    elapsed = time.monotonic() - t0

    if rank != straggler:
        # THE async property: fast workers finish all pushes+pull while the
        # straggler is still asleep — no barrier in push/pull
        assert elapsed < STRAGGLE_S / 2, (
            f"rank {rank} blocked {elapsed:.1f}s behind the straggler")
        # and the pulled value reflects only what has arrived so far: it
        # must be a valid partial sum (monotonicity, not the full total)
        total = float(out.asnumpy()[0])
        full = PUSHES * nw * (nw + 1) / 2
        assert 0 < total <= full, total

    kv.barrier()  # straggler done too
    kv.pull("acc", out=out)
    full = PUSHES * nw * (nw + 1) / 2  # sum over ranks of PUSHES*(r+1)
    np.testing.assert_allclose(out.asnumpy(), np.full((4,), full))
    counts = kv.push_counts()
    assert counts == [PUSHES] * nw, counts

    # --- server-side optimizer (the async contract) ---------------------
    kv2 = mx.kv.create("dist_async")
    kv2.init("w", mx.nd.ones((3,)))
    opt = mx.optimizer.create("sgd", learning_rate=0.1)
    kv2.set_optimizer(opt)  # includes a barrier
    kv2.push("w", mx.nd.ones((3,)))  # each push: w -= 0.1*1
    kv2.barrier()
    got = mx.nd.zeros((3,))
    kv2.pull("w", out=got)
    np.testing.assert_allclose(got.asnumpy(), np.full((3,), 1.0 - 0.1 * nw),
                               rtol=1e-6)

    # --- Module routes its update through the kvstore for dist_* --------
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=1, no_bias=True, name="fc")
    out_sym = S.LinearRegressionOutput(fc, S.var("lin_label"), name="lin")
    mod = mx.mod.Module(out_sym, data_names=("data",), label_names=("lin_label",))
    from incubator_mxnet_tpu.io import NDArrayIter

    x = np.linspace(-1, 1, 16).reshape(16, 1).astype(np.float32)
    y = 3.0 * x
    it = NDArrayIter(data=x, label=y, batch_size=8)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.initializer.Zero())
    mod.init_optimizer(kvstore="dist_async",
                       optimizer_params=(("learning_rate", 0.05),))
    for _ in range(60):
        it.reset()
        for batch in it:
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
    kv.barrier()
    w = mod._exec.arg_dict["fc_weight"].asnumpy()
    assert abs(float(w[0, 0]) - 3.0) < 0.25, w

    print(f"rank {rank}: async assertions passed")


if __name__ == "__main__":
    main()
