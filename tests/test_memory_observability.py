"""Device-memory observability (ISSUE 12): the live HBM ledger, OOM
forensics, budgeted admission, and the chrome-trace memory counter track.

The acceptance contracts:

* **ledger exactness** — owner register/alloc/free/close account to the
  byte; a trainer's weight+grad+state footprint matches an independent
  computation; donated optimizer steps move ZERO ledger bytes;
* **OOM forensics** — a ``RESOURCE_EXHAUSTED`` at a dispatch choke point
  emits exactly ONE postmortem per failure naming the top owners and the
  failed allocation size, however many choke points it propagates
  through;
* **budgeted admission** — ``MemoryBudget.check`` refuses loudly with a
  postmortem; ``GenerationServer`` slot admission DEFERS (not crashes)
  while the budget reports pressure;
* **counter track** — a dumped trace carries ``"C"`` events Perfetto
  renders as a memory timeline, and ``tools/memory_report.py`` reads
  them back.
"""
import gc
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, profiler
from incubator_mxnet_tpu.gluon import Trainer, nn

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def owner(request):
    """A throwaway ledger owner, removed after the test."""
    name = f"test.{request.node.name[:40]}"
    t = profiler.track_memory(name, "test")
    yield t
    t.close()


class TestLedger:
    def test_alloc_free_exact(self, owner):
        owner.alloc(1000)
        owner.alloc(24)
        led = profiler.memory_ledger()
        row = led["owners"][owner.owner]
        assert row["bytes"] == 1024
        assert row["peak"] == 1024
        assert row["allocs"] == 2
        owner.free(24)
        row = profiler.memory_ledger()["owners"][owner.owner]
        assert row["bytes"] == 1000
        assert row["peak"] == 1024          # peak survives the free
        assert row["frees"] == 1

    def test_shared_owner_composes_by_deltas(self, owner):
        again = profiler.track_memory(owner.owner, "test")
        assert again is owner               # same name -> same tracker
        owner.alloc(10)
        again.alloc(5)
        assert profiler.memory_ledger()["owners"][owner.owner]["bytes"] == 15

    def test_set_and_close(self, owner):
        owner.set(4096)
        assert profiler.memory_ledger()["owners"][owner.owner]["bytes"] == 4096
        owner.close()
        assert owner.owner not in profiler.memory_ledger()["owners"]

    def test_category_rollup(self, owner):
        owner.alloc(100)
        led = profiler.memory_ledger()
        assert led["by_category"]["test"] >= 100
        assert led["total_bytes"] == sum(
            i["bytes"] for i in led["owners"].values())

    def test_memory_provider_in_snapshot(self, owner):
        owner.alloc(123)
        snap = profiler.metrics_snapshot()
        mem = snap["providers"]["memory"]
        assert mem["ledger_bytes"] >= 123
        assert mem["owners"] >= 1
        assert "test_bytes" in mem


class TestTrainerAccounting:
    def _train(self, steps=1):
        mx.random.seed(0)
        net = nn.HybridSequential()
        net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
        net.initialize()
        x = mx.nd.array(np.random.RandomState(0).rand(8, 6).astype(
            np.float32))
        net(x)
        opt = mx.optimizer.create("adam", learning_rate=0.01)
        opt.aggregate_num = 100
        tr = Trainer(net.collect_params(), opt)
        for _ in range(steps):
            with autograd.record():
                loss = (net(x) * net(x)).sum()
            loss.backward()
            tr.step(8)
        return net, tr

    @staticmethod
    def _nd_bytes(x):
        if x is None:
            return 0
        if isinstance(x, (list, tuple)):
            return sum(TestTrainerAccounting._nd_bytes(s) for s in x)
        n = 1
        for d in x.shape:
            n *= int(d)
        return n * np.dtype(x.dtype).itemsize

    def test_trainer_footprint_exact_and_donation_stable(self):
        base_p = profiler.memory_ledger()["owners"].get(
            "trainer.params", {}).get("bytes", 0)
        base_s = profiler.memory_ledger()["owners"].get(
            "trainer.optimizer_state", {}).get("bytes", 0)
        net, tr = self._train(steps=1)
        try:
            exp_p = sum(2 * self._nd_bytes(p._data)
                        for p in net.collect_params().values())
            exp_s = sum(self._nd_bytes(st) for st in tr._states.values())
            led = profiler.memory_ledger()["owners"]
            assert led["trainer.params"]["bytes"] - base_p == exp_p
            assert led["trainer.optimizer_state"]["bytes"] - base_s == exp_s
            # donation-move exactness: further steps swap buffers in place
            x = mx.nd.array(np.random.RandomState(1).rand(8, 6).astype(
                np.float32))
            for _ in range(3):
                with autograd.record():
                    loss = (net(x) * net(x)).sum()
                loss.backward()
                tr.step(8)
            led2 = profiler.memory_ledger()["owners"]
            assert led2["trainer.params"]["bytes"] - base_p == exp_p
            assert led2["trainer.optimizer_state"]["bytes"] - base_s == exp_s
        finally:
            tr.close()
        led3 = profiler.memory_ledger()["owners"]
        assert led3.get("trainer.params", {}).get("bytes", 0) == base_p
        assert led3.get("trainer.optimizer_state", {}).get(
            "bytes", 0) == base_s
        tr.close()   # idempotent: a second close must not double-free
        assert profiler.memory_ledger()["owners"].get(
            "trainer.params", {}).get("bytes", 0) == base_p

    def test_abandoned_trainer_released_at_gc(self):
        """A trainer dropped WITHOUT close() (the common local path) must
        still release its ledger share via the finalizer."""
        base = profiler.memory_ledger()["owners"].get(
            "trainer.params", {}).get("bytes", 0)
        net, tr = self._train(steps=1)
        assert profiler.memory_ledger()["owners"][
            "trainer.params"]["bytes"] > base
        del tr
        gc.collect()
        assert profiler.memory_ledger()["owners"].get(
            "trainer.params", {}).get("bytes", 0) == base


class TestKVCacheAccounting:
    def test_pool_register_and_release_exact(self):
        from incubator_mxnet_tpu.serving import SlotKVCache

        owner = "kv_cache.pool_16"
        base = profiler.memory_ledger()["owners"].get(
            owner, {}).get("bytes", 0)
        pool = SlotKVCache(layers=2, slots=3, bucket=16, mem_width=8,
                           heads=2, head_dim=4)
        expected = sum(int(a.nbytes) for a in pool.state.values())
        assert pool.nbytes == expected
        got = profiler.memory_ledger()["owners"][owner]["bytes"]
        assert got - base == expected
        pool.release()
        assert profiler.memory_ledger()["owners"].get(
            owner, {}).get("bytes", 0) == base
        pool.release()   # idempotent
        assert profiler.memory_ledger()["owners"].get(
            owner, {}).get("bytes", 0) == base

    def test_abandoned_pool_released_at_gc(self):
        from incubator_mxnet_tpu.serving import SlotKVCache

        owner = "kv_cache.pool_8"
        base = profiler.memory_ledger()["owners"].get(
            owner, {}).get("bytes", 0)
        pool = SlotKVCache(layers=1, slots=2, bucket=8, mem_width=4,
                           heads=1, head_dim=2)
        assert profiler.memory_ledger()["owners"][owner]["bytes"] > base
        del pool
        gc.collect()
        assert profiler.memory_ledger()["owners"].get(
            owner, {}).get("bytes", 0) == base


class TestOOMForensics:
    def test_parse_failed_bytes(self):
        p = profiler._parse_failed_bytes
        assert p("Out of memory while trying to allocate 4294967296 "
                 "bytes.") == 4294967296
        assert p("Attempting to reserve 5.81G at the bottom") == int(
            5.81 * (1 << 30))
        assert p("allocating 2.5MiB for buffer") == int(2.5 * (1 << 20))
        assert p("no numbers here") is None

    def test_choke_point_postmortem_exactly_once(self, owner):
        """A RESOURCE_EXHAUSTED raised under a StatefulExecutor dispatch
        (the KV-insert/decode choke point) yields exactly one postmortem
        naming the top owner and the failed allocation — and re-reporting
        the SAME exception at an outer choke point adds nothing."""
        import jax.numpy as jnp

        from incubator_mxnet_tpu.predictor import StatefulExecutor

        owner.alloc(10_000_000)   # make this test's owner the top one
        exe = StatefulExecutor({"x": jnp.zeros((4,))}, name="oomtest")

        def boom(state, inputs):
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: Out of memory while trying to "
                "allocate 1048576 bytes.")

        exe.add_program("boom", boom)
        before = profiler.counters()["memory_oom_postmortem"]
        with pytest.raises(RuntimeError) as ei:
            exe.run("boom")
        after = profiler.counters()["memory_oom_postmortem"]
        assert after - before == 1
        rep = getattr(ei.value, "_mx_postmortem", None)
        assert rep is not None
        assert rep["failed_bytes"] == 1048576
        assert rep["kind"] == "oom"
        assert rep["top_owners"][0]["owner"] == owner.owner
        # nested choke point (e.g. the SPMD step around an engine flush):
        # the marker on the exception suppresses a duplicate report
        rep2 = profiler.maybe_oom_postmortem(ei.value, "spmd.step")
        assert rep2 is rep
        assert profiler.counters()["memory_oom_postmortem"] == after

    def test_unrelated_errors_not_reported(self):
        before = profiler.counters()["memory_oom_postmortem"]
        assert profiler.maybe_oom_postmortem(
            ValueError("shape mismatch"), "spmd.step") is None
        assert profiler.counters()["memory_oom_postmortem"] == before


class TestMemoryBudget:
    def test_check_raises_with_one_postmortem(self, owner):
        owner.alloc(5_000_000)
        budget = profiler.MemoryBudget(limit_mb=1)
        before = profiler.counters()["memory_oom_postmortem"]
        with pytest.raises(profiler.MemoryBudgetError) as ei:
            budget.check(64 << 20, "test.forced")
        assert profiler.counters()["memory_oom_postmortem"] - before == 1
        rep = ei.value._mx_postmortem
        assert rep["kind"] == "budget"
        assert rep["failed_bytes"] == 64 << 20
        assert rep["where"] == "budget:test.forced"
        assert profiler.memory_postmortems()[-1]["where"] == rep["where"]

    def test_would_fit_and_pressure_ledger_fallback(self, owner,
                                                    monkeypatch):
        # no device stats (CPU): usage falls back to the ledger total
        monkeypatch.setattr(profiler, "device_memory_stats", lambda *a: {})
        owner.alloc(1000 * 1024)
        budget = profiler.MemoryBudget(limit_mb=1)
        assert budget.usage_bytes() >= 1000 * 1024
        assert not budget.would_fit(200 * 1024)
        assert budget.under_pressure()           # 1000K > 0.95 * 1024K
        big = profiler.MemoryBudget(limit_mb=1024)
        assert big.would_fit(200 * 1024)
        assert not big.under_pressure()

    def test_device_limit_caps_when_uncapped(self, monkeypatch):
        fake = {"dev0": {"bytes_in_use": 90, "peak_bytes_in_use": 95,
                         "bytes_limit": 100}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        budget = profiler.MemoryBudget(limit_mb=0)   # no explicit cap
        assert budget.usage_bytes() == 90
        assert budget.would_fit(5)
        assert not budget.would_fit(20)
        assert budget.under_pressure(frac=0.85)
        assert not budget.under_pressure(frac=0.95)

    def test_pipeline_pressure_consults_shared_budget(self, monkeypatch):
        from incubator_mxnet_tpu.io.pipeline import _Engine

        fake = {"dev0": {"bytes_in_use": 95, "peak_bytes_in_use": 99,
                         "bytes_limit": 100}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        assert _Engine._default_device_pressure(0.9)
        fake["dev0"]["bytes_in_use"] = 10
        assert not _Engine._default_device_pressure(0.9)


class TestWatermarkSampling:
    def test_metrics_snapshot_samples_watermark(self, monkeypatch):
        """Serving-only processes (no step boundaries) must still report
        a watermark: metrics_snapshot() samples device memory itself."""
        fake = {"dev0": {"bytes_in_use": 1000, "peak_bytes_in_use": 2000,
                         "bytes_limit": 10000}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        with profiler._counter_lock:
            profiler._mem_watermark.clear()
        profiler._mem_last[0] = 0.0     # defeat the sampling throttle
        snap = profiler.metrics_snapshot()
        assert snap["memory_watermark_bytes"] == {"dev0": 2000}

    def test_sampling_respects_config_off(self, monkeypatch):
        fake = {"dev0": {"bytes_in_use": 1, "peak_bytes_in_use": 1,
                         "bytes_limit": 10}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        with profiler._counter_lock:
            profiler._mem_watermark.clear()
        profiler._mem_last[0] = 0.0
        profiler.set_config(memory_sampling=False)
        try:
            profiler.metrics_snapshot()
            assert profiler.memory_watermark() == {}
        finally:
            profiler.set_config(memory_sampling=True)


class TestCounterTrack:
    def test_counter_track_in_dump(self, tmp_path, owner, monkeypatch):
        fake = {"dev0": {"bytes_in_use": 4096, "peak_bytes_in_use": 8192,
                         "bytes_limit": 1 << 20}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        owner.alloc(777)
        path = str(tmp_path / "mem_trace.json")
        profiler.set_config(filename=path)
        profiler.start()
        try:
            for _ in range(3):
                profiler.step_boundary()
        finally:
            out = profiler.dump()
        with open(out) as f:
            doc = json.load(f)
        cev = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
        ledger_ev = [e for e in cev if e["name"] == "memory ledger"]
        dev_ev = [e for e in cev if e["name"] == "memory dev0"]
        assert ledger_ev and dev_ev
        assert ledger_ev[-1]["args"]["test"] >= 777
        assert dev_ev[-1]["args"]["bytes_in_use"] == 4096
        # the ledger itself rides otherData.memory
        mem = doc["otherData"]["memory"]
        assert mem["ledger"]["owners"][owner.owner]["bytes"] == 777

    def test_memory_report_cli(self, tmp_path, owner, monkeypatch):
        fake = {"dev0": {"bytes_in_use": 4096, "peak_bytes_in_use": 8192,
                         "bytes_limit": 1 << 20}}
        monkeypatch.setattr(profiler, "device_memory_stats",
                            lambda *a: dict(fake))
        owner.alloc(2048)
        path = str(tmp_path / "mem_trace.json")
        profiler.set_config(filename=path)
        profiler.start()
        try:
            profiler.step_boundary()
            profiler.step_boundary()
        finally:
            out = profiler.dump()
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "memory_report.py"),
             out], capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        assert owner.owner in r.stdout
        assert "counter track" in r.stdout.lower() or "memory" in r.stdout

    def test_memory_report_empty_exits_2(self, tmp_path):
        path = str(tmp_path / "empty.json")
        with open(path, "w") as f:
            json.dump({"traceEvents": [], "otherData": {}}, f)
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "memory_report.py"),
             path], capture_output=True, text=True)
        assert r.returncode == 2
        assert "no memory data" in r.stderr


class TestBudgetRefusedAdmission:
    def test_generation_admission_defers_under_budget(self):
        """A GenerationServer whose MemoryBudget reports no headroom must
        DEFER queued prefills (memory_budget_refusal counts, the request
        stays pending) instead of dispatching into an OOM — and admit as
        soon as the budget recovers."""
        from incubator_mxnet_tpu.gluon.model_zoo.transformer import \
            Transformer
        from incubator_mxnet_tpu.serving import GenerationServer

        profiler.disarm_compile_guard()
        mx.random.seed(0)
        net = Transformer(17, units=16, hidden_size=32, num_heads=2,
                          num_encoder_layers=1, num_decoder_layers=1,
                          dropout=0.0, max_length=64)
        net.initialize()
        net(mx.nd.array(np.ones((1, 8), np.int32), dtype="int32"),
            mx.nd.array(np.ones((1, 1), np.int32), dtype="int32"))

        class FlipBudget:
            blocked = True

            def under_pressure(self, frac=None):
                return self.blocked

        budget = FlipBudget()
        base_pool = profiler.memory_ledger()["owners"].get(
            "kv_cache.pool_8", {}).get("bytes", 0)
        srv = GenerationServer(net, bos=1, eos=2, max_prompt_length=8,
                               max_new_tokens=8, slots_per_bucket=2,
                               memory_budget=budget, name="memtest")
        try:
            before = profiler.counters()["memory_budget_refusal"]
            res = srv.submit(np.array([3, 4, 5], np.int32))
            deadline = time.time() + 5.0
            while profiler.counters()["memory_budget_refusal"] == before:
                assert time.time() < deadline, "no budget refusal recorded"
                time.sleep(0.01)
            assert not res.done()           # deferred, not failed
            assert srv.stats()["active_slots"] == 0
            budget.blocked = False          # headroom recovered
            toks = res.result(timeout=30.0)
            assert len(toks) >= 1
        finally:
            srv.close(drain=False)
            profiler.disarm_compile_guard()   # start() armed it
        # pools released their ledger rows on close
        assert profiler.memory_ledger()["owners"].get(
            "kv_cache.pool_8", {}).get("bytes", 0) == base_pool
