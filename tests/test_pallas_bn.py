"""Pallas fused BN epilogue (ops/pallas_bn.py) correctness vs the stock
batch_norm op — interpret mode on CPU (the chip tier re-runs compiled)."""
import numpy as np
import pytest

import jax.numpy as jnp

from incubator_mxnet_tpu.ops.pallas_bn import bn_apply, bn_stats, fused_bn_relu


@pytest.mark.parametrize("shape", [(4, 16, 14, 14), (2, 8, 7, 7), (3, 12, 5, 9)])
def test_fused_bn_matches_reference(shape):
    rng = np.random.RandomState(0)
    N, C, H, W = shape
    x = jnp.asarray(rng.randn(N, C, H, W).astype(np.float32))
    g = jnp.asarray(rng.rand(C).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(C).astype(np.float32))
    out, mean, var = fused_bn_relu(x, g, b, interpret=True)
    xm = np.asarray(x)
    m = xm.mean(axis=(0, 2, 3))
    v = xm.var(axis=(0, 2, 3))
    want = ((xm - m[None, :, None, None]) / np.sqrt(v[None, :, None, None] + 1e-5)
            * np.asarray(g)[None, :, None, None] + np.asarray(b)[None, :, None, None])
    np.testing.assert_allclose(np.asarray(mean), m, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(var), v, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(out), np.maximum(want, 0.0),
                               rtol=1e-4, atol=1e-4)


def test_fused_bn_residual_and_dtype():
    rng = np.random.RandomState(1)
    N, C, H, W = 2, 8, 14, 14
    x = jnp.asarray(rng.randn(N, C, H, W).astype(np.float32)).astype(jnp.bfloat16)
    res = jnp.asarray(rng.randn(N, C, H, W).astype(np.float32)).astype(jnp.bfloat16)
    g = jnp.ones(C, jnp.float32)
    b = jnp.zeros(C, jnp.float32)
    out, _, _ = fused_bn_relu(x, g, b, residual=res, interpret=True)
    assert out.dtype == jnp.bfloat16
    x32 = np.asarray(x, np.float32)
    m = x32.mean(axis=(0, 2, 3))
    v = x32.var(axis=(0, 2, 3))
    want = np.maximum((x32 - m[None, :, None, None])
                      / np.sqrt(v[None, :, None, None] + 1e-5)
                      + np.asarray(res, np.float32), 0.0)
    np.testing.assert_allclose(np.asarray(out, np.float32), want,
                               rtol=5e-2, atol=5e-2)  # bf16 storage


def test_bn_stats_one_pass_accumulation():
    """The grid revisits the stats block across N — exact fp32 sums."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(5, 6, 33).astype(np.float32))
    s = bn_stats(x, interpret=True)
    np.testing.assert_allclose(np.asarray(s[:, 0]),
                               np.asarray(x).sum(axis=(0, 2)), rtol=1e-5,
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(s[:, 1]),
                               (np.asarray(x) ** 2).sum(axis=(0, 2)),
                               rtol=1e-5, atol=1e-4)


def test_bn_apply_no_relu():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(2, 4, 10).astype(np.float32))
    scale = jnp.asarray(rng.rand(4).astype(np.float32))
    shift = jnp.asarray(rng.randn(4).astype(np.float32))
    out = bn_apply(x, scale, shift, relu=False, interpret=True)
    want = (np.asarray(x) * np.asarray(scale)[None, :, None]
            + np.asarray(shift)[None, :, None])
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


class TestTrainableBN:
    """MXNET_TPU_PALLAS_BN=interpret: the op-level dispatch must match the
    stock batch_norm in value AND gradients (reference-vjp backward)."""

    def test_value_and_grads_match(self, monkeypatch):
        import jax

        from incubator_mxnet_tpu.ops.nn import batch_norm

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 8, 6, 6).astype(np.float32))
        g = jnp.asarray(rng.rand(8).astype(np.float32) + 0.5)
        b = jnp.asarray(rng.randn(8).astype(np.float32))
        mm, mv = jnp.zeros(8), jnp.ones(8)

        def loss(x, g, b, env):
            monkeypatch.setenv("MXNET_TPU_PALLAS_BN", env)
            out, mean, var = batch_norm(x, g, b, mm, mv, fix_gamma=False)
            monkeypatch.setenv("MXNET_TPU_PALLAS_BN", "0")
            return jnp.sum(jnp.sin(out)) + jnp.sum(mean) + jnp.sum(var)

        v1, g1 = jax.value_and_grad(lambda *a: loss(*a, "interpret"),
                                    argnums=(0, 1, 2))(x, g, b)
        v2, g2 = jax.value_and_grad(lambda *a: loss(*a, "0"),
                                    argnums=(0, 1, 2))(x, g, b)
        np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)
        for a, c in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                       rtol=1e-4, atol=1e-4)

    def test_training_through_gluon_layer(self, monkeypatch):
        monkeypatch.setenv("MXNET_TPU_PALLAS_BN", "interpret")
        import incubator_mxnet_tpu as mx
        from incubator_mxnet_tpu import autograd, gluon

        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1), gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"))
        net.initialize()
        x = mx.nd.random.uniform(shape=(2, 3, 8, 8))
        with autograd.record():
            out = net(x)
            loss = (out ** 2).mean()
        loss.backward()
        gsum = float(net[0].weight.grad().abs().sum().asscalar())
        assert gsum > 0
