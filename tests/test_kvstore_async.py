"""In-process unit tier for the dist_async parameter server (async_ps.py):
protocol, applied-on-arrival semantics, the SSP staleness bound, and the
fault-tolerance machinery (leases/eviction, dedup, snapshot/restore,
typed errors) — the single-process complement to tests/test_dist.py's
8-worker subprocess tier and tests/test_chaos.py's fault-injection tier.
"""
import pickle
import socket
import threading
import time

import numpy as np
import pytest

from incubator_mxnet_tpu.kvstore.async_ps import (
    AsyncClient, HeartbeatThread, ParameterServer,
    PSError, PSKeyError, PSProtocolError, PSTimeoutError)


@pytest.fixture()
def server():
    ps = ParameterServer(num_workers=2, port=0)  # ephemeral port
    yield ps
    ps.stop()


def _client(ps):
    host, port = ps.address
    return AsyncClient(host, port)


def test_init_push_pull_roundtrip(server):
    c = _client(server)
    c.request("init", "k", np.zeros(3, np.float32))
    c.request("push", "k", np.ones(3, np.float32), 0)
    c.request("push", "k", 2 * np.ones(3, np.float32), 1)
    np.testing.assert_allclose(c.request("pull", "k"), 3 * np.ones(3))
    assert c.request("counts") == [1, 1]
    with pytest.raises(KeyError):
        c.request("pull", "missing")


def test_pushes_apply_on_arrival_without_peers(server):
    """The async contract: one worker's pushes land with no contribution
    from (or waiting on) the other registered worker."""
    c = _client(server)
    c.request("init", "w", np.zeros(1, np.float32))
    for _ in range(5):
        c.request("push", "w", np.ones(1, np.float32), 0)
    np.testing.assert_allclose(c.request("pull", "w"), [5.0])
    assert c.request("counts") == [5, 0]


def test_server_side_optimizer(server):
    import pickle

    from incubator_mxnet_tpu import optimizer as opt_mod

    c = _client(server)
    c.request("init", "w", np.ones(4, np.float32))
    c.request("set_optimizer",
              pickle.dumps(opt_mod.create("sgd", learning_rate=0.5)))
    c.request("push", "w", np.ones(4, np.float32), 0)
    np.testing.assert_allclose(c.request("pull", "w"), np.full(4, 0.5),
                               rtol=1e-6)


def test_ssp_staleness_bound():
    """With staleness=2 a fast worker blocks once it leads the slowest
    ACTIVE worker by the bound, until the straggler catches up (SSP, Ho et
    al. 2013; bound applies only among workers that have pushed — a
    pull-only rank must never deadlock the pushers)."""
    ps = ParameterServer(num_workers=2, port=0, staleness=2)
    try:
        fast, slow = _client(ps), _client(ps)
        fast.request("init", "k", np.zeros(1, np.float32))

        # peer never pushed -> no bound engages (the no-deadlock rule)
        for _ in range(3):
            fast.request("push", "k", np.ones(1, np.float32), 0)
        assert ps._push_counts == [3, 0]

        slow.request("push", "k", np.ones(1, np.float32), 1)  # now active
        t_done = {}

        def fast_worker():
            for _ in range(3):  # tries to reach 6; bound parks it at 1+2=3
                fast.request("push", "k", np.ones(1, np.float32), 0)
            t_done["fast"] = time.monotonic()

        th = threading.Thread(target=fast_worker)
        t0 = time.monotonic()
        th.start()
        time.sleep(0.6)
        # fast is already 2 ahead of the active slow (3 vs 1): every further
        # push must wait, so counts stay parked
        assert ps._push_counts == [3, 1], ps._push_counts
        for _ in range(3):
            slow.request("push", "k", np.ones(1, np.float32), 1)
        th.join(timeout=10)
        assert not th.is_alive()
        assert t_done["fast"] - t0 > 0.5  # it really did wait
        np.testing.assert_allclose(fast.request("pull", "k"), [10.0])
        assert ps._push_counts == [6, 4]
    finally:
        ps.stop()


def test_unbounded_by_default():
    ps = ParameterServer(num_workers=2, port=0)
    try:
        c = _client(ps)
        c.request("init", "k", np.zeros(1, np.float32))
        t0 = time.monotonic()
        for _ in range(50):
            c.request("push", "k", np.ones(1, np.float32), 0)
        assert time.monotonic() - t0 < 5.0
        assert ps._push_counts == [50, 0]
    finally:
        ps.stop()


def test_push_codes_wire_compression(server):
    """int8 codes + threshold over the wire; server decodes to codes*t."""
    c = _client(server)
    c.request("init", "k", np.zeros(4, np.float32))
    codes = np.array([1, -1, 0, 1], np.int8)
    c.request("push_codes", "k", codes, 0.5, 0)
    np.testing.assert_allclose(c.request("pull", "k"),
                               [0.5, -0.5, 0.0, 0.5])
    assert c.request("counts") == [1, 0]


def test_error_hierarchy(server):
    """Every server-side err reply maps onto the typed hierarchy; only a
    genuinely missing key is a KeyError."""
    c = _client(server)
    with pytest.raises(PSKeyError) as ei:
        c.request("pull", "missing")
    assert isinstance(ei.value, KeyError) and isinstance(ei.value, PSError)
    with pytest.raises(PSProtocolError) as ei:
        c.request("no_such_message")
    assert not isinstance(ei.value, KeyError)
    assert "no_such_message" in str(ei.value)
    with pytest.raises(PSProtocolError):
        c.request("push", "k")  # malformed: missing fields -> type error


def test_register_members_dynamic_num_workers():
    """register/deregister grow and shrink live membership without a
    cluster restart; each change bumps the membership epoch."""
    ps = ParameterServer(num_workers=2, port=0)
    try:
        c = _client(ps)
        m0 = c.request("members")
        assert m0["ranks"] == [0, 1] and ps.num_workers == 2
        assert float(c.request("register", 5)) > 0  # join: lease granted
        m1 = c.request("members")
        assert m1["ranks"] == [0, 1, 5] and ps.num_workers == 3
        assert m1["epoch"] > m0["epoch"]
        c.request("deregister", 5)                  # clean leave
        m2 = c.request("members")
        assert m2["ranks"] == [0, 1] and m2["epoch"] > m1["epoch"]
    finally:
        ps.stop()


def test_lease_eviction_unblocks_ssp_pusher():
    """A registered worker that stops heartbeating is evicted after its
    lease, and a pusher blocked on it by the SSP bound unblocks within the
    eviction window instead of waiting forever."""
    ps = ParameterServer(num_workers=2, port=0, staleness=1, lease_s=0.4)
    try:
        fast, dead = _client(ps), _client(ps)
        fast.request("register", 0)
        dead.request("register", 1)
        fast.request("init", "k", np.zeros(1, np.float32))
        fast.request("push", "k", np.ones(1, np.float32), 0)
        dead.request("push", "k", np.ones(1, np.float32), 1)
        fast.request("push", "k", np.ones(1, np.float32), 0)  # lead 2-1=1
        # rank 1 now goes silent: no heartbeat renews its lease.  rank 0's
        # next push leads it by the bound and must block — then unblock
        # once the reaper evicts rank 1.
        hb = HeartbeatThread(*ps.address, rank=0, interval=0.1)
        hb.start()
        t0 = time.monotonic()
        fast.request("push", "k", np.ones(1, np.float32), 0)
        waited = time.monotonic() - t0
        hb.stop()
        assert waited < 4 * 0.4 + 2.0, f"eviction took {waited:.1f}s"
        members = fast.request("members")
        assert members["ranks"] == [0], members
        # the SSP wait may unblock on LAZY lease expiry a tick before the
        # reaper formally evicts and counts — poll briefly for the counter
        from incubator_mxnet_tpu import profiler

        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline \
                and profiler.counters()["ps_eviction"] < 1:
            time.sleep(0.05)
        assert profiler.counters()["ps_eviction"] >= 1
    finally:
        ps.stop()


def test_heartbeat_thread_keeps_lease_alive():
    ps = ParameterServer(num_workers=1, port=0, lease_s=0.4)
    try:
        c = _client(ps)
        c.request("register", 3)
        hb = HeartbeatThread(*ps.address, rank=3, interval=0.1)
        hb.start()
        time.sleep(1.2)  # three lease windows
        assert 3 in c.request("members")["ranks"]
        hb.stop()
        time.sleep(1.0)  # now the lease lapses
        assert 3 not in c.request("members")["ranks"]
    finally:
        ps.stop()


def test_dedup_window_suppresses_duplicate_push(server):
    """The same (client_id, seq) envelope delivered twice applies once and
    returns the cached reply (at-most-once pushes)."""
    from incubator_mxnet_tpu import profiler

    c = _client(server)
    c.request("init", "k", np.zeros(2, np.float32))
    env = ("req", "dup-client", 0, ("push", "k", np.ones(2, np.float32), 0))
    raw = socket.create_connection(server.address)
    try:
        before = profiler.counters()["ps_dedup_hit"]
        from incubator_mxnet_tpu.kvstore.async_ps import _recv_msg, _send_msg

        _send_msg(raw, env)
        r1 = _recv_msg(raw)
        _send_msg(raw, env)   # duplicate delivery of the SAME request
        r2 = _recv_msg(raw)
        assert r1 == r2 == ("rep", 0, ("ok",))
        assert c.request("counts")[0] == 1  # applied exactly once
        assert profiler.counters()["ps_dedup_hit"] == before + 1
    finally:
        raw.close()


def test_ssp_timeout_names_lagging_rank():
    """Bounded SSP wait: a pusher stuck behind a live-but-stalled peer
    fails loudly after MXNET_KVSTORE_SSP_TIMEOUT, naming the laggard."""
    ps = ParameterServer(num_workers=2, port=0, staleness=1, ssp_timeout=1.5)
    try:
        fast, slow = _client(ps), _client(ps)
        fast.request("init", "k", np.zeros(1, np.float32))
        fast.request("push", "k", np.ones(1, np.float32), 0)
        slow.request("push", "k", np.ones(1, np.float32), 1)
        fast.request("push", "k", np.ones(1, np.float32), 0)  # lead 2-1=1
        # rank 1 is alive (legacy member, no lease to expire) but stalled:
        # the bound engages and only the timeout can end the wait
        with pytest.raises(PSTimeoutError, match="lagging rank 1"):
            fast.request("push", "k", np.ones(1, np.float32), 0)
        assert ps._push_counts == [2, 1]  # the timed-out push did NOT apply
    finally:
        ps.stop()


def test_snapshot_restore_roundtrip(tmp_path):
    """A restarted server resumes from the last complete snapshot: store,
    push counts, server-side optimizer, and dedup window all survive."""
    import incubator_mxnet_tpu.optimizer as opt_mod

    snap = str(tmp_path / "ps.snap")
    ps = ParameterServer(num_workers=2, port=0, snapshot_path=snap,
                         snapshot_every_s=0)  # explicit snapshots only
    c = _client(ps)
    c.request("init", "w", np.ones(3, np.float32))
    c.request("set_optimizer",
              pickle.dumps(opt_mod.create("sgd", learning_rate=0.5)))
    c.request("push", "w", np.ones(3, np.float32), 0)   # w -> 0.5
    c.request("snapshot")
    ps.stop(final_snapshot=False)  # crash: nothing after the snapshot lands

    ps2 = ParameterServer(num_workers=2, port=0, snapshot_path=snap,
                          snapshot_every_s=0)
    try:
        c2 = _client(ps2)
        np.testing.assert_allclose(c2.request("pull", "w"), np.full(3, 0.5))
        assert c2.request("counts") == [1, 0]
        c2.request("push", "w", np.ones(3, np.float32), 0)  # updater survived
        np.testing.assert_allclose(c2.request("pull", "w"), np.zeros(3),
                                   atol=1e-7)
    finally:
        ps2.stop()


def test_barrier_releases_on_clean_leave():
    """A deregister mid-barrier shrinks the target so survivors release
    instead of waiting on a departed worker."""
    ps = ParameterServer(num_workers=2, port=0)
    try:
        a, b = _client(ps), _client(ps)
        a.request("register", 0)
        b.request("register", 1)
        done = threading.Event()

        def waiter():
            a.request("barrier")
            done.set()

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.3)
        assert not done.is_set()  # barrier holds at 1/2
        b.request("deregister", 1)
        assert done.wait(timeout=5), "barrier did not release on leave"
        th.join(timeout=5)
    finally:
        ps.stop()


def test_client_reconnects_across_server_restart(tmp_path):
    """AsyncClient.request survives a server restart transparently:
    the in-flight request retries with backoff until the reborn server
    (same port, restored snapshot) answers."""
    snap = str(tmp_path / "ps.snap")
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    ps = ParameterServer(num_workers=1, port=port, snapshot_path=snap,
                         snapshot_every_s=0)
    c = AsyncClient("127.0.0.1", port, attempt_timeout=1.0, deadline_s=30.0)
    c.request("init", "k", np.arange(4, dtype=np.float32))
    c.request("snapshot")
    ps.stop(final_snapshot=False)

    got = {}

    def puller():
        got["v"] = c.request("pull", "k")

    th = threading.Thread(target=puller, daemon=True)
    th.start()
    time.sleep(0.8)  # the request is now failing against a dead port
    ps2 = ParameterServer(num_workers=1, port=port, snapshot_path=snap,
                          snapshot_every_s=0)
    try:
        th.join(timeout=20)
        assert not th.is_alive(), "request did not recover after restart"
        np.testing.assert_allclose(got["v"], np.arange(4))
        from incubator_mxnet_tpu import profiler

        assert profiler.counters()["ps_retry"] >= 1
    finally:
        ps2.stop()


def test_store_close_leaves_membership(monkeypatch):
    """KVStoreDistAsync registers on construction and close() leaves the
    membership immediately (elastic leave, no eviction window)."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.kvstore import async_ps

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    monkeypatch.setenv("MXNET_ASYNC_PS_PORT", str(port))
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    monkeypatch.setattr(async_ps, "_SERVER", None)

    kv = mx.kv.create("dist_async")
    try:
        assert kv.rank in kv.live_workers()
        assert kv.num_live_workers() >= 1
        epoch0 = kv.membership_epoch()
        server = kv._server
        # Trainer integration: close() rides the trainer teardown (and the
        # context-manager form), deregistering the rank immediately
        from incubator_mxnet_tpu import autograd, gluon

        net = gluon.nn.Dense(1)
        net.initialize()
        net(mx.nd.ones((1, 2)))
        with gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=kv) as trainer:
            with autograd.record():
                loss = (net(mx.nd.ones((1, 2))) ** 2).mean()
            loss.backward()
            trainer.step(1)
        assert kv._closed  # the context manager closed the store
        with server._lock:
            assert kv.rank in server._left  # left NOW, not at lease expiry
            assert server._epoch > epoch0
    finally:
        kv._server.stop()


def test_async_store_compression_end_to_end(monkeypatch):
    """KVStoreDistAsync with gradient compression: error-feedback residual
    on the worker, int8 codes on the wire, exact 2-bit semantics at the
    server."""
    import socket

    import incubator_mxnet_tpu as mx

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    monkeypatch.setenv("MXNET_ASYNC_PS_PORT", str(port))
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    # fresh server singleton for this port
    from incubator_mxnet_tpu.kvstore import async_ps
    monkeypatch.setattr(async_ps, "_SERVER", None)

    kv = mx.kv.create("dist_async")
    try:
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.init("w", mx.nd.zeros((4,)))
        g = mx.nd.array(np.array([0.7, -0.9, 0.2, 0.0], np.float32))
        kv.push("w", g)
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        # first push: codes [1,-1,0,0] * 0.5
        np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
        assert kv._last_wire_dtype == "int8"
        # second identical push: residuals [0.2,-0.4,0.2,0] accumulate ->
        # g+res = [0.9,-1.3,0.4,0.0] -> codes [1,-1,0,0] again
        kv.push("w", g)
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), [1.0, -1.0, 0.0, 0.0])
    finally:
        kv.close()   # stop the heartbeat thread, not just the server
        kv._server.stop()


# ---------------------------------------------------------------------------
# ISSUE 7: clock sampling + metrics piggyback on the heartbeat wire
# ---------------------------------------------------------------------------


def _fake_snap(rank, host="hX", seq=3, step=9):
    return {"schema": 1, "rank": rank, "host": host, "pid": 7000 + rank,
            "seq": seq, "time_unix": time.time(), "counters": {},
            "last_step": {"step": step, "wall_ms": 800.0, "host_ms": 10.0,
                          "comms_ms": 700.0, "device_ms": 90.0},
            "window": {"n": 1, "wall_ms_median": 800.0,
                       "wall_ms_max": 800.0},
            "memory_watermark_bytes": {}}


def _clear_peer(rank):
    from incubator_mxnet_tpu import profiler

    with profiler._counter_lock:
        profiler._peer_metrics.pop(rank, None)


def test_clock_message_and_offset_sampling(server):
    """The ("clock",) read returns the server's wall time, and the
    profiler's midpoint-of-RTT sampler derives a near-zero offset from a
    same-host server (|offset| is bounded by the observed RTT)."""
    from incubator_mxnet_tpu import profiler

    c = _client(server)
    now = c.request("clock")
    assert isinstance(now, float) and abs(now - time.time()) < 5.0
    best = profiler.sample_clock_offset(lambda: c.request("clock"),
                                        samples=3)
    assert best is not None
    off, rtt = best
    assert rtt > 0 and abs(off) <= rtt + 0.05


def test_heartbeat_piggybacks_metrics_and_returns_server_clock(server):
    """("heartbeat", rank, snapshot): the snapshot lands in the server's
    per-rank metrics table AND the co-located profiler peer registry, and
    the reply is the server's wall clock (the free offset sample).  A
    bare 2-tuple heartbeat still works."""
    from incubator_mxnet_tpu import profiler

    c = _client(server)
    try:
        server_now = c.request("heartbeat", 1, _fake_snap(1))
        assert isinstance(server_now, float)
        assert abs(server_now - time.time()) < 5.0
        stored = c.request("metrics")
        assert stored[1]["last_step"]["step"] == 9
        assert profiler.peer_metrics()[1]["host"] == "hX"
        assert isinstance(c.request("heartbeat", 0), float)  # legacy shape
    finally:
        _clear_peer(1)


def test_heartbeat_thread_ships_snapshots_and_samples_clock(server):
    """The background HeartbeatThread does the piggyback unprompted: the
    server accumulates this worker's snapshots and the local clock-offset
    estimate gets (re)sampled from the beat replies."""
    from incubator_mxnet_tpu import profiler
    from incubator_mxnet_tpu.kvstore.async_ps import HeartbeatThread

    hb = HeartbeatThread(*server.address, rank=1, interval=0.05)
    hb.start()
    try:
        deadline = time.monotonic() + 10.0
        stored = {}
        c = _client(server)
        while time.monotonic() < deadline:
            stored = c.request("metrics")
            if 1 in stored:
                break
            time.sleep(0.05)
        assert 1 in stored, "heartbeat never delivered a snapshot"
        assert "counters" in stored[1] and "seq" in stored[1]
        assert profiler.process_info()["clock_rtt_s"] is not None
    finally:
        hb.stop()
        _clear_peer(stored.get(1, {}).get("rank", -1))


def test_ssp_timeout_carries_straggler_telemetry():
    """The bounded-SSP-wait error names the lagging rank WITH its
    heartbeat-shipped host/comms/device split (and degrades to a plain
    rank id when the straggler never heartbeat a snapshot)."""
    ps = ParameterServer(num_workers=2, port=0, staleness=1, ssp_timeout=1.5)
    try:
        c = _client(ps)
        c.request("init", "k", np.zeros(1, np.float32))
        c.request("push", "k", np.ones(1, np.float32), 1)
        c.request("heartbeat", 1, _fake_snap(1))
        c.request("push", "k", np.ones(1, np.float32), 0)
        c.request("push", "k", np.ones(1, np.float32), 0)
        with pytest.raises(PSTimeoutError) as ei:
            c.request("push", "k", np.ones(1, np.float32), 0)
        msg = str(ei.value)
        assert "lagging rank 1" in msg
        assert "host hX" in msg and "host-dispatch 10.0 ms" in msg
        assert "comms 700.0 ms" in msg and "device/other 90.0 ms" in msg
    finally:
        ps.stop()
        _clear_peer(1)


def test_ssp_timeout_without_telemetry_degrades_gracefully():
    ps = ParameterServer(num_workers=2, port=0, staleness=1, ssp_timeout=1.0)
    try:
        c = _client(ps)
        c.request("init", "k", np.zeros(1, np.float32))
        c.request("push", "k", np.ones(1, np.float32), 1)
        c.request("push", "k", np.ones(1, np.float32), 0)
        c.request("push", "k", np.ones(1, np.float32), 0)
        with pytest.raises(PSTimeoutError,
                           match="no telemetry heartbeat"):
            c.request("push", "k", np.ones(1, np.float32), 0)
    finally:
        ps.stop()
