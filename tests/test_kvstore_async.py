"""In-process unit tier for the dist_async parameter server (async_ps.py):
protocol, applied-on-arrival semantics, and the SSP staleness bound — the
single-process complement to tests/test_dist.py's 8-worker subprocess tier.
"""
import threading
import time

import numpy as np
import pytest

from incubator_mxnet_tpu.kvstore.async_ps import AsyncClient, ParameterServer


@pytest.fixture()
def server():
    ps = ParameterServer(num_workers=2, port=0)  # ephemeral port
    yield ps
    ps.stop()


def _client(ps):
    host, port = ps.address
    return AsyncClient(host, port)


def test_init_push_pull_roundtrip(server):
    c = _client(server)
    c.request("init", "k", np.zeros(3, np.float32))
    c.request("push", "k", np.ones(3, np.float32), 0)
    c.request("push", "k", 2 * np.ones(3, np.float32), 1)
    np.testing.assert_allclose(c.request("pull", "k"), 3 * np.ones(3))
    assert c.request("counts") == [1, 1]
    with pytest.raises(KeyError):
        c.request("pull", "missing")


def test_pushes_apply_on_arrival_without_peers(server):
    """The async contract: one worker's pushes land with no contribution
    from (or waiting on) the other registered worker."""
    c = _client(server)
    c.request("init", "w", np.zeros(1, np.float32))
    for _ in range(5):
        c.request("push", "w", np.ones(1, np.float32), 0)
    np.testing.assert_allclose(c.request("pull", "w"), [5.0])
    assert c.request("counts") == [5, 0]


def test_server_side_optimizer(server):
    import pickle

    from incubator_mxnet_tpu import optimizer as opt_mod

    c = _client(server)
    c.request("init", "w", np.ones(4, np.float32))
    c.request("set_optimizer",
              pickle.dumps(opt_mod.create("sgd", learning_rate=0.5)))
    c.request("push", "w", np.ones(4, np.float32), 0)
    np.testing.assert_allclose(c.request("pull", "w"), np.full(4, 0.5),
                               rtol=1e-6)


def test_ssp_staleness_bound():
    """With staleness=2 a fast worker blocks once it leads the slowest
    ACTIVE worker by the bound, until the straggler catches up (SSP, Ho et
    al. 2013; bound applies only among workers that have pushed — a
    pull-only rank must never deadlock the pushers)."""
    ps = ParameterServer(num_workers=2, port=0, staleness=2)
    try:
        fast, slow = _client(ps), _client(ps)
        fast.request("init", "k", np.zeros(1, np.float32))

        # peer never pushed -> no bound engages (the no-deadlock rule)
        for _ in range(3):
            fast.request("push", "k", np.ones(1, np.float32), 0)
        assert ps._push_counts == [3, 0]

        slow.request("push", "k", np.ones(1, np.float32), 1)  # now active
        t_done = {}

        def fast_worker():
            for _ in range(3):  # tries to reach 6; bound parks it at 1+2=3
                fast.request("push", "k", np.ones(1, np.float32), 0)
            t_done["fast"] = time.monotonic()

        th = threading.Thread(target=fast_worker)
        t0 = time.monotonic()
        th.start()
        time.sleep(0.6)
        # fast is already 2 ahead of the active slow (3 vs 1): every further
        # push must wait, so counts stay parked
        assert ps._push_counts == [3, 1], ps._push_counts
        for _ in range(3):
            slow.request("push", "k", np.ones(1, np.float32), 1)
        th.join(timeout=10)
        assert not th.is_alive()
        assert t_done["fast"] - t0 > 0.5  # it really did wait
        np.testing.assert_allclose(fast.request("pull", "k"), [10.0])
        assert ps._push_counts == [6, 4]
    finally:
        ps.stop()


def test_unbounded_by_default():
    ps = ParameterServer(num_workers=2, port=0)
    try:
        c = _client(ps)
        c.request("init", "k", np.zeros(1, np.float32))
        t0 = time.monotonic()
        for _ in range(50):
            c.request("push", "k", np.ones(1, np.float32), 0)
        assert time.monotonic() - t0 < 5.0
        assert ps._push_counts == [50, 0]
    finally:
        ps.stop()


def test_push_codes_wire_compression(server):
    """int8 codes + threshold over the wire; server decodes to codes*t."""
    c = _client(server)
    c.request("init", "k", np.zeros(4, np.float32))
    codes = np.array([1, -1, 0, 1], np.int8)
    c.request("push_codes", "k", codes, 0.5, 0)
    np.testing.assert_allclose(c.request("pull", "k"),
                               [0.5, -0.5, 0.0, 0.5])
    assert c.request("counts") == [1, 0]


def test_async_store_compression_end_to_end(monkeypatch):
    """KVStoreDistAsync with gradient compression: error-feedback residual
    on the worker, int8 codes on the wire, exact 2-bit semantics at the
    server."""
    import socket

    import incubator_mxnet_tpu as mx

    s = socket.socket(); s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]; s.close()
    monkeypatch.setenv("MXNET_ASYNC_PS_PORT", str(port))
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    # fresh server singleton for this port
    from incubator_mxnet_tpu.kvstore import async_ps
    monkeypatch.setattr(async_ps, "_SERVER", None)

    kv = mx.kv.create("dist_async")
    try:
        kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
        kv.init("w", mx.nd.zeros((4,)))
        g = mx.nd.array(np.array([0.7, -0.9, 0.2, 0.0], np.float32))
        kv.push("w", g)
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        # first push: codes [1,-1,0,0] * 0.5
        np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0, 0.0])
        assert kv._last_wire_dtype == "int8"
        # second identical push: residuals [0.2,-0.4,0.2,0] accumulate ->
        # g+res = [0.9,-1.3,0.4,0.0] -> codes [1,-1,0,0] again
        kv.push("w", g)
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), [1.0, -1.0, 0.0, 0.0])
    finally:
        kv._server.stop()
