"""Pipeline-schedule + MoE tier tests (ISSUE 13): the 1F1B/GPipe
training scheduler (parallel/schedule.py), its SPMDTrainer integration
(stages= / pipeline=), and the expert-parallel MoE layer."""
import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, profiler
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.model_zoo.moe import (
    MoEBlock, moe_loss_frame, frame_loss, frame_metrics)
from incubator_mxnet_tpu.ops.moe import moe_capacity, moe_ffn
from incubator_mxnet_tpu.parallel import (
    SPMDTrainer,
    analytic_bubble_fraction,
    build_schedule,
    make_mesh,
    pipeline_value_and_grad,
    simulate_schedule,
)

import jax
import jax.numpy as jnp


class TestScheduleBuilder:
    @pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("P,M", [(2, 2), (4, 8), (4, 3), (8, 16), (1, 4)])
    def test_every_slot_once_and_runnable(self, kind, P, M):
        orders = build_schedule(P, M, kind)
        assert len(orders) == P
        for s in range(P):
            assert sorted(orders[s]) == sorted(
                [("F", m) for m in range(M)] + [("B", m) for m in range(M)])
        # the simulator raises on any dependency deadlock
        sim = simulate_schedule(P, M, kind)
        assert len(sim["timeline"]) == 2 * P * M

    def test_1f1b_in_flight_bound(self):
        """At most P−s microbatches are in flight per stage under 1F1B —
        the activation-memory property the schedule exists for."""
        P, M = 4, 12
        orders = build_schedule(P, M, "1f1b")
        for s, slots in enumerate(orders):
            live = 0
            peak = 0
            for op, _m in slots:
                live += 1 if op == "F" else -1
                peak = max(peak, live)
            assert peak <= P - s, f"stage {s} holds {peak} stashes"

    def test_bubble_fractions(self):
        P, M = 4, 8
        bound = analytic_bubble_fraction(P, M)
        f1 = simulate_schedule(P, M, "1f1b", tf=1.0, tb=2.0, remat=False)
        gp = simulate_schedule(P, M, "gpipe", tf=1.0, tb=2.0, remat=True)
        # 1F1B without remat sits exactly on the fill/drain bound
        assert abs(f1["bubble_fraction"] - bound) < 1e-9
        assert f1["bubble_fraction"] <= 1.5 * bound
        # GPipe in its paper configuration (full remat) pays recompute
        assert gp["bubble_fraction"] > f1["bubble_fraction"]
        # idle fraction (ignoring recompute overhead) matches the classic
        # result: both schedules are work-conserving
        assert abs(gp["idle_fraction"] - bound) < 1e-9

    def test_validation(self):
        with pytest.raises(ValueError):
            build_schedule(2, 4, "zigzag")
        with pytest.raises(ValueError):
            build_schedule(0, 4)
        with pytest.raises(ValueError):
            simulate_schedule(3, 4, remat=[True])  # wrong per-stage length


def _stage_setup(P=4, D=6, B=16):
    rng = np.random.RandomState(0)
    params = [{"w": jnp.asarray(rng.randn(D, D).astype(np.float32) * 0.4),
               "b": jnp.asarray(rng.randn(D).astype(np.float32) * 0.1)}
              for _ in range(P)]
    x = jnp.asarray(rng.randn(B, D).astype(np.float32))
    y = jnp.asarray(rng.randn(B, D).astype(np.float32))
    stage_fn = lambda p, h: jnp.tanh(h @ p["w"] + p["b"])
    loss_fn = lambda out, lab: jnp.sum((out - lab) ** 2)
    return params, x, y, stage_fn, loss_fn


class TestPipelineEngine:
    @pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("remat", [False, True])
    def test_matches_sequential(self, kind, remat):
        P = 4
        params, x, y, stage_fn, loss_fn = _stage_setup(P=P)

        def seq(ps, xx, yy):
            h = xx
            for p in ps:
                h = stage_fn(p, h)
            return jnp.sum((h - yy) ** 2)

        ref_l, ref_g = jax.value_and_grad(seq)(params, x, y)
        task, side, grads, _ = jax.jit(
            lambda ps, xx, yy: pipeline_value_and_grad(
                [stage_fn] * P, loss_fn, ps, xx, yy, 8,
                schedule=kind, remat=remat))(params, x, y)
        np.testing.assert_allclose(float(task), float(ref_l), rtol=1e-5)
        assert float(side) == 0.0
        for s in range(P):
            for k in ("w", "b"):
                np.testing.assert_allclose(
                    np.asarray(grads[s][k]), np.asarray(ref_g[s][k]),
                    rtol=1e-4, atol=1e-5, err_msg=f"stage {s} {k}")

    def test_rich_side_losses_and_metrics(self):
        """Side losses get cotangent 1 through their own slot's vjp —
        including rematerialized stages, where the recompute must
        reproduce them — and metrics arrive per (stage, microbatch)."""
        P, M = 3, 4
        params, x, y, _, loss_fn = _stage_setup(P=P)

        def rich(p, h):
            h2 = jnp.tanh(h @ p["w"] + p["b"])
            return h2, 0.01 * jnp.sum(p["w"] ** 2), {
                "mean": jax.lax.stop_gradient(h2.mean())}

        def seq(ps, xx, yy):
            h = xx
            side = 0.0
            for p in ps:
                h = jnp.tanh(h @ p["w"] + p["b"])
                side = side + M * 0.01 * jnp.sum(p["w"] ** 2)
            return jnp.sum((h - yy) ** 2) + side

        ref_l, ref_g = jax.value_and_grad(seq)(params, x, y)
        for remat in (False, True):
            task, side, grads, mets = jax.jit(
                lambda ps, xx, yy: pipeline_value_and_grad(
                    [rich] * P, loss_fn, ps, xx, yy, M, schedule="1f1b",
                    remat=remat, stage_outputs="rich"))(params, x, y)
            np.testing.assert_allclose(
                float(task) + float(side), float(ref_l), rtol=1e-5)
            for s in range(P):
                np.testing.assert_allclose(
                    np.asarray(grads[s]["w"]), np.asarray(ref_g[s]["w"]),
                    rtol=1e-4, atol=1e-5)
            assert len(mets) == P and all(len(row) == M for row in mets)


def _mlp4(seed, in_dim=12):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(32, activation="relu"),
            nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, in_dim)))
    return net


def _params_of(net):
    return {k: p.data().asnumpy()
            for k, p in net._collect_params_with_prefix().items()}


def _assert_params_close(a, b, **kw):
    kw.setdefault("rtol", 2e-4)
    kw.setdefault("atol", 2e-5)
    pa, pb = _params_of(a), _params_of(b)
    assert set(pa) == set(pb)
    for k in pa:
        np.testing.assert_allclose(pa[k], pb[k], err_msg=k, **kw)


def _data(n=16, d=12, seed=3):
    rng = np.random.RandomState(seed)
    return (rng.randn(n, d).astype(np.float32),
            rng.randint(0, 4, (n,)).astype(np.float32))


class TestSPMDPipelineTrainer:
    @pytest.mark.parametrize("kind,remat", [
        ("gpipe", True), ("gpipe", False), ("1f1b", False), ("1f1b", True)])
    def test_matches_unpipelined(self, kind, remat):
        """The acceptance equivalence: pipelined (both schedules, with and
        without remat) params after 3 steps match the unpipelined
        single-program step on the same params within tolerance."""
        x, y = _data()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        net_a = _mlp4(seed=7)
        tr_a = SPMDTrainer(net_a, loss_fn, "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           mesh=make_mesh())
        for _ in range(3):
            tr_a.step(mx.nd.array(x), mx.nd.array(y))
        tr_a.sync_to_block()

        net_b = _mlp4(seed=7)
        tr_b = SPMDTrainer(
            net_b, loss_fn, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
            mesh=make_mesh(), stages=net_b.split_stages([1, 1, 1, 1]),
            pipeline={"schedule": kind, "n_microbatches": 8, "remat": remat})
        for _ in range(3):
            tr_b.step(mx.nd.array(x), mx.nd.array(y))
        tr_b.sync_to_block()
        _assert_params_close(net_a, net_b)

    def test_vector_loss_mean_parity(self):
        """A loss_fn returning per-ELEMENT losses (e.g. [B, T] token CE):
        the pipelined step must report the same mean as the unpipelined
        jnp.mean — sum/B would be off by a factor of T."""
        rng = np.random.RandomState(0)
        B, T, D = 8, 5, 6
        x = rng.randn(B, T, D).astype(np.float32)
        y = rng.randn(B, T, 4).astype(np.float32)

        def build():
            mx.random.seed(3)
            net = nn.HybridSequential()
            net.add(nn.Dense(16, flatten=False), nn.Dense(4, flatten=False))
            net.initialize()
            net(mx.nd.zeros((2, T, D)))
            return net

        def loss_fn(out, label):
            return (out - label) ** 2   # [B, T, 4] per-element loss

        net_a = build()
        la = SPMDTrainer(net_a, loss_fn, "sgd", {"learning_rate": 0.0},
                         mesh=make_mesh()).step(mx.nd.array(x), mx.nd.array(y))
        net_b = build()
        lb = SPMDTrainer(net_b, loss_fn, "sgd", {"learning_rate": 0.0},
                         mesh=make_mesh(), stages=net_b.split_stages([1, 1]),
                         pipeline={"schedule": "1f1b", "n_microbatches": 4}
                         ).step(mx.nd.array(x), mx.nd.array(y))
        np.testing.assert_allclose(float(la.asnumpy()), float(lb.asnumpy()),
                                   rtol=1e-5)

    def test_engine_pins_slot_for_keys(self):
        """The scheduler pins (stage, microbatch) around every slot trace
        — forward AND remat recompute — which is what lets the trainer
        fold a distinct PRNG key per microbatch (dropout masks must not
        repeat across microbatches) while a remat backward reproduces its
        forward's key exactly."""
        from incubator_mxnet_tpu.parallel.schedule import (
            current_slot, in_backward_trace)

        P, M = 2, 3
        seen = []

        def stage(p, h):
            seen.append((current_slot(), in_backward_trace()))
            return jnp.tanh(h * p)

        params = [jnp.float32(1.1), jnp.float32(0.9)]
        x = jnp.ones((6, 2), jnp.float32)
        loss_fn = lambda out, lab: jnp.sum((out - lab) ** 2)
        pipeline_value_and_grad([stage] * P, loss_fn, params, x,
                                jnp.zeros_like(x), M, schedule="1f1b",
                                remat=True)
        fwd = [slot for slot, bwd in seen if not bwd]
        # every (s, m) traced exactly once forward, slot always pinned —
        # in particular NOT one shared trace reused for every microbatch
        # (jax.checkpoint caches by function identity, so the engine must
        # wrap a fresh callable per slot; a cached reuse here would bake
        # microbatch 0's key fold into every microbatch)
        assert sorted(fwd) == [(s, m) for s in range(P) for m in range(M)]
        assert None not in fwd
        # modern jax.checkpoint replays the saved jaxpr in the backward
        # (no Python re-trace), so the forward trace above is the ONLY
        # place slot-dependent values enter — and they entered correctly

    def test_step_bulk_matches_sequential_steps(self):
        x, y = _data()
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        xa, ya = mx.nd.array(x), mx.nd.array(y)

        def make(seed):
            net = _mlp4(seed=seed)
            return net, SPMDTrainer(
                net, loss_fn, "adam", {"learning_rate": 0.01},
                mesh=make_mesh(), stages=net.split_stages([2, 2]),
                pipeline={"schedule": "1f1b", "n_microbatches": 4})

        mx.random.seed(5)
        net_a, seq = make(23)
        for _ in range(4):
            seq.step(xa, ya)
        seq.sync_to_block()

        mx.random.seed(5)
        net_b, blk = make(23)
        blk.step_bulk(xa, ya, 4)
        blk.sync_to_block()
        assert blk.num_update == seq.num_update == 4
        _assert_params_close(net_a, net_b)

    def test_batchnorm_aux_through_pipeline(self):
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Dense(16), nn.BatchNorm(), nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 8)))
        x, y = _data(n=16, d=8)
        tr = SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=make_mesh(),
            stages=net.split_stages([2, 1]),
            pipeline={"schedule": "1f1b", "n_microbatches": 4})
        params = net.collect_params()
        rm = [k for k in params if "running_mean" in k][0]
        before = params[rm].data().asnumpy().copy()
        tr.step(mx.nd.array(x), mx.nd.array(y))
        tr.sync_to_block()
        assert not np.allclose(before, params[rm].data().asnumpy())

    def test_zero_steady_state_recompiles_guard_raise(self, monkeypatch):
        """Acceptance: the whole scheduled step dispatches as one compiled
        program with zero steady-state recompiles under the raise-mode
        guard (auto-armed after the first step)."""
        monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
        profiler.disarm_compile_guard()
        try:
            x, y = _data()
            net = _mlp4(seed=9)
            tr = SPMDTrainer(
                net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                {"learning_rate": 0.1}, mesh=make_mesh(),
                stages=net.split_stages([2, 2]),
                pipeline={"schedule": "1f1b", "n_microbatches": 4})
            base = profiler.counters()["recompile_steady_state"]
            for _ in range(5):   # guard armed after step 1; raise = failure
                tr.step(mx.nd.array(x), mx.nd.array(y))
            assert profiler.counters()["recompile_steady_state"] == base
        finally:
            profiler.disarm_compile_guard()

    def test_counters_spans_provider(self, tmp_path):
        x, y = _data()
        net = _mlp4(seed=13)
        tr = SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=make_mesh(),
            stages=net.split_stages([1, 3]),
            pipeline={"schedule": "gpipe", "n_microbatches": 4})
        c0 = profiler.counters()
        out = str(tmp_path / "trace.json")
        profiler.set_config(filename=out)
        profiler.start()
        try:
            for _ in range(2):
                tr.step(mx.nd.array(x), mx.nd.array(y))
            out = profiler.dump()
        finally:
            profiler.stop()
        c1 = profiler.counters()
        assert c1["pipeline_step"] - c0["pipeline_step"] == 2
        assert c1["pipeline_microbatch"] - c0["pipeline_microbatch"] == 8
        assert c1["pipeline_bubble_ms"] >= c0["pipeline_bubble_ms"]
        snap = profiler.metrics_snapshot()
        prov = [v for k, v in snap["providers"].items()
                if k.startswith("pipeline")]
        assert prov and any(p.get("stages") == 2 for p in prov)
        with open(out) as f:
            events = json.load(f)["traceEvents"]
        names = {e.get("name") for e in events}
        assert "pipeline.step" in names
        assert "pipeline.stage" in names
        stage_args = [e["args"] for e in events
                      if e.get("name") == "pipeline.stage"
                      and e.get("ph") == "B"]
        assert {a["stage"] for a in stage_args} == {0, 1}

    def test_slow_step_annotator_scoped_to_own_steps(self, caplog):
        """The pipeline annotator names its busiest stage on the
        trainer's OWN slow steps and stays silent on anyone else's (a
        stale not-yet-collected trainer must not annotate an unrelated
        loop — the detector's exactly-once contract is per subsystem)."""
        import logging
        import time

        x, y = _data()
        net = _mlp4(seed=17)
        tr = SPMDTrainer(
            net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
            {"learning_rate": 0.1}, mesh=make_mesh(),
            stages=net.split_stages([2, 2]),
            pipeline={"schedule": "1f1b", "n_microbatches": 4})
        tr.step(mx.nd.array(x), mx.nd.array(y))  # compile outside timing
        profiler.set_config(slow_step_ms=0.001)  # every step is "slow"
        profiler.start()
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="incubator_mxnet_tpu.profiler"):
                tr.step(mx.nd.array(x), mx.nd.array(y))
                tr.step(mx.nd.array(x), mx.nd.array(y))
                main = [r for r in caplog.records
                        if "host-dispatch" in r.getMessage()]
                own = [r for r in caplog.records
                       if "modeled busy" in r.getMessage()]
                # exactly ONE annotator line per slow step, no more
                assert main and len(own) == len(main)
                assert "stage" in own[0].getMessage()
                caplog.clear()
                time.sleep(0.002)
                profiler.step_boundary()   # unrelated slow step
                stale = [r for r in caplog.records
                         if "modeled busy" in r.getMessage()]
                assert not stale
                assert any("slow step" in r.getMessage()
                           for r in caplog.records)
        finally:
            profiler.set_config(slow_step_ms=None)
            profiler.stop()

    def test_validation(self):
        x, y = _data()
        net = _mlp4(seed=2)
        with pytest.raises(ValueError):
            net.split_stages([1, 1])        # sizes don't cover
        with pytest.raises(ValueError):
            net.split_stages([0, 4])        # empty stage
        stages = net.split_stages([2, 2])
        loss = gluon.loss.SoftmaxCrossEntropyLoss()
        with pytest.raises(ValueError):     # missing n_microbatches
            SPMDTrainer(net, loss, "sgd", {}, stages=stages, pipeline={})
        with pytest.raises(ValueError):     # overlapping stage params
            SPMDTrainer(net, loss, "sgd", {},
                        stages=[stages[0], stages[0], stages[1]],
                        pipeline={"n_microbatches": 2})
        with pytest.raises(ValueError):     # pipeline config without stages
            SPMDTrainer(net, loss, "sgd", {},
                        pipeline={"n_microbatches": 2})


class TestMoE:
    def test_capacity_rule(self):
        assert moe_capacity(64, 4, 1, 1.0) == 16
        assert moe_capacity(64, 4, 2, 1.0) == 32
        assert moe_capacity(64, 4, 2, 1.25) == 40
        assert moe_capacity(4, 64, 1, 1.0) == 1    # floor
        assert moe_capacity(8, 2, 2, 100.0) == 8   # ceil at T

    def test_overflow_drop_exact_and_deterministic(self):
        """Force every token onto expert 0 (k=1): dropped must equal
        exactly T − capacity, twice in a row, under a fixed seed."""
        T, E, d = 24, 4, 8
        rng = np.random.RandomState(1)
        x = jnp.asarray(np.abs(rng.randn(T, d)).astype(np.float32) + 0.5)
        rw = np.zeros((d, E), np.float32)
        rw[:, 0] = 1.0
        args = (x, jnp.asarray(rw),
                jnp.asarray(rng.randn(E, d, 16).astype(np.float32) * 0.1),
                jnp.zeros((E, 16), jnp.float32),
                jnp.asarray(rng.randn(E, 16, d).astype(np.float32) * 0.1),
                jnp.zeros((E, d), jnp.float32))
        kw = dict(num_experts=E, top_k=1, capacity_factor=1.0)
        C = moe_capacity(T, E, 1, 1.0)
        o1 = moe_ffn(*args, **kw)
        o2 = moe_ffn(*args, **kw)
        assert float(o1[3]) == T - C == 18
        assert float(o1[4]) == 0.0 and float(o1[5]) == C
        np.testing.assert_array_equal(np.asarray(o1[0]), np.asarray(o2[0]))
        for i in range(1, 6):
            assert float(o1[i]) == float(o2[i])

    def test_dense_equivalence_at_full_capacity(self):
        """With capacity >= T and k = E, the MoE output must equal the
        dense mixture Σ_e gate_e · FFN_e(x) — routing is then a no-op."""
        T, E, d, h = 6, 3, 4, 5
        rng = np.random.RandomState(0)
        x = rng.randn(T, d).astype(np.float32)
        rw = rng.randn(d, E).astype(np.float32) * 0.3
        w1 = rng.randn(E, d, h).astype(np.float32) * 0.5
        b1 = rng.randn(E, h).astype(np.float32) * 0.1
        w2 = rng.randn(E, h, d).astype(np.float32) * 0.5
        b2 = rng.randn(E, d).astype(np.float32) * 0.1
        y, aux, z, dropped, _, _ = moe_ffn(
            jnp.asarray(x), jnp.asarray(rw), jnp.asarray(w1),
            jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
            num_experts=E, top_k=E, capacity_factor=float(E))
        assert float(dropped) == 0.0
        logits = x @ rw
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs = probs / probs.sum(-1, keepdims=True)
        ref = np.zeros_like(x)
        for e in range(E):
            he = np.maximum(x @ w1[e] + b1[e], 0.0)
            ref += probs[:, e:e + 1] * (he @ w2[e] + b2[e])
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4, atol=1e-5)
        # Switch aux at uniform-ish routing ~ 1; z finite
        assert np.isfinite(float(aux)) and np.isfinite(float(z))

    def test_frame_and_eager_aux(self):
        mx.random.seed(0)
        blk = MoEBlock(units=8, hidden_size=16, num_experts=4, top_k=2)
        blk.initialize()
        x = mx.nd.array(np.random.RandomState(0).randn(4, 6, 8)
                        .astype(np.float32))
        with moe_loss_frame() as fr:
            y = blk(x)
        assert y.shape == (4, 6, 8)
        assert frame_loss(fr) is not None
        mets = frame_metrics(fr)
        assert set(mets) == {"tokens_dropped", "expert_load_min",
                             "expert_load_max"}
        y2 = blk(x)   # no frame: stashes for the eager path
        np.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), rtol=1e-6)
        assert float(np.asarray(blk.aux_loss()._data
                                if hasattr(blk.aux_loss(), "_data")
                                else blk.aux_loss())) >= 0.0

    def test_hybridize_does_not_stash_tracer(self):
        """A hybridized MoE forward runs inside the cached-graph trace:
        it must NOT stash that trace's tracer for aux_loss() (which would
        leak out of the finished trace) — and the hybridized output must
        still match eager."""
        mx.random.seed(8)
        blk = MoEBlock(units=8, hidden_size=16, num_experts=4, top_k=2)
        blk.initialize()
        x = mx.nd.array(np.random.RandomState(2).randn(4, 6, 8)
                        .astype(np.float32))
        eager = blk(x).asnumpy()          # eager: stashes a concrete value
        concrete = blk.aux_loss()
        blk.hybridize()
        hybrid = blk(x).asnumpy()
        np.testing.assert_allclose(hybrid, eager, rtol=1e-5, atol=1e-6)
        assert blk.aux_loss() is concrete   # tracer never replaced it
        mx.random.seed(8)
        fresh = MoEBlock(units=8, hidden_size=16, num_experts=4, top_k=2)
        fresh.initialize()
        fresh.hybridize()
        fresh(x)
        with pytest.raises(RuntimeError, match="moe_loss_frame"):
            fresh.aux_loss()

    def test_moe_trains_through_pipeline_acceptance(self, monkeypatch):
        """The ISSUE acceptance: an MoE block trains through the 1F1B
        pipeline on a dp×ep mesh — loss decreases, zero steady-state
        recompiles under the raise guard, drop/load metrics visible in
        metrics_snapshot(), expert weights genuinely ep-sharded."""
        monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
        profiler.disarm_compile_guard()
        try:
            from incubator_mxnet_tpu.gluon.model_zoo.moe import (
                moe_sharding_rules)

            mx.random.seed(5)
            net = nn.HybridSequential()
            net.add(nn.Dense(16, activation="relu", flatten=False),
                    MoEBlock(units=16, hidden_size=32, num_experts=4,
                             top_k=2, capacity_factor=1.1),
                    nn.Dense(4, flatten=False))
            net.initialize()
            net(mx.nd.zeros((2, 6, 12)))
            rng = np.random.RandomState(0)
            x = rng.randn(16, 6, 12).astype(np.float32)
            y = rng.randint(0, 4, (16,)).astype(np.float32)

            def loss_fn(out, label):
                return gluon.loss.SoftmaxCrossEntropyLoss()(
                    out.mean(axis=1), label)

            tr = SPMDTrainer(
                net, loss_fn, "adam", {"learning_rate": 1e-2},
                mesh=make_mesh(dp=2, ep=4), rules=moe_sharding_rules(),
                stages=net.split_stages([2, 1]),
                pipeline={"schedule": "1f1b", "n_microbatches": 8})
            base = profiler.counters()
            losses = [float(tr.step(mx.nd.array(x), mx.nd.array(y))
                            .asnumpy()) for _ in range(6)]
            assert losses[-1] < losses[0]
            c = profiler.counters()
            assert c["recompile_steady_state"] == base[
                "recompile_steady_state"]
            assert c["moe_tokens_dropped"] > base["moe_tokens_dropped"]
            snap = profiler.metrics_snapshot()
            prov = [v for k, v in snap["providers"].items()
                    if k.startswith("pipeline")
                    and "moe_expert_load_max" in v]
            assert prov
            assert prov[-1]["moe_expert_load_max"] >= prov[-1][
                "moe_expert_load_min"] >= 0
            j = [i for i, p in enumerate(tr._params)
                 if "experts_mlp1_weight" in p.name][0]
            assert tr._param_arrays[j].sharding.spec[0] == "ep"
        finally:
            profiler.disarm_compile_guard()

    def test_moe_unpipelined_step_counts_drops(self):
        mx.random.seed(4)
        net = nn.HybridSequential()
        net.add(MoEBlock(units=8, hidden_size=16, num_experts=4, top_k=1,
                         capacity_factor=0.5),
                nn.Dense(4, flatten=False))
        net.initialize()
        net(mx.nd.zeros((2, 4, 8)))
        rng = np.random.RandomState(1)
        x = rng.randn(8, 4, 8).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.float32)

        def loss_fn(out, label):
            return gluon.loss.SoftmaxCrossEntropyLoss()(
                out.mean(axis=1), label)

        tr = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.05},
                         mesh=make_mesh())
        base = profiler.counters()["moe_tokens_dropped"]
        first = float(tr.step(mx.nd.array(x), mx.nd.array(y)).asnumpy())
        for _ in range(5):
            last = float(tr.step(mx.nd.array(x), mx.nd.array(y)).asnumpy())
        # capacity_factor 0.5 guarantees overflow: T·k·(1−cf) slots drop
        assert profiler.counters()["moe_tokens_dropped"] > base
        assert np.isfinite(last) and last < first + 1.0


@pytest.mark.slow
def test_pipeline_bench_smoke(monkeypatch, tmp_path):
    """The opperf harness in smoke mode: acceptance flags set, zero
    post-warmup recompiles, evidence JSON well-formed."""
    monkeypatch.delenv("MXNET_COMPILE_GUARD", raising=False)
    profiler.disarm_compile_guard()
    try:
        from benchmark.opperf import pipeline as bench

        line = bench.run(n_stages=4, layers_per_stage=1, n_microbatches=8,
                         batch=16, seq=4, units=16, hidden=32, heads=2,
                         iters=1, warmup=1, repeats=1)
        assert line["post_warmup_recompiles"] == 0
        assert line["bubble_acceptance"] is True
        assert line["bubble"]["1f1b"]["bubble_fraction"] < line[
            "bubble"]["gpipe"]["bubble_fraction"]
        assert line["bubble"]["1f1b"]["bubble_fraction"] <= (
            1.5 * line["analytic_bound"])
        assert set(line["steps_per_sec"]) == {"single", "gpipe", "1f1b"}
    finally:
        monkeypatch.delenv("MXNET_COMPILE_GUARD", raising=False)
        profiler.disarm_compile_guard()
