"""NDArray core semantics (parity model: [U:tests/python/unittest/test_ndarray.py])."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal

from common import with_seed


def test_creation():
    a = mx.nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert_almost_equal(a, np.zeros((3, 4)))
    b = mx.nd.ones((2,), dtype="int32")
    assert b.dtype == np.int32
    c = mx.nd.full((2, 2), 7.0)
    assert_almost_equal(c, np.full((2, 2), 7.0))
    d = mx.nd.array([[1, 2], [3, 4]])
    assert d.dtype == np.float32
    assert_almost_equal(d, np.array([[1, 2], [3, 4]], dtype="float32"))
    e = mx.nd.arange(0, 10, 2)
    assert_almost_equal(e, np.arange(0, 10, 2, dtype="float32"))


def test_basic_math():
    a = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.nd.array([[5.0, 6.0], [7.0, 8.0]])
    assert_almost_equal(a + b, np.array([[6, 8], [10, 12]]))
    assert_almost_equal(a - b, np.array([[-4, -4], [-4, -4]]))
    assert_almost_equal(a * b, np.array([[5, 12], [21, 32]]))
    assert_almost_equal(b / a, np.array([[5, 3], [7 / 3, 2]]))
    assert_almost_equal(a + 1, np.array([[2, 3], [4, 5]]))
    assert_almost_equal(2 * a, np.array([[2, 4], [6, 8]]))
    assert_almost_equal(1.0 / a, 1.0 / a.asnumpy())
    assert_almost_equal(a ** 2, a.asnumpy() ** 2)
    assert_almost_equal(-a, -a.asnumpy())


def test_inplace_and_setitem():
    a = mx.nd.zeros((3, 3))
    a[:] = 5.0
    assert_almost_equal(a, np.full((3, 3), 5.0))
    a += 1
    assert_almost_equal(a, np.full((3, 3), 6.0))
    a[0, 0] = 0.0
    assert a.asnumpy()[0, 0] == 0.0
    a[1] = np.array([9.0, 9.0, 9.0])
    assert_almost_equal(a.asnumpy()[1], np.full((3,), 9.0))
    v0 = a._version
    a *= 2
    assert a._version > v0


def test_indexing():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    a = mx.nd.array(x)
    assert_almost_equal(a[0], x[0])
    assert_almost_equal(a[1, 2], x[1, 2])
    assert_almost_equal(a[:, 1], x[:, 1])
    assert_almost_equal(a[0, 1:3], x[0, 1:3])
    assert_almost_equal(a[:, :, -1], x[:, :, -1])
    idx = mx.nd.array([1, 0], dtype="int32")
    assert_almost_equal(a[idx], x[[1, 0]])


def test_reshape_magic():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    a = mx.nd.array(x)
    assert a.reshape((-1,)).shape == (24,)
    assert a.reshape((0, -1)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-3, 4)).shape == (6, 4)
    assert a.reshape((0, -3)).shape == (2, 12)
    assert a.reshape((-4, 1, 2, -2)).shape == (1, 2, 3, 4)
    assert a.reshape((2, -4, -1, 3, 4)).shape == (2, 1, 3, 4)
    assert_almost_equal(a.reshape((6, 4)), x.reshape(6, 4))


def test_shape_ops():
    x = np.arange(24, dtype="float32").reshape(2, 3, 4)
    a = mx.nd.array(x)
    assert_almost_equal(a.T, x.T)
    assert_almost_equal(a.transpose((1, 0, 2)), x.transpose(1, 0, 2))
    assert_almost_equal(a.swapaxes(0, 2), x.swapaxes(0, 2))
    assert_almost_equal(a.expand_dims(1), np.expand_dims(x, 1))
    assert_almost_equal(a.flatten(), x.reshape(2, -1))
    assert_almost_equal(mx.nd.flip(a, axis=1), np.flip(x, 1))
    assert_almost_equal(a.tile((2, 1, 1)), np.tile(x, (2, 1, 1)))
    assert_almost_equal(a.repeat(2, axis=1), np.repeat(x, 2, 1))
    parts = a.split(2, axis=2)
    assert len(parts) == 2 and parts[0].shape == (2, 3, 2)
    assert_almost_equal(mx.nd.concat(parts[0], parts[1], dim=2), x)
    assert_almost_equal(mx.nd.stack(a, a, axis=0), np.stack([x, x]))


def test_reductions():
    x = np.random.uniform(-1, 1, (3, 4, 5)).astype("float32")
    a = mx.nd.array(x)
    assert_almost_equal(a.sum(), x.sum())
    assert_almost_equal(a.sum(axis=1), x.sum(1))
    assert_almost_equal(a.mean(axis=(0, 2)), x.mean((0, 2)))
    assert_almost_equal(a.max(axis=0), x.max(0))
    assert_almost_equal(a.min(axis=-1, keepdims=True), x.min(-1, keepdims=True))
    assert_almost_equal(mx.nd.sum(a, axis=1, exclude=True), x.sum((0, 2)))
    assert int(a.argmax(axis=1).asnumpy()[0, 0]) == int(x.argmax(1)[0, 0])
    assert_almost_equal(a.norm(), np.sqrt((x ** 2).sum()), rtol=1e-4, atol=1e-5)


def test_dot():
    a = np.random.uniform(size=(3, 4)).astype("float32")
    b = np.random.uniform(size=(4, 5)).astype("float32")
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)), a @ b, rtol=1e-4, atol=1e-5)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True), a @ b, rtol=1e-4, atol=1e-5
    )
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a.T), mx.nd.array(b), transpose_a=True), a @ b, rtol=1e-4, atol=1e-5
    )
    # batched
    x = np.random.uniform(size=(2, 3, 4)).astype("float32")
    y = np.random.uniform(size=(2, 4, 5)).astype("float32")
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(x), mx.nd.array(y)), x @ y, rtol=1e-4, atol=1e-5)


def test_comparison_and_where():
    a = mx.nd.array([1.0, 2.0, 3.0])
    b = mx.nd.array([3.0, 2.0, 1.0])
    assert_almost_equal(a > b, np.array([0.0, 0.0, 1.0]))
    assert_almost_equal(a == b, np.array([0.0, 1.0, 0.0]))
    assert_almost_equal(mx.nd.where(a > b, a, b), np.array([3.0, 2.0, 3.0]))
    assert_almost_equal(mx.nd.maximum(a, b), np.array([3.0, 2.0, 3.0]))


def test_astype_copy_context():
    a = mx.nd.array([1.5, 2.5])
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = a.copy()
    c[:] = 0
    assert_almost_equal(a, np.array([1.5, 2.5]))
    d = a.as_in_context(mx.cpu())
    assert d.context == mx.cpu()
    e = mx.nd.zeros((2,), ctx=mx.tpu())
    assert e.context.device_type == "tpu"
    # copyto
    f = mx.nd.zeros((2,))
    a.copyto(f)
    assert_almost_equal(f, np.array([1.5, 2.5]))


def test_scalar_conversion():
    a = mx.nd.array([3.5])
    assert a.asscalar() == 3.5
    assert float(a) == 3.5
    assert int(mx.nd.array([2])) == 2
    with pytest.raises(ValueError):
        mx.nd.zeros((2,)).asscalar()


def test_wait_and_version():
    a = mx.nd.ones((10, 10))
    b = (a * 2).wait_to_read()
    assert_almost_equal(b, np.full((10, 10), 2.0))
    mx.nd.waitall()


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays.params")
    d = {"w": mx.nd.array([1.0, 2.0]), "b": mx.nd.array([[3.0]])}
    mx.nd.save(fname, d)
    loaded = mx.nd.load(fname)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], d["w"])
    lst = [mx.nd.array([1.0]), mx.nd.array([2.0, 3.0])]
    mx.nd.save(fname, lst)
    loaded = mx.nd.load(fname)
    assert isinstance(loaded, list) and len(loaded) == 2
    assert_almost_equal(loaded[1], lst[1])


@with_seed()
def test_random_basic():
    a = mx.nd.random.uniform(0, 1, (100, 100))
    assert 0.4 < float(a.mean().asscalar()) < 0.6
    b = mx.nd.random.normal(0, 1, (100, 100))
    assert abs(float(b.mean().asscalar())) < 0.1
    mx.random.seed(42)
    x1 = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    x2 = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert np.array_equal(x1, x2)
    c = mx.nd.random.randint(0, 10, (50,))
    cn = c.asnumpy()
    assert cn.min() >= 0 and cn.max() < 10


def test_take_pick_onehot():
    x = np.arange(12, dtype="float32").reshape(3, 4)
    a = mx.nd.array(x)
    idx = mx.nd.array([2, 0], dtype="int32")
    assert_almost_equal(mx.nd.take(a, idx), x[[2, 0]])
    p = mx.nd.pick(a, mx.nd.array([1, 2, 3]), axis=1)
    assert_almost_equal(p, np.array([x[0, 1], x[1, 2], x[2, 3]]))
    oh = mx.nd.one_hot(mx.nd.array([0, 2], dtype="int32"), 3)
    assert_almost_equal(oh, np.eye(3, dtype="float32")[[0, 2]])


def test_topk_sort():
    x = np.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], dtype="float32")
    a = mx.nd.array(x)
    idx = mx.nd.topk(a, k=2)
    assert idx.shape == (2, 2)
    assert set(idx.asnumpy()[0].astype(int).tolist()) == {0, 2}
    vals = mx.nd.topk(a, k=1, ret_typ="value")
    assert_almost_equal(vals, np.array([[3.0], [5.0]]))
    assert_almost_equal(mx.nd.sort(a, axis=1), np.sort(x, 1))


def test_mx_np_namespace():
    a = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    b = mx.np.exp(a)
    assert isinstance(b, mx.NDArray)
    assert_almost_equal(b, np.exp(a.asnumpy()))
    c = mx.np.concatenate([a, a], axis=0)
    assert c.shape == (4, 2)
    assert float(mx.np.trace(a).asscalar()) == 5.0


def test_gamma_is_gamma_function():
    assert abs(float(mx.nd.gamma(mx.nd.array([3.0])).asscalar()) - 2.0) < 1e-4
    assert abs(float(mx.nd.gammaln(mx.nd.array([3.0])).asscalar()) - np.log(2.0)) < 1e-4


def test_method_tail_pad_round_floor_ceil_diag():
    """Round-5 NDArray method tail mirrors the reference's fluent set."""
    a = mx.nd.array(np.array([[1.5, -2.5], [0.4, 3.6]], np.float32))
    np.testing.assert_allclose(a.round().asnumpy(),
                               [[2.0, -3.0], [0.0, 4.0]])  # half away from 0
    np.testing.assert_allclose(a.floor().asnumpy(), np.floor(a.asnumpy()))
    np.testing.assert_allclose(a.ceil().asnumpy(), np.ceil(a.asnumpy()))
    p = a.pad(pad_width=(0, 0, 1, 1), constant_value=9.0)
    assert p.shape == (2, 4) and p.asnumpy()[0, 0] == 9.0
    d = mx.nd.array(np.array([1.0, 2.0])).diag()
    np.testing.assert_allclose(d.asnumpy(), np.diag([1.0, 2.0]))


def test_contrib_boolean_mask():
    """[U:src/operator/contrib/boolean_mask.cc]: eager data-dependent
    selection, differentiable through the kept rows; traced masks raise."""
    from incubator_mxnet_tpu import autograd

    a = mx.nd.array(np.arange(12.0).reshape(4, 3))
    m = mx.nd.array(np.array([1, 0, 1, 0], np.float32))
    out = mx.nd.contrib.boolean_mask(a, m)
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy()[[0, 2]])
    # axis=1
    mc = mx.nd.array(np.array([0, 1, 1], np.float32))
    out = mx.nd.contrib.boolean_mask(a, mc, axis=1)
    np.testing.assert_allclose(out.asnumpy(), a.asnumpy()[:, 1:])
    # gradient scatters into the kept rows only
    a.attach_grad()
    with autograd.record():
        y = mx.nd.contrib.boolean_mask(a, m).sum()
    y.backward()
    np.testing.assert_allclose(a.grad.asnumpy().sum(1), [3.0, 0.0, 3.0, 0.0])
    # traced mask -> actionable error
    import jax
    import pytest as _pytest
    with _pytest.raises(NotImplementedError, match="CONCRETE mask"):
        jax.jit(lambda d, mm: mx.nd.contrib.boolean_mask(
            mx.nd.NDArray(d), mx.nd.NDArray(mm))._data)(a._data, m._data)
    # explicit bool-dtype mask indexing on NDArray also works eagerly
    mask = (a > 6).astype("bool")
    assert a[mask].shape == (5,)


def test_sym_contrib_namespace():
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    x = S.var("x")
    y = S.contrib.AdaptiveAvgPooling2D(x, output_size=(2, 2))
    _, outs, _ = y.infer_shape(x=(1, 3, 8, 8))
    assert outs == [(1, 3, 2, 2)]
