"""Data-pipeline cursor resume (ISSUE 16 satellite).

A training run stopped at batch ``k`` and restored mid-epoch must yield
EXACTLY the remaining batch sequence — same shuffle permutation, no
duplicates, no omissions — at both layers:

* ``NDArrayIter.state_dict()/load_state_dict()`` — the cursor, carry,
  materialized shuffle order and RNG stream persist, including the
  sharded ``num_parts``/``part_index`` case (and a changed layout is
  refused);
* ``DataPipeline`` — the consumer cursor (epoch, delivered count)
  persists; a fresh pipeline over an identical source replays the
  source-side resets and drops already-delivered batches, stride-aligned.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.io import DataPipeline, NDArrayIter
from incubator_mxnet_tpu.parallel import make_mesh

N, FEAT, BS = 24, 3, 4
BATCHES = N // BS


def _data():
    x = np.arange(N * FEAT, dtype=np.float32).reshape(N, FEAT)
    y = np.arange(N, dtype=np.float32).reshape(N, 1)
    return x, y


def _iter(**kw):
    x, y = _data()
    kw.setdefault("batch_size", BS)
    kw.setdefault("shuffle", True)
    kw.setdefault("seed", 7)
    return NDArrayIter(x, y, **kw)


def _drain(it, batches):
    """Consume ``batches`` batches (resetting across epoch boundaries);
    returns the per-batch sample-index tuples."""
    out = []
    for _ in range(batches):
        if not it.iter_next():
            it.reset()
            it.iter_next()
        out.append(tuple(np.asarray(it.getindex()).tolist()))
    return out


class TestNDArrayIterResume:
    @pytest.mark.parametrize("k", [1, 3, BATCHES + 2])
    def test_mid_epoch_resume_yields_exact_remaining_sequence(self, k):
        total = 2 * BATCHES + 3   # crosses two shuffled epoch boundaries
        ref = _drain(_iter(), total)

        it1 = _iter()
        head = _drain(it1, k)
        state = it1.state_dict()
        it2 = _iter(seed=999)      # resume must overwrite the fresh RNG
        it2.load_state_dict(state)
        tail = _drain(it2, total - k)
        assert head + tail == ref

    def test_resume_has_no_dups_or_omissions_within_epoch(self):
        k = 2
        it1 = _iter()
        head = _drain(it1, k)
        it2 = _iter(seed=999)
        it2.load_state_dict(it1.state_dict())
        tail = _drain(it2, BATCHES - k)
        seen = [i for b in head + tail for i in b]
        assert sorted(seen) == list(range(N))   # the epoch: each sample once

    def test_sharded_multi_part_resume(self):
        """Each part resumes independently; the resumed union of an epoch
        is still an exact partition of the dataset."""
        total = BATCHES + 2
        k = 2
        epoch_union = []
        for part in (0, 1):
            kw = dict(num_parts=2, part_index=part)
            ref = _drain(_iter(**kw), total)
            it1 = _iter(**kw)
            head = _drain(it1, k)
            it2 = _iter(seed=999, **kw)
            it2.load_state_dict(it1.state_dict())
            tail = _drain(it2, total - k)
            assert head + tail == ref
            epoch_union += [i for b in (head + tail)[:BATCHES // 2]
                            for i in b]
        assert sorted(epoch_union) == list(range(N))

    def test_resume_refuses_changed_shard_layout(self):
        state = _iter(num_parts=2, part_index=0).state_dict()
        with pytest.raises(ValueError, match="sharding layout"):
            _iter(num_parts=2, part_index=1).load_state_dict(state)
        with pytest.raises(ValueError, match="sharding layout"):
            _iter().load_state_dict(state)

    def test_resume_refuses_foreign_state(self):
        with pytest.raises(ValueError):
            _iter().load_state_dict({"kind": "DataPipeline", "epoch": 0,
                                     "delivered": 1})


def _pipe_batches(pipe, n):
    """Pull n batches off a pipeline; returns flattened value arrays."""
    out = []
    it = iter(pipe)
    for _ in range(n):
        try:
            b = next(it)
        except StopIteration:
            it = iter(pipe)
            b = next(it)
        arr = b.data[0] if hasattr(b, "data") else b
        out.append(np.asarray(arr).ravel().copy())
    return out


class TestDataPipelineResume:
    @pytest.mark.parametrize("k", [2, BATCHES + 1])
    def test_consumer_cursor_resume_is_exact(self, k):
        """Stop after ``k`` delivered batches (mid-epoch or into epoch 1),
        rebuild over an identical source, restore: the remaining delivery
        is the uninterrupted run's, batch for batch."""
        total = 2 * BATCHES
        mesh = make_mesh()
        with DataPipeline(_iter(), mesh=mesh) as ref_pipe:
            ref = _pipe_batches(ref_pipe, total)

        with DataPipeline(_iter(), mesh=mesh) as p1:
            head = _pipe_batches(p1, k)
            state = p1.state_dict()
        assert state["kind"] == "DataPipeline"
        assert state["delivered"] == k % BATCHES or state["delivered"] == k

        p2 = DataPipeline(_iter(), mesh=mesh, autostart=False)
        p2.load_state_dict(state)
        with p2:
            tail = _pipe_batches(p2, total - k)
        got = head + tail
        assert len(got) == len(ref)
        for i, (a, b) in enumerate(zip(got, ref)):
            np.testing.assert_array_equal(a, b, err_msg=f"batch {i}")

    def test_strided_plain_iterable_resume_keeps_stride_phase(self):
        """num_parts striding over a plain iterable: the resumed reader
        drops already-delivered batches AFTER the stride, so the part
        keeps seeing its own residue class."""
        mesh = make_mesh()
        src = lambda: iter([np.full((2, 2), i, np.float32)  # noqa: E731
                            for i in range(12)])
        kw = dict(mesh=mesh, num_parts=2, part_index=1)
        with DataPipeline(src, **kw) as ref_pipe:
            ref = _pipe_batches(ref_pipe, 9)
        with DataPipeline(src, **kw) as p1:
            head = _pipe_batches(p1, 4)
            state = p1.state_dict()
        p2 = DataPipeline(src, autostart=False, **kw)
        p2.load_state_dict(state)
        with p2:
            tail = _pipe_batches(p2, 5)
        for i, (a, b) in enumerate(zip(head + tail, ref)):
            np.testing.assert_array_equal(a, b, err_msg=f"batch {i}")

    def test_load_state_dict_after_start_raises(self):
        with DataPipeline(_iter(), mesh=make_mesh()) as pipe:
            _pipe_batches(pipe, 1)
            with pytest.raises(RuntimeError, match="start"):
                pipe.load_state_dict({"kind": "DataPipeline", "epoch": 0,
                                      "delivered": 0})
