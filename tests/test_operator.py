"""Operator-surface tests in the reference's test_operator idiom.

Parity target: [U:tests/python/unittest/test_operator.py] — the reference's
~10k-line operator suite built on ``check_numeric_gradient`` +
``assert_almost_equal`` with rotating seeds.  This file covers the round-4
operator families: the full linalg ``la_op`` set, multisample samplers,
multi-tensor optimizer ops, the new optimizers, and the spatial/CV ops —
each against an independent numpy reference implementation, with
finite-difference gradient checks for every differentiable family.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.utils.test_utils import (
    assert_almost_equal,
    check_numeric_gradient,
)

from common import with_seed


def _nd(x, dtype="float32"):
    return mx.nd.array(np.asarray(x, dtype=dtype))


def _spd(n, batch=(), scale=4.0):
    """Random symmetric positive-definite matrices."""
    a = np.random.randn(*batch, n, n).astype(np.float32)
    m = np.einsum("...ij,...kj->...ik", a, a) + scale * np.eye(n, dtype=np.float32)
    return m


# ===========================================================================
# linalg la_op family
# ===========================================================================


class TestLinalgOps:
    @with_seed()
    def test_gemm(self):
        a = np.random.randn(2, 3, 4).astype(np.float32)
        b = np.random.randn(2, 4, 5).astype(np.float32)
        c = np.random.randn(2, 3, 5).astype(np.float32)
        out = mx.nd.linalg_gemm(_nd(a), _nd(b), _nd(c), alpha=2.0, beta=0.5)
        assert_almost_equal(out.asnumpy(), 2.0 * a @ b + 0.5 * c, rtol=1e-5, atol=1e-5)
        # transpose flags
        out = mx.nd.linalg_gemm(
            _nd(a.transpose(0, 2, 1)), _nd(b), _nd(c), transpose_a=True)
        assert_almost_equal(out.asnumpy(), a @ b + c, rtol=1e-5, atol=1e-5)
        out = mx.nd.linalg_gemm(
            _nd(a), _nd(b.transpose(0, 2, 1)), _nd(c), transpose_b=True)
        assert_almost_equal(out.asnumpy(), a @ b + c, rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_gemm_grad(self):
        a = np.random.randn(3, 2).astype(np.float32)
        b = np.random.randn(2, 3).astype(np.float32)
        c = np.random.randn(3, 3).astype(np.float32)
        check_numeric_gradient(
            lambda x, y, z: mx.nd.linalg_gemm(x, y, z, alpha=1.5, beta=2.0),
            [a, b, c])

    @with_seed()
    def test_potrf_potri(self):
        spd = _spd(4, batch=(3,))
        l = mx.nd.linalg_potrf(_nd(spd))
        # L @ Lᵀ reconstructs
        rec = np.einsum("...ij,...kj->...ik", l.asnumpy(), l.asnumpy())
        assert_almost_equal(rec, spd, rtol=1e-4, atol=1e-4)
        # lower-triangular
        assert np.allclose(np.triu(l.asnumpy(), k=1), 0, atol=1e-6)
        inv = mx.nd.linalg_potri(l)
        ident = np.einsum("...ij,...jk->...ik", inv.asnumpy(), spd)
        assert_almost_equal(ident, np.broadcast_to(np.eye(4, dtype=np.float32), (3, 4, 4)),
                            rtol=1e-3, atol=1e-3)

    @with_seed()
    def test_potrf_grad(self):
        spd = _spd(3)
        # symmetrize inside the fn so the finite-difference perturbation
        # stays in the SPD cone's tangent space
        check_numeric_gradient(
            lambda x: mx.nd.linalg_potrf(
                mx.nd.linalg_gemm2(x, x, transpose_b=True) +
                _nd(4 * np.eye(3))),
            [spd * 0.1], rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_trmm(self):
        a = np.tril(np.random.randn(4, 4).astype(np.float32))
        b = np.random.randn(4, 5).astype(np.float32)
        out = mx.nd.linalg_trmm(_nd(a), _nd(b), alpha=2.0)
        assert_almost_equal(out.asnumpy(), 2.0 * a @ b, rtol=1e-5, atol=1e-5)
        # rightside + transpose
        b2 = np.random.randn(5, 4).astype(np.float32)
        out = mx.nd.linalg_trmm(_nd(a), _nd(b2), rightside=True, transpose=True)
        assert_almost_equal(out.asnumpy(), b2 @ a.T, rtol=1e-5, atol=1e-5)
        # only the selected triangle participates
        full = np.random.randn(4, 4).astype(np.float32)
        out = mx.nd.linalg_trmm(_nd(full), _nd(b))
        assert_almost_equal(out.asnumpy(), np.tril(full) @ b, rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_trsm(self):
        a = np.tril(np.random.randn(4, 4).astype(np.float32))
        np.fill_diagonal(a, np.abs(np.diag(a)) + 2.0)
        x = np.random.randn(4, 3).astype(np.float32)
        b = a @ x
        out = mx.nd.linalg_trsm(_nd(a), _nd(b))
        assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)
        # alpha and rightside: X @ A = alpha*B
        xb = np.random.randn(3, 4).astype(np.float32)
        b2 = xb @ a
        out = mx.nd.linalg_trsm(_nd(a), _nd(b2), rightside=True)
        assert_almost_equal(out.asnumpy(), xb, rtol=1e-4, atol=1e-4)
        # transpose: Aᵀ X = B
        b3 = a.T @ x
        out = mx.nd.linalg_trsm(_nd(a), _nd(b3), transpose=True)
        assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_trsm_grad(self):
        a = np.tril(np.random.randn(3, 3).astype(np.float32))
        np.fill_diagonal(a, np.abs(np.diag(a)) + 2.0)
        b = np.random.randn(3, 2).astype(np.float32)
        check_numeric_gradient(
            lambda x, y: mx.nd.linalg_trsm(
                mx.nd.linalg_maketrian(mx.nd.linalg_extracttrian(x)) +
                _nd(2 * np.eye(3)), y),
            [a, b], rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_sumlogdiag(self):
        spd = _spd(4, batch=(2,))
        l = np.linalg.cholesky(spd)
        out = mx.nd.linalg_sumlogdiag(_nd(l))
        expect = np.log(np.diagonal(l, axis1=-2, axis2=-1)).sum(-1)
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-5, atol=1e-5)
        check_numeric_gradient(
            lambda x: mx.nd.linalg_sumlogdiag(x + _nd(3 * np.eye(3))),
            [np.abs(np.random.rand(3, 3).astype(np.float32))])

    @with_seed()
    def test_diag_trian_pack(self):
        a = np.random.randn(2, 4, 4).astype(np.float32)
        d = mx.nd.linalg_extractdiag(_nd(a))
        assert_almost_equal(d.asnumpy(), np.diagonal(a, axis1=-2, axis2=-1),
                            rtol=1e-6, atol=1e-6)
        d1 = mx.nd.linalg_extractdiag(_nd(a), offset=1)
        assert_almost_equal(d1.asnumpy(), np.diagonal(a, offset=1, axis1=-2, axis2=-1),
                            rtol=1e-6, atol=1e-6)
        v = np.random.randn(2, 4).astype(np.float32)
        m = mx.nd.linalg_makediag(_nd(v)).asnumpy()
        for b in range(2):
            assert_almost_equal(m[b], np.diag(v[b]), rtol=1e-6, atol=1e-6)
        m1 = mx.nd.linalg_makediag(_nd(v), offset=1).asnumpy()
        for b in range(2):
            assert_almost_equal(m1[b], np.diag(v[b], k=1), rtol=1e-6, atol=1e-6)
        # triangle pack/unpack roundtrip
        packed = mx.nd.linalg_extracttrian(_nd(a))
        assert packed.shape == (2, 10)
        unpacked = mx.nd.linalg_maketrian(packed).asnumpy()
        assert_almost_equal(unpacked, np.tril(a), rtol=1e-6, atol=1e-6)
        packed_u = mx.nd.linalg_extracttrian(_nd(a), lower=False, offset=1)
        unpacked_u = mx.nd.linalg_maketrian(packed_u, lower=False, offset=1).asnumpy()
        assert_almost_equal(unpacked_u, np.triu(a, k=1), rtol=1e-6, atol=1e-6)

    @with_seed()
    def test_gelqf(self):
        a = np.random.randn(3, 4).astype(np.float32)
        q, l = mx.nd.linalg_gelqf(_nd(a))
        # A = L Q with orthonormal rows of Q
        assert_almost_equal(l.asnumpy() @ q.asnumpy(), a, rtol=1e-4, atol=1e-4)
        assert_almost_equal(q.asnumpy() @ q.asnumpy().T, np.eye(3, dtype=np.float32),
                            rtol=1e-4, atol=1e-4)
        # L lower-triangular with non-negative diagonal
        assert np.allclose(np.triu(l.asnumpy(), k=1), 0, atol=1e-5)
        assert (np.diag(l.asnumpy()) >= -1e-6).all()

    @with_seed()
    def test_syevd(self):
        spd = _spd(4)
        u, lam = mx.nd.linalg_syevd(_nd(spd))
        u, lam = u.asnumpy(), lam.asnumpy()
        # A = Uᵀ diag(L) U (rows are eigenvectors)
        rec = u.T @ np.diag(lam) @ u
        assert_almost_equal(rec, spd, rtol=1e-4, atol=1e-4)
        assert (np.diff(lam) >= -1e-5).all()  # ascending

    @with_seed()
    def test_inverse_det(self):
        a = _spd(3, batch=(2,))
        inv = mx.nd.linalg_inverse(_nd(a))
        ident = np.einsum("...ij,...jk->...ik", inv.asnumpy(), a)
        assert_almost_equal(ident, np.broadcast_to(np.eye(3, dtype=np.float32), (2, 3, 3)),
                            rtol=1e-3, atol=1e-3)
        det = mx.nd.linalg_det(_nd(a))
        assert_almost_equal(det.asnumpy(), np.linalg.det(a), rtol=1e-3, atol=1e-3)
        sign, logabs = mx.nd.linalg_slogdet(_nd(a))
        s_np, l_np = np.linalg.slogdet(a)
        assert_almost_equal(sign.asnumpy(), s_np.astype(np.float32), rtol=1e-5, atol=1e-5)
        assert_almost_equal(logabs.asnumpy(), l_np.astype(np.float32), rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_det_grad(self):
        a = _spd(3) * 0.5
        check_numeric_gradient(lambda x: mx.nd.linalg_det(x), [a], rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_khatri_rao(self):
        a = np.random.randn(3, 4).astype(np.float32)
        b = np.random.randn(5, 4).astype(np.float32)
        out = mx.nd.khatri_rao(_nd(a), _nd(b))
        expect = np.stack([np.kron(a[:, i], b[:, i]) for i in range(4)], axis=1)
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_moments(self):
        x = np.random.randn(3, 4, 5).astype(np.float32)
        mean, var = mx.nd.moments(_nd(x), axes=(0, 2))
        assert_almost_equal(mean.asnumpy(), x.mean(axis=(0, 2)), rtol=1e-5, atol=1e-5)
        assert_almost_equal(var.asnumpy(), x.var(axis=(0, 2)), rtol=1e-4, atol=1e-4)
        mean_k, var_k = mx.nd.moments(_nd(x), axes=(1,), keepdims=True)
        assert mean_k.shape == (3, 1, 5)
        assert var_k.shape == (3, 1, 5)


# ===========================================================================
# random / multisample samplers
# ===========================================================================


class TestRandomOps:
    @with_seed()
    def test_random_uniform_moments(self):
        x = mx.nd._random_uniform(low=2.0, high=6.0, shape=(100000,)).asnumpy()
        assert 3.9 < x.mean() < 4.1
        assert x.min() >= 2.0 and x.max() < 6.0

    @with_seed()
    def test_random_normal_moments(self):
        x = mx.nd._random_normal(loc=1.5, scale=2.0, shape=(100000,)).asnumpy()
        assert abs(x.mean() - 1.5) < 0.05
        assert abs(x.std() - 2.0) < 0.05

    @with_seed()
    def test_random_gamma_moments(self):
        x = mx.nd._random_gamma(alpha=3.0, beta=2.0, shape=(100000,)).asnumpy()
        assert abs(x.mean() - 6.0) < 0.15  # mean = alpha*beta
        assert abs(x.var() - 12.0) < 1.0   # var = alpha*beta^2

    @with_seed()
    def test_random_exponential_poisson(self):
        x = mx.nd._random_exponential(lam=4.0, shape=(100000,)).asnumpy()
        assert abs(x.mean() - 0.25) < 0.01
        p = mx.nd._random_poisson(lam=3.0, shape=(100000,)).asnumpy()
        assert abs(p.mean() - 3.0) < 0.1
        assert abs(p.var() - 3.0) < 0.2

    @with_seed()
    def test_random_negative_binomial(self):
        k, prob = 4, 0.4
        x = mx.nd._random_negative_binomial(k=k, p=prob, shape=(100000,)).asnumpy()
        mean = k * (1 - prob) / prob
        var = mean / prob
        assert abs(x.mean() - mean) < 0.2
        assert abs(x.var() - var) < 1.5
        g = mx.nd._random_generalized_negative_binomial(
            mu=2.0, alpha=0.5, shape=(100000,)).asnumpy()
        # mean mu, var mu + alpha*mu^2
        assert abs(g.mean() - 2.0) < 0.15
        assert abs(g.var() - 4.0) < 0.5

    @with_seed()
    def test_random_randint(self):
        x = mx.nd._random_randint(low=-3, high=7, shape=(50000,)).asnumpy()
        assert x.min() == -3 and x.max() == 6
        assert str(x.dtype).startswith("int")

    @with_seed()
    def test_multisample_shapes_and_rows(self):
        mu = _nd([0.0, 10.0, -10.0])
        sigma = _nd([1.0, 1.0, 1.0])
        s = mx.nd._sample_normal(mu, sigma, shape=5000)
        assert s.shape == (3, 5000)
        m = s.asnumpy().mean(axis=1)
        assert abs(m[0]) < 0.15 and abs(m[1] - 10) < 0.15 and abs(m[2] + 10) < 0.15

    @with_seed()
    def test_multisample_uniform(self):
        low = _nd([[0.0], [5.0]])
        high = _nd([[1.0], [15.0]])
        s = mx.nd._sample_uniform(low, high, shape=(4000,))
        assert s.shape == (2, 1, 4000)
        sn = s.asnumpy()
        assert 0.45 < sn[0, 0].mean() < 0.55
        assert 9.5 < sn[1, 0].mean() < 10.5

    @with_seed()
    def test_multisample_gamma_exponential(self):
        alpha = _nd([2.0, 8.0])
        beta = _nd([3.0, 0.5])
        g = mx.nd._sample_gamma(alpha, beta, shape=(20000,)).asnumpy()
        assert abs(g[0].mean() - 6.0) < 0.3
        assert abs(g[1].mean() - 4.0) < 0.2
        lam = _nd([1.0, 10.0])
        e = mx.nd._sample_exponential(lam, shape=(20000,)).asnumpy()
        assert abs(e[0].mean() - 1.0) < 0.05
        assert abs(e[1].mean() - 0.1) < 0.01

    @with_seed()
    def test_multisample_poisson_nb(self):
        lam = _nd([1.0, 6.0])
        p = mx.nd._sample_poisson(lam, shape=(20000,)).asnumpy()
        assert abs(p[0].mean() - 1.0) < 0.1
        assert abs(p[1].mean() - 6.0) < 0.2
        k = _nd([2.0, 5.0])
        prob = _nd([0.5, 0.25])
        nb = mx.nd._sample_negative_binomial(k, prob, shape=(20000,)).asnumpy()
        assert abs(nb[0].mean() - 2.0) < 0.2      # k(1-p)/p = 2
        assert abs(nb[1].mean() - 15.0) < 0.8     # 5*0.75/0.25 = 15
        mu = _nd([3.0, 3.0])
        al = _nd([0.0, 1.0])
        gnb = mx.nd._sample_generalized_negative_binomial(mu, al, shape=(20000,)).asnumpy()
        assert abs(gnb[0].mean() - 3.0) < 0.15
        assert abs(gnb[0].var() - 3.0) < 0.4       # alpha=0 → Poisson
        assert abs(gnb[1].var() - 12.0) < 2.0      # mu + alpha*mu² = 12

    @with_seed()
    def test_sample_multinomial(self):
        probs = _nd([[0.1, 0.9], [0.8, 0.2]])
        s = mx.nd._sample_multinomial(probs, shape=(8000,))
        assert s.shape == (2, 8000)
        sn = s.asnumpy()
        assert abs(sn[0].mean() - 0.9) < 0.03      # P(idx=1)=0.9
        assert abs(sn[1].mean() - 0.2) < 0.03
        s2, logp = mx.nd._sample_multinomial(probs, shape=(10,), get_prob=True)
        sn2, lp = s2.asnumpy(), logp.asnumpy()
        expect = np.where(sn2 == 1, np.log([0.9, 0.2])[:, None], np.log([0.1, 0.8])[:, None])
        assert_almost_equal(lp, expect.astype(np.float32), rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_like_variants_and_shuffle(self):
        ref = mx.nd.zeros((3, 7))
        u = mx.nd._random_uniform_like(ref)
        assert u.shape == (3, 7)
        n = mx.nd._random_normal_like(ref, loc=5.0, scale=0.1)
        assert abs(n.asnumpy().mean() - 5.0) < 0.2
        x = mx.nd.array(np.arange(1000, dtype=np.float32))
        sh = mx.nd.shuffle(x).asnumpy()
        assert not np.array_equal(sh, x.asnumpy())
        assert_almost_equal(np.sort(sh), x.asnumpy(), rtol=0, atol=0)

    @with_seed()
    def test_seed_determinism(self):
        mx.random.seed(42)
        a = mx.nd._random_normal(shape=(100,)).asnumpy()
        mx.random.seed(42)
        b = mx.nd._random_normal(shape=(100,)).asnumpy()
        assert np.array_equal(a, b)


# ===========================================================================
# multi-tensor optimizer ops
# ===========================================================================


class TestMultiTensorOps:
    @with_seed()
    def test_multi_sgd_matches_single(self):
        ws = [np.random.randn(4, 3).astype(np.float32) for _ in range(3)]
        gs = [np.random.randn(4, 3).astype(np.float32) for _ in range(3)]
        lrs, wds = [0.1, 0.2, 0.3], [0.0, 0.01, 0.1]
        outs = mx.nd.multi_sgd_update(
            [_nd(w)._data for w in ws], [_nd(g)._data for g in gs], lrs, wds)
        for w, g, lr, wd, o in zip(ws, gs, lrs, wds, outs):
            expect = w - lr * (g + wd * w)
            assert_almost_equal(np.asarray(o), expect, rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_multi_sgd_mom_matches_single(self):
        ws = [np.random.randn(5).astype(np.float32) for _ in range(2)]
        gs = [np.random.randn(5).astype(np.float32) for _ in range(2)]
        ms = [np.random.randn(5).astype(np.float32) for _ in range(2)]
        lrs, wds, mom = [0.1, 0.05], [0.0, 0.01], 0.9
        new_ws, new_ms = mx.nd.multi_sgd_mom_update(
            [_nd(w)._data for w in ws], [_nd(g)._data for g in gs],
            [_nd(m)._data for m in ms], lrs, wds, momentum=mom)
        for w, g, m, lr, wd, nw, nm in zip(ws, gs, ms, lrs, wds, new_ws, new_ms):
            em = mom * m - lr * (g + wd * w)
            assert_almost_equal(np.asarray(nm), em, rtol=1e-5, atol=1e-5)
            assert_almost_equal(np.asarray(nw), w + em, rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_multi_mp_sgd(self):
        import jax.numpy as jnp

        ws32 = [np.random.randn(6).astype(np.float32) for _ in range(2)]
        ws16 = [jnp.asarray(w).astype(jnp.bfloat16) for w in ws32]
        gs = [np.random.randn(6).astype(np.float32) for _ in range(2)]
        lrs, wds = [0.1, 0.2], [0.0, 0.01]
        new_w, new_w32 = mx.nd.multi_mp_sgd_update(
            ws16, [_nd(g)._data for g in gs], [_nd(w)._data for w in ws32],
            lrs, wds)
        for w32, g, lr, wd, nw, nw32 in zip(ws32, gs, lrs, wds, new_w, new_w32):
            expect = w32 - lr * (g + wd * w32)
            assert_almost_equal(np.asarray(nw32), expect, rtol=1e-5, atol=1e-5)
            assert str(np.asarray(nw).dtype) == "bfloat16" or nw.dtype == jnp.bfloat16

    @with_seed()
    def test_multi_sum_sq_and_lars(self):
        arrs = [np.random.randn(4, 4).astype(np.float32) for _ in range(3)]
        ss = mx.nd.multi_sum_sq(*[_nd(a)._data for a in arrs])
        expect = np.array([(a ** 2).sum() for a in arrs], dtype=np.float32)
        assert_almost_equal(np.asarray(ss), expect, rtol=1e-4, atol=1e-4)
        lrs = np.array([0.1, 0.1, 0.1], np.float32)
        wds = np.array([0.0, 0.0, 0.0], np.float32)
        w_ss = np.array([4.0, 1.0, 0.0], np.float32)
        g_ss = np.array([1.0, 4.0, 1.0], np.float32)
        out = np.asarray(mx.nd.multi_lars(
            _nd(lrs)._data, _nd(w_ss)._data, _nd(g_ss)._data, _nd(wds)._data,
            eta=1.0, eps=0.0))
        assert_almost_equal(out, np.array([0.2, 0.05, 0.1], np.float32),
                            rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_preloaded_multi_sgd_matches_multi(self):
        """preloaded_* variants take lr/wd as device arrays; results must
        match the scalar-list forms exactly."""
        import jax.numpy as jnp

        ws = [np.random.randn(4, 3).astype(np.float32) for _ in range(3)]
        gs = [np.random.randn(4, 3).astype(np.float32) for _ in range(3)]
        ms = [np.random.randn(4, 3).astype(np.float32) for _ in range(3)]
        lrs, wds = [0.1, 0.2, 0.3], [0.0, 0.01, 0.1]
        lrs_a, wds_a = jnp.asarray(lrs), jnp.asarray(wds)
        outs = mx.nd.preloaded_multi_sgd_update(
            [_nd(w)._data for w in ws], [_nd(g)._data for g in gs], lrs_a, wds_a)
        for w, g, lr, wd, o in zip(ws, gs, lrs, wds, outs):
            expect = w - lr * (g + wd * w)
            assert_almost_equal(np.asarray(o), expect, rtol=1e-5, atol=1e-5)
        new_ws, new_ms = mx.nd.preloaded_multi_sgd_mom_update(
            [_nd(w)._data for w in ws], [_nd(g)._data for g in gs],
            [_nd(m)._data for m in ms], lrs_a, wds_a, momentum=0.9)
        ref_ws, ref_ms = mx.nd.multi_sgd_mom_update(
            [_nd(w)._data for w in ws], [_nd(g)._data for g in gs],
            [_nd(m)._data for m in ms], lrs, wds, momentum=0.9)
        for a, b in zip(list(new_ws) + list(new_ms), list(ref_ws) + list(ref_ms)):
            assert_almost_equal(np.asarray(a), np.asarray(b), rtol=0, atol=0)

    @with_seed()
    def test_preloaded_multi_mp_sgd(self):
        import jax.numpy as jnp

        ws32 = [np.random.randn(6).astype(np.float32) for _ in range(2)]
        ws16 = [jnp.asarray(w).astype(jnp.bfloat16) for w in ws32]
        gs = [np.random.randn(6).astype(np.float32) for _ in range(2)]
        lrs, wds = jnp.asarray([0.1, 0.2]), jnp.asarray([0.0, 0.01])
        new_w, new_w32 = mx.nd.preloaded_multi_mp_sgd_update(
            ws16, [_nd(g)._data for g in gs], [_nd(w)._data for w in ws32],
            lrs, wds)
        for w32, g, lr, wd, nw32 in zip(ws32, gs, [0.1, 0.2], [0.0, 0.01], new_w32):
            expect = w32 - lr * (g + wd * w32)
            assert_almost_equal(np.asarray(nw32), expect, rtol=1e-5, atol=1e-5)
        moms = [np.zeros(6, np.float32) for _ in range(2)]
        out = mx.nd.preloaded_multi_mp_sgd_mom_update(
            ws16, [_nd(g)._data for g in gs], [_nd(m)._data for m in moms],
            [_nd(w)._data for w in ws32], lrs, wds, momentum=0.9)
        assert len(out) == 3 and len(out[0]) == 2

    @with_seed()
    def test_all_finite(self):
        good = _nd(np.ones((3, 3)))._data
        bad = _nd(np.array([1.0, np.inf]))._data
        nan = _nd(np.array([np.nan]))._data
        assert bool(np.asarray(mx.nd.all_finite(good)))
        assert not bool(np.asarray(mx.nd.all_finite(good, bad)))
        assert not bool(np.asarray(mx.nd.multi_all_finite(good, nan)))


# ===========================================================================
# new optimizers
# ===========================================================================


def _run_optimizer(name, steps=5, shape=(8, 4), **kwargs):
    """Drive an optimizer through the public Updater path; returns the
    final weight and the grad sequence used."""
    from incubator_mxnet_tpu import optimizer as opt_mod

    opt = opt_mod.create(name, **kwargs)
    updater = opt_mod.get_updater(opt)
    w = _nd(np.random.randn(*shape).astype(np.float32))
    grads = [np.random.randn(*shape).astype(np.float32) for _ in range(steps)]
    for g in grads:
        updater(0, _nd(g), w)
    return w.asnumpy(), grads


class TestNewOptimizers:
    @with_seed()
    def test_nadam_matches_reference_recurrence(self):
        lr, b1, b2, eps, sd = 0.01, 0.9, 0.999, 1e-8, 0.004
        np.random.seed(7)
        w0 = np.random.randn(6).astype(np.float64)
        from incubator_mxnet_tpu import optimizer as opt_mod

        opt = opt_mod.create("nadam", learning_rate=lr, beta1=b1, beta2=b2,
                             epsilon=eps, schedule_decay=sd)
        updater = opt_mod.get_updater(opt)
        w = _nd(w0.astype(np.float32))
        grads = [np.random.randn(6).astype(np.float64) for _ in range(6)]
        for g in grads:
            updater(0, _nd(g.astype(np.float32)), w)
        # numpy replication of the reference Nadam recurrence
        wn = w0.copy()
        m = np.zeros(6)
        v = np.zeros(6)
        m_sched = 1.0
        for t, g in enumerate(grads, start=1):
            m_t = b1 * (1.0 - 0.5 * 0.96 ** (t * sd))
            m_t1 = b1 * (1.0 - 0.5 * 0.96 ** ((t + 1) * sd))
            m_sched = m_sched * m_t
            sched_next = m_sched * m_t1
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            g_hat = g / (1 - m_sched)
            m_hat = m / (1 - sched_next)
            v_hat = v / (1 - b2 ** t)
            wn -= lr * ((1 - m_t) * g_hat + m_t1 * m_hat) / (np.sqrt(v_hat) + eps)
        assert_almost_equal(w.asnumpy(), wn.astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_ftml_matches_reference_recurrence(self):
        lr, b1, b2, eps = 0.0025, 0.6, 0.999, 1e-8
        np.random.seed(11)
        w0 = np.random.randn(5).astype(np.float64)
        from incubator_mxnet_tpu import optimizer as opt_mod

        opt = opt_mod.create("ftml", learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps)
        updater = opt_mod.get_updater(opt)
        w = _nd(w0.astype(np.float32))
        grads = [np.random.randn(5).astype(np.float64) for _ in range(5)]
        for g in grads:
            updater(0, _nd(g.astype(np.float32)), w)
        wn = w0.copy()
        d = np.zeros(5)
        v = np.zeros(5)
        z = np.zeros(5)
        for t, g in enumerate(grads, start=1):
            v = b2 * v + (1 - b2) * g * g
            d_t = (1 - b1 ** t) / lr * (np.sqrt(v / (1 - b2 ** t)) + eps)
            sigma = d_t - b1 * d
            z = b1 * z + (1 - b1) * g - sigma * wn
            wn = -z / d_t
            d = d_t
        assert_almost_equal(w.asnumpy(), wn.astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_adamax_matches_reference_recurrence(self):
        lr, b1, b2 = 0.002, 0.9, 0.999
        np.random.seed(13)
        w0 = np.random.randn(5).astype(np.float64)
        from incubator_mxnet_tpu import optimizer as opt_mod

        opt = opt_mod.create("adamax", learning_rate=lr, beta1=b1, beta2=b2)
        updater = opt_mod.get_updater(opt)
        w = _nd(w0.astype(np.float32))
        grads = [np.random.randn(5).astype(np.float64) for _ in range(5)]
        for g in grads:
            updater(0, _nd(g.astype(np.float32)), w)
        wn = w0.copy()
        m = np.zeros(5)
        u = np.zeros(5)
        for t, g in enumerate(grads, start=1):
            lr_t = lr / (1 - b1 ** t)
            m = b1 * m + (1 - b1) * g
            u = np.maximum(b2 * u, np.abs(g))
            wn -= lr_t * m / (u + 1e-8)
        assert_almost_equal(w.asnumpy(), wn.astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_dcasgd_matches_reference_recurrence(self):
        lr, mom, lam, wd = 0.05, 0.9, 0.04, 0.01
        np.random.seed(17)
        w0 = np.random.randn(4).astype(np.float64)
        from incubator_mxnet_tpu import optimizer as opt_mod

        opt = opt_mod.create("dcasgd", learning_rate=lr, momentum=mom,
                             lamda=lam, wd=wd)
        updater = opt_mod.get_updater(opt)
        w = _nd(w0.astype(np.float32))
        grads = [np.random.randn(4).astype(np.float64) for _ in range(4)]
        for g in grads:
            updater(0, _nd(g.astype(np.float32)), w)
        wn = w0.copy()
        mv = np.zeros(4)
        prev = w0.copy()
        for g in grads:
            mv = mom * mv - lr * (g + wd * wn + lam * g * g * (wn - prev))
            wn = wn + mv
            prev = wn.copy()
        assert_almost_equal(w.asnumpy(), wn.astype(np.float32), rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_sgld_statistics(self):
        # zero gradient: updates are pure N(0, lr) noise
        from incubator_mxnet_tpu import optimizer as opt_mod

        lr = 0.01
        opt = opt_mod.create("sgld", learning_rate=lr, wd=0.0)
        updater = opt_mod.get_updater(opt)
        w = _nd(np.zeros(200000, np.float32))
        updater(0, _nd(np.zeros(200000, np.float32)), w)
        x = w.asnumpy()
        assert abs(x.mean()) < 2e-3
        assert abs(x.std() - np.sqrt(lr)) < 2e-3

    @with_seed()
    def test_lbsgd_warmup(self):
        from incubator_mxnet_tpu import optimizer as opt_mod

        # batch_scale=1: the multiplier is exactly 1.0 — lr never drops
        # below base (the reference _get_lbmult contract)
        opt = opt_mod.create("lbsgd", learning_rate=0.5, momentum=0.0,
                             warmup_strategy="linear", warmup_epochs=1,
                             updates_per_epoch=10)
        updater = opt_mod.get_updater(opt)
        w = _nd(np.ones(4, np.float32))
        g = np.ones(4, np.float32)
        updater(0, _nd(g), w)  # full base lr from step 1
        assert_almost_equal(w.asnumpy(), np.full(4, 1.0 - 0.5, np.float32),
                            rtol=1e-5, atol=1e-6)

        # batch_scale=2: linear ramp 1 → 2 over the warmup window
        opt = opt_mod.create("lbsgd", learning_rate=1.0, momentum=0.0,
                             warmup_strategy="linear", warmup_epochs=1,
                             updates_per_epoch=10, batch_scale=2)
        updater = opt_mod.get_updater(opt)
        w = _nd(np.ones(4, np.float32))
        updater(0, _nd(g), w)  # t=1 → scale 1 + 0.1 = 1.1
        assert_almost_equal(w.asnumpy(), np.full(4, 1.0 - 1.1, np.float32),
                            rtol=1e-5, atol=1e-6)
        updater(0, _nd(g), w)  # t=2 → scale 1.2
        assert_almost_equal(w.asnumpy(), np.full(4, -0.1 - 1.2, np.float32),
                            rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_lbsgd_lars_ratio(self):
        from incubator_mxnet_tpu import optimizer as opt_mod

        opt = opt_mod.create("lbsgd", learning_rate=1.0, warmup_strategy="lars",
                             warmup_epochs=1, updates_per_epoch=1)
        updater = opt_mod.get_updater(opt)
        w0 = np.full(4, 2.0, np.float32)
        g = np.full(4, 1.0, np.float32)
        w = _nd(w0.copy())
        updater(0, _nd(g), w)
        # trust ratio = eta*|w|/|g| = 0.001*2 = 0.002 (wd=0); step = ratio*g
        assert_almost_equal(w.asnumpy(), w0 - 0.002 * g, rtol=1e-4, atol=1e-6)

    @with_seed()
    def test_all_new_optimizers_reduce_quadratic(self):
        # every optimizer should reduce ||w||² on the gradient of 0.5||w||²
        from incubator_mxnet_tpu import optimizer as opt_mod

        for name in ["nadam", "ftml", "adamax", "dcasgd", "lbsgd", "sgld"]:
            opt = opt_mod.create(name, learning_rate=0.01)
            updater = opt_mod.get_updater(opt)
            w = _nd(np.full(16, 5.0, np.float32))
            for _ in range(50):
                updater(0, _nd(w.asnumpy()), w)
            final = float((w.asnumpy() ** 2).mean())
            assert final < 25.0, f"{name} failed to descend: {final}"


# ===========================================================================
# spatial / CV ops
# ===========================================================================


class TestSpatialOps:
    @with_seed()
    def test_depth_space_roundtrip(self):
        x = np.random.randn(2, 12, 4, 6).astype(np.float32)
        d = mx.nd.depth_to_space(_nd(x), 2)
        assert d.shape == (2, 3, 8, 12)
        back = mx.nd.space_to_depth(d, 2)
        assert_almost_equal(back.asnumpy(), x, rtol=1e-6, atol=1e-6)

    @with_seed()
    def test_depth_to_space_values(self):
        # known DCR layout: channel c maps to offset (c//(C'*bs)? ) — check
        # against the straightforward numpy reshape formulation
        b, c, h, w, bs = 1, 8, 2, 2, 2
        x = np.arange(b * c * h * w, dtype=np.float32).reshape(b, c, h, w)
        out = mx.nd.depth_to_space(_nd(x), bs).asnumpy()
        ref = x.reshape(b, bs, bs, c // bs ** 2, h, w)
        ref = ref.transpose(0, 3, 4, 1, 5, 2).reshape(b, c // bs ** 2, h * bs, w * bs)
        assert_almost_equal(out, ref, rtol=0, atol=0)

    @with_seed()
    def test_unravel_ravel_roundtrip(self):
        shape = (3, 4, 5)
        flat = np.array([0, 7, 23, 59], dtype=np.int64)
        coords = mx.nd.unravel_index(_nd(flat, dtype="int32"), shape)
        assert coords.shape == (3, 4)
        expect = np.stack(np.unravel_index(flat, shape))
        assert_almost_equal(coords.asnumpy().astype(np.int64), expect, rtol=0, atol=0)
        back = mx.nd.ravel_multi_index(coords, shape)
        assert_almost_equal(back.asnumpy().astype(np.int64), flat, rtol=0, atol=0)

    @with_seed()
    def test_index_array_and_copy(self):
        x = mx.nd.zeros((2, 3))
        idx = mx.nd.index_array(x)
        assert idx.shape == (2, 3, 2)
        assert idx.asnumpy()[1, 2].tolist() == [1, 2]
        idx0 = mx.nd.index_array(x, axes=(1,))
        assert idx0.asnumpy()[0].squeeze().tolist() == [0, 1, 2]
        old = mx.nd.zeros((5, 3))
        new = _nd(np.ones((2, 3)) * 7)
        out = mx.nd.index_copy(old, _nd([1, 3], dtype="int32"), new)
        on = out.asnumpy()
        assert (on[[1, 3]] == 7).all() and on[[0, 2, 4]].sum() == 0

    @with_seed()
    def test_arange_like(self):
        x = mx.nd.zeros((2, 3, 4))
        full = mx.nd.arange_like(x)
        assert full.shape == (2, 3, 4)
        assert full.asnumpy().ravel()[-1] == 23
        ax = mx.nd.arange_like(x, axis=1, start=5, step=2)
        assert_almost_equal(ax.asnumpy(), np.array([5, 7, 9], np.float32), rtol=0, atol=0)
        # repeat: output size stays fixed by data; size//repeat distinct values
        rep = mx.nd.arange_like(mx.nd.zeros((2, 3)), repeat=2)
        assert rep.shape == (2, 3)
        assert_almost_equal(rep.asnumpy().ravel(),
                            np.array([0, 0, 1, 1, 2, 2], np.float32),
                            rtol=0, atol=0)
        rax = mx.nd.arange_like(mx.nd.zeros((2, 4)), axis=1, repeat=2)
        assert_almost_equal(rax.asnumpy(), np.array([0, 0, 1, 1], np.float32),
                            rtol=0, atol=0)

    @with_seed()
    def test_masked_softmax(self):
        x = np.random.randn(3, 6).astype(np.float32)
        full = np.ones((3, 6), dtype=bool)
        out = mx.nd.masked_softmax(_nd(x), mx.nd.array(full.astype(np.float32))._data > 0)
        expect = np.exp(x - x.max(-1, keepdims=True))
        expect /= expect.sum(-1, keepdims=True)
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-4, atol=1e-5)
        mask = full.copy()
        mask[:, 3:] = False
        import jax.numpy as jnp

        out = mx.nd.masked_softmax(_nd(x), jnp.asarray(mask)).asnumpy()
        assert np.allclose(out[:, 3:], 0)
        assert_almost_equal(out[:, :3].sum(-1), np.ones(3, np.float32), rtol=1e-5, atol=1e-5)
        lout = mx.nd.masked_log_softmax(_nd(x), jnp.asarray(mask)).asnumpy()
        assert_almost_equal(np.exp(lout[:, :3]), out[:, :3], rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_lrn(self):
        x = np.random.rand(2, 7, 3, 3).astype(np.float32)
        nsize, alpha, beta, k = 5, 1e-4, 0.75, 2.0
        out = mx.nd.LRN(_nd(x), nsize=nsize, alpha=alpha, beta=beta, knorm=k).asnumpy()
        half = nsize // 2
        expect = np.empty_like(x)
        for c in range(7):
            lo, hi = max(0, c - half), min(7, c + half + 1)
            ssum = (x[:, lo:hi] ** 2).sum(axis=1)
            expect[:, c] = x[:, c] / (k + alpha / nsize * ssum) ** beta
        assert_almost_equal(out, expect, rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_bilinear_sampler_identity(self):
        x = np.random.randn(2, 3, 5, 7).astype(np.float32)
        ys = np.linspace(-1, 1, 5)
        xs = np.linspace(-1, 1, 7)
        gy, gx = np.meshgrid(ys, xs, indexing="ij")
        grid = np.stack([gx, gy])[None].repeat(2, axis=0).astype(np.float32)
        out = mx.nd.BilinearSampler(_nd(x), _nd(grid))
        assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_bilinear_sampler_shift_and_oob(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        # constant grid pointing at exact pixel (1, 2) → x[...,1,2] = 6
        gx = np.full((1, 1, 1), 2 / 3 * 2 - 1, np.float32)  # col 2 of 4 → 2*(2/3)-1
        gy = np.full((1, 1, 1), 1 / 3 * 2 - 1, np.float32)
        grid = np.stack([gx, gy], axis=1)
        out = mx.nd.BilinearSampler(_nd(x), _nd(grid)).asnumpy()
        assert abs(out[0, 0, 0, 0] - 6.0) < 1e-4
        # far out-of-bounds → 0
        grid_oob = np.full((1, 2, 1, 1), 5.0, np.float32)
        out = mx.nd.BilinearSampler(_nd(x), _nd(grid_oob)).asnumpy()
        assert abs(out[0, 0, 0, 0]) < 1e-6

    @with_seed()
    def test_grid_generator_identity_affine(self):
        theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)  # identity affine
        grid = mx.nd.GridGenerator(_nd(theta), transform_type="affine",
                                   target_shape=(4, 5)).asnumpy()
        ys = np.linspace(-1, 1, 4)
        xs = np.linspace(-1, 1, 5)
        gy, gx = np.meshgrid(ys, xs, indexing="ij")
        assert_almost_equal(grid[0, 0], gx.astype(np.float32), rtol=1e-5, atol=1e-5)
        assert_almost_equal(grid[0, 1], gy.astype(np.float32), rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_grid_generator_warp_zero_flow(self):
        flow = np.zeros((1, 2, 3, 4), np.float32)
        grid = mx.nd.GridGenerator(_nd(flow), transform_type="warp").asnumpy()
        # zero flow = identity grid
        ys = np.linspace(-1, 1, 3)
        xs = np.linspace(-1, 1, 4)
        gy, gx = np.meshgrid(ys, xs, indexing="ij")
        assert_almost_equal(grid[0, 0], gx.astype(np.float32), rtol=1e-5, atol=1e-5)
        assert_almost_equal(grid[0, 1], gy.astype(np.float32), rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_spatial_transformer_identity(self):
        x = np.random.randn(2, 3, 6, 6).astype(np.float32)
        theta = np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32), (2, 1))
        out = mx.nd.SpatialTransformer(_nd(x), _nd(theta), target_shape=(6, 6))
        assert_almost_equal(out.asnumpy(), x, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_spatial_transformer_grad(self):
        x = np.random.randn(1, 1, 4, 4).astype(np.float32)
        theta = np.array([[1, 0, 0.1, 0, 1, -0.1]], np.float32)
        check_numeric_gradient(
            lambda d, t: mx.nd.SpatialTransformer(d, t, target_shape=(4, 4)),
            [x, theta], rtol=2e-2, atol=2e-3)

    @with_seed()
    def test_roi_pooling_vs_naive(self):
        np.random.seed(3)
        x = np.random.rand(2, 3, 8, 8).astype(np.float32)
        rois = np.array([[0, 0, 0, 7, 7], [1, 2, 2, 6, 6], [0, 1, 3, 3, 5]], np.float32)
        ph, pw = 2, 2
        out = mx.nd.ROIPooling(_nd(x), _nd(rois), pooled_size=(ph, pw),
                               spatial_scale=1.0).asnumpy()

        def naive(feat, roi):
            b, x1, y1, x2, y2 = int(roi[0]), *[int(round(v)) for v in roi[1:]]
            roi_h = max(y2 - y1 + 1, 1)
            roi_w = max(x2 - x1 + 1, 1)
            res = np.zeros((3, ph, pw), np.float32)
            for i in range(ph):
                for j in range(pw):
                    hs = int(np.floor(y1 + i * roi_h / ph))
                    he = int(np.ceil(y1 + (i + 1) * roi_h / ph))
                    ws = int(np.floor(x1 + j * roi_w / pw))
                    we = int(np.ceil(x1 + (j + 1) * roi_w / pw))
                    hs, he = max(hs, 0), min(he, 8)
                    ws, we = max(ws, 0), min(we, 8)
                    if he > hs and we > ws:
                        res[:, i, j] = feat[b, :, hs:he, ws:we].max(axis=(1, 2))
            return res

        for r in range(3):
            assert_almost_equal(out[r], naive(x, rois[r]), rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_roi_pooling_grad_flows(self):
        x = np.random.rand(1, 2, 6, 6).astype(np.float32)
        rois = _nd(np.array([[0, 0, 0, 5, 5]], np.float32))
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.ROIPooling(xa, rois, pooled_size=(2, 2), spatial_scale=1.0)
        out.backward()
        g = xa.grad.asnumpy()
        # exactly one max location per bin per channel receives gradient
        assert g.sum() == pytest.approx(2 * 2 * 2, abs=1e-4)

    @with_seed()
    def test_roi_align_uniform_field(self):
        # constant feature map: every bin averages to the constant
        x = np.full((1, 2, 8, 8), 3.5, np.float32)
        rois = np.array([[0, 1, 1, 6, 6]], np.float32)
        out = mx.nd._contrib_ROIAlign(_nd(x), _nd(rois), pooled_size=(3, 3),
                                      spatial_scale=1.0, sample_ratio=2).asnumpy()
        assert_almost_equal(out, np.full((1, 2, 3, 3), 3.5, np.float32),
                            rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_roi_align_linear_field(self):
        # bilinear sampling of a linear field reproduces it exactly
        h = np.arange(8, dtype=np.float32)
        x = np.broadcast_to(h[None, None, :, None], (1, 1, 8, 8)).copy()
        rois = np.array([[0, 0, 1, 7, 6]], np.float32)  # y1=1, y2=6
        ph = 5
        out = mx.nd._contrib_ROIAlign(_nd(x), _nd(rois), pooled_size=(ph, 1),
                                      spatial_scale=1.0, sample_ratio=2).asnumpy()
        roi_h = 6 - 1
        bin_h = roi_h / ph
        centers = 1 + (np.arange(ph) + 0.5) * bin_h
        assert_almost_equal(out[0, 0, :, 0], centers.astype(np.float32),
                            rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_roi_align_alias_and_grad(self):
        assert mx.nd.ROIAlign is not None
        x = np.random.rand(1, 2, 6, 6).astype(np.float32)
        rois = np.array([[0, 0.5, 0.5, 4.5, 4.5]], np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd._contrib_ROIAlign(xa, _nd(rois), pooled_size=(2, 2),
                                          spatial_scale=1.0, sample_ratio=2)
        out.backward()
        assert float(np.abs(xa.grad.asnumpy()).sum()) > 0

    @with_seed()
    def test_correlation_self_peak(self):
        # correlating a map with itself: the AGGREGATE response peaks at
        # zero displacement (Cauchy–Schwarz over the whole field; pointwise
        # the inequality needs equal norms, which random data doesn't have)
        x = np.random.randn(1, 4, 9, 9).astype(np.float32)
        out = mx.nd.Correlation(_nd(x), _nd(x), kernel_size=1,
                                max_displacement=2, stride1=1, stride2=1,
                                pad_size=2, is_multiply=True).asnumpy()
        D = 5
        assert out.shape[1] == D * D
        sums = out[0].sum(axis=(1, 2))
        assert sums.argmax() == D * D // 2
        # and the center channel IS the normalized self dot product
        expect = (x * x).sum(axis=1)[0] / 4
        assert_almost_equal(out[0, D * D // 2], expect, rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_correlation_values(self):
        # kernel 1, zero displacement = normalized channel dot product
        a = np.random.randn(1, 3, 5, 5).astype(np.float32)
        b = np.random.randn(1, 3, 5, 5).astype(np.float32)
        out = mx.nd.Correlation(_nd(a), _nd(b), kernel_size=1, max_displacement=0,
                                stride1=1, stride2=1, pad_size=0).asnumpy()
        expect = (a * b).sum(axis=1) / 3
        assert_almost_equal(out[0, 0], expect[0], rtol=1e-4, atol=1e-5)
        # subtract mode
        out = mx.nd.Correlation(_nd(a), _nd(b), kernel_size=1, max_displacement=0,
                                is_multiply=False).asnumpy()
        expect = np.abs(a - b).sum(axis=1) / 3
        assert_almost_equal(out[0, 0], expect[0], rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_im2col_col2im(self):
        x = np.random.randn(2, 3, 6, 6).astype(np.float32)
        cols = mx.nd.im2col(_nd(x), kernel=(3, 3), stride=(1, 1), pad=(1, 1))
        assert cols.shape == (2, 27, 36)
        # center-tap of a 3x3 patch at stride 1 pad 1 is the pixel itself
        center = cols.asnumpy().reshape(2, 3, 3, 3, 36)[:, :, 1, 1].reshape(2, 3, 6, 6)
        assert_almost_equal(center, x, rtol=1e-6, atol=1e-6)
        # col2im(im2col(x)) multiplies each pixel by its patch count
        fold = mx.nd.col2im(cols, output_size=(6, 6), kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1)).asnumpy()
        # pixel (i,j) is read by patches centered at [i-1, i+1] ∩ [0, 5]
        cov = lambda i: min(5, i + 1) - max(0, i - 1) + 1
        counts = np.array([[cov(i) * cov(j) for j in range(6)] for i in range(6)],
                          np.float32)
        assert_almost_equal(fold, x * counts[None, None], rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_im2col_kernel2_stride2(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        cols = mx.nd.im2col(_nd(x), kernel=(2, 2), stride=(2, 2))
        assert cols.shape == (1, 8, 4)
        ref = cols.asnumpy().reshape(2, 2, 2, 2, 2)
        # patch (0,0): rows 0:2, cols 0:2
        assert_almost_equal(ref[:, :, :, 0, 0], x[0, :, 0:2, 0:2], rtol=1e-6, atol=1e-6)

    @with_seed()
    def test_bilinear_resize(self):
        x = np.random.randn(2, 3, 4, 4).astype(np.float32)
        same = mx.nd._contrib_BilinearResize2D(_nd(x), height=4, width=4)
        assert_almost_equal(same.asnumpy(), x, rtol=1e-5, atol=1e-5)
        up = mx.nd._contrib_BilinearResize2D(_nd(x), height=7, width=7).asnumpy()
        # align_corners: corners map exactly
        assert_almost_equal(up[..., 0, 0], x[..., 0, 0], rtol=1e-5, atol=1e-5)
        assert_almost_equal(up[..., -1, -1], x[..., -1, -1], rtol=1e-5, atol=1e-5)
        # midpoint of a 2-point segment is the average
        line = np.zeros((1, 1, 1, 2), np.float32)
        line[0, 0, 0] = [0.0, 10.0]
        mid = mx.nd._contrib_BilinearResize2D(_nd(line), height=1, width=3).asnumpy()
        assert_almost_equal(mid[0, 0, 0], np.array([0, 5, 10], np.float32),
                            rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_adaptive_avg_pooling(self):
        x = np.random.randn(2, 3, 6, 8).astype(np.float32)
        # divisible case matches plain average pooling
        out = mx.nd._contrib_AdaptiveAvgPooling2D(_nd(x), output_size=(3, 4)).asnumpy()
        expect = x.reshape(2, 3, 3, 2, 4, 2).mean(axis=(3, 5))
        assert_almost_equal(out, expect, rtol=1e-5, atol=1e-5)
        # global pooling
        out1 = mx.nd._contrib_AdaptiveAvgPooling2D(_nd(x), output_size=1).asnumpy()
        assert_almost_equal(out1[..., 0, 0], x.mean(axis=(2, 3)), rtol=1e-5, atol=1e-5)
        # non-divisible bins follow the floor/ceil rule
        x2 = np.arange(5, dtype=np.float32).reshape(1, 1, 1, 5)
        out2 = mx.nd._contrib_AdaptiveAvgPooling2D(_nd(x2), output_size=(1, 2)).asnumpy()
        assert_almost_equal(out2[0, 0, 0], np.array([1.0, 3.0], np.float32),
                            rtol=1e-5, atol=1e-5)

    @with_seed()
    def test_adaptive_pool_grad(self):
        x = np.random.randn(1, 2, 4, 4).astype(np.float32)
        check_numeric_gradient(
            lambda d: mx.nd._contrib_AdaptiveAvgPooling2D(d, output_size=(2, 2)),
            [x])


# ===========================================================================
# deformable ops (DCN / R-FCN)
# ===========================================================================


class TestDeformableOps:
    @with_seed()
    def test_deformable_conv_zero_offset_matches_conv(self):
        """Zero offsets reduce deformable conv to plain convolution."""
        B, C, H, W, O, kh, kw = 2, 4, 7, 7, 6, 3, 3
        x = np.random.randn(B, C, H, W).astype(np.float32)
        w = np.random.randn(O, C, kh, kw).astype(np.float32)
        b = np.random.randn(O).astype(np.float32)
        for stride, pad, dilate in [((1, 1), (1, 1), (1, 1)), ((2, 2), (0, 0), (1, 1))]:
            Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
            Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1
            off = np.zeros((B, 2 * kh * kw, Ho, Wo), np.float32)
            got = mx.nd._contrib_DeformableConvolution(
                _nd(x), _nd(off), _nd(w), _nd(b), kernel=(kh, kw),
                stride=stride, pad=pad, dilate=dilate, num_filter=O)
            want = mx.nd.Convolution(
                _nd(x), _nd(w), _nd(b), kernel=(kh, kw), stride=stride,
                pad=pad, dilate=dilate, num_filter=O)
            assert_almost_equal(got, want, rtol=1e-4, atol=1e-4)

    @with_seed()
    def test_deformable_conv_matches_naive(self):
        """Fractional random offsets vs a direct numpy loop over taps."""
        B, C, H, W, O, k = 1, 2, 5, 5, 3, 3
        pad = 1
        x = np.random.randn(B, C, H, W).astype(np.float32)
        w = np.random.randn(O, C, k, k).astype(np.float32)
        off = (np.random.rand(B, 2 * k * k, H, W).astype(np.float32) - 0.5) * 2

        def bilin(img, y, xq):
            if y <= -1 or y >= img.shape[0] or xq <= -1 or xq >= img.shape[1]:
                return 0.0
            y0, x0 = int(np.floor(y)), int(np.floor(xq))
            dy, dx = y - y0, xq - x0
            val = 0.0
            for (yy, xx, wt) in [(y0, x0, (1 - dy) * (1 - dx)),
                                 (y0, x0 + 1, (1 - dy) * dx),
                                 (y0 + 1, x0, dy * (1 - dx)),
                                 (y0 + 1, x0 + 1, dy * dx)]:
                if 0 <= yy < img.shape[0] and 0 <= xx < img.shape[1]:
                    val += wt * img[yy, xx]
            return val

        want = np.zeros((B, O, H, W), np.float32)
        for bb in range(B):
            for o in range(O):
                for i in range(H):
                    for j in range(W):
                        acc = 0.0
                        for ki in range(k):
                            for kj in range(k):
                                kk = ki * k + kj
                                dy = off[bb, 2 * kk, i, j]
                                dx = off[bb, 2 * kk + 1, i, j]
                                y = i - pad + ki + dy
                                xq = j - pad + kj + dx
                                for c in range(C):
                                    acc += w[o, c, ki, kj] * bilin(x[bb, c], y, xq)
                        want[bb, o, i, j] = acc
        got = mx.nd._contrib_DeformableConvolution(
            _nd(x), _nd(off), _nd(w), kernel=(k, k), pad=(pad, pad),
            num_filter=O, no_bias=True)
        assert_almost_equal(got, want, rtol=1e-3, atol=1e-4)

    @with_seed()
    def test_deformable_conv_groups_and_grad(self):
        B, C, H, W, O = 1, 4, 6, 6, 4
        x = np.random.randn(B, C, H, W).astype(np.float32)
        w = np.random.randn(O, C // 2, 3, 3).astype(np.float32)
        # off-lattice constant offsets: bilinear interpolation is non-smooth
        # at integer coords, where numeric and analytic gradients legitimately
        # disagree; 0.37 keeps every sample strictly between grid points
        off = np.full((B, 2 * 2 * 9, H, W), 0.37, np.float32)
        out = mx.nd._contrib_DeformableConvolution(
            _nd(x), _nd(off), _nd(w), kernel=(3, 3), pad=(1, 1), num_filter=O,
            num_group=2, num_deformable_group=2, no_bias=True)
        assert out.shape == (B, O, H, W)
        check_numeric_gradient(
            lambda d, f: mx.nd._contrib_DeformableConvolution(
                d, _nd(off), f, kernel=(3, 3), pad=(1, 1), num_filter=O,
                num_group=2, num_deformable_group=2, no_bias=True),
            [x, w], rtol=0.03, atol=0.01)

    @with_seed()
    def test_deformable_psroi_pooling_matches_naive(self):
        """no_trans and learned-offset cases vs a direct numpy port of the
        reference kernel's semantics."""
        OD, G, P, S = 2, 2, 2, 2
        C = OD * G * G
        B, H, W = 1, 8, 8
        scale, trans_std = 0.5, 0.2
        x = np.random.randn(B, C, H, W).astype(np.float32)
        rois = np.array([[0, 1, 1, 11, 13], [0, 3, 2, 9, 9]], np.float32)
        trans = 0.5 * np.random.randn(2, 2, P, P).astype(np.float32)

        def naive(no_trans):
            R = rois.shape[0]
            out = np.zeros((R, OD, P, P), np.float32)
            for r in range(R):
                rx1 = round(rois[r, 1]) * scale - 0.5
                ry1 = round(rois[r, 2]) * scale - 0.5
                rx2 = (round(rois[r, 3]) + 1) * scale - 0.5
                ry2 = (round(rois[r, 4]) + 1) * scale - 0.5
                rw, rh = max(rx2 - rx1, 0.1), max(ry2 - ry1, 0.1)
                bh, bw = rh / P, rw / P
                for ct in range(OD):
                    for ph in range(P):
                        for pw in range(P):
                            if no_trans:
                                tx = ty = 0.0
                            else:
                                tx = trans[r, 0, ph, pw] * trans_std
                                ty = trans[r, 1, ph, pw] * trans_std
                            hs = ph * bh + ry1 + ty * rh
                            ws = pw * bw + rx1 + tx * rw
                            gh = min(max(int(np.floor(ph * G / P)), 0), G - 1)
                            gw = min(max(int(np.floor(pw * G / P)), 0), G - 1)
                            c = (ct * G + gh) * G + gw
                            acc, cnt = 0.0, 0
                            for ih in range(S):
                                for iw in range(S):
                                    hq = hs + ih * bh / S
                                    wq = ws + iw * bw / S
                                    if hq < -0.5 or hq > H - 0.5 or wq < -0.5 or wq > W - 0.5:
                                        continue
                                    hq = min(max(hq, 0.0), H - 1.0)
                                    wq = min(max(wq, 0.0), W - 1.0)
                                    y0, x0 = int(np.floor(hq)), int(np.floor(wq))
                                    dy, dx = hq - y0, wq - x0
                                    y1c, x1c = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                                    v = (x[0, c, y0, x0] * (1 - dy) * (1 - dx)
                                         + x[0, c, y0, x1c] * (1 - dy) * dx
                                         + x[0, c, y1c, x0] * dy * (1 - dx)
                                         + x[0, c, y1c, x1c] * dy * dx)
                                    acc += v
                                    cnt += 1
                            out[r, ct, ph, pw] = acc / cnt if cnt else 0.0
            return out

        got_nt = mx.nd._contrib_DeformablePSROIPooling(
            _nd(x), _nd(rois), spatial_scale=scale, output_dim=OD,
            group_size=G, pooled_size=P, sample_per_part=S, no_trans=True)
        assert_almost_equal(got_nt, naive(True), rtol=1e-4, atol=1e-5)
        got_tr = mx.nd._contrib_DeformablePSROIPooling(
            _nd(x), _nd(rois), _nd(trans), spatial_scale=scale, output_dim=OD,
            group_size=G, pooled_size=P, part_size=P, sample_per_part=S,
            trans_std=trans_std)
        assert_almost_equal(got_tr, naive(False), rtol=1e-4, atol=1e-5)

    def test_deformable_aliases(self):
        assert mx.nd.DeformableConvolution is not None
        assert mx.nd.contrib.DeformableConvolution is not None
        assert mx.nd.contrib.DeformablePSROIPooling is not None


# ===========================================================================
# legacy loss heads
# ===========================================================================


class TestLossHeads:
    @with_seed()
    def test_svm_output_l1_grad(self):
        x = np.random.randn(4, 5).astype(np.float32)
        label = np.array([0, 2, 4, 1], np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.SVMOutput(xa, _nd(label), margin=1.0, use_linear=True)
        assert_almost_equal(out.asnumpy(), x, rtol=1e-6, atol=1e-6)  # fwd = identity
        out.backward()
        g = xa.grad.asnumpy()
        onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
        sgn = 2 * onehot - 1
        viol = 1.0 - sgn * x
        expect = np.where(viol > 0, -sgn, 0.0)
        assert_almost_equal(g, expect, rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_svm_output_l2_grad(self):
        x = np.random.randn(3, 4).astype(np.float32)
        label = np.array([1, 0, 3], np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.SVMOutput(xa, _nd(label), margin=0.5,
                                  regularization_coefficient=2.0)
        out.backward()
        onehot = np.eye(4, dtype=np.float32)[label.astype(int)]
        sgn = 2 * onehot - 1
        viol = 0.5 - sgn * x
        expect = np.where(viol > 0, -2.0 * viol * sgn, 0.0) * 2.0
        assert_almost_equal(xa.grad.asnumpy(), expect, rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_mae_regression_output(self):
        x = np.random.randn(4, 3).astype(np.float32)
        label = np.random.randn(4, 3).astype(np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.MAERegressionOutput(xa, _nd(label))
        assert_almost_equal(out.asnumpy(), x, rtol=1e-6, atol=1e-6)
        out.backward()
        assert_almost_equal(xa.grad.asnumpy(), np.sign(x - label), rtol=1e-5, atol=1e-6)

    @with_seed()
    def test_logistic_regression_output(self):
        x = np.random.randn(4, 3).astype(np.float32)
        label = (np.random.rand(4, 3) > 0.5).astype(np.float32)
        xa = _nd(x)
        xa.attach_grad()
        with autograd.record():
            out = mx.nd.LogisticRegressionOutput(xa, _nd(label))
        expect = 1 / (1 + np.exp(-x))
        assert_almost_equal(out.asnumpy(), expect, rtol=1e-5, atol=1e-5)
        out.backward()
        assert_almost_equal(xa.grad.asnumpy(), expect - label, rtol=1e-5, atol=1e-5)


# ===========================================================================
# CTC loss
# ===========================================================================


def _ctc_ref(logits, labels, blank):
    """Brute-force CTC: enumerate all alignment paths (tiny T only)."""
    import itertools

    T, C = logits.shape
    logp = logits - np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(
        -1, keepdims=True)) - logits.max(-1, keepdims=True) * 0
    # proper log_softmax
    m = logits.max(-1, keepdims=True)
    logp = logits - m - np.log(np.exp(logits - m).sum(-1, keepdims=True))
    total = -np.inf
    for path in itertools.product(range(C), repeat=T):
        # collapse path: remove repeats then blanks
        collapsed = []
        prev = None
        for s in path:
            if s != prev:
                collapsed.append(s)
            prev = s
        collapsed = [s for s in collapsed if s != blank]
        if collapsed == list(labels):
            lp = sum(logp[t, s] for t, s in enumerate(path))
            total = np.logaddexp(total, lp)
    return -total


class TestCTCLoss:
    @with_seed()
    def test_ctc_vs_bruteforce_blank_first(self):
        T, B, C = 4, 3, 4  # blank=0, labels in 1..3
        np.random.seed(5)
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2], [3, 0], [2, 2]], np.float32)  # 0 pads
        out = mx.nd.CTCLoss(_nd(logits), _nd(labels)).asnumpy()
        for b in range(B):
            lab = [int(v) for v in labels[b] if v != 0]
            expect = _ctc_ref(logits[:, b].astype(np.float64), lab, blank=0)
            assert abs(out[b] - expect) < 1e-3, (b, out[b], expect)

    @with_seed()
    def test_ctc_blank_last(self):
        T, B, C = 4, 2, 4  # blank=3, labels in 0..2, -1 pads
        np.random.seed(6)
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[0, 2], [1, -1]], np.float32)
        out = mx.nd.CTCLoss(_nd(logits), _nd(labels), blank_label="last").asnumpy()
        for b in range(B):
            lab = [int(v) for v in labels[b] if v != -1]
            expect = _ctc_ref(logits[:, b].astype(np.float64), lab, blank=3)
            assert abs(out[b] - expect) < 1e-3

    @with_seed()
    def test_ctc_data_lengths(self):
        T, B, C = 6, 2, 3
        np.random.seed(7)
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 0], [2, 0]], np.float32)
        dl = np.array([4, 6], np.float32)
        out = mx.nd.CTCLoss(_nd(logits), _nd(labels), data_lengths=_nd(dl),
                            use_data_lengths=True).asnumpy()
        expect0 = _ctc_ref(logits[:4, 0].astype(np.float64), [1], blank=0)
        expect1 = _ctc_ref(logits[:, 1].astype(np.float64), [2], blank=0)
        assert abs(out[0] - expect0) < 1e-3
        assert abs(out[1] - expect1) < 1e-3

    @with_seed()
    def test_ctc_label_lengths(self):
        T, B, C = 5, 1, 4
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 1, 3]], np.float32)  # explicit length 2 → [1, 1]
        out = mx.nd.CTCLoss(_nd(logits), _nd(labels),
                            label_lengths=_nd([2.0]), use_label_lengths=True).asnumpy()
        expect = _ctc_ref(logits[:, 0].astype(np.float64), [1, 1], blank=0)
        assert abs(out[0] - expect) < 1e-3

    @with_seed()
    def test_ctc_gradient_flows(self):
        T, B, C = 5, 2, 4
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2], [3, 0]], np.float32)
        xa = _nd(logits)
        xa.attach_grad()
        with autograd.record():
            loss = mx.nd.CTCLoss(xa, _nd(labels))
        loss.backward()
        g = xa.grad.asnumpy()
        assert np.abs(g).sum() > 0
        # gradient of log-likelihood wrt logits sums to ~0 per frame minus
        # softmax simplex constraint: columns sum to (p - target-mass) → each
        # frame's grad sums to 0 only pre-softmax composition; just check
        # finiteness and scale
        assert np.isfinite(g).all()

    @with_seed()
    def test_ctc_alias(self):
        T, B, C = 3, 1, 3
        logits = np.random.randn(T, B, C).astype(np.float32)
        labels = np.array([[1]], np.float32)
        a = mx.nd.ctc_loss(_nd(logits), _nd(labels)).asnumpy()
        b = mx.nd._contrib_CTCLoss(_nd(logits), _nd(labels)).asnumpy()
        assert_almost_equal(a, b, rtol=0, atol=0)


# ===========================================================================
# dtype matrix for the new families
# ===========================================================================


class TestDtypeMatrix:
    @with_seed()
    @pytest.mark.parametrize("dtype", ["float32", "float16", "bfloat16"])
    def test_spatial_dtypes(self, dtype):
        x = mx.nd.array(np.random.rand(1, 4, 4, 4), dtype=dtype)
        out = mx.nd.depth_to_space(x, 2)
        assert out.dtype == x.dtype
        out = mx.nd._contrib_AdaptiveAvgPooling2D(x, output_size=(2, 2))
        assert out.dtype == x.dtype
        rois = mx.nd.array(np.array([[0, 0, 0, 3, 3]]), dtype="float32")
        out = mx.nd.ROIPooling(x, rois, pooled_size=(2, 2), spatial_scale=1.0)
        assert out.dtype == x.dtype

    @with_seed()
    @pytest.mark.parametrize("dtype", ["float32", "float16"])
    def test_linalg_dtypes(self, dtype):
        a = mx.nd.array(np.random.rand(2, 3, 3) + 2 * np.eye(3), dtype=dtype)
        out = mx.nd.linalg_extractdiag(a)
        assert out.dtype == a.dtype
        g = mx.nd.linalg_gemm2(a, a)
        assert g.dtype == a.dtype

    @with_seed()
    @pytest.mark.parametrize("dtype", ["float32", "float64"])
    def test_sampler_dtypes(self, dtype):
        if dtype == "float64":
            pytest.skip("x64 disabled by default in this build (jax default)")
        u = mx.nd._random_uniform(shape=(10,), dtype=dtype)
        assert str(u.dtype) == dtype


class TestInterleavedMatmul:
    """_contrib_interleaved_matmul_* (the GluonNLP fused-MHA fast path,
    [U:src/operator/contrib/transformer.cc])."""

    @with_seed()
    def test_selfatt_roundtrip(self):
        S, B, H, D = 6, 2, 2, 4
        qkv = np.random.randn(S, B, H * 3 * D).astype(np.float32)
        sc = mx.nd._contrib_interleaved_matmul_selfatt_qk(_nd(qkv), heads=H)
        x = qkv.reshape(S, B, H, 3, D)
        q, k, v = x[:, :, :, 0], x[:, :, :, 1], x[:, :, :, 2]
        ref = np.einsum("qbhd,kbhd->bhqk", q / np.sqrt(D), k).reshape(B * H, S, S)
        assert_almost_equal(sc.asnumpy(), ref, rtol=1e-4, atol=1e-5)
        att = np.exp(ref)
        att /= att.sum(-1, keepdims=True)
        ctx = mx.nd._contrib_interleaved_matmul_selfatt_valatt(
            _nd(qkv), _nd(att), heads=H)
        ref_ctx = np.einsum("bhqk,kbhd->qbhd", att.reshape(B, H, S, S),
                            v).reshape(S, B, H * D)
        assert_almost_equal(ctx.asnumpy(), ref_ctx, rtol=1e-4, atol=1e-5)

    @with_seed()
    def test_encdec_roundtrip(self):
        Sq, Sk, B, H, D = 5, 7, 2, 2, 4
        qx = np.random.randn(Sq, B, H * D).astype(np.float32)
        kv = np.random.randn(Sk, B, H * 2 * D).astype(np.float32)
        sc = mx.nd._contrib_interleaved_matmul_encdec_qk(_nd(qx), _nd(kv), heads=H)
        kvr = kv.reshape(Sk, B, H, 2, D)
        ref = np.einsum("qbhd,kbhd->bhqk", qx.reshape(Sq, B, H, D) / np.sqrt(D),
                        kvr[:, :, :, 0]).reshape(B * H, Sq, Sk)
        assert_almost_equal(sc.asnumpy(), ref, rtol=1e-4, atol=1e-5)
        att = np.exp(ref)
        att /= att.sum(-1, keepdims=True)
        ctx = mx.nd._contrib_interleaved_matmul_encdec_valatt(
            _nd(kv), _nd(att), heads=H)
        ref_ctx = np.einsum("bhqk,kbhd->qbhd", att.reshape(B, H, Sq, Sk),
                            kvr[:, :, :, 1]).reshape(Sq, B, H * D)
        assert_almost_equal(ctx.asnumpy(), ref_ctx, rtol=1e-4, atol=1e-5)


class TestCastStorage:
    @with_seed()
    def test_roundtrips(self):
        dense = np.zeros((4, 5), np.float32)
        dense[0, 1] = 2.0
        dense[2, 3] = -1.0
        d = _nd(dense)
        csr = mx.nd.cast_storage(d, "csr")
        assert csr.stype == "csr"
        assert_almost_equal(csr.asnumpy(), dense, rtol=0, atol=0)
        rsp = mx.nd.cast_storage(csr, "row_sparse")
        assert rsp.stype == "row_sparse"
        assert rsp.indices.asnumpy().tolist() == [0, 2]
        back = mx.nd.cast_storage(rsp, "default")
        assert_almost_equal(back.asnumpy(), dense, rtol=0, atol=0)
        try:
            mx.nd.cast_storage(d, "coo")
        except ValueError:
            pass
        else:
            raise AssertionError("expected ValueError for unknown stype")


class TestGroupAdaGrad:
    @with_seed()
    def test_matches_reference_recurrence(self):
        from incubator_mxnet_tpu import optimizer as opt_mod

        lr, eps = 0.1, 1e-5
        np.random.seed(21)
        w0 = np.random.randn(6, 4).astype(np.float64)
        opt = opt_mod.create("groupadagrad", learning_rate=lr, eps=eps)
        updater = opt_mod.get_updater(opt)
        w = _nd(w0.astype(np.float32))
        grads = [np.random.randn(6, 4).astype(np.float64) for _ in range(4)]
        for g in grads:
            updater(0, _nd(g.astype(np.float32)), w)
        wn = w0.copy()
        hist = np.zeros((6, 1))
        for g in grads:
            hist = hist + (g ** 2).mean(axis=1, keepdims=True)
            wn -= lr * g / (np.sqrt(hist) + eps)
        assert_almost_equal(w.asnumpy(), wn.astype(np.float32),
                            rtol=1e-4, atol=1e-5)
        # state is per-row: 1/dim the elementwise AdaGrad state
        st = opt.create_state(0, _nd(w0.astype(np.float32)))
        assert st.shape == (6, 1)


class TestRandomNamespaceParity:
    @with_seed()
    def test_negative_binomial_moments(self):
        mx.random.seed(7)
        s = mx.nd.random.negative_binomial(k=4, p=0.5, shape=(30000,)).asnumpy()
        # mean k(1-p)/p = 4, var k(1-p)/p^2 = 8
        assert abs(s.mean() - 4.0) < 0.15
        assert abs(s.var() - 8.0) < 0.6
        assert np.all(s >= 0) and np.allclose(s, np.round(s))

    @with_seed()
    def test_generalized_negative_binomial_moments(self):
        mx.random.seed(8)
        mu, alpha = 2.5, 0.3
        s = mx.nd.random.generalized_negative_binomial(
            mu=mu, alpha=alpha, shape=(30000,)).asnumpy()
        assert abs(s.mean() - mu) < 0.15
        # var = mu + alpha*mu^2
        assert abs(s.var() - (mu + alpha * mu * mu)) < 0.5


class TestLegacyNdFunctions:
    """The pre-Gluon ndarray-function trio + AMP pass ops + Crop
    ([U:src/ndarray/ndarray_function.cc], [U:src/operator/tensor/amp_cast.cc],
    [U:src/operator/crop.cc])."""

    def test_choose_fill_element_0index(self):
        x = np.arange(12, dtype=np.float32).reshape(3, 4)
        idx = np.array([0, 2, 3], np.float32)
        picked = mx.nd.choose_element_0index(_nd(x), _nd(idx)).asnumpy()
        np.testing.assert_allclose(picked, x[np.arange(3), idx.astype(int)])
        vals = np.array([9.0, 8.0, 7.0], np.float32)
        filled = mx.nd.fill_element_0index(_nd(x), _nd(vals), _nd(idx)).asnumpy()
        expect = x.copy()
        expect[np.arange(3), idx.astype(int)] = vals
        np.testing.assert_allclose(filled, expect)

    def test_one_hot_encode_legacy(self):
        out = mx.nd.one_hot_encode(_nd(np.array([1, 0, 2], np.float32)),
                                   mx.nd.zeros((3, 4))).asnumpy()
        np.testing.assert_allclose(out, np.eye(4, dtype=np.float32)[[1, 0, 2]])

    def test_amp_cast_and_multicast(self):
        f32 = mx.nd.array(np.ones(3), dtype="float32")
        i32 = mx.nd.array(np.ones(3), dtype="int32")
        assert mx.nd.amp_cast(f32, dtype="float16").dtype == np.float16
        assert mx.nd.amp_cast(i32, dtype="float16").dtype == np.int32  # passthrough
        h, f, i = mx.nd.amp_multicast(
            mx.nd.array(np.ones(3), dtype="float16"), f32, i32, num_outputs=3)
        assert h.dtype == np.float32 and f.dtype == np.float32
        assert i.dtype == np.int32
        h2, f2 = mx.nd.amp_multicast(
            mx.nd.array(np.ones(3), dtype="float16"), f32,
            num_outputs=2, cast_narrow=True)
        assert h2.dtype == np.float16 and f2.dtype == np.float16

    def test_crop_spatial_and_slice_alias(self):
        x = np.arange(2 * 3 * 5 * 6, dtype=np.float32).reshape(2, 3, 5, 6)
        out = mx.nd.Crop(_nd(x), h_w=(3, 4), offset=(1, 2)).asnumpy()
        np.testing.assert_allclose(out, x[:, :, 1:4, 2:6])
        like = mx.nd.zeros((2, 3, 2, 2))
        out = mx.nd.Crop(_nd(x), like, center_crop=True).asnumpy()
        np.testing.assert_allclose(out, x[:, :, 1:3, 2:4])
        with pytest.raises(ValueError):
            mx.nd.Crop(_nd(x), h_w=(9, 9))
        # lowercase crop is the reference's alias for slice, NOT Crop
        out = mx.nd.crop(_nd(x), begin=(0, 1, 0, 0), end=(2, 3, 2, 3)).asnumpy()
        np.testing.assert_allclose(out, x[0:2, 1:3, 0:2, 0:3])

    def test_broadcast_axes_alias(self):
        out = mx.nd.broadcast_axes(mx.nd.zeros((1, 3, 1)), axis=(0, 2),
                                   size=(4, 2))
        assert out.shape == (4, 3, 2)


class TestRound5TailGradients:
    """Finite-difference gradient rows for the round-5 tail ops
    (the reference's check_numeric_gradient idiom)."""

    def test_crop_gradient(self):
        from incubator_mxnet_tpu.utils.test_utils import check_numeric_gradient

        x = np.random.RandomState(0).rand(1, 2, 6, 6)
        check_numeric_gradient(
            lambda d: mx.nd.Crop(d, h_w=(3, 3), offset=(1, 2)).sum(), [x])
        check_numeric_gradient(
            lambda d: mx.nd.Crop(d, mx.nd.zeros((1, 2, 4, 4)),
                                 center_crop=True).sum(), [x])

    def test_fill_and_choose_element_gradients(self):
        from incubator_mxnet_tpu.utils.test_utils import check_numeric_gradient

        rng = np.random.RandomState(1)
        x = rng.rand(4, 5)
        vals = rng.rand(4)
        idx = mx.nd.array(np.array([0, 2, 4, 1], np.float32))
        check_numeric_gradient(
            lambda d: mx.nd.choose_element_0index(d, idx).sum(), [x])
        check_numeric_gradient(
            lambda d, v: mx.nd.fill_element_0index(d, v, idx).sum(),
            [x, vals])

    def test_boolean_mask_gradient(self):
        from incubator_mxnet_tpu.utils.test_utils import check_numeric_gradient

        x = np.random.RandomState(2).rand(5, 3)
        m = mx.nd.array(np.array([1, 0, 1, 1, 0], np.float32))
        check_numeric_gradient(
            lambda d: (mx.nd.contrib.boolean_mask(d, m) ** 2).sum(), [x])
