"""Profiler bridge + engine fence (parity: [U:tests/python/unittest/
test_profiler.py] control-surface checks, the round-3 device-op aggregate
table and multi-device waitall, plus the ISSUE-5 tracing subsystem: span
recorder / chrome-trace round trip, per-step telemetry, slow-step
detector, strict counters, and the trace_report CLI)."""
import json
import logging
import os
import subprocess
import sys
import threading
import time
from collections import defaultdict

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, profiler
from incubator_mxnet_tpu.gluon import Trainer, nn

import jax

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_profiler(tmp_path):
    """Arm-safe profiler state: fresh filename, stopped recorder, zeroed
    counters and an empty peer-metrics registry before AND after
    (profiler state is module-global; a leftover peer snapshot would make
    every slow step also log a straggler line)."""
    profiler.stop()
    profiler.stop_metrics()
    profiler.set_config(filename=str(tmp_path / "trace.json"),
                        ring_size=65536, slow_step_ms=None)
    profiler.reset_counters()
    with profiler._counter_lock:
        profiler._peer_metrics.clear()
    yield tmp_path
    profiler.stop()
    profiler.stop_metrics()
    profiler.set_config(slow_step_ms=None, ring_size=65536,
                        slow_step_auto=True, memory_sampling=True)
    profiler.reset_counters()
    with profiler._counter_lock:
        profiler._peer_metrics.clear()


def _paired_spans(events):
    """Pair B/E events per (pid, tid); returns the B events (with their
    matching E verified) and asserts nothing is unpaired."""
    stacks = defaultdict(list)
    spans = []
    for e in sorted((e for e in events if e.get("ph") in ("B", "E")),
                    key=lambda e: e["ts"]):
        k = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks[k].append(e)
        else:
            assert stacks[k], f"E without open B at ts={e['ts']}"
            b = stacks[k].pop()
            assert e["ts"] >= b["ts"]
            b["_end"] = e["ts"]
            spans.append(b)
    assert not any(stacks.values()), "B events left unclosed"
    return spans


class TestProfiler:
    def test_scope_and_dumps(self):
        with profiler.scope("unit_region"):
            (mx.nd.ones((8, 8)) * 2).asnumpy()
        s = profiler.dumps()
        assert "Profile Statistics" in s
        assert "unit_region" in s

    def test_device_op_stats_parses_synthetic_xplane(self, tmp_path):
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        xs = xplane_pb2.XSpace()
        plane = xs.planes.add()
        plane.name = "/device:TPU:0"
        md = plane.event_metadata[1]
        md.id = 1
        md.name = "%fusion.42 = f32[8,8]{1,0} fusion(%p0), kind=kLoop"
        line = plane.lines.add()
        line.name = "XLA Ops"
        for _ in range(3):
            ev = line.events.add()
            ev.metadata_id = 1
            ev.duration_ps = int(2e9)  # 2 ms each
        d = tmp_path / "t"
        d.mkdir()
        with open(d / "host.xplane.pb", "wb") as f:
            f.write(xs.SerializeToString())
        rows = profiler._device_op_stats(str(d))
        assert len(rows) == 1
        name, count, total_s = rows[0]
        assert (name, count) == ("fusion", 3)
        # 3 events × 2e9 ps = 6e9 ps = 6 ms
        np.testing.assert_allclose(total_s, 6e-3, rtol=1e-9)

    def test_dumps_mentions_device_section_after_start_stop(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "prof.json"))
        profiler.start()
        (mx.nd.ones((16, 16)) @ mx.nd.ones((16, 16))).asnumpy()
        profiler.stop()
        s = profiler.dumps()
        assert "Profile Statistics" in s  # device rows depend on backend


def test_waitall_covers_all_devices():
    # dispatch work on every device of the 8-device mesh, then fence
    outs = []
    for d in jax.local_devices():
        x = jax.device_put(np.arange(1024.0), d)
        outs.append(x * 2 + 1)
    mx.nd.waitall()
    for o in outs:
        # after waitall every per-device queue has drained; reads are instant
        assert np.isfinite(np.asarray(o)).all()


# ---------------------------------------------------------------------------
# ISSUE 5: span recorder + chrome-trace round trip
# ---------------------------------------------------------------------------


class TestChromeTrace:
    def test_train_trace_roundtrip(self, clean_profiler):
        """The acceptance loop: start(); 3 train steps; dump() -> a
        chrome://tracing-valid JSON with spans from the dispatch-cache,
        bulk-flush, fused-step, and kvstore categories, each tagged with
        the correct (monotone) step id."""
        net = nn.Dense(8)
        net.initialize()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore="device")
        x = mx.nd.ones((4, 16))

        profiler.start()
        first_step = profiler.current_step()
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            with engine.bulk(8):  # eager metric chain -> bulk spans
                m = loss + 0.0
                for _ in range(4):
                    m = m * 1.0
            m.asnumpy()
            trainer.step(4)
        path = profiler.dump()

        with open(path) as f:
            doc = json.load(f)
        assert isinstance(doc["traceEvents"], list)
        spans = _paired_spans(doc["traceEvents"])
        cats = {s["cat"] for s in spans}
        assert {"dispatch", "bulk", "optimizer", "comms", "step",
                "trainer"} <= cats

        # step ids: monotone per thread in timestamp order
        per_tid = defaultdict(list)
        for s in sorted(spans, key=lambda s: s["ts"]):
            per_tid[s["tid"]].append(s["args"]["step"])
        for ids in per_tid.values():
            assert ids == sorted(ids)

        # step ids: CORRECT — every span inside a step span's [B, E] range
        # carries that step's id (asserted for the synchronous train-loop
        # categories; the three steps are first_step..first_step+2)
        step_spans = sorted((s for s in spans if s["cat"] == "step"),
                            key=lambda s: s["ts"])
        assert [s["args"]["step"] for s in step_spans] == [
            first_step, first_step + 1, first_step + 2]
        for s in spans:
            if s["cat"] not in ("optimizer", "comms", "trainer"):
                continue
            owner = [st for st in step_spans
                     if st["ts"] <= s["ts"] and s["_end"] <= st["_end"]]
            assert owner, f"span {s['name']} outside every step"
            assert s["args"]["step"] == owner[0]["args"]["step"]

        # at least one span of each acceptance name family
        names = {s["name"] for s in spans}
        assert "fused.group_apply" in names
        assert "bulk.flush" in names
        assert "kvstore.pushpull" in names
        assert names & {"dispatch.cache_hit", "dispatch.jit_compile"}

        # telemetry rode along: 3 closed steps with bucket splits
        steps = profiler.step_stats()[-3:]
        assert [s["step"] for s in steps] == [first_step, first_step + 1,
                                              first_step + 2]
        for s in steps:
            assert s["wall_ms"] >= s["host_ms"] >= 0
            assert s["device_ms"] >= 0

    def test_dump_finished_false_keeps_recording(self, clean_profiler):
        profiler.start()
        with profiler.span("before", "user"):
            pass
        path = profiler.dump(finished=False)
        assert profiler.state() == "running"
        assert profiler.recording_enabled()
        with profiler.span("after", "user"):
            pass
        path = profiler.dump()  # default finishes
        assert profiler.state() == "stopped"
        assert not profiler.recording_enabled()
        names = {s["name"] for s in
                 _paired_spans(json.load(open(path))["traceEvents"])}
        assert {"before", "after"} <= names

    def test_multithreaded_span_counts(self, clean_profiler):
        """Exact per-thread span counts under concurrency: the per-thread
        rings may not drop or duplicate spans."""
        n_threads, n_spans = 4, 250
        profiler.start()
        barrier = threading.Barrier(n_threads)

        def work():
            barrier.wait()
            for i in range(n_spans):
                t0 = time.perf_counter()
                profiler.record_span(f"mt_{i % 7}", "user", t0)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        profiler.stop()
        spans = _paired_spans(profiler._trace_events())
        per_tid = defaultdict(int)
        for s in spans:
            if s["name"].startswith("mt_"):
                per_tid[s["tid"]] += 1
        assert len(per_tid) == n_threads
        assert all(c == n_spans for c in per_tid.values())

    def test_ring_buffer_bounds_memory(self, clean_profiler):
        """Recording more spans than the ring capacity must not grow
        memory: the oldest spans are evicted and counted as dropped."""
        profiler.set_config(ring_size=64)
        profiler.start()
        for i in range(200):
            t0 = time.perf_counter()
            profiler.record_span(f"ring_{i}", "user", t0)
        stats = profiler.recorder_stats()
        profiler.stop()
        assert stats["spans"] == 64
        assert stats["dropped"] == 200 - 64
        spans = _paired_spans(profiler._trace_events())
        kept = sorted(int(s["name"].split("_")[1]) for s in spans
                      if s["name"].startswith("ring_"))
        assert kept == list(range(136, 200))  # oldest evicted, newest kept

    def test_ring_registry_bounded_under_thread_churn(self, clean_profiler):
        """Short-lived threads (a fresh prefetch worker per epoch) must not
        grow the retained-rings list without bound: dead threads' rings are
        evicted once the cap is exceeded."""
        profiler.set_config(ring_size=8)
        profiler.start()
        for i in range(profiler._MAX_RINGS + 20):
            t = threading.Thread(
                target=lambda: profiler.record_span("churn", "user",
                                                    time.perf_counter()))
            t.start()
            t.join()
        n_rings = profiler.recorder_stats()["threads"]
        profiler.stop()
        # cap + the handful of genuinely-alive threads at eviction time
        assert n_rings <= profiler._MAX_RINGS + 1


# ---------------------------------------------------------------------------
# ISSUE 5: per-step telemetry + slow-step detector
# ---------------------------------------------------------------------------


class TestStepTelemetry:
    def test_slow_step_detector_fires_exactly_once(self, clean_profiler,
                                                   caplog):
        profiler.set_config(slow_step_ms=50.0)
        profiler.start()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            for _ in range(4):      # normal steps: well under 50 ms
                profiler.step_boundary()
            time.sleep(0.08)        # injected stall
            profiler.step_boundary()
            for _ in range(4):      # back to normal
                profiler.step_boundary()
        profiler.stop()
        slow_lines = [r for r in caplog.records if "slow step" in r.message]
        assert len(slow_lines) == 1
        msg = slow_lines[0].getMessage()
        assert "host-dispatch" in msg and "comms" in msg
        assert profiler.counters()["slow_step_detected"] == 1

    def test_slow_step_auto_percentile_mode(self, clean_profiler, caplog):
        """No explicit threshold: a step > mult x the rolling median is
        flagged once the window has enough history."""
        profiler.set_config(slow_step_ms=None, slow_step_auto=True,
                            slow_step_auto_mult=4.0)
        profiler.start()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            for _ in range(20):
                time.sleep(0.01)
                profiler.step_boundary()
            time.sleep(0.3)         # >> 4x the ~10 ms median
            profiler.step_boundary()
        profiler.stop()
        auto = [r for r in caplog.records if "auto:" in r.message]
        assert len(auto) == 1

    def test_step_buckets_accumulate(self, clean_profiler):
        profiler.start()
        sid = profiler.current_step()
        t0 = time.perf_counter()
        profiler.record_span("kvstore.pushpull", "comms", t0, t0 + 0.010)
        profiler.record_span("dispatch.cache_hit", "dispatch", t0, t0 + 0.005)
        profiler.record_span("bulk.trace", "bulk", t0, t0 + 0.003)  # nested:
        profiler.step_boundary()                    # excluded from buckets
        profiler.stop()
        s = [s for s in profiler.step_stats() if s["step"] == sid][-1]
        assert s["comms_ms"] == pytest.approx(10.0, rel=0.3)
        assert s["host_ms"] == pytest.approx(5.0, rel=0.3)

    def test_memory_watermark_surface(self, clean_profiler):
        # CPU devices may expose no memory_stats: the sampler must stay
        # silent/empty, never raise
        profiler.start()
        profiler.step_boundary()
        profiler.step_boundary()
        profiler.stop()
        wm = profiler.memory_watermark()
        assert isinstance(wm, dict)
        assert all(isinstance(v, int) and v >= 0 for v in wm.values())


# ---------------------------------------------------------------------------
# ISSUE 5 satellites: strict counters, locked _tally, trace-error surfacing
# ---------------------------------------------------------------------------


class TestCounters:
    def test_incr_unknown_name_raises(self):
        typo = "dispatch_cache_hti"  # built dynamically elsewhere this
        with pytest.raises(KeyError):  # would silently report zeros forever
            profiler.incr(typo)

    def test_declare_counter_extension_path(self):
        profiler.declare_counter("test_custom_counter")
        profiler.incr("test_custom_counter", 3)
        assert profiler.counters()["test_custom_counter"] == 3
        profiler.reset_counters()
        assert profiler.counters()["test_custom_counter"] == 0

    def test_incr_exact_under_concurrency(self):
        profiler.reset_counters()
        n_threads, n_incr = 8, 500

        def work():
            for _ in range(n_incr):
                profiler.incr("dispatch_cache_hit")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert profiler.counters()["dispatch_cache_hit"] == n_threads * n_incr
        profiler.reset_counters()

    def test_tally_exact_under_concurrency(self):
        """Satellite 1: concurrent scopes must not drop _agg tallies (the
        old unlocked read-modify-write did) and dumps() must iterate a
        stable snapshot."""
        name = "tally_race_probe"
        with profiler._counter_lock:
            profiler._agg.pop(name, None)
        n_threads, n_tallies = 8, 400
        stop = threading.Event()

        def dump_loop():  # concurrent reader: would blow up on a mutating
            while not stop.is_set():  # dict pre-fix
                profiler.dumps()

        reader = threading.Thread(target=dump_loop)
        reader.start()

        def work():
            for _ in range(n_tallies):
                profiler._tally(name, 0.001)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        reader.join()
        cnt, tot = profiler._agg[name]
        assert cnt == n_threads * n_tallies
        assert tot == pytest.approx(cnt * 0.001)
        with profiler._counter_lock:
            profiler._agg.pop(name, None)

    def test_trace_error_warns_once_and_counts(self, clean_profiler,
                                               monkeypatch):
        """Satellite 3: a broken xprof install is diagnosable — RuntimeWarning
        (once) + profiler_trace_error counter, and the span recorder still
        arms."""
        def boom(*a, **k):
            raise RuntimeError("no xprof here")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        monkeypatch.setattr(profiler, "_trace_warned", False)
        with pytest.warns(RuntimeWarning, match="profiler_trace_error"):
            profiler.start()
        assert profiler.recording_enabled()  # python spans still captured
        assert profiler.counters()["profiler_trace_error"] == 1
        profiler.stop()  # must not call stop_trace (xprof never started)
        assert profiler.counters()["profiler_trace_error"] == 1


# ---------------------------------------------------------------------------
# ISSUE 5: disabled-recorder overhead + trace_report CLI
# ---------------------------------------------------------------------------


def test_disabled_recorder_overhead_smoke():
    """The eager-dispatch chain runs with the recorder OFF: no spans may be
    recorded and the benchmark harness must be unperturbed (the <3% number
    is measured by the full paired-median run, not asserted here)."""
    import importlib.util

    profiler.stop()
    assert not profiler.recording_enabled()
    before = profiler.recorder_stats()["spans"]
    path = os.path.join(_REPO, "benchmark", "opperf", "eager_dispatch.py")
    spec = importlib.util.spec_from_file_location("eager_dispatch_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    line = mod.run(n_ops=6, iters=2, shape=(4, 4), warmup=1)
    for mode in ("uncached", "cached_jit", "bulked"):
        assert line["ops_per_sec"][mode]["elemwise"] > 0
    assert profiler.recorder_stats()["spans"] == before


class TestTraceReport:
    def _synthetic_trace(self, path):
        evs = []
        t = 1000.0
        for step in (1, 2, 3):
            evs.append({"ph": "B", "name": "step", "cat": "step", "ts": t,
                        "pid": 1, "tid": 7, "args": {"step": step}})
            evs.append({"ph": "B", "name": "fused.group_apply",
                        "cat": "optimizer", "ts": t + 10, "pid": 1,
                        "tid": 7, "args": {"step": step}})
            evs.append({"ph": "E", "name": "fused.group_apply",
                        "cat": "optimizer", "ts": t + 60, "pid": 1, "tid": 7})
            evs.append({"ph": "E", "name": "step", "cat": "step",
                        "ts": t + 100, "pid": 1, "tid": 7})
            t += 200
        doc = {"traceEvents": evs, "displayTimeUnit": "ms",
               "otherData": {"steps": [
                   {"step": s, "wall_ms": 0.1, "host_ms": 0.05,
                    "comms_ms": 0.0, "device_ms": 0.05} for s in (1, 2, 3)]}}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def test_report_on_synthetic_trace(self, tmp_path):
        trace = self._synthetic_trace(str(tmp_path / "synth.json"))
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             trace, "--top", "5"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "Per-category totals" in out.stdout
        assert "optimizer" in out.stdout
        assert "Step-time histogram" in out.stdout
        assert "fused.group_apply" in out.stdout

    def test_report_rejects_invalid_trace(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             str(bad)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 2

    def test_report_on_real_dump(self, clean_profiler, tmp_path):
        profiler.start()
        with profiler.span("real_work", "user"):
            (mx.nd.ones((8, 8)) * 3).asnumpy()
        profiler.step_boundary()
        path = profiler.dump()
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             path], capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "real_work" in out.stdout


# ---------------------------------------------------------------------------
# ISSUE 7: pause/resume vs the telemetry window + metrics snapshots
# ---------------------------------------------------------------------------

sys.path.insert(0, os.path.join(_REPO, "tools"))
import trace_merge  # noqa: E402


def _peer_snap(rank, host="peer-host", seq=1, wall=900.0, comms=700.0,
               step=77):
    return {"schema": 1, "rank": rank, "host": host, "pid": 10000 + rank,
            "seq": seq, "time_unix": time.time(),
            "counters": {"bulk_flush": 1},
            "last_step": {"step": step, "wall_ms": wall, "host_ms": 50.0,
                          "comms_ms": comms,
                          "device_ms": wall - 50.0 - comms},
            "window": {"n": 1, "wall_ms_median": wall, "wall_ms_max": wall},
            "memory_watermark_bytes": {}}


class TestPauseResumeWindow:
    def test_pause_gap_not_billed_to_window(self, clean_profiler):
        """A pause()d interval must not pollute step_stats(): the first
        post-resume boundary anchors at resume time, so the gap never
        appears as a giant step wall."""
        profiler.start()
        profiler.step_boundary()                    # ~0 ms step
        time.sleep(0.01)
        profiler.step_boundary()                    # ~10 ms step
        profiler.pause()
        time.sleep(0.25)                            # the paused gap
        profiler.resume()
        time.sleep(0.01)
        profiler.step_boundary()                    # measured from resume
        profiler.stop()
        steps = profiler.step_stats()
        assert len(steps) == 3                      # window survived pause
        assert all(s["wall_ms"] < 200.0 for s in steps), steps

    def test_dump_unfinished_keeps_window_accumulating(self, clean_profiler):
        profiler.start()
        time.sleep(0.002)
        profiler.step_boundary()
        profiler.dump(finished=False)
        assert profiler.recording_enabled()
        time.sleep(0.002)
        profiler.step_boundary()
        profiler.dump()
        assert len(profiler.step_stats()) == 2

    def test_metrics_snapshot_monotone_across_session_events(
            self, clean_profiler):
        """Snapshot monotonicity: seq/time/counters/window size never go
        backwards across boundaries, mid-run dumps, and pause/resume."""
        profiler.start()
        profiler.step_boundary()
        s1 = profiler.metrics_snapshot()
        time.sleep(0.005)
        profiler.step_boundary()
        profiler.dump(finished=False)
        s2 = profiler.metrics_snapshot()
        profiler.pause()
        profiler.resume()
        s3 = profiler.metrics_snapshot()
        profiler.stop()
        for a, b in ((s1, s2), (s2, s3)):
            assert b["seq"] > a["seq"]
            assert b["time_unix"] >= a["time_unix"]
            assert b["window"]["n"] >= a["window"]["n"]
            for k, v in a["counters"].items():
                assert b["counters"][k] >= v, k
        assert s2["window"]["n"] == 2
        assert s2["last_step"]["wall_ms"] >= 4.0


# ---------------------------------------------------------------------------
# ISSUE 7: live metrics export (registry, Prometheus endpoint, JSONL)
# ---------------------------------------------------------------------------


class TestMetricsExport:
    def test_render_prometheus_includes_local_and_peers(self, clean_profiler):
        profiler.start()
        time.sleep(0.003)
        profiler.step_boundary()
        time.sleep(0.003)
        profiler.step_boundary()
        profiler.publish_peer_metrics(_peer_snap(9))
        txt = profiler.render_prometheus()
        profiler.stop()
        me = profiler.process_info()["rank"]
        assert f'mxnet_profiler_counter_total{{counter="bulk_flush",rank="9"' \
            in txt
        assert f'rank="{me}"' in txt
        assert 'mxnet_step_last_wall_ms{' in txt
        assert 'mxnet_step_last_comms_ms{rank="9"' in txt
        assert "# TYPE mxnet_profiler_counter_total counter" in txt
        assert "# TYPE mxnet_step_last_wall_ms gauge" in txt

    def test_peer_registry_replaces_by_seq_and_pid(self, clean_profiler):
        profiler.publish_peer_metrics(_peer_snap(4, seq=5, wall=100.0))
        profiler.publish_peer_metrics(_peer_snap(4, seq=3, wall=999.0))
        assert profiler.peer_metrics()[4]["last_step"]["wall_ms"] == 100.0
        restarted = _peer_snap(4, seq=1, wall=50.0)
        restarted["pid"] = 4242                     # a restarted peer wins
        profiler.publish_peer_metrics(restarted)
        assert profiler.peer_metrics()[4]["last_step"]["wall_ms"] == 50.0

    def test_http_endpoint_serves_cluster(self, clean_profiler):
        import urllib.request

        profiler.start()
        time.sleep(0.003)
        profiler.step_boundary()
        time.sleep(0.003)
        profiler.step_boundary()
        profiler.publish_peer_metrics(_peer_snap(9))
        port = profiler.start_metrics(port=0)       # explicit 0 = ephemeral
        try:
            assert port and port == profiler.metrics_server_port()
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
            assert 'mxnet_profiler_counter_total' in body
            assert 'rank="9"' in body               # the peer is on the scrape
            assert 'mxnet_step_last_wall_ms' in body
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=10).read())
            assert "9" in doc["peers"]
            assert doc["local"]["rank"] == profiler.process_info()["rank"]
            assert profiler.counters()["metrics_scrape"] >= 2
        finally:
            profiler.stop_metrics()
        assert profiler.metrics_server_port() is None

    def test_jsonl_exporter_writes_monotone_snapshots(self, clean_profiler,
                                                     tmp_path):
        path = tmp_path / "metrics.jsonl"
        profiler.start()
        profiler.step_boundary()
        profiler.start_metrics(port=None, jsonl=str(path), interval_s=0.05)
        time.sleep(0.35)
        profiler.stop_metrics()
        profiler.stop()
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert len(lines) >= 2
        assert [l["seq"] for l in lines] == sorted(l["seq"] for l in lines)
        assert all(l["schema"] == 1 for l in lines)
        assert all(l["rank"] == lines[0]["rank"] for l in lines)


# ---------------------------------------------------------------------------
# ISSUE 7: cross-rank straggler attribution
# ---------------------------------------------------------------------------


class TestStragglerAttribution:
    def test_straggler_named_exactly_once_per_anomalous_step(
            self, clean_profiler, caplog):
        profiler.set_config(slow_step_ms=30.0)
        profiler.start()
        profiler.publish_peer_metrics(_peer_snap(5, host="worker-h5",
                                                 wall=900.0, comms=700.0))
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            profiler.step_boundary()            # fast step
            time.sleep(0.05)
            profiler.step_boundary()            # THE anomalous step
            profiler.step_boundary()            # fast again
        profiler.stop()
        lines = [r for r in caplog.records if "straggler" in r.message]
        assert len(lines) == 1
        msg = lines[0].getMessage()
        assert "rank 5" in msg and "worker-h5" in msg
        assert "host-dispatch" in msg and "comms" in msg \
            and "device/other" in msg
        assert "700.3" not in msg               # numbers come from the snap
        assert "900.0 ms" in msg and "700.0 ms" in msg
        assert profiler.counters()["straggler_detected"] == 1

    def test_no_straggler_line_without_peer_data(self, clean_profiler,
                                                 caplog):
        profiler.set_config(slow_step_ms=30.0)
        profiler.start()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            profiler.step_boundary()
            time.sleep(0.05)
            profiler.step_boundary()
        profiler.stop()
        assert [r for r in caplog.records if "slow step" in r.message]
        assert not [r for r in caplog.records if "straggler" in r.message]
        assert profiler.counters()["straggler_detected"] == 0

    def test_straggler_report_compares_local_and_peers(self, clean_profiler):
        profiler.start()
        time.sleep(0.003)
        profiler.step_boundary()
        time.sleep(0.003)
        profiler.step_boundary()
        assert profiler.straggler_report() is None  # one rank: nothing to
        profiler.publish_peer_metrics(_peer_snap(2, wall=5000.0))  # compare
        rep = profiler.straggler_report()
        profiler.stop()
        assert rep["rank"] == 2 and rep["wall_ms"] == 5000.0
        assert rep["ranks_compared"] == 2
        assert rep["step"] == 77


# ---------------------------------------------------------------------------
# ISSUE 7: multi-rank trace merge + gz round trip
# ---------------------------------------------------------------------------


class TestTraceMerge:
    def _rank_doc(self, rank, epoch_unix, clock_offset_s, host="hostX"):
        evs = [{"ph": "M", "pid": 1234, "name": "process_name",
                "args": {"name": "local"}}]
        t = 100.0
        for step in (1, 2):
            evs += [{"ph": "B", "name": "step", "cat": "step", "ts": t,
                     "pid": 1234, "tid": 7, "args": {"step": step}},
                    {"ph": "E", "name": "step", "cat": "step", "ts": t + 50,
                     "pid": 1234, "tid": 7}]
            t += 60
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "otherData": {"process": {
                    "rank": rank, "host": host, "pid": 1234,
                    "epoch_unix": epoch_unix,
                    "clock_offset_s": clock_offset_s, "clock_rtt_s": 0.001},
                    "counters": {}, "steps": []}}

    def test_merge_offset_corrects_and_labels_ranks(self, tmp_path):
        # rank 1's wall clock runs 1 s AHEAD (offset +1.0) and its process
        # started 3 s after rank 0: corrected shift = 3 - 1 = 2 s
        p0, p1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
        json.dump(self._rank_doc(0, 1000.0, 0.0, "hostA"), open(p0, "w"))
        json.dump(self._rank_doc(1, 1003.0, 1.0, "hostB"), open(p1, "w"))
        merged = trace_merge.merge_traces([p0, p1])
        names = {e["pid"]: e["args"]["name"] for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
        assert names == {0: "rank 0 (hostA)", 1: "rank 1 (hostB)"}
        ts = {pid: [e["ts"] for e in merged["traceEvents"]
                    if e.get("ph") == "B" and e["pid"] == pid]
              for pid in (0, 1)}
        assert ts[0] == [100.0, 160.0]
        assert ts[1] == [100.0 + 2e6, 160.0 + 2e6]
        summary = trace_merge.check_merged(merged, expect_ranks=2)
        assert summary["steps_per_rank"] == {0: 2, 1: 2}
        assert merged["otherData"]["ranks"]["1"]["shift_us"] == 2e6

    def test_merge_rejects_duplicate_ranks(self, tmp_path):
        p0, p1 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        json.dump(self._rank_doc(0, 1000.0, 0.0), open(p0, "w"))
        json.dump(self._rank_doc(0, 1001.0, 0.0), open(p1, "w"))
        with pytest.raises(ValueError, match="duplicate rank"):
            trace_merge.merge_traces([p0, p1])

    def test_check_catches_non_monotone_steps(self, tmp_path):
        doc = self._rank_doc(0, 1000.0, 0.0)
        for e in doc["traceEvents"]:
            if e.get("args", {}).get("step") == 2:
                e["args"]["step"] = 1               # duplicate id
        p = str(tmp_path / "bad.json")
        json.dump(doc, open(p, "w"))
        merged = trace_merge.merge_traces([p])
        with pytest.raises(ValueError, match="monotone"):
            trace_merge.check_merged(merged)

    def test_real_dump_gz_roundtrip_through_report(self, clean_profiler,
                                                   tmp_path, monkeypatch):
        """dump() honors MXNET_PROFILER_TRACE_GZ=1 and the gz file flows
        through trace_report unchanged."""
        monkeypatch.setenv("MXNET_PROFILER_TRACE_GZ", "1")
        profiler.start()
        with profiler.span("gz_work", "user"):
            time.sleep(0.002)
        profiler.step_boundary()
        path = profiler.dump()
        assert path.endswith(".json.gz") and os.path.exists(path)
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             path], capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "gz_work" in out.stdout

    def test_report_merge_mode_and_empty_diagnosis(self, tmp_path):
        p0, p1 = str(tmp_path / "r0.json"), str(tmp_path / "r1.json")
        json.dump(self._rank_doc(0, 1000.0, 0.0), open(p0, "w"))
        json.dump(self._rank_doc(1, 1000.5, 0.0), open(p1, "w"))
        merged = str(tmp_path / "merged.json")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             p0, p1, "--merge", merged],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "Per-rank attribution" in out.stdout
        assert "hostX" in out.stdout
        assert os.path.exists(merged)
        empty = tmp_path / "empty.json"
        empty.write_text("")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             str(empty)], capture_output=True, text=True, timeout=120)
        assert out.returncode == 2
        assert "empty trace file" in out.stderr
        assert "Traceback" not in out.stderr

    def test_dump_carries_process_metadata(self, clean_profiler):
        profiler.start()
        profiler.step_boundary()
        path = profiler.dump()
        proc = json.load(open(path))["otherData"]["process"]
        assert proc["rank"] == profiler.process_info()["rank"]
        assert proc["host"] and proc["pid"] == os.getpid()
        assert proc["epoch_unix"] > 0


@pytest.mark.slow
def test_dist_trace_smoke_two_workers():
    """The CI acceptance path end to end: 2 dist_async workers -> per-rank
    traces -> offset-corrected merge with one process row per rank; rank
    0's /metrics scrape aggregates both ranks; straggler attribution fires
    exactly once (tools/dist_trace_smoke.py, also run by ci.sh profiler)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, os.path.join(_REPO, "tools", "dist_trace_smoke.py")],
        env=env, capture_output=True, text=True, timeout=280)
    sys.stdout.write(out.stdout[-2000:])
    sys.stderr.write(out.stderr[-2000:])
    assert out.returncode == 0
    assert "dist trace smoke OK" in out.stdout


class TestStragglerRegistryHygiene:
    def test_schema_light_peer_snapshot_cannot_break_step_boundary(
            self, clean_profiler, caplog):
        """A peer on an older build may heartbeat a snapshot whose
        last_step lacks bucket fields; the straggler comparison must
        degrade, never raise out of the training hot path."""
        profiler.set_config(slow_step_ms=30.0)
        profiler.start()
        profiler.publish_peer_metrics(
            {"rank": 8, "pid": 1, "seq": 1, "time_unix": time.time(),
             "last_step": {"step": 3, "wall_ms": 5000.0}})   # no buckets
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            profiler.step_boundary()
            time.sleep(0.05)
            profiler.step_boundary()                 # must not raise
        profiler.stop()
        lines = [r for r in caplog.records if "straggler" in r.message]
        assert len(lines) == 1 and "rank 8" in lines[0].getMessage()
        # and a last_step that is not even a dict is skipped outright
        profiler.publish_peer_metrics(
            {"rank": 9, "pid": 1, "seq": 1, "last_step": "garbage"})
        rep = profiler.straggler_report()
        assert rep is None or rep["rank"] != 9

    def test_stale_peer_snapshot_aged_out_of_comparison(self,
                                                       clean_profiler):
        profiler.start()
        time.sleep(0.003)
        profiler.step_boundary()
        time.sleep(0.003)
        profiler.step_boundary()
        old = _peer_snap(6, wall=9000.0)
        old["time_unix"] = time.time() - 3600.0      # an hour-dead rank
        profiler.publish_peer_metrics(old)
        assert profiler.straggler_report() is None   # nothing fresh to
        profiler.publish_peer_metrics(_peer_snap(7, wall=8000.0))  # compare
        rep = profiler.straggler_report()
        profiler.stop()
        assert rep["rank"] == 7                      # ghost never wins

    def test_forget_peer_metrics_on_deregister_and_eviction(self):
        """The PS purges a departed rank's telemetry from its table AND
        the co-located peer registry — clean leave and lease eviction."""
        from incubator_mxnet_tpu.kvstore.async_ps import (AsyncClient,
                                                          ParameterServer)

        ps = ParameterServer(num_workers=2, port=0, lease_s=0.4)
        try:
            c = AsyncClient(*ps.address)
            snap = {"rank": 1, "pid": 1, "seq": 1, "time_unix": time.time(),
                    "last_step": {"step": 1, "wall_ms": 1.0, "host_ms": 0.0,
                                  "comms_ms": 0.0, "device_ms": 1.0}}
            c.request("register", 1)
            c.request("heartbeat", 1, snap)
            assert 1 in c.request("metrics")
            assert 1 in profiler.peer_metrics()
            c.request("deregister", 1)
            assert 1 not in c.request("metrics")
            assert 1 not in profiler.peer_metrics()
            # eviction path: register + one beat, then let the lease lapse
            c.request("register", 2)
            c.request("heartbeat", 2, dict(snap, rank=2))
            assert 2 in c.request("metrics")
            deadline = time.monotonic() + 10.0
            while 2 in c.request("metrics"):
                assert time.monotonic() < deadline, "reaper never purged"
                time.sleep(0.1)
            assert 2 not in profiler.peer_metrics()
        finally:
            ps.stop()
            with profiler._counter_lock:
                profiler._peer_metrics.clear()
